"""Device telemetry: sample live HBM/host memory stats into gauges.

``utils/profiling.device_memory_stats`` gives a point-in-time PJRT view;
sampling it into the registry turns that into a series an operator can
watch — HBM growth across boost rounds (the binned-dataset cache's
documented retention, models/gbdt/api.py) shows up as a rising
``device_memory_bytes{stat="bytes_in_use"}`` between scrapes.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Dict, Optional

from . import metrics as _metrics
from .env_registry import env_float

__all__ = ["device_memory_gauges", "maybe_sample_device_memory"]

# PJRT stat keys worth exporting (others vary by backend and stay in the
# returned dict for callers that want them).
_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
              "largest_free_block_bytes", "pool_bytes")


def device_memory_gauges() -> Dict[str, Optional[Dict[str, Any]]]:
    """Sample per-device memory stats into ``device_memory_bytes`` gauges
    (labels: ``device``, ``stat``) and return the raw stats dict.

    No-op (returns ``{}``) while telemetry is disabled; devices whose
    runtime exposes no stats are skipped (profiling already records the
    reason), so this never breaks the run it observes.
    """
    if not _metrics.enabled():
        return {}
    from ..utils import profiling  # lazy: jax only touched when sampling

    stats = profiling.device_memory_stats()
    for dev, ms in stats.items():
        if not ms:
            continue
        for key in _STAT_KEYS:
            v = ms.get(key)
            if v is not None:
                _metrics.safe_gauge("device_memory_bytes",
                                    device=dev, stat=key).set(float(v))
    return stats


# -- periodic sampling hook --------------------------------------------------
# Before this, device_memory_bytes only moved when a caller remembered to
# invoke device_memory_gauges() — it flatlined between manual calls. The
# watchdog tick and the federation sweep both call the throttled hook
# below, so any process running either loop gets a fresh sample every
# MMLSPARK_TPU_DEVICE_MEMORY_INTERVAL_SECONDS for free.

_INTERVAL_ENV = "MMLSPARK_TPU_DEVICE_MEMORY_INTERVAL_SECONDS"
_sample_lock = threading.Lock()
_last_sample = 0.0


def maybe_sample_device_memory(now: Optional[float] = None) -> bool:
    """Throttled ``device_memory_gauges()``: samples at most once per
    interval knob, only when telemetry is on AND jax is already loaded
    (a gateway/watchdog host must never import the framework just to
    poll memory it does not hold). Returns True when a sample ran."""
    if not _metrics.enabled() or "jax" not in sys.modules:
        return False
    interval = env_float(_INTERVAL_ENV, 30.0)
    if interval <= 0:
        return False
    global _last_sample
    if now is None:
        now = time.monotonic()
    with _sample_lock:
        if now - _last_sample < interval:
            return False
        _last_sample = now
    try:
        device_memory_gauges()
    except Exception:
        return False
    return True
