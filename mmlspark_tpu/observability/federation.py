"""Cluster metrics federation: one scrape surface for a worker fleet.

Each serving worker exposes its own in-band ``/metrics``; on a pod that
means operators scrape N addresses and mentally merge them. This module
gives the distributed-serving gateway the cluster view: a background
:class:`MetricsFederator` periodically scrapes every registered worker's
``/metrics``, parses the Prometheus text exposition, and merges families
under a ``worker`` label:

- **counters** — exported per worker (``worker="host:port"``) AND as a
  cluster sum (no ``worker`` label);
- **gauges** — per worker only (a summed queue depth hides the one
  wedged worker the gauge exists to show);
- **histograms** — bucket-merged across workers (bucket counts, sum and
  count are additive).

Merged families are renamed ``cluster_<name>`` so the gateway's own
process metrics and the fleet view coexist in one exposition without
family collisions. Scrape health itself is part of the product:
``cluster_scrape_ok{worker=...}`` / ``cluster_scrape_age_seconds`` ride
the same payload, and ``/debug/cluster`` reports per-worker scrape
status, staleness, consecutive failures, and the gateway's last
failover.

Kill-switch contract: the scrape loop checks ``metrics.enabled()`` every
tick and does nothing while disabled (and the gateway only routes debug
paths while enabled), so federation adds zero behavior to a disabled
deployment.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import blackbox as _blackbox
from . import device as _device
from . import flight as _flight
from . import metrics as _metrics

__all__ = [
    "parse_prometheus_text", "merge_worker_families", "render_families",
    "MetricsFederator", "DEFAULT_INTERVAL_SECONDS",
]

_INTERVAL_ENV = "MMLSPARK_TPU_FEDERATION_INTERVAL_SECONDS"
DEFAULT_INTERVAL_SECONDS = 5.0

#: family name -> (kind, [(labels, value)]) — histogram "values" are
#: dicts {"buckets": {le_str: count}, "sum": float, "count": float}
Families = Dict[str, Tuple[str, List[Tuple[Dict[str, str], Any]]]]


def _parse_labels(body: str) -> Dict[str, str]:
    """``a="x",b="y"`` -> dict. Handles escaped quotes/backslashes."""
    out: Dict[str, str] = {}
    i, n = 0, len(body)
    while i < n:
        eq = body.find("=", i)
        if eq < 0:
            break
        key = body[i:eq].strip().strip(",")
        j = eq + 1
        if j >= n or body[j] != '"':
            break
        j += 1
        val: List[str] = []
        while j < n and body[j] != '"':
            if body[j] == "\\" and j + 1 < n:
                nxt = body[j + 1]
                val.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                j += 2
            else:
                val.append(body[j])
                j += 1
        out[key] = "".join(val)
        i = j + 1
    return out


def _parse_sample(line: str) -> Optional[Tuple[str, Dict[str, str], float]]:
    brace = line.find("{")
    if brace >= 0:
        close = line.rfind("}")
        if close < brace:
            return None
        name = line[:brace].strip()
        labels = _parse_labels(line[brace + 1:close])
        rest = line[close + 1:].strip()
    else:
        parts = line.split(None, 1)
        if len(parts) != 2:
            return None
        name, rest = parts[0], parts[1]
        labels = {}
    try:
        value = float(rest.split()[0].replace("+Inf", "inf")
                      .replace("-Inf", "-inf"))
    except (ValueError, IndexError):
        return None
    return name, labels, value


def parse_prometheus_text(text: str) -> Families:
    """Total parse of a text exposition (format 0.0.4) into families.

    Histogram ``_bucket``/``_sum``/``_count`` samples are folded back
    into one histogram entry per label set. Unknown/malformed lines are
    skipped — a half-written scrape must never break the federator.
    """
    kinds: Dict[str, str] = {}
    flat: List[Tuple[str, Dict[str, str], float]] = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3]
            continue
        parsed = _parse_sample(line)
        if parsed is not None:
            flat.append(parsed)

    out: Families = {}
    hist: Dict[str, Dict[Tuple, Dict[str, Any]]] = {}
    for name, labels, value in flat:
        base = None
        part = None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and \
                    kinds.get(name[: -len(suffix)]) == "histogram":
                base, part = name[: -len(suffix)], suffix
                break
        if base is not None:
            le = labels.pop("le", None)
            key = tuple(sorted(labels.items()))
            slot = hist.setdefault(base, {}).setdefault(
                key, {"labels": dict(labels), "buckets": {},
                      "sum": 0.0, "count": 0.0})
            if part == "_bucket" and le is not None:
                slot["buckets"][le] = value
            elif part == "_sum":
                slot["sum"] = value
            elif part == "_count":
                slot["count"] = value
            continue
        kind = kinds.get(name, "gauge")
        if kind == "histogram":
            continue                      # bare histogram base name: skip
        fam = out.setdefault(name, (kind, []))
        fam[1].append((labels, value))
    for base, rows in hist.items():
        fam = out.setdefault(base, ("histogram", []))
        for slot in rows.values():
            fam[1].append((slot["labels"],
                           {"buckets": slot["buckets"], "sum": slot["sum"],
                            "count": slot["count"]}))
    return out


def _labels_key(labels: Dict[str, str]) -> Tuple:
    return tuple(sorted(labels.items()))


def merge_worker_families(
        per_worker: Dict[str, Families]) -> Families:
    """Merge scrapes from several workers into one ``cluster_``-prefixed
    family set, per the counter/gauge/histogram rules in the module doc."""
    merged: Families = {}

    def fam(name: str, kind: str):
        return merged.setdefault(f"cluster_{name}", (kind, []))

    # counters: per-worker series + cluster sum per original label set
    sums: Dict[str, Dict[Tuple, Tuple[Dict[str, str], float]]] = {}
    for worker, families in sorted(per_worker.items()):
        for name, (kind, rows) in sorted(families.items()):
            if kind == "counter":
                f = fam(name, "counter")
                acc = sums.setdefault(name, {})
                for labels, value in rows:
                    f[1].append(({**labels, "worker": worker}, value))
                    key = _labels_key(labels)
                    prev = acc.get(key, (labels, 0.0))
                    acc[key] = (prev[0], prev[1] + float(value))
            elif kind == "histogram":
                f = fam(name, "histogram")
                for labels, h in rows:
                    # fold into the existing aggregate row for this label set
                    row = next((r for r in f[1]
                                if _labels_key(r[0]) == _labels_key(labels)),
                               None)
                    if row is None:
                        f[1].append((dict(labels),
                                     {"buckets": dict(h["buckets"]),
                                      "sum": float(h["sum"]),
                                      "count": float(h["count"])}))
                    else:
                        agg = row[1]
                        for le, c in h["buckets"].items():
                            agg["buckets"][le] = \
                                agg["buckets"].get(le, 0.0) + float(c)
                        agg["sum"] += float(h["sum"])
                        agg["count"] += float(h["count"])
            else:                                     # gauges: per-worker
                f = fam(name, "gauge")
                for labels, value in rows:
                    f[1].append(({**labels, "worker": worker}, value))
    for name, acc in sums.items():
        f = merged[f"cluster_{name}"]
        for labels, total in acc.values():
            f[1].append((dict(labels), total))
    return merged


def _le_sort_key(le: str) -> float:
    try:
        return float(le.replace("+Inf", "inf"))
    except ValueError:
        return float("inf")


def render_families(families: Families) -> str:
    """Families back to text exposition (the federated half of the
    gateway's ``/metrics`` body)."""
    lines: List[str] = []
    for name, (kind, rows) in sorted(families.items()):
        lines.append(f"# TYPE {name} {kind}")
        for labels, value in sorted(
                rows, key=lambda r: _labels_key(r[0])):
            if kind == "histogram":
                for le in sorted(value["buckets"], key=_le_sort_key):
                    lines.append(_metrics._sample(
                        f"{name}_bucket", {**labels, "le": le},
                        value["buckets"][le]))
                lines.append(_metrics._sample(f"{name}_sum", labels,
                                              value["sum"]))
                lines.append(_metrics._sample(f"{name}_count", labels,
                                              value["count"]))
            else:
                lines.append(_metrics._sample(name, labels, value))
    return "\n".join(lines) + ("\n" if lines else "")


class _WorkerState:
    __slots__ = ("label", "families", "last_attempt", "last_success",
                 "consecutive_failures", "error")

    def __init__(self, label: str):
        self.label = label
        self.families: Families = {}
        self.last_attempt = 0.0
        self.last_success = 0.0
        self.consecutive_failures = 0
        self.error: Optional[str] = None


class MetricsFederator:
    """Background scraper + merger over a dynamic worker set.

    ``targets`` returns the current ``[(label, host, port), ...]`` —
    the gateway passes a closure over its :class:`ServiceRegistry`, so
    worker churn is picked up on the next sweep without coordination.
    """

    def __init__(self, targets: Callable[[], List[Tuple[str, str, int]]],
                 interval: Optional[float] = None, timeout: float = 2.0):
        import os
        self.targets = targets
        if interval is None:
            try:
                interval = float(os.environ.get(_INTERVAL_ENV, "")
                                 or DEFAULT_INTERVAL_SECONDS)
            except ValueError:
                interval = DEFAULT_INTERVAL_SECONDS
        self.interval = max(0.05, float(interval))
        self.timeout = timeout
        self._lock = threading.Lock()
        self._workers: Dict[str, _WorkerState] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: set by the gateway on failover (surfaced in /debug/cluster)
        self.last_failover: Optional[Dict[str, Any]] = None
        #: optional callable -> {worker: breaker_state}; the gateway
        #: installs its BreakerBoard view so /debug/cluster shows which
        #: workers the routing plane is currently refusing
        self.breaker_states: Optional[Callable[[], Dict[str, str]]] = None
        #: fleet black-box: worker flight deltas + lifecycle transitions
        #: merged in causal order (/debug/timeline, /debug/trace); fed by
        #: the sweep below when MMLSPARK_TPU_FLIGHT_SCRAPE allows
        self.timeline = _blackbox.FleetTimeline()
        # previous autoscale hint, for crossing-1.0 lifecycle events
        self._prev_hint = 0.0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MetricsFederator":
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                # fresh event per sweeper generation: a start() racing a
                # concurrent stop() must not clear the event the old
                # (not-yet-joined) sweeper is watching — reusing one
                # event could un-stop it and leave two sweepers running
                self._stop = threading.Event()
                self._thread = threading.Thread(
                    target=self._run, args=(self._stop,),
                    name="mmlspark-federation", daemon=True)
                self._thread.start()
        # a crash/SIGUSR2 dump of THIS process also leaves the fleet
        # timeline on disk, next to its own ring (same naming funnel)
        self.timeline.install_dump_hook()
        return self

    def stop(self) -> None:
        self.timeline.uninstall_dump_hook()
        # swap the handles under the lock, signal + join outside it: the
        # sweep thread takes _lock in scrape_once, so joining under it
        # could stall stop() for a full scrape timeout
        with self._lock:
            stop, t = self._stop, self._thread
            self._thread = None
        stop.set()
        if t is not None and t.is_alive():
            t.join(timeout=5)

    def _run(self, stop: threading.Event) -> None:
        while not stop.wait(self.interval):
            if not _metrics.enabled():
                continue
            # piggyback the periodic device-memory sample on the sweep
            # (throttled + jax-guarded inside; a jax-free gateway skips)
            _device.maybe_sample_device_memory()
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — the sweep must never die
                pass

    # -- scraping ------------------------------------------------------------
    #: consecutive scrape failures before a worker is declared
    #: scrape-dead on the timeline (the freshness rule's 3-sweep horizon)
    SCRAPE_DEAD_AFTER = 3

    def scrape_once(self) -> None:
        """One synchronous sweep over the current target set (tests call
        this directly for determinism). Besides ``/metrics``, the sweep
        pulls each worker's flight delta (``/debug/flight?since=``) into
        the fleet timeline and records lifecycle transitions — both
        gated so the disabled deployment's sweep is byte-identical to
        the pre-timeline one."""
        pull = _metrics.enabled() and _blackbox.flight_scrape_enabled()
        targets = list(self.targets())
        seen = set()
        for label, host, port in targets:
            seen.add(label)
            with self._lock:
                known = label in self._workers
            st = self._worker(label)
            if pull and not known:
                self.timeline.lifecycle("worker_registered", worker=label,
                                        addr=f"{host}:{port}")
            was_failing = st.consecutive_failures
            st.last_attempt = time.time()
            try:
                conn = http.client.HTTPConnection(host, int(port),
                                                  timeout=self.timeout)
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                body = resp.read()
                conn.close()
                if resp.status != 200:
                    raise OSError(f"HTTP {resp.status}")
                st.families = parse_prometheus_text(
                    body.decode("utf-8", "replace"))
                st.last_success = time.time()
                st.consecutive_failures = 0
                st.error = None
                _metrics.safe_counter("federation_scrapes_total",
                                      worker=label, outcome="ok").inc()
                if pull and was_failing:
                    self.timeline.lifecycle("worker_scrape_recovered",
                                            worker=label,
                                            after_failures=was_failing)
                if pull:
                    self._pull_flight(label, host, int(port))
            except Exception as e:  # noqa: BLE001 — a sick worker is data
                st.consecutive_failures += 1
                st.error = f"{type(e).__name__}: {e}"
                _metrics.safe_counter("federation_scrapes_total",
                                      worker=label, outcome="error").inc()
                if pull and was_failing == 0:
                    self.timeline.lifecycle("worker_scrape_failed",
                                            worker=label, error=st.error)
                if pull and st.consecutive_failures == self.SCRAPE_DEAD_AFTER:
                    # the same horizon _fresh_states ages the worker out
                    # of every derived signal at — a SIGKILLed worker's
                    # death certificate on the timeline
                    self.timeline.lifecycle("worker_scrape_dead",
                                            worker=label, error=st.error,
                                            consecutive_failures=st
                                            .consecutive_failures)
        with self._lock:
            # deregistered workers leave the cluster view at the sweep
            # AFTER they leave the registry — no ghost series
            gone = [label for label in self._workers if label not in seen]
            for label in gone:
                del self._workers[label]
        if pull:
            for label in gone:
                self.timeline.lifecycle("worker_deregistered", worker=label)
            # the gateway's own ring joins the fleet timeline the same
            # incremental way (no HTTP, same (worker, seq) dedup key) —
            # breaker flips, failovers and deadline drops recorded by the
            # routing plane become timeline events automatically
            self.timeline.extend(
                "gateway",
                _flight.snapshot(since=self.timeline.cursor("gateway")))
        try:
            hint_payload = self.autoscale_hint()  # refresh every sweep
            if pull:
                hint = float(hint_payload.get("hint") or 0.0)
                if self._prev_hint < 1.0 <= hint:
                    self.timeline.lifecycle("autoscale_pressure_high",
                                            hint=hint)
                elif hint < 1.0 <= self._prev_hint:
                    self.timeline.lifecycle("autoscale_pressure_cleared",
                                            hint=hint)
                self._prev_hint = hint
        except Exception:  # noqa: BLE001 — advisory signal only
            pass

    def _pull_flight(self, label: str, host: str, port: int) -> None:
        """Incremental flight scrape of one worker into the timeline.
        Failures are counted but never fail the sweep — the /metrics
        scrape already succeeded, and flight data is forensics, not
        health."""
        try:
            conn = http.client.HTTPConnection(host, port,
                                              timeout=self.timeout)
            conn.request(
                "GET",
                f"/debug/flight?since={self.timeline.cursor(label)}")
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
            if resp.status != 200:
                raise OSError(f"HTTP {resp.status}")
            snap = json.loads(body.decode("utf-8", "replace"))
            added = self.timeline.extend(label, snap)
            _metrics.safe_counter("timeline_events_total",
                                  worker=label).inc(added)
            _metrics.safe_counter("timeline_scrapes_total",
                                  worker=label, outcome="ok").inc()
        except Exception:  # noqa: BLE001 — forensics must not fail health
            _metrics.safe_counter("timeline_scrapes_total",
                                  worker=label, outcome="error").inc()

    # -- timeline / trace views (the /debug/timeline and /debug/trace
    # bodies; thin delegates so debug_body only ever holds the federator)
    def timeline_payload(self) -> Dict[str, Any]:
        payload = self.timeline.snapshot_payload()
        payload["interval_seconds"] = self.interval
        return payload

    def trace_payload(self, trace_id: Optional[str]) -> Dict[str, Any]:
        return self.timeline.trace_payload(trace_id)

    def _worker(self, label: str) -> _WorkerState:
        with self._lock:
            st = self._workers.get(label)
            if st is None:
                st = self._workers[label] = _WorkerState(label)
            return st

    def _fresh_states(self, max_age: Optional[float] = None
                      ) -> List[Tuple[str, "_WorkerState"]]:
        """THE freshness rule for every federated read: workers whose
        last scrape failed, never happened, or is older than ``max_age``
        (default 3 sweep intervals) are omitted. One filter — the
        routing feed, the SLO burn fold, and the autoscale hint's
        queue-wait read all pass through here, so a ghost worker ages
        out of every derived signal at the same instant instead of
        lingering in whichever reader had the laxest rule (the staleness
        contract is pinned in tests/test_federation.py)."""
        if max_age is None:
            max_age = 3.0 * self.interval
        now = time.time()
        with self._lock:
            states = list(self._workers.items())
        return [(label, st) for label, st in states
                if st.error is None and st.last_success
                and now - st.last_success <= max_age]

    def gauge_values(self, family: str,
                     max_age: Optional[float] = None) -> Dict[str, float]:
        """Per-worker value of one gauge family from each worker's last
        successful scrape — the feed for load-aware gateway routing
        (``cluster_serving_queue_depth`` is ``serving_queue_depth`` seen
        from here). Stale/failed workers are omitted (see
        :meth:`_fresh_states`), so the caller can tell "depth 0" apart
        from "no fresh data" and fall back. Series within a family
        (label sets, e.g. one per api) sum per worker."""
        out: Dict[str, float] = {}
        for label, st in self._fresh_states(max_age):
            fam = st.families.get(family)
            if fam is None:
                continue
            kind, rows = fam
            if kind == "histogram":
                continue
            out[label] = sum(float(v) for _labels, v in rows)
        return out

    def gauge_max_values(self, family: str,
                         max_age: Optional[float] = None
                         ) -> Dict[str, float]:
        """Per-worker MAX across one gauge family's series from each
        fresh scrape (same freshness rule: :meth:`_fresh_states`). The
        burn-rate fold reads ``slo_burn_rate`` this way: a worker
        exports one series per (api, window) and summing them
        (``gauge_values``' queue-depth semantics) would double a breach
        just for having two windows."""
        out: Dict[str, float] = {}
        for label, st in self._fresh_states(max_age):
            fam = st.families.get(family)
            if fam is None:
                continue
            kind, rows = fam
            if kind == "histogram" or not rows:
                continue
            out[label] = max(float(v) for _labels, v in rows)
        return out

    def slo_overview(self) -> Dict[str, Any]:
        """Federated SLO view for the gateway's ``/debug/slo``: each
        worker's worst burn rate from its last scrape (any api, either
        window) and the fleet maximum."""
        burns = self.gauge_max_values("slo_burn_rate")
        return {
            "workers": {label: {"burn_rate_max": burns[label]}
                        for label in sorted(burns)},
            "max_burn_rate": max(burns.values()) if burns else None,
            "note": "per-worker max slo_burn_rate from the federation "
                    "sweep; absent workers export no SLO gauges (no "
                    "objective configured or no scrape yet)",
        }

    def autoscale_hint(self) -> Dict[str, Any]:
        """Scale-pressure signal from the fleet's own backpressure
        telemetry (ROADMAP item 1's observability half — the signal
        only, no supervisor acts on it here).

        Two feeds fold into one hint: the mean queue depth per live
        worker (``0`` = arrivals absorbed as they come, sustained
        ``>= 1`` = standing backlog on every worker) and the fleet's
        worst SLO burn rate when it exceeds ``1.0`` — a fleet spending
        error budget faster than it accrues is failing users even with
        shallow queues, so user-visible pain raises the hint too. The
        hint is the max of the two. Per-worker mean queue wait
        (histogram ``sum / count`` from the same scrape) rides along so
        an operator can tell deep-but-fast queues from genuinely slow
        ones. Also sets the ``cluster_autoscale_hint`` gauge."""
        depths = self.gauge_values("serving_queue_depth")
        waits: Dict[str, Optional[float]] = {}
        # the queue-wait read rides the SAME freshness filter as the
        # depth and burn feeds (one _fresh_states rule, not a raw
        # st.families walk): a ghost worker's last samples age out of
        # every component of the hint together
        for label, st in self._fresh_states():
            if label not in depths:
                continue
            mean = None
            fam = st.families.get("serving_queue_wait_seconds")
            if fam is not None and fam[0] == "histogram":
                total = sum(float(h["sum"]) for _l, h in fam[1])
                count = sum(float(h["count"]) for _l, h in fam[1])
                if count > 0:
                    mean = total / count
            waits[label] = mean
        live = len(depths)
        total_depth = sum(depths.values())
        queue_hint = (total_depth / live) if live else 0.0
        burns = self.gauge_max_values("slo_burn_rate")
        burn_max = max(burns.values()) if burns else None
        # burn <= 1.0 is inside budget — only user-visible pain adds
        # pressure beyond what the backlog already shows
        slo_pressure = burn_max if (burn_max or 0.0) > 1.0 else 0.0
        hint = max(queue_hint, slo_pressure)
        _metrics.safe_gauge("cluster_autoscale_hint").set(hint)
        observed = [w for w in waits.values() if w is not None]
        workers = {label: {"queue_depth": depths[label],
                           "queue_wait_mean_seconds": waits.get(label)}
                   for label in sorted(depths)}
        for label, burn in burns.items():
            workers.setdefault(label, {})["slo_burn_rate_max"] = burn
        return {
            "hint": hint,
            "queue_hint": queue_hint,
            "slo_burn_rate_max": burn_max,
            "live_workers": live,
            "total_queue_depth": total_depth,
            "mean_queue_wait_seconds":
                (sum(observed) / len(observed)) if observed else None,
            "workers": workers,
            "note": "max(mean queue depth per live worker, fleet-worst "
                    "slo_burn_rate when > 1); sustained >= 1 suggests "
                    "adding capacity, 0 means arrivals are absorbed "
                    "within objectives (advisory only)",
        }

    # -- export --------------------------------------------------------------
    def _scrape_health_families(self) -> Families:
        now = time.time()
        ok_rows: List[Tuple[Dict[str, str], Any]] = []
        age_rows: List[Tuple[Dict[str, str], Any]] = []
        with self._lock:
            states = list(self._workers.values())
        for st in states:
            ok_rows.append(({"worker": st.label},
                            1.0 if st.error is None and st.last_success
                            else 0.0))
            age_rows.append(({"worker": st.label},
                             round(now - st.last_success, 3)
                             if st.last_success else -1.0))
        return {"cluster_scrape_ok": ("gauge", ok_rows),
                "cluster_scrape_age_seconds": ("gauge", age_rows)}

    def render_metrics(self) -> bytes:
        """The federated suffix of the gateway's ``/metrics`` body:
        merged worker families + scrape-health gauges."""
        with self._lock:
            per_worker = {label: st.families
                          for label, st in self._workers.items()
                          if st.families}
        merged = merge_worker_families(per_worker)
        merged.update(self._scrape_health_families())
        return render_families(merged).encode("utf-8")

    def cluster_payload(self) -> Dict[str, Any]:
        """``/debug/cluster`` body: per-worker scrape health + staleness
        + the gateway's last failover."""
        now = time.time()
        workers: Dict[str, Any] = {}
        with self._lock:
            states = list(self._workers.items())
        for label, st in states:
            workers[label] = {
                "ok": st.error is None and st.last_success > 0,
                "last_attempt": st.last_attempt or None,
                "last_success": st.last_success or None,
                "staleness_seconds": (round(now - st.last_success, 3)
                                      if st.last_success else None),
                "consecutive_failures": st.consecutive_failures,
                "error": st.error,
                "families": len(st.families),
            }
        payload = {"time": now, "interval_seconds": self.interval,
                   "workers": workers, "last_failover": self.last_failover}
        if self.breaker_states is not None:
            try:
                payload["breakers"] = dict(self.breaker_states())
            except Exception:  # noqa: BLE001 — diagnostics must not 500
                pass
        return payload
