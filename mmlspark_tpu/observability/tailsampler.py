"""Per-request tail sampler: stage timelines for objective breaches.

The burn-rate plane (:mod:`.slo`) says *that* an objective is being
missed; this module answers *where the tail went*. Every request that
breaches its endpoint's objective deposits its complete stage timeline
(``admission -> forming_wait -> score -> write``, the shared
``stage_breakdown`` vocabulary both engines stamp) plus its trace id
into a bounded reservoir — the gateway hop deposits its own record
under the same trace id, so a federated read stitches the edge->worker
path via the existing traceparent propagation.

Served at ``/debug/tail`` through the shared ``debug_body`` funnel and
rendered offline by ``tools/tail_report.py`` as a p99-attribution
breakdown ("tail is 72% forming_wait -> raise slots / add worker" vs
"tail is score -> see /debug/roofline").

The reservoir keeps the most recent ``MMLSPARK_TPU_TAIL_SAMPLES``
breaches (default 128) and counts what it evicts — a sustained breach
storm reports its true volume, not just the survivors. Stdlib-only
(``obs-import-cycle``); mutators are no-ops while telemetry is
disabled.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, Optional

from . import metrics as _metrics
from .env_registry import env_int

__all__ = ["TAIL_SAMPLES_ENV", "sample", "attribution",
           "snapshot_payload", "reset"]

TAIL_SAMPLES_ENV = "MMLSPARK_TPU_TAIL_SAMPLES"
_DEFAULT_CAPACITY = 128

_lock = threading.Lock()
_samples: Deque[Dict[str, Any]] = deque()
_capacity: Optional[int] = None
_sampled_total = 0
_dropped_total = 0


def _cap_locked() -> int:
    global _capacity
    if _capacity is None:
        _capacity = max(1, env_int(TAIL_SAMPLES_ENV, _DEFAULT_CAPACITY))
    return _capacity


def sample(api: str, seconds: float, status: int,
           stages: Optional[Dict[str, float]] = None,
           trace_id: Optional[str] = None, hop: str = "worker",
           breach: str = "latency") -> None:
    """Deposit one breaching request's timeline. ``stages`` is the
    ``stage_breakdown`` dict (None for requests that never scored —
    shed/timeout paths still sample, attributed to their status)."""
    global _sampled_total, _dropped_total
    if not _metrics.enabled():
        return
    seconds = float(seconds)
    dominant = None
    stage_sum = None
    if stages:
        stage_sum = sum(stages.values())
        dominant = max(stages, key=lambda s: stages[s])
    record = {"ts": time.time(), "api": api, "hop": hop,
              "seconds": seconds, "status": int(status),
              "breach": breach, "trace_id": trace_id,
              "stages": dict(stages) if stages else None,
              "stage_sum_seconds": stage_sum,
              "dominant_stage": dominant}
    with _lock:
        cap = _cap_locked()
        while len(_samples) >= cap:
            _samples.popleft()
            _dropped_total += 1
        _samples.append(record)
        _sampled_total += 1
    _metrics.safe_counter("tail_samples_total", api=api,
                          breach=breach).inc()


def attribution() -> Dict[str, Any]:
    """Aggregate stage attribution across the reservoir: per-stage
    share of the sampled tail seconds plus the dominant stage — the
    summary ``tools/tail_report.py`` renders remediation hints from."""
    with _lock:
        records = list(_samples)
    totals: Dict[str, float] = {}
    timed = 0
    for r in records:
        if not r["stages"]:
            continue
        timed += 1
        for stage, s in r["stages"].items():
            totals[stage] = totals.get(stage, 0.0) + s
    grand = sum(totals.values())
    shares = {stage: (100.0 * s / grand if grand else 0.0)
              for stage, s in totals.items()}
    dominant = max(shares, key=lambda s: shares[s]) if shares else None
    return {"samples": len(records), "samples_with_stages": timed,
            "stage_seconds": totals, "stage_share_pct": shares,
            "dominant_stage": dominant}


def snapshot_payload() -> Dict[str, Any]:
    """``/debug/tail`` body: reservoir stats, the aggregate
    attribution, and the sampled timelines (most recent last). Always
    renders — a disabled or breach-free process reports an honest
    empty reservoir."""
    with _lock:
        records = list(_samples)
        cap = _cap_locked()
        sampled, dropped = _sampled_total, _dropped_total
    return {"capacity": cap, "sampled_total": sampled,
            "dropped_total": dropped,
            "attribution": attribution(),
            "samples": records}


def reset() -> None:
    """Drop the reservoir and the cached capacity read (tests)."""
    global _sampled_total, _dropped_total, _capacity
    with _lock:
        _samples.clear()
        _sampled_total = 0
        _dropped_total = 0
        _capacity = None
