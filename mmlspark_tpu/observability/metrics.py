"""Process-wide metrics registry: counters, gauges, histograms.

The reference surfaces operational numbers through per-stage StopWatch
scopes (core/utils/StopWatch.scala, stages/Timer.scala:57-92) and VW's
TrainingStats; there is no shared place a serving endpoint or a bench
harness can read them back from. This module is that place for the TPU
rebuild: a thread-safe, label-aware :class:`MetricsRegistry` with a
Prometheus-text renderer, no external dependencies, and a single global
enable flag so every instrumentation site degrades to a cheap no-op
(mirroring utils/profiling.py's never-break-the-pipeline contract).

Conventions:

- metric names match ``[a-z_]+`` (enforced here and by tests/test_lint.py)
  so the Prometheus exposition stays valid without escaping;
- label values are free-form strings;
- histograms default to fixed log-scale latency buckets (100 us .. 60 s).

Usage::

    from mmlspark_tpu.observability import metrics
    metrics.counter("rows_ingested_total", stage="Featurize").inc(n)
    metrics.histogram("serving_request_seconds", api="my_api").observe(dt)
    text = metrics.get_registry().render_prometheus()
"""

from __future__ import annotations

import re
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "counter", "gauge", "histogram",
    "safe_counter", "safe_gauge", "safe_histogram",
    "get_registry", "set_registry", "reset",
    "enabled", "set_enabled",
    "DEFAULT_BUCKETS", "NOOP",
]

_NAME_RE = re.compile(r"^[a-z_]+$")

# Log-scale (1 / 2.5 / 5 per decade) latency ladder: 100 us to 60 s. Wide
# enough for an in-process transform and a cross-host serving hop alike.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 60.0,
)


class _Metric:
    """One labeled series. Subclasses hold their own state; all mutation
    goes through the owning registry's lock (cheap: a few ops per call)."""

    def __init__(self, lock: threading.Lock):
        self._lock = lock


class Counter(_Metric):
    """Monotonically increasing count."""

    def __init__(self, lock: threading.Lock):
        super().__init__(lock)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge(_Metric):
    """A value that can go up and down (queue depth, bytes in use)."""

    def __init__(self, lock: threading.Lock):
        super().__init__(lock)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        return self._value


class Histogram(_Metric):
    """Fixed-bucket histogram (log-scale latency ladder by default)."""

    def __init__(self, lock: threading.Lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(lock)
        self.buckets: Tuple[float, ...] = tuple(sorted(float(b)
                                                       for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        # linear scan beats bisect for ~18 buckets and typical small values
        i = 0
        n = len(self.buckets)
        while i < n and v > self.buckets[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def count(self) -> int:
        return self._count

    def bucket_counts(self) -> Dict[float, int]:
        """CUMULATIVE counts keyed by upper bound (+Inf as float('inf'))."""
        with self._lock:
            return self._bucket_counts_locked()

    def _bucket_counts_locked(self) -> Dict[float, int]:
        # caller must hold self._lock (non-reentrant, hence the split —
        # the registry's consistent-scrape read shares this with
        # bucket_counts so cumulative semantics live in one place)
        out: Dict[float, int] = {}
        acc = 0
        for b, c in zip(self.buckets, self._counts):
            acc += c
            out[b] = acc
        out[float("inf")] = acc + self._counts[-1]
        return out


class _NoopMetric:
    """Disabled-path stand-in: accepts every mutation, records nothing."""

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    value = 0.0
    sum = 0.0
    count = 0

    def bucket_counts(self) -> Dict[float, int]:
        return {}


NOOP = _NoopMetric()

_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Thread-safe family-of-labeled-series store.

    ``counter/gauge/histogram(name, **labels)`` returns the (created-once)
    series for that label set; the same call is both registration and
    lookup, so instrumentation sites stay one-liners.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # name -> (kind, {label_items_tuple: metric}, extra)
        self._families: Dict[str, Tuple[str, Dict[Tuple, _Metric], dict]] = {}

    # -- registration / lookup ---------------------------------------------
    def _series(self, kind: str, name: str, labels: Dict[str, str],
                **extra) -> _Metric:
        if not _NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} must match [a-z_]+ (keeps the "
                "Prometheus exposition valid)")
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = (kind, {}, extra)
                self._families[name] = fam
            elif fam[0] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam[0]}, "
                    f"requested {kind}")
            elif kind == "histogram" and extra.get("buckets") is not None:
                cur = fam[2].get("buckets") or DEFAULT_BUCKETS
                req = tuple(sorted(float(b) for b in extra["buckets"]))
                if req != tuple(sorted(float(b) for b in cur)):
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {tuple(cur)}, requested {req}")
            series = fam[1].get(key)
            if series is None:
                if kind == "histogram":
                    series = Histogram(self._lock,
                                       fam[2].get("buckets")
                                       or DEFAULT_BUCKETS)
                else:
                    series = _KINDS[kind](self._lock)
                fam[1][key] = series
            return series

    def counter(self, name: str, /, **labels: Any) -> Counter:
        return self._series("counter", name, labels)  # type: ignore

    def gauge(self, name: str, /, **labels: Any) -> Gauge:
        return self._series("gauge", name, labels)  # type: ignore

    def histogram(self, name: str, /,
                  buckets: Optional[Sequence[float]] = None,
                  **labels: Any) -> Histogram:
        return self._series("histogram", name, labels,  # type: ignore
                            buckets=tuple(buckets) if buckets else None)

    def reset(self) -> None:
        """Drop every family — tests get a clean slate."""
        with self._lock:
            self._families.clear()

    # -- export -------------------------------------------------------------
    def _read_families(self) -> Dict[str, Tuple[str, Dict[Tuple, tuple]]]:
        """Point-in-time copy of every series taken under ONE lock hold, so
        a histogram's count/sum/buckets are mutually consistent (a scrape
        racing observe() must never show _count != the +Inf bucket). Reads
        metric privates directly: bucket_counts() etc. re-acquire the same
        non-reentrant lock."""
        out: Dict[str, Tuple[str, Dict[Tuple, tuple]]] = {}
        with self._lock:
            for name, (kind, series, _) in self._families.items():
                rows: Dict[Tuple, tuple] = {}
                for key, m in series.items():
                    if kind == "histogram":
                        rows[key] = (m._count, m._sum,
                                     m._bucket_counts_locked())
                    else:
                        rows[key] = (m._value,)
                out[name] = (kind, rows)
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Plain-dict view (JSON-safe): one entry per family, one series per
        label set. bench.py dumps this next to its BENCH_*.json lines."""
        out: Dict[str, Any] = {}
        for name, (kind, series) in sorted(self._read_families().items()):
            rows: List[Dict[str, Any]] = []
            for key, vals in sorted(series.items()):
                row: Dict[str, Any] = {"labels": dict(key)}
                if kind == "histogram":
                    count, total, buckets = vals
                    row["count"] = count
                    row["sum"] = total
                    row["buckets"] = {_fmt(b): c for b, c in buckets.items()}
                else:
                    row["value"] = vals[0]
                rows.append(row)
            out[name] = {"type": kind, "series": rows}
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        for name, (kind, series) in sorted(self._read_families().items()):
            lines.append(f"# TYPE {name} {kind}")
            for key, vals in sorted(series.items()):
                base = dict(key)
                if kind == "histogram":
                    count, total, buckets = vals
                    for b, c in buckets.items():
                        lines.append(_sample(f"{name}_bucket",
                                             {**base, "le": _fmt(b)}, c))
                    lines.append(_sample(f"{name}_sum", base, total))
                    lines.append(_sample(f"{name}_count", base, count))
                else:
                    lines.append(_sample(name, base, vals[0]))
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt(v: float) -> str:
    """Short form for bucket-bound ``le`` labels only ('0.005', '+Inf')."""
    if v == float("inf"):
        return "+Inf"
    return format(v, "g")


def _fmt_value(v: Any) -> str:
    """Full-precision sample value: 'g' would round to 6 significant
    digits, corrupting any counter past ~1e6 (and multi-GB gauges)."""
    f = float(v)
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f.is_integer() and abs(f) < 2**53:
        return str(int(f))
    return repr(f)


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n").replace('"', r"\"")


def _sample(name: str, labels: Dict[str, str], value: Any) -> str:
    if labels:
        body = ",".join(f'{k}="{_escape(str(v))}"'
                        for k, v in sorted(labels.items()))
        return f"{name}{{{body}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


# ---------------------------------------------------------------------------
# Global registry + enable flag
# ---------------------------------------------------------------------------

_registry = MetricsRegistry()
_enabled = True


def get_registry() -> MetricsRegistry:
    return _registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-wide registry (returns the previous one)."""
    global _registry
    prev, _registry = _registry, registry
    return prev


def reset() -> None:
    _registry.reset()


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> bool:
    """Flip the global telemetry flag; returns the previous value.

    Disabled means every ``counter/gauge/histogram`` helper returns a
    shared no-op and span recording stops — instrumented code paths keep
    exactly their uninstrumented behavior.
    """
    global _enabled
    prev, _enabled = _enabled, bool(on)
    return prev


def counter(name: str, /, **labels: Any) -> Counter:
    if not _enabled:
        return NOOP  # type: ignore[return-value]
    return _registry.counter(name, **labels)


def gauge(name: str, /, **labels: Any) -> Gauge:
    if not _enabled:
        return NOOP  # type: ignore[return-value]
    return _registry.gauge(name, **labels)


def histogram(name: str, /, buckets: Optional[Sequence[float]] = None,
              **labels: Any) -> Histogram:
    if not _enabled:
        return NOOP  # type: ignore[return-value]
    return _registry.histogram(name, buckets=buckets, **labels)


# Never-raising variants for framework instrumentation sites (pipeline
# wrappers, serving workers, request handlers): a registry conflict there
# (kind/bucket mismatch with a name the user registered first) must
# degrade to a no-op, not kill the worker thread or drop a response —
# the never-break-the-pipeline contract. Direct/user call sites should
# keep using counter/gauge/histogram, which raise loudly on misuse.

def safe_counter(name: str, /, **labels: Any) -> Counter:
    try:
        return counter(name, **labels)
    except Exception:
        return NOOP  # type: ignore[return-value]


def safe_gauge(name: str, /, **labels: Any) -> Gauge:
    try:
        return gauge(name, **labels)
    except Exception:
        return NOOP  # type: ignore[return-value]


def safe_histogram(name: str, /, buckets: Optional[Sequence[float]] = None,
                   **labels: Any) -> Histogram:
    try:
        return histogram(name, buckets=buckets, **labels)
    except Exception:
        return NOOP  # type: ignore[return-value]
