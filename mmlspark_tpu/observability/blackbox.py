"""Fleet black-box recorder: the flight ring that survives its process.

Every per-process surface (flight ring, spans, SLO burn, tail samples)
dies with its process — a SIGKILLed worker takes its last seconds with
it. This module is the gateway-side answer: a bounded
:class:`FleetTimeline` the :class:`~.federation.MetricsFederator` sweep
feeds by pulling incremental ``/debug/flight?since=<seq>`` deltas from
every registered worker, merged with the gateway's own ring and with
worker lifecycle transitions (register/deregister, scrape death and
recovery, restarts, breaker flips arriving as flight events, autoscale
hints crossing 1.0) recorded as first-class timeline events.

The timeline is served at ``/debug/timeline``, dumped on
SIGUSR2/excepthook alongside the local ring (via
``flight.add_dump_callback``), and is the substrate for distributed
trace assembly: ``/debug/trace?id=<trace_id>`` groups timeline + span
events by ``trace_id`` into the stitched edge→gateway→worker tree, with
a Chrome trace-event export built on the one timebase every process
shares (wall clock).

Dedup contract: events are keyed ``(worker, seq)`` — the per-worker
scrape cursor only ever advances, so an event can enter the timeline at
most once even across scrape retries, worker deregister/re-register,
and ring wrap on the worker side. A pid change under the same label
resets the cursor (new process, new seq space) and records a
``worker_restarted`` lifecycle event.

Knobs: ``MMLSPARK_TPU_TIMELINE_EVENTS`` caps the timeline ring (default
8192); ``MMLSPARK_TPU_FLIGHT_SCRAPE=0`` disables the flight-delta pull
(the /metrics sweep continues untouched). Everything here is inert
behind the global telemetry kill switch.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from . import env_registry as _env
from . import flight as _flight
from . import metrics as _metrics
from . import spans as _spans

__all__ = [
    "FleetTimeline", "assemble_trace", "local_trace_payload",
    "flight_scrape_enabled", "DEFAULT_TIMELINE_EVENTS",
    "TIMELINE_EVENTS_ENV", "FLIGHT_SCRAPE_ENV",
]

TIMELINE_EVENTS_ENV = "MMLSPARK_TPU_TIMELINE_EVENTS"
FLIGHT_SCRAPE_ENV = "MMLSPARK_TPU_FLIGHT_SCRAPE"
DEFAULT_TIMELINE_EVENTS = 8192

_FALSY = frozenset({"0", "false", "no", "off"})


def flight_scrape_enabled() -> bool:
    """The ``MMLSPARK_TPU_FLIGHT_SCRAPE`` toggle (default on). When off,
    the federation sweep never issues a ``/debug/flight`` request and
    never touches the timeline — byte-identical to the pre-timeline
    sweep."""
    return os.environ.get(FLIGHT_SCRAPE_ENV, "").strip().lower() \
        not in _FALSY


def _env_capacity() -> int:
    return max(1, _env.env_int(TIMELINE_EVENTS_ENV, DEFAULT_TIMELINE_EVENTS))


class FleetTimeline:
    """Bounded, thread-safe merge of a fleet's flight rings plus
    gateway-observed lifecycle transitions, in causal order."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = _env_capacity()
        self._lock = threading.Lock()
        self._buf: "collections.deque" = collections.deque(
            maxlen=max(1, int(capacity)))
        self._dropped = 0
        self._arrival = 0
        self._cursors: Dict[str, int] = {}
        self._pids: Dict[str, Any] = {}

    # -- ingest --------------------------------------------------------------
    def cursor(self, worker: str) -> int:
        """The next ``?since=`` value for ``worker`` (0 before any
        merge)."""
        with self._lock:
            return self._cursors.get(worker, 0)

    def extend(self, worker: str, snap: Dict[str, Any]) -> int:
        """Merge one worker's ``/debug/flight`` payload (full or
        ``?since=`` delta); returns the number of events added.

        Only events with ``seq >`` the stored cursor merge — the
        ``(worker, seq)`` dedup key. The payload's ``last_seq`` advances
        the cursor past events the worker's ring already evicted, so a
        slow scraper never re-requests a hole it can no longer fill."""
        events = snap.get("events") or []
        pid = snap.get("pid")
        restarted = False
        prev_pid = None
        with self._lock:
            cur = self._cursors.get(worker, 0)
            prev_pid = self._pids.get(worker)
            if pid is not None:
                if prev_pid is not None and prev_pid != pid:
                    restarted, cur = True, 0
                self._pids[worker] = pid
            added = 0
            for ev in events:
                seq = ev.get("seq")
                if not isinstance(seq, int) or seq <= cur:
                    continue
                cur = seq
                self._append_locked({**ev, "worker": worker,
                                     "source": "flight"})
                added += 1
            last = snap.get("last_seq")
            if isinstance(last, int) and last > cur:
                cur = last
            self._cursors[worker] = cur
        if restarted:
            self.lifecycle("worker_restarted", worker=worker,
                           pid=pid, prev_pid=prev_pid)
        return added

    def lifecycle(self, kind: str, worker: Optional[str] = None,
                  **fields: Any) -> None:
        """Record a fleet transition (register/deregister/scrape-death/
        restart/autoscale crossing) as a first-class timeline event."""
        if not _metrics.enabled():
            return
        ev: Dict[str, Any] = {"kind": kind, "ts": time.time(),
                              "source": "lifecycle"}
        if worker is not None:
            ev["worker"] = worker
        ev.update(fields)
        with self._lock:
            self._append_locked(ev)

    def _append_locked(self, ev: Dict[str, Any]) -> None:
        self._arrival += 1  # graftlint: disable=lock-discipline (caller holds self._lock; _append_locked is only reached from under it)
        ev["timeline_seq"] = self._arrival
        if len(self._buf) == self._buf.maxlen:
            self._dropped += 1  # graftlint: disable=lock-discipline (caller holds self._lock; deque maxlen evicts the oldest)
        self._buf.append(ev)

    def forget(self, worker: str) -> None:
        """Drop cursor/pid state for ``worker`` (tests; NOT called on
        deregister — keeping the cursor is what makes a deregister +
        re-register of the same process duplicate-free)."""
        with self._lock:
            self._cursors.pop(worker, None)
            self._pids.pop(worker, None)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._cursors.clear()
            self._pids.clear()
            self._dropped = 0
            self._arrival = 0

    # -- views ---------------------------------------------------------------
    def capacity(self) -> int:
        return self._buf.maxlen or DEFAULT_TIMELINE_EVENTS

    def dropped(self) -> int:
        return self._dropped

    def events(self) -> List[Dict[str, Any]]:
        """Causal-order copy: sorted by each event's wall-clock ``ts``
        (the one timebase all processes share), gateway arrival order as
        the tiebreak."""
        with self._lock:
            evs = [dict(e) for e in self._buf]
        evs.sort(key=lambda e: (float(e.get("ts") or 0.0),
                                e.get("timeline_seq") or 0))
        return evs

    def workers(self) -> List[str]:
        with self._lock:
            return sorted(self._cursors)

    def trace_ids(self, limit: int = 50) -> List[str]:
        """Distinct trace ids present, newest-first (the ``/debug/trace``
        listing when no ``?id=`` is given)."""
        seen: List[str] = []
        with self._lock:
            evs = list(self._buf)
        for ev in reversed(evs):
            tid = ev.get("trace_id")
            if tid and tid not in seen:
                seen.append(tid)
                if len(seen) >= limit:
                    break
        return seen

    def snapshot_payload(self) -> Dict[str, Any]:
        """The ``/debug/timeline`` body (and the dump format)."""
        with self._lock:
            cursors = dict(self._cursors)
            pids = dict(self._pids)
            drop = self._dropped
        return {
            "pid": os.getpid(),
            "time": time.time(),
            "capacity": self.capacity(),
            "dropped": drop,
            "scrape_enabled": flight_scrape_enabled(),
            "cursors": cursors,
            "worker_pids": pids,
            "events": self.events(),
        }

    def trace_payload(self, trace_id: Optional[str]) -> Dict[str, Any]:
        """The ``/debug/trace`` body: the stitched tree for one trace,
        or the id listing when none is named."""
        if not trace_id:
            return {"trace_id": None, "trace_ids": self.trace_ids(),
                    "note": "pass ?id=<trace_id> (32 hex) to stitch one "
                            "trace; ids listed newest-first from the "
                            "fleet timeline"}
        return assemble_trace(trace_id, self.events(),
                              _span_events_for(trace_id))

    # -- persistence / crash hook --------------------------------------------
    def dump(self, path: Optional[str] = None) -> str:
        """Write the timeline next to the flight ring's dumps (same
        naming funnel, ``timeline-`` prefix); returns the path."""
        if path is None:
            path = _flight.dump_path("timeline")
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "wb") as f:
            f.write(json.dumps(self.snapshot_payload(),
                               default=repr).encode("utf-8"))
        return path

    def install_dump_hook(self) -> None:
        """Dump alongside the ring on SIGUSR2/excepthook (idempotent)."""
        _flight.add_dump_callback(self.dump)

    def uninstall_dump_hook(self) -> None:
        _flight.remove_dump_callback(self.dump)


# ---------------------------------------------------------------------------
# Distributed trace assembly
# ---------------------------------------------------------------------------

def _span_events_for(trace_id: str) -> List[Dict[str, Any]]:
    """This process's span-buffer events belonging to ``trace_id``
    (Chrome 'X' records; their ``ts`` is perf_counter-based, so they
    ride the payload as-is but stay out of the wall-clock export)."""
    out = []
    for e in _spans.get_trace_events():
        args = e.get("args") or {}
        if args.get("trace_id") == trace_id:
            out.append(dict(e))
    return out


def _chrome_trace(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome trace-event doc from wall-clock timeline events: one fake
    pid per worker label (named via process_name metadata), ``span_end``
    events rendered as duration slices (start = ts - dur), everything
    else as instants. Loads in chrome://tracing / ui.perfetto.dev."""
    pids: Dict[str, int] = {}
    rows: List[Dict[str, Any]] = []
    for ev in events:
        worker = str(ev.get("worker") or f"pid:{ev.get('pid', '?')}")
        pid = pids.setdefault(worker, len(pids) + 1)
        ts_us = float(ev.get("ts") or 0.0) * 1e6
        base = {
            "cat": "mmlspark_fleet", "pid": pid,
            "tid": int(ev.get("tid") or 0) % 100000,
            "args": {k: v for k, v in ev.items() if k != "ts"},
        }
        dur_us = ev.get("dur_us")
        if ev.get("kind") == "span_end" and dur_us:
            rows.append({**base, "name": str(ev.get("name") or "span"),
                         "ph": "X", "ts": ts_us - float(dur_us),
                         "dur": float(dur_us)})
        else:
            rows.append({**base, "name": str(ev.get("kind") or "event"),
                         "ph": "i", "s": "p", "ts": ts_us})
    meta = [{"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": worker}} for worker, pid in pids.items()]
    return {"traceEvents": meta + rows, "displayTimeUnit": "ms",
            "otherData": {"timebase": "wall_clock_us"}}


def assemble_trace(trace_id: str, events: List[Dict[str, Any]],
                   span_events: Optional[List[Dict[str, Any]]] = None
                   ) -> Dict[str, Any]:
    """Group ``events`` (timeline or flight records) by hop for one
    ``trace_id``: the stitched edge→gateway→worker tree. Hops appear in
    causal order — the gateway's edge-ingress ``gateway_request`` span
    lands first, the worker hop after it — each with its events and
    first/last timestamps; a Chrome trace export rides along."""
    evs = sorted((e for e in events if e.get("trace_id") == trace_id),
                 key=lambda e: (float(e.get("ts") or 0.0),
                                e.get("timeline_seq") or 0))
    order: List[str] = []
    hops: Dict[str, List[Dict[str, Any]]] = {}
    for ev in evs:
        w = str(ev.get("worker") or "local")
        if w not in hops:
            hops[w] = []
            order.append(w)
        hops[w].append(ev)
    tree = [{
        "hop": w,
        "role": "gateway" if w == "gateway" else "worker",
        "first_ts": hops[w][0].get("ts"),
        "last_ts": hops[w][-1].get("ts"),
        "events": hops[w],
    } for w in order]
    return {
        "trace_id": trace_id,
        "found": bool(evs),
        "hops": order,
        "tree": tree,
        "events": evs,
        "spans": span_events or [],
        "chrome_trace": _chrome_trace(evs),
    }


def local_trace_payload(trace_id: Optional[str]) -> Dict[str, Any]:
    """``/debug/trace`` on a non-gateway process: this process's own hop
    only, from its flight ring + span buffer (the gateway's view is the
    stitched one)."""
    label = f"local:{os.getpid()}"
    evs = [{**e, "worker": label} for e in _flight.events()]
    if not trace_id:
        seen: List[str] = []
        for ev in reversed(evs):
            tid = ev.get("trace_id")
            if tid and tid not in seen:
                seen.append(tid)
                if len(seen) >= 50:
                    break
        return {"trace_id": None, "trace_ids": seen, "federation": None,
                "note": "no federation in this process — local hop only; "
                        "the stitched fleet view lives on the "
                        "distributed-serving gateway. Pass ?id=<trace_id> "
                        "to view one local trace."}
    payload = assemble_trace(trace_id, evs, _span_events_for(trace_id))
    payload["federation"] = None
    payload["note"] = ("local hop only (no federation in this process); "
                       "the stitched edge→gateway→worker view lives on "
                       "the gateway")
    return payload
