"""Structured JSON log funnel: every framework log line, one pipe.

PRs 1 and 3 made the framework *measurable* (metrics, spans, traces,
flight ring); its textual output stayed ad-hoc — scattered ``print``s and
stdlib loggers that carry no trace identity and can't be collected from a
pod of workers. This module is the single funnel: :func:`get_logger`
returns a named logger whose records are JSON objects stamped with the
active trace context (``trace_id`` / ``span_id``), the process identity
fields (``process_index`` / ``role``, via :func:`set_default_fields`),
and free-form structured fields — written as one JSON line per record and
mirrored into the flight recorder's ring, so a crash dump interleaves the
process's last log lines with its span ends and errors in one sequence.

Controls (all env-overridable, all settable at runtime for tests):

- ``MMLSPARK_TPU_LOG_LEVEL`` — ``debug`` / ``info`` / ``warning`` /
  ``error`` (default ``info``).
- ``MMLSPARK_TPU_LOG_FILE`` — append JSON lines here instead of stderr.
- ``MMLSPARK_TPU_LOG_RATE`` — per-logger records/second cap (default
  200; 0 = unlimited). Overflow drops records, bumps
  ``log_records_dropped_total{logger=...}``, and emits ONE suppression
  notice when the window reopens — a hot loop cannot flood the sink.

Contracts (shared with the rest of ``observability``):

- **Kill-switch inert.** While ``metrics.set_enabled(False)`` every log
  call is a byte-identical no-op: no sink write, no flight event, no
  counter — instrumented paths keep exactly their uninstrumented
  behavior.
- **Never raises.** A full disk, a closed pipe, or an unserializable
  field degrades to silence (values fall back to ``repr``), never to an
  exception in the serving or training path.
- **One escape hatch.** :func:`console` is the sanctioned raw-output
  path for CLI ready-lines and crash-path notices that external
  orchestration parses (``tests/test_lint.py`` forbids bare ``print`` /
  ``sys.stderr.write`` / ``logging.getLogger`` everywhere else under
  ``mmlspark_tpu/``).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, Optional, TextIO

from . import flight as _flight
from . import metrics as _metrics
from . import tracing as _tracing

__all__ = [
    "get_logger", "StructuredLogger", "console",
    "get_level", "set_level", "set_log_file", "set_rate_limit",
    "set_default_fields", "LEVELS",
]

_LEVEL_ENV = "MMLSPARK_TPU_LOG_LEVEL"
_FILE_ENV = "MMLSPARK_TPU_LOG_FILE"
_RATE_ENV = "MMLSPARK_TPU_LOG_RATE"

LEVELS: Dict[str, int] = {"debug": 10, "info": 20, "warning": 30,
                          "error": 40}


def _env_level() -> str:
    lvl = (os.environ.get(_LEVEL_ENV) or "info").strip().lower()
    return lvl if lvl in LEVELS else "info"


def _env_rate() -> float:
    try:
        return max(0.0, float(os.environ.get(_RATE_ENV, "") or 200.0))
    except ValueError:
        return 200.0


# RLock: the emit path resolves the sink under the lock, and that
# resolution may itself call set_log_file (env-pointed file, opened once)
_lock = threading.RLock()
_level_no = LEVELS[_env_level()]
_rate_limit = _env_rate()
_default_fields: Dict[str, Any] = {}
_loggers: Dict[str, "StructuredLogger"] = {}
# explicit sink set via set_log_file(); None means "resolve from env/stderr"
_sink: Optional[TextIO] = None
_sink_path: Optional[str] = None
# a path whose open() failed: never re-attempted per record (records fall
# back to stderr instead of silently vanishing behind a broken path)
_sink_failed: Optional[str] = None


def get_level() -> str:
    for name, no in LEVELS.items():
        if no == _level_no:
            return name
    return "info"


def set_level(level: str) -> str:
    """Set the funnel threshold; returns the previous level name
    (env default: ``MMLSPARK_TPU_LOG_LEVEL``)."""
    global _level_no
    prev = get_level()
    _level_no = LEVELS.get(str(level).strip().lower(), _level_no)
    return prev


def set_rate_limit(records_per_second: float) -> float:
    """Per-logger throughput cap; 0 disables limiting. Returns the
    previous cap (env default: ``MMLSPARK_TPU_LOG_RATE``)."""
    global _rate_limit
    prev, _rate_limit = _rate_limit, max(0.0, float(records_per_second))
    return prev


def set_log_file(path: Optional[str]) -> None:
    """Redirect the JSON-line sink (None: back to
    ``MMLSPARK_TPU_LOG_FILE`` or stderr). Closes a previously-set file.
    An unopenable path degrades to stderr — with ONE console notice,
    never one failed ``open()`` per record."""
    global _sink, _sink_path, _sink_failed
    with _lock:
        if _sink is not None:
            try:
                _sink.close()
            except Exception:  # noqa: BLE001
                pass
        _sink, _sink_path, _sink_failed = None, path, None
        if path:
            try:
                _sink = open(path, "a", encoding="utf-8")
            except OSError as e:
                _sink_failed = path
                console(f"[logging] cannot open {path!r} ({e}); "
                        "falling back to stderr", err=True)


def set_default_fields(**fields: Any) -> None:
    """Fields stamped onto every subsequent record (``process_index`` on
    multi-host runs, ``role`` on serving deployments); a None value
    removes the field. Replace-on-write, mirroring
    ``flight.set_default_fields``."""
    global _default_fields
    merged = {**_default_fields, **fields}
    _default_fields = {k: v for k, v in merged.items() if v is not None}


def _resolve_sink() -> TextIO:
    if _sink is not None:
        return _sink
    path = os.environ.get(_FILE_ENV)
    if path and path not in (_sink_failed, _sink_path):
        # env-pointed file: open once and pin (the common deployment
        # case); a failed open is remembered so it is not retried here
        set_log_file(path)
        return _sink if _sink is not None else sys.stderr
    return sys.stderr


def _emit_line(record: Dict[str, Any]) -> None:
    line = json.dumps(record, default=repr)
    with _lock:
        sink = _resolve_sink()
        sink.write(line + "\n")
        sink.flush()


class StructuredLogger:
    """One named pipe into the funnel. ``debug/info/warning/error`` accept
    printf-style positional args (stdlib-logger call sites port verbatim)
    plus structured keyword fields."""

    def __init__(self, name: str):
        self.name = name
        # rate-limit window state: [window_start_monotonic, emitted, dropped]
        self._win = [0.0, 0, 0]

    # -- rate limiting ------------------------------------------------------
    def _admit(self, now: float) -> bool:
        """One-second sliding window per logger. Returns False (and counts
        the drop) when the cap is hit; on window rollover a single
        suppression record reports what was lost."""
        if _rate_limit <= 0:
            return True
        with _lock:
            start, emitted, dropped = self._win
            if now - start >= 1.0:
                self._win = [now, 1, 0]
                suppressed = dropped
            else:
                if emitted >= _rate_limit:
                    self._win[2] += 1
                    return False
                self._win[1] += 1
                suppressed = 0
        if suppressed:
            self._record("warning", "rate limit: suppressed "
                         f"{suppressed} records in the last window",
                         _limited=True, suppressed=suppressed)
        return True

    # -- record path --------------------------------------------------------
    def _record(self, level: str, msg: str, *args: Any,
                _limited: bool = False, **fields: Any) -> None:
        try:
            if args:
                try:
                    msg = msg % args
                except Exception:  # noqa: BLE001 — bad format never raises
                    msg = f"{msg} {args!r}"
            now = time.monotonic()
            if not _limited and not self._admit(now):
                _metrics.safe_counter("log_records_dropped_total",
                                      logger=self.name).inc()
                return
            rec: Dict[str, Any] = {"ts": time.time(), "level": level,
                                   "logger": self.name, "msg": str(msg),
                                   "pid": os.getpid()}
            if _default_fields:
                rec.update(_default_fields)
            ctx = _tracing.current()
            if ctx is not None:
                rec.setdefault("trace_id", ctx.trace_id)
                rec.setdefault("span_id", ctx.span_id)
            for k, v in fields.items():
                rec.setdefault(k, v)
            _emit_line(rec)
            _metrics.safe_counter("log_records_total", level=level).inc()
            # ring-buffer the record: a flight dump interleaves the last
            # log lines with span ends / errors in one event sequence
            _flight.record("log", level=level, logger=self.name,
                           msg=rec["msg"],
                           **{k: v for k, v in fields.items()
                              if k not in ("kind", "level", "logger", "msg")})
        except Exception:  # noqa: BLE001 — logging must never break callers
            pass

    def _log(self, level: str, msg: str, *args: Any, **fields: Any) -> None:
        # the kill switch AND the level gate live here so a disabled or
        # filtered call costs two comparisons and allocates nothing
        if not _metrics.enabled() or LEVELS[level] < _level_no:
            return
        self._record(level, msg, *args, **fields)

    def debug(self, msg: str, *args: Any, **fields: Any) -> None:
        self._log("debug", msg, *args, **fields)

    def info(self, msg: str, *args: Any, **fields: Any) -> None:
        self._log("info", msg, *args, **fields)

    def warning(self, msg: str, *args: Any, **fields: Any) -> None:
        self._log("warning", msg, *args, **fields)

    def error(self, msg: str, *args: Any, **fields: Any) -> None:
        self._log("error", msg, *args, **fields)


def get_logger(name: str) -> StructuredLogger:
    """The (created-once) named logger — the one way framework code logs."""
    with _lock:
        lg = _loggers.get(name)
        if lg is None:
            lg = _loggers[name] = StructuredLogger(name)
        return lg


def console(msg: str, err: bool = False) -> None:
    """Unconditional plain line to stdout (or stderr with ``err=True``).

    The sanctioned raw-output path: CLI ready-lines that external
    orchestration parses (``serving_main``'s ``worker ... serving on``)
    and crash-path notices (flight dump locations) must reach their
    stream regardless of the telemetry kill switch — they are process
    lifecycle output, not telemetry.
    """
    stream = sys.stderr if err else sys.stdout
    try:
        stream.write(str(msg) + "\n")
        stream.flush()
    except Exception:  # noqa: BLE001 — a closed pipe must not kill the host
        pass


def _reset_for_tests() -> None:
    """Restore module defaults (level/rate from env, stderr sink, no
    default fields, fresh per-logger windows)."""
    global _level_no, _rate_limit, _default_fields
    set_log_file(None)
    _level_no = LEVELS[_env_level()]
    _rate_limit = _env_rate()
    _default_fields = {}
    with _lock:
        for lg in _loggers.values():
            lg._win = [0.0, 0, 0]
