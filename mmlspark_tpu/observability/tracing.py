"""Per-request trace context propagated across serving processes.

PR 1 gave every process local spans and a registry; a request that
crosses the serving edge -> distributed gateway -> worker boundary still
lost its identity at each HTTP hop, so dumps from different processes
could not be stitched into one story. This module is the correlation
layer: a contextvar-held :class:`TraceContext` (``trace_id`` /
``span_id`` / ``parent_id``), W3C-traceparent-style header encoding for
the hops, and the slow-request exemplar buffer that attaches trace ids
to latency outliers.

Design rules (shared with the rest of ``observability``):

- **One module owns the header names.** ``TRACEPARENT_HEADER`` and
  ``REQUEST_ID_HEADER`` are the only place those strings exist in the
  framework — ``tests/test_lint.py`` rejects literals at call sites, so
  the wire contract cannot drift per hop.
- **Kill-switch inert.** While ``metrics.set_enabled(False)``,
  extraction returns ``None``, injection adds nothing, and exemplars
  don't record — instrumented paths keep byte-identical behavior.
- **Never breaks the request it labels.** Parsing is total (malformed
  headers yield a fresh context, never an exception).
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import os
import re
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Deque, Dict, Iterator, List, Mapping, Optional

from . import metrics as _metrics

__all__ = [
    "TraceContext", "TRACEPARENT_HEADER", "REQUEST_ID_HEADER",
    "new_context", "child_context", "current", "activate", "deactivate",
    "use", "format_traceparent", "parse_traceparent",
    "context_from_headers", "inject_headers", "outbound_headers",
    "get_slow_threshold", "set_slow_threshold", "maybe_mark_slow",
    "get_exemplars", "clear_exemplars",
]

#: W3C trace-context propagation header (lowercase: HTTP header names are
#: case-insensitive and our parked-request dicts store lowercase keys).
TRACEPARENT_HEADER = "traceparent"
#: Response header echoing the request's trace id back to the caller.
REQUEST_ID_HEADER = "X-Request-Id"

_TRACE_ID_RE = re.compile(r"^[0-9a-f]{32}$")
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


@dataclass(frozen=True)
class TraceContext:
    """One hop's identity inside a distributed request.

    ``trace_id`` is shared by every hop of one request; ``span_id`` is
    this hop's own id; ``parent_id`` is the upstream hop's span id (None
    at the originating edge).
    """

    trace_id: str
    span_id: str
    parent_id: Optional[str] = None


_current: "contextvars.ContextVar[Optional[TraceContext]]" = \
    contextvars.ContextVar("mmlspark_tpu_trace_context", default=None)


def new_context() -> TraceContext:
    """Fresh root context (a request entering at this process)."""
    return TraceContext(trace_id=uuid.uuid4().hex,
                        span_id=uuid.uuid4().hex[:16])


def child_context(ctx: Optional[TraceContext] = None) -> TraceContext:
    """A downstream hop of ``ctx`` (default: the active context): same
    trace, fresh span id, parent pointing at the originating hop."""
    ctx = ctx if ctx is not None else _current.get()
    if ctx is None:
        return new_context()
    return TraceContext(trace_id=ctx.trace_id,
                        span_id=uuid.uuid4().hex[:16],
                        parent_id=ctx.span_id)


def current() -> Optional[TraceContext]:
    """The active trace context in this thread/task (None outside one)."""
    return _current.get()


def activate(ctx: TraceContext) -> "contextvars.Token":
    """Make ``ctx`` the active context; pass the token to
    :func:`deactivate` (contextvar discipline keeps concurrent serving
    threads from seeing each other's requests)."""
    return _current.set(ctx)


def deactivate(token: "contextvars.Token") -> None:
    _current.reset(token)


@contextlib.contextmanager
def use(ctx: TraceContext) -> Iterator[TraceContext]:
    """``with use(ctx):`` — scoped :func:`activate`/:func:`deactivate`."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


# ---------------------------------------------------------------------------
# Header encoding (W3C trace-context traceparent, version 00)
# ---------------------------------------------------------------------------


def format_traceparent(ctx: TraceContext) -> str:
    """``00-{trace_id}-{span_id}-01`` (sampled flag always set: sampling
    decisions belong to the collector, not the serving hot path)."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def parse_traceparent(value: Optional[str]) -> Optional[TraceContext]:
    """Total parse: malformed/None input yields None, never an exception.
    The returned context carries the SENDER's span id; receivers should
    derive a child (see :func:`context_from_headers`)."""
    if not value or not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None or m.group(1) == "ff":        # ff is a forbidden version
        return None
    if m.group(2) == "0" * 32 or m.group(3) == "0" * 16:
        return None                            # all-zero ids are invalid
    return TraceContext(trace_id=m.group(2), span_id=m.group(3))


def _header_get(headers: Mapping[str, str], name: str) -> Optional[str]:
    """Tolerant lookup: email.Message headers are case-insensitive, the
    parked-request dicts are lowercase, user dicts may be anything."""
    v = headers.get(name)
    if v is None:
        v = headers.get(name.lower())
    if v is None and hasattr(headers, "items"):
        low = name.lower()
        for k, val in headers.items():
            if str(k).lower() == low:
                return val
    return v


def context_from_headers(
        headers: Mapping[str, str]) -> Optional[TraceContext]:
    """Inbound extraction at a serving hop.

    Returns None while telemetry is disabled (the kill-switch contract:
    no header echo, no context, byte-identical handling). Otherwise:
    a valid ``traceparent`` yields a child context of the sender's; a
    bare 32-hex ``X-Request-Id`` adopts that trace id; anything else
    starts a fresh trace.
    """
    if not _metrics.enabled():
        return None
    parsed = parse_traceparent(_header_get(headers, TRACEPARENT_HEADER))
    if parsed is not None:
        return child_context(parsed)
    rid = _header_get(headers, REQUEST_ID_HEADER)
    if rid and _TRACE_ID_RE.match(rid.strip().lower()):
        return TraceContext(trace_id=rid.strip().lower(),
                            span_id=uuid.uuid4().hex[:16])
    return new_context()


def inject_headers(headers: Dict[str, str],
                   ctx: Optional[TraceContext] = None) -> Dict[str, str]:
    """Stamp the active (or given) context onto an outbound hop's header
    dict; a no-op when disabled or outside any context."""
    if not _metrics.enabled():
        return headers
    ctx = ctx if ctx is not None else _current.get()
    if ctx is not None and TRACEPARENT_HEADER not in headers:
        headers[TRACEPARENT_HEADER] = format_traceparent(ctx)
    return headers


def outbound_headers(ctx: Optional[TraceContext] = None) -> Dict[str, str]:
    """Headers to add to an outbound request ({} when inert) — for call
    sites that build header sets incrementally (urllib Request objects)."""
    return inject_headers({}, ctx)


# ---------------------------------------------------------------------------
# Slow-request exemplars
# ---------------------------------------------------------------------------
# Latency histograms aggregate away identity; an exemplar re-attaches it:
# any observation over the slow threshold records (metric, seconds,
# trace_id) into a bounded buffer surfaced by /varz, bumps
# slow_requests_total, and leaves a flight-recorder event — so "p99
# regressed" comes with concrete trace ids to chase through merged dumps.

_SLOW_ENV = "MMLSPARK_TPU_SLOW_REQUEST_SECONDS"
_slow_threshold = float(os.environ.get(_SLOW_ENV, "1.0") or 1.0)
_MAX_EXEMPLARS = 64
_exemplars: "Deque[Dict[str, Any]]" = collections.deque(
    maxlen=_MAX_EXEMPLARS)
_exemplar_lock = threading.Lock()


def get_slow_threshold() -> float:
    return _slow_threshold


def set_slow_threshold(seconds: float) -> float:
    """Set the slow-request exemplar threshold; returns the previous
    value (env default: ``MMLSPARK_TPU_SLOW_REQUEST_SECONDS``, 1.0s)."""
    global _slow_threshold
    prev, _slow_threshold = _slow_threshold, float(seconds)
    return prev


def maybe_mark_slow(metric: str, seconds: float,
                    stages: Optional[Dict[str, float]] = None,
                    **labels: Any) -> bool:
    """Record an exemplar if ``seconds`` crosses the slow threshold.

    ``stages`` (optional) is a per-stage wall-time breakdown of the
    same request (e.g. the serving plane's admission / forming_wait /
    score / write decomposition); it rides the exemplar and the flight
    event so a slow request tells you *which leg* was slow.

    Returns whether one was recorded. Near-zero cost on the fast path:
    one float compare when under threshold or disabled.
    """
    if seconds < _slow_threshold or not _metrics.enabled():
        return False
    ctx = _current.get()
    ex: Dict[str, Any] = {
        "metric": metric, "seconds": round(float(seconds), 6),
        "trace_id": ctx.trace_id if ctx else None,
        "span_id": ctx.span_id if ctx else None,
        "ts": time.time(), "labels": dict(labels),
    }
    if stages:
        ex["stages"] = {str(k): round(float(v), 6)
                        for k, v in stages.items()}
    with _exemplar_lock:
        _exemplars.append(ex)
    _metrics.safe_counter("slow_requests_total", metric=metric).inc()
    from . import flight as _flight  # lazy: flight imports tracing
    if stages:
        _flight.record("slow_request", metric=metric,
                       seconds=ex["seconds"], stages=ex["stages"], **labels)
    else:
        _flight.record("slow_request", metric=metric,
                       seconds=ex["seconds"], **labels)
    return True


def get_exemplars() -> List[Dict[str, Any]]:
    """Recent slow-request exemplars, oldest first (bounded at 64)."""
    with _exemplar_lock:
        return [dict(e) for e in _exemplars]


def clear_exemplars() -> None:
    with _exemplar_lock:
        _exemplars.clear()
