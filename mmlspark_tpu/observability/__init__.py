"""Unified telemetry: metrics registry, stage-level spans, device gauges.

Three pieces, one flag:

- :mod:`.metrics` — process-wide ``MetricsRegistry`` (Counter / Gauge /
  Histogram with labels), snapshot-to-dict, Prometheus text renderer.
- :mod:`.spans` — nesting wall-time spans that feed the registry AND enter
  ``utils/profiling.annotate`` so host scopes and XLA device traces share
  names; exportable as Chrome trace-event JSON.
- :mod:`.device` — ``device_memory_gauges()`` sampling live HBM stats.

``metrics.set_enabled(False)`` turns every instrumentation site in the
framework into a cheap no-op (profiling.py's never-break-the-pipeline
contract). ``ServingServer`` exposes the registry at ``GET /metrics``.
See docs/observability.md.
"""

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                      counter, enabled, gauge, get_registry, histogram,
                      reset, safe_counter, safe_gauge, safe_histogram,
                      set_enabled, set_registry)
from .spans import (clear_trace, current_span, dump_trace,  # noqa: F401
                    get_trace_events, instant, set_default_attrs, span,
                    span_fn)
from .device import device_memory_gauges  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "counter", "gauge", "histogram", "get_registry", "set_registry",
    "safe_counter", "safe_gauge", "safe_histogram",
    "reset", "enabled", "set_enabled",
    "span", "span_fn", "instant", "dump_trace", "get_trace_events",
    "clear_trace", "set_default_attrs", "current_span",
    "device_memory_gauges",
]
