"""Unified telemetry: metrics, spans, tracing, flight, logs, watchdog.

Eight pieces, one flag:

- :mod:`.metrics` — process-wide ``MetricsRegistry`` (Counter / Gauge /
  Histogram with labels), snapshot-to-dict, Prometheus text renderer.
- :mod:`.spans` — nesting wall-time spans that feed the registry AND enter
  ``utils/profiling.annotate`` so host scopes and XLA device traces share
  names; exportable as Chrome trace-event JSON.
- :mod:`.tracing` — per-request ``TraceContext`` (trace_id / span_id /
  parent_id) propagated across serving hops via W3C-traceparent headers
  and stamped onto every span, plus slow-request exemplars.
- :mod:`.flight` — bounded crash-safe ring buffer of structured events,
  dumped on unhandled exception, SIGUSR2, or demand (``/debug/flight``).
- :mod:`.device` — ``device_memory_gauges()`` sampling live HBM stats.
- :mod:`.logging` — structured JSON log funnel (``get_logger``): records
  carry trace ids + process identity, mirror into the flight ring, and
  rate-limit per logger; the ONLY sanctioned textual output path.
- :mod:`.watchdog` — heartbeat stall detection for hot loops (all-thread
  stack + flight dumps on stall) and training-health sentinels
  (NaN/divergence/throughput collapse -> ``training_health`` gauge).
- :mod:`.federation` — the distributed gateway's cluster view: scrape
  every worker's ``/metrics``, merge under a ``worker`` label, expose
  ``/debug/cluster`` scrape health.

``metrics.set_enabled(False)`` turns every instrumentation site in the
framework into a cheap no-op (profiling.py's never-break-the-pipeline
contract). ``ServingServer`` exposes the registry at ``GET /metrics``
and the debug trio at ``/healthz`` / ``/varz`` / ``/debug/flight``.
See docs/observability.md.
"""

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                      counter, enabled, gauge, get_registry, histogram,
                      reset, safe_counter, safe_gauge, safe_histogram,
                      set_enabled, set_registry)
from .tracing import (REQUEST_ID_HEADER, TRACEPARENT_HEADER,  # noqa: F401
                      TraceContext)
from .spans import (clear_trace, current_span, dump_trace,  # noqa: F401
                    get_trace_events, instant, set_default_attrs, span,
                    span_fn)
from .device import device_memory_gauges  # noqa: F401
from .logging import console, get_logger  # noqa: F401
from . import federation, flight, tracing, watchdog  # noqa: F401
from . import logging as logging  # noqa: F401,PLC0414 — the funnel module

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "counter", "gauge", "histogram", "get_registry", "set_registry",
    "safe_counter", "safe_gauge", "safe_histogram",
    "reset", "enabled", "set_enabled",
    "span", "span_fn", "instant", "dump_trace", "get_trace_events",
    "clear_trace", "set_default_attrs", "current_span",
    "TraceContext", "TRACEPARENT_HEADER", "REQUEST_ID_HEADER",
    "tracing", "flight", "logging", "watchdog", "federation",
    "get_logger", "console",
    "device_memory_gauges",
]
