"""HBM ledger: named device-memory allocation accounting.

The framework pins a handful of long-lived buffers in device memory —
async-serving slot tables (``io/aserve/slots.py``), bundle-prewarmed
executables, the binned-dataset fit cache, packed-tree predict
arguments. Each claim/release lands here under a stable ``site`` name
and exports as ``hbm_ledger_bytes{site}``, so "where did my HBM go"
has a first-class answer instead of a diff of PJRT totals.

``reconcile()`` closes the loop against PJRT: it reads the
last-sampled ``device_memory_bytes{stat="bytes_in_use"}`` rows out of
the metrics registry (it deliberately does NOT sample jax itself — a
gateway rendering ``/debug/roofline`` must never drag the framework
in) and surfaces claimed-vs-observed drift as
``hbm_ledger_drift_bytes``. Drift is expected to be positive (XLA
scratch, executables, the runtime's own pools are unclaimed); a large
*negative* drift means a site forgot to release.

Stdlib-only (``obs-import-cycle``); mutators no-op while telemetry is
disabled.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from . import metrics as _metrics

__all__ = ["claim", "release", "set_claim", "claims", "total",
           "reconcile", "snapshot_payload", "reset"]

_lock = threading.Lock()
_claims: Dict[str, float] = {}


def _export(site: str, nbytes: float) -> None:
    _metrics.safe_gauge("hbm_ledger_bytes", site=site).set(nbytes)


def claim(site: str, nbytes: float) -> None:
    """Add ``nbytes`` to ``site``'s claimed total. No-op when disabled."""
    if not _metrics.enabled():
        return
    site = str(site)
    with _lock:
        _claims[site] = _claims.get(site, 0.0) + float(nbytes)
        now = _claims[site]
    _export(site, now)


def release(site: str, nbytes: float) -> None:
    """Subtract ``nbytes`` from ``site`` (floored at 0 — a double
    release must not corrupt the ledger). No-op when disabled."""
    if not _metrics.enabled():
        return
    site = str(site)
    with _lock:
        _claims[site] = max(0.0, _claims.get(site, 0.0) - float(nbytes))
        now = _claims[site]
    _export(site, now)


def set_claim(site: str, nbytes: float) -> None:
    """Overwrite ``site``'s claimed total (idempotent sites that
    re-derive their footprint each time). No-op when disabled."""
    if not _metrics.enabled():
        return
    site = str(site)
    with _lock:
        _claims[site] = max(0.0, float(nbytes))
        now = _claims[site]
    _export(site, now)


def claims() -> Dict[str, float]:
    with _lock:
        return dict(_claims)


def total() -> float:
    with _lock:
        return sum(_claims.values())


def _observed_bytes_in_use() -> Optional[float]:
    """Sum of the registry's last-sampled
    ``device_memory_bytes{stat="bytes_in_use"}`` across devices, or None
    when nothing sampled yet (device.py only writes on TPU/GPU runs)."""
    try:
        snap = _metrics.get_registry().snapshot()
    except Exception:
        return None
    fam = snap.get("device_memory_bytes")
    if not fam:
        return None
    vals = [row.get("value") for row in fam.get("series", ())
            if row.get("labels", {}).get("stat") == "bytes_in_use"]
    vals = [v for v in vals if v is not None]
    if not vals:
        return None
    return float(sum(vals))


def reconcile() -> Dict[str, Any]:
    """Claimed vs PJRT-observed bytes; sets ``hbm_ledger_drift_bytes``
    (observed - claimed) when an observation exists."""
    claimed = total()
    observed = _observed_bytes_in_use()
    drift = None
    if observed is not None:
        drift = observed - claimed
        _metrics.safe_gauge("hbm_ledger_drift_bytes").set(drift)
    return {"claimed_bytes": claimed, "observed_bytes_in_use": observed,
            "drift_bytes": drift}


def snapshot_payload() -> Dict[str, Any]:
    """JSON-safe ledger view for ``/debug/roofline``."""
    return {"sites": claims(), **reconcile()}


def reset() -> None:
    with _lock:
        _claims.clear()
