"""SLO plane: declarative per-endpoint objectives + error-budget burn.

The serving stack can measure everything (stage histograms, roofline
ledger, federation sweep) but none of it answers the production
question: *are we meeting our latency/error objectives, and how fast
are we spending the error budget when we miss?* This module holds the
answer's first half — objectives and burn rate; the second half (which
stage ate the tail) lives in :mod:`.tailsampler`.

Objectives are declared in the ``MMLSPARK_TPU_SLO`` registry knob with
a tiny grammar, one clause list per endpoint::

    MMLSPARK_TPU_SLO="predict:p99<25ms,err<0.1%;embed:p95<5ms"

``p<P><<T>ms|s`` reads "P percent of requests complete under T"; the
latency error budget is the allowed slow fraction ``1 - P/100``.
``err<C%`` caps the 5xx fraction at ``C%``. Both engines (and the
gateway, for its own hop) feed :func:`observe_request` from the same
per-request finally path that feeds ``serving_stage_seconds``, so the
SLO verdict and the stage decomposition describe the same requests.

Burn rate is Google-SRE multi-window: a fast 5-minute and a slow
1-hour window, each reporting ``bad_fraction / budget`` — ``1.0``
means the budget is being spent exactly as fast as it accrues;
sustained ``> 1.0`` on both windows means the objective will be
missed. Exported as ``slo_burn_rate{api, window}`` /
``slo_budget_remaining{api, window}`` gauges, which the gateway's
federation sweep scrapes and folds into ``cluster_autoscale_hint``
(user-visible pain scales the fleet, not just backlog).

Stdlib-only by the ``obs-import-cycle`` contract. Every mutator is a
no-op while telemetry is disabled, and the whole plane is one dict
probe per request when no SLO is configured — unconfigured processes
stay byte-identical to pre-SLO behavior.
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, List, Optional

from . import flight as _flight
from . import metrics as _metrics
from . import tailsampler as _tailsampler

__all__ = ["SLO_ENV", "Objective", "parse_spec", "configure",
           "configured", "objectives", "observe_request", "refresh",
           "snapshot_payload", "reset"]

SLO_ENV = "MMLSPARK_TPU_SLO"

#: (window label, span seconds) — Google-SRE fast/slow burn pair
WINDOWS = (("fast5m", 300.0), ("slow1h", 3600.0))

#: ring-bucket width: coarse enough that an hour is 720 buckets, fine
#: enough that the 5m window loses at most one bucket of resolution
_BUCKET_SECONDS = 5.0

#: gauge recompute throttle — the window sums are O(buckets) and must
#: not run per request on the 100k-RPS async path (snapshot_payload and
#: refresh() always recompute, so debug pages and tests stay exact)
_EXPORT_INTERVAL = 0.5

_CLAUSE_LAT_RE = re.compile(
    r"^p(?P<pct>\d+(?:\.\d+)?)<(?P<val>\d+(?:\.\d+)?)(?P<unit>ms|s)$")
_CLAUSE_ERR_RE = re.compile(r"^err<(?P<val>\d+(?:\.\d+)?)(?P<pct>%?)$")


@dataclass(frozen=True)
class Objective:
    """One endpoint's declared objective (parsed, normalized to
    seconds / fractions)."""

    api: str
    #: target percentile for the latency clause (e.g. 99.0), None when
    #: only an error clause is declared
    percentile: Optional[float] = None
    #: latency threshold in seconds the percentile is held against
    threshold_seconds: Optional[float] = None
    #: allowed 5xx fraction (0.001 == 0.1%), None when not declared
    error_ceiling: Optional[float] = None

    @property
    def latency_budget(self) -> Optional[float]:
        """Allowed slow fraction: ``1 - percentile/100``."""
        if self.percentile is None:
            return None
        return max(1.0 - self.percentile / 100.0, 1e-9)


def parse_spec(spec: str) -> Dict[str, Objective]:
    """Parse the ``MMLSPARK_TPU_SLO`` grammar into per-api objectives.

    Raises :class:`ValueError` on any malformed entry — the env path
    catches and degrades (an operator hint must not kill a worker at
    boot), explicit :func:`configure` callers fail loudly.
    """
    out: Dict[str, Objective] = {}
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        api, sep, clauses = entry.partition(":")
        api = api.strip()
        if not sep or not api:
            raise ValueError(f"SLO entry {entry!r}: expected "
                             "'<endpoint>:<clause>[,<clause>...]'")
        if api in out:
            raise ValueError(f"SLO endpoint {api!r} declared twice")
        pct = thr = ceil = None
        for clause in clauses.split(","):
            clause = clause.strip().lower().replace(" ", "")
            if not clause:
                continue
            m = _CLAUSE_LAT_RE.match(clause)
            if m:
                if thr is not None:
                    raise ValueError(f"SLO entry {entry!r}: two latency "
                                     "clauses")
                pct = float(m.group("pct"))
                if not 0.0 < pct <= 100.0:
                    raise ValueError(f"SLO entry {entry!r}: percentile "
                                     f"{pct} outside (0, 100]")
                thr = float(m.group("val"))
                if m.group("unit") == "ms":
                    thr /= 1e3
                continue
            m = _CLAUSE_ERR_RE.match(clause)
            if m:
                if ceil is not None:
                    raise ValueError(f"SLO entry {entry!r}: two error "
                                     "clauses")
                ceil = float(m.group("val"))
                if m.group("pct"):
                    ceil /= 100.0
                if not 0.0 < ceil <= 1.0:
                    raise ValueError(f"SLO entry {entry!r}: error ceiling "
                                     "outside (0%, 100%]")
                continue
            raise ValueError(f"SLO clause {clause!r} (in {entry!r}): "
                             "expected 'p<P><<T>ms' or 'err<C%'")
        if thr is None and ceil is None:
            raise ValueError(f"SLO entry {entry!r}: no clauses")
        out[api] = Objective(api=api, percentile=pct,
                             threshold_seconds=thr, error_ceiling=ceil)
    return out


# -- module state -----------------------------------------------------------

_lock = threading.Lock()
_spec: Optional[str] = None
_objectives: Dict[str, Objective] = {}
_env_loaded = False
#: per-api deque of [bucket_start_monotonic, total, slow, errors]
_rings: Dict[str, Deque[List[float]]] = {}
_last_export: Dict[str, float] = {}


def _ensure_env() -> None:
    """Lazily adopt the env spec (once per process / per reset)."""
    global _env_loaded, _spec, _objectives
    if _env_loaded:
        return
    with _lock:
        if _env_loaded:
            return
        raw = os.environ.get(SLO_ENV, "").strip()
        if raw:
            try:
                _objectives = parse_spec(raw)
                _spec = raw
            except ValueError as e:
                # degrade, don't die: a typo'd objective leaves the
                # process unconfigured with a flight breadcrumb
                _flight.record("slo_config", decision="rejected",
                               spec=raw, error=str(e))
        _env_loaded = True


def configure(spec: Optional[str]) -> Dict[str, Objective]:
    """Install objectives programmatically (tests, embedding apps).
    ``None``/empty clears. Malformed specs raise."""
    global _env_loaded, _spec, _objectives
    parsed = parse_spec(spec) if spec else {}
    with _lock:
        _objectives = parsed
        _spec = spec if parsed else None
        _env_loaded = True
        _rings.clear()
        _last_export.clear()
    return dict(parsed)


def configured() -> bool:
    _ensure_env()
    return bool(_objectives)


def objectives() -> Dict[str, Objective]:
    _ensure_env()
    return dict(_objectives)


def _ring(api: str) -> Deque[List[float]]:
    ring = _rings.get(api)
    if ring is None:
        ring = _rings[api] = deque()
    return ring


def _record_locked(api: str, now: float, slow: bool, error: bool) -> None:
    ring = _ring(api)
    bucket = now - (now % _BUCKET_SECONDS)
    if not ring or ring[-1][0] != bucket:
        ring.append([bucket, 0.0, 0.0, 0.0])
    ring[-1][1] += 1.0
    if slow:
        ring[-1][2] += 1.0
    if error:
        ring[-1][3] += 1.0
    horizon = now - WINDOWS[-1][1] - _BUCKET_SECONDS
    while ring and ring[0][0] < horizon:
        ring.popleft()


def _window_counts_locked(api: str, now: float,
                          span: float) -> Dict[str, float]:
    total = slow = errors = 0.0
    cutoff = now - span
    for bucket, t, s, e in _rings.get(api, ()):
        if bucket + _BUCKET_SECONDS <= cutoff:
            continue
        total += t
        slow += s
        errors += e
    return {"requests": total, "slow": slow, "errors": errors}


def _window_verdict(obj: Objective,
                    counts: Dict[str, float]) -> Dict[str, Any]:
    """Burn rates for one window: ``bad_fraction / budget`` per signal,
    the window's burn is the hotter of the two."""
    total = counts["requests"]
    lat_burn = err_burn = None
    if total > 0:
        if obj.latency_budget is not None:
            lat_burn = (counts["slow"] / total) / obj.latency_budget
        if obj.error_ceiling is not None:
            err_burn = (counts["errors"] / total) / obj.error_ceiling
    candidates = [b for b in (lat_burn, err_burn) if b is not None]
    burn = max(candidates) if candidates else 0.0
    return {**counts, "latency_burn": lat_burn, "error_burn": err_burn,
            "burn_rate": burn,
            "budget_remaining": max(0.0, 1.0 - burn)}


def _export_locked(api: str, now: float) -> Dict[str, Dict[str, Any]]:
    """Recompute every window for one api and set the gauges."""
    obj = _objectives[api]
    out: Dict[str, Dict[str, Any]] = {}
    for window, span in WINDOWS:
        verdict = _window_verdict(
            obj, _window_counts_locked(api, now, span))
        out[window] = verdict
        _metrics.safe_gauge("slo_burn_rate", api=api,
                            window=window).set(verdict["burn_rate"])
        _metrics.safe_gauge("slo_budget_remaining", api=api,
                            window=window).set(
                                verdict["budget_remaining"])
    _last_export[api] = now
    return out


def observe_request(api: str, seconds: float, status: int,
                    stages: Optional[Dict[str, float]] = None,
                    trace_id: Optional[str] = None,
                    hop: str = "worker") -> None:
    """Feed one completed request into the burn windows (and, when it
    breaches its objective, into the tail sampler's reservoir).

    The per-request finally path of both engines and the gateway calls
    this unconditionally; with no SLO configured it is one dict probe.
    """
    _ensure_env()
    if not _objectives:
        return
    if not _metrics.enabled():
        return
    obj = _objectives.get(api)
    if obj is None:
        return
    seconds = float(seconds)
    slow = (obj.threshold_seconds is not None
            and seconds > obj.threshold_seconds)
    error = int(status) >= 500
    breach = slow or (error and obj.error_ceiling is not None)
    now = time.monotonic()
    with _lock:
        _record_locked(api, now, slow, error)
        if now - _last_export.get(api, 0.0) >= _EXPORT_INTERVAL:
            _export_locked(api, now)
    if breach:
        signal = "latency" if slow else "error"
        _metrics.safe_counter("slo_breach_total", api=api,
                              signal=signal).inc()
        _tailsampler.sample(api, seconds, status, stages=stages,
                            trace_id=trace_id, hop=hop,
                            breach=signal)


def current_burn(api: str, window: str = "fast5m") -> float:
    """Live burn rate for one endpoint's window — 0.0 when no SLO is
    configured for it (an unconfigured endpoint cannot be "breaching").

    Computed from the ring directly, not read back from gauges: the
    dispatch-pacing override in ``io/aserve`` checks this per dispatch
    and must see a breach the moment it starts, not after the export
    throttle. Cost is one bounded ring scan under the lock.
    """
    _ensure_env()
    obj = _objectives.get(api)
    if obj is None:
        return 0.0
    span = dict(WINDOWS).get(window)
    if span is None:
        raise ValueError(f"unknown SLO window {window!r} "
                         f"(have {[w for w, _ in WINDOWS]})")
    now = time.monotonic()
    with _lock:
        verdict = _window_verdict(obj, _window_counts_locked(api, now, span))
    return float(verdict["burn_rate"])


def refresh() -> None:
    """Force a gauge recompute for every configured api (tests and the
    federation-facing callers that must not wait out the throttle)."""
    _ensure_env()
    if not _objectives or not _metrics.enabled():
        return
    now = time.monotonic()
    with _lock:
        for api in _objectives:
            _export_locked(api, now)


def _objective_view(obj: Objective) -> Dict[str, Any]:
    return {"percentile": obj.percentile,
            "threshold_ms": (None if obj.threshold_seconds is None
                             else obj.threshold_seconds * 1e3),
            "error_ceiling_pct": (None if obj.error_ceiling is None
                                  else obj.error_ceiling * 100.0),
            "latency_budget": obj.latency_budget}


def snapshot_payload() -> Dict[str, Any]:
    """``/debug/slo`` body: objectives, per-window burn, and a breach
    verdict per endpoint. Always recomputes (and re-exports the gauges)
    so the page and ``/metrics`` agree."""
    _ensure_env()
    now = time.monotonic()
    endpoints: Dict[str, Any] = {}
    with _lock:
        for api, obj in _objectives.items():
            windows = ({w: _window_verdict(
                            obj, _window_counts_locked(api, now, s))
                        for w, s in WINDOWS}
                       if not _metrics.enabled()
                       else _export_locked(api, now))
            endpoints[api] = {
                "objective": _objective_view(obj),
                "windows": windows,
                # breaching NOW means the fast window burns budget
                # faster than it accrues
                "breaching": windows[WINDOWS[0][0]]["burn_rate"] > 1.0,
            }
    return {"configured": bool(endpoints), "spec": _spec,
            "windows": {w: s for w, s in WINDOWS},
            "endpoints": endpoints,
            "note": ("burn_rate = bad_fraction / error_budget per "
                     "window; sustained > 1.0 on both windows means "
                     "the objective will be missed" if endpoints else
                     "no SLO configured — set MMLSPARK_TPU_SLO, e.g. "
                     "'predict:p99<25ms,err<0.1%'")}


def reset() -> None:
    """Drop objectives, windows, and the cached env read (tests)."""
    global _env_loaded, _spec, _objectives
    with _lock:
        _objectives = {}
        _spec = None
        _env_loaded = False
        _rings.clear()
        _last_export.clear()
