"""Structured host-side spans that share names with XLA device traces.

The reference's per-stage timing story is host StopWatch scopes with
human-readable names (stages/Timer.scala:57-92); our device-side story is
utils/profiling.annotate (jax.profiler.TraceAnnotation). A :func:`span` is
the bridge: one context manager that

- records wall-time and nests via a contextvar parent (thread- and
  task-local, so concurrent serving threads don't corrupt each other's
  stacks);
- feeds the metrics registry's histograms (``span_duration_seconds``);
- enters ``utils/profiling.annotate`` with the same name, so device ops
  launched inside the span carry the host span's label in XLA traces.

Spans accumulate into a bounded in-process buffer exportable as a Chrome
trace-event JSON file (``chrome://tracing`` / Perfetto) via
:func:`dump_trace`. Everything is a no-op while the metrics flag is off.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import json
import os
import threading
import time
from typing import Any, Deque, Dict, Iterator, List, Optional

from . import metrics as _metrics
from . import tracing as _tracing

__all__ = [
    "span", "span_fn", "instant", "dump_trace", "get_trace_events",
    "clear_trace", "set_default_attrs", "get_default_attrs", "current_span",
    "MAX_TRACE_EVENTS", "set_max_trace_events", "get_max_trace_events",
    "dropped_events",
]


def _env_cap() -> int:
    try:
        n = int(os.environ.get("MMLSPARK_TPU_MAX_TRACE_EVENTS", "")
                or 100_000)
    except ValueError:
        n = 100_000
    return max(1, n)


# Bounded buffer: long-running servers must not grow without limit; the
# oldest events are dropped once full (dump early, dump often). Tunable
# via MMLSPARK_TPU_MAX_TRACE_EVENTS (a week-long serving process sizes
# this to its memory budget) or set_max_trace_events at runtime.
MAX_TRACE_EVENTS = _env_cap()

_parent: "contextvars.ContextVar[Optional[_SpanRecord]]" = \
    contextvars.ContextVar("mmlspark_tpu_span_parent", default=None)
_buf_lock = threading.Lock()
# deque(maxlen=...) keeps the drop-oldest semantics at O(1) per record —
# a full list's pop(0) would memmove 100k entries inside the lock on every
# span completion of a long-running server
_events: "Deque[Dict[str, Any]]" = collections.deque(maxlen=MAX_TRACE_EVENTS)
_dropped = 0
_default_attrs: Dict[str, Any] = {}


class _SpanRecord:
    """Mutable in-flight span handle; ``set`` attaches attributes that end
    up in the trace event's ``args``."""

    __slots__ = ("name", "attrs", "parent")

    def __init__(self, name: str, attrs: Dict[str, Any],
                 parent: "Optional[_SpanRecord]"):
        self.name = name
        self.attrs = attrs
        self.parent = parent

    def set(self, **attrs: Any) -> "_SpanRecord":
        self.attrs.update(attrs)
        return self


class _NoopSpan:
    """Disabled-path handle so call sites never branch on the flag."""

    name = ""
    parent = None

    def set(self, **attrs: Any) -> "_NoopSpan":
        return self


_NOOP_SPAN = _NoopSpan()


def set_default_attrs(**attrs: Any) -> None:
    """Attributes stamped onto every subsequent event (e.g.
    ``process_index`` on multi-host runs — parallel/distributed.py sets it
    after ``initialize``)."""
    # replace-on-write: readers unpack {**_default_attrs, ...} without a
    # lock, and mutating the shared dict mid-unpack would raise
    # "dictionary changed size during iteration" out of span()'s finally
    # into the instrumented user code
    global _default_attrs
    _default_attrs = {**_default_attrs, **attrs}


def get_default_attrs() -> Dict[str, Any]:
    return dict(_default_attrs)


def current_span():
    """The innermost live span in this context (None outside any span)."""
    return _parent.get()


def _pid() -> int:
    idx = _default_attrs.get("process_index")
    return int(idx) if idx is not None else os.getpid()


def set_max_trace_events(n: int) -> int:
    """Resize the bounded event buffer (keeps the newest events); returns
    the previous cap. Env default: ``MMLSPARK_TPU_MAX_TRACE_EVENTS``."""
    global _events, _dropped, MAX_TRACE_EVENTS
    n = max(1, int(n))
    with _buf_lock:
        prev = MAX_TRACE_EVENTS
        kept = list(_events)[-n:]
        _dropped += len(_events) - len(kept)
        _events = collections.deque(kept, maxlen=n)
        MAX_TRACE_EVENTS = n
    return prev


def get_max_trace_events() -> int:
    return MAX_TRACE_EVENTS


def dropped_events() -> int:
    """Oldest-dropped count since the last :func:`clear_trace` (also
    exported as the ``trace_events_dropped_total`` counter)."""
    return _dropped


def _record(event: Dict[str, Any]) -> None:
    global _dropped
    ctx = _tracing.current()
    if ctx is not None:
        # stitch key: Chrome-trace dumps from different processes merge
        # into one logical request by this id
        args = event.get("args")
        if args is not None:
            args.setdefault("trace_id", ctx.trace_id)
            args.setdefault("span_id", ctx.span_id)
    with _buf_lock:
        full = len(_events) == _events.maxlen
        if full:
            _dropped += 1  # deque maxlen evicts the oldest on append
        _events.append(event)
    if full:
        # outside _buf_lock: the registry has its own lock, never nest them
        _metrics.safe_counter("trace_events_dropped_total").inc()


@contextlib.contextmanager
def span(name: str, metric_label: Optional[str] = None,
         **attrs: Any) -> Iterator[Any]:
    """Time a region: nests, traces, and feeds the registry.

    ``metric_label`` bounds registry label cardinality: the
    ``span_duration_seconds`` histogram is labeled with it instead of
    ``name`` when given (e.g. the pipeline layer passes the stage class
    name while the span itself carries the per-instance uid). The yielded
    handle's ``set(**attrs)`` adds attributes mid-span (row counts etc.).
    """
    if not _metrics.enabled():
        yield _NOOP_SPAN
        return
    from ..utils import profiling  # lazy: keeps observability import-cycle-free

    parent = _parent.get()
    rec = _SpanRecord(name, dict(attrs), parent)
    token = _parent.set(rec)
    t0 = time.perf_counter()
    try:
        # annotate degrades to a no-op itself (never breaks the spanned work)
        with profiling.annotate(name):
            yield rec
    finally:
        dur = time.perf_counter() - t0
        _parent.reset(token)
        args = {**_default_attrs, **rec.attrs}
        if parent is not None:
            args["parent"] = parent.name
        _record({
            "name": name, "ph": "X", "cat": "mmlspark",
            "ts": t0 * 1e6, "dur": dur * 1e6,
            "pid": _pid(), "tid": threading.get_ident(),
            "args": args,
        })
        _metrics.safe_histogram("span_duration_seconds",
                                name=metric_label or name).observe(dur)
        # flight-recorder feed: span ends are the "what was it doing in
        # its final seconds" record a crash dump is made of
        from . import flight as _flight
        _flight.record("span_end", name=name, dur_us=int(dur * 1e6))


def span_fn(name: str, **attrs: Any):
    """Decorator form of :func:`span`."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*a, **kw):
            with span(name, **attrs):
                return fn(*a, **kw)
        return wrapped
    return deco


def instant(name: str, **attrs: Any) -> None:
    """Zero-duration marker (Chrome trace 'i' event) — e.g. one per boost
    round when detailed training telemetry is on."""
    if not _metrics.enabled():
        return
    _record({
        "name": name, "ph": "i", "cat": "mmlspark", "s": "t",
        "ts": time.perf_counter() * 1e6,
        "pid": _pid(), "tid": threading.get_ident(),
        "args": {**_default_attrs, **attrs},
    })


def get_trace_events() -> List[Dict[str, Any]]:
    with _buf_lock:
        return [dict(e) for e in _events]


def clear_trace() -> None:
    global _dropped
    with _buf_lock:
        _events.clear()
        _dropped = 0


def dump_trace(path: str) -> str:
    """Write the buffered events as Chrome trace-event JSON (load in
    chrome://tracing or ui.perfetto.dev). Returns ``path``."""
    with _buf_lock:
        doc = {
            "traceEvents": [dict(e) for e in _events],
            "displayTimeUnit": "ms",
            "otherData": {"dropped_events": _dropped},
        }
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
