"""Crash-safe flight recorder: the last N structured events, always on.

When a serving worker wedges or dies, metrics say *that* it died and
spans say how long things took — neither says what the process was doing
in its final seconds. The flight recorder does: a bounded, thread-safe
ring buffer of structured events (span ends, errors, retries/failovers,
compile events, queue transitions) that costs near-zero when idle and
dumps JSON

- on unhandled exception (chained ``sys.excepthook``),
- on ``SIGUSR2`` (poke a live, wedged process from the outside),
- on demand (:func:`dump`, the ``/debug/flight`` endpoint, bench.py's
  ``GRAFT_BENCH_FLIGHT_SNAPSHOT``).

Ring capacity comes from ``MMLSPARK_TPU_FLIGHT_EVENTS`` (default 4096);
dumps land in ``MMLSPARK_TPU_FLIGHT_DIR`` (default: the system temp
dir). Recording is inert behind the global telemetry kill switch and
stamps the active trace context onto every event, so a dump from a dying
worker stitches into the same story as the gateway's.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional

from . import metrics as _metrics
from . import tracing as _tracing

__all__ = [
    "record", "events", "clear", "dropped", "capacity", "set_capacity",
    "set_default_fields", "snapshot", "dump", "dump_json", "dump_path",
    "add_dump_callback", "remove_dump_callback",
    "install", "uninstall", "DEFAULT_CAPACITY",
]

_CAPACITY_ENV = "MMLSPARK_TPU_FLIGHT_EVENTS"
_DIR_ENV = "MMLSPARK_TPU_FLIGHT_DIR"


def _env_capacity() -> int:
    try:
        n = int(os.environ.get(_CAPACITY_ENV, "") or 4096)
    except ValueError:
        n = 4096
    return max(1, n)


DEFAULT_CAPACITY = _env_capacity()

# RLock, not Lock: the SIGUSR2 dump handler runs on the main thread
# BETWEEN bytecodes — possibly while that same thread is inside record()'s
# critical section. A non-reentrant lock would deadlock the exact process
# the signal was sent to inspect; re-entrancy lets the dump proceed (at
# worst observing one half-appended event, fine for a diagnostic ring).
_lock = threading.RLock()
_buf: "Deque[Dict[str, Any]]" = collections.deque(maxlen=DEFAULT_CAPACITY)
_dropped = 0
_seq = 0
_default_fields: Dict[str, Any] = {}


def record(kind: str, **fields: Any) -> None:
    """Append one event. Near-zero when disabled (one flag check); cheap
    when enabled (one dict build + locked deque append). The active
    trace context's ids are stamped on automatically."""
    if not _metrics.enabled():
        return
    global _dropped, _seq
    ev: Dict[str, Any] = {"kind": kind, "ts": time.time(),
                          "tid": threading.get_ident()}
    if _default_fields:
        ev.update(_default_fields)
    ev.update(fields)
    ctx = _tracing.current()
    if ctx is not None:
        ev.setdefault("trace_id", ctx.trace_id)
        ev.setdefault("span_id", ctx.span_id)
    with _lock:
        _seq += 1
        ev["seq"] = _seq
        if len(_buf) == _buf.maxlen:
            _dropped += 1                 # deque maxlen evicts the oldest
        _buf.append(ev)


def events() -> List[Dict[str, Any]]:
    """Point-in-time copy, oldest first."""
    with _lock:
        return [dict(e) for e in _buf]


def clear() -> None:
    global _dropped, _seq
    with _lock:
        _buf.clear()
        _dropped = 0
        _seq = 0


def dropped() -> int:
    """Events evicted since the last :func:`clear` (ring overwrites)."""
    return _dropped


def capacity() -> int:
    return _buf.maxlen or DEFAULT_CAPACITY


def set_capacity(n: int) -> int:
    """Resize the ring (keeps the newest events); returns the previous
    capacity. Env default: ``MMLSPARK_TPU_FLIGHT_EVENTS``."""
    global _buf, _dropped
    n = max(1, int(n))
    with _lock:
        prev = _buf.maxlen or DEFAULT_CAPACITY
        kept = list(_buf)[-n:]
        _dropped += len(_buf) - len(kept)
        _buf = collections.deque(kept, maxlen=n)
    return prev


def set_default_fields(**fields: Any) -> None:
    """Fields stamped onto every subsequent event (e.g. ``process_index``
    on multi-host runs, ``role`` on serving deployments); a None value
    removes the field. Replace-on-write for lock-free readers, mirroring
    spans.set_default_attrs."""
    global _default_fields
    merged = {**_default_fields, **fields}
    _default_fields = {k: v for k, v in merged.items() if v is not None}


def snapshot(since: Optional[int] = None) -> Dict[str, Any]:
    """JSON-safe view: events plus enough process identity to merge dumps
    from several workers (this is the ``/debug/flight`` payload).

    ``since`` is the incremental-scrape cursor: only events with
    ``seq > since`` are included, and the payload's ``last_seq`` is the
    highest ``seq`` ever assigned — the scraper passes it back as the
    next ``?since=`` so repeated scrapes are deltas, not full rings."""
    with _lock:
        if since is None:
            evs = [dict(e) for e in _buf]
        else:
            evs = [dict(e) for e in _buf if e.get("seq", 0) > since]
        drop = _dropped
        last = _seq
    out = {
        "pid": os.getpid(),
        "time": time.time(),
        "capacity": capacity(),
        "dropped": drop,
        "last_seq": last,
        "default_fields": dict(_default_fields),
        "events": evs,
    }
    if since is not None:
        out["since"] = since
    return out


def dump_json() -> bytes:
    """The snapshot as JSON bytes (non-serializable values are repr()d:
    a dump from a dying process must never fail on a weird field)."""
    return json.dumps(snapshot(), default=repr).encode("utf-8")


def _dump_dir() -> str:
    return os.environ.get(_DIR_ENV) or tempfile.gettempdir()


_dump_seq = 0


def dump_path(prefix: str = "flight") -> str:
    """A fresh, collision-free dump path:
    ``$MMLSPARK_TPU_FLIGHT_DIR/{prefix}-{pid}-{ts}-{n}.json``.

    Every dump producer (explicit :func:`dump`, the SIGUSR2/excepthook
    crash hooks, the watchdog's stall dump, the fleet timeline) names
    files through this one funnel. The pid plus a per-process monotonic
    counter make the name unique even when a gateway and several workers
    share one ``MMLSPARK_TPU_FLIGHT_DIR`` and dump within the same
    second (a wall-clock-only suffix silently overwrote the earlier
    dump — exactly the forensics a post-mortem needed)."""
    global _dump_seq
    with _lock:
        _dump_seq += 1
        n = _dump_seq
    return os.path.join(
        _dump_dir(),
        f"{prefix}-{os.getpid()}-{int(time.time())}-{n:04d}.json")


def dump(path: Optional[str] = None) -> str:
    """Write the snapshot to ``path`` (default: :func:`dump_path`);
    returns the path written."""
    if path is None:
        path = dump_path()
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        f.write(dump_json())
    return path


# ---------------------------------------------------------------------------
# Crash hooks: SIGUSR2 + unhandled-exception dump
# ---------------------------------------------------------------------------

_prev_excepthook = None
_prev_signal = None
_installed_signum: Optional[int] = None

# companions dumped alongside the ring by both crash hooks — e.g. the
# gateway's fleet timeline registers here so a SIGUSR2 poke or an
# unhandled exception leaves the cluster-wide story next to the local one
_dump_callbacks: List[Callable[[], Any]] = []


def add_dump_callback(fn: Callable[[], Any]) -> None:
    """Register ``fn`` to run whenever a crash hook dumps the ring
    (SIGUSR2 / excepthook). Idempotent; exceptions are swallowed —
    a companion dump must never abort the primary one."""
    if fn not in _dump_callbacks:
        _dump_callbacks.append(fn)


def remove_dump_callback(fn: Callable[[], Any]) -> None:
    try:
        _dump_callbacks.remove(fn)
    except ValueError:
        pass


def _run_dump_callbacks() -> None:
    for fn in list(_dump_callbacks):
        try:
            fn()
        except Exception:  # noqa: BLE001 — never kill the crash hook
            pass


def _on_signal(signum, frame) -> None:  # noqa: ARG001 — signal signature
    try:
        from . import logging as _logging  # lazy: logging imports flight
        record("signal_dump", signum=int(signum))
        path = dump()
        _run_dump_callbacks()
        _logging.console(f"[flight] dumped {len(events())} events to {path}",
                         err=True)
    except Exception:  # noqa: BLE001 — a dump hook must never kill the host
        pass


def _on_unhandled(exc_type, exc, tb) -> None:
    try:
        from . import logging as _logging  # lazy: logging imports flight
        record("unhandled_exception",
               error=f"{exc_type.__name__}: {exc}")
        path = dump()
        _run_dump_callbacks()
        _logging.console(f"[flight] unhandled exception; dumped to {path}",
                         err=True)
    except Exception:  # noqa: BLE001
        pass
    hook = _prev_excepthook or sys.__excepthook__
    hook(exc_type, exc, tb)


def install(signum: Optional[int] = None, excepthook: bool = True) -> None:
    """Arm the crash hooks (idempotent).

    ``signum`` defaults to ``SIGUSR2`` where the platform has it; pass
    ``signum=0`` to skip signal installation (e.g. from non-main
    threads, where ``signal.signal`` raises — that failure is swallowed
    and only the excepthook is armed).
    """
    global _prev_excepthook, _prev_signal, _installed_signum
    import signal as _signal
    if signum is None:
        signum = getattr(_signal, "SIGUSR2", 0)
    if signum and _installed_signum is None:
        try:
            _prev_signal = _signal.signal(signum, _on_signal)
            _installed_signum = signum
        except (ValueError, OSError):     # non-main thread / exotic platform
            _prev_signal = None
    if excepthook and _prev_excepthook is None and \
            sys.excepthook is not _on_unhandled:
        _prev_excepthook = sys.excepthook
        sys.excepthook = _on_unhandled


def uninstall() -> None:
    """Disarm the hooks and restore what was there before (tests)."""
    global _prev_excepthook, _prev_signal, _installed_signum
    import signal as _signal
    if _installed_signum is not None:
        try:
            _signal.signal(_installed_signum,
                           _prev_signal or _signal.SIG_DFL)
        except (ValueError, OSError):
            pass
        _prev_signal = None
        _installed_signum = None
    if sys.excepthook is _on_unhandled:
        sys.excepthook = _prev_excepthook or sys.__excepthook__
    _prev_excepthook = None
