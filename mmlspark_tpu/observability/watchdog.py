"""Active watchdog: stall detection for hot loops + training health.

Everything before this module is *passive* — metrics, spans, traces and
the flight ring record what happened, but nothing watches the process. A
hung batch thread, a stuck collective, or a diverging fit produces
silence until a client times out. MLPerf-scale TPU work fails via stalls
and skew, not crashes (Kumar et al., "Scale MLPerf-0.6 models on Google
TPU-v3 Pods"), so the watchdog is the piece that notices:

- **Heartbeats.** Hot loops (:mod:`..io.serving`'s batch thread, the
  streaming prefetcher, the GBDT round loop, distributed barriers)
  :func:`register` a named heartbeat and ``beat()`` once per iteration —
  one monotonic-clock store, nothing else. A daemon sampler thread
  checks ages every ``MMLSPARK_TPU_WATCHDOG_INTERVAL_SECONDS``; a
  heartbeat older than ``MMLSPARK_TPU_WATCHDOG_STALL_SECONDS`` (default
  30) is a stall: the watchdog dumps ALL thread stacks + the flight ring
  to ``MMLSPARK_TPU_FLIGHT_DIR``, records a ``watchdog_stall`` flight
  event carrying the stalled site and the stacks, logs through the
  funnel, and bumps ``watchdog_stalls_total{site=...}`` — exactly once
  per stall episode (it re-arms when the heartbeat resumes).
- **Training-health sentinels.** :func:`report_training_metric` feeds
  per-round losses/durations from the GBDT loop (and
  :func:`scan_eval_history` audits a finished fit, covering the fused
  single-dispatch paths that have no rounds): NaN/Inf loss, loss
  divergence over a window, and per-round throughput collapse each emit
  a flight event, bump ``training_health_events_total{model,kind}``, and
  drop the ``training_health{model}`` gauge to 0.

Kill-switch contract: :func:`register` returns a no-op handle and
:func:`report_training_metric` returns immediately while telemetry is
disabled — no sampler thread is ever started, hot paths keep
byte-identical behavior. The sampler starts lazily on the first real
registration and is shared process-wide.
"""

from __future__ import annotations

import math
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

from . import device as _device
from . import flight as _flight
from . import metrics as _metrics
from .env_registry import env_float as _env_float

__all__ = [
    "Heartbeat", "register", "heartbeats", "stop", "running",
    "get_stall_seconds", "set_stall_seconds",
    "get_interval_seconds", "set_interval_seconds",
    "dump_all_stacks", "report_training_metric", "scan_eval_history",
    "training_healthy", "reset_training_health", "stall_counts",
    "add_event_callback",
]

_STALL_ENV = "MMLSPARK_TPU_WATCHDOG_STALL_SECONDS"
_INTERVAL_ENV = "MMLSPARK_TPU_WATCHDOG_INTERVAL_SECONDS"
_WINDOW_ENV = "MMLSPARK_TPU_WATCHDOG_LOSS_WINDOW"

#: loss must exceed window-min by this factor to count as divergence
DIVERGENCE_FACTOR = 2.0
#: a round slower than median-of-window by this factor is a collapse
COLLAPSE_FACTOR = 5.0


_stall_seconds = max(0.01, _env_float(_STALL_ENV, 30.0))
_interval_seconds = _env_float(_INTERVAL_ENV, 0.0)  # 0 -> derived

_lock = threading.Lock()
_hearts: Dict[int, "Heartbeat"] = {}
_next_id = 0
_thread: Optional[threading.Thread] = None
_stop_evt = threading.Event()
_stall_log: List[Dict[str, Any]] = []          # recent stalls (bounded)
#: subscribers to watchdog events: cb(category, name, fields) fired on
#: every stall episode (category "stall", name = heartbeat site) and
#: every training-health event (category = event kind, name = model) —
#: the hook training loops use to dump a last-good checkpoint when the
#: watchdog declares the fit sick (see models/gbdt/booster.py)
_event_callbacks: List[Any] = []


def add_event_callback(cb) -> Any:
    """Subscribe ``cb(category, name, fields)`` to stall/health events;
    returns a zero-arg unsubscribe. Callbacks run on the emitting thread
    (the sampler for stalls, the training loop for sentinels) and must
    never raise — exceptions are swallowed."""
    with _lock:
        _event_callbacks.append(cb)

    def _remove() -> None:
        with _lock:
            try:
                _event_callbacks.remove(cb)
            except ValueError:
                pass
    return _remove


def _emit_event(category: str, name: str, **fields: Any) -> None:
    with _lock:
        cbs = list(_event_callbacks)
    for cb in cbs:
        try:
            cb(category, name, fields)
        except Exception:  # noqa: BLE001 — a sick callback must not
            pass           # break the watchdog or the training loop


def get_stall_seconds() -> float:
    return _stall_seconds


def set_stall_seconds(seconds: float) -> float:
    """Set the stall threshold; returns the previous value (env default:
    ``MMLSPARK_TPU_WATCHDOG_STALL_SECONDS``)."""
    global _stall_seconds
    prev, _stall_seconds = _stall_seconds, max(0.01, float(seconds))
    return prev


def get_interval_seconds() -> float:
    """Effective sampling period: explicit setting/env, else a quarter of
    the stall threshold clamped to [0.05 s, 5 s]."""
    if _interval_seconds > 0:
        return _interval_seconds
    return min(5.0, max(0.05, _stall_seconds / 4.0))


def set_interval_seconds(seconds: float) -> float:
    global _interval_seconds
    prev = _interval_seconds
    _interval_seconds = max(0.0, float(seconds))
    return prev


class Heartbeat:
    """One registered hot loop. ``beat()`` is the entire per-iteration
    cost: a monotonic read and an attribute store."""

    __slots__ = ("site", "hb_id", "created", "last", "beats", "thread",
                 "stall_seconds", "_stalled", "_closed")

    def __init__(self, site: str, hb_id: int,
                 stall_seconds: Optional[float] = None):
        self.site = site
        self.hb_id = hb_id
        self.created = self.last = time.monotonic()
        self.beats = 0
        self.thread = threading.current_thread()
        #: per-site override of the global threshold (None = global) —
        #: coarse single-beat scopes (a whole inner fit) use a generous
        #: bound, per-iteration loops keep the tight default
        self.stall_seconds = stall_seconds
        self._stalled = False
        self._closed = False

    def beat(self) -> None:
        self.last = time.monotonic()
        self.beats += 1

    def close(self) -> None:
        """Deregister (a finished loop must not read as an eternal stall)."""
        self._closed = True
        with _lock:
            _hearts.pop(self.hb_id, None)

    def __enter__(self) -> "Heartbeat":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class _NoopHeartbeat:
    """Disabled-path stand-in (also usable as a context manager)."""

    site = "noop"
    beats = 0

    def beat(self) -> None:
        pass

    def close(self) -> None:
        pass

    def __enter__(self) -> "_NoopHeartbeat":
        return self

    def __exit__(self, *exc: Any) -> None:
        pass


NOOP_HEARTBEAT = _NoopHeartbeat()


def register(site: str, stall_seconds: Optional[float] = None):
    """Register a heartbeat for a hot loop; returns the handle (a no-op
    handle while telemetry is disabled — the sampler never starts). Use
    as a context manager or call ``close()`` when the loop exits.
    ``stall_seconds`` raises the threshold for this site above the global
    one (a floor for slow-but-alive scopes like cold-compile first
    iterations; the effective threshold is the max of the two)."""
    if not _metrics.enabled():
        return NOOP_HEARTBEAT
    global _next_id
    hb = None
    with _lock:
        _next_id += 1
        hb = Heartbeat(str(site), _next_id, stall_seconds)
        _hearts[hb.hb_id] = hb
    _ensure_thread()
    return hb


def heartbeats() -> List[Dict[str, Any]]:
    """Point-in-time view (the ``/debug/cluster`` and test surface)."""
    now = time.monotonic()
    with _lock:
        return [{"site": h.site, "age_seconds": round(now - h.last, 6),
                 "beats": h.beats, "stalled": h._stalled}
                for h in _hearts.values()]


def running() -> bool:
    return _thread is not None and _thread.is_alive()


def stop() -> None:
    """Stop the sampler thread and drop every registration (tests)."""
    global _thread
    _stop_evt.set()
    t = _thread
    if t is not None and t.is_alive() and t is not threading.current_thread():
        t.join(timeout=5)
    _thread = None
    _stop_evt.clear()
    with _lock:
        _hearts.clear()
        _stall_log.clear()


def _ensure_thread() -> None:
    global _thread
    with _lock:
        if _thread is not None and _thread.is_alive():
            return
        _thread = threading.Thread(target=_run, name="mmlspark-watchdog",
                                   daemon=True)
        _thread.start()


def dump_all_stacks(limit_frames: int = 12) -> Dict[str, str]:
    """Formatted stack per live thread (id+name keyed) — the post-mortem
    payload for "what was every thread doing when the loop stalled"."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, str] = {}
    for tid, frame in sys._current_frames().items():
        stack = "".join(traceback.format_stack(frame, limit=limit_frames))
        out[f"{tid}:{names.get(tid, '?')}"] = stack
    return out


def stall_counts() -> Dict[str, int]:
    """Stalls flagged since process start, per site (bench snapshots)."""
    counts: Dict[str, int] = {}
    with _lock:
        for s in _stall_log:
            counts[s["site"]] = counts.get(s["site"], 0) + 1
    return counts


def _flag_stall(hb: Heartbeat, age: float) -> None:
    from . import logging as _logging
    stacks = dump_all_stacks()
    _metrics.safe_counter("watchdog_stalls_total", site=hb.site).inc()
    _flight.record("watchdog_stall", site=hb.site,
                   age_seconds=round(age, 3), beats=hb.beats,
                   stacks=stacks)
    dump_path = None
    try:
        dump_path = _flight.dump()
    except Exception:  # noqa: BLE001 — a full disk must not kill the sampler
        pass
    _logging.get_logger("mmlspark_tpu.watchdog").error(
        "stall: heartbeat %r silent for %.3fs (threshold %.3fs); "
        "flight ring dumped", hb.site, age,
        max(hb.stall_seconds or 0.0, _stall_seconds),
        site=hb.site, dump=dump_path)
    with _lock:
        _stall_log.append({"site": hb.site, "age_seconds": round(age, 3),
                           "ts": time.time(), "dump": dump_path})
        del _stall_log[:-256]
    _emit_event("stall", hb.site, age_seconds=round(age, 3))


def _run() -> None:
    while not _stop_evt.wait(get_interval_seconds()):
        if not _metrics.enabled():
            continue
        # piggyback the periodic device-memory sample on the watchdog
        # tick (throttled + jax-guarded inside maybe_sample_device_memory)
        _device.maybe_sample_device_memory()
        now = time.monotonic()
        with _lock:
            hearts = list(_hearts.values())
        for hb in hearts:
            if hb._closed:
                continue
            if hb.thread is not None and not hb.thread.is_alive():
                # the loop's thread is gone (crashed out without close()):
                # deregister instead of reading as an eternal stall
                hb.close()
                continue
            age = now - hb.last
            if age > max(hb.stall_seconds or 0.0, _stall_seconds):
                if not hb._stalled:
                    hb._stalled = True      # once per episode
                    try:
                        _flag_stall(hb, age)
                    except Exception:  # noqa: BLE001
                        pass
            elif hb._stalled:
                hb._stalled = False
                _flight.record("watchdog_recovered", site=hb.site,
                               age_seconds=round(age, 3))


# ---------------------------------------------------------------------------
# Training-health sentinels
# ---------------------------------------------------------------------------

# metric names where larger is better: divergence there means *falling*,
# which early stopping already handles — the sentinels only chase blow-ups
_HIGHER_BETTER_TOKENS = ("auc", "ndcg", "map", "accuracy", "acc")


def _higher_is_better(metric_name: Optional[str]) -> bool:
    n = (metric_name or "").lower()
    return any(tok in n for tok in _HIGHER_BETTER_TOKENS)


class _TrainingState:
    __slots__ = ("losses", "durations", "healthy")

    def __init__(self, window: int):
        self.losses: deque = deque(maxlen=window)
        self.durations: deque = deque(maxlen=window)
        self.healthy = True


_training: Dict[str, _TrainingState] = {}


def _loss_window() -> int:
    return max(2, int(_env_float(_WINDOW_ENV, 8)))


def _state(model: str) -> _TrainingState:
    with _lock:
        st = _training.get(model)
        if st is None:
            st = _training[model] = _TrainingState(_loss_window())
        return st


def _unhealthy(model: str, kind: str, **fields: Any) -> None:
    from . import logging as _logging
    st = _state(model)
    st.healthy = False
    _metrics.safe_gauge("training_health", model=model).set(0.0)
    _metrics.safe_counter("training_health_events_total",
                          model=model, kind=kind).inc()
    _flight.record("training_health", model=model, event=kind, **fields)
    _logging.get_logger("mmlspark_tpu.watchdog").error(
        "training health: %s on %s", kind, model, model=model, **fields)
    _emit_event(kind, model, **fields)


def report_training_metric(model: str, iteration: int,
                           loss: Optional[float] = None,
                           metric_name: Optional[str] = None,
                           seconds: Optional[float] = None) -> None:
    """Feed one training round's loss and/or wall time into the sentinels.

    No-op while telemetry is disabled. ``loss`` runs the NaN/Inf and
    windowed-divergence checks (divergence only for lower-is-better
    metrics); ``seconds`` runs the throughput-collapse check.
    """
    if not _metrics.enabled():
        return
    st = _state(model)
    if st.healthy:
        _metrics.safe_gauge("training_health", model=model).set(1.0)
    if loss is not None:
        loss = float(loss)
        if not math.isfinite(loss):
            _unhealthy(model, "nan_loss", iteration=iteration,
                       metric=metric_name, value=repr(loss))
        elif not _higher_is_better(metric_name):
            if (len(st.losses) == st.losses.maxlen
                    and loss > min(st.losses) * DIVERGENCE_FACTOR
                    and loss > st.losses[0]):
                _unhealthy(model, "loss_divergence", iteration=iteration,
                           metric=metric_name, value=loss,
                           window_min=min(st.losses))
            st.losses.append(loss)
    if seconds is not None and seconds > 0:
        if len(st.durations) == st.durations.maxlen:
            med = sorted(st.durations)[len(st.durations) // 2]
            if med > 0 and seconds > med * COLLAPSE_FACTOR:
                _unhealthy(model, "throughput_collapse",
                           iteration=iteration, seconds=round(seconds, 4),
                           window_median=round(med, 4))
        st.durations.append(float(seconds))


def scan_eval_history(model: str, history: Optional[Dict[str, Any]]) -> bool:
    """Post-fit audit of a booster's full metric history — catches NaN /
    divergence on the fused single-dispatch training paths, which never
    invoke a per-round callback. Returns final health."""
    if not _metrics.enabled():
        return True
    st = _state(model)
    for name, series in (history or {}).items():
        vals = [float(v) for v in (series or [])]
        if any(not math.isfinite(v) for v in vals):
            _unhealthy(model, "nan_loss", iteration=len(vals) - 1,
                       metric=str(name), value="non-finite in history")
            continue
        if vals and not _higher_is_better(name):
            lo = min(vals)
            if lo > 0 and vals[-1] > lo * DIVERGENCE_FACTOR:
                _unhealthy(model, "loss_divergence",
                           iteration=len(vals) - 1, metric=str(name),
                           value=vals[-1], window_min=lo)
    if st.healthy:
        _metrics.safe_gauge("training_health", model=model).set(1.0)
    return st.healthy


def training_healthy(model: str) -> bool:
    with _lock:
        st = _training.get(model)
    return st.healthy if st is not None else True


def reset_training_health(model: Optional[str] = None) -> None:
    """Forget sentinel state (all models by default) — a new fit starts
    healthy. Tests and sweep loops call this between fits."""
    with _lock:
        if model is None:
            _training.clear()
        else:
            _training.pop(model, None)
