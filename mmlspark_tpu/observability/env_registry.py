"""Central registry of every ``MMLSPARK_TPU_*`` environment variable.

One declarative table, three consumers:

* **graftlint** (``env-var-registry`` rule): a ``MMLSPARK_TPU_*``
  literal anywhere in the package that is not declared here — or an
  entry here that nothing reads — fails the lint, so the table cannot
  drift from the code.
* **docs**: the env-var tables in ``docs/observability.md`` and
  ``docs/performance.md`` are generated from this table by
  ``tools/gen_env_docs.py`` (``--check`` gates drift in CI).
* **humans**: ``python -c "from mmlspark_tpu.observability import
  env_registry as e; print(e.render_markdown())"``.

Entries read outside the Python package declare it: ``where="native"``
(the C++ host runtime) — the lint then exempts them from the
must-be-read-in-package check. Keep ``doc`` to one line; defaults are
the *effective* defaults (what an unset variable behaves like), quoted
as the reader would type them.

Stdlib-only on purpose: observability modules are imported by every
layer and must stay cycle-free (the ``obs-import-cycle`` rule).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

__all__ = ["EnvVar", "REGISTRY", "get", "names", "render_markdown",
           "SECTIONS", "env_float", "env_int"]


def env_float(name: str, default: float) -> float:
    """Read a float knob; unset, empty, or unparseable -> ``default``
    (the one fallback semantics every consumer shares — keep parsing
    here so it cannot drift between subsystems)."""
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


#: section id -> docs file the generated table lives in
SECTIONS: Dict[str, str] = {"observability": "docs/observability.md",
                            "performance": "docs/performance.md",
                            "robustness": "docs/robustness.md"}

#: who reads an entry: "python" (the package — lint-checked), "native"
#: (the C++ host runtime, exempt from the must-be-read check)
_WHERE = ("python", "native")


@dataclass(frozen=True)
class EnvVar:
    #: exact variable name (the string literal read sites use)
    name: str
    #: effective default when unset, as a human-readable value
    default: str
    #: one-line purpose, rendered into the docs tables
    doc: str
    #: docs table this entry renders into
    section: str = "observability"
    #: who reads it: "python" (the package — lint-checked), "native"
    #: (the C++ host runtime)
    where: str = "python"

    def __post_init__(self) -> None:
        # a typo'd section silently drops the knob from every generated
        # docs table, and a typo'd where silently exempts it from the
        # staleness check — both defeat the single-source-of-truth
        # contract, so they fail at import instead
        if self.section not in SECTIONS:
            raise ValueError(f"{self.name}: unknown section "
                             f"{self.section!r} (known: {sorted(SECTIONS)})")
        if self.where not in _WHERE:
            raise ValueError(f"{self.name}: unknown where "
                             f"{self.where!r} (known: {list(_WHERE)})")
        if not self.name.startswith("MMLSPARK_TPU_"):
            raise ValueError(f"{self.name}: registry entries must be "
                             "MMLSPARK_TPU_* variables")


REGISTRY: Tuple[EnvVar, ...] = (
    # -- logging -----------------------------------------------------------
    EnvVar(name="MMLSPARK_TPU_LOG_LEVEL", default="info",
           doc="log funnel threshold: `debug`/`info`/`warning`/`error` "
               "(runtime: `logging.set_level`)"),
    EnvVar(name="MMLSPARK_TPU_LOG_FILE", default="(stderr)",
           doc="append JSON log lines to this file instead of stderr; an "
               "unopenable path degrades to stderr with one console "
               "notice (runtime: `logging.set_log_file`)"),
    EnvVar(name="MMLSPARK_TPU_LOG_RATE", default="200",
           doc="per-logger records/second cap, 0 = unlimited; overflow "
               "bumps `log_records_dropped_total{logger=...}` and emits "
               "one suppression notice when the window reopens"),
    # -- tracing / flight recorder ----------------------------------------
    EnvVar(name="MMLSPARK_TPU_MAX_TRACE_EVENTS", default="100000",
           doc="span ring-buffer capacity; oldest events drop once full "
               "(`trace_events_dropped_total`; runtime: "
               "`spans.set_max_trace_events`)"),
    EnvVar(name="MMLSPARK_TPU_SLOW_REQUEST_SECONDS", default="1.0",
           doc="requests slower than this record a {metric, seconds, "
               "trace_id} exemplar + `slow_requests_total` (runtime: "
               "`tracing.set_slow_threshold`)"),
    EnvVar(name="MMLSPARK_TPU_FLIGHT_EVENTS", default="4096",
           doc="flight-recorder ring capacity (runtime: "
               "`flight.set_capacity`)"),
    EnvVar(name="MMLSPARK_TPU_FLIGHT_DIR", default="(system temp dir)",
           doc="directory flight-ring dumps land in (crash, SIGUSR2, "
               "watchdog stall, `/debug/flight`); shared-dir safe — "
               "every dump is suffixed pid + per-process counter"),
    EnvVar(name="MMLSPARK_TPU_TIMELINE_EVENTS", default="8192",
           doc="fleet-timeline ring capacity on the gateway (merged "
               "worker flight deltas + lifecycle events; "
               "`/debug/timeline`)"),
    EnvVar(name="MMLSPARK_TPU_FLIGHT_SCRAPE", default="1",
           doc="`0` disables the federation sweep's incremental "
               "`/debug/flight?since=` pull into the fleet timeline "
               "(the `/metrics` scrape itself is unaffected)"),
    # -- federation / watchdog --------------------------------------------
    EnvVar(name="MMLSPARK_TPU_FEDERATION_INTERVAL_SECONDS", default="5.0",
           doc="gateway metrics-federation sweep period over registered "
               "workers"),
    EnvVar(name="MMLSPARK_TPU_WATCHDOG_STALL_SECONDS", default="30",
           doc="global heartbeat stall threshold; per-site floors take "
               "the max (runtime: `watchdog.set_stall_seconds`)"),
    EnvVar(name="MMLSPARK_TPU_WATCHDOG_INTERVAL_SECONDS",
           default="stall/4, clamped to [0.05 s, 5 s]",
           doc="watchdog sampling period (runtime: "
               "`watchdog.set_interval_seconds`)"),
    EnvVar(name="MMLSPARK_TPU_WATCHDOG_LOSS_WINDOW", default="8",
           doc="training-health sentinel window length (divergence / "
               "throughput-collapse detection)"),
    EnvVar(name="MMLSPARK_TPU_TELEMETRY_ROUNDS", default="(off)",
           doc="`1` enables the per-boost-round telemetry callback — "
               "forces the host training loop, so the fused "
               "single-dispatch paths stay the default"),
    # -- SLO plane / tail attribution --------------------------------------
    EnvVar(name="MMLSPARK_TPU_SLO", default="(off)",
           doc="per-endpoint serving objectives, `;`-separated "
               "`endpoint:p99<25ms,err<0.1%` entries (`p<P><<T>ms|s` = "
               "latency clause, `err<C%` = 5xx ceiling); drives the "
               "`slo_burn_rate`/`slo_budget_remaining` gauges, "
               "`/debug/slo`, and the tail sampler on both engines; a "
               "malformed spec degrades to unconfigured with a flight "
               "event (runtime: `slo.configure`)"),
    EnvVar(name="MMLSPARK_TPU_TAIL_SAMPLES", default="128",
           doc="tail-sampler reservoir capacity: how many objective-"
               "breaching request timelines `/debug/tail` retains "
               "(oldest evicted and counted in `dropped_total`)"),
    # -- roofline / device-memory ledgers ---------------------------------
    EnvVar(name="MMLSPARK_TPU_PEAK_FLOPS", default="(per-device_kind table)",
           doc="backend peak FLOP/s the roofline ledger computes "
               "%-of-peak against; overrides the built-in per-"
               "`device_kind` table (unknown backends degrade to "
               "ratios-only)"),
    EnvVar(name="MMLSPARK_TPU_PEAK_BYTES_PER_SECOND",
           default="(per-device_kind table)",
           doc="backend peak HBM bytes/s for the roofline ledger's "
               "memory-bound axis; same override/degradation semantics "
               "as `MMLSPARK_TPU_PEAK_FLOPS`"),
    EnvVar(name="MMLSPARK_TPU_DEVICE_MEMORY_INTERVAL_SECONDS",
           default="30",
           doc="period of the background `device_memory_bytes` sampling "
               "hooked into the watchdog tick and federation sweep "
               "(0 disables; samples only when jax is already loaded)"),
    # -- training / histogram engine --------------------------------------
    EnvVar(name="MMLSPARK_TPU_HIST_ENGINE", default="auto",
           section="performance",
           doc="histogram engine: `pallas` (TPU MXU kernel) / `onehot` "
               "(XLA matmul) / `scatter` (segment-sum; CPU/GPU) / "
               "`auto` (resolve per backend before any cache key)"),
    EnvVar(name="MMLSPARK_TPU_PALLAS_INTERPRET", default="(off)",
           section="performance",
           doc="run the Pallas histogram kernel through the interpreter "
               "on CPU (CI leg: packing/layout bugs surface without TPU "
               "hardware)"),
    EnvVar(name="MMLSPARK_TPU_DISABLE_PALLAS_HIST", default="(off)",
           section="performance",
           doc="set to force the non-Pallas engines even on TPU"),
    EnvVar(name="MMLSPARK_TPU_HIST_UNROLL_MAX", default="128",
           section="performance",
           doc="Pallas kernel unroll cap; 0 keeps the dynamic fori_loop "
               "everywhere (escape hatch for pathological Mosaic "
               "compiles)"),
    EnvVar(name="MMLSPARK_TPU_HIST_BLOCKS", default="0",
           section="performance",
           doc="canonical histogram-reduction block count for "
               "topology-independent GBDT training: device counts "
               "dividing it grow bit-identical trees (`8` covers 1/2/4/8 "
               "devices); 0 keeps the plain psum path (resolved via "
               "`placement.resolve_hist_blocks` before any cache key; "
               "`GrowConfig.hist_blocks` overrides per fit)"),
    EnvVar(name="MMLSPARK_TPU_MESH_DEVICES", default="(all devices)",
           section="performance",
           doc="cap the default mesh to the first N devices (scaling A/B "
               "legs, placement debugging); explicit `make_mesh` "
               "shape/devices arguments are honored as given"),
    EnvVar(name="MMLSPARK_TPU_COMPILE_CACHE_DIR", default="(off)",
           section="performance",
           doc="wires jax's persistent compilation cache to this "
               "directory (read once per process, first call wins; "
               "compile flight events carry the active value)"),
    EnvVar(name="MMLSPARK_TPU_DISABLE_FUSED_VALID", default="(off)",
           section="performance",
           doc="set to force the host round loop instead of the fused "
               "on-device early-stopping training path"),
    EnvVar(name="MMLSPARK_TPU_DISABLE_FUSED_DART", default="(off)",
           section="performance",
           doc="set to force the host round loop for DART training"),
    EnvVar(name="MMLSPARK_TPU_TIMING", default="(off)",
           section="performance",
           doc="`1` prints a wall-time phase breakdown per "
               "`train_booster` call (console output by design — an "
               "explicit operator request, independent of the telemetry "
               "kill switch)"),
    EnvVar(name="MMLSPARK_TPU_BINNED_CACHE", default="1",
           section="performance",
           doc="`0` disables the binned-device-dataset fit cache (the "
               "cache pins up to two [F, n] int32 matrices in device "
               "memory; `clear_binned_dataset_cache()` releases them)"),
    EnvVar(name="MMLSPARK_TPU_PREDICT_DTYPE", default="f32",
           section="performance",
           doc="fused-predict lane: `f32` / `bf16` (thresholds + features "
               "cast, f32 leaves) / `int8` (bin-id routing + quantized "
               "leaves); resolved once in `quantize.resolve_predict_dtype` "
               "before any predictor cache key — unknown values degrade "
               "to `f32` with a flight event; per-call "
               "`predict(..., predict_dtype=...)` overrides"),
    EnvVar(name="MMLSPARK_TPU_INGEST_HOST_QUANT", default="(off)",
           section="performance",
           doc="`1` bins streaming-ingest chunks on the host (same "
               "searchsorted grid as the device binner — bit-identical "
               "matrices) and ships uint8 instead of f32, 4x fewer h2d "
               "bytes; default off because host binning costs CPU per "
               "chunk"),
    # -- streaming / serving ----------------------------------------------
    EnvVar(name="MMLSPARK_TPU_DISABLE_PREFETCH", default="(off)",
           section="performance",
           doc="`1`/`true`/`yes` degrades every streaming adopter to the "
               "plain sequential loop (no background reader thread)"),
    EnvVar(name="MMLSPARK_TPU_SERVING_ENGINE", default="async",
           section="performance",
           doc="serving engine behind `serve()` / `serving_main`: "
               "`async` (io/aserve event loop, continuous batching, "
               "zero-copy slot admission) or `threaded` (deprecated: "
               "ThreadingHTTPServer + get_batch windows — selecting it "
               "logs a structured warning and bumps "
               "`serving_engine_deprecated_total`); `serve().engine(...)` "
               "and `serving_main --engine` override; an unknown env "
               "value degrades to `async` with a flight event"),
    EnvVar(name="MMLSPARK_TPU_BUNDLE_DIR", default="(off)",
           section="performance",
           doc="AOT serving-bundle directory `serving_main` workers "
               "prewarm the predictor cache from before binding "
               "(`--bundle` overrides; build with `python -m "
               "mmlspark_tpu.bundles build`); a fingerprint-mismatched "
               "or corrupt bundle degrades to JIT with a structured "
               "warning"),
    EnvVar(name="MMLSPARK_TPU_ASERVE_SLOTS", default="(max_batch)",
           section="performance",
           doc="async engine slot-table size — rows per pre-pinned "
               "staging buffer, i.e. the device batch cap the compiled "
               "predictor sees (pow2-rounded; 0 follows the query's "
               "`max_batch`; `auto` sizes from the auto-tuner's measured "
               "p99.9 admitted-batch rows reconciled against HBM "
               "headroom — needs `MMLSPARK_TPU_TUNING_DIR`); the "
               "admission backlog bound stays "
               "`MMLSPARK_TPU_MAX_QUEUE_DEPTH`"),
    # -- auto-tuning (docs/performance.md §Auto-tuning) --------------------
    EnvVar(name="MMLSPARK_TPU_TUNING_DIR", default="(off)",
           section="performance",
           doc="directory of the auto-tuner's decision store — setting "
               "it enables the measure→decide loop (engine selection, "
               "bucket ladder, dispatch hold window, slot sizing); "
               "decisions persist here so the second process starts "
               "tuned, fingerprinted on device kind + model hash + "
               "framework version (skew degrades loudly to the static "
               "rules)"),
    EnvVar(name="MMLSPARK_TPU_TUNE_MIN_SAMPLES", default="64",
           section="performance",
           doc="observed-batch evidence bar: the serving-side tuning "
               "decisions (ladder / slots / hold window) are taken once "
               "this many admitted batches have been recorded"),
    EnvVar(name="MMLSPARK_TPU_TUNE_HOLD_MS", default="(tuner decides)",
           section="performance",
           doc="pin the async dispatch hold window in ms (`0` disables "
               "holding entirely) — the opt-out for tuning site 3; "
               "unset lets the tuner derive it from the roofline "
               "`bound` verdict and stage EWMAs"),
    EnvVar(name="MMLSPARK_TPU_TUNE_HOLD_CAP_MS", default="2.0",
           section="performance",
           doc="upper bound on the tuner-computed dispatch hold window "
               "(the latency the pacing decision may spend forming a "
               "fuller batch; the SLO-burn override dispatches "
               "immediately regardless)"),
    # -- explainability ----------------------------------------------------
    EnvVar(name="MMLSPARK_TPU_SHAP_HOST", default="(auto by backend)",
           section="performance",
           doc="`1` forces the host TreeSHAP recursion (the reference "
               "the device path is pinned against)"),
    EnvVar(name="MMLSPARK_TPU_SHAP_DEVICE", default="(auto by backend)",
           section="performance",
           doc="`1` forces the fixed-shape device TreeSHAP program "
               "(default on TPU; loses to host engines on XLA CPU)"),
    EnvVar(name="MMLSPARK_TPU_SHAP_NATIVE", default="1",
           section="performance",
           doc="`0` disables the native C++ TreeSHAP engine inside the "
               "host path (falls back to vectorized numpy recursion)"),
    # -- robustness: fault injection --------------------------------------
    EnvVar(name="MMLSPARK_TPU_FAILPOINTS", default="(off)",
           section="robustness",
           doc="fault-injection rules, `site:kind[:arg][@N]` "
               "comma-separated (kinds `error_<status>`/`error`/`delay`/"
               "`exit`; grammar + site table in docs/robustness.md); "
               "byte-identical no-op when unset"),
    EnvVar(name="MMLSPARK_TPU_FAILPOINTS_SEED", default="0",
           section="robustness",
           doc="seed for probabilistic fault rules — the same spec + "
               "seed replays the same fired-fault sequence"),
    # -- robustness: retry policy -----------------------------------------
    EnvVar(name="MMLSPARK_TPU_RETRY_MAX_ATTEMPTS", default="3",
           section="robustness",
           doc="`RetryPolicy` total attempts including the first"),
    EnvVar(name="MMLSPARK_TPU_RETRY_BASE_MS", default="25",
           section="robustness",
           doc="`RetryPolicy` full-jitter backoff base (delay drawn "
               "uniform(0, min(cap, base·2^attempt)))"),
    EnvVar(name="MMLSPARK_TPU_RETRY_MAX_MS", default="2000",
           section="robustness",
           doc="`RetryPolicy` backoff cap per sleep"),
    EnvVar(name="MMLSPARK_TPU_RETRY_BUDGET_RATIO", default="0.1",
           section="robustness",
           doc="retry-budget tokens accrued per admitted request — under "
               "a total outage retry load converges to this fraction of "
               "live traffic"),
    EnvVar(name="MMLSPARK_TPU_RETRY_BUDGET_MIN", default="10",
           section="robustness",
           doc="retry-budget starting balance (cold starts can fail over "
               "before traffic has accrued tokens)"),
    EnvVar(name="MMLSPARK_TPU_RETRY_BUDGET_CAP", default="100",
           section="robustness",
           doc="retry-budget token ceiling"),
    # -- robustness: circuit breakers -------------------------------------
    EnvVar(name="MMLSPARK_TPU_BREAKER_CONSECUTIVE", default="5",
           section="robustness",
           doc="consecutive soft failures that open a worker's breaker"),
    EnvVar(name="MMLSPARK_TPU_BREAKER_ERROR_RATE", default="0.5",
           section="robustness",
           doc="windowed error-rate threshold that opens a breaker (at "
               "`MIN_VOLUME`+ observations)"),
    EnvVar(name="MMLSPARK_TPU_BREAKER_WINDOW", default="20",
           section="robustness",
           doc="breaker outcome-window length for the error-rate trip"),
    EnvVar(name="MMLSPARK_TPU_BREAKER_MIN_VOLUME", default="10",
           section="robustness",
           doc="minimum windowed observations before the error rate can "
               "trip a breaker"),
    EnvVar(name="MMLSPARK_TPU_BREAKER_OPEN_SECONDS",
           default="(gateway health interval)", section="robustness",
           doc="open-state cooldown before a half-open probe is due"),
    EnvVar(name="MMLSPARK_TPU_BREAKER_HALF_OPEN_SUCCESSES", default="1",
           section="robustness",
           doc="successful health-loop probes needed to re-close a "
               "half-open breaker"),
    EnvVar(name="MMLSPARK_TPU_DEADLINE_MARGIN_MS", default="5",
           section="robustness",
           doc="per-hop attenuation subtracted from the re-emitted "
               "`X-Deadline-Ms` budget (wire + serialization slack)"),
    # -- robustness: admission / drain / gateway --------------------------
    EnvVar(name="MMLSPARK_TPU_MAX_QUEUE_DEPTH", default="512",
           section="robustness",
           doc="worker bounded-queue admission limit — past it requests "
               "shed with 429 + a queue-drain-derived Retry-After "
               "(0 = unbounded)"),
    EnvVar(name="MMLSPARK_TPU_DRAIN_SETTLE_SECONDS", default="0.5",
           section="robustness",
           doc="SIGTERM drain: keep serving this long after "
               "deregistration while gateways drop the worker from "
               "their routing tables"),
    EnvVar(name="MMLSPARK_TPU_DRAIN_TIMEOUT_SECONDS", default="30",
           section="robustness",
           doc="SIGTERM drain: seconds to finish queued + in-flight "
               "work before the worker stops"),
    EnvVar(name="MMLSPARK_TPU_GATEWAY_HEALTH_INTERVAL_SECONDS",
           default="2.0", section="robustness",
           doc="gateway health-sweep period — also the cadence of "
               "half-open breaker probes"),
    EnvVar(name="MMLSPARK_TPU_GATEWAY_MAX_FAILOVERS", default="3",
           section="robustness",
           doc="failover retries per routed request (each also spends "
               "one retry-budget token)"),
    # -- robustness: preemption-safe training -----------------------------
    EnvVar(name="MMLSPARK_TPU_STRICT_RESUME", default="(off)",
           section="robustness",
           doc="`1` = resume-or-die: checkpoints that exist but mismatch "
               "the run's fingerprint raise `CheckpointMismatchError` "
               "instead of silently retraining from scratch"),
    EnvVar(name="MMLSPARK_TPU_CHECKPOINT_ON_UNHEALTHY", default="(off)",
           section="robustness",
           doc="`1` = a watchdog stall or training-health sentinel "
               "during a checkpointed fit dumps the newest HEALTHY "
               "state immediately (one-shot per fit)"),
    # -- native host runtime ----------------------------------------------
    EnvVar(name="MMLSPARK_TPU_NATIVE_CACHE",
           default="(per-user dir under system temp, mode 0700)",
           section="performance",
           doc="cache directory for the compile-on-use native host "
               "runtime `.so`"),
    EnvVar(name="MMLSPARK_TPU_DISABLE_NATIVE", default="(off)",
           section="performance",
           doc="set to skip loading/compiling the native host runtime "
               "entirely (pure-Python fallbacks)"),
    EnvVar(name="MMLSPARK_TPU_NATIVE_THREADS", default="(hardware "
           "concurrency, budget-clamped)", section="performance",
           where="native",
           doc="caps the native TreeSHAP thread pool (read by the C++ "
               "runtime; threads are also clamped to the 256 MiB arena "
               "budget)"),
)

_BY_NAME: Dict[str, EnvVar] = {v.name: v for v in REGISTRY}


def get(name: str) -> Optional[EnvVar]:
    return _BY_NAME.get(name)


def names() -> frozenset:
    return frozenset(_BY_NAME)


def render_markdown(section: Optional[str] = None) -> str:
    """GitHub-markdown table of the registry (one ``section``, or all)."""
    rows = [v for v in REGISTRY
            if section is None or v.section == section]
    out = ["| Variable | Default | Purpose |",
           "| --- | --- | --- |"]
    for v in rows:
        out.append(f"| `{v.name}` | {v.default} | {v.doc} |")
    return "\n".join(out)
