"""Roofline ledger: achieved FLOP/s and bytes/s per hot executable.

Every compiled program the framework observes (the `_ObservedProgram`
predict cache, the train step cache, bundle-prewarmed executables)
already carries XLA ``cost_analysis()`` FLOPs and bytes-accessed on its
compile flight event. This module pairs that static cost with a
*measured* per-call wall time (bounded per-key EWMA + call count, fed by
a lightweight call-site timer) and renders each executable as a point on
the roofline: achieved FLOP/s and bytes/s against backend peaks.

Peaks come from a small per-``device_kind`` table, overridable via the
``MMLSPARK_TPU_PEAK_FLOPS`` / ``MMLSPARK_TPU_PEAK_BYTES_PER_SECOND``
registry knobs. An unknown backend degrades to ratios-only: achieved
rates are still reported, ``*_pct`` fields are ``None`` and the payload
carries an explicit ``peaks.source == "unknown"`` note, so a CPU CI leg
never fabricates a %-of-peak.

Stdlib-only by the ``obs-import-cycle`` contract; jax is touched lazily
(and only when already imported — the gateway-isolation rule) to read
``device_kind``. Every mutator is a no-op while telemetry is disabled,
keeping instrumented call sites byte-identical to their uninstrumented
behavior.
"""

from __future__ import annotations

import sys
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional

from . import metrics as _metrics
from .env_registry import env_float

__all__ = ["note_device_kind", "resolve_peaks", "register_executable",
           "observe_call", "snapshot_payload", "reset"]

# (peak FLOP/s, peak HBM bytes/s) per PJRT device_kind — dense-matmul
# peaks from published specs; ratios, not guarantees. Unlisted kinds
# (CPU, GPU backends) degrade to ratios-only.
_PEAK_TABLE: Dict[str, tuple] = {
    "TPU v4": (275e12, 1.228e12),
    "TPU v5 lite": (197e12, 0.819e12),
    "TPU v5e": (197e12, 0.819e12),
    "TPU v5p": (459e12, 2.765e12),
    "TPU v6e": (918e12, 1.640e12),
}

_PEAK_FLOPS_ENV = "MMLSPARK_TPU_PEAK_FLOPS"
_PEAK_BYTES_ENV = "MMLSPARK_TPU_PEAK_BYTES_PER_SECOND"

_EWMA_ALPHA = 0.2     # ~5-call memory: smooths jitter, tracks re-tuning
_MAX_ENTRIES = 128    # bounded ledger — LRU eviction past this

_lock = threading.Lock()
_entries: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
_device_kind: Optional[str] = None


def _key_label(key_hash: str) -> str:
    """Short stable series label (full hash stays in the payload)."""
    return str(key_hash)[:12]


def note_device_kind(kind: Optional[str]) -> None:
    """Record the backend's PJRT ``device_kind`` (callers that already
    hold a jax device pass it in; last writer wins)."""
    global _device_kind
    if kind:
        _device_kind = str(kind)


def _maybe_device_kind() -> Optional[str]:
    """Best-effort device kind: recorded value, else probe jax — but only
    when jax is already loaded (a gateway or bare CLI must never drag the
    framework in just to render a debug page)."""
    if _device_kind is not None:
        return _device_kind
    if "jax" not in sys.modules:
        return None
    try:
        import jax
        devs = jax.devices()
        if devs:
            note_device_kind(getattr(devs[0], "device_kind", None))
    except Exception:
        pass
    return _device_kind


def resolve_peaks() -> Dict[str, Any]:
    """Backend peaks: ``{"flops_per_second", "bytes_per_second",
    "source"}``. Env overrides win; then the per-device_kind table; an
    unrecognized backend yields ``None`` peaks with ``source:
    "unknown"`` (ratios-only degradation)."""
    env_flops = env_float(_PEAK_FLOPS_ENV, 0.0)
    env_bytes = env_float(_PEAK_BYTES_ENV, 0.0)
    if env_flops > 0 or env_bytes > 0:
        return {"flops_per_second": env_flops if env_flops > 0 else None,
                "bytes_per_second": env_bytes if env_bytes > 0 else None,
                "source": "env"}
    kind = _maybe_device_kind()
    if kind in _PEAK_TABLE:
        flops, byts = _PEAK_TABLE[kind]
        return {"flops_per_second": flops, "bytes_per_second": byts,
                "source": f"table:{kind}"}
    return {"flops_per_second": None, "bytes_per_second": None,
            "source": "unknown"}


def register_executable(key_hash: str, kind: str = "predict",
                        flops: Optional[float] = None,
                        bytes_accessed: Optional[float] = None,
                        compile_seconds: Optional[float] = None,
                        label: Optional[str] = None,
                        dtype: Optional[str] = None) -> None:
    """Add or refresh a ledger entry for a compiled executable.

    ``flops`` / ``bytes_accessed`` come from ``cost_analysis()`` (None
    when the backend exposes none — the entry still tracks wall time).
    ``dtype`` labels the executable's compute lane (the quantized
    predict lanes register as ``int8``/``bf16``, so the ledger shows the
    reduced ``bytes_accessed`` next to the lane that earned it).
    No-op while telemetry is disabled.
    """
    if not _metrics.enabled():
        return
    key_hash = str(key_hash)
    with _lock:
        entry = _entries.get(key_hash)
        if entry is None:
            entry = {"kind": kind, "label": label, "dtype": None,
                     "flops": None, "bytes_accessed": None,
                     "compile_seconds": None,
                     "calls": 0, "ewma_seconds": None}
            _entries[key_hash] = entry
            while len(_entries) > _MAX_ENTRIES:
                _entries.popitem(last=False)
        else:
            _entries.move_to_end(key_hash)
            entry["kind"] = kind
        if label is not None:
            entry["label"] = label
        if dtype is not None:
            entry["dtype"] = dtype
        if flops is not None:
            entry["flops"] = float(flops)
        if bytes_accessed is not None:
            entry["bytes_accessed"] = float(bytes_accessed)
        if compile_seconds is not None:
            entry["compile_seconds"] = float(compile_seconds)


def observe_call(key_hash: str, seconds: float) -> None:
    """Feed one measured call into the per-key EWMA and export the
    ``roofline_*`` families. Unregistered keys get a minimal entry (the
    cost arrives whenever the compile event fires). No-op while
    telemetry is disabled."""
    if not _metrics.enabled():
        return
    key_hash = str(key_hash)
    seconds = float(seconds)
    with _lock:
        entry = _entries.get(key_hash)
        if entry is None:
            entry = {"kind": "unknown", "label": None, "dtype": None,
                     "flops": None, "bytes_accessed": None,
                     "compile_seconds": None,
                     "calls": 0, "ewma_seconds": None}
            _entries[key_hash] = entry
            while len(_entries) > _MAX_ENTRIES:
                _entries.popitem(last=False)
        else:
            _entries.move_to_end(key_hash)
        entry["calls"] += 1
        prev = entry["ewma_seconds"]
        entry["ewma_seconds"] = (seconds if prev is None else
                                 _EWMA_ALPHA * seconds
                                 + (1.0 - _EWMA_ALPHA) * prev)
        ewma = entry["ewma_seconds"]
        flops = entry["flops"]
        byts = entry["bytes_accessed"]
    key = _key_label(key_hash)
    _metrics.safe_counter("roofline_calls_total", key=key).inc()
    _metrics.safe_gauge("roofline_call_seconds", key=key).set(ewma)
    peaks = resolve_peaks()
    if ewma and ewma > 0:
        if flops is not None and peaks["flops_per_second"]:
            _metrics.safe_gauge("roofline_flops_pct", key=key).set(
                100.0 * (flops / ewma) / peaks["flops_per_second"])
        if byts is not None and peaks["bytes_per_second"]:
            _metrics.safe_gauge("roofline_bytes_pct", key=key).set(
                100.0 * (byts / ewma) / peaks["bytes_per_second"])


def _render_entry(key_hash: str, entry: Dict[str, Any],
                  peaks: Dict[str, Any]) -> Dict[str, Any]:
    ewma = entry["ewma_seconds"]
    flops = entry["flops"]
    byts = entry["bytes_accessed"]
    achieved_f = (flops / ewma) if (flops is not None and ewma) else None
    achieved_b = (byts / ewma) if (byts is not None and ewma) else None
    pf, pb = peaks["flops_per_second"], peaks["bytes_per_second"]
    flops_pct = (100.0 * achieved_f / pf) if (achieved_f and pf) else None
    bytes_pct = (100.0 * achieved_b / pb) if (achieved_b and pb) else None
    bound = None
    if flops_pct is not None and bytes_pct is not None:
        bound = "compute" if flops_pct >= bytes_pct else "memory"
    return {"key": key_hash, "key_label": _key_label(key_hash),
            "kind": entry["kind"], "label": entry["label"],
            "dtype": entry.get("dtype"),
            "flops": flops, "bytes_accessed": byts,
            "compile_seconds": entry["compile_seconds"],
            "calls": entry["calls"], "ewma_seconds": ewma,
            "achieved_flops_per_second": achieved_f,
            "achieved_bytes_per_second": achieved_b,
            "flops_pct": flops_pct, "bytes_pct": bytes_pct,
            "bound": bound}


def snapshot_payload() -> Dict[str, Any]:
    """JSON-safe ledger view for ``/debug/roofline`` and the bench
    epilogue. Always renders (even disabled — the route stays truthful
    about an empty ledger)."""
    peaks = resolve_peaks()
    with _lock:
        items = [(k, dict(v)) for k, v in _entries.items()]
    return {"device_kind": _maybe_device_kind(),
            "peaks": peaks,
            "executables": [_render_entry(k, e, peaks)
                            for k, e in items]}


def reset() -> None:
    """Drop every entry and the recorded device kind (tests)."""
    global _device_kind
    with _lock:
        _entries.clear()
    _device_kind = None
