"""Trial-parallel GBDT hyperparameter sweeps: one device dispatch, N models.

The reference parallelizes TuneHyperparameters trials across a Spark thread
pool (reference: automl/TuneHyperparameters.scala:100-160 — awaitable futures
over a fixed pool). The TPU-first equivalent (SURVEY §2b "vmapped/multi-slice
sweeps") runs the trials INSIDE one compiled program: the binned dataset is
replicated, the trial axis is sharded over the mesh's ``data`` axis, and each
device vmaps its slice of trial configs through the shared boosting loop.
Continuous hyperparameters (learning rate, regularization, split thresholds)
become traced scalars, so the sweep compiles ONCE for any number of trials —
the sequential path recompiles per distinct GrowConfig.

Only a restricted estimator envelope is vmappable (plain gbdt boosting, full
rows/features each iteration, K=1 objectives, no early stopping / warm start /
checkpoints); :func:`swept_fit` returns None outside it and the caller falls
back to sequential fits.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ..parallel.placement import pspec as P

from ..core.dataset import Dataset
from ..parallel.compat import shard_map

# estimator param -> GrowConfig field. All are used only inside jnp ops in
# growth.py (verified: no Python-level branching), so they can be traced.
SWEEPABLE: Dict[str, str] = {
    "learningRate": "learning_rate",
    "lambdaL1": "lambda_l1",
    "lambdaL2": "lambda_l2",
    "minGainToSplit": "min_gain_to_split",
    "minSumHessianInLeaf": "min_sum_hessian_in_leaf",
    "minDataInLeaf": "min_data_in_leaf",
}


def _eligible(est, param_maps: List[Dict[str, Any]]) -> bool:
    """True when ``est`` + the swept params fit the vmapped envelope."""
    from ..models.gbdt.api import LightGBMClassifier, LightGBMRegressor

    if not isinstance(est, (LightGBMClassifier, LightGBMRegressor)):
        return False
    if not param_maps or not all(set(m) <= set(SWEEPABLE)
                                 for m in param_maps):
        return False
    g = est.get_or_default
    if g("boostingType") != "gbdt":
        return False
    if (g("baggingFraction") < 1.0 or g("posBaggingFraction") < 1.0
            or g("negBaggingFraction") < 1.0 or g("featureFraction") < 1.0):
        return False
    if g("useQuantizedGrad"):
        return False
    # histSubtraction is NOT gated here: it is tri-state ("auto" default,
    # resolved per backend) and only ENGAGES above the growth layer's row
    # threshold — swept_fit applies that engagement rule once the row
    # count is known, so default-config sweeps keep the vmapped fast path
    if g("earlyStoppingRound") > 0 or g("isProvideTrainingMetric"):
        return False
    if g("modelString") or g("checkpointDir") or g("initScoreCol"):
        return False
    if g("validationIndicatorCol") or (g("numBatches") or 0) > 1:
        return False
    return True


def _objective_of(est, y: np.ndarray):
    """(objective, num_class, kwargs, model_factory) mirroring est.fit."""
    from ..models.gbdt.api import (LightGBMClassificationModel,
                                   LightGBMClassifier,
                                   LightGBMRegressionModel)

    if isinstance(est, LightGBMClassifier):
        classes = np.unique(y[~np.isnan(y.astype(np.float64))])
        num_class = max(int(classes.max()) + 1 if classes.size else 2, 2)
        obj = est.get_or_default("objective") or (
            "binary" if num_class <= 2 else "multiclass")
        if obj != "binary" or num_class > 2:
            return None          # K>1: outside the vmapped envelope
        kwargs = {}
        if est.get_or_default("isUnbalance"):
            pos = float((y > 0).sum())
            kwargs["pos_weight"] = (len(y) - pos) / max(pos, 1.0)
        return obj, num_class, kwargs, (
            lambda b: LightGBMClassificationModel(b, numClasses=num_class))
    obj = est.get_or_default("objective")
    kwargs = {}
    if obj in ("huber", "quantile"):
        kwargs["alpha"] = est.get_or_default("alpha")
    if obj == "tweedie":
        kwargs["tweedie_variance_power"] = est.get_or_default(
            "tweedieVariancePower")
    return obj, 1, kwargs, LightGBMRegressionModel


def swept_fit(est, param_maps: List[Dict[str, Any]],
              train: Dataset) -> Optional[List[Any]]:
    """Fit one model per param map in a single trial-sharded dispatch.

    Returns fitted models (the same classes ``est.fit`` produces, params
    copied from ``est.copy(param_map)``), or None when the estimator/params
    fall outside the vmappable envelope. Trials train on REPLICATED rows
    with per-trial traced hyperparameters — numerically this matches a
    sequential fit on a single-device mesh exactly (same reduction order);
    a sequential fit on a sharded mesh differs only by psum float ordering.
    """
    from ..models.gbdt.api import _cached_binned_dataset
    from ..models.gbdt.booster import _finalize_trees
    from ..models.gbdt.growth import (GrowConfig, grow_tree,
                                      grow_tree_depthwise)
    from ..models.gbdt.objectives import get_objective
    from ..parallel import mesh as meshlib

    if not _eligible(est, param_maps):
        return None
    X, y, w = est._extract_arrays(train)
    base_cfg: GrowConfig = est._grow_config()   # "auto" already resolved
    # subtraction would actually engage inside the trials (single-device
    # rule, resolved config): fall back to sequential fits so the sweep
    # takes exactly the code path — and the memory profile — a plain
    # est.fit() would. The engagement row count is the PADDED dataset size
    # (trials grow on replicated padded rows, not len(y)); below the
    # threshold the resolved flag is inert and the envelope is unchanged.
    from ..models.gbdt.growth import _use_subtraction
    nshards = meshlib.num_shards(meshlib.get_default_mesh())
    n_pad = -(-len(y) // nshards) * nshards
    if _use_subtraction(base_cfg, None, n_pad):
        return None
    objinfo = _objective_of(est, y)
    if objinfo is None:
        return None
    objective, _num_class, obj_kwargs, model_factory = objinfo
    obj = get_objective(objective, 1, **obj_kwargs)
    if obj.num_scores != 1:
        return None
    max_bin = est.get_or_default("maxBin")
    num_iterations = est.get_or_default("numIterations")
    ds = _cached_binned_dataset(
        X, y, w, max_bin=max_bin,
        bin_sample_count=est.get_or_default("binSampleCount"),
        seed=est.get_or_default("baggingSeed"),
        categorical_features=est._categorical_indexes(),
        bin_dtype=est.get_or_default("binDtype"),
        max_bin_by_feature=est.get_or_default("maxBinByFeature"))
    binner = ds.binner
    cfg = base_cfg._replace(num_bins=ds.max_bin)
    is_cat_np = binner.is_cat_mask()
    is_cat_j = jnp.asarray(is_cat_np) if is_cat_np.any() else None

    # replicated copies of the (possibly sharded) binned dataset
    Xbt = np.asarray(ds.Xbt_d)
    yl = np.asarray(ds.y_d)
    wl = np.asarray(ds.w_d)
    vmask = np.asarray(ds.vmask_d)
    F, n_pad = Xbt.shape

    if est.get_or_default("boostFromAverage"):
        base = float(obj.init_score(jnp.asarray(yl),
                                    jnp.asarray(wl * vmask)))
    else:
        base = 0.0

    mesh = meshlib.get_default_mesh()
    axis = mesh.axis_names[0]
    D = mesh.shape[axis]
    # placement decision: the sweep replicates the DATASET and shards the
    # TRIAL axis — the inverse of the training-path row sharding
    from ..parallel import placement
    placement.plan_for("automl.sweep", mesh=mesh, replicate=True,
                       what="trial_axis_sharded")
    T = len(param_maps)
    T_pad = -(-T // D) * D

    # stacked per-trial values; unswept trials keep the estimator's value
    fields = sorted({k for m in param_maps for k in m})
    defaults = {k: float(est.get_or_default(k)) for k in fields}
    hp = {k: np.asarray(
        [float(param_maps[min(t, T - 1)].get(k, defaults[k]))
         for t in range(T_pad)], np.float32) for k in fields}

    grow = (grow_tree_depthwise if cfg.growth_policy == "depthwise"
            else grow_tree)

    def local(Xbt_l, yl_l, wl_l, vm_l, *hp_vals):
        def one(*hp1):
            cfg_t = cfg._replace(
                **{SWEEPABLE[k]: hp1[i] for i, k in enumerate(fields)})
            fmask = jnp.ones(F, dtype=bool)
            scores0 = jnp.full((n_pad,), jnp.float32(base))

            def it_body(sc, _it):
                g, h = obj.grad_hess(sc, yl_l, wl_l)
                tree, row_node = grow(Xbt_l, g, h, vm_l, fmask, cfg_t,
                                      axis_name=None, is_cat=is_cat_j,
                                      qkey=None)
                return sc + tree.leaf_value[row_node], tree

            _, trees = lax.scan(
                it_body, scores0,
                jnp.arange(num_iterations, dtype=jnp.int32))
            return trees                      # pytree: [iters, ...]

        return jax.vmap(one)(*hp_vals)        # pytree: [T_pad/D, iters, ...]

    fit_all = jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(), P(), P()) + (P(axis),) * len(fields),
        out_specs=P(axis), check_vma=False))
    trees_dev = fit_all(Xbt, yl, wl, vmask, *(hp[k] for k in fields))
    trees_np = jax.tree_util.tree_map(np.asarray, trees_dev)

    depth_cap = cfg.max_depth if cfg.max_depth > 0 else max(
        1, cfg.num_leaves - 1)
    depth_cap = min(depth_cap, 2 * cfg.num_leaves)
    base_arr = np.asarray([base], np.float32)

    models = []
    for t in range(T):
        trees_list = [
            jax.tree_util.tree_map(lambda a, _t=t, _i=i: a[_t, _i],
                                   trees_np)
            for i in range(num_iterations)]
        booster = _finalize_trees(
            trees_list, binner, ds.max_bin, 1, base_arr, objective,
            depth_cap, obj_kwargs, -1, {}, None)
        trial = est.copy({k: v for k, v in param_maps[t].items()
                          if est.has_param(k)})
        model = model_factory(trial._apply_slot_names(booster))
        trial._copy_params_to(model)
        models.append(model)
    return models
