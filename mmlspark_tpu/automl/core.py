"""AutoML: hyperparameter spaces, tuning with k-fold CV, model selection.

TPU-native equivalents of the reference's automl package (reference:
automl/TuneHyperparameters.scala:37-235 — random/grid search with thread-pool
parallel x-fold CV; HyperparamBuilder.scala:11-97; ParamSpace.scala:11-34;
FindBestModel.scala:21-199; EvaluationUtils.scala:15). The reference
parallelizes trials across a Spark cluster's thread pool; here trials run
sequentially on the host while each trial's math saturates the device mesh —
the TPU analog of "task-level model parallelism" (SURVEY §2b).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.dataset import Dataset
from ..core.params import Param, TypeConverters
from ..core.pipeline import Estimator, Model, Transformer
from ..train.core import ComputeModelStatistics

# metrics where larger is better (reference: EvaluationUtils.scala metric infos)
_MAXIMIZE = {"AUC", "accuracy", "precision", "recall", "R^2", "r2"}
_METRIC_COL = {
    "AUC": "AUC", "accuracy": "accuracy", "precision": "precision",
    "recall": "recall", "mse": "mean_squared_error",
    "rmse": "root_mean_squared_error", "mae": "mean_absolute_error",
    "r2": "R^2", "R^2": "R^2",
}


# -- hyperparameter distributions (reference: HyperparamBuilder.scala:11-97) ----


class DiscreteHyperParam:
    """A finite set of values (uniform draw)."""

    def __init__(self, values: Sequence[Any], seed: int = 0):
        self.values = list(values)

    def draw(self, rng) -> Any:
        return self.values[int(rng.integers(len(self.values)))]

    def grid(self) -> List[Any]:
        return list(self.values)


class RangeHyperParam:
    """Uniform range [lo, hi); integer if both ends are ints."""

    def __init__(self, lo, hi, seed: int = 0):
        self.lo, self.hi = lo, hi
        self.is_int = isinstance(lo, int) and isinstance(hi, int)

    def draw(self, rng):
        if self.is_int:
            return int(rng.integers(self.lo, self.hi))
        return float(rng.uniform(self.lo, self.hi))

    def grid(self, n: int = 3) -> List[Any]:
        xs = np.linspace(self.lo, self.hi, n)
        return [int(x) for x in xs] if self.is_int else [float(x) for x in xs]


class HyperparamBuilder:
    """Collects (paramName -> dist) pairs (reference: HyperparamBuilder)."""

    def __init__(self):
        self._space: Dict[str, Any] = {}

    def add_hyperparam(self, name: str, dist) -> "HyperparamBuilder":
        self._space[name] = dist
        return self

    addHyperparam = add_hyperparam

    def build(self) -> Dict[str, Any]:
        return dict(self._space)


class RandomSpace:
    """Random draws from a param space (reference: ParamSpace.scala:11-34)."""

    def __init__(self, space: Dict[str, Any], seed: int = 0):
        self.space = space
        self.rng = np.random.default_rng(seed)

    def param_maps(self, n: int):
        for _ in range(n):
            yield {k: d.draw(self.rng) for k, d in self.space.items()}


class GridSpace:
    """Cartesian product of per-param grids."""

    def __init__(self, space: Dict[str, Any], num_range_points: int = 3):
        self.space = space
        self.n = num_range_points

    def param_maps(self, n: Optional[int] = None):
        names = list(self.space)
        grids = [self.space[k].grid(self.n) if isinstance(self.space[k], RangeHyperParam)
                 else self.space[k].grid() for k in names]
        combos = itertools.product(*grids)
        for i, combo in enumerate(combos):
            if n is not None and i >= n:
                return
            yield dict(zip(names, combo))


# -- evaluation helper (reference: EvaluationUtils.scala:15) --------------------


def evaluate_metric(scored: Dataset, metric: str, labelCol: str = "label") -> float:
    """One scalar metric from a scored Dataset."""
    kind = ("classification" if metric in ("AUC", "accuracy", "precision", "recall")
            else "regression")
    stats = ComputeModelStatistics(
        evaluationMetric=kind, labelCol=labelCol).transform(scored)
    col = _METRIC_COL.get(metric, metric)
    if col not in stats:
        raise ValueError(f"metric {metric!r} not produced; have {stats.columns}")
    return float(stats[col][0])


# -- tuning (reference: automl/TuneHyperparameters.scala:37-235) ----------------


class TuneHyperparameters(Estimator):
    """Random/grid search over estimators with k-fold CV.

    reference: TuneHyperparameters.scala:80-160 (thread-pool parallel CV);
    trials here run sequentially, each saturating the device mesh.
    """

    models = Param("models", "estimators to tune", None, is_complex=True)
    evaluationMetric = Param("evaluationMetric", "metric name (AUC, accuracy, "
                             "rmse, ...)", "accuracy", TypeConverters.to_string)
    numFolds = Param("numFolds", "cross-validation folds", 3, TypeConverters.to_int)
    numRuns = Param("numRuns", "total param draws (random search)", 10,
                    TypeConverters.to_int)
    parallelism = Param("parallelism", "trial parallelism (reference: a "
                        "thread pool of concurrent CV fits). >1 runs "
                        "vmappable sweeps as ONE device dispatch per fold — "
                        "trial axis sharded over the mesh, continuous "
                        "hyperparams traced (automl/sweep.py); estimators or "
                        "param spaces outside that envelope fall back to "
                        "sequential fits", 1, TypeConverters.to_int)
    paramSpace = Param("paramSpace", "RandomSpace/GridSpace or dict of dists",
                       None, is_complex=True)
    seed = Param("seed", "random seed", 0, TypeConverters.to_int)
    labelCol = Param("labelCol", "label column", "label", TypeConverters.to_string)

    def __init__(self, models=None, **kwargs):
        super().__init__(**kwargs)
        if models is not None:
            self.set(models=models)

    def _cv_metric(self, est: Estimator, params: Dict[str, Any],
                   folds: List[Dataset], metric: str, label: str) -> float:
        vals = []
        for i in range(len(folds)):
            train = None
            for j, f in enumerate(folds):
                if j != i:
                    train = f if train is None else train.union(f)
            trial = est.copy({k: v for k, v in params.items()
                              if est.has_param(k)})
            scored = trial.fit(train).transform(folds[i])
            vals.append(evaluate_metric(scored, metric, label))
        return float(np.mean(vals))

    def _swept_cv_metrics(self, est: Estimator,
                          param_maps: List[Dict[str, Any]],
                          folds: List[Dataset], metric: str,
                          label: str) -> "Optional[List[float]]":
        """All trials' CV metrics via the trial-parallel device sweep, or
        None when the estimator/space is outside the vmappable envelope
        (the caller falls back to per-trial sequential fits)."""
        from .sweep import swept_fit

        per_trial = np.zeros((len(param_maps), len(folds)))
        for i in range(len(folds)):
            train = None
            for j, f in enumerate(folds):
                if j != i:
                    train = f if train is None else train.union(f)
            models = swept_fit(est, param_maps, train)
            if models is None:
                return None
            for t, model in enumerate(models):
                per_trial[t, i] = evaluate_metric(
                    model.transform(folds[i]), metric, label)
        return [float(m) for m in per_trial.mean(axis=1)]

    def fit(self, dataset: Dataset) -> "TuneHyperparametersModel":
        metric = self.get_or_default("evaluationMetric")
        label = self.get_or_default("labelCol")
        k = self.get_or_default("numFolds")
        folds = dataset.split([1.0 / k] * k, seed=self.get_or_default("seed"))
        space = self.get_if_set("paramSpace")
        if isinstance(space, dict):
            space = RandomSpace(space, self.get_or_default("seed"))
        models = self.get_or_default("models")
        if not isinstance(models, (list, tuple)):
            models = [models]

        maximize = metric in _MAXIMIZE
        best = (-np.inf if maximize else np.inf, None, None)
        history = []
        param_maps = (list(space.param_maps(self.get_or_default("numRuns")))
                      if space is not None else [{}])
        parallelism = self.get_or_default("parallelism")
        for est in models:
            swept = None
            if parallelism and parallelism > 1 and len(param_maps) > 1:
                swept = self._swept_cv_metrics(est, param_maps, folds,
                                               metric, label)
            trial_metrics = (swept if swept is not None else
                             [self._cv_metric(est, p, folds, metric, label)
                              for p in param_maps])
            for params, m in zip(param_maps, trial_metrics):
                history.append((type(est).__name__, dict(params), m))
                if (m > best[0]) if maximize else (m < best[0]):
                    best = (m, est, params)
        _, best_est, best_params = best
        fitted = best_est.copy({k: v for k, v in (best_params or {}).items()
                                if best_est.has_param(k)}).fit(dataset)
        return TuneHyperparametersModel(
            bestModel=fitted, bestMetric=best[0],
            bestParams=best_params, history=history)


class TuneHyperparametersModel(Model):
    bestModel = Param("bestModel", "winning fitted model", None, is_complex=True)
    bestMetric = Param("bestMetric", "winning CV metric", None,
                       TypeConverters.to_float)
    bestParams = Param("bestParams", "winning param map", None, is_complex=True)
    history = Param("history", "all (model, params, metric) trials", None,
                    is_complex=True)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def transform(self, dataset: Dataset) -> Dataset:
        return self.get_or_default("bestModel").transform(dataset)

    def get_best_model_info(self) -> str:
        return (f"metric={self.get_or_default('bestMetric')} "
                f"params={self.get_or_default('bestParams')}")


class FindBestModel(Estimator):
    """Evaluate already-specified models on the fit dataset and keep the best
    (reference: automl/FindBestModel.scala:21-199)."""

    models = Param("models", "fitted Transformers or Estimators to compare",
                   None, is_complex=True)
    evaluationMetric = Param("evaluationMetric", "metric name", "accuracy",
                             TypeConverters.to_string)
    labelCol = Param("labelCol", "label column", "label", TypeConverters.to_string)

    def __init__(self, models=None, **kwargs):
        super().__init__(**kwargs)
        if models is not None:
            self.set(models=models)

    def fit(self, dataset: Dataset) -> "BestModel":
        metric = self.get_or_default("evaluationMetric")
        label = self.get_or_default("labelCol")
        maximize = metric in _MAXIMIZE
        rows = []
        best = (-np.inf if maximize else np.inf, None)
        for m in self.get_or_default("models"):
            fitted = m.fit(dataset) if isinstance(m, Estimator) else m
            scored = fitted.transform(dataset)
            val = evaluate_metric(scored, metric, label)
            rows.append({"model": type(fitted).__name__, metric: val})
            if (val > best[0]) if maximize else (val < best[0]):
                best = (val, fitted)
        out = BestModel(bestModel=best[1], bestMetric=best[0],
                        allModelMetrics=Dataset.from_rows(rows))
        self._copy_params_to(out)
        return out


class BestModel(Model):
    bestModel = Param("bestModel", "winning model", None, is_complex=True)
    bestMetric = Param("bestMetric", "winning metric value", None,
                       TypeConverters.to_float)
    allModelMetrics = Param("allModelMetrics", "per-model metric table", None,
                            is_complex=True)

    def transform(self, dataset: Dataset) -> Dataset:
        return self.get_or_default("bestModel").transform(dataset)

    def get_evaluation_results(self) -> Dataset:
        return self.get_or_default("allModelMetrics")
