"""Native host-runtime loader: compile-on-first-use C++ with ctypes bindings.

TPU-native replacement for the reference's NativeLoader (reference:
core/env/NativeLoader.java:28-140 — extract .so from jar resources, then
``System.load``). Here the native source ships with the package; the loader
compiles it once with the system toolchain into a content-addressed cache and
binds the C ABI via ctypes. Everything has a pure-Python fallback, so the
framework degrades gracefully on hosts without a compiler.

API:
- ``get_lib() -> ctypes.CDLL | None`` — the compiled library (cached), or
  None when unavailable.
- ``murmur3_batch(strings, seeds) -> np.uint32[n]`` — batch feature hashing.
- ``bin_batch(X, upper_bounds) -> np.int32[n, F]`` — quantile-bin apply.
- ``csv_read_floats(text, ncols) -> np.float32[rows, ncols]`` — data loader.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import List, Optional, Sequence

import numpy as np

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
# repo layout keeps the C++ at <root>/native/; installed wheels ship a copy
# as package data next to this file (setup.py build_py_with_native)
_SOURCE_CANDIDATES = (
    os.path.join(os.path.dirname(os.path.dirname(_PKG_DIR)), "native",
                 "mmlspark_native.cpp"),
    os.path.join(_PKG_DIR, "mmlspark_native.cpp"),
)
_SOURCE = next((p for p in _SOURCE_CANDIDATES if os.path.exists(p)),
               _SOURCE_CANDIDATES[0])
# wheels built on a host with a toolchain ship the compiled library too
_PREBUILT = os.path.join(_PKG_DIR, "mmlspark_native_prebuilt.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _cache_dir() -> str:
    # Per-user, mode-0700 cache: a world-writable shared dir would let
    # another local user pre-plant a .so that we'd load into this process.
    d = os.environ.get("MMLSPARK_TPU_NATIVE_CACHE")
    if not d:
        uid = os.getuid() if hasattr(os, "getuid") else "u"
        d = os.path.join(tempfile.gettempdir(), f"mmlspark_tpu_native_{uid}")
    os.makedirs(d, mode=0o700, exist_ok=True)
    st = os.stat(d)
    if hasattr(os, "getuid") and st.st_uid != os.getuid():
        raise PermissionError(f"native cache dir {d} owned by uid {st.st_uid}")
    return d


def _compile() -> Optional[str]:
    if not os.path.exists(_SOURCE):
        return None
    with open(_SOURCE, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    so_path = os.path.join(_cache_dir(), f"mmlspark_native_{digest}.so")
    if os.path.exists(so_path):
        return so_path
    # unique temp name per process: concurrent cold-cache compiles must not
    # race on one .tmp file (os.replace publishes atomically)
    tmp_path = f"{so_path}.{os.getpid()}.tmp"
    for cxx in (os.environ.get("CXX"), "g++", "c++", "clang++"):
        if not cxx:
            continue
        cmd = [cxx, "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
               _SOURCE, "-o", tmp_path]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp_path, so_path)
            return so_path
        except (OSError, subprocess.SubprocessError):
            continue
    return None


def get_lib() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the native library; None if unavailable."""
    global _lib, _lib_tried
    if _lib_tried:        # lock-free fast path for per-hash callers
        return _lib
    with _lock:
        if _lib_tried:
            return _lib
        try:
            lib = _load()
            if lib is not None:
                _bind(lib)
                _lib = lib
        except Exception:
            # corrupt cached .so, missing symbols, etc.: latch to the
            # Python fallback rather than crashing the first caller
            _lib = None
        finally:
            # published last (the lock-free fast path must never observe
            # _lib_tried=True mid-compile), but always published — a failed
            # attempt latches instead of re-running the compile per call
            _lib_tried = True
        return _lib


# every symbol _bind wires up: a prebuilt .so from an older source tree
# (missing a newer symbol) must fall through to a recompile, not latch the
# whole module to the Python fallback
_EXPECTED_SYMBOLS = ("mm_abi_version", "mm_murmur3_32", "mm_murmur3_batch",
                     "mm_bin_batch", "mm_csv_read_floats", "mm_treeshap")
# behavioral version (mm_abi_version in mmlspark_native.cpp): symbol
# presence alone can't catch a prebuilt whose symbols all exist but whose
# SEMANTICS are stale (e.g. the pre-cycle-guard mm_treeshap); bump both
# on any native behavior change (v4: mm_treeshap rejects out-of-range
# split features, cycles, and trees past the 256 MiB arena budget —
# effective depth cutoff ~3094, with a 4096 structural backstop)
_ABI_VERSION = 4


def _prebuilt_current(lib: ctypes.CDLL) -> bool:
    if not all(hasattr(lib, s) for s in _EXPECTED_SYMBOLS):
        return False
    lib.mm_abi_version.restype = ctypes.c_int64
    lib.mm_abi_version.argtypes = []
    return int(lib.mm_abi_version()) == _ABI_VERSION


def _load() -> Optional[ctypes.CDLL]:
    if os.environ.get("MMLSPARK_TPU_DISABLE_NATIVE"):
        return None
    if os.path.exists(_PREBUILT):
        try:
            lib = ctypes.CDLL(_PREBUILT)
            if _prebuilt_current(lib):
                return lib
            # stale prebuilt (old symbols or old behavior): recompile
        except OSError:
            pass  # wrong arch/ABI for this host: recompile from source
    so = _compile()
    if so is None:
        return None
    try:
        return ctypes.CDLL(so)
    except OSError:
        return None


def _bind(lib: ctypes.CDLL) -> None:
    lib.mm_murmur3_32.restype = ctypes.c_uint32
    lib.mm_murmur3_32.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.c_uint32]
    lib.mm_murmur3_batch.restype = None
    lib.mm_murmur3_batch.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_uint32), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint32)]
    lib.mm_bin_batch.restype = None
    lib.mm_bin_batch.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32)]
    lib.mm_csv_read_floats.restype = ctypes.c_int64
    lib.mm_csv_read_floats.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
    lib.mm_treeshap.restype = ctypes.c_int64
    lib.mm_treeshap.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_double)]


def native_available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------------------
# High-level wrappers (with pure-Python fallbacks)
# ---------------------------------------------------------------------------


def murmur3_batch(strings: Sequence[str],
                  seeds: Sequence[int]) -> np.ndarray:
    """Hash n utf-8 strings with per-string seeds -> uint32[n]."""
    lib = get_lib()
    if lib is None:
        from ..ops.murmur import murmur3_32
        return np.asarray([murmur3_32(s, int(seed)) for s, seed
                           in zip(strings, seeds)], dtype=np.uint32)
    encoded: List[bytes] = [s.encode("utf-8") for s in strings]
    offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in encoded], out=offsets[1:])
    buf = b"".join(encoded)
    seeds_arr = np.asarray(seeds, dtype=np.uint32)
    out = np.empty(len(encoded), dtype=np.uint32)
    lib.mm_murmur3_batch(
        buf, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        seeds_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        len(encoded), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
    return out


def bin_batch(X: np.ndarray, upper_bounds: np.ndarray) -> np.ndarray:
    """Apply per-feature quantile bins: [n, F] floats -> [n, F] int32 bins."""
    X = np.ascontiguousarray(X, dtype=np.float32)
    ub = np.ascontiguousarray(upper_bounds, dtype=np.float32)
    n, F = X.shape
    lib = get_lib()
    if lib is None:
        out = np.empty((n, F), dtype=np.int32)
        for f in range(F):
            out[:, f] = np.searchsorted(ub[f], X[:, f], side="left")
        out[np.isnan(X)] = 0
        return out
    out = np.empty((n, F), dtype=np.int32)
    lib.mm_bin_batch(
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n, F,
        ub.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), ub.shape[1],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out


def csv_read_floats(text: str, ncols: int,
                    max_rows: Optional[int] = None) -> np.ndarray:
    """Parse numeric CSV text -> float32[rows, ncols]; raises on ragged rows."""
    data = text.encode("utf-8") if isinstance(text, str) else text
    lib = get_lib()
    if max_rows is None:
        max_rows = data.count(b"\n") + 1
    if lib is None:
        def parse(p: str) -> float:
            p = p.strip()
            if not p:
                return np.nan
            try:
                return float(p)
            except ValueError:
                return np.nan      # same as the native parser: bad field=NaN

        rows = []
        for line in data.decode("utf-8").splitlines():
            if not line.strip():
                continue
            parts = line.split(",")
            if len(parts) != ncols:
                raise ValueError(f"expected {ncols} columns, got {len(parts)}")
            rows.append([parse(p) for p in parts])
            if len(rows) >= max_rows:
                break
        if not rows:
            # keep the native path's [0, ncols] shape so callers can
            # concatenate empty and non-empty parses
            return np.zeros((0, ncols), dtype=np.float32)
        return np.asarray(rows, dtype=np.float32)
    out = np.empty((max_rows, ncols), dtype=np.float32)
    n = lib.mm_csv_read_floats(
        data, len(data), ncols,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), max_rows)
    if n < 0:
        raise ValueError(f"CSV shape mismatch: expected {ncols} columns")
    return out[:n]


def treeshap_tree(feat: np.ndarray, left: np.ndarray, right: np.ndarray,
                  is_leaf: np.ndarray, cover: np.ndarray,
                  values: np.ndarray, go_left: np.ndarray,
                  n_features: int,
                  n_threads: int = 0) -> Optional[np.ndarray]:
    """Exact TreeSHAP for one tree, all instances: -> float64[n, F].

    ``go_left`` is the [M, n] per-node routing matrix the caller
    precomputes (thresholds / categorical bitsets / NaN policy stay in
    models/gbdt/treeshap.py, the single source of split semantics).
    Returns None when the native library is unavailable — the caller
    falls back to the vectorized numpy recursion; there is deliberately
    no Python fallback here because that numpy engine IS the fallback.
    ``n_threads=0`` uses the hardware concurrency.
    """
    lib = get_lib()
    if lib is None:
        return None
    feat = np.ascontiguousarray(feat, dtype=np.int32)
    left = np.ascontiguousarray(left, dtype=np.int32)
    right = np.ascontiguousarray(right, dtype=np.int32)
    is_leaf = np.ascontiguousarray(is_leaf, dtype=np.uint8)
    cover = np.ascontiguousarray(cover, dtype=np.float64)
    values = np.ascontiguousarray(values, dtype=np.float64)
    go_left = np.ascontiguousarray(go_left, dtype=np.uint8)
    M, n = go_left.shape
    phi = np.zeros((n, int(n_features)), dtype=np.float64)
    rc = lib.mm_treeshap(
        feat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        left.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        right.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        is_leaf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        cover.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        go_left.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        M, n, int(n_features), int(n_threads),
        phi.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    if rc != 0:
        # malformed/degenerate tree (bad child or feature index, cycle,
        # depth past the native arena budget): route to the Python engine
        # — shap_values pre-validates split features, bad child indices
        # raise a meaningful IndexError there, and legitimately deep
        # chains run on its heap-based stack instead of C recursion
        return None
    return phi
