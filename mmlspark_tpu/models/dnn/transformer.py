"""Flagship deep model: SPMD transformer with dp x tp x sp mesh parallelism.

This is the framework's DNN compute path — the TPU-native successor of the
reference's CNTK evaluation engine (reference: cntk/CNTKModel.scala:30-532
evaluates a serialized DNN per partition over JNI; no multi-device execution
of a single model existed — SURVEY.md §2b). Here a single model spans the
whole mesh:

  * ``data``  — batch sharding (DP)
  * ``model`` — Megatron-style tensor parallelism (TP): QKV/MLP column-split,
    output projections row-split with one psum per block
  * ``seq``   — sequence/context parallelism (SP): activations sharded over
    sequence; exact attention via ring ppermute (parallel/ring_attention.py)

Everything runs inside one ``shard_map``: collectives are explicit
(psum/pmax/ppermute) and ride ICI. Params live sharded (TP dims) or
replicated; gradients of replicated params are psum'd over (data, seq).
bf16 activations, f32 params/optimizer — the standard TPU recipe.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from ...parallel import placement
from ...parallel.placement import pspec as P
from ...parallel.ring_attention import (ring_attention,
                                        zigzag_ring_attention)
from ...parallel.compat import axis_size as compat_axis_size, shard_map
from ...parallel.ulysses import ulysses_attention


class TransformerConfig(NamedTuple):
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    d_head: int = 64
    n_layers: int = 4
    d_ff: int = 2048
    max_len: int = 2048
    dtype: Any = jnp.bfloat16
    # sequence-parallel attention strategy over the 'seq' mesh axis:
    # "ring" (neighbor ppermute, O(S_local) memory, no head constraint),
    # "ring_zigzag" (ring with the causally load-balanced zig-zag layout —
    # ~2x causal speedup; feed tokens/targets through zigzag_permute), or
    # "ulysses" (two all-to-alls reshard heads<->sequence, plain local
    # attention; needs per-TP-rank heads divisible by the seq shard count)
    seq_attention: str = "ring"
    # Gradient rematerialization: recompute each block's activations in the
    # backward pass instead of storing them — activation memory drops from
    # O(n_layers * S_local * E) to O(S_local * E) at ~1/3 extra FLOPs, the
    # standard trade that lets long-context configs fit HBM. Exact to
    # numerical tolerance (XLA may fuse differently under checkpoint);
    # trajectory agreement is test-pinned.
    remat: bool = False


def init_params(cfg: TransformerConfig, key) -> Dict:
    """f32 parameters; layers stacked on a leading axis (scanned-friendly)."""
    k_embed, k_pos, k_layers, k_head = jax.random.split(key, 4)
    E, H, Dh, F, L = (cfg.d_model, cfg.n_heads, cfg.d_head, cfg.d_ff,
                      cfg.n_layers)

    def norm(k, shape, scale):
        return jax.random.normal(k, shape, jnp.float32) * scale

    ks = jax.random.split(k_layers, 6 * L).reshape(L, 6, 2)
    layers = {
        "ln1_scale": jnp.ones((L, E)), "ln1_bias": jnp.zeros((L, E)),
        # [E, H, 3*Dh]: per-head q|k|v contiguous, so the head-axis TP shard
        # is layout-invariant across tensor-parallel sizes (checkpoint portable)
        "wqkv": jnp.stack([norm(ks[i, 0], (E, H, 3 * Dh), E ** -0.5)
                           for i in range(L)]),
        "wo": jnp.stack([norm(ks[i, 1], (H * Dh, E), (H * Dh) ** -0.5)
                         for i in range(L)]),
        "ln2_scale": jnp.ones((L, E)), "ln2_bias": jnp.zeros((L, E)),
        "w1": jnp.stack([norm(ks[i, 2], (E, F), E ** -0.5) for i in range(L)]),
        "b1": jnp.zeros((L, F)),
        "w2": jnp.stack([norm(ks[i, 3], (F, E), F ** -0.5) for i in range(L)]),
        "b2": jnp.zeros((L, E)),
    }
    return {
        "embed": norm(k_embed, (cfg.vocab_size, E), 1.0),
        "pos": norm(k_pos, (cfg.max_len, E), 0.02),
        "layers": layers,
        "lnf_scale": jnp.ones((E,)), "lnf_bias": jnp.zeros((E,)),
        "head": norm(k_head, (E, cfg.vocab_size), E ** -0.5),
    }


def param_specs(cfg: TransformerConfig) -> Dict:
    """PartitionSpecs mirroring init_params: TP dims sharded over 'model'."""
    return {
        "embed": P(None, "model"),
        "pos": P(None, "model"),
        "layers": {
            "ln1_scale": P(None, None), "ln1_bias": P(None, None),
            "wqkv": P(None, None, "model", None),
            "wo": P(None, "model", None),
            "ln2_scale": P(None, None), "ln2_bias": P(None, None),
            "w1": P(None, None, "model"), "b1": P(None, "model"),
            "w2": P(None, "model", None), "b2": P(None, None),
        },
        "lnf_scale": P(None), "lnf_bias": P(None),
        "head": P(None, "model"),
    }


def _layer_norm(x, scale, bias):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + 1e-5) * scale + bias).astype(x.dtype)


def forward_local(params, tokens, cfg: TransformerConfig,
                  causal: bool = True):
    """Local-shard forward inside shard_map; returns vocab-sharded logits.

    tokens: [B_local, S_local] int32. Axes: data/seq/model as module docstring.
    """
    H, Dh, E = cfg.n_heads, cfg.d_head, cfg.d_model
    tp = compat_axis_size("model")
    sp_idx = lax.axis_index("seq")
    Hl = H // tp
    B, S = tokens.shape
    dt = cfg.dtype

    # embedding: table is E-sharded; gather rows then all-gather E
    emb_local = jnp.take(params["embed"], tokens, axis=0)  # [B, S, E/tp]
    if cfg.seq_attention == "ring_zigzag":
        # zig-zag layout: this shard holds chunk me and chunk 2n-1-me of
        # the global sequence (tokens/targets must be pre-permuted with
        # parallel.ring_attention.zigzag_permute) — slice the positional
        # table accordingly
        n_sp = compat_axis_size("seq")
        C = S // 2
        p1 = lax.dynamic_slice_in_dim(params["pos"], sp_idx * C, C, axis=0)
        p2 = lax.dynamic_slice_in_dim(
            params["pos"], (2 * n_sp - 1 - sp_idx) * C, C, axis=0)
        pos_local = jnp.concatenate([p1, p2], axis=0)
    else:
        pos_local = lax.dynamic_slice_in_dim(params["pos"], sp_idx * S, S,
                                             axis=0)
    x_local = emb_local + pos_local[None]
    x = lax.all_gather(x_local, "model", axis=2, tiled=True).astype(dt)  # [B,S,E]

    def block(x, lp):
        h = _layer_norm(x, lp["ln1_scale"], lp["ln1_bias"])
        qkv = jnp.einsum("bse,ehk->bshk", h, lp["wqkv"].astype(dt),
                         preferred_element_type=jnp.float32)  # [B,S,Hl,3*Dh]
        qkv = qkv.reshape(B, S, Hl, 3, Dh).astype(dt)
        q = qkv[:, :, :, 0].transpose(0, 2, 1, 3)  # [B, Hl, S, Dh]
        k = qkv[:, :, :, 1].transpose(0, 2, 1, 3)
        v = qkv[:, :, :, 2].transpose(0, 2, 1, 3)
        if cfg.seq_attention == "ulysses":
            att = ulysses_attention(q, k, v, axis_name="seq", causal=causal)
        elif cfg.seq_attention == "ring":
            att = ring_attention(q, k, v, axis_name="seq", causal=causal)
        elif cfg.seq_attention == "ring_zigzag":
            att = zigzag_ring_attention(q, k, v, axis_name="seq",
                                        causal=causal)
        else:
            # all strategies are exact, so a typo would silently measure
            # the wrong one — fail loudly instead
            raise ValueError(
                f"unknown seq_attention {cfg.seq_attention!r}: "
                "use 'ring', 'ring_zigzag' or 'ulysses'")
        att = att.transpose(0, 2, 1, 3).reshape(B, S, Hl * Dh)
        out = jnp.einsum("bsk,ke->bse", att, lp["wo"].astype(dt),
                         preferred_element_type=jnp.float32)
        out = lax.psum(out, "model").astype(dt)
        x = x + out
        h = _layer_norm(x, lp["ln2_scale"], lp["ln2_bias"])
        m = jnp.einsum("bse,ef->bsf", h, lp["w1"].astype(dt),
                       preferred_element_type=jnp.float32) + lp["b1"]
        m = jax.nn.gelu(m.astype(jnp.float32)).astype(dt)
        m = jnp.einsum("bsf,fe->bse", m, lp["w2"].astype(dt),
                       preferred_element_type=jnp.float32)
        m = lax.psum(m, "model").astype(dt) + lp["b2"].astype(dt)
        return x + m, None

    # prevent_cse=False: safe and recommended when the checkpointed fn is a
    # lax.scan body (per jax.checkpoint docs) — keeps XLA's CSE instead of
    # paying optimization-barrier overhead on every step
    x, _ = lax.scan(jax.checkpoint(block, prevent_cse=False)
                    if cfg.remat else block,
                    x, params["layers"])
    x = _layer_norm(x, params["lnf_scale"], params["lnf_bias"])
    logits_local = jnp.einsum("bse,ev->bsv", x, params["head"].astype(dt),
                              preferred_element_type=jnp.float32)
    return logits_local  # [B, S, V/tp] f32


def sharded_xent(logits_local, targets, cfg: TransformerConfig):
    """Cross-entropy over vocab-sharded logits (stable log-sum-exp with
    pmax/psum over 'model'); mean over all tokens via pmean over data x seq."""
    tp = compat_axis_size("model")
    v_local = cfg.vocab_size // tp
    v0 = lax.axis_index("model") * v_local
    # stability shift only — constant w.r.t. differentiation (pmax has no JVP,
    # so stop the gradient BEFORE it enters the collective)
    lmax = lax.pmax(lax.stop_gradient(logits_local.max(-1)), "model")
    z = jnp.exp(logits_local - lmax[..., None])
    log_z = jnp.log(lax.psum(z.sum(-1), "model")) + lmax
    t_local = targets - v0
    in_range = (t_local >= 0) & (t_local < v_local)
    picked = jnp.take_along_axis(
        logits_local, jnp.clip(t_local, 0, v_local - 1)[..., None], axis=-1)[..., 0]
    picked = lax.psum(jnp.where(in_range, picked, 0.0), "model")
    nll = log_z - picked
    return lax.pmean(lax.pmean(nll.mean(), "data"), "seq")


# ---------------------------------------------------------------------------
# hand-rolled AdamW (full sharding control over optimizer state)
# ---------------------------------------------------------------------------


def adamw_init(params):
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    return {"mu": zeros, "nu": jax.tree_util.tree_map(jnp.zeros_like, params),
            "count": jnp.zeros((), jnp.int32)}


def adamw_update(grads, state, params, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8,
                 weight_decay=0.01):
    c = state["count"] + 1
    mu = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g,
                                state["mu"], grads)
    nu = jax.tree_util.tree_map(lambda n, g: b2 * n + (1 - b2) * g * g,
                                state["nu"], grads)
    cf = c.astype(jnp.float32)
    bc1 = 1 - b1 ** cf
    bc2 = 1 - b2 ** cf

    def upd(p, m, n):
        return p - lr * (m / bc1 / (jnp.sqrt(n / bc2) + eps) + weight_decay * p)

    new_params = jax.tree_util.tree_map(upd, params, mu, nu)
    return new_params, {"mu": mu, "nu": nu, "count": c}


# ---------------------------------------------------------------------------
# jit-able train / forward steps over a mesh
# ---------------------------------------------------------------------------


def make_train_step(cfg: TransformerConfig, mesh: Mesh, lr: float = 1e-3):
    """Returns jitted (params, opt_state, tokens, targets) -> (params, opt_state, loss).

    Replicated-param gradients are psum'd over (data, seq); TP-sharded params
    update locally. One compiled SPMD program, collectives over ICI.
    """
    specs = param_specs(cfg)

    def step_local(params, opt_state, tokens, targets):
        def loss_fn(p):
            logits = forward_local(p, tokens, cfg)
            return sharded_xent(logits, targets, cfg)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # TP-sharded params get complete local grads (loss aggregates over
        # 'model' via psum in the forward); params REPLICATED across 'model'
        # (layernorms, b2) only get partial contributions per shard — sum them
        # or the replicas silently diverge.
        grads = jax.tree_util.tree_map(
            lambda g, s: g if "model" in tuple(s) else lax.psum(g, "model"),
            grads, specs)
        # all params are replicated across data & seq: average contributions
        grads = jax.tree_util.tree_map(
            lambda g: lax.pmean(lax.pmean(g, "data"), "seq"), grads)
        new_params, new_opt = adamw_update(grads, opt_state, params, lr=lr)
        return new_params, new_opt, loss

    opt_specs = {"mu": specs, "nu": specs, "count": P()}
    data_spec = P("data", "seq")
    fn = shard_map(
        step_local, mesh=mesh,
        in_specs=(specs, opt_specs, data_spec, data_spec),
        out_specs=(specs, opt_specs, P()),
        check_vma=False)
    return jax.jit(fn, donate_argnums=(0, 1))


def make_forward(cfg: TransformerConfig, mesh: Mesh, causal: bool = True):
    """Jitted forward: (params, tokens [B, S]) -> full logits [B, S, V]."""
    specs = param_specs(cfg)

    def fwd_local(params, tokens):
        logits_local = forward_local(params, tokens, cfg, causal=causal)
        return logits_local

    fn = shard_map(
        fwd_local, mesh=mesh,
        in_specs=(specs, P("data", "seq")),
        out_specs=P("data", "seq", "model"),
        check_vma=False)
    return jax.jit(fn)


def shard_params(params, cfg: TransformerConfig, mesh: Mesh):
    placement.plan_for("transformer.fit", mesh=mesh, what="params_tp")
    return placement.put_tree(params, param_specs(cfg), mesh)


def shard_opt_state(opt_state, cfg: TransformerConfig, mesh: Mesh):
    specs = {"mu": param_specs(cfg), "nu": param_specs(cfg), "count": P()}
    return placement.put_tree(opt_state, specs, mesh)
