"""DNNModel: batched DNN inference as a pipeline stage (CNTKModel parity).

TPU-native re-design of the reference's CNTK scoring stack (reference:
cntk/CNTKModel.scala:30-532 — broadcast eval in mapPartitions, feed/fetch
dicts :204-223, minibatching via FixedMiniBatchTransformer + FlattenBatch
:374,496-528, GPU-or-CPU device pick :94). The broadcast-JNI machinery
becomes: one jitted forward (compiled once, cached), batches padded to a
static shape, rows sharded over the mesh's data axis — one shard per TPU
core, the pjit analog of one partition per executor.

Model surgery (SerializableFunction.clone + output-node pick,
com/microsoft/CNTK/SerializableFunction.scala:67-102) is the ``output_node``
param resolved through the model's ``capture`` mechanism — no graph editing,
just asking apply() for a different activation.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ...core.dataset import Dataset
from ...core.params import (HasBatchSize, HasInputCol, HasOutputCol, Param,
                            TypeConverters)
from ...core.pipeline import Model, Transformer
from ...parallel.mesh import get_default_mesh

class DNNModel(Model, HasInputCol, HasOutputCol, HasBatchSize):
    """Wraps a functional model (params + apply) as a scoring Transformer.

    ``apply_fn(params, x) -> output`` or, with ``output_node`` set,
    ``apply_fn(params, x, capture=[node]) -> (logits, {node: act})``.
    """

    outputNode = Param("outputNode", "intermediate node to fetch (model "
                       "surgery; None = final output)", None,
                       TypeConverters.to_string)
    miniBatchSize = Param("miniBatchSize", "rows per device batch", 64,
                          TypeConverters.to_int)
    feedDict = Param(
        "feedDict", "Map of model input name -> dataset column (reference: "
        "CNTKModel feedDict). Multiple entries feed a multi-input apply_fn "
        "as a dict of batches; a single entry is an inputCol alias", None,
        is_complex=True)
    fetchDict = Param(
        "fetchDict", "Map of output column -> capture node (reference: "
        "CNTKModel fetchDict). One forward pass captures every requested "
        "node and writes each to its column", None, is_complex=True)
    convertOutputToDenseVector = Param(
        "convertOutputToDenseVector", "Accepted for reference parity; "
        "outputs here are always dense ndarrays", True,
        TypeConverters.to_bool)
    batchInput = Param(
        "batchInput", "Accepted for reference parity; scoring always "
        "micro-batches to the static compiled shape", True,
        TypeConverters.to_bool)
    shapeOutput = Param(
        "shapeOutput", "Accepted for reference parity: outputs keep the "
        "model's natural [n, ...] array shape (the reference's flag "
        "reshaped CNTK's flattened outputs)", False,
        TypeConverters.to_bool)

    def __init__(self, params: Any = None, apply_fn: Callable = None,
                 apply_spec: Optional[Dict[str, Any]] = None, **kwargs):
        super().__init__(**kwargs)
        self.params = params
        self.apply_spec = apply_spec
        self.apply_fn = apply_fn or (
            _build_apply(apply_spec) if apply_spec else None)
        self._compiled: Dict[Any, Callable] = {}

    @classmethod
    def from_downloader(cls, repo_dir: str, name: str, **kwargs) -> "DNNModel":
        """Load a repository model (ModelDownloader) as a scoring stage."""
        from .downloader import ModelDownloader

        d = ModelDownloader(repo_dir)
        params, cfg, _ = d.load_model(name)
        if type(cfg).__name__ == "AlexNetConfig":
            spec = {"kind": "alexnet",
                    "config": {"num_classes": cfg.num_classes,
                               "input_hw": tuple(cfg.input_hw),
                               "width_mult": cfg.width_mult}}
        else:
            spec = {"kind": "cnn",
                    "config": {"num_classes": cfg.num_classes,
                               "stage_sizes": tuple(cfg.stage_sizes),
                               "width": cfg.width,
                               "block": cfg.block,
                               "input_hw": tuple(cfg.input_hw)}}
        return cls(params, apply_spec=spec, **kwargs)

    # -- model surgery (CNTKModel.setOutputNode analog) ---------------------
    def set_output_node(self, name: Optional[str]) -> "DNNModel":
        return self.set(outputNode=name)

    def cloned_with_shared_params(self) -> "DNNModel":
        """ParameterCloningMethod.Share parity: same param arrays, fresh
        stage (SerializableFunction.scala:96-102)."""
        c = DNNModel(self.params, self.apply_fn, self.apply_spec)
        c._paramMap = dict(self._paramMap)
        c._compiled = self._compiled  # share the jit cache too
        return c

    # -- compiled forward ---------------------------------------------------
    def _forward(self, node) -> Callable:
        """Compiled forward for one capture spec: ``None`` (final output),
        a node name, or a TUPLE of node names (fetchDict — one pass
        captures all of them and returns the dict)."""
        if node not in self._compiled:
            import jax

            if node is None:
                fn = lambda p, x: self.apply_fn(p, x)  # noqa: E731
            elif isinstance(node, tuple):
                def fn(p, x, _nodes=node):
                    _, acts = self.apply_fn(p, x, capture=list(_nodes))
                    return {k: acts[k] for k in _nodes}
            else:
                def fn(p, x):
                    _, acts = self.apply_fn(p, x, capture=[node])
                    return acts[node]
            mesh = get_default_mesh()
            from ...parallel import placement
            # rows shard over the mesh's LEADING axis (the historical
            # behavior — scoring follows whatever topology the mesh leads
            # with, data-parallel or not); plan_for counts shards on that
            # same axis so the logged decision matches the placement
            lead_axis = list(mesh.shape.keys())[0]
            plan = placement.plan_for("dnn.transform", mesh=mesh,
                                      axis=lead_axis)
            if plan.decision == "shard_rows":
                jfn = jax.jit(fn, in_shardings=(
                    plan.replicated(), plan.batch()))
            else:
                jfn = jax.jit(fn)
            self._compiled[node] = jfn
        return self._compiled[node]

    @staticmethod
    def _column_matrix(dataset: Dataset, col: str) -> np.ndarray:
        data = dataset[col]
        return data if isinstance(data, np.ndarray) else np.stack(
            [np.asarray(v, np.float32) for v in data])

    def transform(self, dataset: Dataset) -> Dataset:
        out_col = self.get_or_default("outputCol") or "output"
        node = self.get_or_default("outputNode")
        bs = int(self.get_or_default("miniBatchSize"))
        feed = self.get_or_default("feedDict")
        fetch = self.get_or_default("fetchDict")
        if fetch:
            if node is not None:
                raise ValueError(
                    "set either outputNode or fetchDict, not both (fetchDict "
                    "routes every capture to its own column)")
            # fetchDict: one pass captures every node; column order fixed
            out_cols = sorted(fetch)
            node = tuple(fetch[c] for c in out_cols)
        if feed:
            # Dataset enforces uniform column lengths at construction, so
            # the feed batches are aligned by invariant
            xs = {name: self._column_matrix(dataset, c)
                  for name, c in feed.items()}
            if len(xs) == 1:
                xs = next(iter(xs.values()))   # plain single-input apply
        else:
            xs = self._column_matrix(dataset,
                                     self.get_or_default("inputCol"))
        fwd = self._forward(node)

        multi_in = isinstance(xs, dict)
        n = (next(iter(xs.values())) if multi_in else xs).shape[0]

        def slice_batch(start):
            def one(a):
                b = a[start:start + bs]
                real = b.shape[0]
                if real < bs:
                    # static shapes: pad the tail batch, drop padding after
                    b = np.concatenate(
                        [b, np.repeat(b[-1:], bs - real, axis=0)], axis=0)
                return _pad_to_mesh(b)[0], real
            if multi_in:
                pairs = {k: one(a) for k, a in xs.items()}
                return ({k: v[0] for k, v in pairs.items()},
                        next(iter(pairs.values()))[1])
            return one(xs)

        outs = []
        from ...utils.profiling import annotate
        with annotate(f"dnn_score:{type(self).__name__}"):
            for start in range(0, n, bs):
                batch, real = slice_batch(start)
                out = fwd(self.params, batch)
                if isinstance(node, tuple):
                    outs.append({k: np.asarray(v)[:real]
                                 for k, v in out.items()})
                else:
                    outs.append(np.asarray(out)[:real])
        if isinstance(node, tuple):
            cols = {c: np.concatenate([o[nd] for o in outs], axis=0)
                    if outs else np.zeros((0,))
                    for c, nd in zip(out_cols, node)}
            return dataset.with_columns(cols)
        result = np.concatenate(outs, axis=0) if outs else np.zeros((0,))
        return dataset.with_column(out_col, result)

    # -- persistence --------------------------------------------------------
    # The model format is params + a reconstructable apply spec (the analog of
    # the reference persisting the serialized CNTK Function, not JVM closures).
    # Module-level apply functions without a spec fall back to pickle.
    def _save_extra(self, path: str) -> None:
        payload: Dict[str, Any] = {"params": _to_np(self.params),
                                   "apply_spec": self.apply_spec}
        if self.apply_spec is None:
            try:
                payload["apply_fn"] = pickle.dumps(self.apply_fn)
            except (pickle.PicklingError, AttributeError, TypeError) as e:
                raise ValueError(
                    "DNNModel.apply_fn is not picklable and no apply_spec was "
                    "given; construct with apply_spec (e.g. via "
                    "DNNModel.from_downloader) to make the stage persistable"
                ) from e
        with open(os.path.join(path, "model.pkl"), "wb") as f:
            pickle.dump(payload, f)

    def _load_extra(self, path: str) -> None:
        with open(os.path.join(path, "model.pkl"), "rb") as f:
            d = pickle.load(f)
        self.params = d["params"]
        self.apply_spec = d.get("apply_spec")
        self.apply_fn = (_build_apply(self.apply_spec) if self.apply_spec
                         else pickle.loads(d["apply_fn"]))
        self._compiled = {}


def _pad_to_mesh(batch: np.ndarray):
    """Every core must see rows (SPMD): pad batch to a multiple of the mesh
    data-axis size (SURVEY.md §7 hard part 5 — padded shards + masks)."""
    mesh = get_default_mesh()
    if mesh is None:
        return batch, batch.shape[0]
    shards = int(np.prod(list(mesh.shape.values())))
    n = batch.shape[0]
    rem = n % shards
    if rem:
        pad = np.repeat(batch[-1:], shards - rem, axis=0)
        batch = np.concatenate([batch, pad], axis=0)
    return batch, n


def _to_np(tree):
    import jax
    return jax.tree_util.tree_map(np.asarray, tree)


def _build_apply(spec: Dict[str, Any]) -> Callable:
    """Rebuild an apply function from its declarative spec."""
    kind = spec["kind"]
    if kind == "cnn":
        from .cnn import CNNConfig, apply_cnn

        cfg_d = dict(spec["config"])
        cfg_d["stage_sizes"] = tuple(cfg_d["stage_sizes"])
        cfg_d["input_hw"] = tuple(cfg_d["input_hw"])
        cfg = CNNConfig(**cfg_d)
        return lambda p, x, capture=(): apply_cnn(p, x, cfg, capture)
    if kind == "alexnet":
        from .cnn import AlexNetConfig, apply_alexnet

        cfg_d = dict(spec["config"])
        cfg_d["input_hw"] = tuple(cfg_d["input_hw"])
        cfg = AlexNetConfig(**cfg_d)
        return lambda p, x, capture=(): apply_alexnet(p, x, cfg, capture)
    raise ValueError(f"unknown apply_spec kind {kind!r}")


class ImageFeaturizer(Transformer, HasInputCol, HasOutputCol):
    """Transfer-learning featurizer: resize -> normalize -> CNN -> cut layer.

    Parity: image/ImageFeaturizer.scala:40-191 (resize→unroll→CNTKModel with
    ``cutOutputLayers`` — :96-141). ``cutOutputLayers=1`` (default) fetches
    the global-average-pool features; 0 fetches logits.
    """

    dropNa = Param("dropNa", "drop rows whose image is missing/undecodable "
                   "before featurizing (reference: ImageFeaturizer "
                   "dropNa); False keeps them as None outputs", True,
                   TypeConverters.to_bool)
    cutOutputLayers = Param("cutOutputLayers", "how many layers to cut", 1,
                            TypeConverters.to_int)
    miniBatchSize = Param("miniBatchSize", "rows per device batch", 32,
                          TypeConverters.to_int)
    featureNode = Param("featureNode", "capture node for featurization; "
                        "None = infer from the model's apply_spec "
                        "(pool for resnets, fc7 for alexnet)", None)

    # IMAGENET_STATS: pass as mean/std when featurizing with weights trained
    # on torchvision-preprocessed ImageNet (0..255 pixel scale)
    IMAGENET_MEAN = (123.675, 116.28, 103.53)
    IMAGENET_STD = (58.395, 57.12, 57.375)

    def __init__(self, dnn_model: DNNModel = None, input_hw=(224, 224),
                 mean=(127.5, 127.5, 127.5), std=(127.5, 127.5, 127.5),
                 **kwargs):
        """``mean``/``std``: input normalization in 0..255 pixel units.
        The default maps pixels to [-1, 1] (fine for the deterministic-init
        catalog); for genuinely pretrained torchvision imports use
        ``mean=ImageFeaturizer.IMAGENET_MEAN, std=ImageFeaturizer.
        IMAGENET_STD`` to match the checkpoint's training preprocessing."""
        super().__init__(**kwargs)
        self.dnn_model = dnn_model
        self.input_hw = tuple(input_hw)
        self.norm_mean = tuple(mean)
        self.norm_std = tuple(std)

    def set_model(self, m: DNNModel) -> "ImageFeaturizer":
        self.dnn_model = m
        return self

    def transform(self, dataset: Dataset) -> Dataset:
        from ...image.ops import ImageTransformer

        in_col = self.get_or_default("inputCol")
        out_col = self.get_or_default("outputCol") or "features"
        imgs = dataset[in_col]

        def _present(v) -> bool:
            # Missing = None (DecodeImage's failure value) OR a decoded-but-
            # garbage array: empty, or containing non-finite pixels (any NaN
            # or inf pixel propagates through the conv stack and poisons the
            # whole feature vector, so partially-bad counts as missing too).
            # Without this a NaN-filled array would bypass dropNa and be
            # featurized as garbage.
            if v is None:
                return False
            a = np.asarray(v)
            if a.size == 0:
                return False
            if a.dtype.kind == "f" and not np.isfinite(a).all():
                return False
            return True

        keep = np.asarray([i for i, v in enumerate(imgs) if _present(v)],
                          dtype=np.int64)
        if len(keep) == 0:
            # nothing featurizable: empty dataset under dropNa, or
            # all-None outputs with rows preserved
            if self.get_or_default("dropNa"):
                return dataset.take(keep).with_column(out_col, [])
            return dataset.with_column(out_col, [None] * len(dataset))
        missing = len(keep) != len(dataset)
        if missing and self.get_or_default("dropNa"):
            # reference ImageFeaturizer dropNa: undecodable rows leave
            # the dataset entirely
            dataset = dataset.take(keep)
            missing = False
        valid = dataset.take(keep) if missing else dataset
        h, w = self.input_hw
        prep = (ImageTransformer()
                .set(inputCol=in_col, outputCol="_img_prepped")
                .resize(h, w)
                .normalize(mean=self.norm_mean, std=self.norm_std))
        # the featurization layer is architecture-specific: global-average
        # pool for resnets, fc7 for alexnet (image/ImageFeaturizer.scala's
        # per-model cut-layer map); featureNode overrides for models
        # constructed without an apply_spec
        feat_node = self.get_or_default("featureNode")
        if feat_node is None:
            spec = getattr(self.dnn_model, "apply_spec", None) or {}
            feat_node = "fc7" if spec.get("kind") == "alexnet" else "pool"
        node = (feat_node if self.get_or_default("cutOutputLayers") >= 1
                else "logits")
        if not hasattr(self, "_dnn_clone"):
            self._dnn_clone = self.dnn_model.cloned_with_shared_params()
        dnn = self._dnn_clone.set(
            inputCol="_img_prepped", outputCol=out_col, outputNode=node,
            miniBatchSize=self.get_or_default("miniBatchSize"))
        out = dnn.transform(prep.transform(valid)).drop("_img_prepped")
        if not missing:
            return out
        # dropNa=False with gaps: featurized the valid subset once (no
        # re-scan), reinsert None outputs at the missing positions
        feats = out[out_col]
        outs: List[Any] = [None] * len(dataset)
        for j, i in enumerate(keep):
            outs[int(i)] = feats[j]
        return dataset.with_column(out_col, outs)

    def _save_extra(self, path: str) -> None:
        from ...core.pipeline import save_stage
        save_stage(self.dnn_model, os.path.join(path, "dnn"))
        with open(os.path.join(path, "hw.pkl"), "wb") as f:
            pickle.dump({"input_hw": self.input_hw, "mean": self.norm_mean,
                         "std": self.norm_std}, f)

    def _load_extra(self, path: str) -> None:
        from ...core.pipeline import load_stage
        self.dnn_model = load_stage(os.path.join(path, "dnn"))
        with open(os.path.join(path, "hw.pkl"), "rb") as f:
            d = pickle.load(f)
        if isinstance(d, dict):
            self.input_hw = tuple(d["input_hw"])
            self.norm_mean, self.norm_std = tuple(d["mean"]), tuple(d["std"])
        else:                       # pre-mean/std save format
            self.input_hw = tuple(d)
            self.norm_mean = self.norm_std = (127.5, 127.5, 127.5)
