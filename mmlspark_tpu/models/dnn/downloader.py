"""ModelDownloader: pretrained-model repository with hash check + retries.

Parity: downloader/ModelDownloader.scala:37-276 (fetch CNTK models from the
Azure blob repo with sha-hash verification and FaultToleranceUtils
retry-with-timeout, downloader/Schema.scala:30 ``ModelSchema`` with
layerNames). The TPU model format is a param pytree + an architecture config;
sources are ``file://`` paths or HTTP URLs (fetched through the io.http retry
client), plus a *builtin* registry of deterministically-initialised
architectures so the framework is usable with zero egress — materialising a
builtin is the "download" and lands in the same local repository with the
same hash bookkeeping.

Payloads: the native format is ``.npz`` (flattened pytree, loads with
``allow_pickle=False`` — safe for payloads fetched over HTTP); legacy pickle
payloads from older repos still load. Genuinely pretrained weights enter via
``import_torch_resnet`` (torchvision-format state_dict -> folded-BN pytree ->
repo payload) or ``save_model`` from any user-built pytree.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import pickle
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List

import numpy as np


@dataclass
class ModelSchema:
    """downloader/Schema.scala:30 parity."""

    name: str
    dataset: str = ""
    modelType: str = "image"
    uri: str = ""
    sha256: str = ""
    inputDims: List[int] = field(default_factory=lambda: [224, 224, 3])
    numLayers: int = 0
    layerNames: List[str] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)


def retry_with_timeout(fn, retries: int = 3, backoff: float = 0.5):
    """FaultToleranceUtils.retryWithTimeout parity
    (downloader/ModelDownloader.scala:37-53)."""
    last = None
    for attempt in range(retries):
        try:
            return fn()
        except Exception as e:
            last = e
            if attempt < retries - 1:
                time.sleep(backoff * (2 ** attempt))
    raise last


# the featurizer catalog the reference fetches from its Azure repo
# (downloader/ModelDownloader.scala:37-276: AlexNet + the ResNet family);
# builtin weights are deterministic inits — real weights come in through
# import_torch_resnet / save_model / file:// payloads.
_BUILTIN: Dict[str, Dict[str, Any]] = {
    "ResNet18": dict(arch="resnet", stage_sizes=(2, 2, 2, 2), width=64,
                     block="basic", num_classes=1000, input_hw=(224, 224)),
    "ResNet34": dict(arch="resnet", stage_sizes=(3, 4, 6, 3), width=64,
                     block="basic", num_classes=1000, input_hw=(224, 224)),
    "ResNet50": dict(arch="resnet", stage_sizes=(3, 4, 6, 3), width=64,
                     block="bottleneck", num_classes=1000,
                     input_hw=(224, 224)),
    "ResNet101": dict(arch="resnet", stage_sizes=(3, 4, 23, 3), width=64,
                      block="bottleneck", num_classes=1000,
                      input_hw=(224, 224)),
    "ResNet152": dict(arch="resnet", stage_sizes=(3, 8, 36, 3), width=64,
                      block="bottleneck", num_classes=1000,
                      input_hw=(224, 224)),
    "AlexNet": dict(arch="alexnet", num_classes=1000, input_hw=(224, 224),
                    width_mult=1.0),
    # small variants for tests / CI
    "ResNet18Tiny": dict(arch="resnet", stage_sizes=(2, 2, 2, 2), width=16,
                         block="basic", num_classes=1000,
                         input_hw=(224, 224)),
    "ResNet50Tiny": dict(arch="resnet", stage_sizes=(1, 1, 1, 1), width=8,
                         block="bottleneck", num_classes=10,
                         input_hw=(64, 64)),
    "ResNet10Micro": dict(arch="resnet", stage_sizes=(1, 1, 1, 1), width=8,
                          block="basic", num_classes=1000,
                          input_hw=(64, 64)),
    "AlexNetTiny": dict(arch="alexnet", num_classes=10, input_hw=(64, 64),
                        width_mult=0.0625),
    "ConvNetMNIST": dict(arch="resnet", stage_sizes=(1, 1), width=8,
                         block="basic", num_classes=10, input_hw=(28, 28)),
}

# genuinely TRAINED checkpoints shipped as package fixtures (zero-egress
# stand-in for the reference's Azure blob repo of trained CNTK models);
# sha256 is pinned at training time (tools/train_digits_fixture.py), so a
# corrupted or tampered fixture fails the same hash check a remote fetch
# would (downloader/ModelDownloader.scala:37-276)
_TRAINED_FIXTURES: Dict[str, Dict[str, Any]] = {
    "DigitsConvNet": dict(
        file="digits_convnet.npz", dataset="sklearn-digits (trained, "
        "~0.97 held-out accuracy — tools/train_digits_fixture.py)",
        sha256="6e812a1fb56bd4b603deec27abc49c8d7010bca5ce56909fc5bb0cb2"
               "c7c5e5b4",
        spec=dict(arch="resnet", stage_sizes=(1, 1), width=8, block="basic",
                  num_classes=10, input_hw=(32, 32))),
}


def _layer_names(spec: Dict[str, Any]) -> List[str]:
    if spec["arch"] == "alexnet":
        return [f"conv{i}" for i in range(1, 6)] + ["fc6", "fc7", "logits"]
    return (["stem"]
            + [f"stage{s}_block{b}"
               for s, nb in enumerate(spec["stage_sizes"])
               for b in range(nb)] + ["pool", "logits"])


def _num_layers(spec: Dict[str, Any]) -> int:
    if spec["arch"] == "alexnet":
        return 8
    from .cnn import BLOCK_SPECS
    per_block = BLOCK_SPECS[spec["block"]]["convs"]
    return per_block * sum(spec["stage_sizes"]) + 2


# -- payload (de)serialization ----------------------------------------------


def _flatten(tree: Dict[str, Any], prefix="") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = np.asarray(v)
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split("/")
        d = tree
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return tree


def serialize_payload(params: Dict[str, Any], config: Dict[str, Any]) -> bytes:
    """npz payload: flattened param pytree + a JSON config entry."""
    arrays = _flatten(params, "param/")
    arrays["config_json"] = np.frombuffer(
        json.dumps(config).encode(), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def deserialize_payload(data: bytes,
                        allow_pickle: bool = True) -> Dict[str, Any]:
    """Parse a model payload. ``allow_pickle=False`` (mandatory for bytes
    fetched from remote sources) accepts only the npz format — pickle is
    arbitrary code execution on attacker-controlled data. The pickle branch
    exists solely for pre-npz payloads already sitting in local repos."""
    if data[:2] == b"PK":              # npz (zip magic) — the safe format
        z = np.load(io.BytesIO(data), allow_pickle=False)
        config = json.loads(bytes(z["config_json"]).decode())
        params = _unflatten({k[len("param/"):]: z[k] for k in z.files
                             if k.startswith("param/")})
        return {"params": params, "config": config}
    if not allow_pickle:
        raise IOError("remote model payload is not npz-format; refusing to "
                      "unpickle bytes from a remote source")
    return pickle.loads(data)          # legacy local repos


class ModelDownloader:
    """Local model repository (``repo_dir``) + remote/builtin sources."""

    def __init__(self, repo_dir: str):
        self.repo_dir = repo_dir
        os.makedirs(repo_dir, exist_ok=True)

    # -- listing ------------------------------------------------------------
    def local_models(self) -> List[ModelSchema]:
        out = []
        for name in sorted(os.listdir(self.repo_dir)):
            meta = os.path.join(self.repo_dir, name, "schema.json")
            if os.path.exists(meta):
                with open(meta) as f:
                    out.append(ModelSchema(**json.load(f)))
        return out

    def remote_models(self) -> List[ModelSchema]:
        """The builtin catalog (the Azure-blob listing analog): trained
        package fixtures first, then the deterministic-init architectures."""
        trained = [ModelSchema(name=n, modelType="image",
                               dataset=t["dataset"],
                               uri=f"package://{t['file']}",
                               sha256=t["sha256"],
                               inputDims=[*t["spec"]["input_hw"], 3],
                               numLayers=_num_layers(t["spec"]),
                               layerNames=_layer_names(t["spec"]))
                   for n, t in _TRAINED_FIXTURES.items()]
        return trained + [ModelSchema(name=n, modelType="image",
                                      uri=f"builtin://{n}",
                                      inputDims=[*spec["input_hw"], 3],
                                      numLayers=_num_layers(spec),
                                      layerNames=_layer_names(spec))
                          for n, spec in _BUILTIN.items()]

    # -- fetching -----------------------------------------------------------
    def download_model(self, schema_or_name) -> ModelSchema:
        schema = (self._builtin_schema(schema_or_name)
                  if isinstance(schema_or_name, str) else schema_or_name)
        target = os.path.join(self.repo_dir, schema.name)
        payload = os.path.join(target, "model.pkl")
        if os.path.exists(payload) and self._hash_ok(payload, schema.sha256):
            return self._read_schema(schema.name)
        os.makedirs(target, exist_ok=True)
        data = retry_with_timeout(lambda: self._fetch(schema))
        if schema.uri.startswith(("http://", "https://")):
            # validate BEFORE persisting: remote bytes must be npz (a local
            # pickle file would otherwise execute on the next load_model)
            deserialize_payload(data, allow_pickle=False)
        digest = hashlib.sha256(data).hexdigest()
        if schema.sha256 and digest != schema.sha256:
            raise IOError(f"hash mismatch for {schema.name}: "
                          f"{digest} != {schema.sha256}")
        with open(payload, "wb") as f:
            f.write(data)
        schema.sha256 = digest
        with open(os.path.join(target, "schema.json"), "w") as f:
            f.write(schema.to_json())
        return schema

    def save_model(self, name: str, params: Dict[str, Any],
                   config: Dict[str, Any]) -> ModelSchema:
        """Install a user-built pytree (e.g. converted pretrained weights)
        into the repository as an npz payload."""
        data = serialize_payload(_flatten_to_tree(params), config)
        target = os.path.join(self.repo_dir, name)
        os.makedirs(target, exist_ok=True)
        with open(os.path.join(target, "model.pkl"), "wb") as f:
            f.write(data)
        schema = ModelSchema(
            name=name, modelType="image", uri=f"local://{name}",
            sha256=hashlib.sha256(data).hexdigest(),
            inputDims=[*config.get("input_hw", (224, 224)), 3])
        with open(os.path.join(target, "schema.json"), "w") as f:
            f.write(schema.to_json())
        return schema

    def import_torch_resnet(self, name: str, state_dict: Dict[str, Any],
                            arch_name: str = "ResNet50") -> ModelSchema:
        """Install genuinely pretrained weights from a torchvision-format
        ``resnet*`` state_dict (numpy or torch tensors); batch-norm running
        stats are folded for inference (the trained-model ingestion the
        reference does by downloading CNTK models —
        downloader/ModelDownloader.scala:37-276)."""
        from .cnn import CNNConfig, from_torch_resnet_state_dict

        spec = dict(_BUILTIN[arch_name])
        sd = {k: np.asarray(getattr(v, "numpy", lambda: v)())
              for k, v in state_dict.items()}
        cfg = CNNConfig(num_classes=int(sd["fc.bias"].shape[0]),
                        stage_sizes=spec["stage_sizes"], width=spec["width"],
                        block=spec["block"], input_hw=spec["input_hw"])
        params = from_torch_resnet_state_dict(sd, cfg)
        config = dict(arch="resnet", num_classes=cfg.num_classes,
                      stage_sizes=cfg.stage_sizes, width=cfg.width,
                      block=cfg.block, input_hw=cfg.input_hw)
        return self.save_model(name, params, config)

    def load_model(self, name: str):
        """-> (params, cfg, apply_fn) ready for DNNModel."""
        payload = os.path.join(self.repo_dir, name, "model.pkl")
        if not os.path.exists(payload):
            self.download_model(name)
        with open(payload, "rb") as f:
            d = deserialize_payload(f.read())
        config = dict(d["config"])
        arch = config.pop("arch", "resnet")
        if arch == "alexnet":
            from .cnn import AlexNetConfig, apply_alexnet
            config["input_hw"] = tuple(config["input_hw"])
            cfg = AlexNetConfig(**config)
            apply_fn = lambda p, x, capture=(): apply_alexnet(  # noqa: E731
                p, x, cfg, capture)
        else:
            from .cnn import CNNConfig, apply_cnn
            config["stage_sizes"] = tuple(config["stage_sizes"])
            config["input_hw"] = tuple(config["input_hw"])
            cfg = CNNConfig(**config)
            apply_fn = lambda p, x, capture=(): apply_cnn(  # noqa: E731
                p, x, cfg, capture)
        return d["params"], cfg, apply_fn

    # -- internals ----------------------------------------------------------
    def _builtin_schema(self, name: str) -> ModelSchema:
        for s in self.remote_models():
            if s.name == name:
                return s
        raise KeyError(
            f"unknown model {name!r}; catalog: "
            f"{sorted(_TRAINED_FIXTURES) + sorted(_BUILTIN)}")

    def _read_schema(self, name: str) -> ModelSchema:
        with open(os.path.join(self.repo_dir, name, "schema.json")) as f:
            return ModelSchema(**json.load(f))

    def _hash_ok(self, path: str, expected: str) -> bool:
        if not expected:
            return True
        with open(path, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest() == expected

    def _fetch(self, schema: ModelSchema) -> bytes:
        uri = schema.uri
        if uri.startswith("package://"):
            path = os.path.join(os.path.dirname(__file__), "fixtures",
                                uri[len("package://"):])
            with open(path, "rb") as f:
                return f.read()
        if uri.startswith("builtin://"):
            return self._materialize_builtin(uri[len("builtin://"):])
        if uri.startswith("file://"):
            with open(uri[len("file://"):], "rb") as f:
                return f.read()
        if uri.startswith("http://") or uri.startswith("https://"):
            from ...io.http import HTTPRequestData, advanced_handling
            resp = advanced_handling(HTTPRequestData(url=uri), timeout=120.0)
            if not (200 <= resp.status_code < 300):
                raise IOError(f"fetch failed: {resp.status_code} {resp.reason}")
            return resp.entity or b""
        raise ValueError(f"unsupported model uri {uri!r}")

    def _materialize_builtin(self, name: str) -> bytes:
        import jax

        spec = dict(_BUILTIN[name])
        arch = spec.pop("arch")
        key = jax.random.PRNGKey(
            int(hashlib.sha256(name.encode()).hexdigest()[:8], 16))
        if arch == "alexnet":
            from .cnn import AlexNetConfig, init_alexnet_params
            cfg = AlexNetConfig(**spec)
            params = init_alexnet_params(cfg, key)
        else:
            from .cnn import CNNConfig, init_cnn_params
            cfg = CNNConfig(**spec)
            params = init_cnn_params(cfg, key)
        params = jax.tree_util.tree_map(np.asarray, params)
        return serialize_payload(params, {"arch": arch, **spec})


def _flatten_to_tree(params):
    """Identity for dict pytrees; normalizes array leaves to numpy."""
    import jax
    return jax.tree_util.tree_map(np.asarray, params)
