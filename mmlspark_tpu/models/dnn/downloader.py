"""ModelDownloader: pretrained-model repository with hash check + retries.

Parity: downloader/ModelDownloader.scala:37-276 (fetch CNTK models from the
Azure blob repo with sha-hash verification and FaultToleranceUtils
retry-with-timeout, downloader/Schema.scala:30 ``ModelSchema`` with
layerNames). The TPU model format is a pickled JAX param pytree + CNNConfig;
sources are ``file://`` paths or HTTP URLs (fetched through the io.http retry
client), plus a *builtin* registry of deterministically-initialised
architectures so the framework is usable with zero egress — materialising a
builtin is the "download" and lands in the same local repository with the
same hash bookkeeping.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np


@dataclass
class ModelSchema:
    """downloader/Schema.scala:30 parity."""

    name: str
    dataset: str = ""
    modelType: str = "image"
    uri: str = ""
    sha256: str = ""
    inputDims: List[int] = field(default_factory=lambda: [224, 224, 3])
    numLayers: int = 0
    layerNames: List[str] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)


def retry_with_timeout(fn, retries: int = 3, backoff: float = 0.5):
    """FaultToleranceUtils.retryWithTimeout parity
    (downloader/ModelDownloader.scala:37-53)."""
    last = None
    for attempt in range(retries):
        try:
            return fn()
        except Exception as e:
            last = e
            if attempt < retries - 1:
                time.sleep(backoff * (2 ** attempt))
    raise last


_BUILTIN = {
    # name -> (stage_sizes, width, num_classes, input_hw)
    # full-width families (the featurizer catalog the reference fetches from
    # its Azure repo — downloader/ModelDownloader.scala:37-276; weights here
    # are deterministic random inits, pending a hosted weight repo)
    "ResNet18": ((2, 2, 2, 2), 64, 1000, (224, 224)),
    "ResNet34": ((3, 4, 6, 3), 64, 1000, (224, 224)),
    # small variants for tests / CI
    "ResNet18Tiny": ((2, 2, 2, 2), 16, 1000, (224, 224)),
    "ResNet10Micro": ((1, 1, 1, 1), 8, 1000, (64, 64)),
    "ConvNetMNIST": ((1, 1), 8, 10, (28, 28)),
}


class ModelDownloader:
    """Local model repository (``repo_dir``) + remote/builtin sources."""

    def __init__(self, repo_dir: str):
        self.repo_dir = repo_dir
        os.makedirs(repo_dir, exist_ok=True)

    # -- listing ------------------------------------------------------------
    def local_models(self) -> List[ModelSchema]:
        out = []
        for name in sorted(os.listdir(self.repo_dir)):
            meta = os.path.join(self.repo_dir, name, "schema.json")
            if os.path.exists(meta):
                with open(meta) as f:
                    out.append(ModelSchema(**json.load(f)))
        return out

    def remote_models(self) -> List[ModelSchema]:
        """The builtin catalog (the Azure-blob listing analog)."""
        return [ModelSchema(name=n, modelType="image",
                            uri=f"builtin://{n}",
                            inputDims=[*_BUILTIN[n][3], 3],
                            numLayers=2 * sum(_BUILTIN[n][0]) + 2,
                            layerNames=["stem"]
                            + [f"stage{s}_block{b}"
                               for s, nb in enumerate(_BUILTIN[n][0])
                               for b in range(nb)] + ["pool", "logits"])
                for n in _BUILTIN]

    # -- fetching -----------------------------------------------------------
    def download_model(self, schema_or_name) -> ModelSchema:
        schema = (self._builtin_schema(schema_or_name)
                  if isinstance(schema_or_name, str) else schema_or_name)
        target = os.path.join(self.repo_dir, schema.name)
        payload = os.path.join(target, "model.pkl")
        if os.path.exists(payload) and self._hash_ok(payload, schema.sha256):
            return self._read_schema(schema.name)
        os.makedirs(target, exist_ok=True)
        data = retry_with_timeout(lambda: self._fetch(schema))
        digest = hashlib.sha256(data).hexdigest()
        if schema.sha256 and digest != schema.sha256:
            raise IOError(f"hash mismatch for {schema.name}: "
                          f"{digest} != {schema.sha256}")
        with open(payload, "wb") as f:
            f.write(data)
        schema.sha256 = digest
        with open(os.path.join(target, "schema.json"), "w") as f:
            f.write(schema.to_json())
        return schema

    def load_model(self, name: str):
        """-> (params, cfg, apply_fn) ready for DNNModel."""
        from .cnn import CNNConfig, apply_cnn

        payload = os.path.join(self.repo_dir, name, "model.pkl")
        if not os.path.exists(payload):
            self.download_model(name)
        with open(payload, "rb") as f:
            d = pickle.load(f)
        cfg = CNNConfig(**d["config"])
        apply_fn = lambda p, x, capture=(): apply_cnn(p, x, cfg, capture)  # noqa: E731
        return d["params"], cfg, apply_fn

    # -- internals ----------------------------------------------------------
    def _builtin_schema(self, name: str) -> ModelSchema:
        for s in self.remote_models():
            if s.name == name:
                return s
        raise KeyError(f"unknown model {name!r}; "
                       f"builtins: {sorted(_BUILTIN)}")

    def _read_schema(self, name: str) -> ModelSchema:
        with open(os.path.join(self.repo_dir, name, "schema.json")) as f:
            return ModelSchema(**json.load(f))

    def _hash_ok(self, path: str, expected: str) -> bool:
        if not expected:
            return True
        with open(path, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest() == expected

    def _fetch(self, schema: ModelSchema) -> bytes:
        uri = schema.uri
        if uri.startswith("builtin://"):
            return self._materialize_builtin(uri[len("builtin://"):])
        if uri.startswith("file://"):
            with open(uri[len("file://"):], "rb") as f:
                return f.read()
        if uri.startswith("http://") or uri.startswith("https://"):
            from ...io.http import HTTPRequestData, advanced_handling
            resp = advanced_handling(HTTPRequestData(url=uri), timeout=120.0)
            if not (200 <= resp.status_code < 300):
                raise IOError(f"fetch failed: {resp.status_code} {resp.reason}")
            return resp.entity or b""
        raise ValueError(f"unsupported model uri {uri!r}")

    def _materialize_builtin(self, name: str) -> bytes:
        import jax

        from .cnn import CNNConfig, init_cnn_params

        stage_sizes, width, num_classes, hw = _BUILTIN[name]
        cfg = CNNConfig(num_classes=num_classes, stage_sizes=stage_sizes,
                        width=width, input_hw=hw)
        params = init_cnn_params(cfg, jax.random.PRNGKey(
            int(hashlib.sha256(name.encode()).hexdigest()[:8], 16)))
        params = jax.tree_util.tree_map(np.asarray, params)
        return pickle.dumps({
            "params": params,
            "config": {"num_classes": cfg.num_classes,
                       "stage_sizes": cfg.stage_sizes, "width": cfg.width,
                       "input_hw": cfg.input_hw}})
