"""Shared preprocessing for the DigitsConvNet trained fixture.

Single source of truth for how sklearn digits become DigitsConvNet inputs —
used by the trainer (tools/train_digits_fixture.py), the transfer-learning
example (examples/21), and the fixture tests, so the three can never drift
from the preprocessing the checkpoint was trained with.
"""

from __future__ import annotations

import numpy as np


def upsample_digits(flat: np.ndarray) -> np.ndarray:
    """8x8 [0,16] digit rows -> [n, 32, 32] float arrays in 0..255."""
    imgs = flat.reshape(-1, 8, 8) / 16.0 * 255.0
    return np.kron(imgs, np.ones((1, 4, 4)))


def prep_digits(flat: np.ndarray) -> np.ndarray:
    """Model-input tensors: 32x32x3, normalized to [-1, 1] (the
    mean=std=127.5 convention ImageFeaturizer defaults to)."""
    imgs = np.stack([upsample_digits(flat)] * 3, axis=-1).astype(np.float32)
    return (imgs - 127.5) / 127.5


def digits_images(flat: np.ndarray) -> list:
    """uint8 HWC images for the ImageFeaturizer input column."""
    return [np.stack([im] * 3, axis=-1).astype(np.uint8)
            for im in upsample_digits(flat)]


def heldout_split(X, y):
    """The trainer's exact split; the returned test quarter was never seen
    in pretraining."""
    from sklearn.model_selection import train_test_split

    return train_test_split(X, y, test_size=0.25, random_state=0,
                            stratify=y)
