"""Pure-JAX convolutional networks for the image featurization path.

The reference scores pretrained CNTK CNNs (AlexNet/ResNet-50, fetched by
ModelDownloader — reference: cntk/CNTKModel.scala:30-532,
downloader/ModelDownloader.scala:37-276). Here the model format is a JAX
param pytree + a functional ``apply``; "model surgery" (pick an intermediate
output node, ImageFeaturizer's layer cutting, image/ImageFeaturizer.scala:
96-141) is a ``capture`` argument instead of graph editing: apply returns
(logits, {node_name: activation}).

Two block styles cover the reference's featurizer catalog: ``basic``
(ResNet-18/34) and ``bottleneck`` (ResNet-50/101/152: 1x1 -> 3x3 -> 1x1 with
4x channel expansion), plus a classic AlexNet tower. Convs are NHWC
bfloat16-friendly and lower straight onto the MXU; batch-norm is folded into
inference scale/shift (no training here — this is the scoring path, like
CNTK eval).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@dataclass(frozen=True)
class CNNConfig:
    """ResNet-v1-style config.

    block="basic": two 3x3 convs per block (stage_sizes=[2,2,2,2] ~ ResNet-18,
    [3,4,6,3] ~ ResNet-34). block="bottleneck": 1x1/3x3/1x1 with expansion 4
    ([3,4,6,3] ~ ResNet-50, [3,4,23,3] ~ ResNet-101, [3,8,36,3] ~ ResNet-152).
    """

    num_classes: int = 1000
    stage_sizes: Tuple[int, ...] = (2, 2, 2, 2)
    width: int = 64
    input_hw: Tuple[int, int] = (224, 224)
    dtype: Any = jnp.float32
    block: str = "basic"


# single source of truth for per-block structure, shared with the
# downloader catalog (numLayers) and the weight importer
BLOCK_SPECS = {"basic": {"convs": 2, "expansion": 1},
               "bottleneck": {"convs": 3, "expansion": 4}}
_EXPANSION = {k: v["expansion"] for k, v in BLOCK_SPECS.items()}


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    w = jax.random.normal(key, (kh, kw, cin, cout)) * np.sqrt(2.0 / fan_in)
    return w.astype(jnp.float32)


def _bn_unit(cout):
    return {"scale": jnp.ones((cout,)), "bias": jnp.zeros((cout,))}


def init_cnn_params(cfg: CNNConfig, key) -> Dict[str, Any]:
    expansion = BLOCK_SPECS[cfg.block]["expansion"]
    n_convs = BLOCK_SPECS[cfg.block]["convs"]
    keys = iter(jax.random.split(
        key, 4 + (n_convs + 1) * sum(cfg.stage_sizes) + 2))
    params: Dict[str, Any] = {
        "stem": {"w": _conv_init(next(keys), 7, 7, 3, cfg.width),
                 **_bn_unit(cfg.width)}}
    cin = cfg.width
    for s, n_blocks in enumerate(cfg.stage_sizes):
        mid = cfg.width * (2 ** s)
        cout = mid * expansion
        for b in range(n_blocks):
            if cfg.block == "basic":
                blk = {
                    "conv1": {"w": _conv_init(next(keys), 3, 3, cin, mid),
                              **_bn_unit(mid)},
                    "conv2": {"w": _conv_init(next(keys), 3, 3, mid, cout),
                              **_bn_unit(cout)},
                }
            else:
                blk = {
                    "conv1": {"w": _conv_init(next(keys), 1, 1, cin, mid),
                              **_bn_unit(mid)},
                    "conv2": {"w": _conv_init(next(keys), 3, 3, mid, mid),
                              **_bn_unit(mid)},
                    "conv3": {"w": _conv_init(next(keys), 1, 1, mid, cout),
                              **_bn_unit(cout)},
                }
            if cin != cout:
                blk["proj"] = {"w": _conv_init(next(keys), 1, 1, cin, cout)}
            params[f"stage{s}_block{b}"] = blk
            cin = cout
    params["head"] = {
        "w": jax.random.normal(next(keys), (cin, cfg.num_classes))
        * np.sqrt(1.0 / cin),
        "b": jnp.zeros((cfg.num_classes,))}
    return params


def _conv(x, w, stride=1):
    # explicit symmetric (k-1)//2 padding, not "SAME": under stride 2 SAME
    # pads asymmetrically, which would silently de-align genuinely pretrained
    # weights imported via from_torch_resnet_state_dict (torch pads
    # symmetrically)
    ph, pw = (w.shape[0] - 1) // 2, (w.shape[1] - 1) // 2
    # params are stored f32; cast at use so cfg.dtype=bfloat16 runs the whole
    # conv stack on the MXU in bf16 instead of erroring (or silently promoting
    # back to f32 through the folded-BN affine)
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), ((ph, ph), (pw, pw)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn_affine(x, p):
    return x * p["scale"].astype(x.dtype) + p["bias"].astype(x.dtype)


def _bn_relu(x, p):
    return jax.nn.relu(_bn_affine(x, p))


def apply_cnn(params: Dict[str, Any], x: jnp.ndarray, cfg: CNNConfig,
              capture: Sequence[str] = ()) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Forward pass. ``x``: (N, H, W, 3) float in [0,1] or normalized.
    ``capture`` names intermediate nodes to return: 'stem', 'stageS_blockB',
    'pool' (global avg pool — the standard featurization layer), 'logits'.
    """
    acts: Dict[str, jnp.ndarray] = {}
    x = x.astype(cfg.dtype)
    stem = params["stem"]
    x = _bn_relu(_conv(x, stem["w"], stride=2), stem)
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                          ((0, 0), (1, 1), (1, 1), (0, 0)))
    if "stem" in capture:
        acts["stem"] = x
    for s, n_blocks in enumerate(cfg.stage_sizes):
        for b in range(n_blocks):
            name = f"stage{s}_block{b}"
            blk = params[name]
            stride = 2 if (b == 0 and s > 0) else 1
            if cfg.block == "basic":
                h = _bn_relu(_conv(x, blk["conv1"]["w"], stride), blk["conv1"])
                h = _bn_affine(_conv(h, blk["conv2"]["w"]), blk["conv2"])
            else:
                h = _bn_relu(_conv(x, blk["conv1"]["w"]), blk["conv1"])
                h = _bn_relu(_conv(h, blk["conv2"]["w"], stride), blk["conv2"])
                h = _bn_affine(_conv(h, blk["conv3"]["w"]), blk["conv3"])
            shortcut = x
            if "proj" in blk:
                shortcut = _conv(x, blk["proj"]["w"], stride)
            elif stride != 1:
                shortcut = x[:, ::stride, ::stride]
            x = jax.nn.relu(h + shortcut)
            if name in capture:
                acts[name] = x
    pooled = jnp.mean(x, axis=(1, 2))
    if "pool" in capture:
        acts["pool"] = pooled
    logits = (pooled @ params["head"]["w"].astype(pooled.dtype)
              + params["head"]["b"].astype(pooled.dtype))
    if "logits" in capture:
        acts["logits"] = logits
    return logits, acts


def feature_dim(cfg: CNNConfig) -> int:
    return (cfg.width * (2 ** (len(cfg.stage_sizes) - 1))
            * _EXPANSION[cfg.block])


# ---------------------------------------------------------------------------
# AlexNet (the reference catalog's other featurizer family —
# downloader/ModelDownloader.scala:37-276 fetches CNTK AlexNet; featurization
# cuts at fc7, image/ImageFeaturizer.scala:96-141)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class AlexNetConfig:
    num_classes: int = 1000
    input_hw: Tuple[int, int] = (224, 224)
    width_mult: float = 1.0           # shrink for tests
    dtype: Any = jnp.float32


def _alex_dims(cfg: AlexNetConfig):
    m = cfg.width_mult
    chans = [int(c * m) or 1 for c in (64, 192, 384, 256, 256)]
    fc = int(4096 * m) or 1
    return chans, fc


def _alex_spatial(cfg: AlexNetConfig) -> Tuple[int, int]:
    """Spatial dims entering fc6: stride-4 stem then three stride-2 SAME
    pools, each with ceil semantics — exact for any (even non-square,
    non-multiple-of-32) input size."""
    def axis(d):
        d = -(-d // 4)            # stem conv, stride 4, symmetric padding
        for _ in range(3):        # pools after conv1, conv2, conv5
            d = -(-d // 2)
        return d
    return axis(cfg.input_hw[0]), axis(cfg.input_hw[1])


def init_alexnet_params(cfg: AlexNetConfig, key) -> Dict[str, Any]:
    chans, fc = _alex_dims(cfg)
    keys = iter(jax.random.split(key, 16))
    specs = [(11, 3, chans[0]), (5, chans[0], chans[1]),
             (3, chans[1], chans[2]), (3, chans[2], chans[3]),
             (3, chans[3], chans[4])]
    params: Dict[str, Any] = {}
    for i, (k, cin, cout) in enumerate(specs):
        params[f"conv{i + 1}"] = {
            "w": _conv_init(next(keys), k, k, cin, cout),
            "b": jnp.zeros((cout,))}
    h, w = _alex_spatial(cfg)
    flat = chans[4] * h * w
    for i, (din, dout) in enumerate([(flat, fc), (fc, fc),
                                     (fc, cfg.num_classes)]):
        params[f"fc{i + 6}"] = {
            "w": jax.random.normal(next(keys), (din, dout))
            * np.sqrt(2.0 / din),
            "b": jnp.zeros((dout,))}
    return params


def apply_alexnet(params: Dict[str, Any], x: jnp.ndarray, cfg: AlexNetConfig,
                  capture: Sequence[str] = ()):
    """AlexNet forward; capture nodes: conv1..conv5, fc6, fc7 (the
    featurization layer), logits."""
    acts: Dict[str, jnp.ndarray] = {}
    x = x.astype(cfg.dtype)

    def pool(v):
        return lax.reduce_window(v, -jnp.inf, lax.max, (1, 3, 3, 1),
                                 (1, 2, 2, 1), "SAME")

    strides = [4, 1, 1, 1, 1]
    pools = [True, True, False, False, True]
    for i in range(5):
        p = params[f"conv{i + 1}"]
        x = jax.nn.relu(_conv(x, p["w"], strides[i]) + p["b"])
        if pools[i]:
            x = pool(x)
        if f"conv{i + 1}" in capture:
            acts[f"conv{i + 1}"] = x
    x = x.reshape(x.shape[0], -1)
    for name in ("fc6", "fc7"):
        p = params[name]
        x = jax.nn.relu(x @ p["w"] + p["b"])
        if name in capture:
            acts[name] = x
    p = params["fc8"]
    logits = x @ p["w"] + p["b"]
    if "logits" in capture:
        acts["logits"] = logits
    return logits, acts


def alexnet_feature_dim(cfg: AlexNetConfig) -> int:
    return _alex_dims(cfg)[1]


# ---------------------------------------------------------------------------
# Real-weight import: torchvision ResNet state_dicts -> our pytree.
# ---------------------------------------------------------------------------


def fold_bn(gamma, beta, mean, var, eps: float = 1e-5):
    """Inference-fold batch-norm into (scale, bias): y = x*scale + bias."""
    scale = gamma / np.sqrt(var + eps)
    return scale.astype(np.float32), (beta - mean * scale).astype(np.float32)


def from_torch_resnet_state_dict(sd: Dict[str, np.ndarray],
                                 cfg: CNNConfig) -> Dict[str, Any]:
    """Convert a torchvision ``resnet*`` state_dict (tensors as numpy arrays,
    OIHW conv weights) into the apply_cnn param pytree, folding batch-norm
    running stats into inference scale/bias.

    Enables loading genuinely pretrained ResNet-50 weights from a local
    ``file://`` checkpoint (the reference downloads trained CNTK models the
    same way — downloader/ModelDownloader.scala:37-276). This converter plus
    ``ModelDownloader.save_model`` produces a repo payload from any
    torchvision-format checkpoint without needing torch at load time.
    """
    def conv(prefix):
        return np.ascontiguousarray(
            np.transpose(np.asarray(sd[prefix + ".weight"]), (2, 3, 1, 0))
        ).astype(np.float32)  # OIHW -> HWIO

    def bn(prefix):
        s, b = fold_bn(np.asarray(sd[prefix + ".weight"]),
                       np.asarray(sd[prefix + ".bias"]),
                       np.asarray(sd[prefix + ".running_mean"]),
                       np.asarray(sd[prefix + ".running_var"]))
        return {"scale": s, "bias": b}

    params: Dict[str, Any] = {
        "stem": {"w": conv("conv1"), **bn("bn1")}}
    n_convs = BLOCK_SPECS[cfg.block]["convs"]
    for s, n_blocks in enumerate(cfg.stage_sizes):
        for b in range(n_blocks):
            t = f"layer{s + 1}.{b}"
            blk: Dict[str, Any] = {}
            for c in range(1, n_convs + 1):
                blk[f"conv{c}"] = {"w": conv(f"{t}.conv{c}"),
                                   **bn(f"{t}.bn{c}")}
            if f"{t}.downsample.0.weight" in sd:
                # torchvision's downsample = conv + bn; fold the bn into the
                # projection by scaling its output channels
                w = conv(f"{t}.downsample.0")
                dbn = bn(f"{t}.downsample.1")
                blk["proj"] = {"w": w * dbn["scale"]}
                # bn bias on the shortcut shifts the sum pre-relu; carry it
                # into the main-path bias of the last conv block
                last = f"conv{n_convs}"
                blk[last] = dict(blk[last])
                blk[last]["bias"] = blk[last]["bias"] + dbn["bias"]
            params[f"stage{s}_block{b}"] = blk
    params["head"] = {
        "w": np.ascontiguousarray(
            np.transpose(np.asarray(sd["fc.weight"]))).astype(np.float32),
        "b": np.asarray(sd["fc.bias"]).astype(np.float32)}
    return params
