"""Pure-JAX convolutional networks for the image featurization path.

The reference scores pretrained CNTK CNNs (AlexNet/ResNet-50, fetched by
ModelDownloader — reference: cntk/CNTKModel.scala:30-532,
downloader/ModelDownloader.scala:37-276). Here the model format is a JAX
param pytree + a functional ``apply``; "model surgery" (pick an intermediate
output node, ImageFeaturizer's layer cutting, image/ImageFeaturizer.scala:
96-141) is a ``capture`` argument instead of graph editing: apply returns
(logits, {node_name: activation}).

Convs are NHWC bfloat16-friendly and lower straight onto the MXU; batch-norm
is folded into inference scale/shift (no training here — this is the scoring
path, like CNTK eval).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@dataclass(frozen=True)
class CNNConfig:
    """ResNet-v1-style config. stage_sizes=[2,2,2,2] ~ ResNet-18 shape."""

    num_classes: int = 1000
    stage_sizes: Tuple[int, ...] = (2, 2, 2, 2)
    width: int = 64
    input_hw: Tuple[int, int] = (224, 224)
    dtype: Any = jnp.float32


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    w = jax.random.normal(key, (kh, kw, cin, cout)) * np.sqrt(2.0 / fan_in)
    return w.astype(jnp.float32)


def init_cnn_params(cfg: CNNConfig, key) -> Dict[str, Any]:
    keys = iter(jax.random.split(key, 4 + 2 * sum(cfg.stage_sizes) * 2 + 2))
    params: Dict[str, Any] = {
        "stem": {"w": _conv_init(next(keys), 7, 7, 3, cfg.width),
                 "scale": jnp.ones((cfg.width,)),
                 "bias": jnp.zeros((cfg.width,))}}
    cin = cfg.width
    for s, n_blocks in enumerate(cfg.stage_sizes):
        cout = cfg.width * (2 ** s)
        for b in range(n_blocks):
            blk = {
                "conv1": {"w": _conv_init(next(keys), 3, 3, cin, cout),
                          "scale": jnp.ones((cout,)), "bias": jnp.zeros((cout,))},
                "conv2": {"w": _conv_init(next(keys), 3, 3, cout, cout),
                          "scale": jnp.ones((cout,)), "bias": jnp.zeros((cout,))},
            }
            if cin != cout:
                blk["proj"] = {"w": _conv_init(next(keys), 1, 1, cin, cout)}
            params[f"stage{s}_block{b}"] = blk
            cin = cout
    params["head"] = {
        "w": jax.random.normal(next(keys), (cin, cfg.num_classes))
        * np.sqrt(1.0 / cin),
        "b": jnp.zeros((cfg.num_classes,))}
    return params


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn_relu(x, p):
    return jax.nn.relu(x * p["scale"] + p["bias"])


def apply_cnn(params: Dict[str, Any], x: jnp.ndarray, cfg: CNNConfig,
              capture: Sequence[str] = ()) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Forward pass. ``x``: (N, H, W, 3) float in [0,1] or normalized.
    ``capture`` names intermediate nodes to return: 'stem', 'stageS_blockB',
    'pool' (global avg pool — the standard featurization layer), 'logits'.
    """
    acts: Dict[str, jnp.ndarray] = {}
    x = x.astype(cfg.dtype)
    stem = params["stem"]
    x = _bn_relu(_conv(x, stem["w"], stride=2), stem)
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                          "SAME")
    if "stem" in capture:
        acts["stem"] = x
    for s, n_blocks in enumerate(cfg.stage_sizes):
        for b in range(n_blocks):
            name = f"stage{s}_block{b}"
            blk = params[name]
            stride = 2 if (b == 0 and s > 0) else 1
            h = _bn_relu(_conv(x, blk["conv1"]["w"], stride), blk["conv1"])
            h = _conv(h, blk["conv2"]["w"]) * blk["conv2"]["scale"] + blk["conv2"]["bias"]
            shortcut = x
            if "proj" in blk:
                shortcut = _conv(x, blk["proj"]["w"], stride)
            elif stride != 1:
                shortcut = x[:, ::stride, ::stride]
            x = jax.nn.relu(h + shortcut)
            if name in capture:
                acts[name] = x
    pooled = jnp.mean(x, axis=(1, 2))
    if "pool" in capture:
        acts["pool"] = pooled
    logits = pooled @ params["head"]["w"] + params["head"]["b"]
    if "logits" in capture:
        acts["logits"] = logits
    return logits, acts


def feature_dim(cfg: CNNConfig) -> int:
    return cfg.width * (2 ** (len(cfg.stage_sizes) - 1))
