"""DNN scoring + training path (reference: cntk/ + image featurization).

``transformer``: the flagship SPMD transformer (train + forward) with ring
attention; ``cnn``: pure-JAX convnets for featurization; ``scoring``:
DNNModel/ImageFeaturizer pipeline stages (CNTKModel parity); ``downloader``:
pretrained-model repository.
"""

from .cnn import (AlexNetConfig, CNNConfig, alexnet_feature_dim,
                  apply_alexnet, apply_cnn, feature_dim, fold_bn,
                  from_torch_resnet_state_dict, init_alexnet_params,
                  init_cnn_params)
from .downloader import ModelDownloader, ModelSchema, retry_with_timeout
from .scoring import DNNModel, ImageFeaturizer

__all__ = [
    "CNNConfig", "DNNModel", "ImageFeaturizer", "ModelDownloader",
    "ModelSchema", "apply_cnn", "feature_dim", "init_cnn_params",
    "retry_with_timeout",
]
