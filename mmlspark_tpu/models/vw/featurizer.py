"""VowpalWabbitFeaturizer: hash columns into a fixed sparse feature space.

Parity with the reference's JVM-side featurization
(reference: vw/VowpalWabbitFeaturizer.scala:22-226 and the 11 per-type
featurizers under vw/featurizer/ — numeric / string / map / seq / boolean /
vector / string-split), re-designed for a columnar host pipeline: each input
column contributes hashed (index, value) pairs per row; the output column is a
padded fixed-width sparse block — ``indices [n, nnz_max] int32`` +
``values [n, nnz_max] f32`` — because SPMD training wants rectangles, not
ragged JNI example objects.

Hashing matches ops/murmur.py (VW's murmur3), so feature identity is stable
across train/predict and across the distributed mesh.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from ...core.dataset import Dataset
from ...core.params import (HasInputCols, HasOutputCol, Param, Params,
                            TypeConverters)
from ...core.pipeline import Transformer
from ...ops.murmur import hash_feature, hash_namespace, mask_bits


class VowpalWabbitFeaturizer(Transformer, HasInputCols, HasOutputCol):
    numBits = Param("numBits", "Feature space is 2^numBits", 18, TypeConverters.to_int)
    sumCollisions = Param("sumCollisions", "Sum values on hash collision", True,
                          TypeConverters.to_bool)
    stringSplitInputCols = Param(
        "stringSplitInputCols",
        "Columns whose strings are whitespace-split into words first", None,
        TypeConverters.to_list_string)
    prefixStringsWithColumnName = Param(
        "prefixStringsWithColumnName", "Prefix hashed strings with column name",
        True, TypeConverters.to_bool)
    outputCol = Param("outputCol", "The name of the output column", "features",
                      TypeConverters.to_string)
    hashSeed = Param("hashSeed", "Seed of the murmur feature hashing (VW "
                     "--hash_seed; reference: VowpalWabbitBase hashSeed). "
                     "Train and score featurizers must agree", 0,
                     TypeConverters.to_int)
    preserveOrderNumBits = Param(
        "preserveOrderNumBits", "Reserve this many top bits to encode the "
        "input column's position, so features of different columns cannot "
        "collide and column order is recoverable from indices (reference: "
        "VowpalWabbitFeaturizer preserveOrderNumBits; 0 = off)", 0,
        TypeConverters.to_int)

    def _row_features(self, name: str, value, ns_hash: int, num_bits: int,
                      split: bool, prefix: bool) -> List[Tuple[int, float]]:
        out: List[Tuple[int, float]] = []
        if value is None:
            return out
        if isinstance(value, (bool, np.bool_)):
            if value:
                out.append((mask_bits(hash_feature(name, ns_hash), num_bits), 1.0))
        elif isinstance(value, (int, float, np.integer, np.floating)):
            v = float(value)
            if v != 0.0 and not np.isnan(v):
                out.append((mask_bits(hash_feature(name, ns_hash), num_bits), v))
        elif isinstance(value, str):
            if split:
                for w in value.split():
                    key = f"{name}_{w}" if prefix else w
                    out.append((mask_bits(hash_feature(key, ns_hash), num_bits), 1.0))
            else:
                key = f"{name}_{value}" if prefix else value
                out.append((mask_bits(hash_feature(key, ns_hash), num_bits), 1.0))
        elif isinstance(value, dict):
            for k, v in value.items():
                out.extend(self._row_features(f"{name}_{k}", v, ns_hash, num_bits,
                                              split, prefix))
        elif isinstance(value, np.ndarray) and value.ndim == 1:
            for i, v in enumerate(value):
                v = float(v)
                if v != 0.0:
                    out.append((mask_bits(hash_feature(str(i), ns_hash), num_bits), v))
        elif isinstance(value, (list, tuple)):
            for item in value:
                out.extend(self._row_features(name, item, ns_hash, num_bits,
                                              split, prefix))
        else:
            raise TypeError(f"unsupported feature type {type(value)} in column {name}")
        return out

    def transform(self, dataset: Dataset) -> Dataset:
        in_cols = self.get_or_default("inputCols") or []
        num_bits = self.get_or_default("numBits")
        split_cols = set(self.get_or_default("stringSplitInputCols") or [])
        prefix = self.get_or_default("prefixStringsWithColumnName")
        sum_coll = self.get_or_default("sumCollisions")
        # default namespace, seeded by hashSeed (VW --hash_seed)
        ns_hash = hash_namespace("", self.get_or_default("hashSeed"))
        pon = int(self.get_or_default("preserveOrderNumBits") or 0)
        if pon:
            if pon >= num_bits:
                raise ValueError(
                    f"preserveOrderNumBits={pon} must be < numBits="
                    f"{num_bits}")
            if len(in_cols) > (1 << pon):
                raise ValueError(
                    f"preserveOrderNumBits={pon} encodes at most "
                    f"{1 << pon} columns; got {len(in_cols)}")
        low_bits = num_bits - pon

        n = len(dataset)
        per_row: List[List[Tuple[int, float]]] = [[] for _ in range(n)]
        for ci, col in enumerate(in_cols):
            data = dataset[col]
            is_split = col in split_cols
            prefix_bits = ci << low_bits
            for i in range(n):
                v = data[i] if not isinstance(data, np.ndarray) else data[i]
                feats = self._row_features(col, v, ns_hash, num_bits,
                                           is_split, prefix)
                if pon:
                    # top bits carry the column position; hashes fold into
                    # the remaining low bits
                    feats = [(prefix_bits | (idx & ((1 << low_bits) - 1)),
                              val) for idx, val in feats]
                per_row[i].extend(feats)

        # collapse collisions, then pad to the max active-feature count
        nnz_max = 1
        collapsed: List[Dict[int, float]] = []
        for feats in per_row:
            d: Dict[int, float] = {}
            for idx, val in feats:
                if idx in d:
                    d[idx] = d[idx] + val if sum_coll else val
                else:
                    d[idx] = val
            collapsed.append(d)
            nnz_max = max(nnz_max, len(d))

        indices = np.zeros((n, nnz_max), dtype=np.int32)
        values = np.zeros((n, nnz_max), dtype=np.float32)
        for i, d in enumerate(collapsed):
            if d:
                idx = np.fromiter(d.keys(), dtype=np.int32, count=len(d))
                val = np.fromiter(d.values(), dtype=np.float32, count=len(d))
                indices[i, :len(d)] = idx
                values[i, :len(d)] = val
        out = self.get_or_default("outputCol")
        return dataset.with_columns({
            f"{out}_indices": indices,
            f"{out}_values": values,
        })
