"""Contextual bandits with action-dependent features (ADF), TPU-native.

Re-design of the reference's VW contextual bandit integration (reference:
vw/VowpalWabbitContextualBandit.scala:28-359 — ``--cb_explore_adf`` multiline
examples, epsilon-greedy exploration, IPS/SNIPS counterfactual metrics,
parallel multi-config fit; vw/VectorZipper.scala — action assembly;
vw/VowpalWabbitInteractions.scala — FNV-1 namespace interactions).

Instead of stacking native VW multiline examples, each row is a fixed-shape
(padded) tensor of K action vectors plus one shared vector; training is a
jit-compiled ``lax.scan`` over examples that

- scores every action with a linear model (shared block + ADF action block),
- forms the epsilon-greedy policy over the valid actions,
- folds the IPS/SNIPS counters into the scan carry (the reference's
  ContextualBanditMetrics, updated per-example during learning), and
- applies an MTR-style update on the chosen action: squared-loss gradient on
  the observed cost, importance-weighted by 1/logged_probability
  (VW's default ``cb_type=mtr`` reduction semantics).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...core.dataset import Dataset
from ...core.params import (HasFeaturesCol, HasInputCols, HasLabelCol,
                            HasOutputCol, HasPredictionCol, Param,
                            TypeConverters)
from ...core.pipeline import Estimator, Model, Transformer


class ContextualBanditMetrics:
    """IPS / SNIPS counterfactual estimators (reference:
    VowpalWabbitContextualBandit.scala:55-84, after
    VowpalWabbit/estimators ips_snips.py)."""

    def __init__(self, snips_numerator: float = 0.0, total_events: float = 0.0,
                 snips_denominator: float = 0.0,
                 offline_policy_events: float = 0.0,
                 max_ips_numerator: float = 0.0):
        self.snips_numerator = snips_numerator
        self.total_events = total_events
        self.snips_denominator = snips_denominator
        self.offline_policy_events = offline_policy_events
        self.max_ips_numerator = max_ips_numerator

    def add_example(self, prob_logging_policy: float, reward: float,
                    prob_eval_policy: float, count: int = 1) -> None:
        self.total_events += count
        if prob_eval_policy > 0:
            p_over_p = prob_eval_policy / prob_logging_policy
            self.snips_denominator += p_over_p * count
            self.offline_policy_events += count
            if reward != 0:
                self.snips_numerator += reward * p_over_p * count
                self.max_ips_numerator = max(self.max_ips_numerator,
                                             reward * p_over_p)

    def get_snips_estimate(self) -> float:
        return self.snips_numerator / self.snips_denominator

    def get_ips_estimate(self) -> float:
        return self.snips_numerator / self.total_events


def _stack_actions(col) -> Tuple[np.ndarray, np.ndarray]:
    """Ragged per-row action lists -> ([n, K_max, d] padded, [n, K_max] mask).

    Rows with zero actions are legal at scoring time (mask all-zero, empty
    probability list downstream); the action dimensionality comes from the
    first non-empty row.
    """
    n = len(col)
    ks = [len(row) for row in col]
    k_max = max(ks) if ks else 1
    k_max = max(k_max, 1)
    d = 1
    for row in col:
        if len(row):
            d = len(np.asarray(row[0]).ravel())
            break
    out = np.zeros((n, k_max, d), dtype=np.float32)
    mask = np.zeros((n, k_max), dtype=np.float32)
    for i, row in enumerate(col):
        for k, vec in enumerate(row):
            out[i, k] = np.asarray(vec, dtype=np.float32).ravel()
            mask[i, k] = 1.0
    return out, mask


def _epsilon_greedy(scores, mask, epsilon):
    """Exploration distribution over valid actions: lowest predicted cost gets
    1 - eps + eps/K, the rest eps/K each (VW --cb_explore_adf epsilon)."""
    import jax.numpy as jnp

    # max(k_valid, 1): a zero-action row divides by 1 and, with an all-zero
    # mask, still yields all-zero probabilities instead of NaN
    k_valid = jnp.maximum(jnp.sum(mask, axis=-1, keepdims=True), 1.0)
    masked = jnp.where(mask > 0, scores, jnp.inf)
    best = jnp.argmin(masked, axis=-1)
    base = (epsilon / k_valid) * mask
    one_hot = (jnp.arange(mask.shape[-1]) == best[..., None]).astype(
        jnp.float32) * mask
    return base + (1.0 - epsilon) * one_hot


def _softmax_policy(scores, mask, lam):
    """VW --softmax: p(a) proportional to exp(-lambda * cost_score(a)) over the
    valid actions (scores predict COST, so lower score -> higher probability;
    lambda -> inf recovers greedy, 0 uniform)."""
    import jax.numpy as jnp

    z = jnp.where(mask > 0, -lam * scores, -jnp.inf)
    z = z - jnp.max(jnp.where(mask > 0, z, -jnp.inf), axis=-1, keepdims=True)
    e = jnp.where(mask > 0, jnp.exp(z), 0.0)
    denom = jnp.maximum(jnp.sum(e, axis=-1, keepdims=True), 1e-38)
    return e / denom


def _greedy_policy(scores, mask):
    """Pure exploit: probability 1 on the lowest-cost valid action (the
    post-tau regime of VW --first)."""
    import jax.numpy as jnp

    masked = jnp.where(mask > 0, scores, jnp.inf)
    best = jnp.argmin(masked, axis=-1)
    return (jnp.arange(mask.shape[-1]) == best[..., None]).astype(
        jnp.float32) * mask


def _uniform_policy(mask):
    import jax.numpy as jnp

    k_valid = jnp.maximum(jnp.sum(mask, axis=-1, keepdims=True), 1.0)
    return mask / k_valid


def _vote_policy(greedy_choices, mask, n_policies, smooth=0.0):
    """Ensemble vote distribution (VW --bag / --cover): each policy's greedy
    choice casts one vote; probabilities are vote fractions over valid
    actions, optionally mixed with ``smooth`` * uniform (cover's residual
    uniform exploration)."""
    import jax.numpy as jnp

    K = mask.shape[-1]
    votes = jnp.zeros(K).at[greedy_choices].add(1.0) / n_policies
    votes = votes * mask
    # smooth == 0.0 is the identity, so this stays unconditional (the cover
    # path passes a traced decay that cannot drive Python control flow)
    votes = (1.0 - smooth) * votes + smooth * _uniform_policy(mask)
    # renormalize over valid actions (votes on masked rows are dropped)
    denom = jnp.maximum(jnp.sum(votes, axis=-1, keepdims=True), 1e-38)
    return jnp.where(jnp.sum(mask) > 0, votes / denom, votes)


class _ContextualBanditParams(HasFeaturesCol, HasLabelCol, HasPredictionCol):
    sharedCol = Param("sharedCol", "column of shared-context vectors", "shared")
    additionalSharedFeatures = Param(
        "additionalSharedFeatures", "Extra shared-context vector columns "
        "concatenated onto sharedCol (reference: VowpalWabbitContextualBandit "
        "additionalSharedFeatures)", None, TypeConverters.to_list_string)
    chosenActionCol = Param("chosenActionCol",
                            "1-based index of the logged action",
                            "chosenAction")

    def _shared_block(self, dataset) -> np.ndarray:
        """Shared-context matrix: sharedCol plus any
        additionalSharedFeatures columns, concatenated feature-wise."""
        cols = [self.get_or_default("sharedCol")]
        cols += list(self.get_or_default("additionalSharedFeatures") or [])
        blocks = []
        for c in cols:
            b = np.asarray(dataset[c], dtype=np.float32)
            if b.ndim == 1:
                b = b[:, None]
            blocks.append(b)
        return blocks[0] if len(blocks) == 1 else np.concatenate(blocks,
                                                                 axis=1)
    probabilityCol = Param("probabilityCol",
                           "logged probability of the chosen action",
                           "probability")
    explorationPolicy = Param(
        "explorationPolicy",
        "cb_explore_adf exploration family (reference passes these through "
        "VW's args, VowpalWabbitBase.scala:77-81): 'epsilon' "
        "(epsilon-greedy), 'softmax' (p ~ exp(-lambda*score), "
        "softmaxLambda), 'bag' (bagSize bootstrap policies vote), 'cover' "
        "(coverSize diverse policies, online-cover cost adjustment with "
        "psi, residual uniform smoothing), 'first' (uniform for the first "
        "tau examples, then greedy)", "epsilon", TypeConverters.to_string)
    epsilon = Param("epsilon", "exploration epsilon", 0.05,
                    TypeConverters.to_float)
    softmaxLambda = Param("softmaxLambda",
                          "softmax temperature (VW --lambda)", 1.0,
                          TypeConverters.to_float)
    bagSize = Param("bagSize", "policies in the bag ensemble (VW --bag N)",
                    5, TypeConverters.to_int)
    coverSize = Param("coverSize", "policies in the cover ensemble "
                      "(VW --cover N)", 5, TypeConverters.to_int)
    psi = Param("psi", "cover diversity strength (VW --psi)", 1.0,
                TypeConverters.to_float)
    tau = Param("tau", "first-policy uniform-exploration horizon "
                "(VW --first tau)", 100, TypeConverters.to_int)
    learningRate = Param("learningRate", "sgd learning rate", 0.5,
                         TypeConverters.to_float)
    numPasses = Param("numPasses", "passes over the data", 1,
                      TypeConverters.to_int)
    useInteractions = Param("useInteractions",
                            "include the shared x action interaction block "
                            "(the ``-q sa`` VW flag; without it a linear ADF "
                            "scorer cannot condition actions on context)",
                            True, TypeConverters.to_bool)


class VowpalWabbitContextualBandit(Estimator, _ContextualBanditParams):
    """cb_explore_adf trainer (reference:
    VowpalWabbitContextualBandit.scala:108-260)."""

    parallelism = Param("parallelism", "threads for multi-config fit", 1,
                        TypeConverters.to_int)

    def _validate(self, dataset: Dataset):
        chosen = dataset.array(self.get_or_default("chosenActionCol"))
        if np.any(chosen == 0):
            raise ValueError("chosen action index is 1-based - cannot be 0 "
                             "(reference: VowpalWabbitContextualBandit.scala:232)")
        if np.any(chosen < 0):
            raise ValueError("chosen action index must be positive")
        counts = np.asarray([len(row) for row in
                             dataset[self.get_or_default("featuresCol")]])
        if np.any(chosen > counts):
            bad = int(np.argmax(chosen > counts))
            raise ValueError(
                f"row {bad}: chosen action {int(chosen[bad])} exceeds its "
                f"{int(counts[bad])} offered actions")
        probs = dataset.array(self.get_or_default("probabilityCol"))
        if np.any(probs <= 0):
            raise ValueError("logged probability must be > 0 for every row "
                             "(importance weights divide by it)")

    def fit(self, dataset: Dataset) -> "VowpalWabbitContextualBanditModel":
        import jax
        import jax.numpy as jnp
        from jax import lax

        self._validate(dataset)
        shared = self._shared_block(dataset)
        actions, mask = _stack_actions(
            dataset[self.get_or_default("featuresCol")])
        chosen = dataset.array(self.get_or_default("chosenActionCol")
                               ).astype(np.int32) - 1  # to 0-based
        cost = dataset.array(self.get_or_default("labelCol")).astype(np.float32)
        logged_p = dataset.array(self.get_or_default("probabilityCol")
                                 ).astype(np.float32)

        eps = float(self.get_or_default("epsilon"))
        lr = float(self.get_or_default("learningRate"))
        n_passes = int(self.get_or_default("numPasses"))
        interact = bool(self.get_or_default("useInteractions"))
        policy = self.get_or_default("explorationPolicy")
        lam = float(self.get_or_default("softmaxLambda"))
        psi = float(self.get_or_default("psi"))
        tau = int(self.get_or_default("tau"))
        if policy in ("epsilon", "softmax", "first"):
            N = 1
        elif policy == "bag":
            N = max(1, int(self.get_or_default("bagSize")))
        elif policy == "cover":
            N = max(1, int(self.get_or_default("coverSize")))
        else:
            raise ValueError(
                f"unknown explorationPolicy {policy!r}: use epsilon, "
                "softmax, bag, cover or first")
        d_s, d_a = shared.shape[1], actions.shape[2]
        K = actions.shape[1]
        n = shared.shape[0]

        # bag: per-example per-policy Poisson(1) bootstrap weights (VW's
        # online bootstrap), deterministic seed
        if policy == "bag":
            boot = np.asarray(
                np.random.default_rng(0).poisson(1.0, size=(n, N)),
                np.float32)
        else:
            boot = np.ones((n, N), np.float32)

        def policy_probs(scores_all, amask, greedy_all, t):
            """Exploration distribution of the CURRENT ensemble state —
            feeds the IPS/SNIPS evaluation counters."""
            if policy == "epsilon":
                return _epsilon_greedy(scores_all[0], amask, eps)
            if policy == "softmax":
                return _softmax_policy(scores_all[0], amask, lam)
            if policy == "first":
                return jnp.where(t < tau, _uniform_policy(amask),
                                 _greedy_policy(scores_all[0], amask))
            smooth = (jnp.clip(psi * lax.rsqrt(t + 1.0), 0.0, 1.0)
                      if policy == "cover" else 0.0)
            return _vote_policy(greedy_all, amask, N, smooth)

        def example_step(carry, xs):
            ws, wa, wq, g2s, g2a, g2q, m, t = carry
            xs_shared, xa, amask, k_star, c, p_log, bw = xs
            # per-policy scores [N, K]
            scores_all = (jnp.einsum("kd,nd->nk", xa, wa)
                          + jnp.einsum("s,ns->n", xs_shared, ws)[:, None])
            if interact:
                scores_all = scores_all + jnp.einsum(
                    "s,nsd,kd->nk", xs_shared, wq, xa)
            masked = jnp.where(amask[None, :] > 0, scores_all, jnp.inf)
            greedy_all = jnp.argmin(masked, axis=-1)        # [N]
            probs = policy_probs(scores_all, amask, greedy_all, t)
            p_eval = probs[k_star]

            # IPS/SNIPS counters (reference addExample semantics)
            p_over_p = p_eval / p_log
            live = (p_eval > 0).astype(jnp.float32)
            m = (m[0] + live * c * p_over_p,               # snips numerator
                 m[1] + 1.0,                               # total events
                 m[2] + live * p_over_p,                   # snips denominator
                 m[3] + live,                              # offline events
                 jnp.maximum(m[4], live * c * p_over_p))   # max ips term

            # MTR update on the chosen action, importance 1/p_log — one
            # update per ensemble member (static unroll over small N)
            x_a = xa[k_star]
            for i in range(N):
                ci = c
                if policy == "cover" and i > 0:
                    # online-cover diversity (Agarwal et al. 2014; VW
                    # --cover --psi): discount the cost by how rarely the
                    # PREVIOUS policies pick the logged action, pushing
                    # policy i toward actions the mix neglects
                    prev_votes = jnp.sum(
                        (greedy_all[:i] == k_star).astype(jnp.float32))
                    p_prev = jnp.maximum(prev_votes / i, 1.0 / K)
                    ci = c - psi / (K * p_prev)
                grad = bw[i] * (scores_all[i, k_star] - ci) / p_log
                gs, ga = grad * xs_shared, grad * x_a
                g2s = g2s.at[i].add(gs * gs)
                g2a = g2a.at[i].add(ga * ga)
                ws = ws.at[i].add(-lr * gs * lax.rsqrt(g2s[i] + 1e-6))
                wa = wa.at[i].add(-lr * ga * lax.rsqrt(g2a[i] + 1e-6))
                if interact:
                    gq = grad * jnp.outer(xs_shared, x_a)
                    g2q = g2q.at[i].add(gq * gq)
                    wq = wq.at[i].add(-lr * gq * lax.rsqrt(g2q[i] + 1e-6))
            return (ws, wa, wq, g2s, g2a, g2q, m, t + 1.0), None

        @jax.jit
        def train(xs_shared, xa, amask, k_star, c, p_log, bw):
            carry = (jnp.zeros((N, d_s)), jnp.zeros((N, d_a)),
                     jnp.zeros((N, d_s, d_a)),
                     jnp.zeros((N, d_s)), jnp.zeros((N, d_a)),
                     jnp.zeros((N, d_s, d_a)),
                     (jnp.float32(0), jnp.float32(0), jnp.float32(0),
                      jnp.float32(0), jnp.float32(0)), jnp.float32(0))

            def one_pass(carry, _):
                carry, _ = lax.scan(
                    example_step, carry,
                    (xs_shared, xa, amask, k_star, c, p_log, bw))
                return carry, None

            carry, _ = lax.scan(one_pass, carry, None, length=n_passes)
            return carry

        ws, wa, wq, _, _, _, m, _ = train(
            jnp.asarray(shared), jnp.asarray(actions), jnp.asarray(mask),
            jnp.asarray(chosen), jnp.asarray(cost), jnp.asarray(logged_p),
            jnp.asarray(boot))
        metrics = ContextualBanditMetrics(
            float(m[0]), float(m[1]), float(m[2]), float(m[3]), float(m[4]))

        model = VowpalWabbitContextualBanditModel(
            shared_weights=np.asarray(ws), action_weights=np.asarray(wa),
            interaction_weights=np.asarray(wq) if interact else None,
            metrics=metrics)
        self._copy_params_to(model)
        return model

    def fit_multiple(self, dataset: Dataset,
                     param_maps: List[Dict]) -> List["VowpalWabbitContextualBanditModel"]:
        """Fit one model per param map on a thread pool (reference:
        VowpalWabbitContextualBandit.fit(dataset, paramMaps):268-285)."""
        n_jobs = int(self.get_or_default("parallelism"))

        def fit_one(pm: Dict):
            est = VowpalWabbitContextualBandit()
            self._copy_params_to(est)
            est.set(**pm)
            return est.fit(dataset)

        if n_jobs <= 1:
            return [fit_one(pm) for pm in param_maps]
        with ThreadPoolExecutor(max_workers=n_jobs) as pool:
            return list(pool.map(fit_one, param_maps))


class VowpalWabbitContextualBanditModel(Model, _ContextualBanditParams):
    """Scores actions and emits the epsilon-greedy probability vector per row
    (reference: VowpalWabbitContextualBanditModel.transform:305-350)."""

    sharedWeights = Param("sharedWeights", "shared linear block", None,
                          is_complex=True)
    actionWeights = Param("actionWeights", "ADF action linear block", None,
                          is_complex=True)
    interactionWeights = Param("interactionWeights",
                               "shared x action interaction block", None,
                               is_complex=True)

    def __init__(self, shared_weights: Optional[np.ndarray] = None,
                 action_weights: Optional[np.ndarray] = None,
                 interaction_weights: Optional[np.ndarray] = None,
                 metrics: Optional[ContextualBanditMetrics] = None, **kwargs):
        super().__init__(**kwargs)
        if shared_weights is not None:
            self.set(sharedWeights=np.asarray(shared_weights))
        if action_weights is not None:
            self.set(actionWeights=np.asarray(action_weights))
        if interaction_weights is not None:
            self.set(interactionWeights=np.asarray(interaction_weights))
        self.metrics = metrics or ContextualBanditMetrics()

    def get_performance_statistics(self) -> Dataset:
        m = self.metrics
        return Dataset({
            "ipsEstimate": np.asarray([m.get_ips_estimate()
                                       if m.total_events else np.nan]),
            "snipsEstimate": np.asarray([m.get_snips_estimate()
                                         if m.snips_denominator else np.nan]),
            "totalEvents": np.asarray([m.total_events]),
            "offlinePolicyEvents": np.asarray([m.offline_policy_events]),
        })

    def transform(self, dataset: Dataset) -> Dataset:
        import jax.numpy as jnp

        ws = np.asarray(self.get_or_default("sharedWeights"))
        wa = np.asarray(self.get_or_default("actionWeights"))
        if ws.ndim == 1:      # models saved before the ensemble layout
            ws, wa = ws[None, :], wa[None, :]
        shared = self._shared_block(dataset)
        actions, mask = _stack_actions(
            dataset[self.get_or_default("featuresCol")])
        policy = self.get_or_default("explorationPolicy")
        N = ws.shape[0]

        # per-policy scores [n, N, K]
        scores = (np.einsum("nkd,pd->npk", actions, wa)
                  + np.einsum("ns,ps->np", shared, ws)[:, :, None])
        wq = self.get_or_default("interactionWeights")
        if wq is not None:
            wq = np.asarray(wq)
            if wq.ndim == 2:
                wq = wq[None, :, :]
            scores = scores + np.einsum("ns,psd,nkd->npk", shared, wq,
                                        actions)
        # one policy definition shared with training (no train/serve drift)
        t_seen = float(self.metrics.total_events)
        if policy == "softmax":
            probs = np.asarray(_softmax_policy(
                jnp.asarray(scores[:, 0]), jnp.asarray(mask),
                float(self.get_or_default("softmaxLambda"))))
        elif policy == "first":
            # exploit only once training consumed its tau uniform examples;
            # a model fit on fewer is still in the uniform phase (VW --first)
            if t_seen < int(self.get_or_default("tau")):
                probs = np.asarray(_uniform_policy(jnp.asarray(mask)))
            else:
                probs = np.asarray(_greedy_policy(jnp.asarray(scores[:, 0]),
                                                  jnp.asarray(mask)))
        elif policy in ("bag", "cover"):
            import jax

            masked = np.where(mask[:, None, :] > 0, scores, np.inf)
            greedy = masked.argmin(axis=-1)                # [n, N]
            # same vote + smoothing definition as training (cover's decay
            # evaluated at the end-of-training event count)
            smooth = (float(np.clip(
                float(self.get_or_default("psi")) / (t_seen + 1.0) ** 0.5,
                0.0, 1.0)) if policy == "cover" else 0.0)
            probs = np.asarray(jax.vmap(
                _vote_policy, in_axes=(0, 0, None, None))(
                jnp.asarray(greedy), jnp.asarray(mask), N, smooth))
        else:
            probs = np.asarray(_epsilon_greedy(
                jnp.asarray(scores[:, 0]), jnp.asarray(mask),
                float(self.get_or_default("epsilon"))))
        out = [probs[i, mask[i] > 0].tolist() for i in range(len(probs))]
        return dataset.with_column(
            self.get_or_default("predictionCol") or "prediction", out)

    def _save_extra(self, path: str) -> None:
        import json
        import os
        m = self.metrics
        with open(os.path.join(path, "metrics.json"), "w") as f:
            json.dump(vars(m), f)

    def _load_extra(self, path: str) -> None:
        import json
        import os
        p = os.path.join(path, "metrics.json")
        self.metrics = ContextualBanditMetrics()
        if os.path.exists(p):
            with open(p) as f:
                self.metrics.__dict__.update(json.load(f))


class VectorZipper(Transformer, HasInputCols, HasOutputCol):
    """Combine input columns into a per-row sequence — the action-assembly
    step for ADF (reference: vw/VectorZipper.scala)."""

    def transform(self, dataset: Dataset) -> Dataset:
        cols = [dataset[c] for c in self.get_or_default("inputCols")]
        zipped = [[col[i] for col in cols] for i in range(len(dataset))]
        return dataset.with_column(self.get_or_default("outputCol"), zipped)


class VowpalWabbitInteractions(Transformer, HasInputCols, HasOutputCol):
    """FNV-1 cross-namespace interaction features over dense vector columns
    (reference: vw/VowpalWabbitInteractions.scala — the ``-q`` analog for
    non-VW learners). Emits hashed sparse ``{out}_indices/{out}_values``."""

    numBits = Param("numBits", "feature space is 2^numBits", 18,
                    TypeConverters.to_int)
    sumCollisions = Param("sumCollisions", "sum values on hash collision",
                          True, TypeConverters.to_bool)

    def transform(self, dataset: Dataset) -> Dataset:
        fnv_prime = 16777619
        num_bits = int(self.get_or_default("numBits"))
        mask = (1 << num_bits) - 1
        sum_coll = self.get_or_default("sumCollisions")
        in_cols = self.get_or_default("inputCols")
        mats = [np.asarray(dataset[c], dtype=np.float64) for c in in_cols]
        for m in mats:
            if m.ndim != 2:
                raise ValueError("VowpalWabbitInteractions needs dense "
                                 "vector columns of shape [n, d]")

        n = len(dataset)
        rows: List[Dict[int, float]] = []
        nnz_max = 1
        for i in range(n):
            active = []
            for m in mats:
                nz = np.nonzero(m[i])[0]
                active.append([(int(j), float(m[i, j])) for j in nz])
            acc: Dict[int, float] = {}

            def interact(idx: int, value: float, ns: int):
                if ns == len(active):
                    key = mask & idx
                    if key in acc and sum_coll:
                        acc[key] += value
                    else:
                        acc[key] = value
                    return
                idx1 = (idx * fnv_prime) & 0xFFFFFFFF
                for j, v in active[ns]:
                    interact(idx1 ^ j, value * v, ns + 1)

            interact(0, 1.0, 0)
            rows.append(acc)
            nnz_max = max(nnz_max, len(acc))

        indices = np.zeros((n, nnz_max), dtype=np.int32)
        values = np.zeros((n, nnz_max), dtype=np.float32)
        for i, acc in enumerate(rows):
            if acc:
                indices[i, :len(acc)] = np.fromiter(acc.keys(), np.int32,
                                                    len(acc))
                values[i, :len(acc)] = np.fromiter(acc.values(), np.float32,
                                                   len(acc))
        out = self.get_or_default("outputCol")
        return dataset.with_columns({f"{out}_indices": indices,
                                     f"{out}_values": values})
