"""VowpalWabbit-style estimators: online linear learners on the TPU mesh.

Parity with the reference's VW stages (reference: vw/VowpalWabbitBase.scala:71-521,
VowpalWabbitClassifier.scala, VowpalWabbitRegressor.scala,
VowpalWabbitBaseModel.scala:23-115). Param names match the reference; the
``passThroughArgs`` escape hatch accepts a VW-style argument string and maps
the supported subset onto SGDConfig (the reference forwards it to C++;
:77-81), so existing VW invocations port over.
"""

from __future__ import annotations

import os
import shlex
import time
from typing import Optional

import numpy as np

from ...core.dataset import Dataset
from ...core.params import (HasFeaturesCol, HasLabelCol, HasPredictionCol,
                            HasProbabilityCol, HasRawPredictionCol,
                            HasWeightCol, Param, TypeConverters)
from ...core.pipeline import Estimator, Model
from ...utils.stopwatch import StopWatch
from .sgd import SGDConfig, predict_sgd, train_sgd

# VW's hardcoded intercept ("constant") feature index — every example gets
# it unless --noconstant (reference: the vw core's `constant` symbol; the
# JNI learners inherit it from libvw)
VW_CONSTANT_INDEX = 11650396


class _VowpalWabbitBaseParams(HasLabelCol, HasFeaturesCol, HasWeightCol,
                              HasPredictionCol):
    featuresCol = Param("featuresCol", "Base name of the hashed features columns "
                        "(expects <name>_indices / <name>_values)", "features",
                        TypeConverters.to_string)
    numBits = Param("numBits", "Weight space is 2^numBits", 18, TypeConverters.to_int)
    learningRate = Param("learningRate", "SGD learning rate", 0.5,
                         TypeConverters.to_float)
    powerT = Param("powerT", "Learning-rate decay exponent", 0.5,
                   TypeConverters.to_float)
    initialT = Param("initialT", "Initial example count t", 0.0,
                     TypeConverters.to_float)
    l1 = Param("l1", "L1 regularization", 0.0, TypeConverters.to_float)
    l2 = Param("l2", "L2 regularization", 0.0, TypeConverters.to_float)
    numPasses = Param("numPasses", "Passes over the data "
                      "(sync/AllReduce at each pass end)", 1, TypeConverters.to_int)
    adaptive = Param("adaptive", "AdaGrad-style adaptive updates (--adaptive)",
                     True, TypeConverters.to_bool)
    batchSize = Param("batchSize", "Minibatch size of the compiled SGD scan "
                      "(1 = strict online order)", 128, TypeConverters.to_int)
    passThroughArgs = Param("passThroughArgs", "VW-style argument string", "",
                            TypeConverters.to_string)
    noConstant = Param("noConstant", "Drop VW's implicit intercept feature "
                       "(--noconstant)", False, TypeConverters.to_bool)
    initialModel = Param("initialModel",
                         "Warm-start weights: a raw weight array, or a "
                         "fitted VW model (preferred — its constant-feature "
                         "format marker is then checked against this "
                         "estimator's noConstant; raw pre-v2 arrays require "
                         "noConstant=True by hand)", None, is_complex=True)
    checkpointDir = Param("checkpointDir",
                          "Pass-level checkpoint directory: each finished "
                          "pass saves full optimizer state and training "
                          "resumes from the newest one (preemption-safe)",
                          None, TypeConverters.to_string)
    additionalFeatures = Param(
        "additionalFeatures", "Additional hashed feature column base names "
        "appended to featuresCol — each column acts as a VW namespace "
        "(reference: VowpalWabbitBase additionalFeatures)", None,
        TypeConverters.to_list_string)
    ignoreNamespaces = Param(
        "ignoreNamespaces", "Drop feature columns (namespaces) whose name "
        "starts with one of these letters (VW --ignore; here a namespace "
        "is a features column, so the first letter of its base name is "
        "matched)", None, TypeConverters.to_string)
    useBarrierExecutionMode = Param(
        "useBarrierExecutionMode", "Ignored: SPMD gang scheduling is "
        "inherent on the mesh", False, TypeConverters.to_bool)
    performanceStatistics = Param(
        "performanceStatistics", "Accepted for reference parity: the "
        "fitted model's get_performance_statistics() returns the same "
        "TrainingStats table the reference stored under this param", None,
        is_complex=True)

    def _parse_args(self) -> dict:
        """Map the supported subset of VW command-line args onto config."""
        out = {}
        args = self.get_or_default("passThroughArgs")
        if not args:
            return out
        toks = shlex.split(args)
        i = 0
        while i < len(toks):
            t = toks[i]

            def val():
                return toks[i + 1]

            if t in ("-b", "--bit_precision"):
                out["num_bits"] = int(val()); i += 2
            elif t in ("-l", "--learning_rate"):
                out["learning_rate"] = float(val()); i += 2
            elif t == "--l1":
                out["l1"] = float(val()); i += 2
            elif t == "--l2":
                out["l2"] = float(val()); i += 2
            elif t == "--passes":
                out["num_passes"] = int(val()); i += 2
            elif t == "--adaptive":
                out["adaptive"] = True; i += 1
            elif t == "--sgd":
                out["adaptive"] = False; i += 1
            elif t == "--bfgs":
                # VW batch mode: full-batch L-BFGS, --passes bounds iterations
                out["optimizer"] = "bfgs"; i += 1
            elif t == "--loss_function":
                out["loss"] = val(); i += 2
            elif t == "--power_t":
                out["power_t"] = float(val()); i += 2
            elif t == "--initial_t":
                out["initial_t"] = float(val()); i += 2
            elif t == "--quantile_tau":
                out["quantile_tau"] = float(val()); i += 2
            else:
                i += 1  # unknown args tolerated (defaults live downstream)
        return out

    def _sgd_config(self, default_loss: str) -> SGDConfig:
        cfg = SGDConfig(
            num_bits=self.get_or_default("numBits"),
            loss=default_loss,
            learning_rate=self.get_or_default("learningRate"),
            power_t=self.get_or_default("powerT"),
            initial_t=self.get_or_default("initialT"),
            l1=self.get_or_default("l1"),
            l2=self.get_or_default("l2"),
            adaptive=self.get_or_default("adaptive"),
            num_passes=self.get_or_default("numPasses"),
            batch_size=self.get_or_default("batchSize"),
        )
        overrides = self._parse_args()
        return cfg._replace(**overrides) if overrides else cfg

    def _effective_no_constant(self) -> bool:
        """The constant feature is dropped by EITHER the noConstant Param or
        a --noconstant token in passThroughArgs (_features honors both);
        format-compatibility checks must compare this effective flag."""
        return bool(self.get_or_default("noConstant")
                    or "--noconstant" in shlex.split(
                        self.get_or_default("passThroughArgs")))

    def _features(self, dataset: Dataset):
        bases = [self.get_or_default("featuresCol")]
        bases += list(self.get_or_default("additionalFeatures") or [])
        ign = self.get_or_default("ignoreNamespaces") or ""
        kept = [b for b in bases if not (b and b[0] in ign)]
        if not kept:
            raise ValueError(
                f"ignoreNamespaces={ign!r} drops every features column "
                f"({bases}); no feature columns remain")
        if len(kept) == 1:       # common case: no extra copy
            idx = dataset.array(f"{kept[0]}_indices", np.int32)
            val = dataset.array(f"{kept[0]}_values", np.float32)
        else:
            idx = np.concatenate(
                [dataset.array(f"{b}_indices", np.int32) for b in kept],
                axis=1)
            val = np.concatenate(
                [dataset.array(f"{b}_values", np.float32) for b in kept],
                axis=1)
        no_const = self._effective_no_constant()
        if not no_const:
            # VW adds an implicit intercept ("constant") feature to every
            # example at its hardcoded index (vw's `constant = 11650396`),
            # folded by the same 2^b weight-table mask as everything else.
            # Shared by fit and transform so feature identity always agrees.
            n = idx.shape[0]
            idx = np.concatenate(
                [idx, np.full((n, 1), VW_CONSTANT_INDEX, np.int32)], axis=1)
            val = np.concatenate([val, np.ones((n, 1), np.float32)], axis=1)
        return idx, val

    def _resolve_initial_weights(self, cfg: SGDConfig):
        init = self.get_or_default("initialModel")
        if init is not None and hasattr(init, "weights"):
            # fitted-model warm start: the model carries its constant-feature
            # format (pre-v2 loads set noConstant=True in _load_extra); its
            # weight table only matches an estimator with the same EFFECTIVE
            # setting (Param or --noconstant passthrough, like _features)
            m_nc = (init._effective_no_constant()
                    if hasattr(init, "_effective_no_constant")
                    else bool(init.get_or_default("noConstant")))
            e_nc = self._effective_no_constant()
            if m_nc != e_nc:
                raise ValueError(
                    f"initialModel was trained with noConstant={m_nc} but "
                    f"this estimator has noConstant={e_nc}; set them equal "
                    "(models saved before the implicit constant feature "
                    "existed load with noConstant=True)")
            init = init.weights
        if init is not None and len(init) != (1 << cfg.num_bits):
            raise ValueError(
                f"initialModel weight table has {len(init)} entries but "
                f"numBits={cfg.num_bits} implies {1 << cfg.num_bits}; set "
                "numBits to match the warm-start model's")
        return init

    def _fit_weights_streamed(self, index_path, value_path, label_path,
                              weight_path, cfg: SGDConfig,
                              chunk_rows):  # None -> trainer default
        """Out-of-core fit: pre-hashed .npy shards -> weights + stats.

        The streamed counterpart of ``_fit_weights`` (reference VW trains
        from streamed Spark partitions; here the stream is explicit disk
        shards, mirroring GBDT's ``construct(path=...)``). Shards carry
        ALREADY-HASHED features — the output of
        :class:`VowpalWabbitFeaturizer` written chunk-wise — including the
        constant feature if the estimator expects one (noConstant=False),
        since hashing happens at write time, not here.
        """
        if cfg.optimizer == "bfgs":
            raise ValueError(
                "--bfgs is a batch solver over in-memory arrays; the "
                "streamed path supports the sgd optimizer only")
        if self.get_or_default("checkpointDir"):
            raise ValueError(
                "checkpointDir is not supported with streamed fits yet; "
                "chunk-level state already bounds re-run cost")
        if self.get_or_default("weightCol") and weight_path is None:
            raise ValueError(
                "weightCol is set but no weight_path was given; streamed "
                "fits read sample weights from shards — pass weight_path= "
                "or clear weightCol to train unweighted")
        from ..gbdt.ingest import ShardedMatrixSource
        from .sgd import train_sgd_streamed
        init = self._resolve_initial_weights(cfg)
        # coerce once; train_sgd_streamed accepts sources, so the shard
        # headers are parsed a single time and n comes from the same object
        label_src = ShardedMatrixSource.coerce(label_path)
        n = label_src.n
        sw_time = StopWatch()
        with sw_time:
            weights = train_sgd_streamed(
                index_path, value_path, label_src, weight_path,
                cfg=cfg, initial_weights=init, chunk_rows=chunk_rows)
        stats = {
            "numExamples": n,
            "learnTimeNs": sw_time.elapsed_ns(),
            "numBits": cfg.num_bits,
            "numPasses": cfg.num_passes,
            "numWeights": int((weights != 0).sum()),
        }
        return weights, stats

    def _fit_weights(self, dataset: Dataset, cfg: SGDConfig):
        idx, val = self._features(dataset)
        # VW semantics: the weight table masks hashes by 2^numBits (-b at
        # access time), so a featurizer hashed wider than the learner folds
        # by masking — never by index clamping
        idx = idx & ((1 << cfg.num_bits) - 1)
        y = dataset.array(self.get_or_default("labelCol"), np.float32)
        wcol = self.get_or_default("weightCol")
        sw = dataset.array(wcol, np.float32) if wcol else None
        init = self._resolve_initial_weights(cfg)
        ckpt_dir = self.get_or_default("checkpointDir")
        sw_time = StopWatch()
        with sw_time:
            if cfg.optimizer == "bfgs":
                if ckpt_dir:
                    raise ValueError(
                        "checkpointDir is not supported with --bfgs "
                        "(batch iterations are cheap to rerun; step-level "
                        "checkpointing covers the sgd path)")
                from .sgd import train_bfgs
                weights = train_bfgs(idx, val, y, sw, cfg,
                                     initial_weights=init)
            elif ckpt_dir:
                from .sgd import train_sgd_checkpointed
                weights = train_sgd_checkpointed(idx, val, y, sw, cfg,
                                                 ckpt_dir,
                                                 initial_weights=init)
            else:
                weights = train_sgd(idx, val, y, sw, cfg,
                                    initial_weights=init)
        stats = {
            "numExamples": len(y),
            "learnTimeNs": sw_time.elapsed_ns(),
            "numBits": cfg.num_bits,
            "numPasses": cfg.num_passes,
            "numWeights": int((weights != 0).sum()),
        }
        return weights, stats


class _VowpalWabbitModelBase(Model, _VowpalWabbitBaseParams):
    """Trained linear model (reference: vw/VowpalWabbitBaseModel.scala:23-115)."""

    def __init__(self, weights: Optional[np.ndarray] = None, stats: Optional[dict] = None,
                 **kwargs):
        super().__init__(**kwargs)
        self.weights = weights
        self.stats = stats or {}

    def _margin(self, dataset: Dataset) -> np.ndarray:
        idx, val = self._features(dataset)
        # same 2^numBits weight-table mask as training
        idx = idx & (len(self.weights) - 1)
        return predict_sgd(idx, val, self.weights)

    def predict_margin_streamed(self, index_path, value_path, *,
                                chunk_rows: int = 262_144, out_dir=None):
        """Margins over pre-hashed ``.npy`` shards in bounded row chunks —
        the scoring side of the out-of-core story (``fit_streamed`` is
        the training side). Chunks are independent dot products, so
        streamed margins equal in-memory margins bit-for-bit. Index
        shards fold by ``2^numBits`` at read time like ``fit_streamed``.
        Returns concatenated margins, or shard paths with ``out_dir``.
        """
        import jax.numpy as jnp

        from ...io.streaming import stream_apply
        from ..gbdt.ingest import ShardedMatrixSource

        idx_src = ShardedMatrixSource.coerce(index_path)
        val_src = ShardedMatrixSource.coerce(value_path)
        if idx_src.n != val_src.n:
            raise ValueError(
                f"index rows {idx_src.n} != value rows {val_src.n}")
        if idx_src.row_shape != val_src.row_shape:
            raise ValueError(
                f"index row shape {idx_src.row_shape} != value row shape "
                f"{val_src.row_shape}")
        # swapped-argument guard: both sources are [n, nnz], but float
        # index shards silently truncate to garbage hashes
        probe = idx_src.read(0, 1, dtype=None)
        if probe.size and probe.dtype.kind not in "iu":
            raise ValueError(
                f"index shards must be integer dtype, got {probe.dtype} — "
                "were index_path and value_path swapped?")
        if out_dir is not None:
            # stream_apply guards out_dir against the VALUE source only;
            # overwriting the index shards mid-stream must also be refused
            out_real = os.path.realpath(os.fspath(out_dir))
            if any(os.path.realpath(os.path.dirname(p)) == out_real
                   for p in idx_src.paths):
                raise ValueError(
                    "out_dir contains the index shards; writing outputs "
                    "there would delete inputs mid-stream")
        mask = len(self.weights) - 1
        w_dev = jnp.asarray(self.weights)   # one upload for all chunks
        # stream_apply's contract walks [0, n) in order, one bounded chunk
        # at a time — the cursor below pairs each value chunk with the
        # matching index rows without loading the index side whole
        pos = [0]

        def score(val_chunk: np.ndarray) -> np.ndarray:
            start = pos[0]
            stop = start + len(val_chunk)
            pos[0] = stop
            idx = (idx_src.read(start, stop, dtype=None)
                   .astype(np.int64) & mask).astype(np.int32)
            return predict_sgd(idx, val_chunk, w_dev)

        return stream_apply(val_src, score, chunk_rows=chunk_rows,
                            out_dir=out_dir)

    def get_performance_statistics(self) -> Dataset:
        """Diagnostics DataFrame parity (reference: VowpalWabbitBase.scala:27-46
        TrainingStats surfaced at VowpalWabbitBaseModel.scala:86-92)."""
        return Dataset({k: np.asarray([v]) for k, v in self.stats.items()})

    def get_readable_model(self) -> Dataset:
        """Non-zero weights as (index, weight) rows
        (readable-model dump parity, VowpalWabbitBaseModel.scala:70-84)."""
        nz = np.nonzero(self.weights)[0]
        return Dataset({"index": nz.astype(np.int64),
                        "weight": self.weights[nz].astype(np.float64)})

    def _save_extra(self, path: str) -> None:
        import os
        # format marker v2: weights were trained WITH the implicit constant
        # feature (unless noConstant); its absence on load identifies models
        # saved before the constant feature existed
        np.savez_compressed(os.path.join(path, "weights"), w=self.weights,
                            vw_format=np.asarray(2),
                            **{f"stat_{k}": np.asarray(v) for k, v in self.stats.items()})

    def _load_extra(self, path: str) -> None:
        import os
        z = np.load(os.path.join(path, "weights.npz"))
        self.weights = z["w"]
        self.stats = {k[5:]: z[k].item() for k in z.files if k.startswith("stat_")}
        if "vw_format" not in z.files:
            # pre-constant-feature model: scoring must not append a feature
            # the training run never saw (its hash slot holds an unrelated
            # colliding weight)
            self.set(noConstant=True)


class VowpalWabbitClassifier(Estimator, _VowpalWabbitBaseParams,
                             HasRawPredictionCol, HasProbabilityCol):
    """Binary linear classifier, logistic loss (reference:
    vw/VowpalWabbitClassifier.scala)."""

    lossFunction = Param("lossFunction", "logistic or hinge", "logistic",
                         TypeConverters.to_string)
    labelConversion = Param(
        "labelConversion", "True (default): labels arrive as 0/1 and are "
        "converted to VW's convention internally (reference: "
        "VowpalWabbitClassifier labelConversion). False: labels are "
        "already -1/+1", True, TypeConverters.to_bool)

    def fit(self, dataset: Dataset) -> "VowpalWabbitClassificationModel":
        if not self.get_or_default("labelConversion"):
            lab = self.get_or_default("labelCol")
            y = np.asarray(dataset[lab], np.float32)
            vals = set(np.unique(y).tolist())
            if not vals <= {-1.0, 1.0}:
                raise ValueError(
                    "labelConversion=False expects -1/+1 labels; got "
                    f"values {sorted(vals)[:5]}")
            dataset = dataset.with_column(lab, (y + 1.0) / 2.0)
        cfg = self._sgd_config(self.get_or_default("lossFunction"))
        weights, stats = self._fit_weights(dataset, cfg)
        model = VowpalWabbitClassificationModel(weights, stats)
        self._copy_params_to(model)
        return model

    def fit_streamed(self, index_path, value_path, label_path,
                     weight_path=None, *, chunk_rows: int = None
                     ) -> "VowpalWabbitClassificationModel":
        """Fit from pre-hashed disk shards with bounded host memory (see
        ``_fit_weights_streamed``). Label shards must hold 0/1 labels (the
        in-memory default); labelConversion=False's -1/+1 convention would
        need a disk rewrite, so it is rejected here."""
        if not self.get_or_default("labelConversion"):
            raise ValueError(
                "labelConversion=False is not supported with fit_streamed; "
                "store 0/1 labels in the shards (the default convention)")
        cfg = self._sgd_config(self.get_or_default("lossFunction"))
        weights, stats = self._fit_weights_streamed(
            index_path, value_path, label_path, weight_path, cfg, chunk_rows)
        model = VowpalWabbitClassificationModel(weights, stats)
        self._copy_params_to(model)
        return model


class VowpalWabbitClassificationModel(_VowpalWabbitModelBase,
                                      HasRawPredictionCol, HasProbabilityCol):
    def transform(self, dataset: Dataset) -> Dataset:
        margin = self._margin(dataset)
        # stable sigmoid: exp only of non-positive args (BFGS-fit models can
        # produce very large margins on separable data)
        p1 = np.where(margin >= 0,
                      1.0 / (1.0 + np.exp(-np.clip(margin, 0, None))),
                      np.exp(np.clip(margin, None, 0))
                      / (1.0 + np.exp(np.clip(margin, None, 0))))
        probs = np.stack([1 - p1, p1], axis=1)
        return dataset.with_columns({
            self.get_or_default("rawPredictionCol"): np.stack([-margin, margin], 1),
            self.get_or_default("probabilityCol"): probs,
            self.get_or_default("predictionCol"): (margin > 0).astype(np.float64),
        })


class VowpalWabbitRegressor(Estimator, _VowpalWabbitBaseParams):
    """Linear regressor, squared/quantile loss (reference:
    vw/VowpalWabbitRegressor.scala)."""

    lossFunction = Param("lossFunction", "squared or quantile", "squared",
                         TypeConverters.to_string)

    def fit(self, dataset: Dataset) -> "VowpalWabbitRegressionModel":
        cfg = self._sgd_config(self.get_or_default("lossFunction"))
        weights, stats = self._fit_weights(dataset, cfg)
        model = VowpalWabbitRegressionModel(weights, stats)
        self._copy_params_to(model)
        return model

    def fit_streamed(self, index_path, value_path, label_path,
                     weight_path=None, *, chunk_rows: int = None
                     ) -> "VowpalWabbitRegressionModel":
        """Fit from pre-hashed disk shards with bounded host memory (see
        ``_fit_weights_streamed``)."""
        cfg = self._sgd_config(self.get_or_default("lossFunction"))
        weights, stats = self._fit_weights_streamed(
            index_path, value_path, label_path, weight_path, cfg, chunk_rows)
        model = VowpalWabbitRegressionModel(weights, stats)
        self._copy_params_to(model)
        return model


class VowpalWabbitRegressionModel(_VowpalWabbitModelBase):
    def transform(self, dataset: Dataset) -> Dataset:
        margin = self._margin(dataset)
        return dataset.with_column(self.get_or_default("predictionCol"),
                                   margin.astype(np.float64))
