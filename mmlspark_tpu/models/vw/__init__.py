"""Vowpal-Wabbit-parity online learners (hashed linear SGD on XLA)."""

from .api import (VowpalWabbitClassificationModel, VowpalWabbitClassifier,
                  VowpalWabbitRegressionModel, VowpalWabbitRegressor)
from .bandit import (ContextualBanditMetrics, VectorZipper,
                     VowpalWabbitContextualBandit,
                     VowpalWabbitContextualBanditModel,
                     VowpalWabbitInteractions)
from .featurizer import VowpalWabbitFeaturizer

__all__ = [
    "ContextualBanditMetrics", "VectorZipper", "VowpalWabbitClassifier",
    "VowpalWabbitClassificationModel", "VowpalWabbitContextualBandit",
    "VowpalWabbitContextualBanditModel", "VowpalWabbitFeaturizer",
    "VowpalWabbitInteractions", "VowpalWabbitRegressionModel",
    "VowpalWabbitRegressor",
]
