"""XLA-compiled hashed SGD — the VW native training loop, TPU-style.

The reference drives VW's C++ online SGD example-by-example over JNI
(reference: vw/VowpalWabbitBase.scala:235-266 ``trainRow`` — setLabel, add
features, ``example.learn()``; multi-pass via native cache files :336-341;
distributed AllReduce of the weight vector over a driver-hosted spanning tree
:401-429). The TPU-native loop is a ``lax.scan`` over minibatches of padded
sparse rows: gather weights by hashed index, compute the loss gradient,
scatter-add the update. Each mesh shard trains its replica on local rows and
the replicas are psum-averaged at every pass end — the same
sync-at-pass-boundary semantics as VW AllReduce, over ICI instead of sockets.

Adaptive (AdaGrad) and normalized updates mirror VW's ``--adaptive``
``--normalized`` flags; plain SGD when both off. L1 is VW's lazy truncated
gradient (Langford et al.): each weight shrinks by ``lr * l1`` per elapsed
step, applied at touch time from a per-weight last-touch clock (and caught
up at pass ends), so predictions always see the shrunk weights — not a
truncate-at-end approximation. The shrink rides the base learning rate
(VW scales it by the adaptive rate; a documented approximation). ``--bfgs`` switches to a
full-batch L-BFGS (two-loop recursion, Armijo backtracking) whose gradient
is one psum over the mesh per iteration — the batch-mode counterpart the
reference exposes through VW's own --bfgs passthrough
(vw/VowpalWabbitBase.scala passThroughArgs).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from ...parallel import mesh as meshlib
from ...parallel import placement
from ...parallel.compat import shard_map
from ...parallel.placement import pspec as P


class SGDConfig(NamedTuple):
    num_bits: int = 18
    loss: str = "squared"  # squared | logistic | hinge | quantile
    learning_rate: float = 0.5
    power_t: float = 0.5          # lr decay exponent (VW default)
    initial_t: float = 0.0
    l1: float = 0.0
    l2: float = 0.0
    adaptive: bool = True
    num_passes: int = 1
    batch_size: int = 128
    quantile_tau: float = 0.5
    link: str = "identity"
    optimizer: str = "sgd"        # sgd | bfgs (VW --bfgs)


from collections import OrderedDict

_SGD_FN_CACHE: "OrderedDict" = OrderedDict()  # LRU, same pattern as
_SGD_FN_CACHE_MAX = 32                        # booster._STEP_CACHE


def _loss_grad(loss: str, pred, y, tau: float):
    """d(loss)/d(prediction). Labels: classifier y in {0,1}; regressor real."""
    if loss == "squared":
        return pred - y
    if loss == "logistic":
        # y in {0,1}: grad of log-loss wrt margin
        return jax.nn.sigmoid(pred) - y
    if loss == "hinge":
        s = 2.0 * y - 1.0  # to ±1
        return jnp.where(s * pred < 1.0, -s, 0.0)
    if loss == "quantile":
        d = pred - y
        return jnp.where(d >= 0, 1.0 - tau, -tau)
    raise ValueError(f"unknown loss {loss!r}")


def _loss_value(loss: str, pred, y, tau: float):
    """Pointwise loss values (L-BFGS needs objectives, not just gradients)."""
    if loss == "squared":
        return 0.5 * (pred - y) ** 2
    if loss == "logistic":
        # y in {0,1}: log(1 + exp(-s*pred)) with s = ±1, stable form
        s = 2.0 * y - 1.0
        return jax.nn.softplus(-s * pred)
    if loss == "hinge":
        s = 2.0 * y - 1.0
        return jnp.maximum(0.0, 1.0 - s * pred)
    if loss == "quantile":
        d = pred - y
        return jnp.where(d >= 0, (1.0 - tau) * d, -tau * d)
    raise ValueError(f"unknown loss {loss!r}")


def train_bfgs(indices: np.ndarray, values: np.ndarray, labels: np.ndarray,
               sample_weight: Optional[np.ndarray], cfg: SGDConfig,
               mesh: Optional[Mesh] = None,
               initial_weights: Optional[np.ndarray] = None,
               history: int = 10) -> np.ndarray:
    """VW ``--bfgs`` parity: full-batch L-BFGS over the hashed linear model.

    Each iteration computes the global objective/gradient with one psum over
    the mesh ``data`` axis (rows sharded, weights replicated), updates the
    [m, D] curvature history, and line-searches with Armijo backtracking —
    all inside a single jitted shard_map program (``num_passes`` iterations,
    matching VW where --passes bounds BFGS iterations). L2 regularizes the
    objective; L1 applies as a single truncate-at-end after the final
    iteration (the batch solver has no per-step clock; the SGD path uses
    true lazy truncated-gradient L1).
    """
    mesh = mesh or meshlib.get_default_mesh()
    D = 1 << cfg.num_bits
    nnz = indices.shape[1]
    w0 = (np.zeros(D, np.float32) if initial_weights is None
          else np.asarray(initial_weights, np.float32))
    idx_d, val_d, y_d, sw_d = _prep_sgd_data(
        indices, values, labels, sample_weight, cfg, mesh)
    m = int(history)
    iters = max(int(cfg.num_passes), 1)

    def local(idx, val, y, sw, w):
        wsum = lax.psum(jnp.sum(sw), "data")

        def obj_grad(w):
            pred = jnp.sum(w[idx] * val, axis=1)
            lv = _loss_value(cfg.loss, pred, y, cfg.quantile_tau)
            gp = _loss_grad(cfg.loss, pred, y, cfg.quantile_tau) * sw
            loss = lax.psum(jnp.sum(lv * sw), "data") / wsum
            grad = jnp.zeros(D, jnp.float32).at[idx.reshape(-1)].add(
                (gp[:, None] * val).reshape(-1))
            grad = lax.psum(grad, "data") / wsum
            if cfg.l2 > 0:
                loss = loss + 0.5 * cfg.l2 * jnp.sum(w * w)
                grad = grad + cfg.l2 * w
            return loss, grad

        def two_loop(grad, S, Y, rho, k):
            """L-BFGS direction from the curvature history (ring buffer)."""
            def bwd(i, carry):
                q, alphas = carry
                j = (k - 1 - i) % m
                valid = i < jnp.minimum(k, m)
                a = jnp.where(valid, rho[j] * jnp.dot(S[j], q), 0.0)
                q = q - a * Y[j] * valid
                return q, alphas.at[j].set(a)

            q, alphas = lax.fori_loop(0, m, bwd,
                                      (grad, jnp.zeros(m, jnp.float32)))
            j_last = (k - 1) % m
            sy = jnp.dot(S[j_last], Y[j_last])
            yy = jnp.dot(Y[j_last], Y[j_last])
            gamma = jnp.where((k > 0) & (yy > 0), sy / (yy + 1e-12), 1.0)
            r = gamma * q

            def fwd(i, r):
                j = (k - jnp.minimum(k, m) + i) % m
                valid = i < jnp.minimum(k, m)
                b = jnp.where(valid, rho[j] * jnp.dot(Y[j], r), 0.0)
                return r + (alphas[j] - b) * S[j] * valid

            return lax.fori_loop(0, m, fwd, r)

        def iteration(carry, _):
            w, f, g, S, Y, rho, k = carry
            d = -two_loop(g, S, Y, rho, k)
            gtd = jnp.dot(g, d)
            # fall back to steepest descent if the direction lost descent
            use_sd = gtd >= 0
            d = jnp.where(use_sd, -g, d)
            gtd = jnp.where(use_sd, -jnp.dot(g, g), gtd)

            def ls_cond(st):
                step, tries, fnew, _, _ = st
                # NOT(sufficient decrease): a NaN/inf trial objective keeps
                # backtracking instead of being accepted (NaN > x is False)
                return ~(fnew <= f + 1e-4 * step * gtd) & (tries < 20)

            def ls_body(st):
                step, tries, _, _, _ = st
                step = step * 0.5
                fnew, gnew = obj_grad(w + step * d)
                return step, tries + 1, fnew, gnew, w + step * d

            f1, g1 = obj_grad(w + d)
            step, _, fnew, gnew, wnew = lax.while_loop(
                ls_cond, ls_body, (jnp.float32(1.0), jnp.int32(0), f1, g1,
                                   w + d))
            s_vec = wnew - w
            y_vec = gnew - g
            sy = jnp.dot(s_vec, y_vec)
            ok = sy > 1e-10                     # curvature condition
            j = k % m
            S = jnp.where(ok, S.at[j].set(s_vec), S)
            Y = jnp.where(ok, Y.at[j].set(y_vec), Y)
            rho = jnp.where(ok, rho.at[j].set(1.0 / (sy + 1e-12)), rho)
            k = k + ok.astype(jnp.int32)
            return (wnew, fnew, gnew, S, Y, rho, k), fnew

        f0, g0 = obj_grad(w)
        init = (w, f0, g0,
                jnp.zeros((m, D), jnp.float32), jnp.zeros((m, D), jnp.float32),
                jnp.zeros(m, jnp.float32), jnp.int32(0))
        (w, f, g, *_), _ = lax.scan(iteration, init, None, length=iters)
        if cfg.l1 > 0:
            w = jnp.sign(w) * jnp.maximum(jnp.abs(w) - cfg.l1, 0.0)
        return w

    cache_key = ("bfgs", cfg, nnz, D, m, tuple(mesh.axis_names),
                 tuple(d.id for d in mesh.devices.flat))
    fn = _SGD_FN_CACHE.get(cache_key)
    if fn is None:
        fn = jax.jit(shard_map(
            local, mesh=mesh,
            in_specs=(P("data", None), P("data", None), P("data"), P("data"),
                      P()),
            out_specs=P(), check_vma=False))
        _SGD_FN_CACHE[cache_key] = fn
        while len(_SGD_FN_CACHE) > _SGD_FN_CACHE_MAX:
            _SGD_FN_CACHE.popitem(last=False)
    else:
        _SGD_FN_CACHE.move_to_end(cache_key)
    return np.asarray(fn(idx_d, val_d, y_d, sw_d, jnp.asarray(w0)))


def _prep_sgd_data(indices: np.ndarray, values: np.ndarray,
                   labels: np.ndarray, sample_weight: Optional[np.ndarray],
                   cfg: SGDConfig, mesh: Mesh) -> tuple:
    """Pad + shard the dataset onto the mesh once; reused across passes by
    the checkpointed trainer so resume doesn't redo full-data transfers."""
    n = indices.shape[0]
    sw = np.ones(n, np.float32) if sample_weight is None else np.asarray(
        sample_weight, np.float32)
    nshards = meshlib.num_shards(mesh)
    bs = cfg.batch_size
    placement.plan_for("vw.fit", mesh=mesh, rows=n)
    # pad rows so each shard has a whole number of batches
    mult = nshards * bs
    idx_p, _ = meshlib.pad_rows(indices.astype(np.int32), mult)
    val_p, _ = meshlib.pad_rows(values.astype(np.float32), mult)
    y_p, _ = meshlib.pad_rows(labels.astype(np.float32), mult)
    sw_p, _ = meshlib.pad_rows(sw, mult)
    sw_p = sw_p * meshlib.validity_mask(n, len(sw_p))  # padded rows learn nothing

    idx_d, _ = placement.shard_rows(idx_p, mesh)
    val_d, _ = placement.shard_rows(val_p, mesh)
    y_d, _ = placement.shard_rows(y_p, mesh)
    sw_d, _ = placement.shard_rows(sw_p, mesh)
    return idx_d, val_d, y_d, sw_d


def train_sgd(indices: np.ndarray, values: np.ndarray, labels: np.ndarray,
              sample_weight: Optional[np.ndarray], cfg: SGDConfig,
              mesh: Optional[Mesh] = None,
              initial_weights: Optional[np.ndarray] = None,
              initial_state: Optional[tuple] = None,
              return_state: bool = False,
              prepped: Optional[tuple] = None):
    """Train a hashed linear model; returns the weight vector [2^num_bits].

    ``initial_state``/``return_state`` carry the full optimizer state
    (weights, adagrad accumulators, step counter) across calls so pass-level
    checkpoint/resume reproduces an uninterrupted run exactly
    (see ``train_sgd_checkpointed``). ``prepped`` (from ``_prep_sgd_data``)
    skips the per-call pad/shard/transfer."""
    mesh = mesh or meshlib.get_default_mesh()
    D = 1 << cfg.num_bits
    nnz = indices.shape[1]
    w0 = (np.zeros(D, np.float32) if initial_weights is None
          else np.asarray(initial_weights, np.float32))
    if prepped is None:
        prepped = _prep_sgd_data(indices, values, labels, sample_weight, cfg,
                                 mesh)
    idx_d, val_d, y_d, sw_d = prepped

    bs = cfg.batch_size
    lr = cfg.learning_rate
    eps = 1e-6

    def local_train(idx, val, y, sw, w, g2_0, t_0, lt_0):
        n_local = idx.shape[0]
        nb = n_local // bs
        idx_b = idx.reshape(nb, bs, nnz)
        val_b = val.reshape(nb, bs, nnz)
        y_b = y.reshape(nb, bs)
        sw_b = sw.reshape(nb, bs)

        def _shrink(wv, pending):
            return jnp.sign(wv) * jnp.maximum(
                jnp.abs(wv) - lr * cfg.l1 * pending, 0.0)

        def one_pass(carry, _):
            w, g2, t, lt = carry

            def batch_step(carry, xs):
                w, g2, t, lt = carry
                bi, bv, by, bw = xs
                flat_i = bi.reshape(-1)
                if cfg.l1 > 0:
                    # lazy truncated gradient: catch the touched weights up
                    # on their skipped steps BEFORE predicting/updating
                    wv = _shrink(w[flat_i], jnp.maximum(t - lt[flat_i], 0.0))
                    w = w.at[flat_i].set(wv)
                    lt = lt.at[flat_i].set(t)
                    pred = jnp.sum(wv.reshape(bi.shape) * bv, axis=1)
                else:
                    pred = jnp.sum(w[bi] * bv, axis=1)  # [bs]
                gp = _loss_grad(cfg.loss, pred, by, cfg.quantile_tau) * bw
                gf = gp[:, None] * bv  # [bs, nnz] per-feature grads
                flat_g = gf.reshape(-1)
                if cfg.adaptive:
                    g2 = g2.at[flat_i].add(flat_g * flat_g)
                    scale = lax.rsqrt(g2[flat_i] + eps)
                else:
                    scale = jnp.float32(1.0) / (t + 1.0) ** cfg.power_t
                if cfg.l2 > 0:
                    w = w * (1.0 - lr * cfg.l2)
                w = w.at[flat_i].add(-lr * flat_g * scale)
                return (w, g2, t + 1.0, lt), None

            (w, g2, t, lt), _ = lax.scan(
                batch_step, (w, g2, t, lt), (idx_b, val_b, y_b, sw_b))
            if cfg.l1 > 0:
                # pass-end catch-up so the pmean'd replicas agree exactly
                w = _shrink(w, jnp.maximum(t - lt, 0.0))
                lt = jnp.full_like(lt, t)
            # pass-end AllReduce average (VW spanning-tree parity)
            w = lax.pmean(w, "data")
            g2 = lax.pmean(g2, "data")
            return (w, g2, t, lt), None

        (w, g2, t, lt), _ = lax.scan(one_pass, (w, g2_0, t_0, lt_0), None,
                                     length=cfg.num_passes)
        # lazy L1 leaves every weight caught up at pass end: output == state
        return w, w, g2, t, lt

    # compiled-step cache: pass-by-pass checkpointed training re-enters with
    # identical (cfg, shapes, mesh) and must reuse one XLA executable rather
    # than re-jitting a fresh closure every pass
    cache_key = (cfg, nnz, D, tuple(mesh.axis_names),
                 tuple(d.id for d in mesh.devices.flat))
    fn = _SGD_FN_CACHE.get(cache_key)
    if fn is None:
        fn = jax.jit(shard_map(
            local_train, mesh=mesh,
            in_specs=(P("data", None), P("data", None), P("data"), P("data"),
                      P(), P(), P(), P()),
            out_specs=P(), check_vma=False))
        _SGD_FN_CACHE[cache_key] = fn
        while len(_SGD_FN_CACHE) > _SGD_FN_CACHE_MAX:
            _SGD_FN_CACHE.popitem(last=False)
    else:
        _SGD_FN_CACHE.move_to_end(cache_key)
    # the lazy-L1 last-touch clock is only read when l1 > 0; cfg keys both
    # the jit cache and the checkpoint fingerprint, so the l1 == 0 default
    # carries a 1-element dummy instead of a 2^num_bits array (which would
    # otherwise be allocated, transferred, and checkpointed for nothing)
    D_lt = D if cfg.l1 > 0 else 1
    if initial_state is not None:
        if len(initial_state) == 3:     # pre-lazy-L1 checkpoint format
            w_raw, g2_0, t_0 = initial_state
            lt_0 = jnp.full(D_lt, float(t_0), jnp.float32)
        else:
            w_raw, g2_0, t_0, lt_0 = initial_state
            lt_0 = jnp.asarray(lt_0)
            if lt_0.shape[0] != D_lt:
                # state saved under a different l1 setting: a 1-element dummy
                # clock resumed into an l1>0 run would silently clamp every
                # per-feature gather/scatter to index 0 — rebuild the clock
                # at the current step instead (weights are already caught up
                # at every pass end, so "last touched now" is exact)
                lt_0 = jnp.full(D_lt, float(t_0), jnp.float32)
        w0 = np.asarray(w_raw, np.float32)
        g2_0 = jnp.asarray(g2_0)
        t_0 = jnp.float32(t_0)
    else:
        g2_0 = jnp.zeros(D, jnp.float32)
        t_0 = jnp.float32(cfg.initial_t)
        lt_0 = jnp.full(D_lt, float(cfg.initial_t), jnp.float32)
    from ...utils.profiling import annotate
    with annotate(f"vw_sgd_train:{cfg.num_passes}pass"):
        w_out, w_raw, g2, t, lt = fn(idx_d, val_d, y_d, sw_d,
                                     jnp.asarray(w0), g2_0, t_0, lt_0)
    if return_state:
        return np.asarray(w_out), (np.asarray(w_raw), np.asarray(g2),
                                   float(t), np.asarray(lt))
    return np.asarray(w_out)


def train_sgd_checkpointed(indices: np.ndarray, values: np.ndarray,
                           labels: np.ndarray,
                           sample_weight: Optional[np.ndarray],
                           cfg: SGDConfig, checkpoint_dir: str,
                           mesh: Optional[Mesh] = None,
                           initial_weights: Optional[np.ndarray] = None
                           ) -> np.ndarray:
    """Multi-pass SGD with pass-level checkpoint/resume (SURVEY.md §5).

    Each pass runs as one device call whose full optimizer state (weights,
    adagrad accumulators, step counter, lazy-L1 last-touch clock) is
    checkpointed; resuming reproduces the uninterrupted run exactly. Lazy
    truncated-gradient L1 applies on every pass through the carried
    clock — checkpointed weights are already regularized."""
    from ...utils.checkpoint import CheckpointManager, data_fingerprint

    fingerprint = data_fingerprint(
        indices, values, labels,
        None if sample_weight is None else np.asarray(sample_weight),
        None if initial_weights is None else np.asarray(initial_weights),
        config=cfg._replace(num_passes=0))    # pass count may legally change
    # namespaced by fingerprint: sweeps sharing one dir don't purge each other
    mgr = CheckpointManager(checkpoint_dir, namespace=fingerprint[:12])
    latest = mgr.latest_matching(fingerprint)
    start_pass, state = 0, None
    if latest is not None:
        _, payload = latest
        start_pass = payload["pass"] + 1
        state = payload["state"]
        if start_pass >= cfg.num_passes:
            raise ValueError(
                f"checkpoint in {checkpoint_dir} already covers "
                f"{start_pass} passes but only {cfg.num_passes} were "
                "requested; clear the directory or raise numPasses")
    mesh = mesh or meshlib.get_default_mesh()
    prepped = None
    w = initial_weights
    for p in range(start_pass, cfg.num_passes):
        is_last = p == cfg.num_passes - 1
        # lazy L1 is stateful (per-weight last-touch clock in the carried
        # state), so it applies on every pass — no end-only emulation
        one = cfg._replace(num_passes=1)
        if prepped is None:
            # pad/shard/transfer once; identical for every pass (batch_size
            # is the only prep-relevant cfg field and it doesn't vary)
            prepped = _prep_sgd_data(indices, values, labels, sample_weight,
                                     one, mesh)
        w, state = train_sgd(indices, values, labels, sample_weight, one,
                             mesh=mesh, initial_weights=w,
                             initial_state=state, return_state=True,
                             prepped=prepped)
        if not is_last:
            mgr.save(p, {"pass": p, "state": state,
                         "fingerprint": fingerprint})
    return w


DEFAULT_STREAM_CHUNK_ROWS = 262_144


def train_sgd_streamed(index_path, value_path, label_path,
                       weight_path=None, *, cfg: SGDConfig,
                       mesh: Optional[Mesh] = None,
                       initial_weights: Optional[np.ndarray] = None,
                       chunk_rows: Optional[int] = None,
                       return_state: bool = False):
    """Multi-pass hashed SGD over disk shards — larger-than-RAM training.

    Closes the out-of-core gap for VW the way ``construct(path=...)``
    closed it for GBDT (reference: every VW stage trains from streamed
    Spark partitions — vw/VowpalWabbitBase.scala trainRow iterators):
    each pass replays the shards in order in bounded host chunks, and
    the full optimizer state (weights, adagrad accumulators, example
    clock, lazy-L1 last-touch clock) carries across chunk calls through
    ``train_sgd``'s ``initial_state``/``return_state`` contract, so a
    streamed pass IS the in-memory pass over the same batches.

    Paths: each of index/value/label (and optional weight) is a ``.npy``
    file, a directory of ``.npy`` shards, or a list of paths
    (:class:`~mmlspark_tpu.models.gbdt.ingest.ShardedMatrixSource`).
    Index shards should be integer dtype (read without float32
    round-trip; values/labels/weights read as float32). Indices are
    masked by ``2^num_bits`` here, matching the estimator's hash-fold
    semantics, so shards may carry raw 32-bit hashes if stored as int64.

    Equivalence contract: ``chunk_rows`` (default
    ``DEFAULT_STREAM_CHUNK_ROWS``) is rounded DOWN to a whole number of
    device batches (``shards * batch_size``; rounded up to one such
    group if smaller), so every chunk except the stream tail is
    pad-free and the tail pads exactly where the in-memory path pads —
    same batches, same pad positions, same step-clock trajectory. On a
    single-shard mesh the streamed run is therefore bit-identical to
    ``train_sgd`` on the concatenated arrays (adaptive and ``power_t``
    decay configs; lazy L1 matches to float rounding — its soft-threshold
    catch-up composes exactly only in real arithmetic) — test-pinned.
    On a multi-shard mesh the pass-end pmean becomes a
    chunk-end pmean (more frequent replica averaging than in-memory, and
    a chunk-local row split) — still VW spanning-tree semantics, synced
    per chunk.
    """
    from ..gbdt.ingest import ShardedMatrixSource

    coerce = ShardedMatrixSource.coerce
    idx_src, val_src, y_src = (coerce(index_path), coerce(value_path),
                               coerce(label_path))
    sw_src = None if weight_path is None else coerce(weight_path)
    n = idx_src.n
    lens = {"index": n, "value": val_src.n, "label": y_src.n}
    if sw_src is not None:
        lens["weight"] = sw_src.n
    if len(set(lens.values())) != 1:
        raise ValueError(f"source row counts disagree: {lens}")
    if idx_src.ndim != 2 or val_src.ndim != 2:
        raise ValueError(
            "index/value shards must be 2-D [n, nnz] (got "
            f"{idx_src.ndim}-D / {val_src.ndim}-D); reshape single-feature "
            "data to [n, 1]")
    if idx_src.num_features != val_src.num_features:
        raise ValueError(
            f"index nnz {idx_src.num_features} != value nnz "
            f"{val_src.num_features}")
    if chunk_rows is None:
        chunk_rows = DEFAULT_STREAM_CHUNK_ROWS
    if chunk_rows <= 0:
        raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")
    if n == 0:
        raise ValueError("sources contain no rows")
    mesh = mesh or meshlib.get_default_mesh()
    # align chunks to whole device-batch groups: interior chunks then add
    # no pad rows, so the carried step clock advances exactly as the
    # in-memory scan's (see the equivalence contract above)
    mult = meshlib.num_shards(mesh) * cfg.batch_size
    chunk_rows = max(mult, (chunk_rows // mult) * mult)
    mask = (1 << cfg.num_bits) - 1
    one = cfg._replace(num_passes=1)
    # num_passes <= 0 parity with train_sgd (scan length 0 returns the
    # initial weights): start from the explicit zero vector, not None
    w = (np.zeros(1 << cfg.num_bits, np.float32)
         if initial_weights is None else initial_weights)
    state = None
    for _ in range(cfg.num_passes):
        for start in range(0, n, chunk_rows):
            stop = min(start + chunk_rows, n)
            idx = (idx_src.read(start, stop, dtype=None)
                   .astype(np.int64) & mask).astype(np.int32)
            val = val_src.read(start, stop)
            y = y_src.read(start, stop)
            sw = None if sw_src is None else sw_src.read(start, stop)
            w, state = train_sgd(idx, val, y, sw, one, mesh=mesh,
                                 initial_weights=w, initial_state=state,
                                 return_state=True)
    if return_state:
        return w, state
    return w


@jax.jit
def _margin_fn(idx, val, w):
    return jnp.sum(w[idx] * val, axis=1)


def predict_sgd(indices: np.ndarray, values: np.ndarray, weights: np.ndarray,
                loss: str = "squared") -> np.ndarray:
    """Margin predictions for padded sparse rows.

    The jitted kernel is module-level with the weight table as an
    ARGUMENT: a closure re-jitted per call would re-trace/compile on
    every chunk of a streamed scoring loop. Callers looping over chunks
    can pass ``weights`` as a device array to also skip the per-call
    host->device weight upload."""
    return np.asarray(_margin_fn(jnp.asarray(indices.astype(np.int32)),
                                 jnp.asarray(values.astype(np.float32)),
                                 jnp.asarray(weights)))
