"""GBDT objectives: gradients/hessians + score transforms.

Parity targets: the objective set the reference exposes through LightGBM params
(reference: lightgbm/TrainParams.scala:86-104 — regression incl. quantile /
tweedie / huber / fair / poisson / mape, binary with ``isUnbalance``,
multiclass, lambdarank is handled by the ranker module).
All are elementwise jax functions fused by XLA into the boosting step.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class Objective(NamedTuple):
    name: str
    # (scores [n] or [n,K], label [n], weight [n]) -> (grad, hess) same shape
    grad_hess: Callable
    # raw score -> prediction-space transform (sigmoid/softmax/exp/identity)
    transform: Callable
    num_scores: int = 1  # per-class score columns (1 unless multiclass)
    init_score: Callable = None  # (label, weight) -> scalar base score


def _binary(label_pos_weight: float = 1.0):
    def grad_hess(score, y, w):
        p = jax.nn.sigmoid(score)
        # isUnbalance / scale_pos_weight: positives get extra weight
        wy = w * jnp.where(y > 0, label_pos_weight, 1.0)
        return (p - y) * wy, p * (1 - p) * wy

    def init_score(y, w):
        p = jnp.clip(jnp.sum(y * w) / jnp.sum(w), 1e-15, 1 - 1e-15)
        return jnp.log(p / (1 - p))

    return Objective("binary", grad_hess, jax.nn.sigmoid, 1, init_score)


def _regression_l2():
    def grad_hess(score, y, w):
        return (score - y) * w, w

    return Objective("regression", grad_hess, lambda s: s, 1,
                     lambda y, w: jnp.sum(y * w) / jnp.sum(w))


def _regression_l1():
    def grad_hess(score, y, w):
        return jnp.sign(score - y) * w, w  # constant-hessian approximation

    return Objective("regression_l1", grad_hess, lambda s: s, 1,
                     lambda y, w: jnp.median(y))


def _huber(alpha: float = 0.9):
    def grad_hess(score, y, w):
        d = score - y
        g = jnp.where(jnp.abs(d) <= alpha, d, alpha * jnp.sign(d))
        return g * w, w

    return Objective("huber", grad_hess, lambda s: s, 1,
                     lambda y, w: jnp.sum(y * w) / jnp.sum(w))


def _fair(c: float = 1.0):
    def grad_hess(score, y, w):
        d = score - y
        g = c * d / (jnp.abs(d) + c)
        h = c * c / (jnp.abs(d) + c) ** 2
        return g * w, h * w

    return Objective("fair", grad_hess, lambda s: s, 1,
                     lambda y, w: jnp.sum(y * w) / jnp.sum(w))


def _quantile(alpha: float = 0.5):
    def grad_hess(score, y, w):
        d = score - y
        g = jnp.where(d >= 0, 1.0 - alpha, -alpha)
        return g * w, w

    return Objective("quantile", grad_hess, lambda s: s, 1,
                     lambda y, w: jnp.quantile(y, alpha))


def _poisson():
    def grad_hess(score, y, w):
        e = jnp.exp(score)
        return (e - y) * w, e * w

    def init_score(y, w):
        return jnp.log(jnp.maximum(jnp.sum(y * w) / jnp.sum(w), 1e-15))

    return Objective("poisson", grad_hess, jnp.exp, 1, init_score)


def _tweedie(rho: float = 1.5):
    def grad_hess(score, y, w):
        e1 = jnp.exp((1 - rho) * score)
        e2 = jnp.exp((2 - rho) * score)
        g = -y * e1 + e2
        h = -y * (1 - rho) * e1 + (2 - rho) * e2
        return g * w, jnp.maximum(h, 1e-15) * w

    def init_score(y, w):
        return jnp.log(jnp.maximum(jnp.sum(y * w) / jnp.sum(w), 1e-15))

    return Objective("tweedie", grad_hess, jnp.exp, 1, init_score)


def _mape():
    def grad_hess(score, y, w):
        scale = 1.0 / jnp.maximum(jnp.abs(y), 1.0)
        return jnp.sign(score - y) * scale * w, scale * w

    return Objective("mape", grad_hess, lambda s: s, 1,
                     lambda y, w: jnp.sum(y * w) / jnp.sum(w))


def _multiclass(num_class: int):
    def grad_hess(scores, y, w):  # scores [n, K], y [n] int
        p = jax.nn.softmax(scores, axis=-1)
        onehot = jax.nn.one_hot(y.astype(jnp.int32), num_class, dtype=p.dtype)
        g = (p - onehot) * w[:, None]
        # LightGBM's multiclass hessian carries a factor of 2 (softmax upper bound)
        h = 2.0 * p * (1 - p) * w[:, None]
        return g, h

    return Objective("multiclass", grad_hess,
                     lambda s: jax.nn.softmax(s, axis=-1), num_class,
                     lambda y, w: jnp.float32(0.0))


def get_objective(name: str, num_class: int = 1, alpha: float = 0.9,
                  tweedie_variance_power: float = 1.5,
                  pos_weight: float = 1.0) -> Objective:
    name = (name or "").lower()
    if name in ("binary", "logistic"):
        return _binary(pos_weight)
    if name in ("multiclass", "softmax"):
        return _multiclass(num_class)
    if name in ("regression", "regression_l2", "l2", "mse", "mean_squared_error", ""):
        return _regression_l2()
    if name in ("regression_l1", "l1", "mae"):
        return _regression_l1()
    if name == "huber":
        return _huber(alpha)
    if name == "fair":
        return _fair()
    if name == "quantile":
        return _quantile(alpha)
    if name == "poisson":
        return _poisson()
    if name == "tweedie":
        return _tweedie(tweedie_variance_power)
    if name == "mape":
        return _mape()
    raise ValueError(f"unknown objective {name!r}")


# -- eval metrics for early stopping (reference: TrainUtils.scala:220-315) ------


def eval_metric(objective: Objective, scores, y, w) -> Tuple[str, jnp.ndarray]:
    """Default per-objective eval metric (higher_is_better handled by caller)."""
    name = objective.name
    if name == "binary":
        p = jnp.clip(jax.nn.sigmoid(scores), 1e-15, 1 - 1e-15)
        ll = -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
        return "binary_logloss", jnp.sum(ll * w) / jnp.sum(w)
    if name == "multiclass":
        logp = jax.nn.log_softmax(scores, axis=-1)
        pick = jnp.take_along_axis(logp, y.astype(jnp.int32)[:, None], axis=1)[:, 0]
        return "multi_logloss", -jnp.sum(pick * w) / jnp.sum(w)
    pred = objective.transform(scores)
    se = (pred - y) ** 2
    return "rmse", jnp.sqrt(jnp.sum(se * w) / jnp.sum(w))
