"""GBDT objectives: gradients/hessians + score transforms.

Parity targets: the objective set the reference exposes through LightGBM params
(reference: lightgbm/TrainParams.scala:86-104 — regression incl. quantile /
tweedie / huber / fair / poisson / mape, binary with ``isUnbalance``,
multiclass, lambdarank is handled by the ranker module).
All are elementwise jax functions fused by XLA into the boosting step.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class Objective(NamedTuple):
    name: str
    # (scores [n] or [n,K], label [n], weight [n]) -> (grad, hess) same shape
    grad_hess: Callable
    # raw score -> prediction-space transform (sigmoid/softmax/exp/identity)
    transform: Callable
    num_scores: int = 1  # per-class score columns (1 unless multiclass)
    init_score: Callable = None  # (label, weight) -> scalar base score


def _binary(label_pos_weight: float = 1.0):
    def grad_hess(score, y, w):
        p = jax.nn.sigmoid(score)
        # isUnbalance / scale_pos_weight: positives get extra weight
        wy = w * jnp.where(y > 0, label_pos_weight, 1.0)
        return (p - y) * wy, p * (1 - p) * wy

    def init_score(y, w):
        p = jnp.clip(jnp.sum(y * w) / jnp.sum(w), 1e-15, 1 - 1e-15)
        return jnp.log(p / (1 - p))

    return Objective("binary", grad_hess, jax.nn.sigmoid, 1, init_score)


def _regression_l2():
    def grad_hess(score, y, w):
        return (score - y) * w, w

    return Objective("regression", grad_hess, lambda s: s, 1,
                     lambda y, w: jnp.sum(y * w) / jnp.sum(w))


def weighted_quantile(y, w, q):
    """Weighted q-quantile: smallest y with cumulative weight >= q * total.

    Every init_score must honor zero weights: training feeds the padded,
    sharded label array whose padding rows carry weight 0 (and row_valid /
    sample weights flow through the same path). This is also LightGBM's own
    BoostFromAverage semantics for l1/quantile — a weighted percentile
    (PercentileFun), not an unweighted one.
    """
    order = jnp.argsort(y)
    ys, ws = y[order], w[order]
    cw = jnp.cumsum(ws)
    target = q * cw[-1]
    return ys[jnp.searchsorted(cw, target)]


def _regression_l1():
    def grad_hess(score, y, w):
        return jnp.sign(score - y) * w, w  # constant-hessian approximation

    return Objective("regression_l1", grad_hess, lambda s: s, 1,
                     lambda y, w: weighted_quantile(y, w, 0.5))


def _huber(alpha: float = 0.9):
    def grad_hess(score, y, w):
        d = score - y
        g = jnp.where(jnp.abs(d) <= alpha, d, alpha * jnp.sign(d))
        return g * w, w

    return Objective("huber", grad_hess, lambda s: s, 1,
                     lambda y, w: jnp.sum(y * w) / jnp.sum(w))


def _fair(c: float = 1.0):
    def grad_hess(score, y, w):
        d = score - y
        g = c * d / (jnp.abs(d) + c)
        h = c * c / (jnp.abs(d) + c) ** 2
        return g * w, h * w

    return Objective("fair", grad_hess, lambda s: s, 1,
                     lambda y, w: jnp.sum(y * w) / jnp.sum(w))


def _quantile(alpha: float = 0.5):
    def grad_hess(score, y, w):
        d = score - y
        g = jnp.where(d >= 0, 1.0 - alpha, -alpha)
        return g * w, w

    return Objective("quantile", grad_hess, lambda s: s, 1,
                     lambda y, w: weighted_quantile(y, w, alpha))


def _poisson():
    def grad_hess(score, y, w):
        e = jnp.exp(score)
        return (e - y) * w, e * w

    def init_score(y, w):
        return jnp.log(jnp.maximum(jnp.sum(y * w) / jnp.sum(w), 1e-15))

    return Objective("poisson", grad_hess, jnp.exp, 1, init_score)


def _tweedie(rho: float = 1.5):
    def grad_hess(score, y, w):
        e1 = jnp.exp((1 - rho) * score)
        e2 = jnp.exp((2 - rho) * score)
        g = -y * e1 + e2
        h = -y * (1 - rho) * e1 + (2 - rho) * e2
        return g * w, jnp.maximum(h, 1e-15) * w

    def init_score(y, w):
        return jnp.log(jnp.maximum(jnp.sum(y * w) / jnp.sum(w), 1e-15))

    return Objective("tweedie", grad_hess, jnp.exp, 1, init_score)


def _mape():
    def grad_hess(score, y, w):
        scale = 1.0 / jnp.maximum(jnp.abs(y), 1.0)
        return jnp.sign(score - y) * scale * w, scale * w

    return Objective("mape", grad_hess, lambda s: s, 1,
                     lambda y, w: jnp.sum(y * w) / jnp.sum(w))


def _multiclass(num_class: int):
    def grad_hess(scores, y, w):  # scores [n, K], y [n] int
        p = jax.nn.softmax(scores, axis=-1)
        onehot = jax.nn.one_hot(y.astype(jnp.int32), num_class, dtype=p.dtype)
        g = (p - onehot) * w[:, None]
        # LightGBM's multiclass hessian carries a factor of 2 (softmax upper bound)
        h = 2.0 * p * (1 - p) * w[:, None]
        return g, h

    return Objective("multiclass", grad_hess,
                     lambda s: jax.nn.softmax(s, axis=-1), num_class,
                     lambda y, w: jnp.float32(0.0))


def _label_gains(yy, label_gain):
    """Per-item NDCG gains: LightGBM's default 2^label - 1, or the explicit
    ``label_gain`` table (reference LightGBMRanker labelGain: gain of grade
    g is label_gain[g])."""
    if label_gain is None:
        return jnp.exp2(yy) - 1.0
    table = jnp.asarray(label_gain, jnp.float32)
    idx = jnp.clip(yy.astype(jnp.int32), 0, table.shape[0] - 1)
    return table[idx]


def _lambdarank(group_size: int, max_position: int = 20, sigma: float = 1.0,
                label_gain=None):
    """LambdaRank pairwise gradients over fixed-size padded query groups.

    TPU-native formulation of the reference's lambdarank objective
    (reference: lightgbm/LightGBMRanker.scala, TrainParams.scala `maxPosition`):
    the C++ lib walks variable-length query boundaries; here every group is
    padded to a static ``group_size`` S, so the all-pairs lambda computation is
    a dense [G, S, S] batch that maps straight onto the MXU — no ragged loops.
    Row weight doubles as the validity mask (0 = in-group padding).
    """
    S = int(group_size)

    def _ranks_and_discounts(score, mask):
        # rank of each item within its group by descending score (invalid last)
        sm = jnp.where(mask, score, -jnp.inf)
        order = jnp.argsort(-sm, axis=1)
        ranks = jnp.argsort(order, axis=1)
        disc = 1.0 / jnp.log2(ranks.astype(jnp.float32) + 2.0)
        return ranks, disc * mask

    def _max_dcg(gains, mask):
        # ideal DCG: gains sorted descending, truncated at max_position
        g_sorted = -jnp.sort(-jnp.where(mask, gains, 0.0), axis=1)
        pos = jnp.arange(S)
        d = jnp.where(pos < max_position, 1.0 / jnp.log2(pos + 2.0), 0.0)
        return jnp.maximum((g_sorted * d[None, :]).sum(axis=1), 1e-12)

    def grad_hess(score, y, w):
        s = score.reshape(-1, S)
        yy = y.reshape(-1, S)
        mask = (w.reshape(-1, S) > 0)
        gains = _label_gains(yy, label_gain) * mask
        _, disc = _ranks_and_discounts(s, mask)
        maxdcg = _max_dcg(gains, mask)

        sdiff = s[:, :, None] - s[:, None, :]
        pair = (mask[:, :, None] & mask[:, None, :]
                & (yy[:, :, None] > yy[:, None, :]))
        delta = (jnp.abs(gains[:, :, None] - gains[:, None, :])
                 * jnp.abs(disc[:, :, None] - disc[:, None, :])
                 / maxdcg[:, None, None])
        sig = jax.nn.sigmoid(-sigma * sdiff)
        lam = jnp.where(pair, -sigma * sig * delta, 0.0)
        hpair = jnp.where(pair, sigma * sigma * sig * (1.0 - sig) * delta, 0.0)
        grad = lam.sum(axis=2) - lam.sum(axis=1)
        hess = hpair.sum(axis=2) + hpair.sum(axis=1)
        return grad.reshape(-1), jnp.maximum(hess, 1e-9).reshape(-1)

    def init_score(y, w):
        return jnp.float32(0.0)

    return Objective("lambdarank", grad_hess, lambda sc: sc, 1, init_score)


def _ndcg_metric(scores, y, w, S: int, max_position: int,
                 label_gain=None):
    """Per-row NDCG@max_position of each row's group (weighted mean by caller:
    pass w = 1/group_size on valid rows to get the mean over groups)."""
    s = scores.reshape(-1, S)
    yy = y.reshape(-1, S)
    mask = (w.reshape(-1, S) > 0)
    gains = _label_gains(yy, label_gain) * mask
    sm = jnp.where(mask, s, -jnp.inf)
    order = jnp.argsort(-sm, axis=1)
    ranks = jnp.argsort(order, axis=1)
    disc = jnp.where(ranks < max_position,
                     1.0 / jnp.log2(ranks.astype(jnp.float32) + 2.0), 0.0)
    dcg = (gains * disc * mask).sum(axis=1)
    g_sorted = -jnp.sort(-jnp.where(mask, gains, 0.0), axis=1)
    pos = jnp.arange(S)
    ideal_d = jnp.where(pos < max_position, 1.0 / jnp.log2(pos + 2.0), 0.0)
    idcg = jnp.maximum((g_sorted * ideal_d[None, :]).sum(axis=1), 1e-12)
    ndcg = dcg / idcg  # [G]
    return jnp.broadcast_to(ndcg[:, None], (ndcg.shape[0], S)).reshape(-1)


def get_objective(name: str, num_class: int = 1, alpha: float = 0.9,
                  tweedie_variance_power: float = 1.5,
                  pos_weight: float = 1.0, group_size: int = 0,
                  max_position: int = 20, sigma: float = 1.0,
                  label_gain=None, **_metric_only) -> Objective:
    name = (name or "").lower()
    if name in ("binary", "logistic"):
        return _binary(pos_weight)
    if name in ("multiclass", "softmax"):
        return _multiclass(num_class)
    if name in ("regression", "regression_l2", "l2", "mse", "mean_squared_error", ""):
        return _regression_l2()
    if name in ("regression_l1", "l1", "mae"):
        return _regression_l1()
    if name == "huber":
        return _huber(alpha)
    if name == "fair":
        return _fair()
    if name == "quantile":
        return _quantile(alpha)
    if name == "poisson":
        return _poisson()
    if name == "tweedie":
        return _tweedie(tweedie_variance_power)
    if name == "mape":
        return _mape()
    if name == "lambdarank":
        if group_size <= 0:
            # scoring-only objective: a ranker model loaded from its text
            # dump predicts raw scores without the training-time group
            # layout; only an attempt to TRAIN with it errors
            def _no_train(*_a, **_k):
                raise ValueError(
                    "lambdarank training requires group_size (padded "
                    "group width); this objective instance is "
                    "scoring-only")
            return Objective("lambdarank", _no_train, lambda sc: sc, 1,
                             lambda y, w: jnp.float32(0.0))
        return _lambdarank(group_size, max_position, sigma, label_gain)
    raise ValueError(f"unknown objective {name!r}")


def score_transform(objective: str, num_class: int = 1, **kwargs):
    """Raw-margin -> prediction-space transform as ONE traceable function.

    ``[n, K] -> [n, K]`` for multiclass (softmax over classes), and
    ``[n, 1] -> [n]`` otherwise (the objective's own elementwise transform
    on the single score column) — exactly the shapes ``Booster.predict``
    has always returned. Split out so the device-resident inference
    program can fuse the transform into the compiled forest evaluator
    instead of re-uploading raw scores for a second host round-trip.

    The transform is pinned to f32 regardless of the predict lane's
    dtype (the quantized predictor's f32-epilogue contract, ROADMAP
    item 3): sigmoid/softmax in reduced precision would trade output
    fidelity for nothing — the epilogue is a vanishing share of the
    program's bytes.
    """
    if num_class > 1:
        return lambda raw: jax.nn.softmax(
            raw.astype(jnp.float32), axis=-1)
    transform = get_objective(objective, num_class, **kwargs).transform
    return lambda raw: transform(raw[:, 0].astype(jnp.float32))


# -- eval metrics for early stopping (reference: TrainUtils.scala:220-315) ------


HIGHER_IS_BETTER = {"ndcg", "auc", "map"}

# metric-param override support (reference: LightGBMParams `metric`): which
# eval metrics each objective family accepts. "auc" is host-computed (exact
# rank statistic — not a weighted mean, so it cannot ride the psum combine);
# everything else evaluates on device, fused early stopping included.
SUPPORTED_EVAL_METRICS = {
    "binary": ("binary_logloss", "binary_error", "auc"),
    "multiclass": ("multi_logloss", "multi_error"),
    "lambdarank": ("ndcg",),
    "_regression": ("rmse", "l2", "mae", "l1"),
}


def eval_metric(objective: Objective, scores, y, w,
                group_size: int = 0, max_position: int = 20,
                eval_at: int = 0, metric: str = None,
                label_gain=None, **_unused) -> Tuple[str, jnp.ndarray]:
    """Per-objective eval metric (higher_is_better handled by caller).

    ``metric`` overrides the objective's default with another supported
    metric of the same family (LightGBM `metric` param; validated by the
    caller against SUPPORTED_EVAL_METRICS). Every value returned here is a
    LOCAL weighted mean — the training step re-combines across shards by
    weight, with the "rmse" name square/sqrt special case.

    ``eval_at`` (the reference's evalAt positions) truncates the NDCG metric
    independently of the lambdarank training truncation ``max_position``.
    """
    name = objective.name
    if name == "lambdarank" and int(group_size) <= 0:
        raise ValueError(
            "lambdarank training/evaluation requires group_size (padded "
            "group width); a model loaded for scoring cannot train")
    if metric:
        if name == "binary" and metric == "binary_error":
            miss = ((scores > 0.0) != (y > 0.5)).astype(jnp.float32)
            return "binary_error", jnp.sum(miss * w) / jnp.sum(w)
        if name == "multiclass" and metric == "multi_error":
            pred = jnp.argmax(scores, axis=-1)
            miss = (pred != y.astype(jnp.int32)).astype(jnp.float32)
            return "multi_error", jnp.sum(miss * w) / jnp.sum(w)
        if name not in ("binary", "multiclass", "lambdarank"):
            pred = objective.transform(scores)
            if metric in ("mae", "l1"):
                # l1 is LightGBM's alias for mae; history keys track the
                # requested name
                return metric, jnp.sum(jnp.abs(pred - y) * w) / jnp.sum(w)
            if metric == "l2":
                # LightGBM l2 is MSE (not RMSE) — plain weighted mean, so
                # the cross-shard combine needs no special case
                return "l2", jnp.sum((pred - y) ** 2 * w) / jnp.sum(w)
        # remaining supported values are the family defaults (or host-side
        # auc, which never reaches this function)
    if name == "lambdarank":
        S = int(group_size)
        if scores.shape[0] < S or scores.shape[0] % S != 0:
            return "ndcg", jnp.float32(0.0)  # shape probe only
        vals = _ndcg_metric(scores, y, w, S, eval_at or max_position,
                            label_gain)
        return "ndcg", jnp.sum(vals * w) / jnp.maximum(jnp.sum(w), 1e-12)
    if name == "binary":
        p = jnp.clip(jax.nn.sigmoid(scores), 1e-15, 1 - 1e-15)
        ll = -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
        return "binary_logloss", jnp.sum(ll * w) / jnp.sum(w)
    if name == "multiclass":
        logp = jax.nn.log_softmax(scores, axis=-1)
        pick = jnp.take_along_axis(logp, y.astype(jnp.int32)[:, None], axis=1)[:, 0]
        return "multi_logloss", -jnp.sum(pick * w) / jnp.sum(w)
    pred = objective.transform(scores)
    se = (pred - y) ** 2
    return "rmse", jnp.sqrt(jnp.sum(se * w) / jnp.sum(w))


def auc_weighted(scores, y, w) -> float:
    """Exact weighted AUC with tie-averaged ranks (host numpy; LightGBM's
    binary `auc` metric semantics). Used for metric="auc" early stopping —
    an exact rank statistic can't ride the device weighted-mean combine."""
    import numpy as np

    scores = np.asarray(scores, np.float64)
    pos = np.asarray(y, np.float64) > 0.5
    w = (np.ones_like(scores) if w is None
         else np.asarray(w, np.float64))
    order = np.argsort(scores, kind="mergesort")
    s, p, ww = scores[order], pos[order], w[order]
    wpos = np.where(p, ww, 0.0)
    wneg = np.where(p, 0.0, ww)
    # tie groups: runs of equal score share a rank; a positive in a group
    # is "above" all lighter negatives plus half the group's own negatives
    starts = np.flatnonzero(np.concatenate([[True], np.diff(s) != 0]))
    gpos = np.add.reduceat(wpos, starts)
    gneg = np.add.reduceat(wneg, starts)
    cneg_before = np.concatenate([[0.0], np.cumsum(gneg)[:-1]])
    tp, tn = wpos.sum(), wneg.sum()
    if tp <= 0 or tn <= 0:
        return 0.5               # degenerate: single class (LightGBM: NaN)
    return float(np.sum(gpos * (cneg_before + 0.5 * gneg)) / (tp * tn))
