"""Out-of-core dataset ingest: file shards -> binned device matrix.

The reference gets distributed ingestion for free from Spark: binary row
files are read per-partition (io/binary/BinaryFileFormat.scala:34-245) and
each worker streams its partition into the native chunked dataset
(lightgbm/LightGBMUtils.scala:201-265 — LGBM_DatasetCreateFromMat over
per-partition chunks). The TPU-native equivalent here: row shards on disk
(``.npy``, read via offset-based ``np.fromfile`` — deliberately not
memmaps, see ShardedMatrixSource) are read in bounded host chunks, binned
ON DEVICE chunk by chunk, and written into a preallocated per-device
column-major bin buffer with a donated ``dynamic_update_slice`` — so host
peak memory is one chunk plus the binner sample, and the only dataset-sized
allocation is the binned (uint8-able) device matrix itself. The raw float
matrix never exists in host or device memory at once.

Multi-host: the mesh's ``data`` axis assigns each device a contiguous global
row range; every process reads only the ranges of its *addressable* devices
(file sharding keyed by ``jax.process_index()`` through the device->process
mapping), and the global array is assembled with
``jax.make_array_from_single_device_arrays`` — the standard multi-host data
loading recipe. No process ever touches another process's bytes.

Binner parity: the quantile binner is fit on exactly the rows the in-memory
path would sample (same seed, same ``rng.choice`` draw), read row-by-row
from the shard files — so ``construct(path=...)`` and ``construct(X)``
produce bit-identical bin boundaries, binned matrices, and therefore
models.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from ...observability.env_registry import env_int
from ...ops.binning import QuantileBinner, bin_cols_device
from ...parallel import mesh as meshlib
from ...parallel import placement
from ...parallel.compat import shard_map
from ...parallel.placement import pspec as P
from . import quantize as _quantize

PathLike = Union[str, os.PathLike]

INGEST_HOST_QUANT_ENV = "MMLSPARK_TPU_INGEST_HOST_QUANT"


def host_quant_enabled(max_bin: int) -> bool:
    """Whether ingest chunks are binned ON HOST (through the quantize
    funnel) and shipped to the device as uint8 bin ids — 4x fewer h2d
    bytes per chunk than the default raw-f32 upload + device binning.
    Off by default: host searchsorted costs ~1.6 s/1M rows single-core
    (the reason device binning exists), so this pays off only where the
    interconnect, not the host, is the ingest bottleneck. Requires a
    uint8-able grid (``max_bin <= 256``)."""
    return env_int(INGEST_HOST_QUANT_ENV, 0) == 1 and 0 < max_bin <= 256


class _NpyShard:
    """Header metadata for one .npy shard, read without mapping the file."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            version = np.lib.format.read_magic(f)
            if version == (1, 0):
                shape, fortran, dtype = \
                    np.lib.format.read_array_header_1_0(f)
            elif version in ((2, 0), (3, 0)):
                # 2.0 and 3.0 share the header layout (3.0 = utf8 names)
                shape, fortran, dtype = \
                    np.lib.format.read_array_header_2_0(f)
            else:
                raise ValueError(
                    f"{path}: unsupported .npy format version {version}")
            self.data_offset = f.tell()
        if fortran:
            raise ValueError(f"{path}: Fortran-order .npy not supported")
        if dtype.hasobject:
            raise ValueError(f"{path}: object arrays not supported")
        self.shape = shape
        self.dtype = dtype
        self.row_items = int(np.prod(shape[1:], dtype=np.int64)) or 1
        self.row_bytes = self.row_items * dtype.itemsize


class ShardedMatrixSource:
    """A logical ``[n, F]`` (or ``[n]``) float array backed by .npy shards.

    Accepts a single ``.npy`` file, a directory of ``.npy`` shards (sorted
    by name — the writer's shard index order), or an explicit list of
    paths. Reads go through offset-based ``np.fromfile`` into fresh
    buffers — deliberately NOT memmaps: touched pages of a long-lived
    mapping stay resident and count toward peak RSS, which at the 20M-row
    demo scale inflated the ingest's measured footprint past the raw data
    size. With plain reads the OS page cache stays reclaimable and the
    process's resident set is just the live chunk.
    """

    @classmethod
    def coerce(cls, source) -> "ShardedMatrixSource":
        """Pass through an existing source; wrap a path/list otherwise."""
        return source if isinstance(source, cls) else cls(source)

    def __init__(self, paths: Union[PathLike, Sequence[PathLike]]):
        if isinstance(paths, (str, os.PathLike)):
            p = os.fspath(paths)
            if os.path.isdir(p):
                names = sorted(f for f in os.listdir(p)
                               if f.endswith(".npy"))
                if not names:
                    raise FileNotFoundError(f"no .npy shards in {p}")
                paths = [os.path.join(p, f) for f in names]
            else:
                paths = [p]
        self.paths: List[str] = [os.fspath(p) for p in paths]
        self._shards = [_NpyShard(p) for p in self.paths]
        zero_d = [s.path for s in self._shards if len(s.shape) == 0]
        if zero_d:
            raise ValueError(
                f"0-D .npy shards have no row axis: {zero_d[:3]}")
        trailing = {s.shape[1:] for s in self._shards}
        if len(trailing) != 1:
            raise ValueError(
                f"inconsistent per-row shapes across shards: "
                f"{sorted(trailing)}")
        # GBDT ingest consumes 1-D/2-D; N-D shards (e.g. image batches)
        # serve the streamed-scoring path (io/streaming.py)
        self._lengths = np.array([s.shape[0] for s in self._shards],
                                 dtype=np.int64)
        self._offsets = np.concatenate(
            [[0], np.cumsum(self._lengths)])          # [S+1]

    @property
    def n(self) -> int:
        return int(self._offsets[-1])

    @property
    def ndim(self) -> int:
        return len(self._shards[0].shape)

    @property
    def num_features(self) -> int:
        return int(self._shards[0].shape[1]) if self.ndim == 2 else 1

    @property
    def row_shape(self) -> tuple:
        return tuple(self._shards[0].shape[1:])

    def _read_shard_rows(self, s: int, lo: int, hi: int,
                         dtype=np.float32) -> np.ndarray:
        sh = self._shards[s]
        raw = np.fromfile(sh.path, dtype=sh.dtype,
                          count=(hi - lo) * sh.row_items,
                          offset=sh.data_offset + lo * sh.row_bytes)
        raw = raw.reshape((hi - lo,) + sh.shape[1:])
        return np.asarray(raw, dtype=dtype or sh.dtype)

    def read(self, start: int, stop: int, dtype=np.float32) -> np.ndarray:
        """Rows [start, stop) crossing shard boundaries, coerced to
        ``dtype`` (default float32; ``None`` keeps the stored dtype — the
        VW streamed path reads int32 index shards this way, since a
        float32 round-trip corrupts hashes above 2^24)."""
        start, stop = int(start), int(min(stop, self.n))
        if dtype is None:
            dts = {np.dtype(s.dtype) for s in self._shards}
            if len(dts) > 1:
                raise ValueError(
                    "dtype=None needs a single stored dtype across shards "
                    f"but found {sorted(map(str, dts))}; coercing mixed "
                    "shards silently would reintroduce the float32 "
                    "round-trip this mode exists to avoid")
            dtype = self._shards[0].dtype
        if stop <= start:
            return np.empty((0,) + self.row_shape, dtype)
        out = np.empty((stop - start,) + self.row_shape, dtype)
        self.read_into(out, start, stop)
        return out

    def read_into(self, out: np.ndarray, start: int, stop: int) -> int:
        """Fill ``out[:stop-start]`` with rows [start, stop); returns the
        row count. For float32 C-order shards the bytes land directly in
        ``out`` via ``readinto`` — no intermediate read buffer or dtype
        copy between the file and the caller's chunk."""
        start, stop = int(start), int(min(stop, self.n))
        rows = stop - start
        if rows <= 0:
            return 0
        s0 = int(np.searchsorted(self._offsets, start, side="right")) - 1
        pos = start
        while pos < stop:
            local = pos - int(self._offsets[s0])
            take = min(stop - pos, int(self._lengths[s0]) - local)
            sh = self._shards[s0]
            dst = out[pos - start:pos - start + take]
            if (sh.dtype == dst.dtype and dst.flags.c_contiguous):
                with open(sh.path, "rb") as f:
                    f.seek(sh.data_offset + local * sh.row_bytes)
                    got = f.readinto(memoryview(dst).cast("B"))
                if got != take * sh.row_bytes:
                    raise IOError(f"{sh.path}: short read ({got} bytes)")
            else:
                dst[...] = self._read_shard_rows(s0, local, local + take,
                                                 dtype=dst.dtype)
            pos += take
            s0 += 1
        return rows

    def gather(self, idx: np.ndarray) -> np.ndarray:
        """Rows at (sorted or unsorted) global indices.

        Row-at-a-time seek+read per selected row: the binner sample is
        a few hundred thousand rows at most, and scattered single-row
        reads keep resident memory at the output sample size (a memmap
        fancy-index would fault in a page per row and hold it mapped).
        """
        idx = np.asarray(idx, dtype=np.int64)
        shard = np.searchsorted(self._offsets, idx, side="right") - 1
        out = np.empty((idx.size,) + self.row_shape, np.float32)
        for s in np.unique(shard):
            sel = np.flatnonzero(shard == s)
            sh = self._shards[s]
            base = int(self._offsets[s])
            with open(sh.path, "rb") as f:
                for j in sel:
                    f.seek(sh.data_offset
                           + (int(idx[j]) - base) * sh.row_bytes)
                    row = np.frombuffer(f.read(sh.row_bytes),
                                        dtype=sh.dtype)
                    out[j] = row.astype(np.float32).reshape(self.row_shape)
        return out


def write_shards(arr_iter, out_dir: PathLike, prefix: str = "part") -> List[str]:
    """Write an iterable of row blocks as numbered .npy shards.

    The datagen-side half of the out-of-core path: callers generate (or
    convert) data one bounded block at a time and never hold the full
    matrix. Returns the shard paths in order.
    """
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for i, block in enumerate(arr_iter):
        p = os.path.join(os.fspath(out_dir), f"{prefix}-{i:05d}.npy")
        np.save(p, np.asarray(block, dtype=np.float32))
        paths.append(p)
    return paths


def csv_to_shards(csv_path: PathLike, out_dir: PathLike, *,
                  label_col: int, num_cols: int = None,
                  weight_col: int = None, shard_rows: int = 1_000_000,
                  skip_header: bool = None,
                  read_bytes: int = 64 << 20):
    """Stream a numeric CSV into .npy feature/label(/weight) shards.

    The bridge from interchange data to the out-of-core path: the file is
    read in bounded byte chunks (cut at line boundaries), parsed by the
    native C++ CSV reader (``native.csv_read_floats``; bad fields -> NaN,
    pure-Python fallback), split into feature vs label/weight columns, and
    written as numbered shards of exactly ``shard_rows`` rows (the last
    one smaller) — peak host memory is roughly one read chunk plus one
    shard. Stale ``part-*.npy`` files in the target directories are
    removed first, so re-runs never mix old shards into the dataset.
    Returns ``(x_dir, y_dir, w_dir_or_None)`` ready for
    ``LightGBMDataset.construct(path=..., label_path=...)``.

    ``skip_header=None`` auto-detects: a first line that does not parse as
    numbers is dropped. Reference equivalent: Spark's CSV reader feeding
    partitioned ingestion (the reference gets this from the platform).
    """
    from ...native import csv_read_floats

    if weight_col is not None and weight_col == label_col:
        raise ValueError(
            f"weight_col ({weight_col}) must differ from label_col: the "
            "shared column would be dropped from features once and written "
            "to both y/ and w/, silently training with weights == labels")
    out_dir = os.fspath(out_dir)
    xdir = os.path.join(out_dir, "x")
    ydir = os.path.join(out_dir, "y")
    wdir = os.path.join(out_dir, "w") if weight_col is not None else None

    with open(csv_path, "rb") as f:
        first = f.readline()
        if first.startswith(b"\xef\xbb\xbf"):
            # Excel-style 'CSV UTF-8' BOM would make the first data field
            # non-numeric (and the header auto-detect drop a data row)
            first = first[3:]
            bom = 3
        else:
            bom = 0
        if num_cols is None:
            num_cols = first.count(b",") + 1
        if skip_header is None:
            # the CSV parser maps non-numeric fields to NaN rather than
            # raising, so headers are detected by inspection: any field
            # that is non-empty and non-numeric marks a header line
            def _numeric(p: str) -> bool:
                p = p.strip()
                if not p:
                    return True        # empty field = missing value
                try:
                    float(p)
                    return True
                except ValueError:
                    return False

            parts = first.decode("utf-8", "replace").strip().split(",")
            skip_header = (len(parts) != num_cols
                           or not all(_numeric(p) for p in parts))
        if not skip_header:
            f.seek(bom)

        drop = [label_col] + ([weight_col] if weight_col is not None
                              else [])
        bad = [c for c in drop if not (0 <= c < num_cols)]
        if bad:
            raise ValueError(f"column index {bad} out of range for "
                             f"{num_cols} CSV columns")
        feat_cols = [c for c in range(num_cols) if c not in drop]

        # always clear the w/ layout slot too: a previous weighted run's
        # shards must not survive next to this run's features
        for d in (xdir, ydir, os.path.join(out_dir, "w")):
            if os.path.isdir(d):
                for stale in os.listdir(d):
                    if stale.startswith("part-") and stale.endswith(".npy"):
                        os.unlink(os.path.join(d, stale))
        for d in (xdir, ydir, wdir):
            if d:
                os.makedirs(d, exist_ok=True)

        shard = 0
        pend: list = []              # parsed blocks awaiting shard cuts
        pend_rows = 0
        carry = b""

        def write_shard(block):
            nonlocal shard
            np.save(os.path.join(xdir, f"part-{shard:05d}.npy"),
                    np.ascontiguousarray(block[:, feat_cols]))
            np.save(os.path.join(ydir, f"part-{shard:05d}.npy"),
                    np.ascontiguousarray(block[:, label_col]))
            if wdir:
                np.save(os.path.join(wdir, f"part-{shard:05d}.npy"),
                        np.ascontiguousarray(block[:, weight_col]))
            shard += 1

        def drain(final=False):
            # emit exact shard_rows slices; keep the remainder pending
            nonlocal pend, pend_rows
            if not pend or (pend_rows < shard_rows and not final):
                return
            block = pend[0] if len(pend) == 1 else np.concatenate(pend)
            off = 0
            while block.shape[0] - off >= shard_rows:
                write_shard(block[off:off + shard_rows])
                off += shard_rows
            if final and off < block.shape[0]:
                write_shard(block[off:])
                off = block.shape[0]
            pend = [block[off:]] if off < block.shape[0] else []
            pend_rows = block.shape[0] - off

        while True:
            chunk = f.read(read_bytes)
            if not chunk:
                break
            chunk = carry + chunk
            cut = chunk.rfind(b"\n")
            if cut < 0:
                carry = chunk
                continue
            carry, text = chunk[cut + 1:], chunk[:cut + 1]
            parsed = csv_read_floats(text, num_cols)
            pend.append(parsed)
            pend_rows += parsed.shape[0]
            drain()
        if carry.strip():
            parsed = csv_read_floats(carry, num_cols)
            pend.append(parsed)
            pend_rows += parsed.shape[0]
        drain(final=True)
    if shard == 0:
        raise ValueError(f"{os.fspath(csv_path)}: no data rows parsed")
    return xdir, ydir, wdir


def fit_binner_from_source(src: ShardedMatrixSource, *, max_bin: int,
                           bin_sample_count: int, seed: int,
                           categorical_features=(),
                           max_bin_by_feature=None) -> QuantileBinner:
    """Fit the quantile binner on the same sample the in-memory path draws.

    ``QuantileBinner.fit(X)`` samples ``rng.choice(n, sample_count,
    replace=False)`` when ``n > sample_count``; drawing the identical
    indices here and gathering those rows from the shard files makes the
    out-of-core binner bit-identical to the in-memory one. Host cost is
    the sample (<= bin_sample_count rows), never the dataset.
    """
    binner = QuantileBinner(max_bin, bin_sample_count, seed,
                            categorical_features, max_bin_by_feature)
    n = src.n
    if n > bin_sample_count:
        rng = np.random.default_rng(seed)
        idx = rng.choice(n, bin_sample_count, replace=False)
        sample = src.gather(np.sort(idx))
    else:
        sample = src.read(0, n)
    binner.fit(sample)
    binner.num_features = src.num_features
    return binner


def _data_axis_devices(mesh: Mesh):
    """Devices along the data axis, in global shard order."""
    if meshlib.DATA_AXIS not in mesh.shape:
        raise ValueError(f"mesh {mesh.shape} has no '{meshlib.DATA_AXIS}' "
                         "axis for out-of-core ingest")
    if mesh.devices.size != mesh.shape[meshlib.DATA_AXIS]:
        raise ValueError(
            "out-of-core ingest shards rows over a data-only mesh; got "
            f"mesh shape {dict(mesh.shape)}")
    return list(mesh.devices.reshape(-1))


def binned_matrix_from_source(src: ShardedMatrixSource,
                              binner: QuantileBinner, mesh: Mesh,
                              bin_dtype, chunk_rows: int) -> jnp.ndarray:
    """Stream file rows -> binned column-major ``[F, n_pad]`` device matrix.

    ONE SPMD program does the whole fill: each step transfers a
    row-sharded host chunk (``chunk_rows`` rows split over the data axis),
    and a donated ``shard_map`` bins every device's slice in parallel and
    writes it into that device's shard of the global ``[F, n_pad]`` buffer
    at a shard-relative offset — all devices advance in lockstep, no
    per-device program or collective. The binned chunk never exists as a
    standalone buffer, and one executable serves every device (an earlier
    per-device-loop formulation compiled a program per device and left
    ~180 MB of per-device allocator pool behind on the CPU backend — at
    8 virtual devices that dwarfed the live set).

    Padding columns (global row ids >= n) carry UNSPECIFIED bin content:
    segments the loop never reads stay bin 0, while padding inside a
    partially-read chunk bins as zero-filled rows — and the in-memory path
    bins its own zero padding too. All of it is dead via the validity
    mask; the bit-identity contract (and its test) covers valid columns.

    Multi-host: each process fills ONLY its addressable devices' segments
    of the staging buffer from its own file ranges (`jax.device_put` with
    a NamedSharding transfers just the addressable shards — foreign
    segments are never read or sent).
    """
    devs = _data_axis_devices(mesh)
    k = len(devs)
    n, F = src.n, src.num_features
    per_dev = -(-n // k)
    n_pad = per_dev * k
    c = max(1, min(int(chunk_rows) // k or 1, per_dev))  # rows/device/step
    ub = binner.upper_bounds
    bd = jnp.dtype(bin_dtype)
    host_quant = host_quant_enabled(binner.max_bin)

    buf_sh = placement.sharding(P(None, meshlib.DATA_AXIS), mesh)
    row_sh = placement.sharding(P(meshlib.DATA_AXIS, None), mesh)
    ub_d = placement.put_replicated(ub, mesh)
    buf = jax.jit(lambda: jnp.zeros((F, n_pad), bd),
                  out_shardings=buf_sh)()

    # one jit object; it re-specializes automatically for the (at most
    # two) chunk shapes — full width and the shard tail
    if host_quant:
        # chunks arrive as uint8 bin ids (quantized on the host through
        # the quantize funnel — bit-identical to bin_cols_device: same
        # strict-compare count, same NaN -> 0), so the device step is
        # pure transpose + cast; the h2d per chunk ships 1/4 the bytes
        step = jax.jit(shard_map(
            lambda buf_l, ch_l, off: lax.dynamic_update_slice(
                buf_l, jnp.transpose(ch_l).astype(bd), (0, off)),
            mesh=mesh,
            in_specs=(P(None, meshlib.DATA_AXIS),
                      P(meshlib.DATA_AXIS, None), P()),
            out_specs=P(None, meshlib.DATA_AXIS), check_vma=False),
            donate_argnums=0)
    else:
        step = jax.jit(shard_map(
            lambda buf_l, ch_l, u, off: lax.dynamic_update_slice(
                buf_l, bin_cols_device(ch_l, u, out_dtype=bd), (0, off)),
            mesh=mesh,
            in_specs=(P(None, meshlib.DATA_AXIS),
                      P(meshlib.DATA_AXIS, None), P(), P()),
            out_specs=P(None, meshlib.DATA_AXIS), check_vma=False),
            donate_argnums=0)
    my_proc = jax.process_index()
    my_devs = [i for i, d in enumerate(devs)
               if d.process_index == my_proc]

    def load_chunk(off: int):
        # width never crosses the shard boundary: a clamped
        # dynamic_update_slice would silently shift the write
        width = min(c, per_dev - off)
        # FRESH host buffer every step, never mutated after device_put:
        # the CPU backend zero-copy ALIASES an aligned numpy array, so a
        # reused staging buffer refilled next iteration raced the
        # still-asynchronous step execution (observed as ~1% of bins
        # landing at the previous offset), and other backends make no
        # public promise about when the H2D transfer reads the source.
        # Same-size alloc/free per step recycles in the allocator — the
        # measured RSS pathologies were mixed-size churn and per-device
        # program pools, not this. The prefetch below keeps at most TWO
        # such buffers live (the one transferring + the one being read),
        # so host peak stays chunk-bounded.
        host = np.zeros((k * width, F), np.float32)
        for i in my_devs:
            lo = i * per_dev + off
            hi = min(lo + width, n)
            seg = host[i * width:(i + 1) * width]
            got = src.read_into(seg, lo, hi) if hi > lo else 0
            if got < width:
                seg[got:] = 0.0            # in-file padding rows
        if host_quant:
            # the FRESH-buffer rule holds: quantize_features returns a
            # new uint8 array, never mutated after device_put (padding
            # rows bin as zero rows — same as the device path)
            return off, _quantize.quantize_features(host, ub)
        return off, host

    # chunk i+1's file reads run on the prefetch thread while the device
    # bins chunk i (io/prefetch.py; MMLSPARK_TPU_DISABLE_PREFETCH=1 for
    # the sequential loop). device_put + step stay on the calling thread
    # in offset order, so the filled buffer is identical either way.
    from ...io.prefetch import iter_prefetched
    chunk_reads = ((lambda o=off: load_chunk(o))
                   for off in range(0, per_dev, c))
    for off, host in iter_prefetched(chunk_reads, site="ingest"):
        if host_quant:
            buf = step(buf, placement.device_put(host, row_sh),
                       np.int32(off))
        else:
            buf = step(buf, placement.device_put(host, row_sh), ub_d,
                       np.int32(off))
    return buf


def vector_from_source(src: Optional[ShardedMatrixSource], mesh: Mesh,
                       n: int, n_pad: int) -> Optional[jnp.ndarray]:
    """Row-sharded 1-D device vector read per-device from file shards."""
    if src is None:
        return None
    if src.ndim != 1:
        raise ValueError(f"expected 1-D shards, got ndim={src.ndim}")
    if src.n != n:
        raise ValueError(f"label/weight length {src.n} != feature rows {n}")
    devs = _data_axis_devices(mesh)
    per_dev = n_pad // len(devs)
    my_proc = jax.process_index()
    local = []
    for d_idx, dev in enumerate(devs):
        if dev.process_index != my_proc:
            continue
        lo = d_idx * per_dev
        seg = src.read(lo, min(lo + per_dev, n))
        if seg.shape[0] < per_dev:
            seg = np.pad(seg, (0, per_dev - seg.shape[0]))
        local.append(placement.put_on_device(seg, dev))
    sharding = placement.sharding(P(meshlib.DATA_AXIS), mesh)
    return jax.make_array_from_single_device_arrays(
        (n_pad,), sharding, local)


def construct_from_files(path, label_path, weight_path=None, *,
                         max_bin: int = 255,
                         bin_sample_count: int = 200_000, seed: int = 0,
                         categorical_features=(),
                         mesh: Optional[Mesh] = None,
                         bin_dtype="uint8",
                         chunk_rows: int = 262_144,
                         max_bin_by_feature=None):
    """Build a device-resident LightGBMDataset from on-disk shards.

    ``bin_dtype`` defaults to ``uint8`` here (unlike the in-memory path's
    int32): out-of-core is the large-n regime where narrow bin storage is
    the point. Requires ``max_bin <= 256``.
    """
    from .booster import LightGBMDataset, _device_validity_mask

    from .booster import _validate_bin_dtype

    mesh = mesh or meshlib.get_default_mesh()
    _validate_bin_dtype(bin_dtype, max_bin)
    xsrc = ShardedMatrixSource(path)
    placement.plan_for("gbdt.ingest_files", mesh=mesh, rows=xsrc.n,
                       dtype=jnp.dtype(bin_dtype).name,
                       host_quant=host_quant_enabled(max_bin))
    if xsrc.ndim != 2:
        raise ValueError("feature shards must be 2-D [rows, features]")
    bad_cats = [int(i) for i in categorical_features
                if not (0 <= int(i) < xsrc.num_features)]
    if bad_cats:
        raise ValueError(
            f"categorical_features indexes {bad_cats} out of range for "
            f"{xsrc.num_features} features")
    ysrc = ShardedMatrixSource(label_path)
    wsrc = ShardedMatrixSource(weight_path) if weight_path is not None \
        else None
    binner = fit_binner_from_source(
        xsrc, max_bin=max_bin, bin_sample_count=bin_sample_count,
        seed=seed, categorical_features=categorical_features,
        max_bin_by_feature=max_bin_by_feature)
    Xbt_d = binned_matrix_from_source(xsrc, binner, mesh, bin_dtype,
                                      chunk_rows)
    n = xsrc.n
    n_pad = int(Xbt_d.shape[1])
    y_d = vector_from_source(ysrc, mesh, n, n_pad)
    vmask_d = _device_validity_mask(n, n_pad, mesh)
    w_d = vector_from_source(wsrc, mesh, n, n_pad)
    if w_d is None:
        w_d = vmask_d
    return LightGBMDataset(binner, Xbt_d, y_d, w_d, vmask_d, n, n_pad,
                           mesh, max_bin, categorical_features)
