"""Exact TreeSHAP on device: per-leaf fixed-shape formulation.

The host implementation (treeshap.py, Lundberg Alg. 2) walks each tree with
a Python DFS carrying ``[n]``-wide numpy state — work-efficient, but every
one of the ~O(nodes * depth) vector ops pays numpy dispatch + f64 memory
traffic (~1.3k rows/s at 100 trees x 31 leaves on the builder CPU, and
host-bound however fast the accelerator is). This module trades the
DFS's shared prefixes for fixed shapes the compiler can fuse: each leaf's
root path is folded on host into its unique features (duplicate
occurrences multiply into one ``z``/``o`` slot — EXTEND is
order-independent, it builds a symmetric polynomial), and the whole
O(depth^2) Shapley-weight computation runs as one jitted program,
vectorized over (leaves, rows) with trees scanned and contributions
scattered to features by a one-hot matmul (MXU work on TPU).

Key identity making EXTEND data-parallel over the path axis: appending
element (pz, po) to a path of length l is the linear two-term recurrence

    w'[i] = pz * (l - i)/(l + 1) * w[i] + po * i/(l + 1) * w[i - 1]

— no sequential dependence, one vector op per path element. Only UNWIND's
``next_one`` carry is sequential, and its loop is bounded by the depth cap
(<= 17 steps, unrolled by XLA).

Exactness: same math as the host path (modulo f32 vs f64 accumulation);
pinned against it in tests/test_treeshap.py.

Backend choice: this formulation targets the TPU (hundreds of small fused
VPU/MXU ops per tree, one scanned executable, rows on the lane axis). On
the XLA **CPU** backend those same small ops lose to the host engines
(measured 706 vs ~1150 rows/s at 100 trees against the numpy recursion,
and the round-5 native C++ engine runs 4-5x the numpy one on top), so
``predict_contrib`` defaults to host off-accelerator and device on TPU
(MMLSPARK_TPU_SHAP_DEVICE=1 / MMLSPARK_TPU_SHAP_HOST=1 override).

Reference parity anchor: lightgbm/LightGBMBooster.scala:250-269
(predict_contrib through native TreeSHAP).
"""

from __future__ import annotations

import numpy as np


def _fold_tree_paths(feat, left, right, is_leaf, cover, n_features):
    """Fold every leaf's root path into unique-feature slots.

    Returns a dict of per-leaf arrays padded to [L, D]:
      step_node / step_left / step_valid — the raw path steps (for o)
      slot — which unique slot each step folds into
      z [L, D] — per-slot cold (cover) fraction products
      ufeat [L, D] — per-slot feature id (n_features for padding)
      m [L] — unique slot count; vleaf [L]; leaf_ok [L]
    """
    M = len(feat)
    parent = np.full(M, -1, np.int64)
    from_left = np.zeros(M, bool)
    for j in range(M):
        if not is_leaf[j]:
            parent[left[j]] = j
            from_left[left[j]] = True
            parent[right[j]] = j
            from_left[right[j]] = False
    leaves = [j for j in range(M) if is_leaf[j] and cover[j] > 0
              and (parent[j] >= 0 or j == 0)]
    paths = []
    for leaf in leaves:
        steps = []                     # (parent_node, went_left, ratio)
        j = leaf
        while parent[j] >= 0:
            p = parent[j]
            r = float(cover[j]) / max(float(cover[p]), 1e-12)
            steps.append((p, bool(from_left[j]), r))
            j = p
        steps.reverse()
        # fold duplicates into unique slots, path order of first occurrence
        slots: dict = {}
        z = []
        slot_of_step = []
        for p, _, r in steps:
            f = int(feat[p])
            if f not in slots:
                slots[f] = len(z)
                z.append(r)
            else:
                z[slots[f]] *= r
            slot_of_step.append(slots[f])
        paths.append((leaf, steps, slot_of_step,
                      np.asarray(z, np.float32),
                      np.fromiter(slots.keys(), np.int64,
                                  len(slots))))
    L = len(paths)
    # steps (Ds) and unique slots (Du) pad independently: a chain-shaped
    # tree splitting one feature 60 times has Ds=60 but Du=1, and the
    # O(Du^2) Shapley loops must not pay the step count
    Ds = max((len(s) for _, s, *_ in paths), default=1) or 1
    Du = max((len(z) for *_, z, _ in paths), default=1) or 1
    out = dict(
        step_node=np.zeros((L, Ds), np.int32),
        step_left=np.zeros((L, Ds), bool),
        step_valid=np.zeros((L, Ds), bool),
        slot=np.zeros((L, Ds), np.int32),
        z=np.ones((L, Du), np.float32),
        ufeat=np.full((L, Du), n_features, np.int32),
        m=np.zeros(L, np.int32),
        vleaf=np.zeros(L, np.float32),
        leaf_id=np.zeros(L, np.int32),
    )
    for i, (leaf, steps, slot_of_step, z, ufeats) in enumerate(paths):
        d = len(steps)
        out["leaf_id"][i] = leaf
        if d:
            out["step_node"][i, :d] = [s[0] for s in steps]
            out["step_left"][i, :d] = [s[1] for s in steps]
            out["step_valid"][i, :d] = True
            out["slot"][i, :d] = slot_of_step
            u = len(z)
            out["z"][i, :u] = z
            out["ufeat"][i, :u] = ufeats
            out["m"][i] = u
    return out


def _shap_block_program(L: int, Ds: int, Du: int, Fp1: int):
    """Jitted per-class program: scan trees, return phi [Fp1, nb]."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def one_tree(phi, tree):
        gl = tree["gl"].astype(jnp.float32)              # [Mmax, nb]
        g = gl[tree["step_node"]]                        # [L, Ds, nb]
        ind = jnp.where(tree["step_left"][:, :, None], g, 1.0 - g)
        ind = jnp.where(tree["step_valid"][:, :, None], ind, 1.0)
        # o[l, u, :] = prod over steps s of leaf l with slot[s] == u.
        # ind is exactly {0, 1} (routing indicators), so the product is 1
        # iff no selected step missed — one batched matmul counting misses
        # (MXU work) instead of Ds sequential masked multiplies.
        slot_oh = (tree["slot"][:, None, :]
                   == jnp.arange(Du, dtype=jnp.int32)[None, :, None])
        slot_oh &= tree["step_valid"][:, None, :]        # [L, Du, Ds]
        misses = jnp.einsum("lus,lsn->lun", slot_oh.astype(jnp.float32),
                            1.0 - ind)
        o = (misses < 0.5).astype(jnp.float32)           # [L, Du, nb]

        z = tree["z"]                                    # [L, Du]
        m = tree["m"]                                    # [L]
        nb = ind.shape[-1]
        # EXTEND all unique slots: w [L, Du+1, nb], slot axis i
        iota = jnp.arange(Du + 1, dtype=jnp.float32)     # [Du+1]
        w = jnp.zeros((L, Du + 1, nb), jnp.float32).at[:, 0, :].set(1.0)
        for j in range(Du):
            lj = jnp.float32(j + 1)                      # path len incl root
            ca = ((lj - iota) / (lj + 1.0))[None, :, None]
            cb = (iota / (lj + 1.0))[None, :, None]
            w_shift = jnp.concatenate(
                [jnp.zeros((L, 1, nb), jnp.float32), w[:, :-1, :]], axis=1)
            w_new = (z[:, j, None, None] * ca * w
                     + o[:, j, None, :] * cb * w_shift)
            w = jnp.where((m > j)[:, None, None], w_new, w)

        # per-slot unwound sums; sequential next_one carry over i
        phi_contrib = jnp.zeros((L, Du, nb), jnp.float32)
        for j in range(Du):
            lm = m.astype(jnp.float32)                   # full length
            zf = z[:, j, None]                           # [L, 1]
            of = o[:, j, :]                              # [L, nb]
            nzmask = of != 0
            safe_of = jnp.where(nzmask, of, 1.0)
            total = jnp.zeros((L, nb), jnp.float32)
            next_one = jnp.take_along_axis(
                w, m[:, None, None].astype(jnp.int32), axis=1)[:, 0, :]
            for i in range(Du - 1, -1, -1):
                fi = jnp.float32(i)
                active = (m > i) & (m > j)
                ta = next_one * (lm[:, None] + 1.0) / ((fi + 1.0) * safe_of)
                tb = jnp.where(zf != 0,
                               w[:, i, :] * (lm[:, None] + 1.0)
                               / jnp.maximum(zf * (lm[:, None] - fi),
                                             1e-38),
                               0.0)
                t = jnp.where(nzmask, ta, tb)
                t = jnp.where(active[:, None], t, 0.0)
                total = total + t
                next_one = jnp.where(
                    (m > i)[:, None],
                    w[:, i, :] - t * zf * (lm[:, None] - fi)
                    / (lm[:, None] + 1.0),
                    next_one)
            phi_contrib = phi_contrib.at[:, j, :].set(
                total * (of - zf) * tree["vleaf"][:, None])

        # scatter to features: one-hot [L*Du, Fp1]^T @ contrib [L*Du, nb]
        oh = jax.nn.one_hot(tree["ufeat"].reshape(-1), Fp1,
                            dtype=jnp.float32)           # [L*Du, Fp1]
        phi = phi + oh.T @ phi_contrib.reshape(L * Du, nb)
        return phi, None

    @jax.jit
    def run(trees, nb_shape_probe):
        phi0 = jnp.zeros((Fp1, nb_shape_probe.shape[0]), jnp.float32)
        phi, _ = lax.scan(one_tree, phi0, trees)
        return phi

    return run


def shap_values_device(booster, X: np.ndarray,
                       row_block: int = 4096) -> np.ndarray:
    """Device TreeSHAP: same contract as treeshap.shap_values."""
    import jax
    import jax.numpy as jnp

    from .treeshap import _cat_member_np, _has_device_arrays

    X = np.asarray(X, dtype=np.float32)
    n, F = X.shape
    K = booster.num_class
    trees = jax.tree_util.tree_map(np.asarray, booster.trees) \
        if _has_device_arrays(booster.trees) else booster.trees
    thr_raw = np.asarray(booster.thr_raw)
    feat_np = np.asarray(trees.feat)
    root_covers = np.asarray(trees.node_cnt)[:, 0]
    if booster.num_trees and not np.all(root_covers > 0):
        raise ValueError(
            "exact TreeSHAP needs per-node training counts, but this "
            "booster has trees with zero root cover (typically a model "
            "imported from a LightGBM text dump without "
            "internal_count/leaf_count fields) — use "
            "predict_contrib(method='saabas') for cover-free attribution")
    is_cat = booster._is_cat()
    is_cat_np = None if is_cat is None else np.asarray(is_cat)

    # host fold: per tree path tables, padded tree-uniformly per class
    folded = []
    for t in range(booster.num_trees):
        folded.append(_fold_tree_paths(
            feat_np[t], np.asarray(trees.left[t]),
            np.asarray(trees.right[t]), np.asarray(trees.is_leaf[t]),
            np.asarray(trees.node_cnt[t], np.float64),
            F))
    out = np.zeros((n, (F + 1) * K), dtype=np.float64)
    for k in range(K):
        out[:, k * (F + 1) + F] = booster.base_score[k]
    if not booster.num_trees:
        return out

    M = feat_np.shape[1]
    for k in range(K):
        tids = [t for t in range(booster.num_trees) if t % K == k]
        L = max(f["m"].shape[0] for t in tids for f in [folded[t]])
        Ds = max(folded[t]["step_node"].shape[1] for t in tids)
        Du = max(folded[t]["z"].shape[1] for t in tids)
        T = len(tids)

        def padded(name, fill, dtype, width=None):
            outp = np.full((T, L, width) if width is not None
                           else (T, L), fill, dtype)
            for i, t in enumerate(tids):
                a = folded[t][name]
                if a.ndim == 2:
                    outp[i, :a.shape[0], :a.shape[1]] = a
                else:
                    outp[i, :a.shape[0]] = a
            return outp

        stacked = dict(
            step_node=padded("step_node", 0, np.int32, Ds),
            step_left=padded("step_left", False, bool, Ds),
            step_valid=padded("step_valid", False, bool, Ds),
            slot=padded("slot", 0, np.int32, Ds),
            z=padded("z", 1.0, np.float32, Du),
            ufeat=padded("ufeat", F, np.int32, Du),
            m=padded("m", 0, np.int32),
        )
        # leaf values with shrinkage are in leaf_value at leaf node ids
        vleaf = np.zeros((T, L), np.float32)
        exp_val = 0.0
        for i, t in enumerate(tids):
            f = folded[t]
            lv = np.asarray(trees.leaf_value[t], np.float64)
            cv = np.asarray(trees.node_cnt[t], np.float64)
            il = np.asarray(trees.is_leaf[t])
            vleaf[i, :f["m"].shape[0]] = lv[f["leaf_id"]]
            sel = il & (cv > 0)
            exp_val += float((lv[sel] * cv[sel]).sum()
                             / max(cv[sel].sum(), 1e-12))
        stacked["vleaf"] = vleaf

        # bounded LRU shared with the training-step programs: long-lived
        # processes must not pin one executable per tree-shape forever
        from .booster import _cached_program
        prog = _cached_program(
            ("treeshap", L, Ds, Du, F + 1),
            lambda: _shap_block_program(L, Ds, Du, F + 1))

        col = slice(k * (F + 1), (k + 1) * (F + 1))
        stacked_dev = {kk: jnp.asarray(v) for kk, v in stacked.items()}
        for lo in range(0, n, row_block):
            hi = min(lo + row_block, n)
            gl = np.zeros((T, M, hi - lo), bool)
            for i, t in enumerate(tids):
                feat_t = feat_np[t]
                xv = X[lo:hi][:, feat_t]                 # [nb, M]
                g = ~(xv > thr_raw[t][None, :])          # NaN -> left
                if is_cat_np is not None:
                    g = np.where(
                        is_cat_np[feat_t][None, :],
                        _cat_member_np(np.asarray(trees.cat_bitset[t]),
                                       xv.T, booster._cat_max_idx(),
                                       booster._cat_strict()).T,
                        g)
                gl[i] = g.T
            tree_in = dict(stacked_dev, gl=jnp.asarray(gl))
            phi = np.asarray(prog(tree_in,
                                  jnp.zeros(hi - lo, jnp.float32)))
            out[lo:hi, col] += phi.T
        out[:, k * (F + 1) + F] += exp_val
    return out
