"""Boosting orchestration + the Booster (trained ensemble) container.

TPU-native equivalent of the reference's per-task training loop and booster
object (reference: lightgbm/TrainUtils.scala:220-315 ``trainCore`` — the
per-iteration loop with eval tracking, early stopping and delegate hooks;
lightgbm/LightGBMBooster.scala:186-339 — the inference/persistence side).

Design: the per-iteration work (gradients -> grow tree -> update scores ->
eval metrics) is ONE jitted shard_map program over the ``data`` mesh axis;
the Python host loop around it handles early stopping and callbacks, exactly
where the reference put its JVM-side loop. Trees come back as tiny fixed-shape
arrays per iteration and are stacked into the Booster.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np
from jax.sharding import Mesh

from ...observability import flight as _flight
from ...observability import hbm as _hbm
from ...observability import metrics as _metrics
from ...observability import roofline as _roofline
from ...observability import spans as _spans
from ...observability import watchdog as _watchdog
from ...observability.logging import console as _console
from ...robustness.failpoints import fault_point as _failpoint
from ... import tuning as _tuning
from ...utils import compile_cache as _compile_cache
from ...ops.binning import QuantileBinner, bin_cols_device
from ...parallel import mesh as meshlib
from ...parallel import placement
from ...parallel.compat import shard_map
from ...parallel.placement import pspec as P
from . import quantize as _quantize
from .growth import (GrowConfig, Tree, bitset_words, grow_tree,
                     grow_tree_depthwise, predict_forest_raw,
                     predict_tree_binned, resolve_growth_backend)
from .objectives import (HIGHER_IS_BETTER, Objective, eval_metric,
                         get_objective, score_transform)


# bounded LRU of compiled boosting steps: one executable per
# (shape, config, mesh) combination; evict oldest so long-lived processes
# (sweeps, services) don't pin executables forever
_STEP_CACHE: "OrderedDict" = OrderedDict()
_STEP_CACHE_MAX = 32


def _cached_program(key, build):
    """Get-or-build a compiled program in the bounded LRU step cache."""
    prog = _STEP_CACHE.get(key)
    if prog is None:
        # wire the persistent compile cache before ANY cached program is
        # built — dataset construction (bin_cols, synth masks) builds
        # programs before train_booster's own ensure() runs
        _compile_cache.ensure()
        t0 = time.perf_counter()
        prog = build()
        # compile event: XLA hands this cache jitted programs that compile
        # lazily, so the recorded time is stage-out only — the predict
        # cache (below) is the one that observes real compile wall time
        _flight.record("program_build", cache="gbdt_step",
                       key=repr(key),
                       seconds=round(time.perf_counter() - t0, 6),
                       persistent_cache=_compile_cache.cache_dir() or "")
        _metrics.safe_counter("gbdt_program_builds_total",
                              cache="gbdt_step").inc()
        # roofline ledger entry: step programs compile lazily, so no
        # cost_analysis here — the entry still names the executable
        _roofline.register_executable(predict_key_hash(key), kind="step",
                                      label="gbdt_step")
        _STEP_CACHE[key] = prog
        while len(_STEP_CACHE) > _STEP_CACHE_MAX:
            _STEP_CACHE.popitem(last=False)
    else:
        _STEP_CACHE.move_to_end(key)
    return prog


class _PhaseTimer:
    """Opt-in wall-time phase breakdown of a train_booster call
    (MMLSPARK_TPU_TIMING=1) — the TPU analog of the reference's per-phase
    TrainingStats diagnostics (vw/VowpalWabbitBase.scala:27-46)."""

    def __init__(self):
        import os
        self.on = bool(os.environ.get("MMLSPARK_TPU_TIMING"))
        self._t = time.perf_counter() if self.on else 0.0

    def mark(self, name: str) -> None:
        if self.on:
            now = time.perf_counter()
            # console, not the JSON funnel: MMLSPARK_TPU_TIMING=1 is an
            # explicit operator request that must print regardless of the
            # telemetry kill switch
            _console(f"[gbdt-timing] {name}: {now - self._t:.3f}s")
            self._t = now


# --- single-buffer tree transfer -------------------------------------------
# A Tree has 13 leaf arrays; downloading them individually costs one host
# round-trip each, which dominates result readback on remotely-attached TPUs
# (~70 ms/array over a tunneled PJRT link). pack_trees flattens everything
# into ONE f32 buffer on device (ints bitcast, bools widened) so the download
# is a single transfer; unpack_trees restores the exact arrays on host.

_TREE_FIELD_DTYPES = dict(
    feat=np.int32, thr_bin=np.int32, left=np.int32, right=np.int32,
    is_leaf=np.bool_, leaf_value=np.float32, node_count=np.int32,
    node_grad=np.float32, node_hess=np.float32, node_cnt=np.float32,
    split_gain=np.float32, node_value=np.float32, cat_bitset=np.uint32)


def pack_trees(trees: Tree) -> jnp.ndarray:
    """Flatten a (possibly stacked) Tree into one int32 device buffer.

    The buffer is int32, not f32: small integers bitcast to f32 are
    subnormals, and the TPU flushes subnormals to zero somewhere in the
    f32 copy pipeline (observed: every int field read back as 0). Float
    bits ride bitcast inside int32 instead — integer ops never flush.
    """
    parts = []
    for arr in trees:
        if arr.dtype == jnp.bool_:
            arr = arr.astype(jnp.int32)
        if arr.dtype != jnp.int32:
            arr = lax.bitcast_convert_type(arr, jnp.int32)
        parts.append(arr.reshape(-1))
    return jnp.concatenate(parts)


def _tree_field_shape(name: str, lead: Tuple[int, ...], M: int,
                      BW: int) -> Tuple[int, ...]:
    """THE single source of truth for the packed-buffer field layout:
    per-tree ``[M, BW]`` for the category bitsets, scalar for node_count,
    ``[M]`` for every other field — shared by the host and device
    unpackers so the wire layout cannot drift between them."""
    return lead + ((M, BW) if name == "cat_bitset"
                   else () if name == "node_count" else (M,))


def unpack_trees(flat: np.ndarray, lead: Tuple[int, ...], M: int,
                 BW: int) -> Tree:
    """Inverse of :func:`pack_trees`: trees with leading dims ``lead``."""
    fields, off = {}, 0
    for name in Tree._fields:
        shape = _tree_field_shape(name, lead, M, BW)
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        seg = np.ascontiguousarray(flat[off:off + size])
        off += size
        dt = _TREE_FIELD_DTYPES[name]
        if dt == np.bool_:
            seg = seg.astype(np.bool_)
        elif dt != np.int32:
            seg = seg.view(dt)
        fields[name] = seg.reshape(shape)
    assert off == flat.size, (
        f"unpack_trees: buffer has {flat.size} elements, layout expects "
        f"{off} — num_leaves/num_bins mismatch between pack and unpack")
    return Tree(**fields)


# --- device-resident inference hot path -------------------------------------
# The fused predictor: ONE compiled program evaluates the forest, sums the
# per-class tree outputs, adds the base score, and (for predict()) applies
# the objective transform — so a scoring call downloads only [n, K] instead
# of [T, n] + a host tile/loop + a re-upload for the transform. Packed trees
# ride as ARGUMENTS (never jit constants), which makes the executables
# shareable process-wide: any Booster with the same shape key — including
# one just unpickled in a serving worker, or a num_iteration sweep — hits
# the same compiled program.


def _pow2_ceil(v: int) -> int:
    """Smallest power of two >= max(1, v)."""
    return 1 << (max(1, int(v)) - 1).bit_length()


def _pack_trees_host(trees: Tree, t_end: int,
                     predict_dtype: str = "f32") -> np.ndarray:
    """Host-side mirror of :func:`pack_trees`: flatten the first ``t_end``
    trees into ONE int32 buffer (bools widened, float/uint bits riding
    bitcast) so the forest upload is a single host->device transfer and the
    executable's tree argument is one flat array.

    ``predict_dtype == "int8"`` shrinks the buffer: the ``leaf_value``
    segment carries per-tree int8-quantized leaves packed four per word
    (``quantize.quantize_leaves`` — the scale math stays in the funnel)
    and the ``[t_end]`` f32 leaf scales ride bitcast at the buffer's
    tail, beside the trees in the same single transfer."""
    parts, tail = [], None
    for name, arr in zip(Tree._fields, trees):
        a = np.asarray(arr)[:t_end].astype(_TREE_FIELD_DTYPES[name],
                                           copy=False)
        if name == "leaf_value" and predict_dtype == "int8":
            q, scale = _quantize.quantize_leaves(a)
            flatq = np.pad(q.reshape(-1), (0, (-q.size) % 4))
            parts.append(np.ascontiguousarray(flatq).view(np.int32))
            tail = np.ascontiguousarray(scale).view(np.int32)
            continue
        if a.dtype == np.bool_:
            a = a.astype(np.int32)
        elif a.dtype != np.int32:
            a = np.ascontiguousarray(a).view(np.int32)
        parts.append(np.ascontiguousarray(a).reshape(-1))
    if tail is not None:
        parts.append(tail)
    return np.concatenate(parts)


def _unpack_trees_device(flat: jnp.ndarray, T: int, M: int, BW: int,
                         predict_dtype: str = "f32") -> Tree:
    """Device-side inverse of :func:`_pack_trees_host` (static slicing —
    traces into pure reshapes/bitcasts, no data movement). Field order,
    shapes and bitcast rules are shared with the host pack/unpack pair
    (``Tree._fields`` / :func:`_tree_field_shape` /
    ``_TREE_FIELD_DTYPES``). The int8 lane unpacks the packed int8 leaf
    segment and dequantizes against the tail scales through the quantize
    funnel — the Tree handed to traversal carries f32 leaves either way
    (the f32-epilogue contract)."""
    fields, off = {}, 0
    for name in Tree._fields:
        shape = _tree_field_shape(name, (T,), M, BW)
        size = int(np.prod(shape, dtype=np.int64))
        if name == "leaf_value" and predict_dtype == "int8":
            nwords = (size + 3) // 4
            q = lax.bitcast_convert_type(flat[off:off + nwords],
                                         jnp.int8).reshape(-1)[:size]
            off += nwords
            scale = lax.bitcast_convert_type(flat[flat.shape[0] - T:],
                                             jnp.float32)
            fields[name] = _quantize.dequantize_leaves_device(
                q.reshape(shape), scale)
            continue
        seg = flat[off:off + size]
        off += size
        dt = _TREE_FIELD_DTYPES[name]
        if dt == np.bool_:
            seg = seg.astype(jnp.bool_)
        elif dt != np.int32:
            seg = lax.bitcast_convert_type(seg, jnp.dtype(dt))
        fields[name] = seg.reshape(shape)
    return Tree(**fields)


def _to_device(x):
    """The predict hot path's ONLY host->device transfer funnel — tests
    shim this to assert exactly one upload per scoring call. Rides the
    placement layer (ROADMAP item 6): placement.to_device is the
    package-wide h2d funnel."""
    return placement.to_device(x)


def _from_device(x) -> np.ndarray:
    """The predict hot path's ONLY device->host transfer funnel — tests
    shim this to assert exactly one download per scoring call."""
    return placement.to_host(x)


# process-wide fused-predictor executable cache. Keyed on shape/config only
# (tree bucket, batch bucket, num_class, transform...), NEVER on a Booster
# instance: a serving worker that unpickles a model, or a sweep re-scoring
# at many num_iteration values, reuses compiled executables instead of
# recompiling per object.
_PREDICT_CACHE: "OrderedDict" = OrderedDict()
_PREDICT_CACHE_MAX = 64
_PREDICT_CACHE_LOCK = threading.Lock()


def _forest_args_nbytes(ent) -> float:
    """Total device bytes a cached forest-argument tuple pins — the
    ``packed_trees`` HBM-ledger claim (None members contribute 0)."""
    return float(sum(getattr(a, "nbytes", 0) or 0 for a in ent
                     if a is not None))


def _cost_summary(compiled) -> dict:
    """FLOPs / bytes-accessed from XLA ``cost_analysis()`` where the
    backend exposes it ({} elsewhere) — the GSPMD observation that what
    got compiled, and how big, is itself a key runtime observable."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        out = {}
        if ca.get("flops") is not None:
            out["flops"] = float(ca["flops"])
        if ca.get("bytes accessed") is not None:
            out["bytes_accessed"] = float(ca["bytes accessed"])
        return out
    except Exception:  # noqa: BLE001 — telemetry must not fail a predict
        return {}


class _ObservedProgram:
    """Cache entry that makes the compile observable.

    ``jax.jit`` compiles lazily on first dispatch, which hides compile
    wall time and the compiled artifact. This wrapper AOT-compiles on the
    first call instead (``lower(*args).compile()`` — exact shapes are
    pinned by the cache key, so one compile serves every call), records a
    flight-recorder compile event with the cache key, wall time, and XLA
    ``cost_analysis()`` FLOPs/bytes, and feeds ``gbdt_compile_seconds``.
    If the AOT path is unavailable it falls back to plain jit dispatch —
    scoring never depends on the observability path.
    """

    __slots__ = ("_jitted", "_key", "_key_hash", "_compiled", "_lock",
                 "_dtype")

    def __init__(self, jitted, key, dtype=None):
        self._jitted = jitted
        self._key = key
        self._key_hash = predict_key_hash(key)
        self._compiled = None
        self._lock = threading.Lock()
        self._dtype = dtype

    @classmethod
    def from_compiled(cls, compiled, key, dtype=None):
        """Wrap an ALREADY-COMPILED executable (the bundle-prewarm path)
        so prewarmed entries get the same call-site roofline timing as
        organically-compiled ones."""
        prog = cls(None, key, dtype=dtype)
        prog._compiled = compiled
        return prog

    def __call__(self, *args):
        fn = self._compiled
        if fn is None:
            fn = self._compile_observed(args)
        if not _metrics.enabled():
            return fn(*args)
        # roofline call-site timer: block on the output so the sample is
        # device wall time, not dispatch time. Cheap in context — every
        # consumer immediately downloads the result (a blocking d2h), so
        # the sync this timer adds was about to happen anyway.
        t0 = time.perf_counter()
        out = fn(*args)
        try:
            jax.block_until_ready(out)
        except Exception:  # noqa: BLE001 — telemetry must not fail a call
            pass
        _roofline.observe_call(self._key_hash, time.perf_counter() - t0)
        return out

    def _compile_observed(self, args):
        # serialized: two serving threads hitting a cold entry must not
        # both pay the multi-second XLA compile (the plain-jit path
        # deduplicated this inside jax's dispatch cache) nor double-count
        # the compile metrics
        with self._lock:
            if self._compiled is not None:
                return self._compiled
            t0 = time.perf_counter()
            cost = {}
            try:
                fn = self._jitted.lower(*args).compile()
                cost = _cost_summary(fn)
            except Exception:  # noqa: BLE001 — AOT API drift: plain jit
                fn = self._jitted
            dt = time.perf_counter() - t0
            self._compiled = fn
        _metrics.safe_counter("gbdt_compiles_total", cache="predict").inc()
        _metrics.safe_histogram("gbdt_compile_seconds",
                                cache="predict").observe(dt)
        # persistent_cache: the active MMLSPARK_TPU_COMPILE_CACHE_DIR ("" =
        # off). With a warm dir, `seconds` is the disk fetch, not an XLA
        # compile — persistent_compile_cache_hits_total counts those.
        _flight.record("compile", cache="predict", key=repr(self._key),
                       seconds=round(dt, 6),
                       persistent_cache=_compile_cache.cache_dir() or "",
                       **cost)
        try:
            devs = jax.devices()
            if devs:
                _roofline.note_device_kind(
                    getattr(devs[0], "device_kind", None))
        except Exception:  # noqa: BLE001 — peaks degrade to unknown
            pass
        _roofline.register_executable(
            self._key_hash, kind="predict",
            flops=cost.get("flops"),
            bytes_accessed=cost.get("bytes_accessed"),
            compile_seconds=dt, label="gbdt_predict",
            dtype=self._dtype)
        return fn


def _predict_program(key, build, dtype=None):
    """Get-or-build in the bounded process-wide predictor cache, counting
    hits/misses (``gbdt_predict_cache_{hits,misses}_total``)."""
    with _PREDICT_CACHE_LOCK:
        fn = _PREDICT_CACHE.get(key)
        if fn is not None:
            _PREDICT_CACHE.move_to_end(key)
    if fn is None:
        _metrics.safe_counter("gbdt_predict_cache_misses_total").inc()
        with _spans.span("gbdt_predict_build"):
            fn = _ObservedProgram(build(), key, dtype=dtype)
        with _PREDICT_CACHE_LOCK:
            fn = _PREDICT_CACHE.setdefault(key, fn)
            _PREDICT_CACHE.move_to_end(key)
            while len(_PREDICT_CACHE) > _PREDICT_CACHE_MAX:
                _PREDICT_CACHE.popitem(last=False)
    else:
        _metrics.safe_counter("gbdt_predict_cache_hits_total").inc()
    return fn


def preload_predict_program(key, fn, dtype=None) -> bool:
    """Install an ALREADY-COMPILED program under ``key`` — the serving-
    bundle prewarm path (``mmlspark_tpu/bundles``): a worker restarting
    from an AOT bundle populates the predictor cache before its first
    request, so the serving hot path never pays (or even observes) a
    compile. Never clobbers a live entry (a program the process already
    built and warmed beats a deserialized one); returns whether the
    preload took. Counted separately from hits/misses so cold-start
    dashboards can tell prewarmed capacity from organically-warmed."""
    with _PREDICT_CACHE_LOCK:
        if key in _PREDICT_CACHE:
            return False
    # wrap outside the lock (cost_analysis can be slow): prewarmed
    # entries get the same call-site roofline timing as organic ones
    if not isinstance(fn, _ObservedProgram):
        cost = _cost_summary(fn)
        prog = _ObservedProgram.from_compiled(fn, key, dtype=dtype)
        _roofline.register_executable(
            prog._key_hash, kind="predict",
            flops=cost.get("flops"),
            bytes_accessed=cost.get("bytes_accessed"),
            label="gbdt_predict(prewarm)", dtype=dtype)
        fn = prog
    with _PREDICT_CACHE_LOCK:
        if key in _PREDICT_CACHE:      # lost the race while wrapping
            return False
        _PREDICT_CACHE[key] = fn
        while len(_PREDICT_CACHE) > _PREDICT_CACHE_MAX:
            _PREDICT_CACHE.popitem(last=False)
    _metrics.safe_counter("gbdt_predict_cache_preloads_total").inc()
    return True


def predict_key_hash(key) -> str:
    """Stable content hash of a predictor cache key — the name a bundle
    stores an exported executable under. ``repr`` over the key tuple is
    deterministic for everything a key may contain (ints, bools, strings,
    None, nested tuples, and the ``_freeze_kwargs`` ndarray rendering,
    whose payload is raw bytes)."""
    import hashlib
    return hashlib.sha256(repr(key).encode("utf-8")).hexdigest()


class PredictPlan(NamedTuple):
    """One fused predict executable's identity + builder, shared by the
    online dispatch path (:meth:`Booster._predict_device`) and the
    offline AOT bundle builder so the two can never disagree on a cache
    key. ``builder()`` returns the jitted (un-compiled) program."""

    key: tuple
    t_end: int
    n_pad: int
    T_pad: int
    num_features: int
    builder: Callable
    predict_dtype: str = "f32"


def iter_predict_plans(booster: "Booster", batch_sizes,
                       num_iterations=(-1,), transforms=(True,),
                       dtypes=("f32",)):
    """Yield ``(meta, plan)`` for every DISTINCT fused predict
    executable a serving deployment of ``booster`` dispatches over the
    given batch sizes / iteration counts / transform / predict-dtype
    variants. THE one enumeration: the key-manifest export below and
    the bundle builder (``mmlspark_tpu/bundles``) both iterate this, so
    what a bundle pins and what a manifest reports can never drift.
    Batch sizes aliasing into one pow2 bucket dedupe to one plan (the
    executable is shared), and a requested dtype the model degrades
    (``quantize.resolve_predict_dtype``) dedupes into its f32 plan —
    the meta records the EFFECTIVE dtype."""
    seen = set()
    for dt in dtypes:
        for transformed in transforms:
            for it in num_iterations:
                for b in batch_sizes:
                    plan = booster.predict_plan(int(b), int(it),
                                                transformed=transformed,
                                                predict_dtype=dt)
                    if plan.key in seen:
                        continue
                    seen.add(plan.key)
                    yield ({"batch_size": int(b), "num_iteration": int(it),
                            "transformed": bool(transformed),
                            "predict_dtype": plan.predict_dtype}, plan)


def predict_key_manifest(booster: "Booster", batch_sizes,
                         num_iterations=(-1,),
                         transformed: bool = True) -> List[Dict]:
    """Key-manifest export: the (batch bucket x iteration) predictor
    cache keys a serving deployment of ``booster`` will dispatch to —
    what the bundle builder enumerates and what its MANIFEST.json pins."""
    return [{**meta, "n_pad": plan.n_pad, "t_pad": plan.T_pad,
             "key_hash": predict_key_hash(plan.key)}
            for meta, plan in iter_predict_plans(
                booster, batch_sizes, num_iterations,
                transforms=(transformed,))]


def _freeze_kwargs(kwargs: dict):
    """Hashable rendering of objective kwargs for the executable-cache
    key. JSON round-trips turn tuples into lists (e.g. a ranker's
    label_gain), which would make the key unhashable — values are frozen
    structurally, never passed back to the objective (the builder uses
    the booster's own kwargs for that)."""
    def freeze(v):
        if isinstance(v, (list, tuple)):
            return tuple(freeze(x) for x in v)
        if isinstance(v, dict):
            return tuple(sorted((k, freeze(x)) for k, x in v.items()))
        if isinstance(v, np.ndarray):
            return ("ndarray", v.dtype.str, v.shape, v.tobytes())
        return v
    return tuple(sorted((k, freeze(v)) for k, v in kwargs.items()))


def _build_predict_program(T_pad: int, M: int, BW: int, depth_cap: int,
                           K: int, cat_max_bin: int, transform,
                           predict_dtype: str = "f32"):
    """Build the fused device-resident scoring program.

    ``run(packed, thr, base, active, is_cat, mdec, X)`` evaluates all
    ``T_pad`` trees, masks out trees past ``t_end`` via ``active`` (so one
    executable serves every t_end inside the bucket), reduces per class,
    adds the base score and — when ``transform`` (a traceable raw->
    prediction function, see ``objectives.score_transform``) is set —
    applies the objective transform, all inside ONE jitted program.
    ``is_cat`` / ``mdec`` are passed as ``None`` when absent (the key
    distinguishes those variants).

    ``predict_dtype`` selects the traversal lane (ROADMAP item 3):
    ``int8`` compares uint8 bin-id features against uint8 bin-id
    thresholds (routing bit-exact vs f32 — see ``quantize.py``) over
    int8-packed leaves; ``bf16`` compares bfloat16 features/thresholds.
    Both keep the epilogue — leaf gather, per-class sum, base score,
    transform — in f32."""

    def run(packed, thr, base, active, is_cat, mdec, X):
        trees = _unpack_trees_device(packed, T_pad, M, BW,
                                     predict_dtype=predict_dtype)
        leaf = predict_forest_raw(trees, thr, X, depth_cap, is_cat=is_cat,
                                  cat_max_bin=cat_max_bin,
                                  missing_dec=mdec)            # [T_pad, n]
        masked = leaf * active[:, None]
        if T_pad % K == 0:
            # tree t scores class t % K: [T_pad/K, K, n] groups each
            # class's trees in one reshaped axis — same mapping as the
            # old host loop's per_tree[k::K].sum(0)
            per_class = masked.reshape(T_pad // K, K,
                                       masked.shape[1]).sum(axis=0)
        else:                       # defensive: partial final iteration
            onehot = jax.nn.one_hot(jnp.arange(T_pad) % K, K,
                                    dtype=masked.dtype)
            per_class = jnp.einsum("tk,tn->kn", onehot, masked)
        raw = per_class.T + base[None, :]                      # [n, K]
        return raw if transform is None else transform(raw)

    return jax.jit(run)


# --- device-side synthesis of row-shaped defaults ---------------------------
# The validity mask, default unit weights, and base-score broadcast are pure
# functions of scalars; generating them on device avoids three dataset-sized
# host->device transfers per training call.


def _device_validity_mask(n: int, n_pad: int, mesh: Mesh):
    fn = _cached_program(("synth_vmask", n, n_pad, mesh), lambda: jax.jit(
        lambda: (jnp.arange(n_pad) < n).astype(jnp.float32),
        out_shardings=placement.row_sharding(mesh)))
    return fn()


def _device_tile_scores(base_d, n_pad: int, K: int, mesh: Mesh):
    fn = _cached_program(("synth_scores", n_pad, K, mesh), lambda: jax.jit(
        lambda b: jnp.broadcast_to(
            b[None, :].astype(jnp.float32), (n_pad, K)),
        out_shardings=placement.row_sharding(mesh, ndim=2)))
    return fn(base_d)


def _bin_program(x_shape, max_bin: int, mesh: Mesh, bin_dtype=jnp.int32):
    return _cached_program(
        ("bin_cols", x_shape, max_bin, mesh, jnp.dtype(bin_dtype).name),
        lambda: jax.jit(shard_map(
            lambda X, ub: bin_cols_device(X, ub, out_dtype=bin_dtype),
            mesh=mesh,
            in_specs=(P("data", None), P()), out_specs=P(None, "data"),
            check_vma=False)))


def _validate_bin_dtype(bin_dtype, max_bin: int):
    """Bin-id storage dtype: int32 (default), int16, uint8 or int8. Bin
    ids are < max_bin, so narrow storage is lossless within range; it
    shrinks the HBM-resident dataset 2x/4x — the lever that fits
    Criteo-scale binned matrices on a v5e pod (docs/performance.md
    "scaling"). int8 (ids < 128, i.e. max_bin <= 128) matches the
    quantized predict lane's signed-byte staging for frameworks that
    want one dtype end to end. Kernels and routing widen per block in
    VMEM, never in HBM."""
    bd = jnp.dtype(bin_dtype)
    limits = {"int32": 1 << 31, "int16": 1 << 15, "uint8": 256,
              "int8": 128}
    if bd.name not in limits:
        raise ValueError(
            f"bin_dtype must be one of {sorted(limits)}, got {bd.name}")
    if max_bin > limits[bd.name]:
        raise ValueError(
            f"bin_dtype={bd.name} holds bin ids < {limits[bd.name]}, "
            f"but max_bin={max_bin}")
    return bd


class LightGBMDataset:
    """Pre-binned, device-resident GBDT training dataset: bin once, train many.

    Parity with the reference's native dataset construction
    (lightgbm/LightGBMDataset.scala:70-159, built via LGBM_DatasetCreateFromMat
    — LightGBMUtils.scala:227): the reference builds the binned native dataset
    once per partition before the iteration loop ever runs. Here construction
    quantile-bins on device into the column-major ``[F, n_pad]`` layout and
    every ``train_booster(dataset=...)`` call starts from that device matrix —
    the expensive ingest (binner fit + feature-matrix transfer + binning) is
    paid once, not per training run. This also matches how LightGBM itself is
    measured: Dataset construction is one-time setup, train() is the timed
    phase.
    """

    def __init__(self, binner, Xbt_d, y_d, w_d, vmask_d, n: int, n_pad: int,
                 mesh: Mesh, max_bin: int, categorical_features):
        self.binner = binner
        self.Xbt_d = Xbt_d
        self.y_d = y_d
        self.w_d = w_d
        self.vmask_d = vmask_d
        self.n = n
        self.n_pad = n_pad
        self.mesh = mesh
        self.max_bin = max_bin
        self.categorical_features = tuple(
            int(i) for i in categorical_features)

    @property
    def num_features(self) -> int:
        return int(self.Xbt_d.shape[0])

    @classmethod
    def construct(cls, X=None, y=None, weight=None, *, max_bin: int = 255,
                  bin_sample_count: int = 200_000, seed: int = 0,
                  categorical_features=(), mesh: Optional[Mesh] = None,
                  row_valid: Optional[np.ndarray] = None,
                  bin_dtype=None, path=None, label_path=None,
                  weight_path=None, chunk_rows: Optional[int] = None,
                  max_bin_by_feature=None,
                  _timer: Optional[_PhaseTimer] = None) -> "LightGBMDataset":
        if path is None and (label_path is not None
                             or weight_path is not None
                             or chunk_rows is not None):
            raise ValueError(
                "label_path/weight_path/chunk_rows only apply with path= "
                "(out-of-core); for in-memory arrays pass y/weight directly")
        if path is not None:
            # out-of-core: stream file shards through chunked device binning
            # (host peak = one chunk + the binner sample). The reference's
            # equivalent is Spark partition files feeding the chunked native
            # dataset (lightgbm/LightGBMUtils.scala:201-265).
            if X is not None or y is not None or weight is not None:
                raise ValueError(
                    "pass either in-memory arrays or path=..., not both")
            if label_path is None:
                raise ValueError("path= requires label_path=")
            if row_valid is not None:
                raise ValueError("row_valid is not supported with path= "
                                 "(ranker group padding is in-memory only)")
            from .ingest import construct_from_files
            # out-of-core is the large-n regime: default bin storage narrows
            # to uint8 when max_bin allows; an explicit bin_dtype (including
            # 'int32') is honored as given.
            if bin_dtype is None:
                bin_dtype = "uint8" if max_bin <= 256 else "int32"
            _validate_bin_dtype(bin_dtype, max_bin)
            return construct_from_files(
                path, label_path, weight_path, max_bin=max_bin,
                bin_sample_count=bin_sample_count, seed=seed,
                categorical_features=categorical_features, mesh=mesh,
                bin_dtype=bin_dtype,
                chunk_rows=262_144 if chunk_rows is None else chunk_rows,
                max_bin_by_feature=max_bin_by_feature)
        if X is None or y is None:
            raise ValueError(
                "construct needs in-memory arrays (X, y) or file shards "
                "(path=..., label_path=...)")
        tw = _timer or _PhaseTimer()
        mesh = mesh or meshlib.get_default_mesh()
        X = np.asarray(X, dtype=np.float32)
        y = np.asarray(y, dtype=np.float32)
        n, F = X.shape
        bad_cats = [int(i) for i in categorical_features
                    if not (0 <= int(i) < F)]
        if bad_cats:
            raise ValueError(
                f"categorical_features indexes {bad_cats} out of range for "
                f"{F} features")
        bd = _validate_bin_dtype("int32" if bin_dtype is None else bin_dtype,
                                 max_bin)
        binner = QuantileBinner(max_bin, bin_sample_count, seed,
                                categorical_features,
                                max_bin_by_feature).fit(X)
        tw.mark("binner_fit")
        # placement decision (observable): dataset rows are batch-dim
        # sharded over the mesh's data axis when it has >1 shard; the
        # note carries the binned matrix's storage dtype so the flight
        # ring shows how wide the HBM-resident dataset landed
        placement.plan_for("gbdt.ingest", mesh=mesh, rows=n, dtype=bd.name)
        # Binning runs ON DEVICE, producing the column-major [F, n_local]
        # layout tree growth consumes (the host searchsorted pass measured
        # 1.6 s at the 1Mx28 bench shape vs ~ms of VPU compare-sums; raw and
        # binned rows are the same byte count so the transfer is unchanged).
        # Padding rows bin to garbage but carry vmask 0 downstream.
        X_d, _ = placement.shard_rows(X, mesh)
        if tw.on:
            X_d.block_until_ready()
            tw.mark("xfer_X")
        bin_fn = _bin_program(X_d.shape, max_bin, mesh, bin_dtype=bd)
        n_pad = X_d.shape[0]
        Xbt_d = bin_fn(X_d, jnp.asarray(binner.upper_bounds))
        # the raw copy served only to produce the binned matrix: free its
        # HBM now or both dataset-sized buffers stay live for the whole run
        Xbt_d.block_until_ready()
        tw.mark("bin_device")
        X_d.delete()
        del X_d
        y_d, _ = placement.shard_rows(y, mesh)
        if row_valid is not None:
            # in-group padding rows (ranker) are dead for counts/histograms
            vmask = meshlib.validity_mask(n, n_pad)
            vmask[:n] *= np.asarray(row_valid, np.float32)
            vmask_d, _ = placement.shard_rows(vmask, mesh)
        else:
            vmask_d = _device_validity_mask(n, n_pad, mesh)
        if weight is not None:
            w_d, _ = placement.shard_rows(
                np.asarray(weight, np.float32), mesh)
        else:
            # default unit weights with zeros on padding rows — exactly the
            # validity mask, so no second array is synthesized or stored
            w_d = vmask_d
        if tw.on:
            jax.block_until_ready((y_d, w_d, vmask_d))
            tw.mark("aux_shards")
        return cls(binner, Xbt_d, y_d, w_d, vmask_d, n, n_pad, mesh,
                   max_bin, categorical_features)


def _with_tree_defaults(fields: Dict) -> Dict:
    """Backfill tree fields added after format v1 (e.g. node_value) so models
    saved by older versions still load; node_value falls back to leaf_value
    (SHAP contributions then attribute only at leaves)."""
    if "node_value" not in fields:
        fields["node_value"] = np.asarray(fields["leaf_value"])
    if "cat_bitset" not in fields:
        shape = np.asarray(fields["feat"]).shape   # [T, M] or [M]
        fields["cat_bitset"] = np.zeros((*shape, 1), np.uint32)
    else:
        fields["cat_bitset"] = np.asarray(
            fields["cat_bitset"]).astype(np.uint32)
    return fields


def _densify(X):
    """Accept scipy.sparse CSR/CSC input (LGBM_DatasetCreateFromCSR parity,
    reference: lightgbm/LightGBMUtils.scala:227): densify in row blocks so
    peak host memory is the output array plus one block, then feed the
    standard dense path (pad/densify-per-shard is the TPU-native layout —
    histograms need dense bin matrices on the MXU anyway)."""
    from ...core.dataset import _is_sparse
    if not _is_sparse(X):
        return X
    X = X.tocsr()
    n, F = X.shape
    out = np.zeros((n, F), dtype=np.float32)
    step = max(1, (8 << 20) // max(F * 4, 1))
    for start in range(0, n, step):
        out[start:start + step] = X[start:start + step].toarray()
    return out


class Booster:
    """A trained GBDT ensemble (stacked fixed-shape trees)."""

    def __init__(self, trees: Tree, thr_raw: np.ndarray, num_class: int,
                 base_score: np.ndarray, objective: str, depth_cap: int,
                 binner_state: dict, best_iteration: int = -1,
                 eval_history: Optional[Dict[str, List[float]]] = None,
                 objective_kwargs: Optional[dict] = None):
        self.trees = jax.tree_util.tree_map(np.asarray, trees)  # [T*K, M] arrays
        self.thr_raw = np.asarray(thr_raw)
        self.num_class = int(num_class)
        self.base_score = np.asarray(base_score, dtype=np.float32).reshape(-1)
        self.objective = objective
        self.objective_kwargs = objective_kwargs or {}
        self.depth_cap = int(depth_cap)
        self.binner_state = binner_state
        self.best_iteration = int(best_iteration)
        self.eval_history = eval_history or {}
        # Per-node LightGBM decision_type bytes [T, M] (missing-value
        # routing: bit 1 default-left, bits 2-3 missing type), set only by
        # the native-model import path. None = the framework's own training
        # semantics (NaN routes left — decision_type 10), which the fast
        # `~(x > thr)` routing implements directly.
        self.missing_dec: Optional[np.ndarray] = None

    # -- inference -------------------------------------------------------------
    @property
    def num_trees(self) -> int:
        return int(self.trees.feat.shape[0])

    @property
    def num_iterations(self) -> int:
        return self.num_trees // self.num_class

    def __getstate__(self):
        # device-resident predictor state (uploaded tree buffers, active
        # masks) is rebuilt on demand and never pickled; the COMPILED
        # executables live in the process-wide _PREDICT_CACHE keyed by
        # shape, so an unpickled model in a serving worker reuses them
        # without recompiling
        d = dict(self.__dict__)
        d.pop("_dev_forest", None)
        d.pop("_dev_active", None)
        d.pop("_predict_fn", None)    # legacy per-instance jit cache
        return d

    def _obj(self) -> Objective:
        return get_objective(self.objective, self.num_class, **self.objective_kwargs)

    def _cat_max_idx(self) -> int:
        """Largest valid category bin id (the binner's catch-all bin)."""
        mb = self.binner_state.get("max_bin") or 0
        if mb > 0:
            return mb - 1
        return int(np.asarray(self.trees.cat_bitset).shape[-1]) * 32 - 1

    def _cat_strict(self) -> bool:
        """Imported stock-LightGBM models (no binner): FindInBitset
        semantics — out-of-range/NaN categories route right."""
        return (self.binner_state.get("max_bin") or 0) <= 0

    def _is_cat(self):
        """[F] bool device mask of categorical features, or None."""
        cats = self.binner_state.get("categorical_features") or ()
        F = self.binner_state["upper_bounds"].shape[0]
        cats = [int(i) for i in cats if 0 <= int(i) < F]
        if not cats:
            return None
        m = np.zeros(F, dtype=bool)
        m[np.asarray(cats, dtype=int)] = True
        return jnp.asarray(m)

    def _tree_bucket(self, t_end: int) -> int:
        """Tree-count bucket for the executable cache: the full model keeps
        its exact shape (the serving hot path must not pay padded-forest
        compute), partial t_end — num_iteration sweeps, best_iteration
        scoring — rounds the iteration count up to a power of two so a
        sweep hits log2 executables instead of one per value. Trees past
        ``t_end`` inside the bucket are masked by the ``active`` argument,
        so bucketing never changes results."""
        T_full = self.num_trees
        if t_end >= T_full:
            return T_full
        bucket = self.num_class * _pow2_ceil(t_end // self.num_class)
        return T_full if bucket >= T_full else bucket

    def _device_forest_args(self, T_pad: int, predict_dtype: str = "f32"):
        """Device-RESIDENT forest arguments for the first ``T_pad`` trees:
        (packed trees, thresholds, base score, categorical mask, missing
        decisions) — uploaded once per bucket, cached on the instance
        (dropped by ``__getstate__``), and passed as jit ARGUMENTS so the
        compiled program itself stays model-independent. Narrow predict
        lanes cache their own entries: the int8 lane packs int8 leaves
        and uint8 bin-id thresholds (quantize funnel), the bf16 lane
        narrows thresholds — so the ``packed_trees`` HBM claim shrinks
        with the lane."""
        cache = self.__dict__.setdefault("_dev_forest", OrderedDict())
        ck = (T_pad, predict_dtype)
        ent = cache.get(ck)
        if ent is None:
            packed = _pack_trees_host(self.trees, T_pad, predict_dtype)
            thr = np.ascontiguousarray(
                np.asarray(self.thr_raw, np.float32)[:T_pad])
            if predict_dtype == "int8":
                thr = _quantize.quantize_thresholds(
                    thr, np.asarray(self.trees.feat)[:T_pad],
                    _quantize.feature_bounds(self.binner_state))
            elif predict_dtype == "bf16":
                thr = _quantize.cast_thresholds_bf16(thr)
            is_cat = self._is_cat()
            mdec = (None if self.missing_dec is None
                    else jnp.asarray(
                        np.ascontiguousarray(self.missing_dec[:T_pad])))
            ent = (jnp.asarray(packed), jnp.asarray(thr),
                   jnp.asarray(self.base_score), is_cat, mdec)
            _hbm.claim("packed_trees", _forest_args_nbytes(ent))
            # bounded LRU: each entry pins a device tree buffer, so a
            # learning-curve sweep over every t_end must not pin O(T^2)
            cache[ck] = ent
            while len(cache) > 4:
                _k, old = cache.popitem(last=False)
                _hbm.release("packed_trees", _forest_args_nbytes(old))
        else:
            cache.move_to_end(ck)
        return ent

    def _device_active(self, T_pad: int, t_end: int):
        """[T_pad] f32 device mask selecting trees below ``t_end``."""
        cache = self.__dict__.setdefault("_dev_active", OrderedDict())
        key = (T_pad, t_end)
        a = cache.get(key)
        if a is None:
            a = jnp.asarray((np.arange(T_pad) < t_end)
                            .astype(np.float32))
            cache[key] = a
            while len(cache) > 8:
                cache.popitem(last=False)
        else:
            cache.move_to_end(key)
        return a

    def resolved_predict_dtype(self, requested: Optional[str] = None) -> str:
        """The effective predict lane for THIS model: delegates to the
        quantize funnel's resolver with this booster's capability flags
        (imported missing-value semantics, binner grid width). What a
        serving worker pins once at startup and surfaces on ``/varz`` —
        the same resolution :meth:`predict_plan` performs per call, so
        the pinned lane and the cache key can never disagree."""
        return _quantize.resolve_predict_dtype(
            requested, has_mdec=self.missing_dec is not None,
            max_bin=int(self.binner_state.get("max_bin") or 0))

    def predict_plan(self, n: int, num_iteration: int = -1,
                     transformed: bool = True,
                     num_features: Optional[int] = None,
                     predict_dtype: Optional[str] = None) -> "PredictPlan":
        """The fused predict executable a batch of ``n`` rows dispatches
        to: its process-wide cache key plus everything needed to build
        (or AOT-export) the program WITHOUT running it.

        This is the one place the predictor cache key is computed —
        :meth:`_predict_device` (the online hot path) and the offline
        serving-bundle builder (``mmlspark_tpu/bundles``) both call it,
        so a key manifested into a bundle at build time is byte-identical
        to the key the restarted worker looks up at serve time. Host-only:
        no device transfer and no compile happen here."""
        if num_iteration is None or num_iteration < 0:
            num_iteration = self.num_iterations
        t_end = min(num_iteration * self.num_class, self.num_trees)
        # row bucket for SMALL batches only: serving's varying micro-batch
        # sizes hit a bounded set of cached executables instead of one
        # trace per size. The bucket ladder is resolved HERE, before the
        # cache key below (the PR 4 rule, lint-anchored): the auto-tuner's
        # measured ladder (tuning site 2 — rungs at the observed
        # workload's batch-size percentiles, pow2 above them) when one is
        # decided, else the static pow2 grid. Large batch scoring keeps
        # its exact shape — padding 600k rows to 1M would waste up to 2x
        # forest compute.
        ladder = _tuning.resolve_bucket_ladder()
        if 0 < n <= 8192:
            n_pad = (_tuning.ladder_pad(n, ladder) if ladder
                     else 1 << (n - 1).bit_length())
        else:
            n_pad = max(n, 1)
        T_pad = self._tree_bucket(t_end)
        M = int(np.asarray(self.trees.feat).shape[1])
        BW = int(np.asarray(self.trees.cat_bitset).shape[-1])
        cat_max_bin = int(self.binner_state.get("max_bin") or 0)
        F_bin = int(self.binner_state["upper_bounds"].shape[0])
        if num_features is None:
            num_features = F_bin
        # the dtype lane is resolved HERE, before the cache key exists
        # (the PR 4 rule, lint-anchored): env/explicit resolution and
        # capability degrades live in the quantize funnel, so a key can
        # never contain an unresolved or unsupported dtype
        predict_dtype = _quantize.resolve_predict_dtype(
            predict_dtype, has_mdec=self.missing_dec is not None,
            max_bin=cat_max_bin)
        spec_key = transform = None
        if transformed:
            spec_key = (self.objective, self.num_class,
                        _freeze_kwargs(self.objective_kwargs))
            transform = score_transform(self.objective, self.num_class,
                                        **self.objective_kwargs)
        # mirrors _is_cat()/_device_forest_args WITHOUT touching the
        # device: the key only records whether the optional args exist
        has_cat = any(0 <= int(i) < F_bin for i in
                      (self.binner_state.get("categorical_features") or ()))
        has_mdec = self.missing_dec is not None
        key = (T_pad, M, BW, n_pad, num_features, self.num_class,
               self.depth_cap, cat_max_bin, has_cat, has_mdec,
               predict_dtype, spec_key)
        depth_cap, K = self.depth_cap, self.num_class
        return PredictPlan(
            key=key, t_end=t_end, n_pad=n_pad, T_pad=T_pad,
            num_features=num_features,
            builder=lambda: _build_predict_program(
                T_pad, M, BW, depth_cap, K, cat_max_bin, transform,
                predict_dtype),
            predict_dtype=predict_dtype)

    def predict_plan_args(self, plan: "PredictPlan"):
        """The exact argument tuple ``plan``'s program is called with —
        real device forest args plus a shape-only stand-in for the
        feature batch. What the bundle builder traces/AOT-lowers against
        (and the prewarm path compiles deserialized exports against)."""
        packed, thr, base, is_cat, mdec = self._device_forest_args(
            plan.T_pad, plan.predict_dtype)
        active = self._device_active(plan.T_pad, plan.t_end)
        x_sds = jax.ShapeDtypeStruct(
            (plan.n_pad, plan.num_features),
            jnp.dtype(_quantize.staging_dtype(plan.predict_dtype)))
        return (packed, thr, base, active, is_cat, mdec, x_sds)

    def _predict_device(self, X: np.ndarray, num_iteration: int,
                        transformed: bool,
                        predict_dtype: Optional[str] = None) -> np.ndarray:
        """Shared device-resident scoring driver for predict/predict_raw.

        Steady state (device args warm) a call is exactly ONE host->device
        transfer (the feature batch, via :func:`_to_device`) and ONE
        device->host transfer (the ``[n, K]`` result, via
        :func:`_from_device`): tree-sum, base-score add and the objective
        transform are fused into the cached executable.

        Narrow lanes stage the batch in the lane's dtype before the
        upload (quartering/halving the h2d bytes); input ALREADY in the
        staged dtype — async-serving slot-table rows quantized at
        admission — passes through untouched.
        """
        _compile_cache.ensure()
        # placement decision (deduped flight event): the fused predictor
        # replicates — its executable cache is keyed on exact batch shapes
        placement.plan_for("gbdt.predict", replicate=True)
        X = np.asarray(X)
        n = X.shape[0]
        plan = self.predict_plan(n, num_iteration, transformed,
                                 num_features=X.shape[1],
                                 predict_dtype=predict_dtype)
        if X.dtype != _quantize.staging_dtype(plan.predict_dtype):
            if plan.predict_dtype == "int8":
                X = _quantize.quantize_features(
                    X, _quantize.feature_bounds(self.binner_state))
            elif plan.predict_dtype == "bf16":
                X = _quantize.cast_features_bf16(X)
            else:
                X = np.asarray(X, dtype=np.float32)
        packed, thr, base, is_cat, mdec = self._device_forest_args(
            plan.T_pad, plan.predict_dtype)
        active = self._device_active(plan.T_pad, plan.t_end)
        fn = _predict_program(plan.key, plan.builder,
                              dtype=plan.predict_dtype)
        n_pad = plan.n_pad
        Xp = np.pad(X, ((0, n_pad - n), (0, 0))) if n_pad != n else X
        out = fn(packed, thr, base, active, is_cat, mdec, _to_device(Xp))
        return _from_device(out)[:n]

    def predict_raw(self, X: np.ndarray, num_iteration: int = -1,
                    predict_dtype: Optional[str] = None) -> np.ndarray:
        """Raw margin scores: [n, num_class] (num_class=1 for
        binary/regression). Device-resident end to end: the per-class
        tree-sum and base-score add run inside the compiled forest program
        (see :meth:`_predict_device`), downloading only ``[n, K]``."""
        return self._predict_device(X, num_iteration, transformed=False,
                                    predict_dtype=predict_dtype)

    def predict(self, X: np.ndarray, num_iteration: int = -1,
                predict_dtype: Optional[str] = None) -> np.ndarray:
        """Transformed prediction (probability for binary/multiclass).
        The sigmoid/softmax/exp transform is fused into the same compiled
        program as the forest evaluation — no raw-score download and
        re-upload between the two. ``predict_dtype`` selects the scoring
        lane (``f32``/``bf16``/``int8``; None reads
        ``MMLSPARK_TPU_PREDICT_DTYPE``) — see ``quantize.py``."""
        return self._predict_device(X, num_iteration, transformed=True,
                                    predict_dtype=predict_dtype)

    def predict_streamed(self, source, *, chunk_rows: int = 262_144,
                         out_dir=None, num_iteration: int = -1,
                         raw: bool = False):
        """Score ``.npy`` feature shards in bounded row chunks —
        larger-than-RAM inference. Each chunk runs exactly
        :meth:`predict` / :meth:`predict_raw`, so streamed outputs equal
        in-memory outputs bit-for-bit. The reference gets this shape for
        free from Spark partition streaming
        (io/binary/BinaryFileReader.scala:20 feeding the native scorer,
        lightgbm/LightGBMBooster.scala:250); here it is an explicit
        bounded-chunk loop (io/streaming.py). Returns concatenated scores,
        or output shard paths with ``out_dir``.
        """
        from ...io.streaming import stream_apply

        if raw:
            fn = lambda c: self.predict_raw(c, num_iteration)   # noqa: E731
        else:
            fn = lambda c: self.predict(c, num_iteration)       # noqa: E731
        return stream_apply(source, fn, chunk_rows=chunk_rows,
                            out_dir=out_dir)

    def predict_contrib_streamed(self, source, *,
                                 chunk_rows: int = 16_384, out_dir=None,
                                 method: str = "treeshap"):
        """Per-feature contributions over ``.npy`` feature shards in
        bounded row chunks — larger-than-RAM explanation. Each chunk runs
        exactly :meth:`predict_contrib` (TreeSHAP is row-independent, so
        streamed == in-memory bit-for-bit); the output is [n, (F+1)*K],
        F+1 times wider than the input, hence the smaller default chunk.
        Reference bar: featuresShapCol over streamed partitions
        (lightgbm/LightGBMBooster.scala:250-269). Returns concatenated
        contributions, or output shard paths with ``out_dir``.
        """
        from ...io.streaming import stream_apply

        if method not in ("treeshap", "saabas"):
            # validate BEFORE stream_apply clears any existing out_dir
            # shards: a typo'd method must not destroy a prior run's output
            raise ValueError(
                f"unknown contribution method {method!r}; expected "
                "'treeshap' or 'saabas'")
        return stream_apply(
            source, lambda c: self.predict_contrib(c, method=method),
            chunk_rows=chunk_rows, out_dir=out_dir)

    def _check_missing_routing(self, X: np.ndarray) -> None:
        """The SHAP/leaf paths route NaN left unconditionally. For imported
        models storing different missing handling (missing_dec set), inputs
        that would hit those rules must not silently diverge from the
        decision_type-aware predict() path."""
        if self.missing_dec is None:
            return
        # check the float32 view the SHAP/leaf paths actually traverse:
        # f64 values that underflow to 0.0 in f32 must not slip the guard
        X = np.asarray(X, dtype=np.float32)
        mt = (self.missing_dec >> 2) & 3
        internal = ~np.asarray(self.trees.is_leaf)
        if (bool(((mt == 1) & internal).any())
                and (np.abs(X) <= 1e-35).any()):
            raise NotImplementedError(
                "predict_contrib/predict_leaf do not implement "
                "zero-as-missing routing for imported models; use "
                "predict()/predict_raw()")
        if np.isnan(X).any():
            raise NotImplementedError(
                "predict_contrib/predict_leaf route NaN left "
                "unconditionally, but this imported model stores different "
                "missing handling; impute NaNs or use "
                "predict()/predict_raw()")

    def predict_contrib(self, X: np.ndarray,
                        method: str = "treeshap") -> np.ndarray:
        """Per-feature contributions ([n, (F+1) * num_class]; the last slot
        of each class block is the bias/expected value).

        ``method="treeshap"`` (default — parity with the reference's
        ``featuresShapCol``, lightgbm/LightGBMBooster.scala:250-269, which
        rides LightGBM's native TreeSHAP): exact Shapley values of the
        cover-conditional value function. Runs the fixed-shape per-leaf
        device formulation (:mod:`.treeshap_device` — leaf paths folded on
        host, all O(depth^2) Shapley-weight work jitted and vectorized
        over leaves x rows); set ``MMLSPARK_TPU_SHAP_HOST=1`` to force the
        reference host recursion (:mod:`.treeshap`, Lundberg Alg. 2) the
        device path is pinned against.

        ``method="saabas"``: fast on-device path attribution — walking
        root->leaf attributes the change in expected node value to the
        split feature. Sums to the same prediction but is NOT Shapley on
        correlated features; kept as the throughput option.
        """
        self._check_missing_routing(X)
        if method == "treeshap":
            # default by backend: the fixed-shape device program is built
            # for TPU (tiny fused VPU/MXU ops, one scanned executable);
            # measured on the XLA CPU backend it loses to the numpy host
            # recursion, so CPU defaults to host. Env overrides both ways.
            force_host = os.environ.get("MMLSPARK_TPU_SHAP_HOST") == "1"
            force_dev = os.environ.get("MMLSPARK_TPU_SHAP_DEVICE") == "1"
            on_accel = jax.devices()[0].platform not in ("cpu",)
            if force_dev or (on_accel and not force_host):
                from .treeshap_device import shap_values_device
                return shap_values_device(self, X)
            from .treeshap import shap_values
            return shap_values(self, X)
        if method != "saabas":
            raise ValueError(
                f"unknown contribution method {method!r}: use 'treeshap' "
                "(exact, host) or 'saabas' (approximate, device)")
        X = np.asarray(X, dtype=np.float32)
        Xd = jnp.asarray(X)
        trees = jax.tree_util.tree_map(jnp.asarray, self.trees)
        thr = jnp.asarray(self.thr_raw)
        is_cat = self._is_cat()
        cat_max_idx = self._cat_max_idx()
        cat_strict = self._cat_strict()
        n, F = X.shape
        K = self.num_class
        T = self.num_trees
        class_of_tree = jnp.arange(T, dtype=jnp.int32) % K

        def scan_body(carry, xs):
            # accumulate per-class sums: peak memory [K, n, F], not [T, n, F]
            csum, rsum = carry
            ts, thr_t, k = xs
            node = jnp.zeros(n, dtype=jnp.int32)
            contrib = jnp.zeros((n, F), dtype=jnp.float32)

            def body(_, st):
                node, contrib = st
                f = ts.feat[node]
                x = jnp.take_along_axis(Xd, f[:, None], axis=1)[:, 0]
                go_left = ~(x > thr_t[node])
                if is_cat is not None:
                    from .growth import cat_member
                    go_left = jnp.where(
                        is_cat[f],
                        cat_member(ts.cat_bitset[node], x, cat_max_idx,
                                   cat_strict),
                        go_left)
                nxt = jnp.where(go_left, ts.left[node], ts.right[node])
                internal = ~ts.is_leaf[node]
                delta = ts.node_value[nxt] - ts.node_value[node]
                contrib = contrib.at[jnp.arange(n), f].add(
                    jnp.where(internal, delta, 0.0))
                return jnp.where(internal, nxt, node), contrib

            _, contrib = jax.lax.fori_loop(0, self.depth_cap, body,
                                           (node, contrib))
            return (csum.at[k].add(contrib),
                    rsum.at[k].add(ts.node_value[0])), None

        init = (jnp.zeros((K, n, F), jnp.float32), jnp.zeros(K, jnp.float32))
        (csum, rsum), _ = jax.lax.scan(scan_body, init,
                                       (trees, thr, class_of_tree))
        csum, rsum = np.asarray(csum), np.asarray(rsum)
        out = np.zeros((n, (F + 1) * K), dtype=np.float32)
        for k in range(K):
            out[:, k * (F + 1):k * (F + 1) + F] = csum[k]
            out[:, k * (F + 1) + F] = self.base_score[k] + rsum[k]
        return out

    def predict_leaf(self, X: np.ndarray) -> np.ndarray:
        """Per-tree leaf index for each row: [n, T] (predLeaf parity,
        reference: lightgbm/LightGBMBooster.scala:250-269)."""
        X32 = np.asarray(X, dtype=np.float32)
        self._check_missing_routing(X32)
        X = jnp.asarray(X32)
        trees = jax.tree_util.tree_map(jnp.asarray, self.trees)
        n = X.shape[0]

        is_cat = self._is_cat()
        cat_max_idx = self._cat_max_idx()
        cat_strict = self._cat_strict()

        def one_tree(ts, thr):
            node = jnp.zeros(n, dtype=jnp.int32)

            def body(_, node):
                f = ts.feat[node]
                x = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
                go_left = ~(x > thr[node])
                if is_cat is not None:
                    from .growth import cat_member
                    go_left = jnp.where(
                        is_cat[f],
                        cat_member(ts.cat_bitset[node], x, cat_max_idx,
                                   cat_strict),
                        go_left)
                nxt = jnp.where(go_left, ts.left[node], ts.right[node])
                return jnp.where(ts.is_leaf[node], node, nxt)

            return jax.lax.fori_loop(0, self.depth_cap, body, node)

        return np.asarray(jax.vmap(one_tree)(trees, jnp.asarray(self.thr_raw))).T

    # -- introspection -----------------------------------------------------------
    def feature_importances(self, importance_type: str = "split") -> np.ndarray:
        """Per-feature importances (reference: LightGBMBooster.scala:306)."""
        F = self.binner_state["upper_bounds"].shape[0]
        out = np.zeros(F, dtype=np.float64)
        internal = ~self.trees.is_leaf
        feats = self.trees.feat[internal]
        if importance_type == "split":
            np.add.at(out, feats, 1.0)
        elif importance_type == "gain":
            np.add.at(out, feats, self.trees.split_gain[internal])
        else:
            raise ValueError(f"importance_type must be split|gain, got {importance_type}")
        return out

    # -- persistence -------------------------------------------------------------
    def save(self, path: str) -> None:
        arrays = {f"tree_{k}": v for k, v in self.trees._asdict().items()}
        arrays["thr_raw"] = self.thr_raw
        arrays["base_score"] = self.base_score
        arrays["binner_upper_bounds"] = self.binner_state["upper_bounds"]
        if self.missing_dec is not None:
            arrays["missing_dec"] = self.missing_dec
        meta = dict(
            num_class=self.num_class, objective=self.objective,
            objective_kwargs=self.objective_kwargs, depth_cap=self.depth_cap,
            best_iteration=self.best_iteration, eval_history=self.eval_history,
            binner=dict(max_bin=self.binner_state["max_bin"],
                        sample_count=self.binner_state["sample_count"],
                        seed=self.binner_state["seed"],
                        num_features=self.binner_state["num_features"],
                        categorical_features=list(
                            self.binner_state.get("categorical_features")
                            or []),
                        max_bin_by_feature=self.binner_state.get(
                            "max_bin_by_feature"),
                        feature_names=self.binner_state.get(
                            "feature_names")),
        )
        arrays["meta_json"] = np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8)
        np.savez_compressed(path, **arrays)

    @staticmethod
    def load(path: str) -> "Booster":
        if not str(path).endswith(".npz"):
            path = str(path) + ".npz"
        z = np.load(path, allow_pickle=False)
        meta = json.loads(bytes(z["meta_json"]).decode())
        trees = Tree(**_with_tree_defaults(
            {k: z[f"tree_{k}"] for k in Tree._fields if f"tree_{k}" in z}))
        binner_state = dict(meta["binner"])
        binner_state["upper_bounds"] = z["binner_upper_bounds"]
        b = Booster(
            trees, z["thr_raw"], meta["num_class"], z["base_score"],
            meta["objective"], meta["depth_cap"], binner_state,
            meta["best_iteration"], meta["eval_history"],
            meta.get("objective_kwargs") or {})
        if "missing_dec" in z:
            b.missing_dec = z["missing_dec"]
        return b

    def to_lightgbm_string(self) -> str:
        """Stock-LightGBM ``tree`` v3 text model string — loads in any
        LightGBM tooling (saveNativeModel parity, reference:
        LightGBMClassifier.scala:172-194, LightGBMBooster.scala:289)."""
        from .lgbm_format import to_lightgbm_string
        return to_lightgbm_string(self)

    @staticmethod
    def from_lightgbm_string(s: str) -> "Booster":
        """Load a LightGBM text model (produced by stock LightGBM or by
        ``to_lightgbm_string``). base_score is 0: LightGBM folds any init
        score into the first iteration's leaves."""
        from .lgbm_format import parse_lightgbm_string
        (trees, thr_raw, K, objective, kwargs, F,
         cat_features, missing_dec) = parse_lightgbm_string(s)
        M = trees.feat.shape[1]
        depth_cap = max(1, (M + 1) // 2 - 1)
        binner_state = dict(upper_bounds=np.zeros((F, 1), np.float32),
                            max_bin=0, sample_count=0, seed=0,
                            num_features=F,
                            categorical_features=list(cat_features))
        b = Booster(trees, thr_raw, K, np.zeros(K, np.float32), objective,
                    depth_cap, binner_state, objective_kwargs=kwargs)
        b.missing_dec = missing_dec
        return b

    def model_string(self) -> str:
        """Portable JSON model string (the framework's internal format:
        keeps binner state, base score and history exactly — used by
        checkpoints and pipeline persistence). For LightGBM-tool interop
        use ``to_lightgbm_string``; ``from_string`` auto-detects both."""
        d = {
            "version": 1,
            "num_class": self.num_class,
            "objective": self.objective,
            "objective_kwargs": self.objective_kwargs,
            "depth_cap": self.depth_cap,
            "best_iteration": self.best_iteration,
            "base_score": self.base_score.tolist(),
            "thr_raw": self.thr_raw.tolist(),
            "trees": {k: np.asarray(v).tolist() for k, v in self.trees._asdict().items()},
            "binner": {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                       for k, v in self.binner_state.items()},
        }
        if self.missing_dec is not None:
            d["missing_dec"] = self.missing_dec.tolist()
        return json.dumps(d)

    @staticmethod
    def from_string(s: str) -> "Booster":
        if s.lstrip().startswith("tree"):
            return Booster.from_lightgbm_string(s)
        d = json.loads(s)
        trees = Tree(**_with_tree_defaults(
            {k: np.asarray(v) for k, v in d["trees"].items()}))
        binner_state = dict(d["binner"])
        binner_state["upper_bounds"] = np.asarray(
            binner_state["upper_bounds"], dtype=np.float32)
        b = Booster(trees, np.asarray(d["thr_raw"], np.float32), d["num_class"],
                    np.asarray(d["base_score"], np.float32), d["objective"],
                    d["depth_cap"], binner_state, d["best_iteration"],
                    objective_kwargs=d.get("objective_kwargs") or {})
        if d.get("missing_dec") is not None:
            b.missing_dec = np.asarray(d["missing_dec"], np.uint8)
        return b


# ---------------------------------------------------------------------------
# Training
# ---------------------------------------------------------------------------


def _fused_es_scan(one_iter, state0, num_iterations: int,
                   early_stopping_rounds: int, higher_is_better: bool,
                   track_metric: bool, tol: float = 0.0):
    """Shared on-device training-loop harness for the fused paths (plain
    gbdt with validation, dart with/without validation).

    ``one_iter(it, state) -> (state, packed_trees [Tp] f32/i32,
    metric f32 scalar)`` — metric is ignored when ``track_metric`` is
    False. Returns ``(buf [T, Tp], mbuf [T], n_done i32, best_it i32)``;
    without metric tracking the scan runs every iteration and
    ``best_it = -1``. With it, iteration 0 runs inline (its packed length
    sizes the static buffer) and a ``lax.while_loop`` applies the same
    stopping bookkeeping the host loops use. ``tol`` is the
    improvementTolerance: an iteration only counts as improved when it
    beats the best metric by more than tol. The default 0.0 mirrors the
    host's strict compare; note a device-side tol below one f32 ulp of
    the metric value vanishes (the compare runs in f32, the host's in
    f64) — equivalence holds because the metric itself is f32-quantized,
    so any sub-ulp tolerance makes the same decision on both sides."""
    if not track_metric:
        def it_body(state, it):
            state, packed, _ = one_iter(it, state)
            return state, packed

        _, buf = lax.scan(it_body, state0,
                          jnp.arange(num_iterations, dtype=jnp.int32))
        return (buf, jnp.full((num_iterations,), jnp.nan, jnp.float32),
                jnp.int32(num_iterations), jnp.int32(-1))

    def track(best, best_it, rni, m, it):
        if higher_is_better:
            improved = m > best + jnp.float32(tol)
        else:
            improved = m < best - jnp.float32(tol)
        return (jnp.where(improved, m, best),
                jnp.where(improved, it, best_it),
                jnp.where(improved, 0, rni + 1))

    it0 = jnp.int32(0)
    state, packed0, m0 = one_iter(it0, state0)
    buf = jnp.zeros((num_iterations, packed0.shape[0]),
                    packed0.dtype).at[0].set(packed0)
    mbuf = jnp.full((num_iterations,), jnp.nan, jnp.float32).at[0].set(m0)
    init_best = jnp.float32(-jnp.inf if higher_is_better else jnp.inf)
    best, best_it, rni = track(init_best, jnp.int32(-1), jnp.int32(0),
                               m0, it0)

    def cond(carry):
        it = carry[0]
        keep = it < num_iterations
        if early_stopping_rounds > 0:
            keep &= carry[4] < early_stopping_rounds
        return keep

    def body(carry):
        it, state, best, best_it, rni, buf, mbuf = carry
        state, packed, m = one_iter(it, state)
        buf = lax.dynamic_update_index_in_dim(buf, packed, it, 0)
        mbuf = mbuf.at[it].set(m)
        best, best_it, rni = track(best, best_it, rni, m, it)
        return it + 1, state, best, best_it, rni, buf, mbuf

    it, _, _, best_it, _, buf, mbuf = lax.while_loop(
        cond, body, (jnp.int32(1), state, best, best_it, rni, buf, mbuf))
    return buf, mbuf, it, best_it


def _grow_with_warmup(grow, it_scalar, cfg, qk, binned_t, grad_k, hess_k,
                      row_mask, fmask, *, axis_name, is_cat):
    """Dispatch one tree growth honoring ``quant_warmup_iters``: iterations
    below the warmup count grow at full precision, later ones ride the int8
    quantized-histogram path (GrowConfig.quant_warmup_iters rationale). Both
    variants live in ONE ``lax.cond`` so the fused scans and the
    early-stopping while_loop keep their traced iteration index; the
    predicate derives from the replicated scan counter, so the branch cannot
    diverge across shards."""
    if not cfg.quantized_grad:
        return grow(binned_t, grad_k, hess_k, row_mask, fmask, cfg,
                    axis_name=axis_name, is_cat=is_cat, qkey=None)
    if cfg.quant_warmup_iters <= 0:
        return grow(binned_t, grad_k, hess_k, row_mask, fmask, cfg,
                    axis_name=axis_name, is_cat=is_cat, qkey=qk)
    fp_cfg = cfg._replace(quantized_grad=False)
    return lax.cond(
        it_scalar < cfg.quant_warmup_iters,
        lambda: grow(binned_t, grad_k, hess_k, row_mask, fmask, fp_cfg,
                     axis_name=axis_name, is_cat=is_cat, qkey=None),
        lambda: grow(binned_t, grad_k, hess_k, row_mask, fmask, cfg,
                     axis_name=axis_name, is_cat=is_cat, qkey=qk))


def _grow_axis_for(mesh, cfg) -> "str | None":
    """Collective axis for tree growth: None on a single-shard data axis so
    depthwise histogram subtraction (single-device only) can engage — psum
    over a size-1 axis is the identity it replaces. Voting keeps the axis
    even at size 1: its top-2k ballot restricts the split search and must
    behave identically regardless of shard count — and so does a resolved
    hist_blocks (the deterministic blocked reduction must run the SAME
    gather-fold program on a 1-device mesh that it runs on 8)."""
    det = isinstance(cfg.hist_blocks, int) and cfg.hist_blocks > 1
    return ("data" if (dict(mesh.shape).get("data", 1) > 1 or cfg.voting
                       or det)
            else None)


def _measure_hist_engine(engine: str, binned_d, stats_d,
                         num_bins: int) -> float:
    """One measured histogram round for the auto-tuner's engine
    calibration: compile + warm, then time a single steady-state
    execution of ``histogram_cols`` under the candidate engine. Runs a
    standalone jit over an unsharded, undonated calibration slice — the
    full step program (sharded, donated buffers) is never replayed here,
    and the hint is always restored before returning."""
    from ...ops import histogram as _hist
    _hist.set_tuned_engine(engine)
    try:
        fn = jax.jit(lambda b, s: _hist.histogram_cols(b, s, num_bins))
        jax.block_until_ready(fn(binned_d, stats_d))
        t0 = time.perf_counter()
        jax.block_until_ready(fn(binned_d, stats_d))
        return time.perf_counter() - t0
    finally:
        _hist.set_tuned_engine("")


#: row cap for the calibration slice: large enough that engine ranking
#: matches full-dataset behavior, small enough that calibration stays a
#: negligible fraction of the first fit
_HIST_CAL_ROWS = 16384


def train_booster(
    X: Optional[np.ndarray] = None,
    y: Optional[np.ndarray] = None,
    weight: Optional[np.ndarray] = None,
    *,
    dataset: Optional[LightGBMDataset] = None,
    objective: str = "regression",
    num_class: int = 1,
    num_iterations: int = 100,
    cfg: Optional[GrowConfig] = None,
    max_bin: int = 255,
    bin_sample_count: int = 200_000,
    feature_fraction: float = 1.0,
    bagging_fraction: float = 1.0,
    bagging_freq: int = 0,
    seed: int = 0,
    valid_set: Optional[Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]] = None,
    early_stopping_rounds: int = 0,
    init_booster: Optional[Booster] = None,
    boost_from_average: bool = True,
    mesh: Optional[Mesh] = None,
    objective_kwargs: Optional[dict] = None,
    iteration_callback: Optional[Callable[[int, Dict[str, float]], None]] = None,
    metric_eval_period: int = 1,
    row_valid: Optional[np.ndarray] = None,
    boosting_type: str = "gbdt",
    top_rate: float = 0.2,
    other_rate: float = 0.1,
    drop_rate: float = 0.1,
    max_drop: int = 50,
    skip_drop: float = 0.5,
    drop_seed: int = 4,
    checkpoint_dir: Optional[str] = None,
    checkpoint_period: int = 10,
    categorical_features=(),
    bin_dtype=None,
    pos_bagging_fraction: float = 1.0,
    neg_bagging_fraction: float = 1.0,
    early_stopping_tolerance: float = 0.0,
    provide_training_metric: bool = False,
    max_bin_by_feature=None,
    eval_metric_name: Optional[str] = None,
) -> Booster:
    """Train a boosted ensemble, rows sharded over the mesh ``data`` axis.

    The per-iteration schedule matches the reference's trainCore
    (TrainUtils.scala:220-315): update one iteration (K trees for K classes),
    evaluate on the optional validation set, maybe early-stop;
    ``iteration_callback`` is the delegate hook
    (reference: lightgbm/LightGBMDelegate.scala).

    ``dataset`` (a pre-built :class:`LightGBMDataset`) skips the per-call
    ingest — binner fit, feature transfer, device binning — the way the
    reference trains against a pre-constructed native dataset
    (lightgbm/LightGBMDataset.scala). When given, ``X``/``y``/``weight``/
    ``max_bin``/``bin_sample_count``/``categorical_features``/``row_valid``/
    ``mesh`` are taken from the dataset (``X`` may still be passed alongside
    for ``init_booster`` warm starts, which score raw rows).
    """
    # persistent compile cache (MMLSPARK_TPU_COMPILE_CACHE_DIR): wire it
    # before the first program of this fit traces, so serving workers and
    # repeat CLI fits skip the cold multi-second XLA compile
    _compile_cache.ensure()
    # each fit starts with clean training-health sentinel windows — a
    # diverging fit yesterday must not poison today's gauge
    _watchdog.reset_training_health("gbdt")
    # resolve backend-adaptive tri-states ("auto" hist_subtraction /
    # compact_selector) to concrete values up front: cfg flows into the
    # checkpoint fingerprint and every compiled-program cache key below,
    # and an unresolved sentinel there would alias programs across
    # backends (lint-pinned in tests/test_lint.py)
    cfg = resolve_growth_backend(cfg or GrowConfig())
    if dataset is not None and checkpoint_dir is not None:
        raise ValueError(
            "checkpointDir requires raw X/y arrays (the resume fingerprint "
            "hashes them); pass arrays instead of a pre-built dataset")
    if dataset is None and (X is None or y is None):
        raise ValueError("either X and y arrays or dataset= must be given")
    if dataset is not None and (y is not None or weight is not None
                                or row_valid is not None):
        # X alone is allowed alongside dataset= (init_booster warm starts
        # score raw rows); anything else would be silently ignored in favor
        # of the dataset's stored arrays — refuse instead
        raise ValueError(
            "y/weight/row_valid are baked into the dataset at construct() "
            "time; do not pass them alongside dataset=")
    # --- step-level checkpoint resume (SURVEY.md §5): the newest checkpoint
    # becomes the warm-start booster and already-completed iterations are
    # skipped; the caller's init_booster is subsumed (training that produced
    # the checkpoint already started from it). Checkpoints carry a
    # data+config fingerprint — a stale checkpoint from different data or
    # hyperparameters is ignored, not silently resumed.
    if boosting_type not in ("gbdt", "goss", "rf", "dart"):
        raise ValueError(
            f"boostingType {boosting_type!r} is not supported "
            "(supported: gbdt, rf, dart, goss)")
    if boosting_type in ("rf", "dart"):
        if init_booster is not None:
            raise ValueError(
                f"warm start (modelString/numBatches) is not supported with "
                f"boostingType={boosting_type!r}: its trees carry "
                "normalization state that a warm-start prefix lacks")
        if checkpoint_dir is not None:
            raise ValueError(
                f"checkpointDir is not supported with "
                f"boostingType={boosting_type!r} (gbdt/goss only)")
    stratified_bagging = (pos_bagging_fraction != 1.0
                          or neg_bagging_fraction != 1.0)
    if boosting_type == "rf" and not (
            (bagging_fraction < 1.0 or stratified_bagging)
            and bagging_freq > 0):
        raise ValueError(
            "boostingType='rf' requires bagging: set baggingFraction < 1.0 "
            "(or pos/negBaggingFraction) and baggingFreq > 0 (LightGBM "
            "semantics — without bagging every random-forest tree would be "
            "identical)")
    if stratified_bagging:
        # LightGBM: pos/neg bagging fractions are a binary-only, set-together
        # stratified alternative to bagging_fraction
        if objective != "binary":
            raise ValueError(
                "posBaggingFraction/negBaggingFraction apply to the binary "
                f"objective only (got objective={objective!r})")
        if bagging_freq <= 0:
            raise ValueError(
                "posBaggingFraction/negBaggingFraction need baggingFreq > 0")
        if not (0.0 < pos_bagging_fraction <= 1.0
                and 0.0 < neg_bagging_fraction <= 1.0):
            raise ValueError(
                "pos/negBaggingFraction must be in (0, 1]; got "
                f"{pos_bagging_fraction}/{neg_bagging_fraction}")
        if boosting_type == "goss":
            raise ValueError("goss does its own gradient-based sampling; "
                             "pos/negBaggingFraction do not apply")
        if boosting_type == "dart":
            raise ValueError(
                "pos/negBaggingFraction are supported for gbdt/rf; dart's "
                "fused drop-schedule path keeps plain baggingFraction")
        # LightGBM semantics: when the stratified fractions are set they
        # replace bagging_fraction entirely — reject the ambiguous combo
        # rather than silently ignoring one of them
        if bagging_fraction < 1.0:
            raise ValueError(
                "set either baggingFraction or pos/negBaggingFraction, "
                "not both (the stratified fractions replace it)")
    if early_stopping_tolerance < 0:
        raise ValueError(
            f"improvementTolerance must be >= 0, got {early_stopping_tolerance}")
    if provide_training_metric and boosting_type in ("rf", "dart"):
        raise ValueError(
            "isProvideTrainingMetric is supported for gbdt/goss (rf keeps "
            "train scores at the base margin and dart rescales past trees "
            "each iteration, so neither has a running train margin to "
            "evaluate)")
    # metric override (LightGBM `metric` param): validated against the
    # objective family before anything traces
    requested_metric = (eval_metric_name or "").strip() or None
    eval_override = requested_metric
    auc_host = False
    if eval_override:
        from .objectives import SUPPORTED_EVAL_METRICS
        fam = objective if objective in ("binary", "multiclass",
                                         "lambdarank") else "_regression"
        allowed = SUPPORTED_EVAL_METRICS[fam]
        if eval_override not in allowed:
            raise ValueError(
                f"metric={eval_override!r} is not supported for the "
                f"{objective!r} objective (choose from {allowed})")
        if boosting_type == "dart":
            raise ValueError("metric overrides are not supported with "
                             "dart (its fused drop-schedule eval keeps the "
                             "objective default)")
        auc_host = eval_override == "auc"
        if auc_host:
            eval_override = None      # device steps keep the default metric
            if jax.process_count() > 1:
                raise ValueError(
                    "metric='auc' computes the exact rank statistic on "
                    "the host and needs the validation scores addressable "
                    "in one process")

    ckpt_mgr = None
    ckpt_fingerprint = None
    iterations_done = 0
    user_init_booster = init_booster
    resume_state: Optional[dict] = None
    if checkpoint_dir is not None:
        from ...utils.checkpoint import CheckpointManager, data_fingerprint
        cfg_norm = cfg._replace(num_bins=max_bin)
        ckpt_fingerprint = data_fingerprint(
            np.asarray(X, np.float32), np.asarray(y, np.float32),
            None if weight is None else np.asarray(weight, np.float32),
            # the warm-start model is part of run identity: resuming a
            # checkpoint that subsumed a *different* init would be silent.
            # Every param that shapes the trained model belongs here —
            # bin_sample_count/boost_from_average change bin boundaries /
            # the base score, so a changed value must invalidate resume.
            config=(objective, num_class, cfg_norm, max_bin, bin_sample_count,
                    tuple(int(i) for i in categorical_features),
                    boost_from_average, feature_fraction,
                    bagging_fraction, bagging_freq, seed, boosting_type,
                    top_rate, other_rate,
                    pos_bagging_fraction, neg_bagging_fraction,
                    early_stopping_tolerance,
                    requested_metric,
                    None if max_bin_by_feature is None
                    else tuple(int(b) for b in max_bin_by_feature),
                    sorted((objective_kwargs or {}).items()),
                    None if user_init_booster is None
                    else user_init_booster.model_string()))
        # namespaced by fingerprint: concurrent runs sharing checkpoint_dir
        # (sweeps) never purge each other's files
        ckpt_mgr = CheckpointManager(checkpoint_dir,
                                     namespace=ckpt_fingerprint[:12])
        # resolved here, before any compiled-program cache key is built
        # (the resolve-before-cache-key rule): the dump hook itself is
        # armed much later, next to the round loop
        dump_on_unhealthy = os.environ.get(
            "MMLSPARK_TPU_CHECKPOINT_ON_UNHEALTHY",
            "").lower() in ("1", "true", "yes")
        latest = ckpt_mgr.latest_matching(ckpt_fingerprint)
        # MMLSPARK_TPU_STRICT_RESUME=1: resume-or-die — checkpoints that
        # exist but mismatch (changed data/config/warm start) raise a
        # CheckpointMismatchError instead of silently retraining from
        # scratch. Only probed when the namespaced resume found NOTHING
        # (the happy path must not unpickle every file twice), and the
        # probe checks ACROSS namespaces: the un-namespaced inspection
        # view sees the mismatched files a namespaced manager filters
        # out (config drift changes the namespace).
        if latest is None and os.environ.get(
                "MMLSPARK_TPU_STRICT_RESUME",
                "").lower() in ("1", "true", "yes"):
            # a MATCH here is a legacy un-namespaced checkpoint the
            # namespaced manager can't see — resume from it rather than
            # silently retraining (the outcome strict mode forbids)
            latest = CheckpointManager(checkpoint_dir).latest_matching(
                ckpt_fingerprint, purge_stale=False, strict=True)
        if latest is not None:
            step, payload = latest
            init_booster = Booster.from_string(payload["model"])
            iterations_done = payload["iteration"] + 1
            resume_state = payload
            if iterations_done >= num_iterations:
                # checkpoint already covers the request: truncate to the
                # warm-start prefix plus the requested trained iterations
                prior = payload.get("prior_iterations", 0)
                return _truncate_booster(init_booster,
                                         prior + num_iterations)

    tw = _PhaseTimer()
    if boosting_type == "rf":
        # random forest: no shrinkage; the averaged ensemble is scaled at
        # finalize time instead (LightGBM rf semantics)
        cfg = cfg._replace(learning_rate=1.0)
    objective_kwargs = objective_kwargs or {}
    obj = get_objective(objective, num_class, **objective_kwargs)
    K = obj.num_scores

    if dataset is None:
        dataset = LightGBMDataset.construct(
            _densify(X), y, weight, max_bin=max_bin,
            bin_sample_count=bin_sample_count, seed=seed,
            categorical_features=categorical_features, mesh=mesh,
            row_valid=row_valid, bin_dtype=bin_dtype,
            max_bin_by_feature=max_bin_by_feature, _timer=tw)
    mesh = dataset.mesh
    binner = dataset.binner
    max_bin = dataset.max_bin
    cfg = cfg._replace(num_bins=max_bin)
    n, n_pad, F = dataset.n, dataset.n_pad, dataset.num_features
    Xbt_d, y_d, w_d, vmask_d = (dataset.Xbt_d, dataset.y_d, dataset.w_d,
                                dataset.vmask_d)
    # categorical routing mask: None when absent so the purely-numeric path
    # compiles with zero bitset overhead
    is_cat_np = binner.is_cat_mask()
    is_cat_j = jnp.asarray(is_cat_np) if is_cat_np.any() else None
    nshards = meshlib.num_shards(mesh)

    # placement + determinism resolution — BEFORE any compiled-program
    # cache key below (the PR 4 resolve-before-cache-key rule): the plan
    # resolves the backend (which decides buffer donation) and emits the
    # placement flight event; hist_blocks resolves the canonical reduction
    # geometry. Both land in cfg / the cache key as concrete values.
    plan = placement.plan_for("gbdt.fit", mesh=mesh, rows=n_pad,
                              boosting=boosting_type)
    cfg = cfg._replace(hist_blocks=placement.resolve_hist_blocks(
        cfg.hist_blocks, mesh, n_pad, voting=cfg.voting))
    deterministic = isinstance(cfg.hist_blocks, int) and cfg.hist_blocks > 1

    # auto-tuned histogram engine (tuning site 1) — resolved HERE, before
    # the compiled-program cache key below, because the hint flows into
    # that key through resolve_engine(). Only `auto` consults the tuner
    # (an explicit MMLSPARK_TPU_HIST_ENGINE pin is the opt-out); the
    # first tuned fit of a shape bucket calibrates each candidate engine
    # with one real histogram round over a slice of this dataset's own
    # binned columns, later fits/processes answer from the store.
    from ...ops import histogram as _hist
    _hist_env = (os.environ.get("MMLSPARK_TPU_HIST_ENGINE")
                 or "auto").strip().lower()
    if _tuning.enabled() and _hist_env in ("auto", ""):
        _cal: Dict[str, tuple] = {}

        def _measure(eng: str) -> float:
            if "data" not in _cal:
                rows = int(min(n_pad, _HIST_CAL_ROWS))
                # gather once, share across candidates; unsharded (the
                # calibration program must not depend on the mesh)
                xbt = np.asarray(placement.to_host(Xbt_d))[:, :rows]
                _cal["data"] = (placement.to_device(np.ascontiguousarray(xbt)),
                                placement.to_device(
                                    np.ones((2, rows), np.float32)))
            return _measure_hist_engine(eng, *_cal["data"], max_bin)

        _hist.set_tuned_engine(_tuning.resolve_hist_engine(
            n_pad, F, max_bin, _hist.engine_candidates(),
            measure=_measure) or "")

    # base score (replicated scalar per class). Computed on device from the
    # already-sharded label/weight arrays, then broadcast to the initial
    # score matrix on device — no dataset-sized host round-trips.
    if init_booster is not None:
        base = init_booster.base_score
        if X is None:
            raise ValueError(
                "init_booster warm start scores raw rows: pass X alongside "
                "dataset=")
        # checkpoint resume restores the EXACT accumulated score matrix
        # the interrupted run held (downloaded into the payload at save
        # time): re-deriving it via predict_raw would replay the forest
        # in a different float-summation order and the resumed run would
        # drift from the uninterrupted one by an ulp — enough to pick
        # different splits. Stored state is what makes a failpoint-killed
        # fit resume to bit-identical trees. Shape-guarded fallback:
        # an old-format checkpoint re-scores through the model.
        resume_scores = (None if resume_state is None
                         else resume_state.get("scores"))
        if resume_scores is not None and \
                np.asarray(resume_scores).shape == (n, K):
            scores0 = np.asarray(resume_scores, np.float32)
        else:
            scores0 = init_booster.predict_raw(
                np.asarray(_densify(X), np.float32))  # [n, K]
        scores_d, _ = placement.shard_rows(scores0.astype(np.float32), mesh)
    elif boost_from_average:
        if deterministic:
            # topology-independent base score: a jit reduction over sharded
            # arrays lets GSPMD pick a device-count-dependent f32 combine
            # order, so the deterministic mode gathers the (one-time,
            # [n]-sized) label/weight arrays and computes the init score on
            # the default device — the same program at every device count.
            base_d = jnp.broadcast_to(
                obj.init_score(
                    placement.to_device(placement.to_host(y_d)),
                    placement.to_device(placement.to_host(w_d)
                                        * placement.to_host(vmask_d))),
                (K,)).astype(jnp.float32)
        else:
            base_fn = _cached_program(
                ("init_score", objective, num_class,
                 tuple(sorted(objective_kwargs.items())), y_d.shape, mesh),
                lambda: jax.jit(lambda yy, ww, vm: jnp.broadcast_to(
                    obj.init_score(yy, ww * vm), (K,)).astype(jnp.float32)))
            base_d = base_fn(y_d, w_d, vmask_d)
        base = np.asarray(base_d, dtype=np.float32)
        scores_d = _device_tile_scores(base_d, n_pad, K, mesh)
    else:
        base = np.zeros(K, dtype=np.float32)
        scores_d = _device_tile_scores(jnp.zeros(K, jnp.float32), n_pad, K,
                                       mesh)
    if tw.on:
        jax.block_until_ready(scores_d)
        tw.mark("base_scores")

    has_valid = valid_set is not None
    valid_fp = None
    if has_valid:
        Xv, yv, wv = valid_set
        Xv = np.asarray(_densify(Xv), np.float32)
        yv = np.asarray(yv, np.float32)
        wv = np.ones_like(yv) if wv is None else np.asarray(wv, np.float32)
        nv = len(yv)
        if ckpt_mgr is not None:
            # the valid set is NOT part of the resume fingerprint (a
            # changed eval set must not discard training progress), so
            # the exact-state vscores restore needs its own identity
            # check — restoring V1's accumulated scores against V2's
            # labels would silently corrupt early stopping
            from ...utils.checkpoint import data_fingerprint as _vfp
            valid_fp = _vfp(Xv, yv, wv)
        Xvb_d, _ = placement.shard_rows(binner.transform(Xv), mesh)
        yv_d, _ = placement.shard_rows(yv, mesh)
        # fold validity into the weight so padded rows don't count
        wv_pad, _ = meshlib.pad_rows(wv, nshards)
        wv_pad = wv_pad * meshlib.validity_mask(nv, len(wv_pad))
        wv_d, _ = placement.shard_rows(wv_pad, mesh)
        # same exact-state rule as the training scores above — but only
        # when the checkpoint was written against THIS valid set
        resume_vscores = (None if resume_state is None
                          else resume_state.get("vscores"))
        if (resume_vscores is not None and valid_fp is not None
                and resume_state.get("valid_fingerprint") == valid_fp
                and np.asarray(resume_vscores).shape == (nv, K)):
            vscores0 = np.asarray(resume_vscores, np.float32)
        elif init_booster is not None:
            vscores0 = init_booster.predict_raw(Xv)
        else:
            vscores0 = np.tile(base[None, :], (nv, 1))
        vscores_d, _ = placement.shard_rows(vscores0.astype(np.float32), mesh)
        if tw.on:
            jax.block_until_ready((Xvb_d, yv_d, wv_d, vscores_d))
            tw.mark("valid_prep")
    else:
        Xvb_d = yv_d = wv_d = vscores_d = None

    depth_cap = cfg.max_depth if cfg.max_depth > 0 else max(1, cfg.num_leaves - 1)
    depth_cap = min(depth_cap, 2 * cfg.num_leaves)

    use_goss = boosting_type == "goss"
    is_rf = boosting_type == "rf"
    use_bagging = ((not use_goss) and bagging_freq > 0
                   and (bagging_fraction < 1.0 or stratified_bagging))
    # device-side metric name (what the step computes); the published
    # early-stopping metric name diverges only for host-computed auc
    device_metric_name = eval_metric(
        obj, jnp.zeros((1, K)) if K > 1 else jnp.zeros(1),
        jnp.zeros(1), jnp.ones(1), metric=eval_override,
        **objective_kwargs)[0]
    metric_name = "auc" if auc_host else device_metric_name

    if boosting_type == "dart":
        return _train_dart(
            mesh=mesh, cfg=cfg, K=K, obj=obj,
            objective=objective, objective_kwargs=objective_kwargs,
            Xbt_d=Xbt_d, y_d=y_d, w_d=w_d, vmask_d=vmask_d, base=base,
            has_valid=has_valid, Xvb_d=Xvb_d, yv_d=yv_d, wv_d=wv_d,
            depth_cap=depth_cap, metric_name=metric_name,
            num_iterations=num_iterations, seed=seed,
            feature_fraction=feature_fraction, use_bagging=use_bagging,
            bagging_fraction=bagging_fraction, bagging_freq=bagging_freq,
            early_stopping_rounds=early_stopping_rounds,
            early_stopping_tolerance=float(early_stopping_tolerance),
            iteration_callback=iteration_callback,
            metric_eval_period=metric_eval_period,
            drop_rate=drop_rate, max_drop=max_drop, skip_drop=skip_drop,
            drop_seed=drop_seed, binner=binner, max_bin=max_bin,
            is_cat_j=is_cat_j)

    grow_axis = _grow_axis_for(mesh, cfg)

    def step_local(binned_t, yl, wl, vmask, scores, vbinned, vy, vw,
                   vscores, key, bag_key, it_f):
        """One boosting iteration on local shard rows (inside shard_map).

        ``it_f``: f32 iteration index — gates the quantized-gradient warmup
        cond (``_grow_with_warmup``), and rf's validation metric evaluates
        the *average* of the trees grown so far.
        """
        if K > 1:
            grad, hess = obj.grad_hess(scores, yl, wl)
        else:
            grad, hess = obj.grad_hess(scores[:, 0], yl, wl)
            grad, hess = grad[:, None], hess[:, None]
        if use_goss:
            # GOSS (boostingType=goss): keep the top_rate fraction by |grad|,
            # sample other_rate of the rest amplified by (1-a)/b. The
            # amplification rides the row mask, so weighted counts see it too
            # (a documented deviation from LightGBM's unweighted counts).
            absg = jnp.abs(grad).sum(axis=1) * vmask
            n_valid = jnp.maximum(jnp.sum(vmask), 1.0)
            # keep top_rate*n_valid rows of an N-row shard (padded rows have
            # absg 0 and cluster at the bottom of the quantile)
            q = jnp.clip(1.0 - top_rate * n_valid / vmask.shape[0], 0.0, 1.0)
            top = absg >= jnp.quantile(absg, q)
            k2 = jax.random.fold_in(bag_key, jax.lax.axis_index("data"))
            keep_p = other_rate / max(1.0 - top_rate, 1e-6)
            rest_keep = jax.random.uniform(k2, vmask.shape) < keep_p
            amp = (1.0 - top_rate) / max(other_rate, 1e-6)
            row_mask = vmask * jnp.where(top, 1.0,
                                         jnp.where(rest_keep, amp, 0.0))
        elif use_bagging:
            # bag_key changes only every bagging_freq iterations (LightGBM
            # semantics: the subsample is reused for baggingFreq rounds)
            k = jax.random.fold_in(bag_key, jax.lax.axis_index("data"))
            if stratified_bagging:
                # LightGBM pos/neg_bagging_fraction: per-class keep
                # probability (binary labels; validated at entry)
                frac = jnp.where(yl > 0.5,
                                 jnp.float32(pos_bagging_fraction),
                                 jnp.float32(neg_bagging_fraction))
            else:
                frac = jnp.float32(bagging_fraction)
            bag = (jax.random.uniform(k, vmask.shape) < frac)
            row_mask = vmask * bag.astype(jnp.float32)
        else:
            row_mask = vmask

        trees_out = []
        fmask = jnp.ones(F, dtype=bool)
        if feature_fraction < 1.0:
            # derived from the replicated iteration key: identical on all shards
            fkey = jax.random.fold_in(key, 7)
            u = jax.random.uniform(fkey, (F,))
            fmask = u < feature_fraction
            fmask = fmask.at[jnp.argmin(u)].set(True)  # guarantee >=1 feature
        grow = (grow_tree_depthwise if cfg.growth_policy == "depthwise"
                else grow_tree)
        for k in range(K):
            tree, row_node = _grow_with_warmup(
                grow, it_f, cfg, jax.random.fold_in(key, 13 + k),
                binned_t, grad[:, k], hess[:, k], row_mask, fmask,
                axis_name=grow_axis, is_cat=is_cat_j)
            if not is_rf:
                # rf: trees are independent (gradients stay at the base
                # score); gbdt/goss: boost on the updated margin
                scores = scores.at[:, k].add(tree.leaf_value[row_node])
            trees_out.append(tree)
        trees_stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *trees_out)

        metrics = {}
        if provide_training_metric:
            # isProvideTrainingMetric: the train-set metric on the updated
            # margin, combined across shards exactly like the valid metric
            tsc = scores if K > 1 else scores[:, 0]
            _, tnum = eval_metric(obj, tsc, yl, wl * vmask,
                                  metric=eval_override, **objective_kwargs)
            twsum = jax.lax.psum(jnp.sum(wl * vmask), "data")
            tlocal = jnp.sum(wl * vmask)
            if device_metric_name == "rmse":
                metrics["train"] = jnp.sqrt(
                    jax.lax.psum(tnum * tnum * tlocal, "data") / twsum)
            else:
                metrics["train"] = (jax.lax.psum(tnum * tlocal, "data")
                                    / twsum)
        if has_valid:
            for k in range(K):
                tr = jax.tree_util.tree_map(lambda a: a[k], trees_stacked)
                vscores = vscores.at[:, k].add(
                    predict_tree_binned(tr, vbinned, depth_cap,
                                        is_cat=is_cat_j))
            if is_rf:
                # ensemble-so-far = base + average of accumulated raw trees
                vbase = jnp.asarray(base)[None, :]
                veval = vbase + (vscores - vbase) / (it_f + 1.0)
            else:
                veval = vscores
            sc = veval if K > 1 else veval[:, 0]
            _, num = eval_metric(obj, sc, vy, vw, metric=eval_override,
                                 **objective_kwargs)
            # metric is a weighted mean: combine across shards. The combine
            # rule keys off the DEVICE-computed metric name — with a
            # host-computed early-stopping metric (auc) the step still
            # evaluates the objective default here
            wsum = jax.lax.psum(jnp.sum(vw), "data")
            local_wsum = jnp.sum(vw)
            if device_metric_name == "rmse":
                local = num * num * local_wsum
                metrics["valid"] = jnp.sqrt(jax.lax.psum(local, "data") / wsum)
            else:
                metrics["valid"] = jax.lax.psum(num * local_wsum, "data") / wsum
        return scores, vscores if has_valid else jnp.zeros((1, 1)), trees_stacked, metrics

    row_spec = P("data")
    row2_spec = P("data", None)
    col_spec = P(None, "data")
    in_specs = (col_spec, row_spec, row_spec, row_spec, row2_spec,
                row2_spec if has_valid else P(), row_spec if has_valid else P(),
                row_spec if has_valid else P(), row2_spec if has_valid else P(),
                P(), P(), P())
    out_specs = (row2_spec, row2_spec if has_valid else P(), P(), P())

    dummy = np.zeros((), np.float32)
    # cache the compiled step across train_booster calls: the closure is fresh
    # per call, so jit's identity-keyed cache would otherwise recompile.
    # The resolved histogram engine keys the cache too: engine selection is
    # trace-time static (env/backend), so an MMLSPARK_TPU_HIST_ENGINE flip
    # mid-process must build a new program, not reuse the old engine's.
    from ...ops.histogram import resolve_engine as _resolve_hist_engine
    cache_key = (_resolve_hist_engine(),
                 cfg, K, objective, tuple(sorted(objective_kwargs.items())),
                 tuple(np.flatnonzero(is_cat_np).tolist()),
                 Xbt_d.shape, None if not has_valid else Xvb_d.shape,
                 use_bagging, bagging_fraction, bagging_freq,
                 stratified_bagging, pos_bagging_fraction,
                 neg_bagging_fraction, provide_training_metric,
                 eval_override, feature_fraction, depth_cap,
                 boosting_type, top_rate, other_rate, mesh,
                 # rf's validation eval closes over the data-dependent base
                 # score; it must key the cache or a sweep over same-shape
                 # datasets would reuse the wrong base
                 tuple(np.asarray(base).tolist()) if is_rf else None)
    def step_packed(*args):
        scores, vscores, trees_stacked, metrics = step_local(*args)
        # one flat download buffer instead of 13 per-field transfers
        return scores, vscores, pack_trees(trees_stacked), metrics

    # donate the per-round score buffers: the host loop immediately rebinds
    # scores_d/vscores_d to the step outputs, so XLA can update them in
    # place instead of allocating + copying a fresh [n_pad, K] in HBM every
    # boosting round. vscores (arg 8) only when real — without validation
    # that slot holds a shared dummy scalar whose shape matches no output,
    # and donating it would just warn per call. ACCELERATORS ONLY: on the
    # XLA CPU backend donating these sharded shard_map buffers produced
    # nondeterministic heap corruption (review-reproduced: ~40% of
    # test_histogram_engines runs segfaulted mid-host-loop on jax 0.4.37;
    # 0/6 with donation off), and host-RAM copies are not the bottleneck
    # the donation targets anyway. The placement plan resolved the backend
    # up front (PlacementPlan.donate_buffers).
    if not plan.donate_buffers:
        donate = ()
    else:
        donate = (4, 8) if has_valid else (4,)
    step = _cached_program(cache_key, lambda: jax.jit(shard_map(
        step_packed, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False), donate_argnums=donate))

    all_trees: List[Tree] = []
    history: Dict[str, List[float]] = {metric_name: []}
    higher_is_better = metric_name in HIGHER_IS_BETTER
    es_tol = float(early_stopping_tolerance)
    best_metric = -np.inf if higher_is_better else np.inf
    best_iter, rounds_no_improve = -1, 0
    if resume_state is not None:
        # continue the early-stopping bookkeeping exactly where it stopped
        best_metric = resume_state.get("best_metric", best_metric)
        best_iter = resume_state.get("best_iter", best_iter)
        rounds_no_improve = resume_state.get("rounds_no_improve", 0)
        history = resume_state.get("history", history)

    def _iter_keys(base_key, it):
        """Per-iteration PRNG derivation, shared by the host loop and both
        fused paths — host/fused equivalence depends on these staying
        bit-identical (``it`` may be a Python int or a traced scalar)."""
        key = jax.random.fold_in(base_key, it)
        if use_goss or is_rf:
            # GOSS resamples every iteration; rf re-bags every iteration
            # too (its gradients are constant, so a reused bag would
            # duplicate trees); gbdt bagging reuses its subsample for
            # bagging_freq rounds (LightGBM semantics)
            bag_step = it
        elif use_bagging:
            bag_step = it // max(bagging_freq, 1)
        else:
            bag_step = 0
        return key, jax.random.fold_in(base_key, 1_000_003 + bag_step)

    # --- fused fast path: no validation loop, no delegate callbacks, no
    # checkpointing, no resume -> run every iteration inside ONE compiled
    # scan. One device dispatch instead of num_iterations round-trips, which
    # dominates wall time on remote-attached TPUs.
    fuse = (not has_valid and iteration_callback is None and ckpt_mgr is None
            and iterations_done == 0 and not provide_training_metric)
    if fuse:
        fuse_key = (cache_key, num_iterations, seed, "fused")

        def build_multi():
            def multi_local(binned_l, yl, wl, vmask_l, scores_l):
                base_key = jax.random.PRNGKey(seed)

                def it_body(scores_c, it):
                    key, bag_key = _iter_keys(base_key, it)
                    d = jnp.zeros((), jnp.float32)
                    scores_c, _, trees_stacked, _ = step_local(
                        binned_l, yl, wl, vmask_l, scores_c, d, d, d, d,
                        key, bag_key, it.astype(jnp.float32))
                    return scores_c, trees_stacked

                _, trees_seq = lax.scan(
                    it_body, scores_l,
                    jnp.arange(num_iterations, dtype=jnp.int32))
                # one flat download buffer instead of 13 per-field transfers
                return pack_trees(trees_seq)

            return jax.jit(shard_map(
                multi_local, mesh=mesh,
                in_specs=(col_spec, row_spec, row_spec, row_spec, row2_spec),
                out_specs=P(), check_vma=False))

        multi = _cached_program(fuse_key, build_multi)
        tw.mark("build_multi")
        from ...utils.profiling import annotate
        with annotate(f"gbdt_train_fused:{num_iterations}it"):
            trees_dev = multi(Xbt_d, y_d, w_d, vmask_d, scores_d)
        if tw.on:
            jax.block_until_ready(trees_dev)
            tw.mark("multi_exec")
        trees_seq = unpack_trees(np.asarray(trees_dev),
                                 (num_iterations, K),
                                 2 * cfg.num_leaves - 1,
                                 bitset_words(cfg.num_bins))
        tw.mark("trees_download")
        all_seq: List[Tree] = []
        for it in range(num_iterations):
            for k in range(K):
                all_seq.append(jax.tree_util.tree_map(
                    lambda a: a[it, k], trees_seq))
        booster = _finalize_trees(all_seq, binner, max_bin, K, base, objective,
                                  depth_cap, objective_kwargs, -1,
                                  {metric_name: []}, init_booster)
        if is_rf:
            booster = _scale_booster_values(
                booster, np.full(booster.num_trees,
                                 1.0 / booster.num_iterations))
        return booster

    def _finalize(trees_list: List[Tree]) -> Booster:
        return _finalize_trees(trees_list, binner, max_bin, K, base,
                               objective, depth_cap, objective_kwargs,
                               best_iter, history, init_booster)

    # --- fused early-stopped validation path: validation + early-stopping
    # bookkeeping run ON DEVICE inside one lax.while_loop, so an
    # early-stopped training run is still ONE dispatch (the host loop costs
    # a ~67 ms round-trip per iteration through the tunnel). The stopping
    # predicate derives from the psum'd metric — replicated across shards,
    # so the while cond is SPMD-safe. Gated to the plain configuration
    # (period-1 eval, no callbacks/checkpoint/resume) and equivalence with
    # the host loop is pinned by tests (same best_iter, history, model);
    # MMLSPARK_TPU_DISABLE_FUSED_VALID=1 forces the host loop.
    fuse_es = (has_valid and iteration_callback is None and ckpt_mgr is None
               and iterations_done == 0 and metric_eval_period == 1
               and not provide_training_metric and not auc_host
               and not os.environ.get("MMLSPARK_TPU_DISABLE_FUSED_VALID"))  # graftlint: disable=resolve-before-cache-key (gates the fused path off entirely; never feeds a key)
    if fuse_es:
        fuse_key = (cache_key, num_iterations, seed, early_stopping_rounds,
                    es_tol, "fused_valid")

        def build_multi_valid():
            def multi_local(binned_l, yl, wl, vmask_l, scores_l, vbinned_l,
                            vy_l, vw_l, vscores_l):
                base_key = jax.random.PRNGKey(seed)

                def one_iter(it, state):
                    scores_c, vscores_c = state
                    key, bag_key = _iter_keys(base_key, it)
                    scores_c, vscores_c, trees_stacked, metrics = step_local(
                        binned_l, yl, wl, vmask_l, scores_c, vbinned_l,
                        vy_l, vw_l, vscores_c, key, bag_key,
                        it.astype(jnp.float32))
                    return ((scores_c, vscores_c), pack_trees(trees_stacked),
                            metrics["valid"].astype(jnp.float32))

                return _fused_es_scan(one_iter, (scores_l, vscores_l),
                                      num_iterations, early_stopping_rounds,
                                      higher_is_better, True, tol=es_tol)

            return jax.jit(shard_map(
                multi_local, mesh=mesh,
                in_specs=(col_spec, row_spec, row_spec, row_spec, row2_spec,
                          row2_spec, row_spec, row_spec, row2_spec),
                out_specs=(P(), P(), P(), P()), check_vma=False))

        multi_v = _cached_program(fuse_key, build_multi_valid)
        tw.mark("build_multi_valid")
        from ...utils.profiling import annotate
        with annotate(f"gbdt_train_fused_valid:{num_iterations}it"):
            buf_dev, mbuf_dev, n_done_dev, best_it_dev = multi_v(
                Xbt_d, y_d, w_d, vmask_d, scores_d, Xvb_d, yv_d, wv_d,
                vscores_d)
        n_done = int(n_done_dev)
        best_iter = int(best_it_dev)
        # slice on device before downloading: when early stopping fires well
        # before num_iterations, the static buffer's unused zero rows must
        # not cross the (slow, tunneled) host link
        mbuf = np.asarray(mbuf_dev[:n_done])
        history[metric_name].extend(float(x) for x in mbuf)
        rows = np.asarray(buf_dev[:n_done])
        tw.mark("trees_download")
        for it in range(n_done):
            # each buffer row is one iteration's pack of K stacked trees —
            # the same layout the host loop downloads per iteration
            trees_host = unpack_trees(rows[it], (K,),
                                      2 * cfg.num_leaves - 1,
                                      bitset_words(cfg.num_bins))
            for k in range(K):
                all_trees.append(jax.tree_util.tree_map(
                    lambda a: a[k], trees_host))
        # falls through to the shared finalize/truncate/rf-scale epilogue

    base_key = jax.random.PRNGKey(seed)
    # watchdog: one beat + one duration report per boosting round — a host
    # loop wedged on a stuck dispatch stops beating and gets stack-dumped;
    # a round suddenly 5x slower than its window trips the throughput
    # sentinel (fused paths have no rounds; scan_eval_history covers them)
    hb = _watchdog.register("gbdt_round_loop", stall_seconds=120.0) \
        if not fuse_es else _watchdog.NOOP_HEARTBEAT
    # last-good-checkpoint dump on watchdog events (opt-in via
    # MMLSPARK_TPU_CHECKPOINT_ON_UNHEALTHY=1): a NaN/divergence sentinel
    # or a stall episode during a checkpointed fit writes the newest
    # HEALTHY state immediately — for sentinels the flagged round's trees
    # are dropped (they embody the bad update), for stalls every complete
    # round is good. The dump rides the normal checkpoint format, so the
    # standard auto-resume picks it up after the operator kills the job.
    unregister_dump = None
    if ckpt_mgr is not None and dump_on_unhealthy:
        dump_once = threading.Event()

        def _last_good_dump(category, name, fields):
            # sentinel events name the model stream ("gbdt"); stall
            # episodes name the heartbeat site
            if name not in ("gbdt", "gbdt_round_loop") or dump_once.is_set():
                return
            trees_snap = list(all_trees)    # append-only: snapshot is safe
            complete = (len(trees_snap) // K) * K
            if category in ("nan_loss", "loss_divergence") and complete >= K:
                complete -= K
            if complete <= 0:
                # nothing healthy to dump yet — stay ARMED: a round-0
                # event must not burn the one-shot latch and silence a
                # real mid-fit dump later
                return
            step = iterations_done + complete // K - 1
            try:
                ckpt_mgr.save(step, {
                    "model": _finalize(trees_snap[:complete]).model_string(),
                    "iteration": step,
                    "fingerprint": ckpt_fingerprint,
                    "prior_iterations": 0 if user_init_booster is None
                    else user_init_booster.num_iterations,
                    "best_metric": best_metric,
                    "best_iter": best_iter,
                    "rounds_no_improve": rounds_no_improve,
                    "history": history,
                    "valid_fingerprint": valid_fp,
                    "emergency": True, "reason": category})
            except Exception:  # noqa: BLE001 — disk full mid-incident:
                return         # stay armed for a later, luckier event
            # latch only AFTER a successful publish — a failed dump must
            # not permanently disable the safety net
            dump_once.set()
            _flight.record("checkpoint_emergency_dump", model="gbdt",
                           reason=category, iteration=step)

        unregister_dump = _watchdog.add_event_callback(_last_good_dump)
    t_round = time.perf_counter()
    try:
        for it in ([] if fuse_es else range(iterations_done, num_iterations)):
            hb.beat()
            # chaos hook: one evaluation per boosting round — `error`
            # kills the fit mid-train (the preemption drill the resume
            # path is tested against), `delay` simulates a slow round
            _failpoint("gbdt.round")
            key, bag_key = _iter_keys(base_key, it)
            scores_d, vscores_d_new, trees_packed, metrics = step(
                Xbt_d, y_d, w_d, vmask_d, scores_d,
                Xvb_d if has_valid else dummy, yv_d if has_valid else dummy,
                wv_d if has_valid else dummy, vscores_d if has_valid else dummy,
                key, bag_key, np.float32(it))
            if has_valid:
                vscores_d = vscores_d_new
            trees_host = unpack_trees(np.asarray(trees_packed), (K,),  # graftlint: disable=hot-path-host-sync (deliberate: one tree download per round grows the host forest)
                                      2 * cfg.num_leaves - 1,
                                      bitset_words(cfg.num_bins))
            for k in range(K):
                all_trees.append(jax.tree_util.tree_map(lambda a: a[k], trees_host))

            if provide_training_metric and (it % metric_eval_period == 0
                                            or it == num_iterations - 1):
                # the train history records what the device step computes —
                # with metric='auc' that is the objective default, so key by
                # the device metric name, not the early-stopping one
                history.setdefault(f"training_{device_metric_name}", []).append(
                    float(metrics["train"]))  # graftlint: disable=hot-path-host-sync (deliberate per-eval-period metric download)

            if has_valid and (it % metric_eval_period == 0 or it == num_iterations - 1):
                if auc_host:
                    # exact weighted tie-handled AUC from the downloaded
                    # validation margin (rank statistics don't psum)
                    from .objectives import auc_weighted
                    # (no rf rescale: AUC is rank-based, invariant under the
                    # strictly increasing average-so-far transform)
                    m = auc_weighted(np.asarray(vscores_d)[:nv, 0], yv, wv)  # graftlint: disable=hot-path-host-sync (deliberate: host AUC needs the validation margin)
                else:
                    m = float(metrics["valid"])  # graftlint: disable=hot-path-host-sync (deliberate per-eval-period metric download)
                history[metric_name].append(m)
                _watchdog.report_training_metric("gbdt", it, loss=m,
                                                 metric_name=metric_name)
                improved = (m > best_metric + es_tol if higher_is_better
                            else m < best_metric - es_tol)
                if improved:
                    best_metric, best_iter, rounds_no_improve = m, it, 0
                else:
                    rounds_no_improve += 1
                if iteration_callback is not None:
                    iteration_callback(it, {metric_name: m})
                if early_stopping_rounds > 0 and rounds_no_improve >= early_stopping_rounds:
                    break
            elif iteration_callback is not None:
                iteration_callback(it, {})
            now_round = time.perf_counter()
            _watchdog.report_training_metric("gbdt", it,
                                             seconds=now_round - t_round)
            t_round = now_round

            if (ckpt_mgr is not None and checkpoint_period > 0
                    and (it + 1) % checkpoint_period == 0
                    and it + 1 < num_iterations):
                # the accumulated score matrices ride in the payload so a
                # resume restarts from the EXACT optimizer state — see the
                # resume_scores comment above (bit-identical trees). One
                # d2h per checkpoint period; best-effort on exotic
                # placements (a non-addressable mesh falls back to the
                # predict_raw reconstruction on resume).
                try:
                    scores_host = np.asarray(scores_d)[:n]  # graftlint: disable=hot-path-host-sync (deliberate: one d2h per checkpoint period, exact-state resume needs the host copy)
                    vscores_host = (np.asarray(vscores_d)[:nv]  # graftlint: disable=hot-path-host-sync (same deliberate checkpoint d2h as scores_host)
                                    if has_valid else None)
                except Exception:  # noqa: BLE001
                    scores_host = vscores_host = None
                ckpt_mgr.save(it, {"model": _finalize(all_trees).model_string(),
                                   "iteration": it,
                                   "fingerprint": ckpt_fingerprint,
                                   "prior_iterations":
                                       0 if user_init_booster is None
                                       else user_init_booster.num_iterations,
                                   "best_metric": best_metric,
                                   "best_iter": best_iter,
                                   "rounds_no_improve": rounds_no_improve,
                                   "history": history,
                                   "scores": scores_host,
                                   "vscores": vscores_host,
                                   "valid_fingerprint": valid_fp})

    finally:
        hb.close()
        if unregister_dump is not None:
            unregister_dump()
    booster = _finalize(all_trees)
    # early-stop truncation applies to fresh runs and checkpoint resumes
    # alike (the checkpoint's trees carry global iteration indices); only a
    # user-supplied warm-start booster suppresses it, as before.
    if (early_stopping_rounds > 0 and best_iter >= 0
            and user_init_booster is None):
        booster = _truncate_booster(booster, best_iter + 1)
    if is_rf:
        # forest prediction = base + average of (unshrunk) trees
        booster = _scale_booster_values(
            booster, np.full(booster.num_trees, 1.0 / booster.num_iterations))
    return booster


def _scale_booster_values(b: Booster, per_tree_scale: np.ndarray) -> Booster:
    """Scale each tree's output values (rf averaging / dart normalization)."""
    s = np.asarray(per_tree_scale, np.float32)[:, None]
    trees = b.trees._replace(
        leaf_value=np.asarray(b.trees.leaf_value) * s,
        node_value=np.asarray(b.trees.node_value) * s)
    return Booster(trees, b.thr_raw, b.num_class, b.base_score, b.objective,
                   b.depth_cap, b.binner_state, b.best_iteration,
                   b.eval_history, b.objective_kwargs)


def _train_dart(*, mesh, cfg, K, obj, objective, objective_kwargs,
                Xbt_d, y_d, w_d, vmask_d, base, has_valid, Xvb_d, yv_d, wv_d,
                depth_cap, metric_name, num_iterations, seed,
                feature_fraction, use_bagging, bagging_fraction, bagging_freq,
                early_stopping_rounds, iteration_callback, metric_eval_period,
                early_stopping_tolerance=0.0,
                drop_rate, max_drop, skip_drop, drop_seed,
                binner, max_bin, is_cat_j=None) -> Booster:
    """DART boosting: Dropouts meet Multiple Additive Regression Trees.

    Parity target: LightGBM's ``boosting=dart`` (reference exposes it via
    TrainParams.scala:9-10). Per iteration, each existing tree is dropped
    with probability ``drop_rate`` (skipped entirely with probability
    ``skip_drop``, capped at ``max_drop``); the new tree fits gradients at
    the ensemble *without* the dropped trees; then the new tree is scaled by
    1/(k+1) and the dropped trees by k/(k+1) (DART-paper normalization, the
    LightGBM default mode).

    TPU design: per-tree training-row contributions are kept as one sharded
    [T, n, K] device array so "the ensemble minus dropped trees" is a single
    weighted reduction with a host-supplied per-tree scale vector — no
    re-walking historical trees. Early stopping records best_iteration but
    does not truncate (dropping later trees would denormalize earlier ones).
    """
    F, npad = Xbt_d.shape
    T_max = num_iterations
    grow = (grow_tree_depthwise if cfg.growth_policy == "depthwise"
            else grow_tree)
    grow_axis = _grow_axis_for(mesh, cfg)
    base_j = jnp.asarray(base)

    def dart_step_local(binned_t, yl, wl, vmask, contribs, eff_scales,
                        vbinned, vcontribs, key, bag_key, it_i):
        scores = base_j[None, :] + jnp.einsum("t,tnk->nk", eff_scales,
                                              contribs)
        if K > 1:
            grad, hess = obj.grad_hess(scores, yl, wl)
        else:
            grad, hess = obj.grad_hess(scores[:, 0], yl, wl)
            grad, hess = grad[:, None], hess[:, None]
        if use_bagging:
            k2 = jax.random.fold_in(bag_key, jax.lax.axis_index("data"))
            bag = jax.random.uniform(k2, vmask.shape) < bagging_fraction
            row_mask = vmask * bag.astype(jnp.float32)
        else:
            row_mask = vmask
        fmask = jnp.ones(F, dtype=bool)
        if feature_fraction < 1.0:
            fkey = jax.random.fold_in(key, 7)
            u = jax.random.uniform(fkey, (F,))
            fmask = (u < feature_fraction).at[jnp.argmin(u)].set(True)
        trees_out, new_contrib = [], []
        for k in range(K):
            tree, row_node = _grow_with_warmup(
                grow, it_i, cfg, jax.random.fold_in(key, 13 + k),
                binned_t, grad[:, k], hess[:, k], row_mask, fmask,
                axis_name=grow_axis, is_cat=is_cat_j)
            new_contrib.append(tree.leaf_value[row_node])
            trees_out.append(tree)
        nc = jnp.stack(new_contrib, axis=1)                # [n_local, K]
        contribs = lax.dynamic_update_slice(contribs, nc[None], (it_i, 0, 0))
        trees_stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *trees_out)
        if has_valid:
            vc = jnp.stack(
                [predict_tree_binned(
                    jax.tree_util.tree_map(lambda a: a[k], trees_stacked),
                    vbinned, depth_cap, is_cat=is_cat_j)
                 for k in range(K)], axis=1)
            vcontribs = lax.dynamic_update_slice(
                vcontribs, vc[None], (it_i, 0, 0))
        # one flat download buffer instead of 13 per-field transfers
        return contribs, vcontribs, pack_trees(trees_stacked)

    def dart_eval_local(vcontribs, scales, vy, vw):
        sc2 = base_j[None, :] + jnp.einsum("t,tnk->nk", scales, vcontribs)
        sc = sc2 if K > 1 else sc2[:, 0]
        _, num = eval_metric(obj, sc, vy, vw, **objective_kwargs)
        wsum = jax.lax.psum(jnp.sum(vw), "data")
        local_wsum = jnp.sum(vw)
        if metric_name == "rmse":
            return jnp.sqrt(jax.lax.psum(num * num * local_wsum, "data")
                            / wsum)
        return jax.lax.psum(num * local_wsum, "data") / wsum

    row_spec, row2_spec = P("data"), P("data", None)
    col_spec = P(None, "data")
    c_spec = P(None, "data", None)
    # compiled-step cache, same rationale as the gbdt path: the closures are
    # fresh per fit() call, so jit's identity-keyed cache would recompile on
    # every trial of a sweep (the resolved histogram engine keys it for the
    # same reason as the gbdt step cache)
    from ...ops.histogram import resolve_engine as _resolve_hist_engine
    cache_key = ("dart", _resolve_hist_engine(), cfg, K, objective,
                 tuple(sorted(objective_kwargs.items())),
                 None if is_cat_j is None
                 else tuple(np.flatnonzero(np.asarray(is_cat_j)).tolist()),
                 Xbt_d.shape,
                 None if not has_valid else Xvb_d.shape, T_max,
                 use_bagging, bagging_fraction, bagging_freq,
                 feature_fraction, depth_cap, metric_name,
                 tuple(np.asarray(base).tolist()), mesh)
    def build_dart():
        dstep = jax.jit(shard_map(
            dart_step_local, mesh=mesh,
            in_specs=(col_spec, row_spec, row_spec, row_spec, c_spec, P(),
                      row2_spec if has_valid else P(),
                      c_spec if has_valid else P(), P(), P(), P()),
            out_specs=(c_spec, c_spec if has_valid else P(), P()),
            check_vma=False))
        deval = (jax.jit(shard_map(
            dart_eval_local, mesh=mesh,
            in_specs=(c_spec, P(), row_spec, row_spec), out_specs=P(),
            check_vma=False)) if has_valid else None)
        return dstep, deval

    dstep, deval = _cached_program(cache_key, build_dart)

    sh = lambda spec: placement.sharding(spec, mesh)
    contribs_d = placement.device_put(
        np.zeros((T_max, npad, K), np.float32), sh(c_spec))
    vcontribs_d = (placement.device_put(
        np.zeros((T_max, Xvb_d.shape[0], K), np.float32), sh(c_spec))
        if has_valid else np.zeros((), np.float32))
    dummy = np.zeros((), np.float32)

    scales = np.zeros(T_max, np.float32)
    rng_drop = np.random.default_rng(drop_seed)
    all_trees: List[Tree] = []
    history: Dict[str, List[float]] = {metric_name: []}
    higher_is_better = metric_name in HIGHER_IS_BETTER
    es_tol = float(early_stopping_tolerance)
    best_metric = -np.inf if higher_is_better else np.inf
    best_iter, rounds_no_improve = -1, 0
    base_key = jax.random.PRNGKey(seed)

    # The drop sets depend only on the numpy RNG stream, never on data, so
    # the whole schedule + scale evolution precomputes up front; BOTH the
    # fused dispatch and the host loop consume these rows, so there is one
    # copy of the drop/scale logic (eff_rows[it] = scales entering
    # iteration it with its drop set zeroed; post_rows[it] = scales after
    # the iteration's DART renormalization).
    eff_rows = np.zeros((T_max, T_max), np.float32)
    post_rows = np.zeros((T_max, T_max), np.float32)
    for it in range(T_max):
        if it == 0 or rng_drop.uniform() < skip_drop:
            dropped = np.empty(0, np.int64)
        else:
            dropped = np.nonzero(rng_drop.uniform(size=it) < drop_rate)[0]
            if max_drop > 0 and len(dropped) > max_drop:
                dropped = rng_drop.choice(dropped, size=max_drop,
                                          replace=False)
        eff_rows[it] = scales
        eff_rows[it, dropped] = 0.0
        kdrop = len(dropped)
        scales[dropped] *= kdrop / (kdrop + 1.0)
        scales[it] = 1.0 / (kdrop + 1.0)
        post_rows[it] = scales

    # --- fused dart: the entire run in ONE device dispatch — a scan
    # without validation, the shared _fused_es_scan while_loop with
    # on-device early stopping with it (previously every dart iteration
    # paid a tunnel round-trip).
    fuse_dart = (iteration_callback is None
                 and (not has_valid or metric_eval_period == 1)
                 and not os.environ.get("MMLSPARK_TPU_DISABLE_FUSED_DART"))  # graftlint: disable=resolve-before-cache-key (gates the fused path off entirely; never feeds a key)
    if fuse_dart:
        fuse_key = (cache_key, num_iterations, seed, early_stopping_rounds,
                    es_tol, "dart_fused")

        def build_dart_fused():
            def multi_local(binned_l, yl, wl, vmask_l, contribs_l,
                            vbinned_l, vcontribs_l, eff_mat, post_mat,
                            vy_l, vw_l):
                def one_iter(it, state):
                    contribs_c, vcontribs_c = state
                    key = jax.random.fold_in(base_key, it)
                    bag_step = (it // max(bagging_freq, 1)
                                if use_bagging else 0)
                    bag_key = jax.random.fold_in(base_key,
                                                 1_000_003 + bag_step)
                    contribs_c, vcontribs_c, packed = dart_step_local(
                        binned_l, yl, wl, vmask_l, contribs_c, eff_mat[it],
                        vbinned_l, vcontribs_c, key, bag_key, it)
                    if has_valid:
                        m = dart_eval_local(vcontribs_c, post_mat[it],
                                            vy_l, vw_l).astype(jnp.float32)
                    else:
                        m = jnp.float32(jnp.nan)
                    return (contribs_c, vcontribs_c), packed, m

                return _fused_es_scan(one_iter, (contribs_l, vcontribs_l),
                                      num_iterations, early_stopping_rounds,
                                      higher_is_better,
                                      track_metric=has_valid, tol=es_tol)

            return jax.jit(shard_map(
                multi_local, mesh=mesh,
                in_specs=(col_spec, row_spec, row_spec, row_spec, c_spec,
                          row2_spec if has_valid else P(),
                          c_spec if has_valid else P(), P(), P(),
                          row_spec if has_valid else P(),
                          row_spec if has_valid else P()),
                out_specs=(P(), P(), P(), P()), check_vma=False))

        multi_d = _cached_program(fuse_key, build_dart_fused)
        from ...utils.profiling import annotate
        with annotate(f"dart_train_fused:{num_iterations}it"):
            buf_dev, mbuf_dev, n_done_dev, best_it_dev = multi_d(
                Xbt_d, y_d, w_d, vmask_d, contribs_d,
                Xvb_d if has_valid else dummy,
                vcontribs_d if has_valid else dummy,
                jnp.asarray(eff_rows), jnp.asarray(post_rows),
                yv_d if has_valid else dummy,
                wv_d if has_valid else dummy)
        n_done = int(n_done_dev)
        best_iter = int(best_it_dev)
        if has_valid:
            # device-side slice: don't download unexecuted zero rows
            history[metric_name].extend(
                float(x) for x in np.asarray(mbuf_dev[:n_done]))
        rows = np.asarray(buf_dev[:n_done])
        for it in range(n_done):
            trees_host = unpack_trees(rows[it], (K,),
                                      2 * cfg.num_leaves - 1,
                                      bitset_words(cfg.num_bins))
            for k in range(K):
                all_trees.append(jax.tree_util.tree_map(
                    lambda a: a[k], trees_host))
        # the per-tree scale vector is the post-step scales of the last
        # executed iteration — identical to the host loop's final `scales`
        scales = post_rows[n_done - 1].copy()
        booster = _finalize_trees(all_trees, binner, max_bin, K, base,
                                  objective, depth_cap, objective_kwargs,
                                  best_iter, history, None)
        return _scale_booster_values(booster,
                                     np.repeat(scales[:n_done], K))

    hb = _watchdog.register("gbdt_dart_round_loop", stall_seconds=120.0)
    t_round = time.perf_counter()
    try:
        for it in range(num_iterations):
            hb.beat()
            key = jax.random.fold_in(base_key, it)
            bag_step = it // max(bagging_freq, 1) if use_bagging else 0
            bag_key = jax.random.fold_in(base_key, 1_000_003 + bag_step)
            contribs_d, vcontribs_new, trees_packed = dstep(
                Xbt_d, y_d, w_d, vmask_d, contribs_d,
                jnp.asarray(eff_rows[it]),
                Xvb_d if has_valid else dummy,
                vcontribs_d if has_valid else dummy,
                key, bag_key, np.int32(it))
            if has_valid:
                vcontribs_d = vcontribs_new
            trees_host = unpack_trees(np.asarray(trees_packed), (K,),  # graftlint: disable=hot-path-host-sync (deliberate: one tree download per round grows the host forest)
                                      2 * cfg.num_leaves - 1,
                                      bitset_words(cfg.num_bins))
            for k in range(K):
                all_trees.append(jax.tree_util.tree_map(lambda a: a[k],
                                                        trees_host))
            scales = post_rows[it].copy()

            if has_valid and (it % metric_eval_period == 0
                              or it == num_iterations - 1):
                m = float(deval(vcontribs_d, jnp.asarray(scales), yv_d, wv_d))  # graftlint: disable=hot-path-host-sync (deliberate per-eval-period metric download)
                history[metric_name].append(m)
                _watchdog.report_training_metric("gbdt", it, loss=m,
                                                 metric_name=metric_name)
                improved = (m > best_metric + es_tol if higher_is_better
                            else m < best_metric - es_tol)
                if improved:
                    best_metric, best_iter, rounds_no_improve = m, it, 0
                else:
                    rounds_no_improve += 1
                if iteration_callback is not None:
                    iteration_callback(it, {metric_name: m})
                if (early_stopping_rounds > 0
                        and rounds_no_improve >= early_stopping_rounds):
                    break
            elif iteration_callback is not None:
                iteration_callback(it, {})
            now_round = time.perf_counter()
            _watchdog.report_training_metric("gbdt", it,
                                             seconds=now_round - t_round)
            t_round = now_round

    finally:
        hb.close()
    booster = _finalize_trees(all_trees, binner, max_bin, K, base, objective,
                              depth_cap, objective_kwargs, best_iter, history,
                              None)
    n_done = len(all_trees) // K
    per_tree = np.repeat(scales[:n_done], K)
    return _scale_booster_values(booster, per_tree)


def _finalize_trees(trees_list: List[Tree], binner, max_bin: int, K: int,
                    base, objective: str, depth_cap: int,
                    objective_kwargs: Optional[dict], best_iter: int,
                    history: Dict[str, List[float]],
                    init_booster: Optional[Booster]) -> Booster:
    """Stack grown trees into a Booster (raw thresholds from bin bounds)."""
    stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *trees_list)
    upper = binner.bin_upper_raw()  # [F, B]
    thr_raw = upper[stacked.feat, np.minimum(stacked.thr_bin, max_bin - 1)]
    thr_raw = np.where(stacked.is_leaf, np.float32(np.inf), thr_raw)
    b = Booster(stacked, thr_raw.astype(np.float32), K, base,
                objective, depth_cap, binner.state(),
                best_iteration=best_iter, eval_history=history,
                objective_kwargs=objective_kwargs)
    if init_booster is not None:
        b = _merge_boosters(init_booster, b)
    return b


def _truncate_booster(b: Booster, num_iterations: int) -> Booster:
    t_end = num_iterations * b.num_class
    trees = jax.tree_util.tree_map(lambda a: a[:t_end], b.trees)
    out = Booster(trees, b.thr_raw[:t_end], b.num_class, b.base_score,
                  b.objective, b.depth_cap, b.binner_state, b.best_iteration,
                  b.eval_history, b.objective_kwargs)
    if b.missing_dec is not None:
        out.missing_dec = b.missing_dec[:t_end]
    return out


def _pad_tree_slots(trees: Tree, thr: np.ndarray, M: int):
    """Widen fixed-shape tree arrays to M node slots (inert leaf padding)."""
    cur = trees.feat.shape[1]
    if cur == M:
        return trees, thr
    pad = M - cur

    def pad_field(name, a):
        a = np.asarray(a)
        if a.ndim == 1:          # per-tree scalars (node_count)
            return a
        fill = {"is_leaf": True}.get(name, 0)
        width = [(0, 0), (0, pad)] + [(0, 0)] * (a.ndim - 2)
        return np.pad(a, width, constant_values=fill)

    trees = Tree(**{k: pad_field(k, v)
                    for k, v in trees._asdict().items()})
    thr = np.pad(thr, ((0, 0), (0, pad)), constant_values=np.float32(np.inf))
    return trees, thr


def _merge_boosters(first: Booster, second: Booster) -> Booster:
    """Concatenate tree sequences (BoosterMerge parity,
    reference: TrainUtils.scala:165-168 warm-start via LGBM_BoosterMerge).

    Slot widths may differ (e.g. a warm start loaded from a LightGBM text
    model vs freshly grown trees): both sides are padded to the wider M."""
    assert first.num_class == second.num_class
    M = max(first.trees.feat.shape[1], second.trees.feat.shape[1])
    # bitset word widths may also differ (e.g. max_bin 63 vs 255 models)
    BW = max(first.trees.cat_bitset.shape[-1],
             second.trees.cat_bitset.shape[-1])

    def widen_bits(t: Tree) -> Tree:
        cur = t.cat_bitset.shape[-1]
        if cur == BW:
            return t
        return t._replace(cat_bitset=np.pad(
            np.asarray(t.cat_bitset), ((0, 0), (0, 0), (0, BW - cur))))

    t1, thr1 = _pad_tree_slots(widen_bits(first.trees), first.thr_raw, M)
    t2, thr2 = _pad_tree_slots(widen_bits(second.trees), second.thr_raw, M)
    trees = jax.tree_util.tree_map(
        lambda a, c: np.concatenate([np.asarray(a), np.asarray(c)], axis=0),
        t1, t2)
    thr = np.concatenate([thr1, thr2], axis=0)
    out = Booster(trees, thr, first.num_class, first.base_score, second.objective,
                  max(first.depth_cap, second.depth_cap), second.binner_state,
                  second.best_iteration, second.eval_history, second.objective_kwargs)
    if first.missing_dec is not None or second.missing_dec is not None:
        # absent side = the framework's own semantics (decision_type 10)
        def _md(b, t):
            if b.missing_dec is not None:
                md = b.missing_dec
                return np.pad(md, ((0, 0), (0, M - md.shape[1])),
                              constant_values=10)
            return np.full((t.feat.shape[0], M), 10, np.uint8)
        out.missing_dec = np.concatenate([_md(first, t1), _md(second, t2)],
                                         axis=0)
    return out
