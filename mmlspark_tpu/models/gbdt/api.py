"""LightGBM-style estimators/models: the public GBDT API surface.

Parity with the reference's LightGBM stages (reference:
lightgbm/LightGBMClassifier.scala:24-195, LightGBMRegressor.scala,
lightgbm/LightGBMParams.scala — param names are kept verbatim so code written
against the reference's PySpark wrappers ports by renaming imports only).
Execution is the TPU-native booster: rows sharded over the mesh ``data`` axis,
histogram psum over ICI instead of the socket ring; cluster-topology params of
the reference (numTasks/parallelism/timeout) are accepted for compatibility
but the mesh defines the actual topology.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ...core.dataset import Dataset, _is_sparse
from ...core.params import (HasFeaturesCol, HasGroupCol, HasInitScoreCol,
                            HasLabelCol, HasPredictionCol, HasProbabilityCol,
                            HasRawPredictionCol, HasValidationIndicatorCol,
                            HasWeightCol, Param, Params, TypeConverters)
from ...core.pipeline import Estimator, Model
from ...observability import hbm as _hbm
from ...observability import metrics as _metrics
from ...observability import spans as _spans
from ...observability import watchdog as _watchdog
from .booster import Booster, LightGBMDataset, _densify, train_booster
from .growth import GrowConfig, resolve_growth_backend

# Bounded cache of pre-binned device datasets keyed by a CONTENT fingerprint
# of the training arrays + every binning-relevant param. Hyperparameter
# sweeps (automl/TuneHyperparameters) fit many candidates on the same data;
# with the key being a real content hash (strided-page sha256 + full crc32,
# utils/checkpoint.data_fingerprint), candidates that only change
# learner params reuse one ingest (binner fit + transfer + device binning)
# instead of re-paying it per fit. Two entries bound device memory: each
# dataset pins an [F, n] int32 matrix in HBM.
from collections import OrderedDict

_BINNED_CACHE: "OrderedDict" = OrderedDict()
_BINNED_CACHE_MAX = 2


def _to_tristate_bool(v):
    """Param converter for True | False | "auto": keeps the sentinel,
    coerces everything else exactly like ``TypeConverters.to_bool`` (so
    1/0/'true'/'false' inputs keep working across the tri-state change)."""
    if isinstance(v, str) and v.strip().lower() == "auto":
        return "auto"
    return TypeConverters.to_bool(v)


def _dataset_nbytes(ds) -> float:
    """Device bytes one cached binned dataset pins (the ``binned_cache``
    HBM-ledger claim): the [F, n_pad] bin matrix + label/weight/mask."""
    return float(sum(getattr(a, "nbytes", 0) or 0
                     for a in (ds.Xbt_d, ds.y_d, ds.w_d, ds.vmask_d)
                     if a is not None))


def clear_binned_dataset_cache() -> None:
    """Release the cached pre-binned device datasets (frees their HBM) —
    call after a sweep when the process moves on to other device work."""
    _BINNED_CACHE.clear()
    _hbm.set_claim("binned_cache", 0)


def _cache_enabled() -> bool:
    import os
    return os.environ.get("MMLSPARK_TPU_BINNED_CACHE", "1") != "0"


def _cached_binned_dataset(X, y, w, *, max_bin, bin_sample_count, seed,
                           categorical_features,
                           bin_dtype="int32",
                           max_bin_by_feature=None) -> LightGBMDataset:
    if not _cache_enabled():
        # skip fingerprinting entirely: hashing a 1M-row matrix per fit is
        # pure waste when the result will never be cached
        return LightGBMDataset.construct(
            _densify(X), y, w, max_bin=max_bin,
            bin_sample_count=bin_sample_count, seed=seed,
            categorical_features=categorical_features, bin_dtype=bin_dtype,
            max_bin_by_feature=max_bin_by_feature)
    from ...parallel import mesh as meshlib
    from ...utils.checkpoint import data_fingerprint

    # sparse input: fingerprint the CSR buffers directly — densifying is
    # deferred to a cache MISS so repeated sweep fits never allocate the
    # dense copy just to compute the key
    if _is_sparse(X):
        fp = data_fingerprint(X.data, X.indices, X.indptr,
                              np.asarray(X.shape), y, w)
    else:
        fp = data_fingerprint(X, y, w)
    # the active mesh is part of identity: a dataset constructed on one mesh
    # must not serve a fit running under a different default mesh
    # bin_dtype is part of identity: a uint8 fit after an int32 fit on
    # identical data must not silently reuse the wide dataset
    key = (fp, max_bin, bin_sample_count, seed,
           tuple(int(i) for i in categorical_features),
           str(bin_dtype),
           None if max_bin_by_feature is None
           else tuple(int(b) for b in max_bin_by_feature),
           meshlib.get_default_mesh())
    ds = _BINNED_CACHE.get(key)
    if ds is None:
        ds = LightGBMDataset.construct(
            _densify(X), y, w, max_bin=max_bin,
            bin_sample_count=bin_sample_count, seed=seed,
            categorical_features=categorical_features, bin_dtype=bin_dtype,
            max_bin_by_feature=max_bin_by_feature)
        _BINNED_CACHE[key] = ds
        _hbm.claim("binned_cache", _dataset_nbytes(ds))
        while len(_BINNED_CACHE) > _BINNED_CACHE_MAX:
            _k, old = _BINNED_CACHE.popitem(last=False)
            _hbm.release("binned_cache", _dataset_nbytes(old))
    else:
        _BINNED_CACHE.move_to_end(key)
    return ds


class _LightGBMParams(HasLabelCol, HasFeaturesCol, HasWeightCol, HasInitScoreCol,
                      HasValidationIndicatorCol, HasPredictionCol):
    """Shared LightGBM params (reference: lightgbm/LightGBMParams.scala)."""

    boostingType = Param("boostingType", "gbdt, rf, dart or goss", "gbdt",
                         TypeConverters.to_string)
    numIterations = Param("numIterations", "Number of boosting iterations", 100,
                          TypeConverters.to_int)
    learningRate = Param("learningRate", "Shrinkage rate", 0.1, TypeConverters.to_float)
    numLeaves = Param("numLeaves", "Max leaves per tree", 31, TypeConverters.to_int)
    maxDepth = Param("maxDepth", "Max tree depth (<=0: unlimited)", -1,
                     TypeConverters.to_int)
    maxBin = Param("maxBin", "Max feature bins", 255, TypeConverters.to_int)
    binSampleCount = Param("binSampleCount", "Rows sampled to pick bin boundaries",
                           200000, TypeConverters.to_int)
    baggingFraction = Param("baggingFraction", "Row subsample fraction", 1.0,
                            TypeConverters.to_float)
    baggingFreq = Param("baggingFreq", "Resample every k iterations (0=off)", 0,
                        TypeConverters.to_int)
    baggingSeed = Param("baggingSeed", "Bagging seed", 3, TypeConverters.to_int)
    featureFraction = Param("featureFraction", "Feature subsample per tree", 1.0,
                            TypeConverters.to_float)
    lambdaL1 = Param("lambdaL1", "L1 regularization", 0.0, TypeConverters.to_float)
    lambdaL2 = Param("lambdaL2", "L2 regularization", 0.0, TypeConverters.to_float)
    minDataInLeaf = Param("minDataInLeaf", "Minimum rows per leaf", 20,
                          TypeConverters.to_int)
    minSumHessianInLeaf = Param("minSumHessianInLeaf", "Minimum hessian sum per leaf",
                                1e-3, TypeConverters.to_float)
    minGainToSplit = Param("minGainToSplit", "Minimum gain to make a split", 0.0,
                           TypeConverters.to_float)
    earlyStoppingRound = Param("earlyStoppingRound",
                               "Stop if validation metric stalls this many rounds (0=off)",
                               0, TypeConverters.to_int)
    metricEvalPeriod = Param("metricEvalPeriod", "Evaluate metrics every k iterations",
                             1, TypeConverters.to_int)
    numBatches = Param("numBatches",
                       "Split data into sequential batches, warm-starting each "
                       "(reference: LightGBMBase.scala:28-50)", 0, TypeConverters.to_int)
    modelString = Param("modelString", "Warm-start model string", None,
                        TypeConverters.to_string)
    checkpointDir = Param("checkpointDir",
                          "Step-level checkpoint directory: training saves "
                          "every checkpointInterval iterations and resumes "
                          "from the newest checkpoint (preemption-safe; "
                          "extends the reference's model-level warm start)",
                          None, TypeConverters.to_string)
    checkpointInterval = Param("checkpointInterval",
                               "Iterations between checkpoints", 10,
                               TypeConverters.to_int)
    verbosity = Param("verbosity", "Log verbosity", -1, TypeConverters.to_int)
    growthPolicy = Param("growthPolicy",
                         "leafwise (LightGBM-parity best-first, batched: top "
                         "leafBatch pending leaves split per histogram pass) "
                         "or depthwise (TPU-throughput mode: one batched "
                         "histogram pass per level, num_leaves budget "
                         "enforced best-gain-first)", "leafwise",
                         TypeConverters.to_string)
    leafBatch = Param("leafBatch",
                      "Leafwise growth: pending leaves split per fused "
                      "histogram pass. Leaves' row sets are disjoint, so "
                      "batching only reorders splits near num_leaves "
                      "exhaustion; 1 = strict sequential best-first "
                      "(LightGBM's exact order)", 8, TypeConverters.to_int)
    # cluster-compat params: topology comes from the device mesh on TPU
    parallelism = Param("parallelism", "data_parallel or voting_parallel "
                        "(mesh collectives implement both)", "data_parallel",
                        TypeConverters.to_string)
    topK = Param("topK", "Features each shard votes for under voting_parallel "
                 "(reference: LightGBMConstants.scala:24 DefaultTopK)", 20,
                 TypeConverters.to_int)
    topRate = Param("topRate", "GOSS: top-gradient retain fraction", 0.2,
                    TypeConverters.to_float)
    otherRate = Param("otherRate", "GOSS: random retain fraction of the rest", 0.1,
                      TypeConverters.to_float)
    dropRate = Param("dropRate", "DART: per-tree dropout probability", 0.1,
                     TypeConverters.to_float)
    maxDrop = Param("maxDrop", "DART: max trees dropped per iteration", 50,
                    TypeConverters.to_int)
    skipDrop = Param("skipDrop", "DART: probability of skipping dropout for "
                     "an iteration", 0.5, TypeConverters.to_float)
    dropSeed = Param("dropSeed", "DART: dropout random seed", 4,
                     TypeConverters.to_int)
    defaultListenPort = Param("defaultListenPort", "Ignored on TPU (no socket ring)",
                              12400, TypeConverters.to_int)
    timeout = Param("timeout", "Ignored on TPU (no rendezvous)", 1200.0,
                    TypeConverters.to_float)
    useBarrierExecutionMode = Param("useBarrierExecutionMode",
                                    "Ignored: SPMD gang scheduling is inherent",
                                    False, TypeConverters.to_bool)
    boostFromAverage = Param("boostFromAverage", "Init score from label mean", True,
                             TypeConverters.to_bool)
    leafPredictionCol = Param(
        "leafPredictionCol", "If set, output per-tree leaf indices here "
        "(reference: LightGBMModelMethods predLeaf)", None, TypeConverters.to_string)
    featuresShapCol = Param(
        "featuresShapCol", "If set, output per-feature contributions here "
        "(reference: LightGBMBooster.scala:250-269). Computed per "
        "shapMethod: exact TreeSHAP by default", None,
        TypeConverters.to_string)
    shapMethod = Param(
        "shapMethod", "featuresShapCol algorithm: 'treeshap' (exact Shapley "
        "values, LightGBM native-TreeSHAP parity, host) or 'saabas' (fast "
        "on-device path attribution — sums to the prediction but deviates "
        "from Shapley on correlated features)", "treeshap",
        TypeConverters.to_string)
    categoricalSlotIndexes = Param(
        "categoricalSlotIndexes", "Feature-vector slots to treat as "
        "categorical (values are category ids; splits are LightGBM "
        "sorted-subset bitsets — reference: LightGBMParams "
        "categoricalSlotIndexes, core/schema/Categoricals.scala)", None)
    useQuantizedGrad = Param(
        "useQuantizedGrad", "Quantized-gradient histograms (LightGBM "
        "use_quantized_grad): int8 grad/hess with stochastic rounding ride "
        "the 2x-rate int8 MXU path", False, TypeConverters.to_bool)
    quantRenewLeaf = Param(
        "quantRenewLeaf", "With useQuantizedGrad: renew leaf outputs from "
        "the original f32 grad/hess after each quantized tree (LightGBM "
        "quant_train_renew_leaf) so leaf values carry no int8 error",
        True, TypeConverters.to_bool)
    quantWarmupIters = Param(
        "quantWarmupIters", "With useQuantizedGrad: run the first k "
        "boosting iterations at full precision before switching to int8 "
        "histograms — stabilizes early split selection on targets whose "
        "root-level gains are near zero (pure interactions)", 2,
        TypeConverters.to_int)
    binDtype = Param(
        "binDtype", "Storage dtype of the device-resident binned matrix: "
        "int32 (default), int16 or uint8. Bin ids are < maxBin, so narrow "
        "storage is lossless (training is bit-identical) and shrinks the "
        "HBM-resident dataset 2x/4x — the lever that fits Criteo-scale "
        "data on a pod (docs/performance.md)", "int32",
        TypeConverters.to_string)
    histSubtraction = Param(
        "histSubtraction", "Parent-minus-sibling histogram subtraction "
        "(LightGBM's constant-time trick, here as smaller-child row "
        "compaction — bounds per-pass histogram rows at n/2). Single-device "
        "fits only; sharded fits keep full-width passes regardless. "
        "True | False | 'auto' (default): auto engages it on non-TPU "
        "backends, where halving histogram rows is a measured win, and "
        "keeps full-width MXU passes on TPU (docs/tpu_capture_r05)",
        "auto", _to_tristate_bool)
    compactSelector = Param(
        "compactSelector", "Row-compaction selector for histSubtraction: "
        "argsort (one stable sort), searchsorted (cumsum + binary search) "
        "or 'auto' (default: argsort on TPU, searchsorted elsewhere)",
        "auto", TypeConverters.to_string)
    categoricalSlotNames = Param(
        "categoricalSlotNames", "Categorical slots by feature name; requires "
        "a featuresCol with slot names (use categoricalSlotIndexes for "
        "plain arrays)", None)
    improvementTolerance = Param(
        "improvementTolerance", "Early stopping: an iteration counts as "
        "improved only when it beats the best validation metric by more "
        "than this (reference: LightGBMParams improvementTolerance)", 0.0,
        TypeConverters.to_float)
    isProvideTrainingMetric = Param(
        "isProvideTrainingMetric", "Record the training-set metric every "
        "iteration into evalHistory['training_<metric>'] (reference: "
        "TrainParams isProvideTrainingMetric). gbdt/goss only; forces the "
        "per-iteration host loop instead of the fused dispatch", False,
        TypeConverters.to_bool)
    posBaggingFraction = Param(
        "posBaggingFraction", "Stratified bagging: keep probability for "
        "positive rows (binary only; set with negBaggingFraction and "
        "baggingFreq > 0)", 1.0, TypeConverters.to_float)
    negBaggingFraction = Param(
        "negBaggingFraction", "Stratified bagging: keep probability for "
        "negative rows (binary only)", 1.0, TypeConverters.to_float)
    maxDeltaStep = Param(
        "maxDeltaStep", "Clamp each leaf's raw output to +-this before "
        "shrinkage (0 = off; stabilizes poisson / highly imbalanced "
        "binary)", 0.0, TypeConverters.to_float)
    maxBinByFeature = Param(
        "maxBinByFeature", "Per-feature max bin counts (list as long as "
        "the feature vector; each capped by maxBin)", None)
    metric = Param(
        "metric", "Validation/early-stopping metric override (reference: "
        "LightGBMParams metric). Per objective family: binary -> "
        "binary_logloss | binary_error | auc; multiclass -> multi_logloss "
        "| multi_error; regression family -> rmse/l2 | mae/l1; ranker -> "
        "ndcg. auc computes the exact weighted rank statistic on host",
        None, TypeConverters.to_string)
    slotNames = Param(
        "slotNames", "Feature names for the feature-vector slots — flow "
        "into the native model string's feature_names and importances "
        "(reference: LightGBMParams slotNames)", None)
    driverListenPort = Param(
        "driverListenPort", "Ignored on TPU (no driver rendezvous socket)",
        0, TypeConverters.to_int)
    numTasks = Param(
        "numTasks", "Ignored on TPU: shard count comes from the device "
        "mesh (reference capped Spark task count)", 0,
        TypeConverters.to_int)
    repartitionByGroupingColumn = Param(
        "repartitionByGroupingColumn", "Ignored on TPU: the ranker pads "
        "and shards whole groups itself, so group alignment never depends "
        "on input partitioning", True, TypeConverters.to_bool)

    def _grow_config(self) -> GrowConfig:
        # resolved ("auto" -> concrete per backend) BEFORE the config can
        # reach any compiled-program cache key — train_booster re-resolves
        # defensively, but the sweep path consumes this config directly.
        # The resolver also owns compact_selector/hist_subtraction value
        # validation (one error message, one allowed-values list).
        return resolve_growth_backend(GrowConfig(
            num_leaves=self.get_or_default("numLeaves"),
            max_depth=self.get_or_default("maxDepth"),
            num_bins=self.get_or_default("maxBin"),
            learning_rate=self.get_or_default("learningRate"),
            lambda_l1=self.get_or_default("lambdaL1"),
            lambda_l2=self.get_or_default("lambdaL2"),
            min_data_in_leaf=self.get_or_default("minDataInLeaf"),
            min_sum_hessian_in_leaf=self.get_or_default("minSumHessianInLeaf"),
            min_gain_to_split=self.get_or_default("minGainToSplit"),
            voting=self.get_or_default("parallelism") == "voting_parallel",
            top_k=self.get_or_default("topK"),
            growth_policy=self.get_or_default("growthPolicy"),
            leaf_batch=self.get_or_default("leafBatch"),
            quantized_grad=self.get_or_default("useQuantizedGrad"),
            quant_renew_leaf=self.get_or_default("quantRenewLeaf"),
            quant_warmup_iters=self.get_or_default("quantWarmupIters"),
            hist_subtraction=self.get_or_default("histSubtraction"),
            compact_selector=self.get_or_default("compactSelector"),
            max_delta_step=self.get_or_default("maxDeltaStep"),
        ))

    def _extract_arrays(self, dataset: Dataset):
        fcol = self.get_or_default("featuresCol")
        raw = dataset[fcol]
        # sparse CSR features pass through untouched (train_booster densifies
        # per row block — LGBM_DatasetCreateFromCSR parity)
        X = raw if _is_sparse(raw) else dataset.array(fcol, np.float32)
        y = dataset.array(self.get_or_default("labelCol"), np.float32)
        wcol = self.get_or_default("weightCol")
        w = dataset.array(wcol, np.float32) if wcol else None
        return X, y, w

    def _categorical_indexes(self):
        if self.get_or_default("categoricalSlotNames"):
            raise ValueError(
                "categoricalSlotNames requires named feature slots; this "
                "columnar Dataset API carries plain arrays — use "
                "categoricalSlotIndexes")
        idx = self.get_or_default("categoricalSlotIndexes")
        return tuple(int(i) for i in idx) if idx else ()

    def _split_validation(self, dataset: Dataset):
        """validationIndicatorCol semantics (reference: LightGBMBase.scala:214-219)."""
        vcol = self.get_or_default("validationIndicatorCol")
        if not vcol or vcol not in dataset:
            return dataset, None
        mask = dataset.array(vcol).astype(bool)
        return dataset.filter(~mask), dataset.filter(mask)

    def _round_callback(self):
        """Per-boost-round telemetry callback, or None.

        Opt-in via MMLSPARK_TPU_TELEMETRY_ROUNDS=1: a non-None
        iteration_callback forces train_booster onto its host loop (one
        device dispatch per round), so round-level spans must never be the
        silent default — the fused single-dispatch paths are the product.
        """
        if not (_metrics.enabled()
                and os.environ.get("MMLSPARK_TPU_TELEMETRY_ROUNDS") == "1"):
            return None
        cls = type(self).__name__
        import time as _time
        last = [_time.perf_counter()]

        def cb(it: int, round_metrics: dict) -> None:
            vals = {k: float(v) for k, v in round_metrics.items()}
            _spans.instant("boost_round", model=cls, iteration=it, **vals)
            _metrics.safe_counter("gbdt_boost_rounds_total", model=cls).inc()
            # live training-health sentinels: per-round loss (NaN /
            # divergence) and round wall time (throughput collapse)
            now = _time.perf_counter()
            _watchdog.report_training_metric(cls, it, seconds=now - last[0])
            last[0] = now
            for k, v in vals.items():
                _metrics.safe_gauge("gbdt_round_metric",
                                    model=cls, metric=k).set(v)
                _watchdog.report_training_metric(cls, it, loss=v,
                                                 metric_name=k)
        return cb

    def _publish_booster_telemetry(self, booster: Booster) -> None:
        """Registry view of a finished fit: round count, best iteration,
        final value of each tracked loss/metric series, and a fresh HBM
        sample (the binned-dataset cache retains device memory across fits
        — exactly the growth device_memory_bytes should make visible)."""
        if not _metrics.enabled():
            return
        cls = type(self).__name__
        _metrics.safe_counter("gbdt_fits_total", model=cls).inc()
        _metrics.safe_gauge("gbdt_trained_iterations",
                            model=cls).set(booster.num_iterations)
        if booster.best_iteration is not None and booster.best_iteration >= 0:
            _metrics.safe_gauge("gbdt_best_iteration",
                                model=cls).set(booster.best_iteration)
        for mname, series in (booster.eval_history or {}).items():
            if series:
                _metrics.safe_gauge("gbdt_train_metric", model=cls,
                                    metric=str(mname)).set(float(series[-1]))
        # post-fit health audit: NaN / divergence anywhere in the metric
        # history flips training_health{model} — this is the path that
        # covers the fused single-dispatch fits, which have no rounds
        _watchdog.scan_eval_history(cls, booster.eval_history)
        from ...observability.device import device_memory_gauges
        device_memory_gauges()

    def _fit_booster(self, dataset: Dataset, objective: str, num_class: int,
                     objective_kwargs: Optional[dict] = None) -> Booster:
        cls = type(self).__name__
        # fresh sentinel windows for this estimator's health stream (the
        # booster-level "gbdt" stream resets inside train_booster)
        _watchdog.reset_training_health(cls)
        with _spans.span(f"{self.uid}.train_booster",
                         metric_label=f"{cls}.train_booster",
                         objective=objective, num_class=num_class):
            booster = self._fit_booster_impl(dataset, objective, num_class,
                                             objective_kwargs)
        self._publish_booster_telemetry(booster)
        return booster

    def _fit_booster_impl(self, dataset: Dataset, objective: str,
                          num_class: int,
                          objective_kwargs: Optional[dict] = None) -> Booster:
        train_ds, valid_ds = self._split_validation(dataset)
        X, y, w = self._extract_arrays(train_ds)
        valid_set = None
        if valid_ds is not None and len(valid_ds) > 0:
            valid_set = self._extract_arrays(valid_ds)

        init_booster = None
        ms = self.get_or_default("modelString")
        if ms:
            init_booster = Booster.from_string(ms)

        num_batches = self.get_or_default("numBatches")
        common = dict(
            checkpoint_dir=self.get_or_default("checkpointDir"),
            checkpoint_period=self.get_or_default("checkpointInterval"),
            objective=objective, num_class=num_class,
            cfg=self._grow_config(),
            max_bin=self.get_or_default("maxBin"),
            bin_sample_count=self.get_or_default("binSampleCount"),
            feature_fraction=self.get_or_default("featureFraction"),
            bagging_fraction=self.get_or_default("baggingFraction"),
            bagging_freq=self.get_or_default("baggingFreq"),
            seed=self.get_or_default("baggingSeed"),
            early_stopping_rounds=self.get_or_default("earlyStoppingRound"),
            metric_eval_period=self.get_or_default("metricEvalPeriod"),
            boost_from_average=self.get_or_default("boostFromAverage"),
            objective_kwargs=objective_kwargs or {},
            boosting_type=self.get_or_default("boostingType"),
            top_rate=self.get_or_default("topRate"),
            other_rate=self.get_or_default("otherRate"),
            drop_rate=self.get_or_default("dropRate"),
            max_drop=self.get_or_default("maxDrop"),
            skip_drop=self.get_or_default("skipDrop"),
            drop_seed=self.get_or_default("dropSeed"),
            categorical_features=self._categorical_indexes(),
            bin_dtype=self.get_or_default("binDtype"),
            pos_bagging_fraction=self.get_or_default("posBaggingFraction"),
            neg_bagging_fraction=self.get_or_default("negBaggingFraction"),
            early_stopping_tolerance=self.get_or_default(
                "improvementTolerance"),
            provide_training_metric=self.get_or_default(
                "isProvideTrainingMetric"),
            max_bin_by_feature=self.get_or_default("maxBinByFeature"),
            eval_metric_name=self.get_or_default("metric"),
            # None unless MMLSPARK_TPU_TELEMETRY_ROUNDS=1: a live callback
            # forces the host loop, so fused dispatch stays the default
            iteration_callback=self._round_callback(),
        )
        num_iterations = self.get_or_default("numIterations")
        if (num_batches and num_batches > 1
                and common["boosting_type"] in ("rf", "dart")):
            # fail before batch 0 trains: batch 1 would reject the warm start
            raise ValueError(
                f"numBatches > 1 is not supported with boostingType="
                f"{common['boosting_type']!r} (its trees carry normalization "
                "state that a warm-start prefix lacks)")
        if num_batches and num_batches > 1:
            # sequential warm-started batches (reference: LightGBMBase.scala:28-50)
            n = len(y)
            bounds = np.linspace(0, n, num_batches + 1).astype(int)
            booster = init_booster
            base_ckpt = common.get("checkpoint_dir")
            for i in range(num_batches):
                sl = slice(bounds[i], bounds[i + 1])
                if base_ckpt:
                    # one subdir per batch: batch i must never resume from
                    # batch i-1's mid-train checkpoint
                    common["checkpoint_dir"] = os.path.join(
                        base_ckpt, f"batch_{i:04d}")
                booster = train_booster(
                    X[sl], y[sl], None if w is None else w[sl],
                    num_iterations=num_iterations, valid_set=valid_set,
                    init_booster=booster, **common)
            return self._apply_slot_names(booster)
        if common["checkpoint_dir"] is None:
            # sweep fast path: reuse the binned device dataset across fits
            # on identical data + binning params (content-fingerprint keyed)
            dset = _cached_binned_dataset(
                X, y, w,
                max_bin=common["max_bin"],
                bin_sample_count=common["bin_sample_count"],
                seed=common["seed"],
                categorical_features=common["categorical_features"],
                bin_dtype=common["bin_dtype"],
                max_bin_by_feature=common["max_bin_by_feature"])
            return self._apply_slot_names(train_booster(
                X=X if init_booster is not None else None,
                dataset=dset, num_iterations=num_iterations,
                valid_set=valid_set, init_booster=init_booster, **common))
        return self._apply_slot_names(train_booster(
            X, y, w, num_iterations=num_iterations,
            valid_set=valid_set, init_booster=init_booster, **common))

    def _apply_slot_names(self, booster: Booster) -> Booster:
        """Record slotNames as the model's feature names (they flow into
        the native model string; reference: LightGBMParams slotNames)."""
        names = self.get_or_default("slotNames")
        if names:
            F = booster.binner_state.get("num_features")
            if F is not None and len(names) != F:
                raise ValueError(
                    f"slotNames has {len(names)} entries for {F} features")
            names = [str(x) for x in names]
            bad = [x for x in names if not x or any(c.isspace() for c in x)]
            if bad:
                # the native text format is whitespace-delimited
                raise ValueError(
                    f"slotNames must be non-empty and whitespace-free for "
                    f"native-model interop; got {bad[:3]}")
            booster.binner_state["feature_names"] = names
        return booster


class _LightGBMModelBase(Model, _LightGBMParams):
    """Shared trained-model behavior (importances, native model export)."""

    def __init__(self, booster: Optional[Booster] = None, **kwargs):
        super().__init__(**kwargs)
        self.booster = booster

    def _add_introspection_cols(self, dataset: Dataset, X) -> Dataset:
        leaf_col = self.get_or_default("leafPredictionCol")
        if leaf_col:
            dataset = dataset.with_column(
                leaf_col, self.booster.predict_leaf(X).astype(np.float64))
        shap_col = self.get_or_default("featuresShapCol")
        if shap_col:
            dataset = dataset.with_column(
                shap_col, self.booster.predict_contrib(
                    X, method=self.get_or_default("shapMethod")
                ).astype(np.float64))
        return dataset

    def get_feature_importances(self, importance_type: str = "split"):
        return self.booster.feature_importances(importance_type).tolist()

    def get_native_model(self) -> str:
        """The model as a stock-LightGBM text string (loads in any LightGBM
        tooling; reference: LightGBMModelMethods getNativeModel)."""
        return self.booster.to_lightgbm_string()

    def save_native_model(self, path: str) -> None:
        """reference: LightGBMClassifier.scala:172-194 saveNativeModel —
        writes the LightGBM ``tree`` v3 text format for tool interop."""
        with open(path, "w") as f:
            f.write(self.booster.to_lightgbm_string())

    def _save_extra(self, path: str) -> None:
        import os
        self.booster.save(os.path.join(path, "booster"))

    def _load_extra(self, path: str) -> None:
        import os
        self.booster = Booster.load(os.path.join(path, "booster"))


class LightGBMClassifier(Estimator, _LightGBMParams, HasRawPredictionCol,
                         HasProbabilityCol):
    """Distributed GBDT classifier (reference: lightgbm/LightGBMClassifier.scala:24-66).

    HBM note: ``fit`` caches the binned device dataset (two fits, LRU) so
    sweeps skip re-ingest; the cache pins up to two [F, n] int32 matrices in
    device memory after training ends. Call
    :func:`clear_binned_dataset_cache` to release them, or set
    ``MMLSPARK_TPU_BINNED_CACHE=0`` to disable the cache entirely.
    """

    objective = Param("objective", "binary or multiclass (auto from label arity)",
                      None, TypeConverters.to_string)
    isUnbalance = Param("isUnbalance", "Upweight the minority class (binary)", False,
                        TypeConverters.to_bool)
    thresholds = Param("thresholds", "Per-class prediction thresholds", None,
                       TypeConverters.to_list_float)

    def fit(self, dataset: Dataset) -> "LightGBMClassificationModel":
        y = dataset.array(self.get_or_default("labelCol"))
        classes = np.unique(y[~np.isnan(y.astype(np.float64))])
        if classes.size and (classes.min() < 0 or
                             not np.allclose(classes, classes.astype(int))):
            raise ValueError(
                "labels must be non-negative integers 0..k-1 (use ValueIndexer "
                f"or TrainClassifier to index them); got values {classes[:5]}")
        # num_class from the max label so non-contiguous labels (e.g. {0, 2})
        # are handled as multiclass rather than silently treated as binary
        num_class = max(int(classes.max()) + 1 if classes.size else 2, 2)
        obj = self.get_or_default("objective")
        if obj is None:
            obj = "binary" if num_class <= 2 else "multiclass"
        if obj == "binary" and num_class > 2:
            raise ValueError(
                f"binary objective needs labels in {{0,1}}, got {num_class} classes")
        kwargs = {}
        if obj == "binary" and self.get_or_default("isUnbalance"):
            pos = float((y > 0).sum())
            neg = float(len(y) - pos)
            kwargs["pos_weight"] = neg / max(pos, 1.0)
        booster = self._fit_booster(
            dataset, obj, num_class if obj == "multiclass" else 1, kwargs)
        model = LightGBMClassificationModel(booster, numClasses=num_class)
        self._copy_params_to(model)
        return model


class LightGBMClassificationModel(_LightGBMModelBase, HasRawPredictionCol,
                                  HasProbabilityCol):
    numClasses = Param("numClasses", "Number of classes", 2, TypeConverters.to_int)
    thresholds = Param("thresholds", "Per-class prediction thresholds", None,
                       TypeConverters.to_list_float)

    def get_actual_num_classes(self) -> int:
        """reference: LightGBMClassificationModel actualNumClasses —
        the class count the trained booster actually models."""
        return max(self.booster.num_class, 2)

    def transform(self, dataset: Dataset) -> Dataset:
        X = _features_dense(dataset, self.get_or_default("featuresCol"))
        raw = self.booster.predict_raw(X)  # [n, K]
        K = self.get_or_default("numClasses")
        if self.booster.num_class == 1:  # binary: margin for [neg, pos]
            margins = np.concatenate([-raw, raw], axis=1)
            p1 = 1.0 / (1.0 + np.exp(-raw[:, 0]))
            probs = np.stack([1 - p1, p1], axis=1)
        else:
            margins = raw
            e = np.exp(raw - raw.max(axis=1, keepdims=True))
            probs = e / e.sum(axis=1, keepdims=True)
        th = self.get_or_default("thresholds")
        scaled = probs / np.asarray(th)[None, :] if th else probs
        pred = scaled.argmax(axis=1).astype(np.float64)
        out = dataset.with_columns({
            self.get_or_default("rawPredictionCol"): margins,
            self.get_or_default("probabilityCol"): probs,
            self.get_or_default("predictionCol"): pred,
        })
        return self._add_introspection_cols(out, X)

    @staticmethod
    def load_native_model(path: str) -> "LightGBMClassificationModel":
        with open(path) as f:
            booster = Booster.from_string(f.read())
        k = 2 if booster.num_class == 1 else booster.num_class
        return LightGBMClassificationModel(booster, numClasses=k)


class LightGBMRegressor(Estimator, _LightGBMParams):
    """Distributed GBDT regressor (reference: lightgbm/LightGBMRegressor.scala;
    objectives per TrainParams.scala:86-104).

    HBM note: ``fit`` caches binned device datasets — see
    :class:`LightGBMClassifier` for the retention/release story.
    """

    objective = Param("objective", "regression|regression_l1|huber|fair|poisson|"
                      "quantile|mape|tweedie", "regression", TypeConverters.to_string)
    alpha = Param("alpha", "Huber/quantile alpha", 0.9, TypeConverters.to_float)
    tweedieVariancePower = Param("tweedieVariancePower",
                                 "Tweedie variance power in [1, 2)", 1.5,
                                 TypeConverters.to_float)

    def fit(self, dataset: Dataset) -> "LightGBMRegressionModel":
        obj = self.get_or_default("objective")
        kwargs = {}
        if obj in ("huber", "quantile"):
            kwargs["alpha"] = self.get_or_default("alpha")
        if obj == "tweedie":
            kwargs["tweedie_variance_power"] = self.get_or_default("tweedieVariancePower")
        booster = self._fit_booster(dataset, obj, 1, kwargs)
        model = LightGBMRegressionModel(booster)
        self._copy_params_to(model)
        return model


class LightGBMRegressionModel(_LightGBMModelBase):
    def transform(self, dataset: Dataset) -> Dataset:
        X = _features_dense(dataset, self.get_or_default("featuresCol"))
        pred = self.booster.predict(X).astype(np.float64)
        out = dataset.with_column(self.get_or_default("predictionCol"), pred)
        return self._add_introspection_cols(out, X)

    @staticmethod
    def load_native_model(path: str) -> "LightGBMRegressionModel":
        with open(path) as f:
            return LightGBMRegressionModel(Booster.from_string(f.read()))


def _features_dense(dataset: Dataset, col: str) -> np.ndarray:
    """Features column as dense float32 (scoring path accepts the same
    sparse CSR input fit does)."""
    from .booster import _densify
    raw = dataset[col]
    if _is_sparse(raw):
        return _densify(raw)
    return dataset.array(col, np.float32)


def _pad_groups(X: np.ndarray, y: np.ndarray, w: Optional[np.ndarray],
                group: np.ndarray, S: int, n_shard_multiple: int):
    """Sort rows by group and pad every query group to a static width S.

    The TPU replacement for the reference's group-aware repartition
    (lightgbm/LightGBMRanker.scala:80-98 keeps each query's rows inside one
    partition): each group becomes a fixed [S] block, groups are padded to a
    multiple of the shard count, so shard boundaries never cut a group and
    every shard sees an identical static shape.

    Returns (Xp, yp, wp, valid, n_groups) with Xp of shape [G_pad*S, F].
    """
    group = np.asarray(group)
    order = np.argsort(group, kind="stable")
    X, y = X[order], y[order]
    w = None if w is None else w[order]
    _, starts, counts = np.unique(group[order], return_index=True,
                                  return_counts=True)
    G = len(starts)
    G_pad = -(-G // n_shard_multiple) * n_shard_multiple
    F = X.shape[1]
    Xp = np.zeros((G_pad * S, F), dtype=np.float32)
    yp = np.zeros(G_pad * S, dtype=np.float32)
    wp = np.zeros(G_pad * S, dtype=np.float32)
    valid = np.zeros(G_pad * S, dtype=np.float32)
    for g in range(G):
        c = min(int(counts[g]), S)  # truncate oversize groups
        sl = slice(starts[g], starts[g] + c)
        dst = slice(g * S, g * S + c)
        Xp[dst], yp[dst] = X[sl], y[sl]
        wp[dst] = 1.0 if w is None else w[sl]
        valid[dst] = 1.0
    return Xp, yp, wp, valid, G


class LightGBMRanker(Estimator, _LightGBMParams, HasGroupCol):
    """Distributed LambdaRank (reference: lightgbm/LightGBMRanker.scala).

    HBM note: ``fit`` caches binned device datasets — see
    :class:`LightGBMClassifier` for the retention/release story.

    Groups are padded to ``maxGroupSize`` static blocks so the pairwise
    lambda computation is one dense MXU batch; each shard holds whole groups
    (the reference's group-aware repartition, LightGBMRanker.scala:80-98).
    """

    objective = Param("objective", "ranking objective", "lambdarank",
                      TypeConverters.to_string)
    labelGain = Param("labelGain", "NDCG gain per relevance grade: grade "
                      "g scores labelGain[g] (reference: LightGBMRanker "
                      "labelGain; default 2^g - 1)", None,
                      TypeConverters.to_list_float)
    maxPosition = Param("maxPosition", "NDCG truncation position "
                        "(reference: TrainParams maxPosition)", 20,
                        TypeConverters.to_int)
    evalAt = Param("evalAt", "Positions for NDCG evaluation", [1, 3, 5, 10],
                   TypeConverters.to_list_int)
    maxGroupSize = Param("maxGroupSize",
                         "Static padded width per query group (rows beyond "
                         "this are truncated)", 128, TypeConverters.to_int)
    sigma = Param("sigma", "LambdaRank sigmoid steepness", 1.0,
                  TypeConverters.to_float)

    def fit(self, dataset: Dataset) -> "LightGBMRankerModel":
        from ...parallel import mesh as meshlib

        train_ds, valid_ds = self._split_validation(dataset)
        gcol = self.get_or_default("groupCol")
        if not gcol:
            raise ValueError("LightGBMRanker requires groupCol")
        nshards = meshlib.num_shards(meshlib.get_default_mesh())

        X, y, w = self._extract_arrays(train_ds)
        from .booster import _densify
        X = _densify(X)            # ranker pads groups before train_booster
        group = np.asarray(train_ds[gcol])
        sizes = np.unique(group, return_counts=True)[1]
        S = int(min(self.get_or_default("maxGroupSize"),
                    1 << int(np.ceil(np.log2(max(sizes.max(), 2))))))
        Xp, yp, wp, valid, _ = _pad_groups(X, y, w, group, S, nshards)

        valid_set = None
        if valid_ds is not None and len(valid_ds) > 0:
            Xv, yv, _ = self._extract_arrays(valid_ds)
            Xv = _densify(Xv)
            gv = np.asarray(valid_ds[gcol])
            Xvp, yvp, _, validv, _ = _pad_groups(Xv, yv, None, gv, S, nshards)
            # per-row metric weight 1/group_size -> weighted mean == mean NDCG
            # over groups (see objectives._ndcg_metric)
            gsz = validv.reshape(-1, S).sum(axis=1)
            wv = (validv.reshape(-1, S)
                  / np.maximum(gsz, 1.0)[:, None]).reshape(-1)
            valid_set = (Xvp, yvp, wv.astype(np.float32))

        eval_at = self.get_or_default("evalAt") or []
        kwargs = dict(group_size=S,
                      max_position=self.get_or_default("maxPosition"),
                      sigma=self.get_or_default("sigma"),
                      eval_at=int(max(eval_at)) if eval_at else 0)
        lg = self.get_or_default("labelGain")
        if lg is not None:
            # LightGBM fails fast when a label grade exceeds the gain
            # table; silent clamping would train against wrong gains
            max_grade = int(np.nanmax(yp)) if len(yp) else 0
            if max_grade >= len(lg):
                raise ValueError(
                    f"labelGain has {len(lg)} entries but the data "
                    f"contains relevance grade {max_grade}")
            # tuple: objective_kwargs flow into hashed program-cache keys
            kwargs["label_gain"] = tuple(float(g) for g in lg)
        booster = train_booster(
            Xp, yp, wp,
            objective="lambdarank", num_class=1,
            cfg=self._grow_config(),
            max_bin=self.get_or_default("maxBin"),
            bin_sample_count=self.get_or_default("binSampleCount"),
            feature_fraction=self.get_or_default("featureFraction"),
            bagging_fraction=self.get_or_default("baggingFraction"),
            bagging_freq=self.get_or_default("baggingFreq"),
            seed=self.get_or_default("baggingSeed"),
            num_iterations=self.get_or_default("numIterations"),
            valid_set=valid_set,
            early_stopping_rounds=self.get_or_default("earlyStoppingRound"),
            early_stopping_tolerance=self.get_or_default(
                "improvementTolerance"),
            provide_training_metric=self.get_or_default(
                "isProvideTrainingMetric"),
            max_bin_by_feature=self.get_or_default("maxBinByFeature"),
            eval_metric_name=self.get_or_default("metric"),
            metric_eval_period=self.get_or_default("metricEvalPeriod"),
            boost_from_average=False,
            objective_kwargs=kwargs,
            row_valid=valid,
            boosting_type=self.get_or_default("boostingType"),
        )
        booster = self._apply_slot_names(booster)
        model = LightGBMRankerModel(booster)
        self._copy_params_to(model)
        return model


class LightGBMRankerModel(_LightGBMModelBase):
    def transform(self, dataset: Dataset) -> Dataset:
        X = _features_dense(dataset, self.get_or_default("featuresCol"))
        score = self.booster.predict_raw(X)[:, 0].astype(np.float64)
        out = dataset.with_column(self.get_or_default("predictionCol"), score)
        return self._add_introspection_cols(out, X)

    @staticmethod
    def load_native_model(path: str) -> "LightGBMRankerModel":
        with open(path) as f:
            return LightGBMRankerModel(Booster.from_string(f.read()))
