"""Leaf-wise (best-first) tree growth as a fixed-shape XLA program.

TPU-native replacement for LightGBM's C++ tree learner invoked per iteration
through LGBM_BoosterUpdateOneIter (reference: lightgbm/TrainUtils.scala:246,
with distributed semantics of the ``data_parallel`` learner —
lightgbm/LightGBMParams.scala:13-18). Where the reference mutates dynamic row
sets per leaf, the TPU formulation keeps everything static-shape:

  * a tree is ``M = 2*num_leaves - 1`` preallocated node slots;
  * each row carries its current node id (``row_node``), updated by masked
    ``where`` — no repartitioning;
  * each of the ``num_leaves - 1`` split rounds is one ``fori_loop`` step:
    pick the cached best leaf, build both children's histograms in a single
    MXU pass (6 stats: grad/hess/count × left/right), find their best splits,
    record the split — all data-dependent choices via argmax + where, never
    Python control flow.

Layout: the binned matrix rides **column-major** (``binned_t``: [F, n]) for
the whole training run — histogram row blocks and per-feature column reads
are then contiguous device slices, with no per-level transposes or per-row
feature gathers (both measured dominators of the row-major formulation).

Run inside ``shard_map`` with rows sharded over the ``data`` axis, the single
``psum`` on histograms reproduces the reference's per-iteration histogram
all-reduce over its TCP ring (TrainUtils.scala:496-512), but on ICI.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ...ops.histogram import node_histogram, quant_q_max, quantize_stats
from ...parallel.compat import axis_size as _axis_size

NEG_INF = jnp.float32(-jnp.inf)


class GrowConfig(NamedTuple):
    num_leaves: int = 31
    max_depth: int = -1  # <0: unlimited (bounded by num_leaves chain)
    num_bins: int = 255
    learning_rate: float = 0.1
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    # "leafwise" = LightGBM-parity best-first growth. "depthwise" =
    # TPU-throughput mode: one histogram pass per LEVEL with every frontier
    # node's stats batched into the stat axis (histogram cost is flat in
    # that axis up to ~128 lanes, so a 31-leaf tree takes ~6 passes instead
    # of 30); the num_leaves budget is enforced by splitting the best nodes
    # first.
    growth_policy: str = "leafwise"
    # leafwise batching: split the top ``leaf_batch`` pending leaves (by
    # cached gain) per histogram pass instead of one. Splits of distinct
    # leaves are independent (disjoint row sets), so batching only changes
    # the ORDER splits are taken in — which matters solely when num_leaves
    # runs out mid-batch and a child's gain would have outranked a pending
    # leaf's. leaf_batch=1 is exact sequential best-first (LightGBM order);
    # the default trades that tail-order nuance for ~4-5x fewer passes.
    # The histogram pass cost here is flat in the node axis (the one-hot
    # matmul scans all rows regardless of node sizes), so subtraction alone
    # would not reduce pass cost — batching cuts the PASS COUNT, and
    # ``hist_subtraction`` additionally cuts per-pass cost by compacting the
    # smaller children's rows into a half-width buffer.
    # Caveat under voting_parallel: the top-2k feature ballot then spans the
    # whole batch's children (one vote per pass, like depthwise's
    # frontier-wide vote) rather than one split's two children, so voting
    # runs are a batch-wide approximation, not a pure reordering — voting
    # is itself an approximate-split mode, and leaf_batch=1 restores the
    # per-split ballot exactly.
    leaf_batch: int = 8
    # voting_parallel (reference: lightgbm/LightGBMParams.scala:13-27,
    # LightGBMConstants.scala:24 DefaultTopK): shards vote on locally-best
    # top_k features; only the globally top 2k features' histograms are
    # all-reduced — two small collectives instead of one [F,3,B] psum.
    voting: bool = False
    top_k: int = 20
    # categorical splits (reference ingests categorical metadata natively:
    # core/schema/Categoricals.scala, LightGBMUtils.scala:227,256): category
    # bins are sorted by smoothed gradient ratio and scanned as prefixes
    # (LightGBM's sorted-subset search); the chosen subset is a bitset.
    cat_smooth: float = 10.0
    max_cat_threshold: int = 32
    # quantized-gradient histograms (LightGBM use_quantized_grad): grad/hess
    # quantize to int8 per tree (stochastic rounding) and histograms ride
    # the 2x-rate int8 MXU path with exact int32 accumulation.
    quantized_grad: bool = False
    # Quantized-mode quality controls (both only engage with quantized_grad):
    # - quant_renew_leaf (LightGBM quant_train_renew_leaf): after growing a
    #   quantized tree, recompute the LEAF grad/hess/count sums from the
    #   original f32 stats with one segment-sum over the final row->leaf map,
    #   so leaf outputs carry no quantization error (split STRUCTURE still
    #   comes from int8 histograms — that's where the 2x MXU win lives).
    # - quant_warmup_iters: run the first k boosting iterations at full
    #   precision before switching to int8. Early iterations on targets with
    #   near-zero marginal gains (pure interactions) are where quantization
    #   noise can misroute split selection; after the ensemble has carved the
    #   first partitions, per-node gains are real and int8 selection matches.
    #   Runtime cost: warmup iterations run at bf16 histogram rate; both
    #   variants live in one compiled program (lax.cond), so fused scans and
    #   the early-stopping while_loop keep their single-dispatch shape.
    quant_renew_leaf: bool = True
    quant_warmup_iters: int = 2
    # LightGBM max_delta_step: clamp each leaf's raw output (pre-shrinkage)
    # to +-this; 0 disables. Stabilizes extreme leaf values (LightGBM
    # recommends it for poisson / highly imbalanced binary).
    max_delta_step: float = 0.0
    # Histogram subtraction (LightGBM's parent-minus-sibling trick, made
    # profitable on TPU by row compaction), honored by BOTH growth policies:
    # gather the rows of each sibling pair's SMALLER child — at most n//2
    # rows in total, guaranteed — into a half-width buffer, build only those
    # children's histograms, and derive each larger sibling as parent minus
    # smaller. Depthwise engages from level 1 (the previous level's
    # histograms are the parents); leafwise caches every node's histogram
    # so every round subtracts (see the nhist comment in grow_tree).
    # Single-device only: a shard's local membership of the globally-smaller
    # children is unbounded, so sharded fits (axis_name set) keep full-width
    # passes regardless of this flag.
    # Tri-state: True | False | "auto" (default). "auto" resolves per
    # BACKEND via :func:`resolve_growth_backend` — off on TPU, where the
    # round-5 live capture (docs/tpu_capture_r05/) measured the
    # row-compaction gather/sort at 3.4-10x the full-width one-hot pass it
    # saves (depthwise 24.2 -> 7.0 argsort / 2.4 searchsorted trees/sec);
    # ON elsewhere, where halving histogram rows is a measured CPU-side
    # win. The sentinel NEVER reaches traced code or a compiled-program
    # cache key: train_booster and the estimator layer both resolve it
    # first (lint-pinned in tests/test_lint.py).
    hist_subtraction: "bool | str" = "auto"
    # Row-compaction selector for hist_subtraction: "argsort" (one stable
    # [n] sort), "searchsorted" (cumsum + binary search, no sort), or
    # "auto" (default: argsort on TPU — r5 measured it 2.9x the
    # searchsorted variant there — searchsorted elsewhere, where the
    # sort-free form wins). A config field — not an env var — so every
    # compiled-program cache keyed on cfg stays correct for free; resolved
    # alongside hist_subtraction.
    compact_selector: str = "auto"
    # Deterministic histogram-reduction geometry (topology-independent
    # training). 0 = the plain path: per-shard histograms psum'd across the
    # mesh — fast, but f32 accumulation order (and therefore the last ulp
    # of every gain and leaf value) depends on the device count. An int
    # k >= 2 pins a CANONICAL geometry instead: rows are processed as k
    # fixed blocks, per-block histograms/stat-sums are all_gather'd in
    # block order and folded left-to-right, and quantized-gradient scales/
    # rounding derive from global row indices — so every device count
    # dividing k grows BIT-IDENTICAL trees (model_string() equality at
    # k=8 across 1/2/4/8 devices; the preemption-resume story across
    # topology changes). Costs one gathered [k, F, 3W, B] transient per
    # pass and disables histogram subtraction. "auto" (default) resolves
    # via placement.resolve_hist_blocks (MMLSPARK_TPU_HIST_BLOCKS, default
    # 0) BEFORE entering any compiled-program cache key; unresolved "auto"
    # reaching growth behaves as 0.
    hist_blocks: "int | str" = "auto"


def resolve_growth_backend(cfg: GrowConfig) -> GrowConfig:
    """Resolve the backend-adaptive tri-states to concrete values.

    ``hist_subtraction="auto"`` -> False on TPU (full-width MXU passes win
    there), True elsewhere; ``compact_selector="auto"`` -> "argsort" on
    TPU, "searchsorted" elsewhere (rationale on the GrowConfig fields).
    MUST run before the config enters any compiled-program cache key or
    traced code: two processes on different backends resolve differently,
    and an unresolved sentinel in a cache key would alias their programs.
    Idempotent; validates ``compact_selector`` either way.
    """
    hs, cs = cfg.hist_subtraction, cfg.compact_selector
    if cs not in ("auto", "argsort", "searchsorted"):
        raise ValueError(
            f"compact_selector must be 'auto', 'argsort' or 'searchsorted',"
            f" got {cs!r}")
    if hs != "auto" and not isinstance(hs, bool):
        raise ValueError(
            f"hist_subtraction must be True, False or 'auto', got {hs!r}")
    if hs == "auto" or cs == "auto":
        from ...ops.histogram import _on_tpu_device
        from ... import tuning as _tuning
        # the auto-tuner's measured engine winner carries more signal
        # than the backend name: a box whose measured histogram winner is
        # the MXU-shaped pallas path wants the TPU-side tri-state
        # resolution (full-width passes, argsort compaction) even if the
        # platform string is a tunneled plugin — and vice versa. No
        # measurement -> today's backend-name rule, unchanged.
        hint = _tuning.growth_tristate_hint()
        tpu_like = (hint == "pallas") if hint else _on_tpu_device()
        if hs == "auto":
            hs = not tpu_like
        if cs == "auto":
            cs = "argsort" if tpu_like else "searchsorted"
        cfg = cfg._replace(hist_subtraction=bool(hs), compact_selector=cs)
    return cfg


# ---------------------------------------------------------------------------
# Deterministic blocked reduction (GrowConfig.hist_blocks): the canonical
# geometry that makes sharded training topology-independent. Every reduction
# that crosses rows — histograms, stat totals, leaf renewal — is computed per
# fixed row block, gathered into canonical block order, and folded
# left-to-right, so the f32 rounding sequence is a function of the BLOCK
# COUNT, never of how many devices happen to hold the blocks.
# Scope: the contract covers the TRAINING reductions (histograms, stat
# totals, quantization, leaf renewal). Validation METRIC combining stays a
# psum — early stopping driven by a valid set may therefore stop at a
# different round across topologies when a round's metric lands within one
# ulp of the best; fits without validation-driven stopping are
# bit-identical end to end (docs/performance.md "Sharded training").
# ---------------------------------------------------------------------------


def _hist_block_geometry(cfg: GrowConfig, axis_name, n: int):
    """(blocks_local, rows_per_block) for the blocked reduction; (0, n) on
    the plain psum path. Raises when a pinned block count cannot tile this
    shard (train_booster resolves these cases up front via
    placement.resolve_hist_blocks; direct growth callers fail loudly)."""
    hb = cfg.hist_blocks
    if hb == "auto" or not hb or (isinstance(hb, int) and hb <= 1):
        return 0, n
    if cfg.voting:
        raise ValueError(
            "hist_blocks does not compose with voting_parallel (the "
            "shard-local ballot is inherently topology-dependent)")
    axis_sz = _axis_size(axis_name) if axis_name is not None else 1
    if hb % axis_sz:
        raise ValueError(
            f"hist_blocks={hb} is not a multiple of the {axis_sz}-shard "
            "data axis")
    bl = hb // axis_sz
    if n % bl:
        raise ValueError(
            f"shard row count {n} does not tile into {bl} blocks "
            f"(hist_blocks={hb} over {axis_sz} shards)")
    return bl, n // bl


def _blocked_fold(parts: jnp.ndarray, axis_name):
    """Gather per-shard block partials into canonical order and fold them
    left-to-right. ``parts``: [blocks_local, ...] stacked partials; the
    explicit unrolled fold (not a reduce op) pins the f32 rounding order
    regardless of how XLA would lower an axis reduction."""
    if axis_name is not None:
        parts = lax.all_gather(parts, axis_name, axis=0, tiled=True)
    acc = parts[0]
    for j in range(1, parts.shape[0]):
        acc = acc + parts[j]
    return acc


def _positional_uniform(key, channels: int, n_local: int, axis_name):
    """[channels, n_local] uniforms derived from GLOBAL row indices.

    ``jax.random.uniform(key, shape)`` draws depend on position within the
    local shape, so a sharded run and a single-device run would round the
    same row differently. This hash (murmur3-style finalizers over the key
    words and the global row id) gives every global row the same draw on
    every topology — quality is ample for stochastic rounding."""
    kd = key
    try:
        kd = jax.random.key_data(key)
    except Exception:  # noqa: BLE001 — raw uint32 key arrays (default impl)
        pass
    kd = jnp.asarray(kd).astype(jnp.uint32).reshape(-1)
    k0, k1 = kd[0], kd[-1]
    idx = jnp.arange(n_local, dtype=jnp.uint32)
    if axis_name is not None:
        idx = idx + (jnp.uint32(n_local)
                     * lax.axis_index(axis_name).astype(jnp.uint32))
    ch = jnp.arange(channels, dtype=jnp.uint32)[:, None]
    x = (idx[None, :] ^ k0) + ch * jnp.uint32(0x9E3779B9)

    def _mix(v):
        v = (v ^ (v >> jnp.uint32(16))) * jnp.uint32(0x85EBCA6B)
        v = (v ^ (v >> jnp.uint32(13))) * jnp.uint32(0xC2B2AE35)
        return v ^ (v >> jnp.uint32(16))

    x = _mix(x ^ k1)
    x = _mix(x + jnp.uint32(0x27D4EB2F))
    return (x >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(
        1.0 / (1 << 24))


def _quantize_for(cfg: GrowConfig, base_t, qkey, axis_name, blocks_local,
                  rows_per_block):
    """int8 stat quantization, topology-aware. Blocked mode derives the
    scales from the GLOBAL amax (pmax is exact, so every shard count
    computes the same scale), bounds the int32 accumulator by the
    rows-per-block (the actual per-accumulation row count), and draws the
    stochastic-rounding bits from global row indices."""
    if not blocks_local:
        return quantize_stats(base_t, qkey)
    amax = jnp.max(jnp.abs(base_t), axis=1)
    if axis_name is not None:
        amax = lax.pmax(amax, axis_name)
    q_max = quant_q_max(rows_per_block)
    u = None if qkey is None else _positional_uniform(
        qkey, base_t.shape[0], base_t.shape[1], axis_name)
    return quantize_stats(base_t, qkey, amax=amax, q_max=q_max, u=u)


def _blocked_node_hist(binned_t, row_pos, base_t, W: int, B: int, qscales,
                       blocks_local: int, rows_per_block: int, axis_name):
    """[F, W*3, B] histogram via the canonical blocked reduction: one
    engine pass per fixed row block (identical shapes on every topology),
    gathered and folded in block order."""
    parts = jnp.stack([
        node_histogram(
            binned_t[:, j * rows_per_block:(j + 1) * rows_per_block],
            row_pos[j * rows_per_block:(j + 1) * rows_per_block],
            base_t[:, j * rows_per_block:(j + 1) * rows_per_block],
            W, B, scales=qscales)
        for j in range(blocks_local)])
    return _blocked_fold(parts, axis_name)


def _stat_totals(base_t, qscales, axis_name, blocks_local, rows_per_block):
    """[3] global grad/hess/count totals. Blocked mode folds per-block sums
    in canonical order; the plain path keeps the historical psum.

    Quantized per-BLOCK sums accumulate in int32 (bounded: _quantize_for
    caps q_max by rows_per_block, so a block sum stays under 2^31) and
    widen to f32 BEFORE the cross-block fold — folding raw int32 across
    all hist_blocks would wrap once q_max * total_rows crosses 2^31
    (~17M rows at q_max=127). The f32 fold is the same rounding class as
    the plain path's scale-before-psum order, and stays deterministic:
    identical values folded in identical order on every topology."""
    if blocks_local:
        def block_sum(j):
            seg = base_t[:, j * rows_per_block:(j + 1) * rows_per_block]
            if qscales is not None:
                return jnp.sum(seg.astype(jnp.int32),
                               axis=1).astype(jnp.float32)
            return jnp.sum(seg, axis=1)

        tot = _blocked_fold(
            jnp.stack([block_sum(j) for j in range(blocks_local)]),
            axis_name)
        if qscales is not None:
            tot = tot * qscales
        return tot
    if qscales is not None:
        tot = jnp.sum(base_t.astype(jnp.int32), axis=1) * qscales
    else:
        tot = jnp.sum(base_t, axis=1)
    if axis_name is not None:
        tot = lax.psum(tot, axis_name)
    return tot


def _soft_threshold(g, l1):
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def _feature_best_gains(hist, fm, cfg):
    """[F] best LOCAL split gain per feature from a local [F, 3, B]
    histogram (node totals taken from the local histogram itself) — the
    per-shard vote of voting_parallel."""
    B = hist.shape[-1]
    gl = jnp.cumsum(hist[:, 0, :], axis=-1)
    hl = jnp.cumsum(hist[:, 1, :], axis=-1)
    cl = jnp.cumsum(hist[:, 2, :], axis=-1)
    tg, th, tc = gl[:, -1:], hl[:, -1:], cl[:, -1:]
    gr, hr, cr = tg - gl, th - hl, tc - cl
    gain = (_leaf_objective(gl, hl, cfg) + _leaf_objective(gr, hr, cfg)
            - _leaf_objective(tg, th, cfg))
    ok = ((cl >= cfg.min_data_in_leaf) & (cr >= cfg.min_data_in_leaf)
          & (hl >= cfg.min_sum_hessian_in_leaf)
          & (hr >= cfg.min_sum_hessian_in_leaf) & fm[:, None])
    ok = ok.at[:, B - 1].set(False)
    return jnp.max(jnp.where(ok, gain, NEG_INF), axis=-1)


def _voting_select(h, feat_mask, cfg, axis_name, W):
    """voting_parallel feature selection (LightGBMParams.scala:13-27):
    each shard votes its top_k features by best local gain (max over the
    W frontier nodes), votes are psum'd, and only the global top-2k
    features' histograms are all-reduced — scattered back into a zeroed
    full array so downstream split search keeps static shapes.
    Returns (h_global, selected_mask)."""
    F, _, B = h.shape
    hw = h.reshape(F, W, 3, B).transpose(1, 0, 2, 3)          # [W, F, 3, B]
    g = jnp.max(jax.vmap(_feature_best_gains, in_axes=(0, None, None))(
        hw, feat_mask, cfg), axis=0)                           # [F]
    k = min(int(cfg.top_k), F)
    top_g, local_top = lax.top_k(g, k)
    # a shard with no locally-feasible split (all NEG_INF — common for
    # small nodes at deep levels) must not cast junk votes for the
    # arbitrary indices top_k returns
    ballots = (top_g > NEG_INF).astype(jnp.float32)
    votes = lax.psum(jnp.zeros(F).at[local_top].add(ballots), axis_name)
    # deterministic tie-break toward low feature index on every shard
    _, sel = lax.top_k(votes - jnp.arange(F) * 1e-6, min(2 * k, F))
    sel = jnp.sort(sel)
    hsel = lax.psum(h[sel], axis_name)
    hfull = jnp.zeros_like(h).at[sel].set(hsel)
    return hfull, jnp.zeros(F, dtype=bool).at[sel].set(True)


def _leaf_objective(g, h, cfg):
    sg = _soft_threshold(g, cfg.lambda_l1)
    return sg * sg / (h + cfg.lambda_l2 + 1e-38)


def bitset_words(num_bins: int) -> int:
    return -(-int(num_bins) // 32)


def _pack_bits(member: jnp.ndarray) -> jnp.ndarray:
    """[B] bool -> [ceil(B/32)] uint32 bitset."""
    B = member.shape[0]
    BW = bitset_words(B)
    m = jnp.pad(member.astype(jnp.uint32), (0, BW * 32 - B))
    m = m.reshape(BW, 32)
    return jnp.sum(m << jnp.arange(32, dtype=jnp.uint32)[None, :], axis=1,
                   dtype=jnp.uint32)


def bit_test(bits: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """bits: [..., BW] uint32; idx: [...] int — membership test, broadcast
    over leading dims."""
    word = jnp.take_along_axis(
        bits, (idx >> 5)[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return ((word >> (idx.astype(jnp.uint32) & 31)) & 1).astype(bool)


def _best_split(hist, tot_g, tot_h, tot_c, cfg: GrowConfig, feat_mask, allow,
                is_cat=None):
    """Best split of one node from its histogram — numeric or categorical.

    hist: [F, 3, B] (grad, hess, count per bin). Numeric features split
    "bin <= b" for b in [0, B-2]. Categorical features (``is_cat`` [F] bool)
    use LightGBM's sorted-subset search: bins ordered by smoothed ratio
    g/(h + cat_smooth), prefixes scanned as candidate left-subsets (capped at
    ``max_cat_threshold`` categories), the winner encoded as a bin bitset.
    Returns (gain, feat, bin, left_g, left_h, left_c, bits[BW] uint32) —
    ``bits`` is all-zero for a numeric winner.
    """
    B = hist.shape[-1]
    g, h, c = hist[:, 0, :], hist[:, 1, :], hist[:, 2, :]
    gl = jnp.cumsum(g, axis=-1)
    hl = jnp.cumsum(h, axis=-1)
    cl = jnp.cumsum(c, axis=-1)
    prefix_ok = jnp.ones((hist.shape[0], B), dtype=bool)
    rank = None
    if is_cat is not None:
        # categorical tables: cumsums in smoothed-ratio order; empty bins
        # sort last (+inf) so prefixes enumerate real categories first
        ratio = jnp.where(c > 0, g / (h + cfg.cat_smooth), jnp.inf)
        order = jnp.argsort(ratio, axis=-1)                     # [F, B]
        rank = jnp.zeros_like(order).at[
            jnp.arange(order.shape[0])[:, None], order].set(
            jnp.broadcast_to(jnp.arange(B), order.shape))
        gs = jnp.take_along_axis(g, order, axis=-1)
        hs = jnp.take_along_axis(h, order, axis=-1)
        cs = jnp.take_along_axis(c, order, axis=-1)
        glc = jnp.cumsum(gs, axis=-1)
        hlc = jnp.cumsum(hs, axis=-1)
        clc = jnp.cumsum(cs, axis=-1)
        icat = is_cat[:, None]
        gl = jnp.where(icat, glc, gl)
        hl = jnp.where(icat, hlc, hl)
        cl = jnp.where(icat, clc, cl)
        # prefix length b+1 capped (LightGBM max_cat_threshold)
        prefix_ok = jnp.where(
            icat, jnp.arange(B)[None, :] < int(cfg.max_cat_threshold),
            prefix_ok)
    gr, hr, cr = tot_g - gl, tot_h - hl, tot_c - cl
    gain = (_leaf_objective(gl, hl, cfg) + _leaf_objective(gr, hr, cfg)
            - _leaf_objective(tot_g, tot_h, cfg))
    ok = ((cl >= cfg.min_data_in_leaf) & (cr >= cfg.min_data_in_leaf)
          & (hl >= cfg.min_sum_hessian_in_leaf) & (hr >= cfg.min_sum_hessian_in_leaf)
          & feat_mask[:, None] & allow & prefix_ok)
    ok = ok.at[:, B - 1].set(False)  # last bin: empty right side
    gain = jnp.where(ok, gain, NEG_INF)
    flat = jnp.argmax(gain)
    f, b = flat // B, flat % B
    pick = lambda a: a[f, b]
    BW = bitset_words(B)
    if is_cat is None:
        bits = jnp.zeros(BW, dtype=jnp.uint32)
    else:
        member = is_cat[f] & (rank[f] <= b)                     # [B] bool
        bits = _pack_bits(member)
    return (gain[f, b], f.astype(jnp.int32), b.astype(jnp.int32),
            pick(gl), pick(hl), pick(cl), bits)


def _route_rows_to_children(binned_t, row_node, slots, do, feats, bins_,
                            bits_k, lid, is_cat):
    """Shared [W, n] row-routing for batched growth (leafwise rounds and
    depthwise levels): rows whose current node is a splitting candidate move
    to its left/right child slot (``lid``/``lid+1``). All routing is
    elementwise [W, n] + reduce (XLA fuses into one pass) — no per-row
    feature gathers.

    Returns (new_row_node, move [W, n], goleft_k [W, n]).
    """
    pos_oh = row_node[None, :] == slots[:, None]
    move = pos_oh & do[:, None]
    # widen narrow bin storage once into a [W, n] transient (W is small)
    rows = binned_t[feats].astype(jnp.int32)         # [W, n]
    goleft_k = rows <= bins_[:, None]
    if is_cat is not None:
        word = jnp.take_along_axis(bits_k, rows >> 5, axis=1)
        member = ((word >> (rows.astype(jnp.uint32) & 31)) & 1).astype(bool)
        goleft_k = jnp.where(is_cat[feats][:, None], member, goleft_k)
    in_any = jnp.any(move, axis=0)
    go_left_row = jnp.any(move & goleft_k, axis=0)
    lid_row = jnp.sum(jnp.where(move, lid[:, None], 0), axis=0)
    new_row_node = jnp.where(
        in_any, jnp.where(go_left_row, lid_row, lid_row + 1), row_node)
    return new_row_node, move, goleft_k


class Tree(NamedTuple):
    """Fixed-shape tree: node slot 0 is the root; unused slots are inert leaves."""
    feat: jnp.ndarray       # [M] int32 split feature (internal nodes)
    thr_bin: jnp.ndarray    # [M] int32 split bin ("go left if bin <= thr")
    left: jnp.ndarray       # [M] int32 child ids
    right: jnp.ndarray      # [M] int32
    is_leaf: jnp.ndarray    # [M] bool
    leaf_value: jnp.ndarray  # [M] f32 (shrinkage already applied)
    node_count: jnp.ndarray  # [] int32 — nodes actually allocated
    node_grad: jnp.ndarray  # [M] f32 sum of gradients in node (for importances)
    node_hess: jnp.ndarray  # [M] f32
    node_cnt: jnp.ndarray   # [M] f32
    split_gain: jnp.ndarray  # [M] f32 gain of the split at internal nodes
    node_value: jnp.ndarray  # [M] f32 expected value at every node (SHAP path)
    cat_bitset: jnp.ndarray  # [M, BW] uint32 left-subset bitset (categorical
    #                          splits; all-zero rows are numeric splits)


def _use_subtraction(cfg, axis_name, n: int) -> bool:
    """Single engagement rule for histogram subtraction, shared by both
    growth policies: single-device only (see the GrowConfig comment), not
    under voting, not under the deterministic blocked reduction (the
    compacted smaller-child pass has no canonical block tiling), and only
    worth the selector/gather overhead at real row counts (threshold
    provisional until TPU gather costs are measured)."""
    if cfg.hist_subtraction == "auto":
        raise ValueError(
            "hist_subtraction='auto' reached tree growth unresolved — "
            "callers must apply resolve_growth_backend(cfg) first")
    blocked = isinstance(cfg.hist_blocks, int) and cfg.hist_blocks > 1
    return (cfg.hist_subtraction and axis_name is None and not blocked
            and not cfg.voting and n >= 8192)


def _subtracted_pair_hists(binned_t, base_t, qscales, row_small,
                           small_is_left, parent_hists, K, B, h_buf, cfg):
    """Shared compaction+subtraction core for both growth policies.

    row_small: [n] in [-1, K) -- each row's pair index if it lies in that
    pair's SMALLER child, else -1. small_is_left: [K] bool. parent_hists:
    [K, F, 3, B]. Gathers the selected rows (caller guarantees their count
    is <= h_buf = n//2: pair row sets are disjoint and min(l, r) <= total/2),
    builds the K smaller-child histograms in one pass over the half-width
    buffer, derives each larger sibling as parent minus smaller (exact for
    the count channel; f32-rounding-level differences on grad/hess, as in
    LightGBM's own subtraction), and returns [2K, F, 3, B] interleaved as
    [l0, r0, l1, r1, ...]."""
    F = binned_t.shape[0]
    src, n_sel = _compact_select(row_small >= 0, h_buf, cfg.compact_selector)
    pos_h = jnp.where(jnp.arange(h_buf) < n_sel, row_small[src], -1)
    h_small = node_histogram(jnp.take(binned_t, src, axis=1), pos_h,
                             jnp.take(base_t, src, axis=1), K, B,
                             scales=qscales)           # [F, K*3, B]
    h_small = h_small.reshape(F, K, 3, B).transpose(1, 0, 2, 3)
    h_large = parent_hists - h_small
    sl = small_is_left[:, None, None, None]
    left_h = jnp.where(sl, h_small, h_large)
    right_h = jnp.where(sl, h_large, h_small)
    return jnp.stack([left_h, right_h], axis=1).reshape(2 * K, F, 3, B)


def grow_tree(binned_t: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
              valid: jnp.ndarray, feat_mask: jnp.ndarray, cfg: GrowConfig,
              axis_name: Optional[str] = None,
              is_cat: Optional[jnp.ndarray] = None, qkey=None):
    """Grow one tree on (possibly sharded) rows.

    binned_t: [F, n] int32/int16/uint8 (column-major); grad/hess: [n] f32; valid: [n] f32
    row mask (0 for padding / bagged-out rows); feat_mask: [F] bool
    (feature_fraction). With ``axis_name`` set (inside shard_map), histograms
    are psum'd so every shard takes identical split decisions —
    data_parallel GBDT semantics.
    """
    F, n = binned_t.shape
    L = int(cfg.num_leaves)
    M = 2 * L - 1
    B = int(cfg.num_bins)
    BW = bitset_words(B)

    vm = valid.astype(jnp.float32)
    base_t = jnp.stack([grad * vm, hess * vm, vm], axis=0)   # [3, n]
    bl, rpb = _hist_block_geometry(cfg, axis_name, n)
    qscales = None
    if cfg.quantized_grad:
        base_t, qscales = _quantize_for(cfg, base_t, qkey, axis_name, bl,
                                        rpb)

    def all_hist(row_pos, W):
        """Global per-node histogram [F, W*3, B] + selected-feature mask.

        data_parallel: one full [F, W*3, B] psum — or, under hist_blocks,
        the canonical blocked fold (topology-independent f32 order).
        voting_parallel: vote top_k locally, psum the votes, psum only the
        global top-2k features' histograms (scattered back into a zeroed
        full array so downstream split search keeps static shapes;
        unselected features are masked)."""
        if bl:
            return (_blocked_node_hist(binned_t, row_pos, base_t, W, B,
                                       qscales, bl, rpb, axis_name),
                    jnp.ones(F, dtype=bool))
        h = node_histogram(binned_t, row_pos, base_t, W, B, scales=qscales)
        if axis_name is None:
            return h, jnp.ones(F, dtype=bool)
        if not cfg.voting:
            return lax.psum(h, axis_name), jnp.ones(F, dtype=bool)
        return _voting_select(h, feat_mask, cfg, axis_name, W)

    # Leafwise histogram subtraction: every round's candidates already have
    # their own histograms cached in ``nhist`` (root from the root pass,
    # every later node from the round that created it), so each round can
    # stream ONLY the smaller child of each split (disjoint candidate row
    # sets bound the total at n//2) and derive the larger sibling by
    # subtraction. Same engagement rule as depthwise.
    use_sub = _use_subtraction(cfg, axis_name, n)
    h_buf = max(n // 2, 1)

    root_hist, sel0 = all_hist(jnp.zeros(n, dtype=jnp.int32), 1)
    # totals from the raw stats (not the histogram: under voting_parallel an
    # unselected feature's rows are zeroed there). Quantized mode totals the
    # DEQUANTIZED stats so node stats stay consistent with histogram sums.
    tot = _stat_totals(base_t, qscales, axis_name, bl, rpb)
    tot_g, tot_h, tot_c = tot[0], tot[1], tot[2]

    # cfg is static Python config: root may split unless max_depth == 0
    root_allow = jnp.bool_(cfg.max_depth < 0 or cfg.max_depth >= 1)
    g0, f0, b0, lg0, lh0, lc0, bits0 = _best_split(
        root_hist, tot_g, tot_h, tot_c, cfg, feat_mask & sel0, root_allow,
        is_cat)

    zi = jnp.zeros(M, dtype=jnp.int32)
    zf = jnp.zeros(M, dtype=jnp.float32)
    zbits = jnp.zeros((M, BW), dtype=jnp.uint32)
    state = dict(
        row_node=jnp.zeros(n, dtype=jnp.int32),
        feat=zi, thr=zi, left=zi, right=zi,
        is_leaf=jnp.ones(M, dtype=bool),
        depth=zi,
        ng=zf.at[0].set(tot_g), nh=zf.at[0].set(tot_h), nc=zf.at[0].set(tot_c),
        cg=jnp.full(M, NEG_INF).at[0].set(g0),
        cf=zi.at[0].set(f0), cb=zi.at[0].set(b0),
        clg=zf.at[0].set(lg0), clh=zf.at[0].set(lh0), clc=zf.at[0].set(lc0),
        cbits=zbits.at[0].set(bits0),
        tbits=zbits,
        gain=zf,
        num_nodes=jnp.int32(1),
    )
    if use_sub:
        # per-node histogram cache [M, F, 3, B] f32 = M*F*3*B*4 bytes —
        # ~5 MB at 31 leaves x 28 features x 255 bins, LINEAR IN F (a
        # 1000-feature fit holds ~190 MB of HBM for the whole tree):
        # the subtraction parent for every future candidate
        state["nhist"] = jnp.zeros((M, F, 3, B), jnp.float32).at[0].set(
            root_hist.reshape(F, 3, B))

    # Batched best-first: each round splits the top ``leaf_batch`` pending
    # leaves by cached gain in ONE fused histogram pass (their 2*KB children
    # ride the flat stat axis). Leaves' row sets are disjoint, so batched
    # splits are exactly the splits sequential best-first would take — the
    # only divergence is split ORDER near num_leaves exhaustion (see
    # GrowConfig.leaf_batch). KB=1 reproduces strict sequential growth.
    KB = max(1, min(int(cfg.leaf_batch), L - 1))
    W2 = 2 * KB
    vsplit = jax.vmap(_best_split, in_axes=(0, 0, 0, 0, None, None, 0, None))
    arange_kb = jnp.arange(KB, dtype=jnp.int32)

    def round_work(st):
        top_g, slots = lax.top_k(st["cg"], KB)       # gain-desc candidates
        leaves = (st["num_nodes"] + 1) // 2
        budget = jnp.int32(L) - leaves
        do = (top_g > cfg.min_gain_to_split) & (arange_kb < budget)
        n_split = jnp.sum(do.astype(jnp.int32))
        offset = jnp.cumsum(do.astype(jnp.int32)) - 1
        lid = st["num_nodes"] + 2 * offset           # [KB] child slot ids
        rid = lid + 1

        feats = st["cf"][slots]
        bins_ = st["cb"][slots]
        bits_k = st["cbits"][slots]                  # [KB, BW]

        new_row_node, move, goleft_k = _route_rows_to_children(
            binned_t, st["row_node"], slots, do, feats, bins_, bits_k, lid,
            is_cat)
        if use_sub:
            # stream only each candidate's SMALLER child (by raw routed row
            # count, which is what bounds the n//2 buffer); the larger
            # sibling derives from the cached candidate histogram
            rawL = jnp.sum(move & goleft_k, axis=1).astype(jnp.int32)
            rawA = jnp.sum(move, axis=1).astype(jnp.int32)
            small_is_left = rawL <= rawA - rawL               # ties -> left
            in_small = jnp.any(
                move & (goleft_k == small_is_left[:, None]), axis=0)
            spos = jnp.sum(jnp.where(move, arange_kb[:, None], 0), axis=0)
            row_small = jnp.where(in_small, spos, -1).astype(jnp.int32)
            hw = _subtracted_pair_hists(
                binned_t, base_t, qscales, row_small, small_is_left,
                st["nhist"][jnp.where(do, slots, 0)], KB, B, h_buf, cfg)
            sel = jnp.ones(F, dtype=bool)
        else:
            # child position in [0, 2*KB): 2i = left child of candidate i
            cpos = jnp.where(goleft_k, 2 * arange_kb[:, None],
                             2 * arange_kb[:, None] + 1)
            in_any = jnp.any(move, axis=0)
            child_pos = jnp.where(
                in_any, jnp.sum(jnp.where(move, cpos, 0), axis=0), -1
            ).astype(jnp.int32)

            h, sel = all_hist(child_pos, W2)         # [F, W2*3, B]
            hw = h.reshape(F, W2, 3, B).transpose(1, 0, 2, 3)  # [W2,F,3,B]

        # child totals: left from the candidate cache, right = parent - left
        lg = st["clg"][slots]
        lh = st["clh"][slots]
        lc = st["clc"][slots]
        tg = jnp.stack([lg, st["ng"][slots] - lg], 1).reshape(-1)   # [W2]
        th = jnp.stack([lh, st["nh"][slots] - lh], 1).reshape(-1)
        tc = jnp.stack([lc, st["nc"][slots] - lc], 1).reshape(-1)
        child_depth = st["depth"][slots] + 1         # [KB]
        can_split = jnp.where(cfg.max_depth < 0, True,
                              child_depth + 1 <= cfg.max_depth)
        allow2 = jnp.repeat(can_split & do, 2)
        g2, f2, b2, lg2, lh2, lc2, bits2 = vsplit(
            hw, tg, th, tc, cfg, feat_mask & sel, allow2, is_cat)

        new = dict(st)
        new["row_node"] = new_row_node

        # record splits; index M is out of bounds -> dropped for non-splits
        pslot = jnp.where(do, slots, M)
        cslot = jnp.where(jnp.repeat(do, 2),
                          jnp.stack([lid, rid], 1).reshape(-1), M)
        cdep2 = jnp.repeat(child_depth, 2)
        new["feat"] = st["feat"].at[pslot].set(feats, mode="drop")
        new["thr"] = st["thr"].at[pslot].set(bins_, mode="drop")
        new["left"] = st["left"].at[pslot].set(lid, mode="drop")
        new["right"] = st["right"].at[pslot].set(rid, mode="drop")
        new["is_leaf"] = st["is_leaf"].at[pslot].set(False, mode="drop")
        new["gain"] = st["gain"].at[pslot].set(top_g, mode="drop")
        new["tbits"] = st["tbits"].at[pslot].set(bits_k, mode="drop")
        new["depth"] = st["depth"].at[cslot].set(cdep2, mode="drop")
        new["ng"] = st["ng"].at[cslot].set(tg, mode="drop")
        new["nh"] = st["nh"].at[cslot].set(th, mode="drop")
        new["nc"] = st["nc"].at[cslot].set(tc, mode="drop")
        new["cg"] = (st["cg"].at[pslot].set(NEG_INF, mode="drop")
                     .at[cslot].set(g2, mode="drop"))
        new["cf"] = st["cf"].at[cslot].set(f2, mode="drop")
        new["cb"] = st["cb"].at[cslot].set(b2, mode="drop")
        new["clg"] = st["clg"].at[cslot].set(lg2, mode="drop")
        new["clh"] = st["clh"].at[cslot].set(lh2, mode="drop")
        new["clc"] = st["clc"].at[cslot].set(lc2, mode="drop")
        new["cbits"] = st["cbits"].at[cslot].set(bits2, mode="drop")
        new["num_nodes"] = st["num_nodes"] + 2 * n_split
        if use_sub:
            # cache the children's histograms: they are the subtraction
            # parents of every round that later splits them (cslot order is
            # [l0, r0, l1, r1, ...], matching hw's channel order)
            new["nhist"] = st["nhist"].at[cslot].set(hw, mode="drop")
        return new

    def round_body(_, st):
        # skip finished rounds (budget spent / no positive-gain candidate):
        # the static trip count below covers the worst case of one split per
        # round, so batched runs leave most rounds as this cheap no-op. The
        # predicate is identical on every shard (histograms are psum'd), so
        # the branch cannot diverge under shard_map.
        pred = ((st["num_nodes"] < jnp.int32(M))
                & (jnp.max(st["cg"]) > cfg.min_gain_to_split))
        return lax.cond(pred, round_work, lambda s: s, st)

    state = lax.fori_loop(0, L - 1, round_body, state)

    if cfg.quantized_grad and cfg.quant_renew_leaf:
        state = _renew_leaf_stats(state, grad, hess, vm, M, axis_name,
                                  bl, rpb)

    lr = jnp.float32(cfg.learning_rate)
    raw_val = -_soft_threshold(state["ng"], cfg.lambda_l1) / (
        state["nh"] + cfg.lambda_l2 + 1e-38)
    if cfg.max_delta_step > 0:
        raw_val = jnp.clip(raw_val, -cfg.max_delta_step, cfg.max_delta_step)
    leaf_value = jnp.where(state["is_leaf"] & (state["nc"] > 0), raw_val * lr, 0.0)
    node_value = jnp.where(state["nc"] > 0, raw_val * lr, 0.0)

    tree = Tree(
        feat=state["feat"], thr_bin=state["thr"], left=state["left"],
        right=state["right"], is_leaf=state["is_leaf"], leaf_value=leaf_value,
        node_count=state["num_nodes"], node_grad=state["ng"],
        node_hess=state["nh"], node_cnt=state["nc"], split_gain=state["gain"],
        node_value=node_value, cat_bitset=state["tbits"])
    # row_node is each row's final leaf: leaf_value[row_node] is this tree's
    # prediction for the training rows — no traversal needed during boosting.
    return tree, state["row_node"]


def _renew_leaf_stats(state, grad, hess, vm, M: int, axis_name,
                      blocks_local: int = 0, rows_per_block: int = 0):
    """Full-precision leaf-stat renewal for quantized training (LightGBM
    quant_train_renew_leaf): leaf grad/hess/count sums recomputed from the
    original f32 stats by one segment-sum over the final row->leaf map, so
    leaf VALUES carry no int8 quantization error while split structure keeps
    the 2x-rate int8 histogram path. Internal-node stats stay as recorded
    (structural metadata only). Under hist_blocks the segment-sums run per
    canonical block and fold in block order, like every other row
    reduction."""
    seg = state["row_node"]
    stats = jnp.stack([grad * vm, hess * vm, vm])            # [3, n]
    if blocks_local:
        parts = jnp.stack([
            jnp.zeros((3, M), jnp.float32).at[
                :, seg[j * rows_per_block:(j + 1) * rows_per_block]].add(
                stats[:, j * rows_per_block:(j + 1) * rows_per_block])
            for j in range(blocks_local)])
        renew = _blocked_fold(parts, axis_name)
    else:
        renew = jnp.zeros((3, M), jnp.float32).at[:, seg].add(stats)
        if axis_name is not None:
            renew = lax.psum(renew, axis_name)
    for i, k in enumerate(("ng", "nh", "nc")):
        state[k] = jnp.where(state["is_leaf"], renew[i], state[k])
    return state


def _compact_select(sel: jnp.ndarray, h_buf: int, mode: str = "argsort"):
    """Indices of the selected rows, compacted to the front of an ``h_buf``
    buffer (stable order). Returns (src [h_buf] int32, n_sel int32 scalar);
    entries past n_sel point at unselected rows and must be masked by the
    caller (via the gathered per-row positions, not by index value).

    ``mode`` (GrowConfig.compact_selector) picks the formulation:
    - "argsort" (default): stable argsort of the not-selected key — one
      [n] sort.
    - "searchsorted": cumsum + vectorized binary search for the k-th
      selected row — 20 rounds of [h_buf] gathers, no sort.
    Both are measured through the TPU relay before a default is locked in;
    they are bit-identical in output for valid (j < n_sel) entries.
    """
    if mode not in ("argsort", "searchsorted"):
        raise ValueError(
            f"compact_selector must be 'argsort' or 'searchsorted', got "
            f"{mode!r}")
    n = sel.shape[0]
    n_sel = jnp.sum(sel.astype(jnp.int32))
    if mode == "searchsorted":
        c = jnp.cumsum(sel.astype(jnp.int32))
        src = jnp.searchsorted(c, jnp.arange(1, h_buf + 1, dtype=jnp.int32),
                               side="left")
        src = jnp.minimum(src, n - 1).astype(jnp.int32)
    else:
        key = jnp.where(sel, jnp.int8(0), jnp.int8(1))
        src = jnp.argsort(key, stable=True)[:h_buf].astype(jnp.int32)
    return src, n_sel


def grow_tree_depthwise(binned_t: jnp.ndarray, grad: jnp.ndarray,
                        hess: jnp.ndarray, valid: jnp.ndarray,
                        feat_mask: jnp.ndarray, cfg: GrowConfig,
                        axis_name: Optional[str] = None,
                        is_cat: Optional[jnp.ndarray] = None, qkey=None):
    """Level-synchronous growth: one histogram pass per level.

    Every node on the level frontier contributes 3 stat channels
    (grad/hess/count x node one-hot), so a single MXU histogram pass covers
    the whole level — the measured histogram cost is flat in the stat axis,
    making a 31-leaf tree ~6 passes instead of the 30 sequential passes of
    best-first growth. The ``num_leaves`` budget is respected by ranking the
    level's candidate splits by gain. Same Tree layout / slot allocation
    discipline as ``grow_tree`` (slot ids in allocation order).
    """
    F, n = binned_t.shape
    L = int(cfg.num_leaves)
    M = 2 * L - 1
    B = int(cfg.num_bins)
    BW = bitset_words(B)
    # Without an explicit max_depth, allow two levels of slack beyond the
    # balanced depth so moderately skewed trees can still spend the leaf
    # budget (extreme skew is leafwise's domain — a perfectly unbalanced
    # chain would need num_leaves-1 levels and defeat the batching).
    depth_cap = (cfg.max_depth if cfg.max_depth > 0
                 else min(L - 1, (L - 1).bit_length() + 2))

    vm = valid.astype(jnp.float32)
    base_t = jnp.stack([grad * vm, hess * vm, vm], axis=0)   # [3, n]
    bl, rpb = _hist_block_geometry(cfg, axis_name, n)
    qscales = None
    if cfg.quantized_grad:
        base_t, qscales = _quantize_for(cfg, base_t, qkey, axis_name, bl,
                                        rpb)
    zi = jnp.zeros(M, dtype=jnp.int32)
    zf = jnp.zeros(M, dtype=jnp.float32)
    tree_arrays = dict(
        feat=zi, thr=zi, left=zi, right=zi,
        is_leaf=jnp.ones(M, dtype=bool), gain=zf,
        ng=zf, nh=zf, nc=zf,
        bits=jnp.zeros((M, BW), dtype=jnp.uint32))

    row_node = jnp.zeros(n, dtype=jnp.int32)
    num_nodes = jnp.int32(1)
    leaves = jnp.int32(1)

    # root totals (dequantized sums: consistent with histogram sums)
    tot0 = _stat_totals(base_t, qscales, axis_name, bl, rpb)
    tree_arrays["ng"] = tree_arrays["ng"].at[0].set(tot0[0])
    tree_arrays["nh"] = tree_arrays["nh"].at[0].set(tot0[1])
    tree_arrays["nc"] = tree_arrays["nc"].at[0].set(tot0[2])

    # frontier: node slot ids at the current level (-1 = inactive slot)
    frontier = jnp.full(L, -1, dtype=jnp.int32).at[0].set(0)

    vsplit = jax.vmap(_best_split, in_axes=(0, 0, 0, 0, None, None, 0, None))

    use_sub = _use_subtraction(cfg, axis_name, n)
    h_buf = max(n // 2, 1)

    def _zero_aux(depth: int):
        """(h_prev, pair_parent, child_raw) zeros shaped for level ``depth``:
        the previous level's assembled histograms [W_prev, F, 3, B], each
        sibling pair's parent position in the previous frontier [W//2], and
        raw per-child row counts [W] (raw = including invalid rows — that is
        what bounds the compaction buffer)."""
        Wp = min(2 ** max(depth - 1, 0), L)
        W = min(2 ** depth, L)
        return (jnp.zeros((Wp, F, 3, B), jnp.float32),
                jnp.full((W // 2,), -1, dtype=jnp.int32),
                jnp.zeros((W,), dtype=jnp.int32))

    def _sub_level_hist(aux, frontier, row_node, W):
        """[W, F, 3, B] level histograms via smaller-child compaction.

        Gathers the rows of each pair's smaller child (by raw count; at most
        n//2 rows in total since the pairs' row sets are disjoint) into the
        half-width buffer, builds only those W//2 histograms, and derives
        each larger sibling as parent minus smaller (exact for the count
        channel; f32-rounding-level differences on grad/hess, as in
        LightGBM's own subtraction)."""
        h_prev, pair_parent, child_raw = aux
        Wh = W // 2
        pair_active = pair_parent >= 0
        left_raw = child_raw[0::2][:Wh]
        right_raw = child_raw[1::2][:Wh]
        small_off = (right_raw < left_raw).astype(jnp.int32)  # ties -> left
        small_pos = 2 * jnp.arange(Wh, dtype=jnp.int32) + small_off
        small_slot = frontier[small_pos]
        slot_to_small = jnp.full(M, -1, dtype=jnp.int32)
        slot_to_small = slot_to_small.at[
            jnp.where(pair_active & (small_slot >= 0), small_slot, M)
        ].set(jnp.arange(Wh, dtype=jnp.int32), mode="drop")
        row_small = slot_to_small[row_node]            # [n] in [-1, Wh)
        hw = _subtracted_pair_hists(
            binned_t, base_t, qscales, row_small, small_off == 0,
            h_prev[jnp.maximum(pair_parent, 0)], Wh, B, h_buf, cfg)
        if 2 * Wh != W:
            # odd frontier width: the last slot never holds a child (children
            # arrive in pairs), so its channel is inert zero padding
            hw = jnp.pad(hw, ((0, W - 2 * Wh), (0, 0), (0, 0), (0, 0)))
        return hw

    def make_level(depth: int, W: int):
        def level_work(state):
            row_node, frontier, num_nodes, leaves, tree_arrays = state[:5]
            fr = frontier[:W]
            active = fr >= 0

            if use_sub and depth >= 1:
                h = _sub_level_hist(state[5], frontier, row_node, W)
                feat_mask_lvl = feat_mask
            else:
                # per-row frontier position (rows at finished leaves get -1);
                # index M is out of bounds -> dropped for inactive slots
                slot_to_pos = jnp.full(M, -1, dtype=jnp.int32)
                slot_to_pos = slot_to_pos.at[jnp.where(active, fr, M)].set(
                    jnp.arange(W, dtype=jnp.int32), mode="drop")
                row_pos = slot_to_pos[row_node]      # [n] in [-1, W)

                # one fused histogram pass covers the whole level: the
                # row->position one-hot and masked stats are built in VMEM
                feat_mask_lvl = feat_mask
                if bl:
                    # canonical blocked fold: topology-independent f32 order
                    h = _blocked_node_hist(binned_t, row_pos, base_t, W, B,
                                           qscales, bl, rpb, axis_name)
                else:
                    h = node_histogram(binned_t, row_pos, base_t, W, B,
                                       scales=qscales)         # [F, W*3, B]
                    if axis_name is not None:
                        if cfg.voting:
                            # per-level voting: shards vote top_k features
                            # by their best local gain across the WHOLE
                            # frontier, then only the global top-2k
                            # features' level histograms cross the
                            # interconnect
                            h, sel = _voting_select(h, feat_mask, cfg,
                                                    axis_name, W)
                            feat_mask_lvl = feat_mask & sel
                        else:
                            h = lax.psum(h, axis_name)
                h = h.reshape(F, W, 3, B).transpose(1, 0, 2, 3)  # [W,F,3,B]

            tot = jnp.stack([tree_arrays["ng"][jnp.maximum(fr, 0)],
                             tree_arrays["nh"][jnp.maximum(fr, 0)],
                             tree_arrays["nc"][jnp.maximum(fr, 0)]],
                            axis=1)                                    # [W, 3]

            allow = active & jnp.bool_(cfg.max_depth < 0
                                       or depth + 1 <= cfg.max_depth)
            gains, feats, bins_, lgs, lhs, lcs, bits_w = vsplit(
                h, tot[:, 0], tot[:, 1], tot[:, 2], cfg, feat_mask_lvl,
                allow, is_cat)
            gains = jnp.where(active, gains, NEG_INF)

            # budget: leaves + #splits <= num_leaves — best gains first
            order = jnp.argsort(-gains)
            rank = jnp.zeros(W, jnp.int32).at[order].set(
                jnp.arange(W, dtype=jnp.int32))
            budget = jnp.int32(L) - leaves
            do = (gains > cfg.min_gain_to_split) & (rank < budget) & active

            # allocate child slots in frontier order among split nodes
            offset = jnp.cumsum(do.astype(jnp.int32)) - 1
            lid = num_nodes + 2 * offset
            rid = lid + 1
            n_split = jnp.sum(do.astype(jnp.int32))

            # update rows: rows in split nodes move to their child slot
            # (keyed on node slot ids — inactive frontier slots are -1 and
            # match no row since row_node >= 0)
            row_node, move, goleft_k = _route_rows_to_children(
                binned_t, row_node, jnp.where(active, fr, -1), do, feats,
                bins_, bits_w, lid, is_cat)

            # record splits into tree arrays; index M (out of bounds) drops
            # the scatter for nodes that don't split
            slot = jnp.where(do, fr, M)
            ta = dict(tree_arrays)
            ta["feat"] = ta["feat"].at[slot].set(feats, mode="drop")
            ta["thr"] = ta["thr"].at[slot].set(bins_, mode="drop")
            ta["left"] = ta["left"].at[slot].set(lid, mode="drop")
            ta["right"] = ta["right"].at[slot].set(rid, mode="drop")
            ta["is_leaf"] = ta["is_leaf"].at[slot].set(False, mode="drop")
            ta["gain"] = ta["gain"].at[slot].set(gains, mode="drop")
            ta["bits"] = ta["bits"].at[slot].set(bits_w, mode="drop")
            # children stats
            parent_g, parent_h, parent_c = tot[:, 0], tot[:, 1], tot[:, 2]
            lslot = jnp.where(do, lid, M)
            rslot = jnp.where(do, rid, M)
            ta["ng"] = ta["ng"].at[lslot].set(lgs, mode="drop")
            ta["ng"] = ta["ng"].at[rslot].set(parent_g - lgs, mode="drop")
            ta["nh"] = ta["nh"].at[lslot].set(lhs, mode="drop")
            ta["nh"] = ta["nh"].at[rslot].set(parent_h - lhs, mode="drop")
            ta["nc"] = ta["nc"].at[lslot].set(lcs, mode="drop")
            ta["nc"] = ta["nc"].at[rslot].set(parent_c - lcs, mode="drop")

            # next frontier: the children, compacted into 2*W slots
            W_next = min(2 * W, L)
            child_slots = jnp.stack([jnp.where(do, lid, -1),
                                     jnp.where(do, rid, -1)],
                                    axis=1).reshape(-1)
            # compact actives to the front (stable) and pad with -1
            key = jnp.where(child_slots >= 0, 0, 1)
            perm = jnp.argsort(key, stable=True)
            compacted = child_slots[perm]
            frontier = jnp.full(L, -1, dtype=jnp.int32).at[:W_next].set(
                compacted[:W_next])

            out = (row_node, frontier, num_nodes + 2 * n_split,
                   leaves + n_split, ta)
            if use_sub:
                # aux for the next level, packed with the SAME stable perm as
                # the child slots so pairs stay adjacent: raw per-child row
                # counts (from the routing masks — includes invalid rows,
                # which is what bounds the compaction buffer) and each pair's
                # parent position in THIS frontier. h_prev = this level's
                # assembled histograms.
                rawL = jnp.sum(move & goleft_k, axis=1).astype(jnp.int32)
                rawA = jnp.sum(move, axis=1).astype(jnp.int32)
                raw2 = jnp.stack([rawL, rawA - rawL], axis=1).reshape(-1)
                pp2 = jnp.repeat(
                    jnp.where(do, jnp.arange(W, dtype=jnp.int32), -1), 2)
                raw_next = raw2[perm][:W_next]
                pp_next = pp2[perm][:2 * (W_next // 2)][0::2]
                out = out + ((h, pp_next, raw_next),)
            return out

        return level_work

    state = (row_node, frontier, num_nodes, leaves, tree_arrays)
    if use_sub:
        state = state + (_zero_aux(0),)
    for depth in range(depth_cap):           # static unroll: W varies by level
        W = min(2 ** depth, L)
        # runtime skip: once the budget is spent or the frontier is empty,
        # the remaining (slack) levels cost nothing
        pred = (state[3] < jnp.int32(L)) & jnp.any(state[1] >= 0)
        if use_sub:
            # the skip branch must still produce next-level aux shapes (its
            # content is never read once the tree is finished)
            def _skip(s, _d=depth):
                return s[:5] + (_zero_aux(_d + 1),)
        else:
            def _skip(s):
                return s
        state = lax.cond(pred, make_level(depth, W), _skip, state)
    row_node, frontier, num_nodes, leaves, tree_arrays = state[:5]

    if cfg.quantized_grad and cfg.quant_renew_leaf:
        tree_arrays = _renew_leaf_stats(
            dict(tree_arrays, row_node=row_node), grad, hess, vm, M,
            axis_name, bl, rpb)

    lr = jnp.float32(cfg.learning_rate)
    raw_val = -_soft_threshold(tree_arrays["ng"], cfg.lambda_l1) / (
        tree_arrays["nh"] + cfg.lambda_l2 + 1e-38)
    if cfg.max_delta_step > 0:
        raw_val = jnp.clip(raw_val, -cfg.max_delta_step, cfg.max_delta_step)
    leaf_value = jnp.where(tree_arrays["is_leaf"] & (tree_arrays["nc"] > 0),
                           raw_val * lr, 0.0)
    node_value = jnp.where(tree_arrays["nc"] > 0, raw_val * lr, 0.0)

    tree = Tree(
        feat=tree_arrays["feat"], thr_bin=tree_arrays["thr"],
        left=tree_arrays["left"], right=tree_arrays["right"],
        is_leaf=tree_arrays["is_leaf"], leaf_value=leaf_value,
        node_count=num_nodes, node_grad=tree_arrays["ng"],
        node_hess=tree_arrays["nh"], node_cnt=tree_arrays["nc"],
        split_gain=tree_arrays["gain"], node_value=node_value,
        cat_bitset=tree_arrays["bits"])
    return tree, row_node


def predict_tree_binned(tree: Tree, binned: jnp.ndarray, depth_cap: int,
                        is_cat: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Evaluate one tree on binned rows: [n, F] -> [n] leaf values."""
    n = binned.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)

    def body(_, node):
        f = tree.feat[node]
        t = tree.thr_bin[node]
        x = jnp.take_along_axis(binned, f[:, None], axis=1)[:, 0]
        go_left = x <= t
        if is_cat is not None:
            go_left = jnp.where(is_cat[f],
                                bit_test(tree.cat_bitset[node], x), go_left)
        nxt = jnp.where(go_left, tree.left[node], tree.right[node])
        return jnp.where(tree.is_leaf[node], node, nxt)

    node = lax.fori_loop(0, depth_cap, body, node)
    return tree.leaf_value[node]


def raw_to_cat_bin(x: jnp.ndarray, max_bin_idx: int) -> jnp.ndarray:
    """Raw categorical value -> bin id: round-to-nearest, NaN/negative -> 0
    (matching the binner's 0.5-boundary categorical bins)."""
    b = jnp.where(jnp.isnan(x), 0.0, jnp.floor(x + 0.5))
    return jnp.clip(b, 0, max_bin_idx).astype(jnp.int32)


def cat_member(bits_rows: jnp.ndarray, x: jnp.ndarray, max_bin_idx: int,
               strict: bool) -> jnp.ndarray:
    """Categorical membership for raw values.

    ``strict=False`` (models trained HERE): ids bin exactly as training did
    — NaN/negative -> bin 0, out-of-range clips into the catch-all bin.
    ``strict=True`` (models imported from stock LightGBM, which has no
    catch-all): FindInBitset semantics — NaN or any id outside the bitset
    routes right (non-member).
    """
    if not strict:
        return bit_test(bits_rows, raw_to_cat_bin(x, max_bin_idx))
    b = jnp.where(jnp.isnan(x), -1.0, jnp.floor(x + 0.5))
    in_range = (b >= 0) & (b <= max_bin_idx)
    cbin = jnp.clip(b, 0, max_bin_idx).astype(jnp.int32)
    return bit_test(bits_rows, cbin) & in_range


def predict_forest_raw(trees, thr_raw, features: jnp.ndarray,
                       depth_cap: int,
                       is_cat: Optional[jnp.ndarray] = None,
                       cat_max_bin: int = 0,
                       missing_dec: Optional[jnp.ndarray] = None
                       ) -> jnp.ndarray:
    """Evaluate a stacked forest on RAW float features.

    trees: Tree of arrays stacked on a leading [T] axis; thr_raw: [T, M] f32 raw
    thresholds ("go left if x <= thr", NaN goes left — matching the binning
    convention of NaN -> bin 0). Categorical features (``is_cat``) route by
    bitset membership of the rounded category id. features: [n, F].
    Returns [T, n].

    ``missing_dec`` ([T, M] per-node LightGBM decision_type bytes) switches
    numerical routing to stock LightGBM's NumericalDecision semantics
    (lightgbm tree.h): NaN maps to 0.0 unless the node's missing type is
    NaN; zero-as-missing and NaN-missing route to the stored default side;
    everything else compares ``x <= thr``. Needed for imported models —
    the framework's own training always writes decision_type 10
    (default-left, NaN missing), which equals the fast default path.
    """
    n = features.shape[0]

    def one_tree(tree_slice, thr, mdec):
        node = jnp.zeros(n, dtype=jnp.int32)
        # clip to the BINNER's last bin (the training-time catch-all), not
        # the bitset word boundary — out-of-range ids must route exactly as
        # they did during training. Imported stock-LightGBM models
        # (cat_max_bin == 0) have no catch-all: out-of-range routes right.
        strict = cat_max_bin <= 0
        max_bin_idx = (cat_max_bin - 1 if cat_max_bin > 0
                       else tree_slice.cat_bitset.shape[-1] * 32 - 1)

        def body(_, node):
            f = tree_slice.feat[node]
            t = thr[node]
            x = jnp.take_along_axis(features, f[:, None], axis=1)[:, 0]
            if mdec is None:
                go_left = ~(x > t)  # NaN compares false -> goes left
            else:
                md = mdec[node]
                mt = (md >> 2) & 3          # 0 none, 1 zero, 2 NaN
                dl = (md & 2) != 0          # default-left
                x_nan = jnp.isnan(x)
                xv = jnp.where(x_nan & (mt != 2), 0.0, x)
                # stock Tree::IsZero: |x| <= kZeroThreshold (1e-35), not
                # exact equality
                is_zero = jnp.abs(xv) <= jnp.float32(1e-35)
                use_default = (((mt == 1) & is_zero) | ((mt == 2) & x_nan))
                go_left = jnp.where(use_default, dl, ~(xv > t))
            if is_cat is not None:
                # int8 predict lane: features arrive as integer bin ids
                # (quantize.quantize_features); category routing widens to
                # f32 — bin id == category id under the binner's identity
                # bins, exact for ids < 256
                xc = (x.astype(jnp.float32)
                      if jnp.issubdtype(x.dtype, jnp.integer) else x)
                go_left = jnp.where(
                    is_cat[f],
                    cat_member(tree_slice.cat_bitset[node], xc, max_bin_idx,
                               strict),
                    go_left)
            nxt = jnp.where(go_left, tree_slice.left[node], tree_slice.right[node])
            return jnp.where(tree_slice.is_leaf[node], node, nxt)

        node = lax.fori_loop(0, depth_cap, body, node)
        return tree_slice.leaf_value[node]

    if missing_dec is None:
        return jax.vmap(lambda ts, th: one_tree(ts, th, None))(
            trees, thr_raw)
    return jax.vmap(one_tree)(trees, thr_raw, missing_dec)
