"""THE quantization funnel — every quantize/dequantize in the predict lane.

ROADMAP item 3: quantization used to stop at training (``quantized_grad``
int8 histogram stats); this module extends it through the serving path as
ONE place where scale math lives. The fused predictor, the async slot
table, and the ingest path all call through here — graftlint's
``quantize-funnel`` rule rejects inline ``* scale`` / bin-boundary
reimplementations anywhere else, so the three layers can never disagree
about what an int8 row means. (Training's int8 gradient quantization in
``growth.py`` is a separate, pre-existing funnel with different semantics
— per-round dynamic grad/hess scales — and stays where it is.)

The int8 lane's "feature scales" are the model's OWN bin boundaries:
a row quantizes to its per-feature bin ids (``#{upper_bounds < x}``,
NaN -> bin 0 — byte-identical to the training-time binning convention),
and a split threshold — always some feature's bin upper bound —
quantizes to its bin id under the SAME comparison. ``x > thr`` on raw
floats and ``q(x) > q(thr)`` on bin ids therefore route IDENTICALLY:
int8 traversal is bit-exact against f32, and the only accuracy delta of
the int8 lane is the per-tree int8 leaf quantization (symmetric,
amax/127). Bin ids live in ``[0, max_bin)`` so the staged dtype is
``uint8`` (the lane keyword stays ``int8`` = 8-bit integer staging).

The bf16 lane simply narrows thresholds and the feature batch to
bfloat16 (leaves stay f32) — half the h2d bytes, rounding-level routing
deltas, no binner required.

Resolution contract (the PR 4 rule): :func:`resolve_predict_dtype` is
called by ``Booster.predict_plan`` BEFORE the ``_PREDICT_CACHE`` key is
assembled — lint-anchored in ``tools/graftlint/checks/cachekey.py`` —
so a cache key never contains an unresolved "whatever the env said"
dtype, and capability degrades (no binner, imported missing-value
semantics) are decided in exactly one place.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Tuple

import jax.numpy as jnp
import ml_dtypes
import numpy as np

from ...observability import flight as _flight

__all__ = [
    "PREDICT_DTYPES", "PREDICT_DTYPE_ENV", "resolve_predict_dtype",
    "staging_dtype", "feature_bounds", "quantize_features",
    "quantize_thresholds", "quantize_leaves", "dequantize_leaves_device",
    "cast_features_bf16", "cast_thresholds_bf16", "row_quantizer",
]

PREDICT_DTYPES = ("f32", "bf16", "int8")
PREDICT_DTYPE_ENV = "MMLSPARK_TPU_PREDICT_DTYPE"

# numpy staging dtype per lane — what the slot table allocates and the
# predict hot path uploads
_STAGING = {"f32": np.dtype(np.float32),
            "bf16": np.dtype(ml_dtypes.bfloat16),
            "int8": np.dtype(np.uint8)}

# one degrade flight event per distinct (requested, effective, reason):
# resolve runs on every predict call, the ring must not fill with repeats
_SEEN_DEGRADES: set = set()
_SEEN_LOCK = threading.Lock()


def _degrade(requested: str, effective: str, reason: str) -> str:
    key = (requested, effective, reason)
    with _SEEN_LOCK:
        fresh = key not in _SEEN_DEGRADES
        if fresh:
            _SEEN_DEGRADES.add(key)
    if fresh:
        _flight.record("predict_dtype", requested=requested,
                       effective=effective, reason=reason)
    return effective


def resolve_predict_dtype(requested: Optional[str] = None, *,
                          has_mdec: bool = False,
                          max_bin: int = 0) -> str:
    """Resolve the predict lane's dtype to a concrete member of
    :data:`PREDICT_DTYPES` — THE one resolution point, called before the
    predictor cache key exists.

    ``requested=None`` reads ``MMLSPARK_TPU_PREDICT_DTYPE`` (default
    ``f32``); an unknown env value degrades to ``f32`` with a flight
    event (an operator hint must not kill scoring), an unknown explicit
    argument raises (caller bug). Capability degrades — both to ``f32``,
    each with a flight event:

    * ``has_mdec`` (imported stock-LightGBM missing-value semantics):
      the NumericalDecision branch needs real NaN/zero tests, so any
      narrow lane degrades.
    * ``int8`` needs the model's binner (``0 < max_bin <= 256``) — the
      bin boundaries ARE the quantization grid; imported models without
      one (or wide-binned models) have no int8 code for a feature.
    """
    if requested is None:
        env = os.environ.get(PREDICT_DTYPE_ENV, "") or "f32"
        if env not in PREDICT_DTYPES:
            return _degrade(env, "f32", "unknown_env_value")
        requested = env
    elif requested not in PREDICT_DTYPES:
        raise ValueError(
            f"predict_dtype must be one of {PREDICT_DTYPES}, "
            f"got {requested!r}")
    if requested == "f32":
        return "f32"
    if has_mdec:
        return _degrade(requested, "f32", "imported_missing_semantics")
    if requested == "int8" and not (0 < int(max_bin) <= 256):
        return _degrade(requested, "f32", "no_binner_grid")
    return requested


def staging_dtype(predict_dtype: str) -> np.dtype:
    """The numpy dtype a ``predict_dtype`` lane stages feature rows in
    (slot-table buffers, the predict h2d upload)."""
    return _STAGING[predict_dtype]


def feature_bounds(binner_state: dict) -> np.ndarray:
    """The model's quantization grid: ``[F, max_bin-1]`` f32 per-feature
    bin upper bounds (inf-padded), straight from the binner state."""
    return np.asarray(binner_state["upper_bounds"], np.float32)


def quantize_features(X: np.ndarray, upper_bounds: np.ndarray) -> np.ndarray:
    """Raw f32 rows -> uint8 bin ids under the model's bin boundaries.

    ``q = #{j : upper_bounds[f, j] < x}`` per feature — the same
    "NaN -> bin 0, beyond-last-bound -> catch-all" convention the
    training-time binner used, so quantized traversal routes exactly as
    training binned. Vectorized as one searchsorted per feature (bounds
    are sorted, inf padding never counts for finite x).
    """
    X = np.asarray(X, np.float32)
    ub = np.asarray(upper_bounds, np.float32)
    out = np.empty(X.shape, np.uint8)
    for f in range(X.shape[1]):
        col = X[:, f]
        q = np.searchsorted(ub[f], col, side="left")
        np.minimum(q, 255, out=q)
        out[:, f] = np.where(np.isnan(col), 0, q)
    return out


def quantize_thresholds(thr: np.ndarray, feat: np.ndarray,
                        upper_bounds: np.ndarray) -> np.ndarray:
    """Split thresholds -> uint8 bin ids under each node's feature grid.

    A learned threshold is always some bin's upper bound, and the count
    ``#{j : upper_bounds[feat, j] < thr}`` uses the SAME strict compare
    as :func:`quantize_features` — so ``x > thr  <=>  q(x) > q(thr)``
    holds for every finite x, tied boundaries included. Leaf/padding
    nodes carry arbitrary thresholds; their ids are never routing-live.
    """
    thr = np.asarray(thr, np.float32)
    feat = np.asarray(feat)
    ub = np.asarray(upper_bounds, np.float32)
    q = np.zeros(thr.shape, np.int64)
    for f in range(ub.shape[0]):
        sel = feat == f
        if sel.any():
            q[sel] = np.searchsorted(ub[f], thr[sel], side="left")
    # features out of the binner's range (defensive) keep id 0
    return np.minimum(q, 255).astype(np.uint8)


def quantize_leaves(leaf_value: np.ndarray,
                    num_class: int = 1) -> Tuple[np.ndarray, np.ndarray]:
    """Per-tree symmetric int8 leaf quantization.

    Returns ``(q, scale)``: ``q`` int8 ``[T, M]`` with
    ``leaf ~= q * scale[t]``, ``scale`` f32 ``[T]`` = per-tree
    ``amax(|leaf|) / 127`` (tiny-floored so all-zero trees stay exact).
    Per-tree, not global: late trees in a boosted ensemble carry leaves
    orders of magnitude smaller than tree 0's, and a global scale would
    flush them to zero.
    """
    lv = np.asarray(leaf_value, np.float32)
    amax = np.abs(lv).max(axis=1)
    scale = np.maximum(amax / 127.0, 1e-30).astype(np.float32)
    q = np.clip(np.rint(lv / scale[:, None]), -127, 127).astype(np.int8)
    return q, scale


def dequantize_leaves_device(qleaf, scale):
    """Device-side int8 leaf dequantization (the f32 epilogue's entry
    point): ``[T, M]`` f32 = ``q * scale[t]``."""
    return qleaf.astype(jnp.float32) * scale[:, None]


def cast_features_bf16(X: np.ndarray) -> np.ndarray:
    """Raw rows -> host bfloat16 (``ml_dtypes`` — already a jax
    dependency), halving the h2d bytes of the feature batch."""
    return np.asarray(X).astype(ml_dtypes.bfloat16)


def cast_thresholds_bf16(thr: np.ndarray) -> np.ndarray:
    """Thresholds -> host bfloat16 (uploaded once per tree bucket)."""
    return np.asarray(thr, np.float32).astype(ml_dtypes.bfloat16)


def row_quantizer(predict_dtype: str, upper_bounds: Optional[np.ndarray]):
    """The slot-table admission transform for one bound model: a
    callable mapping an f32 feature row (or row batch) to the lane's
    staged dtype, or ``None`` for the f32 lane (plain cast suffices).
    Created HERE so admission code holds an opaque callable and never
    touches scale math."""
    if predict_dtype == "int8":
        ub = np.asarray(upper_bounds, np.float32)

        def quantize_row(row):
            r = np.asarray(row, np.float32)
            q = quantize_features(r.reshape(1, -1) if r.ndim == 1 else r,
                                  ub)
            return q[0] if r.ndim == 1 else q

        return quantize_row
    if predict_dtype == "bf16":
        return cast_features_bf16
    return None
