"""LightGBM text model format: emit + parse for native-model interop.

The reference round-trips real LightGBM model strings through
``saveNativeModel``/``getNativeModel`` (reference:
lightgbm/LightGBMClassifier.scala:172-194, TrainUtils.scala:176-180
``LGBM_BoosterSaveModelToStringSWIG``, LightGBMBooster.scala:289) so saved
models interop with every LightGBM tool. This module implements that
contract for the TPU booster: ``to_lightgbm_string`` emits the ``tree``
v3 text format stock LightGBM loads, and ``parse_lightgbm_string`` loads
model strings produced by stock LightGBM (or by us).

Format notes (LightGBM C++ ``GBDT::SaveModelToString`` / ``Tree::ToString``):

* node numbering: internal nodes are ``0..num_leaves-2``; child pointers
  ``< 0`` encode leaves as ``-(leaf_index)-1``;
* ``decision_type`` bit 0 = categorical split, bit 1 = default-left for
  missing, bits 2-3 = missing type (0 none, 1 zero, 2 NaN);
* numerical decision is ``x <= threshold -> left`` (same as this repo);
* ``boost_from_average``'s init score is folded into the FIRST iteration's
  tree leaf values — the file carries no separate base score;
* multiclass interleaves ``num_tree_per_iteration`` trees per iteration
  (same it-major/class-minor order as the Booster's tree stack).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .growth import Tree

_KNOWN_MISSING_NAN = 2


def _objective_line(objective: str, num_class: int, kwargs: Dict) -> str:
    if objective == "binary":
        return "binary sigmoid:1"
    if objective == "multiclass":
        return f"multiclass num_class:{num_class}"
    if objective == "lambdarank":
        return "lambdarank"
    if objective == "quantile":
        return f"quantile alpha:{kwargs.get('alpha', 0.5)}"
    if objective == "huber":
        return f"huber alpha:{kwargs.get('alpha', 0.9)}"
    if objective == "tweedie":
        rho = kwargs.get("tweedie_variance_power", 1.5)
        return f"tweedie tweedie_variance_power:{rho}"
    if objective == "poisson":
        return "poisson"
    if objective in ("l1", "regression_l1", "mae"):
        return "regression_l1"
    return "regression"


def _parse_objective_line(line: str):
    parts = line.split()
    head = parts[0] if parts else "regression"
    kwargs: Dict = {}
    num_class = 1
    for tok in parts[1:]:
        if ":" in tok:
            k, v = tok.split(":", 1)
            if k == "num_class":
                num_class = int(v)
            elif k == "alpha":
                kwargs["alpha"] = float(v)
            elif k == "tweedie_variance_power":
                kwargs["tweedie_variance_power"] = float(v)
    if head in ("multiclassova", "ova", "ovr"):
        raise NotImplementedError(
            "one-vs-all multiclass models (multiclassova) are not supported: "
            "this booster applies softmax across classes, which would "
            "silently change the model's probabilities")
    if head == "binary":
        for tok in parts[1:]:
            if tok.startswith("sigmoid:") and float(tok.split(":")[1]) != 1.0:
                raise NotImplementedError(
                    f"binary models with sigmoid scale {tok.split(':')[1]} "
                    "!= 1 are not supported")
    name_map = {"binary": "binary", "multiclass": "multiclass",
                "lambdarank": "lambdarank", "rank_xendcg": "lambdarank",
                "regression_l1": "l1", "regression_l2": "regression",
                "regression": "regression", "quantile": "quantile",
                "huber": "huber", "poisson": "poisson", "tweedie": "tweedie",
                "mape": "regression", "fair": "regression"}
    return name_map.get(head, "regression"), num_class, kwargs


def _fmt(x: float) -> str:
    """LightGBM writes full-precision floats; repr round-trips doubles."""
    return repr(float(x))


def _tree_to_string(tree: Tree, thr_raw: np.ndarray, idx: int,
                    add_bias: float, shrinkage: float,
                    catchall_bin: int = -1,
                    missing_dec: Optional[np.ndarray] = None) -> str:
    """One ``Tree=i`` block from the fixed-shape slot arrays.

    Categorical splits emit LightGBM's bitset encoding: decision_type bit 0
    set, ``threshold`` holding the split's index into ``cat_boundaries``,
    and ``cat_threshold`` carrying the uint32 membership words
    (Tree::ToString / FindInBitset semantics: member -> left).

    Caveat: ids >= maxBin-1 share the binner's catch-all bin during
    training; in the exported format that bin's bit reads as exactly the
    single category maxBin-1, so exports are bit-exact only when every
    category id is < maxBin-1 (keep maxBin above the categorical
    cardinality — a warning fires otherwise)."""
    n_nodes = int(tree.node_count)
    is_leaf = np.asarray(tree.is_leaf)[:n_nodes]
    internal_slots = [s for s in range(n_nodes) if not is_leaf[s]]
    leaf_slots = [s for s in range(n_nodes) if is_leaf[s]]
    # a 1-slot tree is a single leaf; >1 slots have root at slot 0 internal
    num_leaves = max(len(leaf_slots), 1)
    int_index = {s: i for i, s in enumerate(internal_slots)}
    leaf_index = {s: i for i, s in enumerate(leaf_slots)}

    def child_ref(slot: int) -> int:
        return (int_index[slot] if slot in int_index
                else -leaf_index[slot] - 1)

    bits = np.asarray(tree.cat_bitset, np.uint32)
    cat_slots = [s for s in internal_slots if bits[s].any()]
    lines = [f"Tree={idx}", f"num_leaves={num_leaves}",
             f"num_cat={len(cat_slots)}"]
    lv = np.asarray(tree.leaf_value, np.float64)
    nv = np.asarray(tree.node_value, np.float64)
    nh = np.asarray(tree.node_hess, np.float64)
    nc = np.asarray(tree.node_cnt, np.float64)
    gain = np.asarray(tree.split_gain, np.float64)
    if internal_slots:
        feats = [int(np.asarray(tree.feat)[s]) for s in internal_slots]
        # decision_type: numerical splits are default-left w/ missing=NaN
        # (our binning sends NaN to bin 0, i.e. left); categorical splits
        # set bit 0 and route by bitset membership. Imported models carry
        # their original per-node bytes (missing_dec) — re-emission must
        # preserve their missing-value routing, not overwrite it.
        dt_num = 2 | (_KNOWN_MISSING_NAN << 2)
        dts, thrs = [], []
        cat_boundaries = [0]
        cat_words: List[int] = []
        cat_set = set(cat_slots)
        for s_ in internal_slots:
            if s_ in cat_set:
                dts.append(1)
                thrs.append(str(len(cat_boundaries) - 1))   # cat_idx
                words = [int(w) for w in bits[s_]]
                if (catchall_bin >= 0
                        and (words[catchall_bin >> 5]
                             >> (catchall_bin & 31)) & 1):
                    import warnings
                    warnings.warn(
                        "categorical split includes the catch-all bin "
                        f"({catchall_bin}): ids >= maxBin-1 shared that bin "
                        "in training, but stock LightGBM will read it as "
                        "the single category id; re-train with maxBin above "
                        "the categorical cardinality for a bit-exact export")
                # trim trailing zero words (LightGBM stores minimal width)
                while len(words) > 1 and words[-1] == 0:
                    words.pop()
                cat_words.extend(words)
                cat_boundaries.append(len(cat_words))
            else:
                dts.append(dt_num if missing_dec is None
                           else int(missing_dec[s_]))
                thrs.append(_fmt(thr_raw[s_]))
        lines += [
            "split_feature=" + " ".join(str(f) for f in feats),
            "split_gain=" + " ".join(_fmt(gain[s]) for s in internal_slots),
            "threshold=" + " ".join(thrs),
            "decision_type=" + " ".join(str(d) for d in dts),
            "left_child=" + " ".join(
                str(child_ref(int(np.asarray(tree.left)[s])))
                for s in internal_slots),
            "right_child=" + " ".join(
                str(child_ref(int(np.asarray(tree.right)[s])))
                for s in internal_slots),
        ]
        if cat_slots:
            lines += [
                "cat_boundaries=" + " ".join(str(b) for b in cat_boundaries),
                "cat_threshold=" + " ".join(str(w) for w in cat_words),
            ]
    lines += [
        "leaf_value=" + " ".join(_fmt(lv[s] + add_bias) for s in leaf_slots),
        "leaf_weight=" + " ".join(_fmt(nh[s]) for s in leaf_slots),
        "leaf_count=" + " ".join(str(int(nc[s])) for s in leaf_slots),
    ]
    if internal_slots:
        lines += [
            "internal_value=" + " ".join(
                _fmt(nv[s] + add_bias) for s in internal_slots),
            "internal_weight=" + " ".join(
                _fmt(nh[s]) for s in internal_slots),
            "internal_count=" + " ".join(
                str(int(nc[s])) for s in internal_slots),
        ]
    lines.append(f"shrinkage={_fmt(shrinkage)}")
    return "\n".join(lines)


def to_lightgbm_string(booster) -> str:
    """Emit the booster as a stock-LightGBM ``tree`` v3 model string."""
    trees = booster.trees
    T = booster.num_trees
    K = booster.num_class
    F = int(booster.binner_state["upper_bounds"].shape[0])
    ub = np.asarray(booster.binner_state["upper_bounds"], np.float64)
    # slotNames flow through as the emitted feature names (reference:
    # LightGBMParams slotNames); default to LightGBM's Column_<i>
    fnames = booster.binner_state.get("feature_names") or [
        f"Column_{i}" for i in range(F)]

    header = [
        "tree",
        "version=v3",
        f"num_class={K}",
        f"num_tree_per_iteration={K}",
        "label_index=0",
        f"max_feature_idx={F - 1}",
        "objective=" + _objective_line(booster.objective, K,
                                       booster.objective_kwargs),
        "feature_names=" + " ".join(fnames),
        # bin upper bounds give a usable [min:max] range per feature
        "feature_infos=" + " ".join(
            f"[{_fmt(ub[i, 0])}:{_fmt(ub[i, -2] if ub.shape[1] > 1 else ub[i, 0])}]"
            for i in range(F)),
    ]
    blocks = []
    for t in range(T):
        tree = Tree(*[np.asarray(a)[t] for a in trees])
        # base score folds into the first iteration's trees (LightGBM rule)
        bias = float(booster.base_score[t % K]) if t < K else 0.0
        mb = booster.binner_state.get("max_bin") or 0
        mdec = (None if getattr(booster, "missing_dec", None) is None
                else np.asarray(booster.missing_dec[t]))
        blocks.append(_tree_to_string(tree, np.asarray(booster.thr_raw[t]),
                                      t, bias, 1.0,
                                      catchall_bin=mb - 1 if mb else -1,
                                      missing_dec=mdec))
    importances = booster.feature_importances("split")
    imp_lines = [f"{fnames[i]}={int(importances[i])}"
                 for i in np.argsort(-importances) if importances[i] > 0]
    return ("\n".join(header) + "\n\n"
            + "\n\n\n".join(blocks) + "\n\n\n"
            + "end of trees\n\n"
            + "feature_importances:\n" + "\n".join(imp_lines) + "\n\n"
            + "parameters:\n"
            + f"[objective: {_objective_line(booster.objective, K, booster.objective_kwargs).split()[0]}]\n"
            + "end of parameters\n\n"
            + "pandas_categorical:null\n")


def _parse_block(block: str) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = {}
    for line in block.strip().splitlines():
        if "=" in line:
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip().split()
    return out


def parse_lightgbm_string(s: str):
    """Parse a LightGBM text model into Booster constructor pieces.

    Returns (trees: Tree stacked [T, M], thr_raw [T, M], num_class,
    objective, objective_kwargs, num_features, categorical_features,
    missing_dec). ``missing_dec`` is a [T, M] uint8 of per-node
    decision_type bytes when any split stores missing handling other than
    the framework's default-left/NaN encoding, else None (fast path).
    The parsed model predicts with base_score = 0: LightGBM folds any init
    score into tree leaves. Categorical splits (decision_type bit 0) load
    their cat_threshold bitsets; the features they split on are returned so
    the Booster routes them by category-id membership.
    """
    if not s.lstrip().startswith("tree"):
        raise ValueError("not a LightGBM text model (must start with 'tree')")
    body = s.split("end of trees")[0]
    parts = body.split("Tree=")
    header = _parse_block(parts[0])
    num_class = int(header.get("num_class", ["1"])[0])
    obj_line = " ".join(header.get("objective", ["regression"]))
    objective, num_class_obj, obj_kwargs = _parse_objective_line(obj_line)
    num_class = max(num_class, num_class_obj)
    F = int(header.get("max_feature_idx", ["0"])[0]) + 1

    tree_blocks = parts[1:]
    max_leaves = 1
    max_cat_words = 1
    for blk in tree_blocks:
        fields = _parse_block("x=" + blk)  # keep first line (index) harmless
        max_leaves = max(max_leaves, int(fields["num_leaves"][0]))
        bounds = [int(x) for x in fields.get("cat_boundaries", [])]
        for a, b in zip(bounds, bounds[1:]):
            max_cat_words = max(max_cat_words, b - a)
    M = 2 * max_leaves - 1
    BW = max_cat_words
    cat_features: set = set()

    def zeros_i():
        return np.zeros(M, np.int32)

    def zeros_f():
        return np.zeros(M, np.float32)

    stacked = {k: [] for k in Tree._fields}
    thr_all = []
    mdec_all = []
    # the framework's own emit: default-left + NaN missing (see _tree_lines
    # dt_num) — the fast `~(x > thr)` predictor implements exactly this
    _DT_NATIVE = 2 | (_KNOWN_MISSING_NAN << 2)
    exotic_missing = False
    for blk in tree_blocks:
        fields = _parse_block("idx=" + blk)
        nl = int(fields["num_leaves"][0])
        n_int = nl - 1
        feat, thr, left, right = zeros_i(), zeros_f(), zeros_i(), zeros_i()
        is_leaf = np.ones(M, bool)
        leaf_value, node_value = zeros_f(), zeros_f()
        node_hess, node_cnt, gain = zeros_f(), zeros_f(), zeros_f()
        mdec = np.full(M, _DT_NATIVE, np.uint8)
        cat_bits = np.zeros((M, BW), np.uint32)
        cat_boundaries = [int(x) for x in fields.get("cat_boundaries", [])]
        cat_words = [int(x) for x in fields.get("cat_threshold", [])]

        def slot(ref: int) -> int:
            # internal i -> slot i; leaf j -> slot n_int + j
            return ref if ref >= 0 else n_int - ref - 1

        lv = [float(x) for x in fields["leaf_value"]]
        lw = [float(x) for x in fields.get("leaf_weight", ["0"] * nl)]
        lc = [float(x) for x in fields.get("leaf_count", ["0"] * nl)]
        for j in range(nl):
            sj = n_int + j
            leaf_value[sj] = lv[j]
            node_value[sj] = lv[j]
            node_hess[sj] = lw[j] if j < len(lw) else 0.0
            node_cnt[sj] = lc[j] if j < len(lc) else 0.0
        if n_int > 0:
            sf = [int(x) for x in fields["split_feature"]]
            th = [float(x) for x in fields["threshold"]]
            dts = [int(float(x)) for x in fields["decision_type"]]
            lch = [int(x) for x in fields["left_child"]]
            rch = [int(x) for x in fields["right_child"]]
            iv = [float(x) for x in fields.get("internal_value",
                                               ["0"] * n_int)]
            iw = [float(x) for x in fields.get("internal_weight",
                                               ["0"] * n_int)]
            ic = [float(x) for x in fields.get("internal_count",
                                               ["0"] * n_int)]
            sg = [float(x) for x in fields.get("split_gain", ["0"] * n_int)]
            for i in range(n_int):
                if dts[i] & 1:
                    # categorical split: threshold holds the cat_idx into
                    # cat_boundaries; membership words -> our bitset rows
                    cat_idx = int(float(fields["threshold"][i]))
                    w0, w1 = cat_boundaries[cat_idx], cat_boundaries[cat_idx + 1]
                    words = cat_words[w0:w1][:BW]
                    cat_bits[i, :len(words)] = np.asarray(words, np.uint32)
                    is_leaf[i] = False
                    feat[i] = sf[i]
                    thr[i] = np.inf       # unused: routing is by bitset
                    left[i] = slot(lch[i])
                    right[i] = slot(rch[i])
                    node_value[i] = iv[i] if i < len(iv) else 0.0
                    node_hess[i] = iw[i] if i < len(iw) else 0.0
                    node_cnt[i] = ic[i] if i < len(ic) else 0.0
                    gain[i] = sg[i] if i < len(sg) else 0.0
                    cat_features.add(sf[i])
                    continue
                # Stock missing-value routing (NumericalDecision, lightgbm
                # tree.h): recorded per node; anything other than the
                # framework's own default-left/NaN-missing encoding flips
                # the predictor onto the decision_type-aware path.
                mdec[i] = dts[i] & 0xFF
                if (dts[i] & 0x0E) != _DT_NATIVE:
                    exotic_missing = True
                is_leaf[i] = False
                feat[i] = sf[i]
                thr[i] = th[i]
                left[i] = slot(lch[i])
                right[i] = slot(rch[i])
                node_value[i] = iv[i] if i < len(iv) else 0.0
                node_hess[i] = iw[i] if i < len(iw) else 0.0
                node_cnt[i] = ic[i] if i < len(ic) else 0.0
                gain[i] = sg[i] if i < len(sg) else 0.0
        stacked["feat"].append(feat)
        stacked["thr_bin"].append(zeros_i())
        stacked["left"].append(left)
        stacked["right"].append(right)
        stacked["is_leaf"].append(is_leaf)
        stacked["leaf_value"].append(leaf_value)
        stacked["node_count"].append(np.int32(2 * nl - 1))
        stacked["node_grad"].append(zeros_f())
        stacked["node_hess"].append(node_hess)
        stacked["node_cnt"].append(node_cnt)
        stacked["split_gain"].append(gain)
        stacked["node_value"].append(node_value)
        stacked["cat_bitset"].append(cat_bits)
        thr_leaf = np.where(is_leaf, np.float32(np.inf), thr)
        thr_all.append(thr_leaf.astype(np.float32))
        mdec_all.append(mdec)

    trees = Tree(**{k: np.stack(v) for k, v in stacked.items()})
    thr_raw = np.stack(thr_all)
    missing_dec = np.stack(mdec_all) if exotic_missing else None
    return (trees, thr_raw, num_class, objective, obj_kwargs, F,
            sorted(cat_features), missing_dec)
