"""Exact TreeSHAP feature contributions.

Parity target: the reference's ``featuresShapCol`` rides LightGBM's native
TreeSHAP (reference: lightgbm/LightGBMBooster.scala:250-269, which calls
``LGBM_BoosterPredictForMatSingle`` with ``predict_contrib``). TreeSHAP
computes the exact Shapley values of the tree's cover-conditional value
function v(S) = E[f(x) | x_S] in polynomial time (Lundberg, Erion & Lee
2018, "Consistent Individualized Feature Attribution for Tree Ensembles",
Algorithm 2) — unlike Saabas path attribution (``method="saabas"`` on
:meth:`Booster.predict_contrib`), which distributes credit only along the
instance's own path and diverges from Shapley on correlated features.

Formulation: the classic algorithm is per-instance recursion with scalar
path state. Here the recursion runs ONCE per tree over its (fixed, ~2L-1
node) topology, and every per-instance quantity — the "one fraction" (does
this instance follow the split?) and the path weights — is carried as an
``[n]`` / ``[L, n]`` numpy array, so the O(D^2) EXTEND/UNWIND updates are
vectorized over all rows at once. Per-path zero fractions (cover ratios)
stay scalars. Cost: O(nodes * depth^2) vector ops of length n per tree.

This runs on host: the recursion's data-dependent path bookkeeping (dynamic
path length, per-node feature-duplicate unwinding) fits numpy better than
fixed-shape XLA; the device path keeps the throughput-critical Saabas mode.
"""

from __future__ import annotations

import os

import numpy as np


def _extend(d, z, o, w, pz, po, pi):
    """EXTEND: append (pi, pz, po) to the path and update weights.

    d: [l] int features; z: [l] float zero fractions; o: [l, n] one
    fractions; w: [l, n] path weights. Returns extended copies (l+1).
    po is [n]; pz scalar.
    """
    l = len(d)
    n = w.shape[1] if l else len(po)
    d2 = np.append(d, pi)
    z2 = np.append(z, pz)
    o2 = np.concatenate([o, po[None, :]], axis=0) if l else po[None, :].copy()
    w2 = np.concatenate(
        [w, np.full((1, n), 1.0 if l == 0 else 0.0, dtype=np.float64)],
        axis=0)
    for i in range(l - 1, -1, -1):
        w2[i + 1] += po * w2[i] * (i + 1) / (l + 1)
        w2[i] = pz * w2[i] * (l - i) / (l + 1)
    return d2, z2, o2, w2


def _unwind(d, z, o, w, k):
    """UNWIND: remove path element k, inverting its EXTEND. Vectorized over
    instances: the o[k] == 0 / != 0 branches of the scalar algorithm are
    evaluated elementwise with np.where. Weights are positional (the scalar
    algorithm recomputes pweight[0..l-1] in place and shifts only d/z/o)."""
    l = len(d) - 1
    of = o[k]                                     # [n]
    zf = z[k]                                     # scalar
    n = w.shape[1]
    nz = of != 0
    safe_of = np.where(nz, of, 1.0)
    next_one = w[l].copy()
    new_w = np.empty((l, n), dtype=np.float64)
    for i in range(l - 1, -1, -1):
        tmp = w[i]
        wa = next_one * (l + 1) / ((i + 1) * safe_of)
        if zf != 0:
            wb = tmp * (l + 1) / (zf * (l - i))
        else:
            wb = np.zeros_like(tmp)
        new_w[i] = np.where(nz, wa, wb)
        next_one = tmp - new_w[i] * zf * (l - i) / (l + 1)
    return (np.delete(d, k), np.delete(z, k),
            np.delete(o, k, axis=0), new_w)


def _unwound_sum(d, z, o, w, k):
    """Sum of path weights after unwinding element k — the leaf-time
    per-feature weight of Algorithm 2, without materializing the unwound
    path. Returns [n]."""
    l = len(d) - 1
    of = o[k]
    zf = z[k]
    nz = of != 0
    safe_of = np.where(nz, of, 1.0)
    next_one = w[l].copy()
    total = np.zeros_like(next_one)
    for i in range(l - 1, -1, -1):
        tmp_a = next_one * (l + 1) / ((i + 1) * safe_of)
        if zf != 0:
            tmp_b = w[i] * (l + 1) / (zf * (l - i))
        else:
            tmp_b = np.zeros_like(tmp_a)
        t = np.where(nz, tmp_a, tmp_b)
        total += t
        next_one = w[i] - t * zf * (l - i) / (l + 1)
    return total


def tree_shap_single(feat, left, right, is_leaf, cover, values,
                     go_left, n_features):
    """Exact SHAP values for one tree, all instances at once.

    go_left: [M, n] bool — instance routing decision at every node (only
    internal nodes are read). cover: [M] float training row weight per node.
    values: [M] leaf values (shrinkage applied). Returns [n, F+1]; the last
    column is the tree's expected value E[f] (the SHAP base value).
    """
    n = go_left.shape[1]
    phi = np.zeros((n, n_features + 1), dtype=np.float64)

    # explicit-stack DFS: leafwise trees can be chain-shaped with depth
    # ~num_leaves, which would blow Python's recursion limit
    d0 = np.empty(0, dtype=np.int64)
    z0 = np.empty(0, dtype=np.float64)
    o0 = np.empty((0, n), dtype=np.float64)
    w0 = np.empty((0, n), dtype=np.float64)
    stack = [(0, d0, z0, o0, w0, 1.0, np.ones(n, dtype=np.float64), -1)]
    while stack:
        j, d, z, o, w, pz, po, pi = stack.pop()
        d, z, o, w = _extend(d, z, o, w, pz, po, pi)
        if is_leaf[j]:
            for i in range(1, len(d)):
                s = _unwound_sum(d, z, o, w, i)
                phi[:, d[i]] += s * (o[i] - z[i]) * float(values[j])
            continue
        f = int(feat[j])
        lo, hi = int(left[j]), int(right[j])
        iz, io = 1.0, np.ones(n, dtype=np.float64)
        # previous occurrence of this feature on the path is unwound and its
        # fractions fold into the incoming ones (paper's duplicate handling)
        for k in range(1, len(d)):
            if d[k] == f:
                iz, io = z[k], o[k].copy()
                d, z, o, w = _unwind(d, z, o, w, k)
                break
        cj = max(float(cover[j]), 1e-12)
        gl = go_left[j].astype(np.float64)
        stack.append((lo, d, z, o, w, float(cover[lo]) / cj * iz, io * gl,
                      f))
        stack.append((hi, d, z, o, w, float(cover[hi]) / cj * iz,
                      io * (1.0 - gl), f))

    phi[:, n_features] = _expected_value(is_leaf, cover, values)
    return phi


def shap_values(booster, X: np.ndarray) -> np.ndarray:
    """Exact TreeSHAP contributions for a fitted :class:`Booster`.

    Returns [n, (F+1) * num_class] matching the reference's predict_contrib
    layout: per class block, F per-feature Shapley values then the expected
    value (base score + sum of per-tree expectations).
    """
    import jax

    X = np.asarray(X, dtype=np.float32)
    n, F = X.shape
    K = booster.num_class
    # one bulk device->host conversion for all tree fields, not per tree
    trees = jax.tree_util.tree_map(np.asarray, booster.trees) \
        if _has_device_arrays(booster.trees) else booster.trees
    thr_raw = np.asarray(booster.thr_raw)
    feat_np = np.asarray(trees.feat)
    out = np.zeros((n, (F + 1) * K), dtype=np.float64)
    for k in range(K):
        out[:, k * (F + 1) + F] = booster.base_score[k]
    is_cat = booster._is_cat()
    is_cat_np = None if is_cat is None else np.asarray(is_cat)

    # TreeSHAP's value function conditions on training covers; a model
    # imported from a LightGBM text dump without the optional
    # leaf_count/internal_count fields has node_cnt == 0 everywhere and
    # would silently produce garbage (zero fractions all zero)
    root_covers = np.asarray(trees.node_cnt)[:, 0]
    if booster.num_trees and not np.all(root_covers > 0):
        raise ValueError(
            "exact TreeSHAP needs per-node training counts, but this "
            "booster has trees with zero root cover (typically a model "
            "imported from a LightGBM text dump without "
            "internal_count/leaf_count fields) — use "
            "predict_contrib(method='saabas') for cover-free attribution")

    # engine: the native C++ per-instance recursion (threaded; the same
    # role the reference's LGBM_BoosterPredictForMatSingle plays) unless
    # unavailable or disabled, else this module's vectorized numpy
    # recursion. Both consume the SAME go_left routing matrix, so split
    # semantics (thresholds, categoricals, NaN) have one definition.
    use_native = os.environ.get("MMLSPARK_TPU_SHAP_NATIVE") != "0"
    if use_native:
        from ...native import treeshap_tree
    for t in range(booster.num_trees):
        k = t % K
        feat = feat_np[t]
        thr = thr_raw[t]
        is_leaf = np.asarray(trees.is_leaf[t])
        # split-feature bounds are validated HERE, before engine dispatch:
        # the native walk rejects such trees (routing them to this
        # function), but the numpy engine would wrap feat=-1 to the
        # last phi column and write feat==F into the expected-value
        # column — silently corrupted attributions, not an error
        internal_feat = feat[~is_leaf.astype(bool)]
        if internal_feat.size and (internal_feat.min() < 0
                                   or internal_feat.max() >= F):
            raise ValueError(
                f"tree {t} has an internal node with split feature "
                f"outside [0, {F}) — malformed or truncated model")
        # routing decisions for every node at once: [M, n]
        xv = X[:, feat]                              # [n, M] gathered
        gl = (~(xv > thr[None, :])).T                # [M, n]; NaN -> left
        if is_cat_np is not None:
            gl = np.where(
                is_cat_np[feat][:, None],
                _cat_member_np(np.asarray(trees.cat_bitset[t]), xv.T,
                               booster._cat_max_idx(),
                               booster._cat_strict()),
                gl)
        cover = np.asarray(trees.node_cnt[t], dtype=np.float64)
        values = np.asarray(trees.leaf_value[t], dtype=np.float64)
        phi_f = None
        if use_native:
            phi_f = treeshap_tree(
                feat, np.asarray(trees.left[t]),
                np.asarray(trees.right[t]), is_leaf, cover, values, gl, F)
        if phi_f is not None:
            out[:, k * (F + 1):k * (F + 1) + F] += phi_f
            out[:, k * (F + 1) + F] += _expected_value(is_leaf, cover,
                                                       values)
        else:
            phi = tree_shap_single(
                feat, np.asarray(trees.left[t]),
                np.asarray(trees.right[t]), is_leaf, cover, values, gl, F)
            out[:, k * (F + 1):k * (F + 1) + F] += phi[:, :F]
            out[:, k * (F + 1) + F] += phi[:, F]
    return out


def _expected_value(is_leaf, cover, values) -> float:
    """Cover-weighted mean of leaf values — the tree's E[f], the base the
    contributions sum from (sum(phi) + E[f] == f(x))."""
    leaves = is_leaf & (cover > 0)
    tot = max(float(cover[leaves].sum()), 1e-12)
    return float((values[leaves] * cover[leaves]).sum() / tot)


def _has_device_arrays(trees) -> bool:
    return not isinstance(trees.feat, np.ndarray)


def _cat_member_np(bits, x, max_bin_idx, strict):
    """Numpy mirror of growth.cat_member, broadcast as [M, n] without
    materializing a per-instance bitset copy. bits: [M, BW]; x: [M, n] raw
    values gathered per node."""
    if strict:
        b = np.where(np.isnan(x), -1.0, np.floor(x + 0.5))
        in_range = (b >= 0) & (b <= max_bin_idx)
    else:
        b = np.where(np.isnan(x) | (x < 0), 0.0, np.floor(x + 0.5))
        in_range = np.ones(x.shape, dtype=bool)
    cbin = np.clip(b, 0, max_bin_idx).astype(np.int64)
    word = np.take_along_axis(bits, cbin >> 5, axis=1)   # [M, n]
    return (((word >> (cbin & 31)) & 1).astype(bool)) & in_range
