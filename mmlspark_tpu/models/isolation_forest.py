"""Isolation Forest anomaly detection, scored on the device mesh.

Re-design of the reference's thin wrapper over LinkedIn's isolation-forest
(reference: isolationforest/IsolationForest.scala:15-58) as a native
implementation: isolation trees are random feature/threshold splits, so tree
*construction* is trivial host work on small subsamples, while *scoring* —
the per-row expected path length over hundreds of trees — is the hot path and
runs as one vmapped fixed-shape traversal on device (same static-tree
formulation as the GBDT forest).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dataset import Dataset
from ..core.params import HasFeaturesCol, HasPredictionCol, Param, TypeConverters
from ..core.pipeline import Estimator, Model


def _avg_path_length(n) -> float:
    """c(n): average unsuccessful-search path length in a BST of n nodes."""
    n = np.maximum(np.asarray(n, np.float64), 2.0)
    return 2.0 * (np.log(n - 1.0) + 0.5772156649) - 2.0 * (n - 1.0) / n


class IsolationForest(Estimator, HasFeaturesCol, HasPredictionCol):
    """reference: isolationforest/IsolationForest.scala:15-58 (param parity:
    numEstimators, maxSamples, maxFeatures, bootstrap, contamination,
    scoreCol, predictionCol)."""

    numEstimators = Param("numEstimators", "Number of isolation trees", 100,
                          TypeConverters.to_int)
    maxSamples = Param("maxSamples", "Subsample size per tree (<=1: fraction)",
                       256.0, TypeConverters.to_float)
    maxFeatures = Param("maxFeatures", "Features per tree (<=1: fraction)", 1.0,
                        TypeConverters.to_float)
    bootstrap = Param("bootstrap", "Sample with replacement", False,
                      TypeConverters.to_bool)
    contamination = Param("contamination",
                          "Expected outlier fraction (sets the label threshold; "
                          "0 disables labels)", 0.0, TypeConverters.to_float)
    scoreCol = Param("scoreCol", "Output anomaly-score column", "outlierScore",
                     TypeConverters.to_string)
    randomSeed = Param("randomSeed", "Seed", 1, TypeConverters.to_int)

    def fit(self, dataset: Dataset) -> "IsolationForestModel":
        X = np.asarray(dataset.array(self.get_or_default("featuresCol")),
                       np.float32)
        n, F = X.shape
        T = self.get_or_default("numEstimators")
        ms = self.get_or_default("maxSamples")
        sample_n = int(ms * n) if ms <= 1.0 else int(min(ms, n))
        sample_n = max(sample_n, 2)
        mf = self.get_or_default("maxFeatures")
        feat_n = max(int(mf * F) if mf <= 1.0 else int(min(mf, F)), 1)
        rng = np.random.default_rng(self.get_or_default("randomSeed"))

        depth_cap = int(np.ceil(np.log2(sample_n)))
        M = 2 ** (depth_cap + 1) - 1  # perfect-tree slot layout: kids of i at 2i+1/2i+2

        feat = np.zeros((T, M), np.int32)
        thr = np.zeros((T, M), np.float32)
        is_leaf = np.ones((T, M), bool)
        leaf_size = np.zeros((T, M), np.float32)

        for t in range(T):
            rows = rng.choice(n, sample_n, replace=self.get_or_default("bootstrap"))
            feats = (rng.choice(F, feat_n, replace=False) if feat_n < F
                     else np.arange(F))
            # iterative build over slot ids; each slot holds its row subset
            subsets = {0: X[rows][:, :]}
            for slot in range(M):
                rows_here = subsets.pop(slot, None)
                if rows_here is None:
                    continue
                depth = int(np.floor(np.log2(slot + 1)))
                if len(rows_here) <= 1 or depth >= depth_cap:
                    leaf_size[t, slot] = max(len(rows_here), 1)
                    continue
                f = int(rng.choice(feats))
                lo, hi = rows_here[:, f].min(), rows_here[:, f].max()
                if hi <= lo:  # constant feature here: give up, make a leaf
                    leaf_size[t, slot] = len(rows_here)
                    continue
                s = rng.uniform(lo, hi)
                feat[t, slot], thr[t, slot], is_leaf[t, slot] = f, s, False
                go_left = rows_here[:, f] < s
                subsets[2 * slot + 1] = rows_here[go_left]
                subsets[2 * slot + 2] = rows_here[~go_left]

        model = IsolationForestModel(
            feat=feat, thr=thr, is_leaf=is_leaf, leaf_size=leaf_size,
            depth_cap=depth_cap, sample_n=sample_n)
        self._copy_params_to(model)
        if self.get_or_default("contamination") > 0:
            scores = model._score(X)
            model.set(threshold=float(np.quantile(
                scores, 1.0 - self.get_or_default("contamination"))))
        return model


class IsolationForestModel(Model, HasFeaturesCol, HasPredictionCol):
    scoreCol = Param("scoreCol", "Output anomaly-score column", "outlierScore",
                     TypeConverters.to_string)
    threshold = Param("threshold", "Score threshold for outlier label", None,
                      TypeConverters.to_float)

    def __init__(self, feat=None, thr=None, is_leaf=None, leaf_size=None,
                 depth_cap: int = 0, sample_n: int = 2, **kwargs):
        super().__init__(**kwargs)
        self.feat, self.thr = feat, thr
        self.is_leaf, self.leaf_size = is_leaf, leaf_size
        self.depth_cap, self.sample_n = depth_cap, sample_n

    def _score(self, X: np.ndarray) -> np.ndarray:
        Xd = jnp.asarray(X, jnp.float32)
        n = X.shape[0]
        feat, thr = jnp.asarray(self.feat), jnp.asarray(self.thr)
        is_leaf = jnp.asarray(self.is_leaf)
        leaf_size = jnp.asarray(self.leaf_size)

        def one_tree(ft, th, lf, ls):
            node = jnp.zeros(n, jnp.int32)
            depth = jnp.zeros(n, jnp.float32)

            def body(_, carry):
                node, depth = carry
                f = ft[node]
                x = jnp.take_along_axis(Xd, f[:, None], axis=1)[:, 0]
                internal = ~lf[node]
                nxt = jnp.where(x < th[node], 2 * node + 1, 2 * node + 2)
                return (jnp.where(internal, nxt, node),
                        depth + internal.astype(jnp.float32))

            node, depth = jax.lax.fori_loop(0, self.depth_cap, body,
                                            (node, depth))
            # unresolved subtrees contribute the expected extra path length
            sz = jnp.maximum(ls[node], 2.0)
            extra = (2.0 * (jnp.log(sz - 1.0 + 1e-9) + 0.5772156649)
                     - 2.0 * (sz - 1.0) / sz)
            return depth + jnp.where(ls[node] > 1, extra, 0.0)

        paths = jax.vmap(one_tree)(feat, thr, is_leaf, leaf_size)  # [T, n]
        e_h = np.asarray(paths).mean(axis=0)
        c = _avg_path_length(self.sample_n)
        return np.power(2.0, -e_h / c)

    def transform(self, dataset: Dataset) -> Dataset:
        X = np.asarray(dataset.array(self.get_or_default("featuresCol")),
                       np.float32)
        scores = self._score(X)
        out = dataset.with_column(self.get_or_default("scoreCol"), scores)
        th = self.get_or_default("threshold")
        if th is not None:
            out = out.with_column(self.get_or_default("predictionCol"),
                                  (scores > th).astype(np.float64))
        return out

    def _save_extra(self, path):
        import os
        np.savez_compressed(
            os.path.join(path, "forest.npz"), feat=self.feat, thr=self.thr,
            is_leaf=self.is_leaf, leaf_size=self.leaf_size,
            meta=np.asarray([self.depth_cap, self.sample_n]))

    def _load_extra(self, path):
        import os
        z = np.load(os.path.join(path, "forest.npz"))
        self.feat, self.thr = z["feat"], z["thr"]
        self.is_leaf, self.leaf_size = z["is_leaf"], z["leaf_size"]
        self.depth_cap, self.sample_n = int(z["meta"][0]), int(z["meta"][1])
