"""SAR — Smart Adaptive Recommendations — on the device mesh.

TPU-native re-design of the reference's SAR (reference:
recommendation/SAR.scala:38-258 — item-item similarity via cooccurrence /
jaccard / lift + time-decayed user affinity; SARModel.scala:23-169;
RecommendationIndexer.scala:17-101). The hot path — user-affinity x
item-similarity scoring and top-k — is dense matmul + top_k on device; the
co-occurrence build is one X^T X matmul over the (users x items) interaction
matrix, which rides the MXU instead of the reference's pairwise RDD joins.
"""

from __future__ import annotations

import math
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dataset import Dataset
from ..core.params import Param, TypeConverters
from ..core.pipeline import Estimator, Model


class RecommendationIndexer(Estimator):
    """String user/item ids -> dense indices and back
    (reference: recommendation/RecommendationIndexer.scala:17-101)."""

    userInputCol = Param("userInputCol", "raw user column", "user",
                         TypeConverters.to_string)
    itemInputCol = Param("itemInputCol", "raw item column", "item",
                         TypeConverters.to_string)
    userOutputCol = Param("userOutputCol", "indexed user column", "user_idx",
                          TypeConverters.to_string)
    itemOutputCol = Param("itemOutputCol", "indexed item column", "item_idx",
                          TypeConverters.to_string)

    def fit(self, dataset: Dataset) -> "RecommendationIndexerModel":
        users = list(dict.fromkeys(dataset[self.get_or_default("userInputCol")]))
        items = list(dict.fromkeys(dataset[self.get_or_default("itemInputCol")]))
        model = RecommendationIndexerModel(userLevels=users, itemLevels=items)
        self._copy_params_to(model)
        return model


class RecommendationIndexerModel(Model):
    userInputCol = Param("userInputCol", "raw user column", "user",
                         TypeConverters.to_string)
    itemInputCol = Param("itemInputCol", "raw item column", "item",
                         TypeConverters.to_string)
    userOutputCol = Param("userOutputCol", "indexed user column", "user_idx",
                          TypeConverters.to_string)
    itemOutputCol = Param("itemOutputCol", "indexed item column", "item_idx",
                          TypeConverters.to_string)
    userLevels = Param("userLevels", "user id vocabulary", None, is_complex=True)
    itemLevels = Param("itemLevels", "item id vocabulary", None, is_complex=True)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def transform(self, dataset: Dataset) -> Dataset:
        u_map = {v: i for i, v in enumerate(self.get_or_default("userLevels"))}
        i_map = {v: i for i, v in enumerate(self.get_or_default("itemLevels"))}
        u = np.asarray([u_map.get(v, -1)
                        for v in dataset[self.get_or_default("userInputCol")]],
                       np.int32)
        it = np.asarray([i_map.get(v, -1)
                         for v in dataset[self.get_or_default("itemInputCol")]],
                        np.int32)
        ds = dataset.with_columns({self.get_or_default("userOutputCol"): u,
                                   self.get_or_default("itemOutputCol"): it})
        keep = (u >= 0) & (it >= 0)
        return ds.filter(keep) if not keep.all() else ds

    def recover_user(self, idx: int):
        return self.get_or_default("userLevels")[idx]

    def recover_item(self, idx: int):
        return self.get_or_default("itemLevels")[idx]


class SAR(Estimator):
    """reference: recommendation/SAR.scala:38-258 (param parity:
    similarityFunction, timeDecayCoeff, supportThreshold, ...)."""

    userCol = Param("userCol", "indexed user column", "user_idx",
                    TypeConverters.to_string)
    itemCol = Param("itemCol", "indexed item column", "item_idx",
                    TypeConverters.to_string)
    ratingCol = Param("ratingCol", "rating column (absent: implicit 1.0)",
                      "rating", TypeConverters.to_string)
    timeCol = Param("timeCol", "event-time column (epoch seconds) for decay",
                    None, TypeConverters.to_string)
    similarityFunction = Param("similarityFunction",
                               "cooccurrence | jaccard | lift", "jaccard",
                               TypeConverters.to_string)
    timeDecayCoeff = Param("timeDecayCoeff", "affinity half-life in days", 30,
                           TypeConverters.to_int)
    supportThreshold = Param("supportThreshold",
                             "min co-occurrence count to keep a similarity", 4,
                             TypeConverters.to_int)
    startTime = Param("startTime", "reference timestamp for decay (default: "
                      "max event time)", None, TypeConverters.to_float)

    def fit(self, dataset: Dataset) -> "SARModel":
        u = dataset.array(self.get_or_default("userCol"), np.int32)
        it = dataset.array(self.get_or_default("itemCol"), np.int32)
        rcol = self.get_or_default("ratingCol")
        r = (dataset.array(rcol, np.float32) if rcol in dataset
             else np.ones(len(u), np.float32))
        n_users, n_items = int(u.max()) + 1, int(it.max()) + 1

        # user affinity with optional exponential time decay
        # (reference: SAR.scala user-affinity time decay)
        tcol = self.get_or_default("timeCol")
        if tcol and tcol in dataset:
            t = dataset.array(tcol, np.float64)
            t_ref = self.get_or_default("startTime") or float(t.max())
            half_life_s = self.get_or_default("timeDecayCoeff") * 86400.0
            decay = np.exp2(-(t_ref - t) / half_life_s).astype(np.float32)
            r = r * decay
        affinity = np.zeros((n_users, n_items), np.float32)
        np.add.at(affinity, (u, it), r)

        # item-item co-occurrence: one MXU matmul over the binarized matrix
        seen = np.zeros((n_users, n_items), np.float32)
        seen[u, it] = 1.0
        seen_d = jnp.asarray(seen)
        cooc = np.asarray(seen_d.T @ seen_d)  # [I, I]
        occ = np.diag(cooc).copy()

        thresh = self.get_or_default("supportThreshold")
        sim_fn = self.get_or_default("similarityFunction")
        if sim_fn == "cooccurrence":
            sim = cooc.copy()
        elif sim_fn == "jaccard":
            denom = occ[:, None] + occ[None, :] - cooc
            sim = cooc / np.maximum(denom, 1e-9)
        elif sim_fn == "lift":
            sim = cooc / np.maximum(occ[:, None] * occ[None, :], 1e-9)
        else:
            raise ValueError(f"unknown similarityFunction {sim_fn!r}")
        sim = np.where(cooc >= thresh, sim, 0.0).astype(np.float32)

        model = SARModel(itemSimilarity=sim, userAffinity=affinity,
                         seen=seen.astype(bool))
        self._copy_params_to(model)
        return model


class SARModel(Model):
    userCol = Param("userCol", "indexed user column", "user_idx",
                    TypeConverters.to_string)
    itemCol = Param("itemCol", "indexed item column", "item_idx",
                    TypeConverters.to_string)
    predictionCol = Param("predictionCol", "score column", "prediction",
                          TypeConverters.to_string)
    removeSeenItems = Param("removeSeenItems",
                            "exclude train-time items from recommendations",
                            True, TypeConverters.to_bool)

    def __init__(self, itemSimilarity: Optional[np.ndarray] = None,
                 userAffinity: Optional[np.ndarray] = None,
                 seen: Optional[np.ndarray] = None, **kwargs):
        super().__init__(**kwargs)
        self.itemSimilarity = itemSimilarity
        self.userAffinity = userAffinity
        self.seen = seen

    def transform(self, dataset: Dataset) -> Dataset:
        """Score only the (user, item) pairs present in the dataset —
        one gather + row-wise dot, never the full users x items matrix."""
        u = dataset.array(self.get_or_default("userCol"), np.int32)
        it = dataset.array(self.get_or_default("itemCol"), np.int32)
        n_users, n_items = self.userAffinity.shape
        bad_u, bad_i = (u < 0) | (u >= n_users), (it < 0) | (it >= n_items)
        if bad_u.any() or bad_i.any():
            raise ValueError(
                f"{int(bad_u.sum())} users / {int(bad_i.sum())} items are "
                f"outside the trained range ({n_users} users, {n_items} "
                "items); index with the same RecommendationIndexer used for fit")
        aff = jnp.asarray(self.userAffinity)[jnp.asarray(u)]        # [n, I]
        sim = jnp.asarray(self.itemSimilarity)[:, jnp.asarray(it)]  # [I, n]
        scores = jnp.sum(aff * sim.T, axis=1)
        return dataset.with_column(self.get_or_default("predictionCol"),
                                   np.asarray(scores, np.float64))

    def recommend_for_all_users(self, k: int) -> Dataset:
        """Top-k unseen items per user (reference: SARModel.scala:23-169).
        One device matmul + top_k."""
        aff = jnp.asarray(self.userAffinity)
        sim = jnp.asarray(self.itemSimilarity)
        scores = aff @ sim
        if self.get_or_default("removeSeenItems"):
            scores = jnp.where(jnp.asarray(self.seen), -jnp.inf, scores)
        k = min(k, scores.shape[1])
        vals, ids = jax.lax.top_k(scores, k)
        return Dataset({
            self.get_or_default("userCol"): np.arange(scores.shape[0], dtype=np.int32),
            "recommendations": list(np.asarray(ids)),
            "ratings": list(np.asarray(vals).astype(np.float64)),
        })

    recommendForAllUsers = recommend_for_all_users

    def _save_extra(self, path):
        import os
        np.savez_compressed(os.path.join(path, "sar.npz"),
                            sim=self.itemSimilarity, aff=self.userAffinity,
                            seen=self.seen)

    def _load_extra(self, path):
        import os
        z = np.load(os.path.join(path, "sar.npz"))
        self.itemSimilarity, self.userAffinity = z["sim"], z["aff"]
        self.seen = z["seen"]
