"""SAR — Smart Adaptive Recommendations — on the device mesh.

TPU-native re-design of the reference's SAR (reference:
recommendation/SAR.scala:38-258 — item-item similarity via cooccurrence /
jaccard / lift + time-decayed user affinity; SARModel.scala:23-169;
RecommendationIndexer.scala:17-101). The hot path — user-affinity x
item-similarity scoring and top-k — is dense matmul + top_k on device; the
co-occurrence build is one X^T X matmul over the (users x items) interaction
matrix, which rides the MXU instead of the reference's pairwise RDD joins.
"""

from __future__ import annotations

import math
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dataset import Dataset
from ..core.params import Param, TypeConverters
from ..core.pipeline import Estimator, Model

# Above this many user x item cells, fit() switches from the dense in-memory
# formulation to sparse CSR (the reference stays sparse in DataFrames
# throughout — SAR.scala:38-258; the dense path is kept below the threshold
# because it rides single device matmuls with zero indexing overhead).
# ~50M f32 cells = 200 MB per matrix.
DENSE_CELLS_MAX = 50_000_000


def _sparse():
    import scipy.sparse as sp
    return sp


def _is_sparse_mat(x) -> bool:
    return x is not None and not isinstance(x, np.ndarray) and hasattr(x, "tocsr")


class RecommendationIndexer(Estimator):
    """String user/item ids -> dense indices and back
    (reference: recommendation/RecommendationIndexer.scala:17-101)."""

    userInputCol = Param("userInputCol", "raw user column", "user",
                         TypeConverters.to_string)
    itemInputCol = Param("itemInputCol", "raw item column", "item",
                         TypeConverters.to_string)
    userOutputCol = Param("userOutputCol", "indexed user column", "user_idx",
                          TypeConverters.to_string)
    itemOutputCol = Param("itemOutputCol", "indexed item column", "item_idx",
                          TypeConverters.to_string)

    def fit(self, dataset: Dataset) -> "RecommendationIndexerModel":
        users = list(dict.fromkeys(dataset[self.get_or_default("userInputCol")]))
        items = list(dict.fromkeys(dataset[self.get_or_default("itemInputCol")]))
        model = RecommendationIndexerModel(userLevels=users, itemLevels=items)
        self._copy_params_to(model)
        return model


class RecommendationIndexerModel(Model):
    userInputCol = Param("userInputCol", "raw user column", "user",
                         TypeConverters.to_string)
    itemInputCol = Param("itemInputCol", "raw item column", "item",
                         TypeConverters.to_string)
    userOutputCol = Param("userOutputCol", "indexed user column", "user_idx",
                          TypeConverters.to_string)
    itemOutputCol = Param("itemOutputCol", "indexed item column", "item_idx",
                          TypeConverters.to_string)
    userLevels = Param("userLevels", "user id vocabulary", None, is_complex=True)
    itemLevels = Param("itemLevels", "item id vocabulary", None, is_complex=True)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def transform(self, dataset: Dataset) -> Dataset:
        u_map = {v: i for i, v in enumerate(self.get_or_default("userLevels"))}
        i_map = {v: i for i, v in enumerate(self.get_or_default("itemLevels"))}
        u = np.asarray([u_map.get(v, -1)
                        for v in dataset[self.get_or_default("userInputCol")]],
                       np.int32)
        it = np.asarray([i_map.get(v, -1)
                         for v in dataset[self.get_or_default("itemInputCol")]],
                        np.int32)
        ds = dataset.with_columns({self.get_or_default("userOutputCol"): u,
                                   self.get_or_default("itemOutputCol"): it})
        keep = (u >= 0) & (it >= 0)
        return ds.filter(keep) if not keep.all() else ds

    def recover_user(self, idx: int):
        return self.get_or_default("userLevels")[idx]

    def recover_item(self, idx: int):
        return self.get_or_default("itemLevels")[idx]


class SAR(Estimator):
    """reference: recommendation/SAR.scala:38-258 (param parity:
    similarityFunction, timeDecayCoeff, supportThreshold, ...)."""

    userCol = Param("userCol", "indexed user column", "user_idx",
                    TypeConverters.to_string)
    itemCol = Param("itemCol", "indexed item column", "item_idx",
                    TypeConverters.to_string)
    ratingCol = Param("ratingCol", "rating column (absent: implicit 1.0)",
                      "rating", TypeConverters.to_string)
    timeCol = Param("timeCol", "event-time column (epoch seconds) for decay",
                    None, TypeConverters.to_string)
    similarityFunction = Param("similarityFunction",
                               "cooccurrence | jaccard | lift", "jaccard",
                               TypeConverters.to_string)
    timeDecayCoeff = Param("timeDecayCoeff", "affinity half-life in days", 30,
                           TypeConverters.to_int)
    supportThreshold = Param("supportThreshold",
                             "min co-occurrence count to keep a similarity", 4,
                             TypeConverters.to_int)
    startTime = Param("startTime", "reference timestamp for decay (default: "
                      "max event time)", None, TypeConverters.to_float)

    def fit(self, dataset: Dataset) -> "SARModel":
        u = dataset.array(self.get_or_default("userCol"), np.int32)
        it = dataset.array(self.get_or_default("itemCol"), np.int32)
        rcol = self.get_or_default("ratingCol")
        r = (dataset.array(rcol, np.float32) if rcol in dataset
             else np.ones(len(u), np.float32))
        n_users, n_items = int(u.max()) + 1, int(it.max()) + 1

        # user affinity with optional exponential time decay
        # (reference: SAR.scala user-affinity time decay)
        tcol = self.get_or_default("timeCol")
        if tcol and tcol in dataset:
            t = dataset.array(tcol, np.float64)
            t_ref = self.get_or_default("startTime") or float(t.max())
            half_life_s = self.get_or_default("timeDecayCoeff") * 86400.0
            decay = np.exp2(-(t_ref - t) / half_life_s).astype(np.float32)
            r = r * decay
        thresh = self.get_or_default("supportThreshold")
        sim_fn = self.get_or_default("similarityFunction")
        if sim_fn not in ("cooccurrence", "jaccard", "lift"):
            raise ValueError(f"unknown similarityFunction {sim_fn!r}")

        if n_users * n_items > DENSE_CELLS_MAX:
            model = self._fit_sparse(u, it, r, n_users, n_items, sim_fn,
                                     thresh)
            self._copy_params_to(model)
            return model

        affinity = np.zeros((n_users, n_items), np.float32)
        np.add.at(affinity, (u, it), r)

        # item-item co-occurrence: one MXU matmul over the binarized matrix
        seen = np.zeros((n_users, n_items), np.float32)
        seen[u, it] = 1.0
        seen_d = jnp.asarray(seen)
        cooc = np.asarray(seen_d.T @ seen_d)  # [I, I]
        occ = np.diag(cooc).copy()

        if sim_fn == "cooccurrence":
            sim = cooc.copy()
        elif sim_fn == "jaccard":
            denom = occ[:, None] + occ[None, :] - cooc
            sim = cooc / np.maximum(denom, 1e-9)
        else:  # lift
            sim = cooc / np.maximum(occ[:, None] * occ[None, :], 1e-9)
        sim = np.where(cooc >= thresh, sim, 0.0).astype(np.float32)

        model = SARModel(itemSimilarity=sim, userAffinity=affinity,
                         seen=seen.astype(bool))
        self._copy_params_to(model)
        return model

    def _fit_sparse(self, u, it, r, n_users: int, n_items: int,
                    sim_fn: str, thresh: float) -> "SARModel":
        """CSR formulation for beyond-RAM-dense scales: affinity and seen
        stay sparse, the co-occurrence is one SpGEMM (S^T S), and the
        similarity transform runs on the nonzero COO entries only. Matches
        the dense path exactly on shared cells (pinned in tests); cells
        the dense path stores as explicit 0 simply don't exist here."""
        sp = _sparse()
        aff = sp.coo_matrix((r, (u, it)), shape=(n_users, n_items),
                            dtype=np.float32).tocsr()
        ones = np.ones(len(u), np.float32)
        seen = sp.coo_matrix((ones, (u, it)), shape=(n_users, n_items),
                             dtype=np.float32).tocsr()
        seen.data[:] = 1.0                       # binarize duplicate events
        cooc = (seen.T @ seen).tocoo()           # [I, I], sparse SpGEMM
        occ = np.zeros(n_items, np.float32)
        diag = cooc.row == cooc.col
        occ[cooc.row[diag]] = cooc.data[diag]

        data, row, col = cooc.data, cooc.row, cooc.col
        keep = data >= thresh
        data, row, col = data[keep], row[keep], col[keep]
        if sim_fn == "cooccurrence":
            sim_data = data
        elif sim_fn == "jaccard":
            sim_data = data / np.maximum(occ[row] + occ[col] - data, 1e-9)
        else:  # lift
            sim_data = data / np.maximum(occ[row] * occ[col], 1e-9)
        sim = sp.csr_matrix((sim_data.astype(np.float32), (row, col)),
                            shape=(n_items, n_items))
        return SARModel(itemSimilarity=sim, userAffinity=aff, seen=seen)


class SARModel(Model):
    userCol = Param("userCol", "indexed user column", "user_idx",
                    TypeConverters.to_string)
    itemCol = Param("itemCol", "indexed item column", "item_idx",
                    TypeConverters.to_string)
    predictionCol = Param("predictionCol", "score column", "prediction",
                          TypeConverters.to_string)
    removeSeenItems = Param("removeSeenItems",
                            "exclude train-time items from recommendations",
                            True, TypeConverters.to_bool)

    def __init__(self, itemSimilarity: Optional[np.ndarray] = None,
                 userAffinity: Optional[np.ndarray] = None,
                 seen: Optional[np.ndarray] = None, **kwargs):
        super().__init__(**kwargs)
        self.itemSimilarity = itemSimilarity
        self.userAffinity = userAffinity
        self.seen = seen

    def transform(self, dataset: Dataset) -> Dataset:
        """Score only the (user, item) pairs present in the dataset —
        one gather + row-wise dot, never the full users x items matrix."""
        u = dataset.array(self.get_or_default("userCol"), np.int32)
        it = dataset.array(self.get_or_default("itemCol"), np.int32)
        n_users, n_items = self.userAffinity.shape
        bad_u, bad_i = (u < 0) | (u >= n_users), (it < 0) | (it >= n_items)
        if bad_u.any() or bad_i.any():
            raise ValueError(
                f"{int(bad_u.sum())} users / {int(bad_i.sum())} items are "
                f"outside the trained range ({n_users} users, {n_items} "
                "items); index with the same RecommendationIndexer used for fit")
        out_col = self.get_or_default("predictionCol")
        if _is_sparse_mat(self.userAffinity):
            # sparse scale: per-pair dot = elementwise product of the user's
            # affinity row and the item's similarity column, both sparse.
            # The CSC view is cached — rebuilding it is O(nnz) and would
            # dominate small-batch scoring.
            if getattr(self, "_sim_csc", None) is None:
                self._sim_csc = self.itemSimilarity.tocsc()
            aff_rows = self.userAffinity[u]                       # [n, I]
            sim_cols = self._sim_csc[:, it].T                     # [n, I]
            scores = np.asarray(
                aff_rows.multiply(sim_cols).sum(axis=1)).ravel()
            return dataset.with_column(out_col, scores.astype(np.float64))
        aff = jnp.asarray(self.userAffinity)[jnp.asarray(u)]        # [n, I]
        sim = jnp.asarray(self.itemSimilarity)[:, jnp.asarray(it)]  # [I, n]
        scores = jnp.sum(aff * sim.T, axis=1)
        return dataset.with_column(out_col, np.asarray(scores, np.float64))

    def recommend_for_all_users(self, k: int) -> Dataset:
        """Top-k unseen items per user (reference: SARModel.scala:23-169).
        Dense: one device matmul + top_k. Sparse scale: user-blocked
        SpGEMM (aff_block @ sim stays sparse) with per-block device top_k
        on the densified [block, I] result — HBM holds one block, never
        the users x items matrix."""
        ucol = self.get_or_default("userCol")
        if _is_sparse_mat(self.userAffinity):
            n_users, n_items = self.userAffinity.shape
            k = min(k, n_items)
            remove = self.get_or_default("removeSeenItems")
            block = max(1, min(n_users, 33_554_432 // max(n_items, 1)))
            ids_out, vals_out = [], []
            for lo in range(0, n_users, block):
                hi = min(lo + block, n_users)
                sb = (self.userAffinity[lo:hi] @ self.itemSimilarity)
                dense = np.asarray(sb.todense(), np.float32)
                if remove:
                    seen_b = self.seen[lo:hi].tocoo()
                    dense[seen_b.row, seen_b.col] = -np.inf
                vals, ids = jax.lax.top_k(jnp.asarray(dense), k)
                ids_out.append(np.asarray(ids))
                vals_out.append(np.asarray(vals))
            return Dataset({
                ucol: np.arange(n_users, dtype=np.int32),
                "recommendations": list(np.concatenate(ids_out)),
                "ratings": list(
                    np.concatenate(vals_out).astype(np.float64)),
            })
        aff = jnp.asarray(self.userAffinity)
        sim = jnp.asarray(self.itemSimilarity)
        scores = aff @ sim
        if self.get_or_default("removeSeenItems"):
            scores = jnp.where(jnp.asarray(self.seen), -jnp.inf, scores)
        k = min(k, scores.shape[1])
        vals, ids = jax.lax.top_k(scores, k)
        return Dataset({
            ucol: np.arange(scores.shape[0], dtype=np.int32),
            "recommendations": list(np.asarray(ids)),
            "ratings": list(np.asarray(vals).astype(np.float64)),
        })

    recommendForAllUsers = recommend_for_all_users

    def _save_extra(self, path):
        import os
        # clear the OTHER format's files: saving over a directory that held
        # the previous format must not leave a stale model that _load_extra
        # would prefer
        sparse_files = ("sar_sim.npz", "sar_aff.npz", "sar_seen.npz")
        if _is_sparse_mat(self.userAffinity):
            dense_f = os.path.join(path, "sar.npz")
            if os.path.exists(dense_f):
                os.unlink(dense_f)
            sp = _sparse()
            sp.save_npz(os.path.join(path, "sar_sim.npz"),
                        self.itemSimilarity.tocsr())
            sp.save_npz(os.path.join(path, "sar_aff.npz"),
                        self.userAffinity.tocsr())
            sp.save_npz(os.path.join(path, "sar_seen.npz"),
                        self.seen.tocsr())
            return
        for f in sparse_files:
            if os.path.exists(os.path.join(path, f)):
                os.unlink(os.path.join(path, f))
        np.savez_compressed(os.path.join(path, "sar.npz"),
                            sim=self.itemSimilarity, aff=self.userAffinity,
                            seen=self.seen)

    def _load_extra(self, path):
        import os
        dense = os.path.join(path, "sar.npz")
        if os.path.exists(dense):
            z = np.load(dense)
            self.itemSimilarity, self.userAffinity = z["sim"], z["aff"]
            self.seen = z["seen"]
            return
        sp = _sparse()
        self.itemSimilarity = sp.load_npz(os.path.join(path, "sar_sim.npz"))
        self.userAffinity = sp.load_npz(os.path.join(path, "sar_aff.npz"))
        self.seen = sp.load_npz(os.path.join(path, "sar_seen.npz"))
        self._sim_csc = None            # invalidate any cached CSC view
