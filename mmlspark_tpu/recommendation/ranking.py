"""Ranking evaluation + train/validation tooling for recommenders.

TPU-native equivalents of the reference's ranking helpers (reference:
recommendation/RankingEvaluator.scala:15-152 — ndcgAt, map, precisionAtk,
recallAtK, diversityAtK, maxDiversity; RankingAdapter.scala:16-151;
RankingTrainValidationSplit.scala:24-328 — per-user stratified split :283).
Metric math is vectorized numpy over fixed-width top-k blocks.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.dataset import Dataset
from ..core.params import Param, TypeConverters
from ..core.pipeline import Estimator, Model, Transformer


def _per_user_lists(ds: Dataset, userCol: str, itemCol: str,
                    ratingCol: Optional[str] = None) -> Dict:
    out: Dict = {}
    users = ds[userCol]
    items = ds[itemCol]
    ratings = ds[ratingCol] if ratingCol and ratingCol in ds else None
    for i in range(len(ds)):
        u = users[i]
        out.setdefault(u, []).append(
            (items[i], float(ratings[i]) if ratings is not None else 1.0))
    return out


def _filter_min_counts(dataset, col: str, lo) -> "Dataset":
    """Drop rows whose ``col`` value occurs fewer than ``lo`` times."""
    if not lo or lo <= 1:
        return dataset
    vals = np.asarray(dataset[col])
    uniq, counts = np.unique(vals, return_counts=True)
    mask = np.isin(vals, uniq[counts >= lo])
    return dataset.filter(mask) if not mask.all() else dataset


class RankingEvaluator(Transformer):
    """Computes ranking metrics from (recommendations, ground-truth) datasets
    (reference: recommendation/RankingEvaluator.scala:15-152).

    ``transform`` expects a dataset with a recommendations column (list of
    item ids per user) and a ground-truth column (list of relevant item ids);
    ``evaluate`` returns one scalar.
    """

    k = Param("k", "cutoff position", 10, TypeConverters.to_int)
    metricName = Param("metricName", "ndcgAt | map | precisionAtk | recallAtK "
                       "| diversityAtK | maxDiversity", "ndcgAt",
                       TypeConverters.to_string)
    recsCol = Param("recsCol", "recommended item-id lists", "recommendations",
                    TypeConverters.to_string)
    labelsCol = Param("labelsCol", "ground-truth item-id lists", "labels",
                      TypeConverters.to_string)
    nItems = Param("nItems", "catalog size (diversity metrics)", -1,
                   TypeConverters.to_int)

    def evaluate(self, dataset: Dataset) -> float:
        k = self.get_or_default("k")
        recs = [list(r)[:k] for r in dataset[self.get_or_default("recsCol")]]
        labels = [set(l) for l in dataset[self.get_or_default("labelsCol")]]
        name = self.get_or_default("metricName")
        if name == "ndcgAt":
            vals = []
            for rec, lab in zip(recs, labels):
                if not lab:
                    continue
                dcg = sum(1.0 / np.log2(i + 2.0)
                          for i, item in enumerate(rec) if item in lab)
                idcg = sum(1.0 / np.log2(i + 2.0)
                           for i in range(min(len(lab), k)))
                vals.append(dcg / idcg if idcg > 0 else 0.0)
            return float(np.mean(vals)) if vals else 0.0
        if name == "map":
            vals = []
            for rec, lab in zip(recs, labels):
                if not lab:
                    continue
                hits, s = 0, 0.0
                for i, item in enumerate(rec):
                    if item in lab:
                        hits += 1
                        s += hits / (i + 1.0)
                vals.append(s / min(len(lab), k))
            return float(np.mean(vals)) if vals else 0.0
        if name == "precisionAtk":
            return float(np.mean([
                len([x for x in rec if x in lab]) / float(k)
                for rec, lab in zip(recs, labels)]))
        if name == "recallAtK":
            vals = [len([x for x in rec if x in lab]) / float(len(lab))
                    for rec, lab in zip(recs, labels) if lab]
            return float(np.mean(vals)) if vals else 0.0
        if name in ("diversityAtK", "maxDiversity"):
            shown = {x for rec in recs for x in rec}
            n = self.get_or_default("nItems")
            if n <= 0:
                n = len({x for lab in labels for x in lab} | shown)
            return len(shown) / float(max(n, 1))
        raise ValueError(f"unknown metricName {name!r}")

    def transform(self, dataset: Dataset) -> Dataset:
        return Dataset({self.get_or_default("metricName"):
                        np.asarray([self.evaluate(dataset)])})


class RankingAdapter(Estimator):
    """Wraps a recommender so its output feeds RankingEvaluator
    (reference: recommendation/RankingAdapter.scala:16-151)."""

    recommender = Param("recommender", "inner recommender estimator", None,
                        is_complex=True)
    k = Param("k", "recommendations per user", 10, TypeConverters.to_int)
    userCol = Param("userCol", "user column", "user_idx", TypeConverters.to_string)
    itemCol = Param("itemCol", "item column", "item_idx", TypeConverters.to_string)
    ratingCol = Param("ratingCol", "rating column", "rating", TypeConverters.to_string)
    minRatingsPerUser = Param("minRatingsPerUser", "drop users below this", 1,
                              TypeConverters.to_int)
    minRatingsPerItem = Param("minRatingsPerItem", "drop items below this "
                              "(reference: RankingAdapter "
                              "minRatingsPerItem)", 1,
                              TypeConverters.to_int)

    def __init__(self, recommender=None, **kwargs):
        super().__init__(**kwargs)
        if recommender is not None:
            self.set(recommender=recommender)

    def _filtered(self, dataset: Dataset) -> Dataset:
        # sequential: item counts are recomputed AFTER cold users leave,
        # so surviving items honor their stated minimum on the rows that
        # actually remain
        dataset = _filter_min_counts(
            dataset, self.get_or_default("userCol"),
            self.get_or_default("minRatingsPerUser"))
        return _filter_min_counts(
            dataset, self.get_or_default("itemCol"),
            self.get_or_default("minRatingsPerItem"))

    def fit(self, dataset: Dataset) -> "RankingAdapterModel":
        dataset = self._filtered(dataset)
        fitted = self.get_or_default("recommender").fit(dataset)
        model = RankingAdapterModel(recommenderModel=fitted)
        self._copy_params_to(model)
        return model


class RankingAdapterModel(Model):
    recommenderModel = Param("recommenderModel", "fitted recommender", None,
                             is_complex=True)
    k = Param("k", "recommendations per user", 10, TypeConverters.to_int)
    userCol = Param("userCol", "user column", "user_idx", TypeConverters.to_string)
    itemCol = Param("itemCol", "item column", "item_idx", TypeConverters.to_string)
    ratingCol = Param("ratingCol", "rating column", "rating", TypeConverters.to_string)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def transform(self, dataset: Dataset) -> Dataset:
        """Emit (recommendations, labels) rows per user in the eval dataset."""
        inner = self.get_or_default("recommenderModel")
        k = self.get_or_default("k")
        recs = inner.recommend_for_all_users(k)
        ucol, icol = self.get_or_default("userCol"), self.get_or_default("itemCol")
        truth = _per_user_lists(dataset, ucol, icol,
                                self.get_or_default("ratingCol"))
        rows = []
        rec_users = recs[ucol]
        rec_lists = recs["recommendations"]
        for i in range(len(recs)):
            u = rec_users[i]
            if u in truth:
                rows.append({ucol: u,
                             "recommendations": list(rec_lists[i]),
                             "labels": [it for it, _ in truth[u]]})
        return Dataset.from_rows(rows)


class RankingTrainValidationSplit(Estimator):
    """Per-user stratified train/validation split + fit
    (reference: recommendation/RankingTrainValidationSplit.scala:24-328;
    the per-user split is :283)."""

    estimator = Param("estimator", "recommender to fit on the train split",
                      None, is_complex=True)
    trainRatio = Param("trainRatio", "per-user train fraction", 0.75,
                       TypeConverters.to_float)
    userCol = Param("userCol", "user column", "user_idx", TypeConverters.to_string)
    itemCol = Param("itemCol", "item column", "item_idx", TypeConverters.to_string)
    ratingCol = Param("ratingCol", "rating column", "rating", TypeConverters.to_string)
    minRatingsPerUser = Param("minRatingsPerUser", "drop users below this", 2,
                              TypeConverters.to_int)
    minRatingsPerItem = Param("minRatingsPerItem", "drop items below this "
                              "before splitting", 1, TypeConverters.to_int)
    seed = Param("seed", "random seed", 0, TypeConverters.to_int)
    validationMetrics = Param("validationMetrics", "metrics of the fitted "
                              "candidate on the validation split, set by "
                              "fit() (reference: RankingTrainValidationSplit "
                              "validationMetrics)", None, is_complex=True)

    def __init__(self, estimator=None, **kwargs):
        super().__init__(**kwargs)
        if estimator is not None:
            self.set(estimator=estimator)

    def split(self, dataset: Dataset):
        """Per-user stratified (train, validation) datasets."""
        ucol = self.get_or_default("userCol")
        dataset = _filter_min_counts(
            dataset, self.get_or_default("itemCol"),
            self.get_or_default("minRatingsPerItem"))
        users = np.asarray(dataset[ucol])
        rng = np.random.default_rng(self.get_or_default("seed"))
        ratio = self.get_or_default("trainRatio")
        min_r = self.get_or_default("minRatingsPerUser")
        train_mask = np.zeros(len(dataset), bool)
        keep_mask = np.ones(len(dataset), bool)
        for u in np.unique(users):
            idx = np.nonzero(users == u)[0]
            if len(idx) < min_r:
                keep_mask[idx] = False
                continue
            perm = rng.permutation(idx)
            n_train = max(int(round(ratio * len(idx))), 1)
            if n_train == len(idx):
                n_train -= 1  # every kept user contributes >=1 validation row
            train_mask[perm[:n_train]] = True
        return (dataset.filter(train_mask & keep_mask),
                dataset.filter(~train_mask & keep_mask))

    def fit(self, dataset: Dataset):
        train, valid = self.split(dataset)
        fitted = self.get_or_default("estimator").fit(train)
        self.validation = valid  # exposed for evaluation
        try:
            # validationMetrics parity: when the candidate is a
            # RankingAdapter, its model emits the (recommendations,
            # labels) rows the evaluator consumes — score the held-out
            # split with NDCG like the reference's default metric
            scored = fitted.transform(valid)
            k = (fitted.get_or_default("k")
                 if any(p.name == "k" for p in fitted.params()) else 10)
            self.set(validationMetrics=[float(RankingEvaluator(
                metricName="ndcgAt", k=int(k)).evaluate(scored))])
        except Exception:
            # metric capture is best-effort (non-adapter candidates have
            # no standard eval shape); fitting must not fail on it
            self.set(validationMetrics=None)
        return fitted
