"""Quantile feature binning for histogram GBDT.

Host-side (numpy) equivalent of LightGBM's dataset construction: features are
discretized into at most ``max_bin`` bins using sample quantiles, and training
then operates on the integer bin indices only (reference: dataset creation via
LGBM_DatasetCreateFromMat at lightgbm/LightGBMUtils.scala:227,256 with
``max_bin``/``bin_construct_sample_cnt`` params, LightGBMUtils.scala:218-221).

Bins are defined by upper bounds: value v falls in bin b iff
``upper[b-1] < v <= upper[b]`` (searchsorted left on upper bounds). NaN maps to
bin 0 (its own region at the low end), matching the "missing goes to a fixed
side" convention; the split rule ``bin <= threshold_bin`` then sends NaN left.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import lax


def bin_cols_device(X: "jnp.ndarray", upper_bounds: "jnp.ndarray",
                    out_dtype=jnp.int32):
    """Device-side bin apply: floats [n, F] -> column-major bins [F, n].

    Exact parity with the host path (searchsorted side='left' == the count of
    strictly-smaller bounds; NaN compares false everywhere -> bin 0, matching
    native bin_batch's NaN->0). The compare-sum runs as fused VPU work — at
    1M x 28 x 255 it replaces a ~1.6 s single-core host pass — and emits the
    [F, n] layout tree growth consumes, so no separate device transpose.

    ``out_dtype`` is the storage dtype of the binned matrix (int32 default;
    uint8/int16 shrink the HBM-resident dataset 4x/2x for large-n /
    many-chip fits — bin ids are < max_bin <= 255 so uint8 is lossless).
    """
    xt = jnp.transpose(X.astype(jnp.float32))          # [F, n]

    def one(_, xu):
        xf, uf = xu                                    # [n], [B-1]
        b = jnp.sum(uf[:, None] < xf[None, :], axis=0).astype(out_dtype)
        return _, b

    _, bt = lax.scan(one, None, (xt, upper_bounds))
    return bt


class QuantileBinner:
    """Fit per-feature quantile bin boundaries; transform floats -> bin indices.

    ``categorical_features``: indices whose values are category ids — their
    bins are the ids themselves (boundaries at c + 0.5, so bin(c) == c for
    c in [0, max_bin-1], round-to-nearest for non-integral values, NaN and
    negatives -> bin 0). The same searchsorted machinery (native C++, numpy
    and on-device compare-sum) then handles both kinds with no special cases
    (reference ingests categorical metadata natively:
    core/schema/Categoricals.scala, LightGBMUtils.scala:227,256).
    """

    def __init__(self, max_bin: int = 255, sample_count: int = 200_000,
                 seed: int = 0, categorical_features=(),
                 max_bin_by_feature=None):
        self.max_bin = int(max_bin)
        self.sample_count = int(sample_count)
        self.seed = seed
        self.categorical_features = tuple(int(i) for i in categorical_features)
        # per-feature bin-count caps (LightGBM max_bin_by_feature): feature f
        # gets min(max_bin, max_bin_by_feature[f]) bins; unused boundary
        # slots pad with +inf so downstream shapes stay [F, max_bin-1]
        self.max_bin_by_feature = (None if max_bin_by_feature is None
                                   else [int(b) for b in max_bin_by_feature])
        self.upper_bounds: Optional[np.ndarray] = None  # [F, max_bin-1] f32
        self.num_features: Optional[int] = None

    def _feature_bins(self, f: int) -> int:
        if self.max_bin_by_feature is None:
            return self.max_bin
        if f >= len(self.max_bin_by_feature):
            raise ValueError(
                f"max_bin_by_feature has {len(self.max_bin_by_feature)} "
                f"entries but feature index {f} was requested")
        bf = self.max_bin_by_feature[f]
        if bf < 2:
            raise ValueError(f"max_bin_by_feature[{f}] = {bf}: every "
                             "feature needs at least 2 bins")
        return min(self.max_bin, bf)

    def fit(self, X: np.ndarray) -> "QuantileBinner":
        X = np.asarray(X, dtype=np.float32)
        n, F = X.shape
        self.num_features = F
        if n > self.sample_count:
            rng = np.random.default_rng(self.seed)
            X = X[rng.choice(n, self.sample_count, replace=False)]
        B = self.max_bin
        bounds = np.empty((F, B - 1), dtype=np.float32)
        cat = set(self.categorical_features)
        for f in range(F):
            if f in cat:
                # identity bins for category ids (bin(c) == c, clipped)
                bounds[f] = np.arange(B - 1, dtype=np.float32) + 0.5
                continue
            Bf = self._feature_bins(f)
            qs = np.linspace(0.0, 1.0, Bf + 1)[1:-1]  # interior quantiles
            col = X[:, f]
            col = col[~np.isnan(col)]
            if col.size == 0:
                bounds[f] = 0.0
                continue
            uniq = np.unique(col)
            if uniq.size <= Bf - 1:
                # few distinct values: one bin per value; boundaries at midpoints
                mids = (uniq[:-1] + uniq[1:]) / 2.0 if uniq.size > 1 else np.array([uniq[0]])
                pad = np.full(B - 1 - mids.size, np.float32(np.inf))
                bounds[f] = np.concatenate([mids.astype(np.float32), pad])
            else:
                q = np.quantile(col, qs).astype(np.float32)
                # strictly increasing boundaries; collapse duplicates to the right
                q = np.maximum.accumulate(q)
                bounds[f] = np.concatenate(
                    [q, np.full(B - Bf, np.float32(np.inf))]) \
                    if Bf < B else q
        self.upper_bounds = bounds
        return self

    def transform(self, X: np.ndarray) -> np.ndarray:
        """floats [n, F] -> int32 bins [n, F] in [0, max_bin-1]; NaN -> 0.

        Dispatches to the native C++ runtime when available (the
        LGBM_DatasetCreateFromMat analog); numpy searchsorted otherwise.
        """
        assert self.upper_bounds is not None, "fit first"
        from ..native import bin_batch
        return bin_batch(np.asarray(X, dtype=np.float32), self.upper_bounds)

    def fit_transform(self, X: np.ndarray) -> np.ndarray:
        return self.fit(X).transform(X)

    def bin_upper_raw(self) -> np.ndarray:
        """Raw-value threshold for "bin <= t": upper_bounds[f, t] (inf for last bin).

        Used to translate bin-space splits back into raw-feature thresholds so a
        trained model predicts directly on floats (the reference's native model
        string stores raw thresholds the same way).
        """
        F = self.upper_bounds.shape[0]
        inf = np.full((F, 1), np.float32(np.inf))
        return np.concatenate([self.upper_bounds, inf], axis=1)  # [F, max_bin]

    # -- persistence ------------------------------------------------------------
    def state(self) -> dict:
        return {
            "max_bin": self.max_bin,
            "sample_count": self.sample_count,
            "seed": self.seed,
            "upper_bounds": self.upper_bounds,
            "num_features": self.num_features,
            "categorical_features": list(self.categorical_features),
            "max_bin_by_feature": self.max_bin_by_feature,
        }

    @staticmethod
    def from_state(state: dict) -> "QuantileBinner":
        b = QuantileBinner(state["max_bin"], state["sample_count"],
                           state["seed"],
                           state.get("categorical_features") or (),
                           state.get("max_bin_by_feature"))
        b.upper_bounds = state["upper_bounds"]
        b.num_features = state["num_features"]
        return b

    def is_cat_mask(self) -> np.ndarray:
        """[F] bool mask of categorical features."""
        F = self.num_features or (
            self.upper_bounds.shape[0] if self.upper_bounds is not None else 0)
        m = np.zeros(F, dtype=bool)
        for i in self.categorical_features:
            if 0 <= i < F:
                m[i] = True
        return m
