"""Scatter-add GBDT histograms — the engine XLA CPU/GPU lowers well.

The one-hot MXU formulation in :mod:`.histogram` is the right shape for a
systolic array, but on backends with a real scatter-add unit (CPU SIMD,
GPU atomics) it pays for a dense ``[n, B]`` one-hot transient plus an
``[S, n] @ [n, B]`` contraction per feature — work that a bin-indexed
scatter does in ``O(n * S)``. This module is that formulation:

    hist[f, s, b] = sum_{r : binned[f, r] == b} stats[s, r]

built as a ``lax.scan`` over features, each step one flattened
``.at[seg].add`` scatter (``segment_sum`` shape) into the ``[B, S]``
accumulator. The fused node variant folds the row->frontier-node position
into the segment id (``seg = pos * B + bin``), so the ``[3W, n]``
masked-stats transient of the one-hot fallback never materializes either.

Numeric contract (shared by all engines, tested cross-engine): the count
channel is exact; grad/hess stats are rounded to bf16 on input — exactly
the rounding the one-hot engines apply — and accumulated in f32, so
engines agree to f32 accumulation-order tolerance. The int8 quantized
path accumulates in int32 and is exact.

Engine selection lives in :func:`.histogram.resolve_engine`; these
functions assume in-range bin ids (same contract as the other engines)
and are dispatched through the same ``histogram_cols``/``node_histogram``
entry points, so callers never import this module directly.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def hist_scatter(binned_t: jnp.ndarray, stats_t: jnp.ndarray,
                 num_bins: int, acc_dtype=jnp.float32) -> jnp.ndarray:
    """``[F, S, B]`` histogram via per-feature scatter-adds.

    binned_t: [F, n] bin ids (int32/int16/uint8 — widened per feature in
    registers, never in memory); stats_t: [S, n]. Stats are accumulated in
    ``acc_dtype`` (f32, or int32 for the quantized path); any bf16 input
    rounding has already been applied by the caller.
    """
    B = int(num_bins)
    data = jnp.transpose(stats_t).astype(acc_dtype)          # [n, S]

    def body(_, row):                                        # row: [n]
        seg = row.astype(jnp.int32)
        h = jnp.zeros((B, data.shape[1]), acc_dtype).at[seg].add(data)
        return _, jnp.transpose(h)                           # [S, B]

    _, out = lax.scan(body, None, binned_t)
    return out                                               # [F, S, B]


def node_hist_scatter(binned_t: jnp.ndarray, row_pos: jnp.ndarray,
                      base_t: jnp.ndarray, num_nodes: int, num_bins: int,
                      acc_dtype=jnp.float32) -> jnp.ndarray:
    """Fused per-frontier-node histograms ``[F, W*3, B]`` via scatter.

    Matches :func:`.histogram.node_histogram`'s channel layout
    (``out[f, w*3 + s, b]``). The frontier position rides inside the
    segment id (``pos * B + bin``); rows at finished leaves
    (``row_pos < 0``) scatter into a dropped overflow segment, so neither
    the ``[3W, n]`` masked stats nor any one-hot ever exists.
    """
    W = int(num_nodes)
    B = int(num_bins)
    data = jnp.transpose(base_t).astype(acc_dtype)           # [n, 3]
    valid = row_pos >= 0
    pos = jnp.where(valid, row_pos, 0).astype(jnp.int32)

    def body(_, row):                                        # row: [n]
        seg = jnp.where(valid, pos * B + row.astype(jnp.int32), W * B)
        h = jnp.zeros((W * B + 1, 3), acc_dtype).at[seg].add(data)
        return _, h[:W * B].reshape(W, B, 3)

    _, out = lax.scan(body, None, binned_t)                  # [F, W, B, 3]
    F = binned_t.shape[0]
    return jnp.transpose(out, (0, 1, 3, 2)).reshape(F, 3 * W, B)
