"""GBDT gradient histograms as MXU matmuls.

The reference delegates histogram building to LightGBM's C++ (CUDA/CPU) kernels
behind LGBM_BoosterUpdateOneIter (reference: lightgbm/TrainUtils.scala:246).
TPUs have no fast scatter-add, so the TPU-native formulation turns the
bin-scatter into dense one-hot contractions that run on the systolic array:

    hist[f, s, b] = sum_r stats[r, s] * (binned[r, f] == b)

i.e. per feature a ``[S, n] @ [n, B]`` matmul with the one-hot bin matrix.
Stats ride in bf16 (one-hot products are exact; values round at 2^-8 relative)
and accumulate in f32 on the MXU. Rows and features are chunked so the
transient one-hot stays within a fixed element budget, keeping HBM pressure
flat regardless of dataset size.

Under ``shard_map`` with rows sharded over the ``data`` mesh axis, callers
``psum`` the result — that single collective replaces the reference's entire
TCP ring all-reduce (LGBM_NetworkInit, TrainUtils.scala:496-512).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

# one-hot transient element budget per chunk (bf16 elements); ~64M ≈ 128 MB
_ONEHOT_BUDGET = 64 * 1024 * 1024


def _use_pallas() -> bool:
    import os
    if os.environ.get("MMLSPARK_TPU_DISABLE_PALLAS_HIST"):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def histogram(binned: jnp.ndarray, stats: jnp.ndarray, num_bins: int,
              stats_dtype=jnp.bfloat16) -> jnp.ndarray:
    """Compute ``[F, S, B]`` histogram of per-row stats over feature bins.

    binned: [n, F] int32 bin indices in [0, num_bins)
    stats:  [n, S] float stats (e.g. grad, hess, count-mask, possibly per-child)
    Returns [F, S, B] float32.

    On TPU this runs the fused Pallas kernel (one-hot never touches HBM);
    elsewhere the XLA one-hot-matmul formulation below.
    """
    n, F = binned.shape
    S = stats.shape[1]
    B = int(num_bins)
    if _use_pallas() and _pallas_fits(n, F, S, B):
        return _hist_pallas(binned, stats.astype(stats_dtype), B)
    stats = stats.astype(stats_dtype)

    # feature chunk size bounded by the one-hot budget for a full row pass
    fc = max(1, min(F, _ONEHOT_BUDGET // max(n * B, 1)))
    if fc >= 1 and n * B <= _ONEHOT_BUDGET:
        return _hist_feature_scan(binned, stats, B, fc)
    # rows too large for even one feature at a time: block rows too
    rows_per_block = max(1, _ONEHOT_BUDGET // B)
    # round to an MXU-friendly multiple
    rows_per_block = max(8, (rows_per_block // 1024) * 1024 or rows_per_block)
    return _hist_row_blocks(binned, stats, B, rows_per_block)


def _hist_feature_scan(binned, stats, B, fc):
    n, F = binned.shape
    S = stats.shape[1]
    n_chunks = -(-F // fc)
    Fp = n_chunks * fc
    binned_t = jnp.transpose(binned)  # [F, n]
    if Fp != F:
        binned_t = jnp.pad(binned_t, ((0, Fp - F), (0, 0)), constant_values=0)
    chunks = binned_t.reshape(n_chunks, fc, n)
    bins = jnp.arange(B, dtype=binned.dtype)

    def body(_, chunk):  # chunk [fc, n]
        oh = (chunk[:, :, None] == bins).astype(stats.dtype)  # [fc, n, B]
        h = jnp.einsum("ns,fnb->fsb", stats, oh,
                       preferred_element_type=jnp.float32)
        return _, h

    _, hists = lax.scan(body, None, chunks)  # [n_chunks, fc, S, B]
    return hists.reshape(Fp, S, B)[:F].astype(jnp.float32)


def _hist_row_blocks(binned, stats, B, rows_per_block):
    n, F = binned.shape
    S = stats.shape[1]
    nb = -(-n // rows_per_block)
    n_pad = nb * rows_per_block
    if n_pad != n:
        binned = jnp.pad(binned, ((0, n_pad - n), (0, 0)), constant_values=0)
        stats = jnp.pad(stats, ((0, n_pad - n), (0, 0)))  # zero stats: no effect
    binned_b = binned.reshape(nb, rows_per_block, F)
    stats_b = stats.reshape(nb, rows_per_block, S)
    bins = jnp.arange(B, dtype=binned.dtype)

    def body(acc, xs):
        bb, sb = xs  # [R, F], [R, S]

        def feat_body(_, fchunk):  # fchunk [1, R]
            oh = (fchunk[:, :, None] == bins).astype(sb.dtype)  # [1, R, B]
            return _, jnp.einsum("ns,fnb->fsb", sb, oh,
                                 preferred_element_type=jnp.float32)

        _, h = lax.scan(feat_body, None, jnp.transpose(bb)[:, None, :])
        return acc + h.reshape(F, S, B), None

    acc0 = jnp.zeros((F, S, B), dtype=jnp.float32)
    acc, _ = lax.scan(body, acc0, (binned_b, stats_b))
    return acc


# ---------------------------------------------------------------------------
# Pallas TPU kernel: the hot op of GBDT training.
#
# The XLA formulations above materialize the [n, B] one-hot (and the masked
# stats) in HBM, so at 1M rows x 255 bins they run bandwidth-bound at ~55 ms.
# The kernel below keeps the one-hot entirely in VMEM: grid (F, n/RB), each
# step builds a [RB, B] one-hot in registers/VMEM, feeds the MXU with a
# [S, RB] x [RB, B] contraction, and accumulates the [S, B] block in the
# output block that stays resident across the row-block axis (classic matmul
# accumulation pattern). Measured ~1.5 ms for the same shape — ~35x.
# ---------------------------------------------------------------------------

_PALLAS_VMEM_BUDGET = 10 * 1024 * 1024   # headroom under the 16 MB scoped
# vmem limit: the compiler's accounting adds dot outputs, copies and padding
# beyond the blocks modeled below (a 12 MB budget was observed to produce a
# 16.15 MB scoped allocation at S=96)


def _pick_row_block(n: int, F: int, S: int, B: int) -> int:
    """Largest row-block size whose resident VMEM fits the budget.

    VMEM model (matches ``_make_hist_kernel``): input blocks are
    double-buffered across grid steps (binned [F, RB] int32 and stats
    [Sp, RB] bf16); the [F, Sp, BP] f32 accumulator stays resident; the
    per-feature one-hot [RB, BP] bf16 is kernel scratch (single copy).
    """
    BP = -(-B // 128) * 128
    Sp = -(-max(S, 1) // 16) * 16
    for RB in (8192, 4096, 2048, 1024, 512):
        if RB > max(512, n):
            continue  # don't pad a small input up to a huge block
        binned_block = F * RB * 4
        stats_block = Sp * RB * 2
        out_block = F * Sp * BP * 4
        onehot = RB * BP * 2
        if 2 * (binned_block + stats_block) + out_block + onehot \
                <= _PALLAS_VMEM_BUDGET:
            return RB
    return 0


def _pallas_fits(n: int, F: int, S: int, B: int) -> bool:
    return _pick_row_block(n, F, S, B) > 0


def _make_hist_kernel(F: int, BP: int):
    def kernel(b_ref, s_ref, o_ref):
        j = pl.program_id(0)
        sb = s_ref[:, :]                            # [Sp, RB] bf16

        @pl.when(j == 0)
        def _():
            o_ref[...] = jnp.zeros_like(o_ref)

        def body(f, _):
            # sequential features: exactly one [RB, BP] one-hot live in VMEM
            row = b_ref[0, f, :]                    # [RB] int32
            bins = lax.broadcasted_iota(jnp.int32, (row.shape[0], BP), 1)
            oh = (row[:, None] == bins).astype(sb.dtype)  # VMEM-only
            h = lax.dot_general(sb, oh, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [Sp, BP]
            o_ref[f] += h
            return 0

        lax.fori_loop(0, F, body, 0)

    return kernel


def _hist_pallas(binned: jnp.ndarray, stats: jnp.ndarray,
                 num_bins: int) -> jnp.ndarray:
    n, F = binned.shape
    S = stats.shape[1]
    B = int(num_bins)
    BP = -(-B // 128) * 128                        # pad bins to lane multiple
    Sp = -(-S // 16) * 16                          # pad stats to sublane tile
    RB = _pick_row_block(n, F, S, B)
    n_pad = -(-max(n, RB) // RB) * RB
    if n_pad != n:
        # zero stats on padding rows: they contribute nothing to any bin
        binned = jnp.pad(binned, ((0, n_pad - n), (0, 0)), constant_values=0)
        stats = jnp.pad(stats, ((0, n_pad - n), (0, 0)))
    if Sp != S:
        stats = jnp.pad(stats, ((0, 0), (0, Sp - S)))
    nb = n_pad // RB
    # [nb, F, RB]: each grid step sees one row block of every feature.
    # stats transposed to [Sp, n]: rows ride the 128-lane axis, so a small
    # stat count doesn't waste lanes (and the dot contracts the lane axis).
    binned_b = jnp.transpose(binned.reshape(nb, RB, F), (0, 2, 1))
    stats_t = jnp.transpose(stats)

    out = pl.pallas_call(
        _make_hist_kernel(F, BP),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((1, F, RB), lambda j: (j, 0, 0)),
            pl.BlockSpec((Sp, RB), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((F, Sp, BP), lambda j: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, Sp, BP), jnp.float32),
    )(binned_b, stats_t)
    return out[:, :S, :B]

