"""GBDT gradient histograms — backend-adaptive engine dispatch.

The reference delegates histogram building to LightGBM's C++ (CUDA/CPU) kernels
behind LGBM_BoosterUpdateOneIter (reference: lightgbm/TrainUtils.scala:246).
Here ONE resolver (:func:`resolve_engine`, ``MMLSPARK_TPU_HIST_ENGINE``)
picks the formulation the current backend actually lowers well — all three
produce equal histograms through the same entry points (count channel
exact, grad/hess to f32 accumulation tolerance; docs/performance.md
"Histogram engine selection"):

  * ``pallas`` — the TPU kernels below (one-hot in VMEM, MXU contraction);
  * ``onehot`` — the XLA one-hot-matmul fallback below (MXU-shaped, used
    on TPU for shapes the kernel can't tile);
  * ``scatter`` — :mod:`.histogram_scatter`'s segment-sum scatter-adds
    (CPU/GPU: no ``[n, B]`` one-hot transient at all).

TPUs have no fast scatter-add, so the TPU-native formulation turns the
bin-scatter into dense one-hot contractions that run on the systolic array:

    hist[f, s, b] = sum_r stats[r, s] * (binned[r, f] == b)

i.e. per feature a ``[S, n] @ [n, B]`` matmul with the one-hot bin matrix.
Stats ride in bf16 (one-hot products are exact; values round at 2^-8 relative)
and accumulate in f32 on the MXU.

Layout: everything here is **column-major** — ``binned_t`` is ``[F, n]`` and
stats are ``[S, n]`` — so the Pallas grid slices the row axis (the 128-lane
axis) directly with no per-call transposes. Training materializes ``binned_t``
once; the per-level inputs are then tiny ([n] node positions + [3, n] stats).

``node_histogram`` is the fused training entry point: tree growth needs
``hist[f, w, s, b]`` for every frontier node ``w``; instead of materializing
the ``[3W, n]`` masked-stats matrix in HBM, the kernel rebuilds it per row
block in VMEM from the row->frontier-position vector and the shared
(grad, hess, count) stats.

Under ``shard_map`` with rows sharded over the ``data`` mesh axis, callers
``psum`` the result — that single collective replaces the reference's entire
TCP ring all-reduce (LGBM_NetworkInit, TrainUtils.scala:496-512).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .histogram_scatter import hist_scatter, node_hist_scatter

# one-hot transient element budget per chunk (bf16 elements); ~64M ≈ 128 MB
_ONEHOT_BUDGET = 64 * 1024 * 1024


def _interpret_mode() -> bool:
    return bool(os.environ.get("MMLSPARK_TPU_PALLAS_INTERPRET"))


def _on_tpu_device() -> bool:
    try:
        # device_kind, not just jax.default_backend(): TPU PJRT plugins may
        # register under a different platform name (e.g. a tunneled plugin)
        # while still lowering Pallas TPU kernels. default_backend() then
        # reports the plugin name and a name check would silently fall back
        # to the ~10x slower XLA one-hot path.
        if jax.default_backend() == "tpu":
            return True
        dev = jax.devices()[0]
        kind = f"{getattr(dev, 'device_kind', '')} {dev.platform}"
        return "tpu" in kind.lower()
    except Exception:
        return False


def _use_pallas() -> bool:
    if os.environ.get("MMLSPARK_TPU_DISABLE_PALLAS_HIST"):
        return False
    if _interpret_mode():
        # CI leg: run the real kernel logic through the Pallas interpreter
        # on CPU so packing/layout bugs surface without TPU hardware
        return True
    return _on_tpu_device()


# ---------------------------------------------------------------------------
# Engine resolution: pallas (TPU MXU kernel) / onehot (XLA one-hot matmul —
# the MXU-shaped fallback) / scatter (flattened segment-sum scatter-adds —
# what XLA CPU/GPU lowers well). One resolver, three engines, identical
# results through the same entry points (count channel exact, grad/hess to
# f32 accumulation tolerance) — so `growth.py` never cares which ran.
# ---------------------------------------------------------------------------

_ENGINES = ("pallas", "onehot", "scatter")

# measured-winner hint installed by the auto-tuner (mmlspark_tpu/tuning)
# before the train step's cache key is assembled; None = untuned. Module
# state, not an argument: resolve_engine() is consulted from inside
# traced program builders that cannot thread a hint through.
_TUNED_ENGINE: str = ""


def set_tuned_engine(engine: str = "") -> None:
    """Install (or clear, with ``""``) the tuner's measured engine winner
    consulted by ``auto``. An explicit ``MMLSPARK_TPU_HIST_ENGINE`` pin
    always beats the hint — that is the documented opt-out."""
    global _TUNED_ENGINE
    if engine and engine not in _ENGINES:
        raise ValueError(f"tuned engine must be one of {_ENGINES}, "
                         f"got {engine!r}")
    _TUNED_ENGINE = engine


def engine_candidates() -> tuple:
    """Engines worth measuring on this backend, static-rule choice first
    (calibration order; the tuner needs >= 2 to decide)."""
    if _use_pallas():
        return ("pallas", "onehot")
    return ("scatter", "onehot")


def resolve_engine() -> str:
    """Histogram engine for the current backend/env (before shape gates).

    ``MMLSPARK_TPU_HIST_ENGINE=pallas|onehot|scatter|auto`` (default auto):
    ``auto`` prefers the auto-tuner's measured winner when one is
    installed (:func:`set_tuned_engine` — see docs/performance.md
    §Auto-tuning), else picks ``pallas`` where the TPU kernel can lower
    (TPU device_kind, or ``MMLSPARK_TPU_PALLAS_INTERPRET``) and
    ``scatter`` elsewhere. An explicit ``pallas`` remains subject to
    ``MMLSPARK_TPU_DISABLE_PALLAS_HIST`` and hardware availability — where
    the kernel cannot lower, it degrades to the backend-appropriate engine
    instead of failing Mosaic compilation.
    """
    env = (os.environ.get("MMLSPARK_TPU_HIST_ENGINE") or "auto")
    env = env.strip().lower() or "auto"
    if env not in _ENGINES + ("auto",):
        raise ValueError(
            f"MMLSPARK_TPU_HIST_ENGINE must be one of "
            f"{('auto',) + _ENGINES}, got {env!r}")
    if env == "auto" and _TUNED_ENGINE:
        # measured hint: pallas is re-checked against lowerability (a
        # store tuned on TPU must not pick pallas on a CPU fallback box)
        if _TUNED_ENGINE != "pallas" or _use_pallas():
            return _TUNED_ENGINE
    if env in ("auto", "pallas"):
        if _use_pallas():
            return "pallas"
        return "onehot" if _on_tpu_device() else "scatter"
    return env


def _note_engine(engine: str) -> None:
    """hist_engine_selected_total{engine}: selections happen at trace time
    (engine choice is static per compiled program), so the counter tracks
    program builds, not per-batch executions."""
    try:
        from ..observability import metrics as _metrics
        _metrics.safe_counter("hist_engine_selected_total",
                              engine=engine).inc()
    except Exception:  # noqa: BLE001 — telemetry must not fail the kernel
        pass


def _select_engine(n: int, F: int, S: int, B: int, fused_w: int = 0,
                   quantized: bool = False) -> str:
    """Resolved engine with the Pallas shape gate applied: shapes the
    kernel cannot tile within the VMEM budget fall back to the one-hot
    matmul (the proven fallback on every backend)."""
    eng = resolve_engine()
    if eng == "pallas" and _pick_row_block(n, F, S, B, fused_w=fused_w,
                                           quantized=quantized) <= 0:
        eng = "onehot"
    _note_engine(eng)
    return eng


# ---------------------------------------------------------------------------
# Public entry points
# ---------------------------------------------------------------------------


def histogram(binned: jnp.ndarray, stats: jnp.ndarray, num_bins: int,
              stats_dtype=jnp.bfloat16) -> jnp.ndarray:
    """Row-major convenience wrapper: ``[n, F]`` bins + ``[n, S]`` stats.

    Transposes and delegates to :func:`histogram_cols`. Training code should
    use the column-major entry points directly and hoist the ``binned``
    transpose out of the per-level loop.
    """
    return histogram_cols(jnp.transpose(binned), jnp.transpose(stats),
                          num_bins, stats_dtype)


def histogram_cols(binned_t: jnp.ndarray, stats_t: jnp.ndarray, num_bins: int,
                   stats_dtype=jnp.bfloat16) -> jnp.ndarray:
    """Compute ``[F, S, B]`` histogram of per-row stats over feature bins.

    binned_t: [F, n] bin indices in [0, num_bins) — int32, int16 or uint8
        (narrow storage is widened per block in VMEM, never in HBM)
    stats_t:  [S, n] float stats (e.g. grad, hess, count-mask)
    Returns [F, S, B] float32.
    """
    F, n = binned_t.shape
    S = stats_t.shape[0]
    B = int(num_bins)
    # stats round to stats_dtype (bf16 default) on EVERY engine — scatter
    # included — so engine choice never changes the values being summed,
    # only the (f32) accumulation order
    stats_t = stats_t.astype(stats_dtype)
    eng = _select_engine(n, F, S, B)
    if eng == "pallas":
        return _hist_pallas(binned_t, stats_t, B)
    if eng == "scatter":
        return hist_scatter(binned_t, stats_t, B)
    return _hist_xla(binned_t, stats_t, B)


def quant_q_max(rows: int) -> float:
    """THE int8 quantization target for ``rows`` accumulated stats: shrinks
    below 127 once a histogram cell could overflow the int32 accumulator
    (q_max * rows must stay under 2^31). One definition shared by the
    plain path (rows = the local shard) and the deterministic blocked path
    (rows = rows-per-block) — if the accumulator ever widens, both paths
    move together or the bit-identity contract silently breaks."""
    return float(max(1, min(127, (2 ** 31 - 1) // max(int(rows), 1))))


def quantize_stats(base_t: jnp.ndarray, key=None, *, amax=None, q_max=None,
                   u=None):
    """Per-row-stat int8 quantization (LightGBM quantized training,
    use_quantized_grad): symmetric per-channel scale, stochastic rounding
    when a PRNG key is given (round-to-nearest otherwise). Returns
    (int8 stats [S, n], f32 scales [S]); dequantized histogram =
    int_hist * scale. int8 one-hot contractions run the MXU at 2x bf16
    throughput on v5e+.

    The quantization target shrinks below 127 for shards so large that a
    histogram cell could overflow the int32 accumulator (q_max * n must
    stay under 2^31): giant shards trade precision gracefully instead of
    wrapping negative.

    ``amax`` / ``q_max`` / ``u`` override the locally-derived scale
    maximum, accumulator bound and stochastic-rounding uniforms — the
    deterministic blocked-reduction path (growth.GrowConfig.hist_blocks)
    supplies GLOBAL values so every mesh topology quantizes each row
    identically."""
    n = base_t.shape[1]
    if q_max is None:
        q_max = quant_q_max(n)
    if amax is None:
        amax = jnp.max(jnp.abs(base_t), axis=1)
    scales = jnp.where(amax > 0, amax / q_max, 1.0)
    x = base_t / scales[:, None]
    if u is None and key is not None:
        u = jax.random.uniform(key, base_t.shape)
    q = jnp.floor(x + u) if u is not None else jnp.round(x)
    return jnp.clip(q, -q_max, q_max).astype(jnp.int8), scales


def node_histogram(binned_t: jnp.ndarray, row_pos: jnp.ndarray,
                   base_t: jnp.ndarray, num_nodes: int,
                   num_bins: int, scales=None) -> jnp.ndarray:
    """Per-frontier-node histograms in one fused pass: ``[F, W*3, B]``.

    binned_t: [F, n] int32/int16/uint8; row_pos: [n] int32 in [-1, W) — each row's
    position in the frontier (-1: row is at a finished leaf, contributes
    nothing); base_t: [3, n] f32 (grad*mask, hess*mask, mask).

    Channel layout matches ``stack([g*m_w, h*m_w, m_w for w])``:
    ``out[f, w*3 + s, b]`` is stat ``s`` of frontier node ``w``.

    On TPU the row->node one-hot and the masked stats never touch HBM: the
    Pallas kernel rebuilds them per row block in VMEM (the HBM inputs per
    level are just binned_t + [n] positions + [3, n] stats, vs the
    [3W, n] materialization the XLA fallback does).

    ``scales`` (with int8 ``base_t`` from :func:`quantize_stats`) switches to
    quantized-gradient histograms: int8 x int8 MXU contractions with int32
    accumulation (2x bf16 throughput on v5e+), dequantized on return.
    """
    F, n = binned_t.shape
    W = int(num_nodes)
    B = int(num_bins)
    quantized = scales is not None
    eng = _select_engine(n, F, 3 * W, B, fused_w=W, quantized=quantized)
    if eng == "pallas":
        out = _node_hist_pallas(binned_t, row_pos, base_t, W, B,
                                quantized=quantized)
    elif eng == "scatter":
        # the position rides inside the scatter segment id, so neither the
        # [3W, n] masked stats nor any [n, B] one-hot ever materializes.
        # Non-quantized stats round to bf16 first — the same input rounding
        # the one-hot engines apply — and accumulate in f32; int8 stats
        # accumulate exactly in int32 (the scatter mirror of the MXU path).
        if quantized:
            out = node_hist_scatter(binned_t, row_pos, base_t, W, B,
                                    acc_dtype=jnp.int32)
        else:
            out = node_hist_scatter(binned_t, row_pos,
                                    base_t.astype(jnp.bfloat16), W, B)
    else:
        woh = row_pos[None, :] == jnp.arange(W, dtype=row_pos.dtype)[:, None]
        if quantized:
            # exact int32 accumulation (the XLA mirror of the int8 MXU
            # path); operands stay int8 so the masked-stats and one-hot
            # transients cost half the bf16 path, not 2x
            sb = jnp.where(woh[:, None, :], base_t[None, :, :],
                           jnp.int8(0)).reshape(3 * W, n)
            out = _hist_xla(binned_t, sb, B, acc_dtype=jnp.int32)
        else:
            sb = jnp.where(woh[:, None, :], base_t[None, :, :], 0.0)
            return _hist_xla(binned_t,
                             sb.reshape(3 * W, n).astype(jnp.bfloat16), B)
    if quantized:
        chan_scale = scales[jnp.arange(3 * W) % 3]
        out = out.astype(jnp.float32) * chan_scale[None, :, None]
    return out


# ---------------------------------------------------------------------------
# XLA fallback formulations (CPU tests / shapes the kernel can't tile)
# ---------------------------------------------------------------------------


def _hist_xla(binned_t, stats_t, B, acc_dtype=jnp.float32):
    F, n = binned_t.shape
    # feature chunk size bounded by the one-hot budget for a full row pass
    fc = max(1, min(F, _ONEHOT_BUDGET // max(n * B, 1)))
    if n * B <= _ONEHOT_BUDGET:
        return _hist_feature_scan(binned_t, stats_t, B, fc, acc_dtype)
    # rows too large for even one feature at a time: block rows too
    rows_per_block = max(1, _ONEHOT_BUDGET // B)
    rows_per_block = max(8, (rows_per_block // 1024) * 1024 or rows_per_block)
    return _hist_row_blocks(binned_t, stats_t, B, rows_per_block, acc_dtype)


def _hist_feature_scan(binned_t, stats_t, B, fc, acc_dtype=jnp.float32):
    F, n = binned_t.shape
    S = stats_t.shape[0]
    n_chunks = -(-F // fc)
    Fp = n_chunks * fc
    if Fp != F:
        binned_t = jnp.pad(binned_t, ((0, Fp - F), (0, 0)), constant_values=0)
    chunks = binned_t.reshape(n_chunks, fc, n)
    bins = jnp.arange(B, dtype=binned_t.dtype)

    def body(_, chunk):  # chunk [fc, n]
        oh = (chunk[:, :, None] == bins).astype(stats_t.dtype)  # [fc, n, B]
        h = jnp.einsum("sn,fnb->fsb", stats_t, oh,
                       preferred_element_type=acc_dtype)
        return _, h

    _, hists = lax.scan(body, None, chunks)  # [n_chunks, fc, S, B]
    return hists.reshape(Fp, S, B)[:F].astype(acc_dtype)


def _hist_row_blocks(binned_t, stats_t, B, rows_per_block,
                     acc_dtype=jnp.float32):
    F, n = binned_t.shape
    S = stats_t.shape[0]
    nb = -(-n // rows_per_block)
    n_pad = nb * rows_per_block
    if n_pad != n:
        binned_t = jnp.pad(binned_t, ((0, 0), (0, n_pad - n)),
                           constant_values=0)
        stats_t = jnp.pad(stats_t, ((0, 0), (0, n_pad - n)))  # zero: no effect
    binned_b = binned_t.reshape(F, nb, rows_per_block)
    stats_b = stats_t.reshape(S, nb, rows_per_block)
    bins = jnp.arange(B, dtype=binned_t.dtype)

    def body(acc, xs):
        bb, sb = xs  # [F, R], [S, R]

        def feat_body(_, fchunk):  # fchunk [1, R]
            oh = (fchunk[:, :, None] == bins).astype(sb.dtype)  # [1, R, B]
            return _, jnp.einsum("sn,fnb->fsb", sb, oh,
                                 preferred_element_type=acc_dtype)

        _, h = lax.scan(feat_body, None, bb[:, None, :])
        return acc + h.reshape(F, S, B), None

    acc0 = jnp.zeros((F, S, B), dtype=acc_dtype)
    acc, _ = lax.scan(body, acc0,
                      (jnp.transpose(binned_b, (1, 0, 2)),
                       jnp.transpose(stats_b, (1, 0, 2))))
    return acc


# ---------------------------------------------------------------------------
# Pallas TPU kernels: the hot op of GBDT training.
#
# The XLA formulations above materialize the [n, B] one-hot (and the masked
# stats) in HBM, so at 1M rows x 255 bins they run bandwidth-bound at ~55 ms.
# The kernels below keep the one-hot entirely in VMEM: grid (n/RB,), each
# step builds a transposed [B, RB] one-hot in registers/VMEM per feature
# (bins on sublanes, rows on lanes — no relayout of the lane-major bin row),
# feeds the MXU with a lane-axis [S, RB] x [B, RB] contraction, and
# accumulates the [S, B] block in
# the output block that stays resident across the row-block axis (classic
# matmul accumulation pattern). Measured ~1.5 ms for the same shape — ~35x.
# ---------------------------------------------------------------------------

# v5e has 128 MB of VMEM; the compiler's default scoped-vmem limit is only
# 16 MB, which forces tiny row blocks (RB<=2048) once the unrolled feature
# loop keeps ~8 one-hot temporaries live — and the resulting 500-1000-step
# grids were measured 2x slower than roofline (per-step overhead). Both
# pallas_calls therefore request a raised limit and the block picker budgets
# against it (with headroom: the compiler's accounting adds dot outputs,
# copies and padding beyond the blocks modeled below — a 12 MB budget was
# observed to produce a 16.15 MB scoped allocation at S=96).
_PALLAS_VMEM_LIMIT = 100 * 1024 * 1024
_PALLAS_VMEM_BUDGET = 64 * 1024 * 1024
# v2/v3 cores have only 16 MiB of physical VMEM — the raised limit would fail
# Mosaic compilation outright there, so those generations keep the old
# conservative budget and the compiler's default scoped limit.
_SMALL_VMEM_BUDGET = 10 * 1024 * 1024


def _small_vmem_device() -> bool:
    try:
        kind = getattr(jax.devices()[0], "device_kind", "").lower()
    except Exception:
        return True
    return ("v2" in kind) or ("v3" in kind)


def _vmem_budget() -> int:
    if _interpret_mode():
        return _PALLAS_VMEM_BUDGET  # interpreter: no physical limit
    return _SMALL_VMEM_BUDGET if _small_vmem_device() else _PALLAS_VMEM_BUDGET


def _compiler_kwargs() -> dict:
    """Extra pallas_call kwargs: the raised scoped-vmem limit, where the
    runtime supports it (CompilerParams was TPUCompilerParams before
    jax 0.7; interpret mode and small-VMEM generations pass nothing)."""
    if _interpret_mode() or _small_vmem_device():
        return {}
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams",
                  getattr(pltpu, "TPUCompilerParams", None))
    if cls is None:
        return {}
    return dict(compiler_params=cls(vmem_limit_bytes=_PALLAS_VMEM_LIMIT))


def _bin_packing(B: int):
    """(BP, P): per-feature lane width and features packed per 128-lane dot.

    Small-bin configs (LightGBM's own GPU guidance recommends max_bin=63 on
    accelerators) would otherwise pad to 128 lanes and waste the MXU: with
    B <= 64 the kernel packs P = 128//BP features' one-hots side by side in
    one dot, cutting the unit-matmul count by P.
    """
    if B <= 64:
        BP = 1 << max(int(B - 1).bit_length(), 3)   # pow2, >= 8
        return BP, 128 // BP
    return -(-B // 128) * 128, 1


def _pick_row_block(n: int, F: int, S: int, B: int, fused_w: int = 0,
                    quantized: bool = False) -> int:
    """Largest row-block size whose resident VMEM fits the budget.

    VMEM model (matches the kernels): input blocks are double-buffered across
    grid steps (binned [Fp, RB] int32 and stats [Sp, RB] bf16 — or, fused,
    [8, RB] f32 base + [1, RB] i32 positions); the [Fp, Sp, BP] f32
    accumulator stays resident; kernel scratch is the packed transposed one-hot
    [max(BP,128), RB] bf16 plus, fused, the rebuilt [W, 3, RB] + [Sp, RB]
    masked stats. int8 (quantized) scratch is charged at 4 B/elem, not 1:
    Mosaic widens narrow-sublane int8 tiles internally, and the measured
    stack footprint tracks the 32-bit accounting (a 1 B model produced a
    16.8 MB scoped allocation against the 16 MB limit at W=31, B=63).

    When the feature loop is statically unrolled (groups <= the unroll cap),
    Mosaic software-pipelines the unrolled iterations and keeps ~8 one-hot
    temporaries live on the kernel stack at once — measured on v5e: 38.0 MB
    scoped at RB=8192 and 19.2 MB at RB=4096 for B=255/W<=16, i.e. ~8x the
    single-buffer model. Charge 8 one-hot buffers in that case so the chosen
    RB actually compiles on hardware.
    """
    BP, P = _bin_packing(B)
    Fp = -(-F // P) * P
    Sp = -(-max(S, 1) // 16) * 16
    elt = 4 if quantized else 2
    onehot_bufs = 8 if (Fp // P) <= _unroll_max() else 1
    for RB in (8192, 4096, 2048, 1024, 512):
        if RB > max(512, n):
            continue  # don't pad a small input up to a huge block
        binned_block = Fp * RB * 4
        if fused_w:
            in_blocks = binned_block + RB * 4 + 8 * RB * 4
            scratch = (onehot_bufs * RB * max(BP, 128) * elt
                       + 2 * (fused_w * 3 * RB * elt) + Sp * RB * elt)
        else:
            in_blocks = binned_block + Sp * RB * 2
            scratch = onehot_bufs * RB * max(BP, 128) * elt
        out_block = Fp * Sp * BP * 4
        if 2 * in_blocks + out_block + scratch <= _vmem_budget():
            return RB
    return 0


def _hist_dot_accumulate(o_ref, b_ref, sb, Fp: int, BP: int, P: int):
    """Shared inner loop: per step, pack P features' one-hots into one
    128-lane dot with the [Sp, RB] stats and accumulate the [Sp, BP] slices
    into their o_ref rows. int8 stats accumulate in int32 (the 2x-rate MXU
    path); bf16 in f32.

    The feature loop is a static Python unroll, NOT lax.fori_loop: the
    dynamically-indexed loop measured ~3-5 us of scalar-core overhead per
    step (flat in B and W — the kernel ran no faster at B=63 than B=255),
    dominating the whole pass at ~17 ms for F=28 x 1M rows. Unrolled,
    Mosaic schedules the slices statically. Above _UNROLL_MAX feature
    groups the loop stays dynamic so very wide datasets don't pay
    linear-in-F compile time/program size for a sub-us-per-step win.
    """
    acc = jnp.int32 if sb.dtype == jnp.int8 else jnp.float32

    groups = Fp // P
    if groups > _unroll_max():
        def body(g, _):
            _hist_group_dot(o_ref, b_ref, sb, g, BP, P, acc)
            return 0

        lax.fori_loop(0, groups, body, 0)
        return
    for g in range(groups):
        _hist_group_dot(o_ref, b_ref, sb, g, BP, P, acc)


_UNROLL_MAX = 128


def _unroll_max() -> int:
    """Unroll cap, overridable via MMLSPARK_TPU_HIST_UNROLL_MAX (0 keeps the
    dynamic fori_loop everywhere — the escape hatch if a Mosaic version
    compiles large unrolled kernels pathologically)."""
    v = os.environ.get("MMLSPARK_TPU_HIST_UNROLL_MAX", "").strip()
    if not v:
        return _UNROLL_MAX
    try:
        return int(v)
    except ValueError:
        raise ValueError(
            f"MMLSPARK_TPU_HIST_UNROLL_MAX must be an integer, got {v!r}"
        ) from None


def _hist_group_dot(o_ref, b_ref, sb, g, BP: int, P: int, acc):
    """One feature group: build P features' one-hots, dot, accumulate.

    The one-hot is built TRANSPOSED — bins on sublanes, rows staying on
    lanes — and contracted on the lane axis of both operands. The naive
    orientation (``row[:, None] == iota[RB, BP]``) forces a lane->sublane
    relayout of the [RB] bin row for every feature in every grid step;
    measured on v5e that relayout dominated the whole kernel — 2.4x slower
    per pass at 1M rows x 28 features x 255 bins, with pass time flat in
    both bin count and stats dtype (the signature of a non-MXU bottleneck).
    Removing it took the fused training step from 9.1 to 24.2 trees/sec.
    [Capture condition: builder-measured through the round-3 TPU tunnel
    (tools/tpu_microbench.py), best-of-2 under multi-second transport
    jitter; NOT yet corroborated by a driver BENCH artifact — see
    docs/performance.md "Provenance tags".]
    """
    if P == 1:
        # widen narrow bin storage (uint8/int16) per block, in VMEM only
        row = b_ref[g, :].astype(jnp.int32)         # [RB], rows on lanes
        bins = lax.broadcasted_iota(jnp.int32, (BP, row.shape[0]), 0)
        oht = (row[None, :] == bins).astype(sb.dtype)      # [BP, RB]
        h = lax.dot_general(sb, oht, (((1,), (1,)), ((), ())),
                            preferred_element_type=acc)
        o_ref[g] += h
    else:
        pieces = []
        for p in range(P):
            row = b_ref[g * P + p, :].astype(jnp.int32)
            bins = lax.broadcasted_iota(jnp.int32, (BP, row.shape[0]), 0)
            pieces.append((row[None, :] == bins).astype(sb.dtype))
        oht = jnp.concatenate(pieces, axis=0)       # [P*BP, RB] = 128 sublanes
        h = lax.dot_general(sb, oht, (((1,), (1,)), ((), ())),
                            preferred_element_type=acc)
        for p in range(P):
            o_ref[g * P + p] += h[:, p * BP:(p + 1) * BP]


def _make_hist_kernel(Fp: int, BP: int, P: int):
    def kernel(b_ref, s_ref, o_ref):
        j = pl.program_id(0)
        sb = s_ref[:, :]                            # [Sp, RB] bf16

        @pl.when(j == 0)
        def _():
            o_ref[...] = jnp.zeros_like(o_ref)

        _hist_dot_accumulate(o_ref, b_ref, sb, Fp, BP, P)

    return kernel


def _make_node_hist_kernel(Fp: int, W: int, Sp: int, BP: int, P: int,
                           quantized: bool = False):
    def kernel(b_ref, p_ref, base_ref, o_ref):
        j = pl.program_id(0)
        pos = p_ref[0, :]                           # [RB] int32
        if quantized:
            base = base_ref[0:3, :]                 # [3, RB] int8
            zero = jnp.int8(0)
        else:
            base = base_ref[0:3, :].astype(jnp.bfloat16)  # [3, RB]
            zero = jnp.bfloat16(0.0)
        woh = (lax.broadcasted_iota(jnp.int32, (W, pos.shape[0]), 0)
               == pos[None, :])                     # [W, RB] bool
        sb = jnp.where(woh[:, None, :], base[None, :, :],
                       zero).reshape(3 * W, pos.shape[0])
        if Sp != 3 * W:
            sb = jnp.pad(sb, ((0, Sp - 3 * W), (0, 0)))

        @pl.when(j == 0)
        def _():
            o_ref[...] = jnp.zeros_like(o_ref)

        _hist_dot_accumulate(o_ref, b_ref, sb, Fp, BP, P)

    return kernel


def _pad_rows_to(x, n_pad, fill=0):
    n = x.shape[-1]
    if n_pad == n:
        return x
    width = [(0, 0)] * (x.ndim - 1) + [(0, n_pad - n)]
    return jnp.pad(x, width, constant_values=fill)


def _pad_features_to(binned_t, Fp):
    F = binned_t.shape[0]
    if Fp == F:
        return binned_t
    # padding features bin everything to 0; their histogram rows are sliced
    # off the output
    return jnp.pad(binned_t, ((0, Fp - F), (0, 0)), constant_values=0)


def _hist_pallas(binned_t: jnp.ndarray, stats_t: jnp.ndarray,
                 num_bins: int) -> jnp.ndarray:
    F, n = binned_t.shape
    S = stats_t.shape[0]
    B = int(num_bins)
    BP, P = _bin_packing(B)
    Fp = -(-F // P) * P
    Sp = -(-S // 16) * 16                          # pad stats to sublane tile
    RB = _pick_row_block(n, F, S, B)
    n_pad = -(-max(n, RB) // RB) * RB
    # zero stats on padding rows: they contribute nothing to any bin
    binned_t = _pad_features_to(_pad_rows_to(binned_t, n_pad), Fp)
    stats_t = _pad_rows_to(stats_t, n_pad)
    if Sp != S:
        stats_t = jnp.pad(stats_t, ((0, Sp - S), (0, 0)))
    nb = n_pad // RB

    out = pl.pallas_call(
        _make_hist_kernel(Fp, BP, P),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((Fp, RB), lambda j: (0, j)),
            pl.BlockSpec((Sp, RB), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((Fp, Sp, BP), lambda j: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((Fp, Sp, BP), jnp.float32),
        interpret=_interpret_mode(),
        **_compiler_kwargs(),
    )(binned_t, stats_t)
    return out[:F, :S, :B]


def _node_hist_pallas(binned_t: jnp.ndarray, row_pos: jnp.ndarray,
                      base_t: jnp.ndarray, W: int, B: int,
                      quantized: bool = False) -> jnp.ndarray:
    F, n = binned_t.shape
    S = 3 * W
    BP, P = _bin_packing(B)
    Fp = -(-F // P) * P
    Sp = -(-S // 16) * 16
    RB = _pick_row_block(n, F, S, B, fused_w=W, quantized=quantized)
    n_pad = -(-max(n, RB) // RB) * RB
    binned_t = _pad_features_to(_pad_rows_to(binned_t, n_pad), Fp)
    # padding rows: position -1 matches no frontier node -> contribute nothing
    row_pos = _pad_rows_to(row_pos, n_pad, fill=-1)[None, :]
    # base rides [8, n] sublane-aligned (f32; int8 when quantized — Mosaic
    # relayouts the narrower sublane tile); rows 3..7 are dead padding
    base8 = jnp.pad(base_t, ((0, 5), (0, 0)))
    base8 = _pad_rows_to(base8, n_pad)
    nb = n_pad // RB
    out_dtype = jnp.int32 if quantized else jnp.float32

    out = pl.pallas_call(
        _make_node_hist_kernel(Fp, W, Sp, BP, P, quantized),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((Fp, RB), lambda j: (0, j)),
            pl.BlockSpec((1, RB), lambda j: (0, j)),
            pl.BlockSpec((8, RB), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((Fp, Sp, BP), lambda j: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((Fp, Sp, BP), out_dtype),
        interpret=_interpret_mode(),
        **_compiler_kwargs(),
    )(binned_t, row_pos, base8)
    return out[:F, :S, :B]
