"""MurmurHash3 (x86, 32-bit) — feature identity for the VW-style featurizer.

The reference exposes VW's murmur hash to the JVM for featurization
(reference: vw/VowpalWabbitMurmurWithPrefix.scala, JNI class
``VowpalWabbitMurmur``). Hashing defines feature identity, so the TPU build
implements the same public MurmurHash3_x86_32 algorithm (Austin Appleby,
public domain) in pure Python/numpy — host-side, cached per distinct string;
the training loop itself only ever sees integer indices.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Union

import numpy as np

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_MASK = 0xFFFFFFFF


def _native_lib():
    """Lazy handle to the compiled host runtime (None if unavailable)."""
    from ..native import get_lib
    return get_lib()


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _MASK


def murmur3_32(data: Union[bytes, str], seed: int = 0) -> int:
    """MurmurHash3_x86_32 of ``data`` with ``seed``; returns uint32.

    Uses the native C++ runtime when available (exact same algorithm — see
    native/mmlspark_native.cpp mm_murmur3_32); pure-Python otherwise."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    lib = _native_lib()
    if lib is not None:
        return int(lib.mm_murmur3_32(data, len(data), seed & _MASK))
    n = len(data)
    h = seed & _MASK
    nblocks = n // 4
    for i in range(nblocks):
        k = int.from_bytes(data[4 * i:4 * i + 4], "little")
        k = (k * _C1) & _MASK
        k = _rotl32(k, 15)
        k = (k * _C2) & _MASK
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & _MASK
    k = 0
    tail = data[nblocks * 4:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * _C1) & _MASK
        k = _rotl32(k, 15)
        k = (k * _C2) & _MASK
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK
    h ^= h >> 16
    return h


@lru_cache(maxsize=1 << 20)
def hash_namespace(name: str, seed: int = 0) -> int:
    """VW namespace seed: murmur of the namespace string with ``seed``
    (VW's --hash_seed, default 0 — the reference's hashSeed param)."""
    return murmur3_32(name, seed)


@lru_cache(maxsize=1 << 20)
def hash_feature(name: str, namespace_hash: int) -> int:
    """VW feature hash: numeric names index directly (offset by the namespace
    seed), everything else is murmur-hashed with the namespace seed."""
    if name.isdigit():
        return (int(name) + namespace_hash) & _MASK
    return murmur3_32(name, namespace_hash)


def mask_bits(h: Union[int, np.ndarray], num_bits: int):
    return h & ((1 << num_bits) - 1)
