"""CLI: ``python -m mmlspark_tpu.codegen [output_dir]`` — emit the generated
``mmlspark`` compat namespace, API reference, and smoke tests (the build-time
codegen step; reference: sbt packagePythonTask at build.sbt:204-247)."""

import sys

from ..observability.logging import console
from . import generate_all

if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else "python_api"
    result = generate_all(out)
    console(f"wrote {len(result['namespace_files'])} namespace modules, "
            f"{result['docs']}, {result['tests']}, "
            f"{result['migration']}")
