"""CLI for serving bundles.

    python -m mmlspark_tpu.bundles build \
        --model /models/churn.txt --out /models/churn.bundle \
        --max-batch 32
    python -m mmlspark_tpu.bundles inspect /models/churn.bundle

``build`` AOT-lowers the fused predict executables for every pow2
batch bucket the serving engines dispatch (override with
``--batch-sizes``), serializes them via ``jax.export``, and writes the
bundle atomically. ``inspect`` prints the manifest without touching
jax — safe on any machine.
"""

from __future__ import annotations

import argparse
import json


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="mmlspark_tpu.bundles")
    sub = p.add_subparsers(dest="cmd", required=True)

    b = sub.add_parser("build", help="AOT-build a serving bundle")
    b.add_argument("--model", required=True,
                   help="saved pipeline dir, LightGBM .txt model, or "
                        "native .npz booster (.npz keeps the binner "
                        "grid the int8 lane needs)")
    b.add_argument("--out", required=True, help="bundle directory to write")
    b.add_argument("--batch-sizes", default=None,
                   help="comma-separated batch sizes (default: the pow2 "
                        "ladder up to --max-batch — the only shapes the "
                        "serving engines dispatch)")
    b.add_argument("--max-batch", type=int, default=32,
                   help="serving batch cap the pow2 ladder runs to "
                        "(match the worker's --max-batch)")
    b.add_argument("--num-iterations", default="-1",
                   help="comma-separated num_iteration values to bundle "
                        "(-1 = the full model)")
    b.add_argument("--include-raw", action="store_true",
                   help="also bundle the untransformed predict_raw "
                        "executables")
    b.add_argument("--predict-dtypes", default="f32",
                   help="comma-separated predict lanes to bundle "
                        "(f32,bf16,int8; default f32) — match the "
                        "fleet's MMLSPARK_TPU_PREDICT_DTYPE so the "
                        "quantized executables warm-start too")
    b.add_argument("--tuned-from", default=None, metavar="STORE_DIR",
                   help="tuning store directory (MMLSPARK_TPU_TUNING_DIR "
                        "of a measured deployment): bakes the measured "
                        "bucket ladder into the enumeration next to the "
                        "pow2 grid and stamps tuning provenance into the "
                        "manifest (docs/performance.md §Auto-tuning)")
    b.add_argument("--force", action="store_true",
                   help="replace an existing bundle directory")

    i = sub.add_parser("inspect", help="print a bundle's manifest")
    i.add_argument("bundle", help="bundle directory")

    args = p.parse_args(argv)

    from ..observability.logging import console
    if args.cmd == "inspect":
        from .bundle import read_manifest
        # console, not the JSON log funnel: CLI output parsed by humans
        # and scripts, like the serving_main ready-line
        console(json.dumps(read_manifest(args.bundle), indent=2,
                           sort_keys=True))
        return 0

    from .bundle import build_bundle
    if getattr(args, "tuned_from", None):
        from .. import tuning as _tuning
        # point the tuner at the measured store BEFORE the enumeration
        # runs; the model hash joins the fingerprint check so a store
        # measured against a different model degrades loudly
        from .bundle import model_hash
        _tuning.configure(store_dir=args.tuned_from,
                          model_sha256=model_hash(args.model))
    batch_sizes = None
    if args.batch_sizes:
        batch_sizes = [int(x) for x in args.batch_sizes.split(",") if x]
    num_iterations = tuple(
        int(x) for x in args.num_iterations.split(",") if x)
    predict_dtypes = tuple(
        x.strip() for x in args.predict_dtypes.split(",") if x.strip())
    manifest = build_bundle(
        args.model, args.out, batch_sizes=batch_sizes,
        max_batch=args.max_batch, num_iterations=num_iterations,
        include_raw=args.include_raw, predict_dtypes=predict_dtypes,
        force=args.force)
    console(f"bundle written: {args.out} "
            f"({len(manifest['entries'])} programs, "
            f"fingerprint {manifest['fingerprint']['backend']}/"
            f"{manifest['fingerprint']['device_kind']})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
