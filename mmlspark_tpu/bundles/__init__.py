"""Serving bundles: offline AOT build + online prewarm (ROADMAP item 4).

``python -m mmlspark_tpu.bundles build --model m.txt --out m.bundle``
writes an atomic, versioned, checksummed directory of ``jax.export``-
serialized fused predict executables; ``serving_main --bundle`` (or
``MMLSPARK_TPU_BUNDLE_DIR``) prewarms a worker's predictor cache from
it before the worker binds or registers — a warm-bundle restart serves
its first request with zero compile events in the flight ring. See
``docs/serving.md`` ("Serving bundles & cold start").
"""

from .bundle import (BundleError, FORMAT_VERSION, MANIFEST_NAME,
                     boosters_of, build_bundle, load_model_boosters,
                     model_hash, prewarm, read_manifest,
                     runtime_fingerprint)

__all__ = ["BundleError", "FORMAT_VERSION", "MANIFEST_NAME", "boosters_of",
           "build_bundle", "load_model_boosters", "model_hash", "prewarm",
           "read_manifest", "runtime_fingerprint"]
