"""AOT serving bundles: zero-compile fleet cold start.

A serving worker restart pays XLA compiles before its first reply —
fatal for a fleet rolling thousands of workers under live load (ROADMAP
item 4). The fix is the reference framework's own premise turned up one
level: ship pre-BUILT artifacts onto the cluster, where "built" now
means *whole-program AOT-lowered*, not source — the fused predict
executables the ``_PREDICT_CACHE`` machinery (models/gbdt/booster.py)
lazily compiles online are exactly the artifact to serialize offline.

Offline half (``build_bundle`` / ``python -m mmlspark_tpu.bundles
build``): load the model, enumerate the predictor cache keys its pow2
batch/tree buckets dispatch to (``Booster.predict_plan`` — the SAME
key computation the serving hot path uses, so offline and online can
never disagree), AOT-lower each program through the placement funnel,
serialize via ``jax.export``, and write an atomic, versioned,
checksummed bundle directory. The bundle also carries a populated
persistent-compile-cache dir (``xla_cache/``, the PR 4 funnel) so even
the deserialize-then-compile step at load time is a disk fetch where
the backend supports it.

Online half (``prewarm`` — wired into ``serving_main --bundle`` /
``MMLSPARK_TPU_BUNDLE_DIR``): verify the manifest + per-file checksums
+ runtime fingerprint, deserialize and compile every entry, and install
the finished programs into ``_PREDICT_CACHE`` **before the worker
binds**. The first request then takes the cache-hit path: zero compile
events in the flight ring, readiness gated on ``/healthz`` until the
prewarm completes.

A fingerprint mismatch (different jax/XLA, backend, device kind, or
model bytes) is a LOUD structured warning plus fallback to online JIT
— never a silent load that could serve wrong numerics: the executables
are only ever installed under keys recomputed from the live model, so
a stale bundle cannot be consulted for a model it wasn't built from.

Only this package may touch ``jax.export`` (graftlint
``bundle-io-funnel``): deserializing executables is an IO boundary with
version-skew and integrity concerns that must stay behind one door.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional, Tuple

from ..observability import flight as _flight
from ..observability import hbm as _hbm
from ..observability import metrics as _metrics
from ..observability.logging import get_logger
from ..utils import compile_cache as _compile_cache

logger = get_logger("mmlspark_tpu.bundles")

FORMAT_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"
PROGRAMS_DIR = "programs"
XLA_CACHE_DIR = "xla_cache"


class BundleError(Exception):
    """A bundle that cannot be used (missing, torn, or mismatched).

    Raised by the offline/strict paths; the serving prewarm path catches
    it and degrades to online JIT with the structured warning instead —
    a bad bundle must never keep a worker from coming up."""


# ---------------------------------------------------------------------------
# Hashing / fingerprint
# ---------------------------------------------------------------------------


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def model_hash(model_path: str) -> str:
    """Content hash of a model artifact: file bytes for a ``.txt``
    booster, a stable digest over (relpath, file-sha) pairs for a saved
    pipeline directory. The bundle pins this so a bundle built from one
    model can never prewarm a different one."""
    if os.path.isdir(model_path):
        h = hashlib.sha256()
        for root, dirs, files in os.walk(model_path):
            dirs.sort()
            for name in sorted(files):
                p = os.path.join(root, name)
                rel = os.path.relpath(p, model_path).replace(os.sep, "/")
                h.update(rel.encode("utf-8"))
                h.update(_sha256_file(p).encode("ascii"))
        return h.hexdigest()
    return _sha256_file(model_path)


def runtime_fingerprint() -> Dict[str, Any]:
    """What must match between bundle build and bundle load for the
    serialized executables to be trusted: jax/XLA version, resolved
    backend platform (the placement funnel's decision input), and the
    device kind. Captured AFTER the placement funnel resolves the
    backend, so the fingerprint records what the programs were actually
    lowered for."""
    import jax

    from .. import __version__
    from ..parallel import placement

    # resolve placement exactly the way the online predict path does —
    # the funnel's backend decision is part of what the bundle pins
    placement.plan_for("gbdt.predict", replicate=True)
    devices = jax.devices()
    return {
        "framework_version": __version__,
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "device_kind": devices[0].device_kind if devices else None,
    }


def _fingerprint_mismatches(built: Dict[str, Any],
                            now: Dict[str, Any]) -> List[str]:
    return [f"{k}: built={built.get(k)!r} runtime={now.get(k)!r}"
            for k in sorted(set(built) | set(now))
            if built.get(k) != now.get(k)]


# ---------------------------------------------------------------------------
# Model loading (shared by the build CLI and the serving prewarm)
# ---------------------------------------------------------------------------


def boosters_of(model: Any) -> List[Any]:
    """Every :class:`Booster` an in-memory model object dispatches
    predictions through, in a stable order: the booster itself, or the
    ``.booster`` of each fitted GBDT stage of a pipeline. The bundle
    indexes entries by position in this list. Callers that already hold
    the loaded model (the serving worker) pass this to :func:`prewarm`
    so the model text is never parsed twice on the startup path."""
    from ..models.gbdt.booster import Booster

    if isinstance(model, Booster):
        return [model]
    out = []
    stages = getattr(model, "stages", None) or [model]
    for stage in stages:
        b = getattr(stage, "booster", None)
        if isinstance(b, Booster):
            out.append(b)
    return out


def load_model_boosters(model_path: str) -> List[Any]:
    """:func:`boosters_of` for a model still on disk: the booster itself
    for a ``.txt`` native model, the fitted GBDT stages of a saved
    pipeline directory."""
    from ..models.gbdt.booster import Booster

    if model_path.endswith(".txt"):
        with open(model_path) as f:
            return [Booster.from_string(f.read())]
    if model_path.endswith(".npz"):
        # native persistence keeps the full binner grid (a LightGBM
        # .txt roundtrip loses it), so this is the format that can
        # bundle the int8 predict lane without degrading to f32
        return [Booster.load(model_path)]
    from ..core.pipeline import load_stage
    return boosters_of(load_stage(model_path))


def _default_batch_sizes(max_batch: int) -> List[int]:
    """The ladder serving actually dispatches: both engines bucket
    micro-batches up to the batch cap (``bucket_size`` /
    ``SlotTable.bucket_view``), so these are the only batch shapes a
    warmed worker will ever look up. Unions the pow2 grid with the
    auto-tuner's measured rungs when a tuning store is wired
    (``bundles build --tuned-from <store>``) — a worker serving a tuned
    ladder must find its rung-shaped executables prewarmed, and the
    pow2 grid stays in because out-of-distribution batches fall back
    to it (``tuning.ladder_pad``)."""
    from .. import tuning as _tuning

    sizes, b = [], 1
    while b < max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch)
    ladder = _tuning.resolve_bucket_ladder() or ()
    sizes.extend(r for r in ladder if r <= max_batch)
    return sorted(set(sizes))


# ---------------------------------------------------------------------------
# Build (offline)
# ---------------------------------------------------------------------------


def build_bundle(model_path: str, out_dir: str,
                 batch_sizes: Optional[List[int]] = None,
                 max_batch: int = 32,
                 num_iterations: Tuple[int, ...] = (-1,),
                 include_raw: bool = False,
                 predict_dtypes: Tuple[str, ...] = ("f32",),
                 force: bool = False) -> Dict[str, Any]:
    """AOT-lower and serialize every fused predict executable a serving
    deployment of ``model_path`` will dispatch to; write an atomic,
    versioned, checksummed bundle directory. Returns the manifest.

    ``predict_dtypes`` adds quantized predict lanes to the enumeration
    (``"bf16"``/``"int8"`` next to ``"f32"``): the lane rides the SAME
    plan/key machinery, so a fleet pinned to
    ``MMLSPARK_TPU_PREDICT_DTYPE=int8`` warm-starts its quantized
    executables exactly like f32 ones. Lanes the model degrades
    (``quantize.resolve_predict_dtype``) dedupe into their f32 plans.

    The bundle is built in a sibling temp directory and renamed into
    place, so a crashed build never leaves a half-written bundle where
    a prewarm could find it."""
    import jax
    from jax import export as jax_export

    t0 = time.perf_counter()
    boosters = load_model_boosters(model_path)
    if not boosters:
        raise BundleError(f"no boosters found in model {model_path!r} — "
                          "nothing to bundle")
    if batch_sizes is None:
        batch_sizes = _default_batch_sizes(max_batch)
    out_dir = os.path.abspath(out_dir)
    if os.path.exists(out_dir) and not force:
        raise BundleError(f"bundle dir {out_dir} already exists "
                          "(pass force=True / --force to replace)")
    tmp = f"{out_dir}.tmp-{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(os.path.join(tmp, PROGRAMS_DIR))
    xla_cache = os.path.join(tmp, XLA_CACHE_DIR)
    os.makedirs(xla_cache)
    # wire the persistent compile cache at the bundle's own xla_cache
    # (env knob wins when set): the AOT compiles below populate it, so
    # a prewarming worker's deserialize-then-compile step becomes a
    # disk fetch on backends with persistent-cache support. ensure() is
    # first-call-wins per process — a warm process (in-process build
    # after training) may already have locked a different dir, in which
    # case the shipped xla_cache stays EMPTY and prewarm pays real XLA
    # compiles: say so loudly rather than ship a silently-hollow cache
    active = _compile_cache.ensure(xla_cache)
    if active != xla_cache:
        logger.warning(
            "bundle xla_cache not populated: the process compile cache "
            "was already wired to %r (first-call-wins) — prewarming "
            "workers will recompile from StableHLO; build bundles in a "
            "fresh process (the CLI) for a warm shipped cache", active,
            bundle=out_dir)
        _flight.record("bundle", event="xla_cache_not_populated",
                       bundle=out_dir, active=active or "")

    from ..models.gbdt.booster import iter_predict_plans

    entries: List[Dict[str, Any]] = []
    transforms = (True, False) if include_raw else (True,)
    seen_keys = set()
    for bi, booster in enumerate(boosters):
        # THE enumeration lives in booster.iter_predict_plans — shared
        # with predict_key_manifest so bundle and manifest cannot drift.
        # Dedup spans boosters too: keys are model-INDEPENDENT (trees
        # ride as arguments), so two same-shape pipeline stages share
        # one executable — exporting twice would overwrite the same
        # {key_hash}.jaxexp file and waste a duplicate AOT compile
        for meta, plan in iter_predict_plans(booster, batch_sizes,
                                             num_iterations, transforms,
                                             dtypes=tuple(predict_dtypes)):
            if plan.key in seen_keys:
                continue
            seen_keys.add(plan.key)
            entries.append(_export_entry(
                jax_export, booster, plan, tmp, booster_index=bi, **meta))
    manifest = {
        "format_version": FORMAT_VERSION,
        "created_at": time.time(),
        "model": {"path": os.path.abspath(model_path),
                  "sha256": model_hash(model_path),
                  "boosters": len(boosters)},
        "fingerprint": runtime_fingerprint(),
        "jax_export_platforms": sorted(
            {p for e in entries for p in e.pop("_platforms")}),
        "entries": entries,
    }
    # tuning provenance: which measured decisions shaped this bundle's
    # enumeration (the ladder above) — inspect/compare tooling can tell a
    # tuner flip from a model change
    from .. import tuning as _tuning
    if _tuning.enabled():
        manifest["tuning"] = _tuning.provenance()
    with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    if os.path.exists(out_dir):          # force=True: replace atomically-ish
        shutil.rmtree(out_dir)
    os.rename(tmp, out_dir)
    dt = time.perf_counter() - t0
    _metrics.safe_histogram("bundle_build_seconds").observe(dt)
    _flight.record("bundle", event="built", path=out_dir,
                   entries=len(entries), seconds=round(dt, 3))
    logger.info("bundle built", path=out_dir, entries=len(entries),
                seconds=round(dt, 3))
    return manifest


def _export_entry(jax_export, booster, plan, tmp_dir: str, **meta
                  ) -> Dict[str, Any]:
    """AOT-lower one plan's program (through the placement funnel — the
    builder already resolves placement in ``runtime_fingerprint``) and
    serialize it via ``jax.export`` under its key hash."""
    from ..models.gbdt.booster import predict_key_hash

    args = booster.predict_plan_args(plan)
    exported = jax_export.export(plan.builder())(*args)
    blob = bytes(exported.serialize())
    # warm the persistent compile cache with the real XLA compile while
    # we are here: exactly what a prewarming worker will re-run
    import jax
    jax.jit(exported.call).lower(*args).compile()
    key_hash = predict_key_hash(plan.key)
    fname = f"{key_hash}.jaxexp"
    with open(os.path.join(tmp_dir, PROGRAMS_DIR, fname), "wb") as f:
        f.write(blob)
    return {
        **meta,
        "n_pad": plan.n_pad,
        "t_pad": plan.T_pad,
        "key_hash": key_hash,
        "file": f"{PROGRAMS_DIR}/{fname}",
        "sha256": hashlib.sha256(blob).hexdigest(),
        "size_bytes": len(blob),
        "_platforms": list(exported.platforms),
    }


# ---------------------------------------------------------------------------
# Load / prewarm (online)
# ---------------------------------------------------------------------------


def read_manifest(bundle_dir: str) -> Dict[str, Any]:
    """Parse + structurally validate a bundle's manifest (no program
    deserialization). Raises :class:`BundleError` on anything torn."""
    path = os.path.join(bundle_dir, MANIFEST_NAME)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise BundleError(f"unreadable bundle manifest {path}: "
                          f"{type(e).__name__}: {e}") from e
    if not isinstance(manifest, dict) or "entries" not in manifest \
            or "fingerprint" not in manifest:
        raise BundleError(f"malformed bundle manifest {path}")
    if manifest.get("format_version") != FORMAT_VERSION:
        raise BundleError(
            f"bundle format_version {manifest.get('format_version')!r} "
            f"(this build reads {FORMAT_VERSION})")
    return manifest


def _count_load(status: str) -> None:
    _metrics.safe_counter("bundle_loads_total", status=status).inc()


def _warn_fallback(bundle_dir: str, status: str, **fields) -> None:
    """THE loud structured degradation: one warning record + one flight
    event + the status-labeled counter — and the caller falls back to
    online JIT. Wrong numerics are impossible by construction (programs
    install only under keys recomputed from the live model), so the
    failure mode of a bad bundle is cold-start latency, surfaced here."""
    logger.warning("serving bundle unusable, falling back to JIT "
                   "compilation: %s", status, bundle=bundle_dir,
                   status=status, **fields)
    _flight.record("bundle", event=status, bundle=bundle_dir, **fields)
    _count_load(status)


def prewarm(model_path: str, bundle_dir: str,
            boosters: Optional[List[Any]] = None) -> Dict[str, Any]:
    """Populate ``_PREDICT_CACHE`` from a bundle before a worker binds.

    Returns stats ``{status, entries_loaded, entries_skipped, seconds}``.
    Degrades (never raises) on any defect: missing/torn bundle, version
    or fingerprint skew, checksum mismatch, per-entry deserialization
    failure — each a structured warning + ``bundle_*`` telemetry, with
    the worker falling back to online JIT for the affected programs.

    ``boosters`` lets the caller pass the already-loaded model (the
    serving worker has it); otherwise the model loads from
    ``model_path``. Keys are recomputed from THAT model, so a bundle
    built from different model bytes cannot install anything even
    before the fingerprint check rejects it.
    """
    t0 = time.perf_counter()
    stats = {"status": "ok", "entries_loaded": 0, "entries_skipped": 0}
    _flight.record("bundle", event="prewarm_begin", bundle=bundle_dir)
    try:
        manifest = read_manifest(bundle_dir)
    except BundleError as e:
        _warn_fallback(bundle_dir, "corrupt", error=str(e))
        stats["status"] = "corrupt"
        return _finish(stats, t0)

    # the bundle's shipped xla_cache joins the persistent-cache funnel
    # (only when the operator hasn't pointed the env knob elsewhere, and
    # only if writable — jax appends new entries to the active dir)
    xla_cache = os.path.join(bundle_dir, XLA_CACHE_DIR)
    if os.path.isdir(xla_cache) and os.access(xla_cache, os.W_OK):
        _compile_cache.ensure(xla_cache)
    else:
        _compile_cache.ensure()

    fp_now = runtime_fingerprint()
    mismatches = _fingerprint_mismatches(manifest["fingerprint"], fp_now)
    mh = model_hash(model_path) if os.path.exists(model_path) else None
    if mh is not None and mh != manifest.get("model", {}).get("sha256"):
        mismatches.append(
            f"model_sha256: built={manifest.get('model', {}).get('sha256')!r}"
            f" runtime={mh!r}")
    if mismatches:
        _warn_fallback(bundle_dir, "fingerprint_mismatch",
                       mismatches=mismatches)
        stats["status"] = "fingerprint_mismatch"
        return _finish(stats, t0)

    if boosters is None:
        boosters = load_model_boosters(model_path)
    loaded = skipped = 0
    for entry in manifest["entries"]:
        if _load_entry(bundle_dir, entry, boosters):
            loaded += 1
        else:
            skipped += 1
    stats.update(entries_loaded=loaded, entries_skipped=skipped)
    if loaded == 0 and manifest["entries"]:
        stats["status"] = "empty"
        _warn_fallback(bundle_dir, "empty",
                       entries=len(manifest["entries"]))
    else:
        _count_load("ok")
    return _finish(stats, t0)


def _finish(stats: Dict[str, Any], t0: float) -> Dict[str, Any]:
    stats["seconds"] = round(time.perf_counter() - t0, 3)
    _metrics.safe_histogram("bundle_prewarm_seconds").observe(
        stats["seconds"])
    _flight.record("bundle", event="prewarm_complete", **stats)
    logger.info("bundle prewarm complete", **stats)
    return stats


def _load_entry(bundle_dir: str, entry: Dict[str, Any],
                boosters: List[Any]) -> bool:
    """Deserialize + AOT-compile one manifest entry and install it in
    the predictor cache. False (with telemetry) on any defect — the
    affected bucket falls back to online JIT, nothing else."""
    import jax
    from jax import export as jax_export

    from ..models.gbdt.booster import (predict_key_hash,
                                       preload_predict_program)

    def skip(reason: str, **fields) -> bool:
        _metrics.safe_counter("bundle_entries_skipped_total",
                              reason=reason).inc()
        _flight.record("bundle", event="entry_skipped", reason=reason,
                       key_hash=entry.get("key_hash", ""), **fields)
        logger.warning("bundle entry skipped: %s", reason,
                       key_hash=entry.get("key_hash", ""), **fields)
        return False

    try:
        bi = int(entry.get("booster_index", 0))
        batch_size = int(entry["batch_size"])
        num_iteration = int(entry["num_iteration"])
        transformed = bool(entry["transformed"])
        # pre-dtype bundles carry no lane field: f32, the only lane
        # their builds could enumerate
        predict_dtype = str(entry.get("predict_dtype", "f32"))
        entry["file"], entry["sha256"]
    except (KeyError, TypeError, ValueError) as e:
        # a structurally bad entry (hand-edited bundle, torn build)
        # degrades like every other defect — prewarm NEVER raises
        return skip("malformed_entry", error=f"{type(e).__name__}: {e}")
    if not 0 <= bi < len(boosters):
        return skip("booster_index_out_of_range", booster_index=bi)
    booster = boosters[bi]
    try:
        plan = booster.predict_plan(batch_size, num_iteration,
                                    transformed=transformed,
                                    predict_dtype=predict_dtype)
    except ValueError as e:
        # an unknown lane name in a (newer-format) manifest degrades
        # like any other per-entry defect
        return skip("malformed_entry", error=f"{type(e).__name__}: {e}")
    key_hash = predict_key_hash(plan.key)
    if key_hash != entry.get("key_hash"):
        # the live model computes a different key than the build did —
        # a key miss, not a corruption: count it distinctly so rollouts
        # can see bundles drifting from the models they front
        _metrics.safe_counter("bundle_key_miss_total").inc()
        return skip("key_mismatch", expected=entry.get("key_hash", ""),
                    computed=key_hash)
    root = os.path.abspath(bundle_dir)
    path = os.path.normpath(
        os.path.join(root, *entry["file"].split("/")))
    if not path.startswith(root + os.sep):
        # a crafted manifest must not walk the checksum/deserialize
        # pipeline out of the bundle directory
        return skip("path_escape", file=entry["file"])
    try:
        # one read serves both the checksum and the deserialize — the
        # in-memory hash also closes the hash-then-reread TOCTOU window
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        return skip("missing_program", error=f"{type(e).__name__}: {e}")
    if hashlib.sha256(blob).hexdigest() != entry.get("sha256"):
        return skip("checksum_mismatch", file=entry["file"])
    try:
        exported = jax_export.deserialize(bytearray(blob))
        args = booster.predict_plan_args(plan)
        compiled = jax.jit(exported.call).lower(*args).compile()
    except Exception as e:  # noqa: BLE001 — any skew degrades to JIT
        return skip("deserialize_failed", error=f"{type(e).__name__}: {e}")
    if not preload_predict_program(plan.key, compiled,
                                   dtype=plan.predict_dtype):
        return skip("already_cached")
    # HBM-ledger claim: the deserialized program's device footprint is
    # opaque pre-execution, so the ledger carries the artifact size — a
    # stable lower bound that still shows prewarm residency per site
    _hbm.claim("bundle_prewarm", float(len(blob)))
    _metrics.safe_counter("bundle_entries_loaded_total").inc()
    _flight.record("bundle", event="entry_loaded", key_hash=key_hash,
                   batch_size=batch_size, n_pad=plan.n_pad,
                   t_pad=plan.T_pad, predict_dtype=plan.predict_dtype)
    return True
