"""Resilience plane: fault injection + the policy the stack degrades through.

Two halves, consumed by ``io/serving.py``, ``io/distributed_serving.py``,
``io/http.py``, ``io/prefetch.py``, ``models/gbdt/booster.py`` and
``parallel/distributed.py``:

- :mod:`.failpoints` — seeded, rule-driven fault injection
  (``MMLSPARK_TPU_FAILPOINTS=site:kind[:arg][@N]``): named sites across
  the edge→gateway→worker path, training rounds, streaming, and
  barriers inject synthetic errors, latency, crashes, or hard process
  exits deterministically, each fired fault counted and flight-logged
  so chaos runs replay from the ring. Byte-identical no-op when no
  rules are set.
- :mod:`.policy` — the resilience policy those paths degrade through:
  deadline-budgeted retries (full-jitter backoff honoring both RFC 9110
  Retry-After forms), token-bucket retry budgets, per-worker circuit
  breakers (half-open probes ride the gateway health loop),
  ``X-Deadline-Ms`` propagation attenuated per hop, and the shared
  Retry-After math for 429/503/504 responses.

See docs/robustness.md for the rule grammar, env knobs, drain
semantics, and the chaos-run recipe.
"""

from . import failpoints, policy  # noqa: F401
from .failpoints import (FaultAction, InjectedFault, SITES,  # noqa: F401
                         fault_point)
from .policy import (BreakerBoard, BreakerConfig, CircuitBreaker,  # noqa: F401
                     DEADLINE_HEADER, Deadline, RetryBudget, RetryPolicy,
                     backoff, backoff_delay, parse_retry_after,
                     retry_after_seconds)

__all__ = [
    "failpoints", "policy",
    "SITES", "InjectedFault", "FaultAction", "fault_point",
    "BreakerBoard", "BreakerConfig", "CircuitBreaker", "RetryBudget",
    "RetryPolicy", "Deadline", "DEADLINE_HEADER", "backoff",
    "backoff_delay", "parse_retry_after", "retry_after_seconds",
]
