"""Seeded, rule-driven fault injection: named failpoints for chaos runs.

The reference survives executor crashes by construction (history-queue
requeue, web-service retry schedules) but never *proves* it: there is no
way to make an executor fail on demand. This module is that switch for
the TPU rebuild — chaos runs become deterministic, replayable inputs
instead of hardware folklore, the way a fleet that rolls and fails
continuously has to be tested.

One env var drives everything::

    MMLSPARK_TPU_FAILPOINTS="gateway.route:error_503:0.2,gbdt.round:exit@5"

Grammar (comma-separated rules)::

    rule := site ":" kind [":" arg] ["@" N]

    kind = "error_<status>"  synthetic HTTP failure returned at the site
                             (arg = fire probability, default 1.0;
                             status 0 = connection failure for http.send)
         | "error"           raise InjectedFault at the site
                             (arg = fire probability)
         | "delay"           added latency; arg REQUIRED: "250ms", "1.5s",
                             or a plain number of milliseconds (an extra
                             ":p" field sets a fire probability)
         | "exit"            os._exit at the site — the preemption
                             simulation; no cleanup handlers run, exactly
                             like a real SIGKILL (arg = status, default 17)
    @N   = fire ONLY on the Nth evaluation of the site (1-based; the
           site's hit counter is process-wide), so "kill the fit at
           round 5" or "fail only the first forward" replay exactly

Sites are a closed set (:data:`SITES`): a typo'd site fails loudly at
:func:`configure` time instead of silently never firing, and graftlint's
``failpoint-site-grammar`` rule pins every call-site literal to the same
set.

Determinism: every rule owns a :class:`random.Random` seeded from
``MMLSPARK_TPU_FAILPOINTS_SEED`` (default 0) plus the rule's position,
site, and kind — string seeding hashes via sha512, stable across
processes and ``PYTHONHASHSEED``, so the same spec and seed replay the
same fire pattern and a chaos run that found a bug is a regression
test, not an anecdote.

Kill-switch contract (the PR 1/5 idiom): with no rules configured,
:func:`fault_point` is one falsy check and the instrumented paths are
byte-identical to the uninstrumented build. Every fired fault is
recorded as a ``failpoint`` flight event and counted in
``failpoints_fired_total{site,kind}`` BEFORE its effect, so the ring
replays the chaos sequence even when the effect kills the thread (or,
for ``exit``, the process).
"""

from __future__ import annotations

import os
import random
import re
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..observability import flight as _flight
from ..observability import metrics as _metrics

__all__ = [
    "SITES", "InjectedFault", "FaultAction", "Rule",
    "configure", "clear", "active", "ensure_configured", "rules",
    "hit_count", "fault_point",
    "FAILPOINTS_ENV", "SEED_ENV",
]

FAILPOINTS_ENV = "MMLSPARK_TPU_FAILPOINTS"
SEED_ENV = "MMLSPARK_TPU_FAILPOINTS_SEED"

_SITE_RE = re.compile(r"^[a-z_.]+$")

#: The registered injection sites — the closed set a rule may name,
#: spanning the edge→gateway→worker request path, training rounds,
#: streaming, and barriers. Wiring lives next to the code it perturbs;
#: descriptions here are the single source for docs/robustness.md.
SITES: Dict[str, str] = {
    "serving.handle": "worker HTTP handler, before a request is admitted "
                      "to the batch queue (io/serving.py)",
    "serving.batch": "ServingQuery micro-batch loop, before the transform "
                     "runs — `error` rides the requeue-once recovery path "
                     "(io/serving.py)",
    "gateway.route": "gateway worker hop: the picked worker's reply is "
                     "replaced, delayed, or crashed before any bytes hit "
                     "the wire (io/distributed_serving.py)",
    "gateway.probe": "gateway health-loop probe of a half-open worker "
                     "(io/distributed_serving.py)",
    "http.send": "outbound HTTP-on-X exchange in send_request "
                 "(io/http.py)",
    "gbdt.round": "GBDT host round loop, top of each boosting round — "
                  "`exit` is the mid-fit preemption drill the resume "
                  "path is tested against (models/gbdt/booster.py)",
    "checkpoint.write": "CheckpointManager.save, between the payload "
                        "write and the atomic publish — a torn-write "
                        "crash (utils/checkpoint.py)",
    "prefetch.chunk": "streaming prefetch, at the consumer's yield point "
                      "— a failing or slow chunk load (io/prefetch.py)",
    "barrier.wait": "distributed barrier: a peer stuck (delay) or lost "
                    "(error) at the rendezvous (parallel/distributed.py)",
}


class InjectedFault(RuntimeError):
    """Raised by ``error`` rules — deliberately a plain RuntimeError
    subclass so it rides the same recovery paths a real crash would."""

    def __init__(self, site: str, hit: int):
        super().__init__(f"failpoint {site!r} fired (hit {hit})")
        self.site = site
        self.hit = hit


@dataclass(frozen=True)
class FaultAction:
    """What a fired, non-raising rule did at the call site."""

    site: str
    kind: str                      # "error_503" / "delay"
    status: Optional[int]          # set for error_<status> rules
    delay_s: float                 # set (and already slept) for delay
    rule: str                      # the spec text, for forensics


class Rule:
    """One parsed fault rule with its own deterministic RNG + @N pin."""

    __slots__ = ("site", "kind", "status", "delay_s", "exit_code", "p",
                 "at", "fired", "spec", "_rng")

    def __init__(self, site: str, kind: str, status: Optional[int],
                 delay_s: float, exit_code: int, p: float,
                 at: Optional[int], spec: str, seed: Any, index: int):
        self.site = site
        self.kind = kind               # "error" | "error_status" | "delay" | "exit"
        self.status = status
        self.delay_s = delay_s
        self.exit_code = exit_code
        self.p = p
        self.at = at
        self.fired = 0
        self.spec = spec
        self._rng = random.Random(f"{seed}|{index}|{site}|{kind}")

    @property
    def kind_label(self) -> str:
        return (f"error_{self.status}" if self.kind == "error_status"
                else self.kind)

    def try_fire(self, hit: int) -> bool:
        """One draw; caller holds the module lock (the RNG is not
        thread-safe and the @N pin must not race). An @N pin and a
        probability compose as the grammar documents ([:arg][@N]):
        the draw happens only at the pinned hit."""
        if self.at is not None and hit != self.at:
            return False
        if self.p < 1.0 and self._rng.random() >= self.p:
            return False
        self.fired += 1
        return True

    def describe(self) -> Dict[str, Any]:
        return {"site": self.site, "kind": self.kind_label,
                "delay_s": self.delay_s, "p": self.p, "at": self.at,
                "fired": self.fired, "spec": self.spec}


def _parse_prob(tok: str, part: str) -> float:
    try:
        p = float(tok)
    except ValueError:
        raise ValueError(f"failpoint rule {part!r}: bad probability "
                         f"{tok!r}") from None
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"failpoint rule {part!r}: probability {p} "
                         "outside [0, 1]")
    return p


def _parse_duration(tok: str, part: str) -> float:
    try:
        if tok.endswith("ms"):
            return float(tok[:-2]) / 1000.0
        if tok.endswith("s"):
            return float(tok[:-1])
        return float(tok) / 1000.0     # bare number = milliseconds
    except ValueError:
        raise ValueError(f"failpoint rule {part!r}: bad duration "
                         f"{tok!r} (want 250ms / 1.5s / plain ms)") from None


def _parse_rule(part: str, seed: Any, index: int) -> Rule:
    at: Optional[int] = None
    body = part
    if "@" in body:
        body, at_s = body.rsplit("@", 1)
        try:
            at = int(at_s)
        except ValueError:
            raise ValueError(f"failpoint rule {part!r}: @N must be an "
                             f"integer, got {at_s!r}") from None
        if at < 1:
            raise ValueError(f"failpoint rule {part!r}: @N is 1-based")
    fields = [f.strip() for f in body.split(":")]
    if len(fields) < 2 or not fields[0] or not fields[1]:
        raise ValueError(
            f"failpoint rule {part!r}: expected site:kind[:arg][@N]")
    site, kindf = fields[0], fields[1]
    if not _SITE_RE.match(site):
        raise ValueError(f"failpoint site {site!r} must match [a-z_.]+")
    if site not in SITES:
        raise ValueError(f"failpoint rule {part!r}: unknown site {site!r} "
                         f"(registered: {sorted(SITES)})")
    arg = fields[2] if len(fields) > 2 else None
    status: Optional[int] = None
    delay_s, exit_code, p = 0.0, 17, 1.0
    if kindf.startswith("error_"):
        kind = "error_status"
        try:
            status = int(kindf[len("error_"):])
        except ValueError:
            raise ValueError(f"failpoint rule {part!r}: bad status in "
                             f"{kindf!r}") from None
        if not 0 <= status <= 599:
            raise ValueError(f"failpoint rule {part!r}: status {status} "
                             "out of range (0..599; 0 = connection "
                             "failure for http.send)")
        if arg is not None:
            p = _parse_prob(arg, part)
    elif kindf == "error":
        kind = "error"
        if arg is not None:
            p = _parse_prob(arg, part)
    elif kindf == "delay":
        kind = "delay"
        if arg is None:
            raise ValueError(f"failpoint rule {part!r}: delay needs a "
                             "duration (site:delay:250ms)")
        delay_s = _parse_duration(arg, part)
        if delay_s <= 0:
            raise ValueError(f"failpoint rule {part!r}: delay must be "
                             "positive")
        if len(fields) > 3:
            p = _parse_prob(fields[3], part)
    elif kindf == "exit":
        kind = "exit"
        if arg is not None:
            try:
                exit_code = int(arg)
            except ValueError:
                raise ValueError(f"failpoint rule {part!r}: bad exit "
                                 f"code {arg!r}") from None
    else:
        raise ValueError(f"failpoint rule {part!r}: unknown kind "
                         f"{kindf!r} (error_<status> | error | delay | "
                         "exit)")
    return Rule(site, kind, status, delay_s, exit_code, p, at, part,
                seed, index)


def parse_spec(spec: str, seed: Any = 0) -> Tuple[Rule, ...]:
    """Parse a ``MMLSPARK_TPU_FAILPOINTS`` spec; raises ValueError on
    unknown sites/kinds or malformed fields (a chaos config must never
    be silently half-applied)."""
    out = []
    for index, part in enumerate(p.strip() for p in spec.split(",")):
        if not part:
            continue
        out.append(_parse_rule(part, seed, index))
    return tuple(out)


# ---------------------------------------------------------------------------
# Module state: None = env not read yet; () = loaded, no rules (the
# byte-identical fast path is then one falsy check per fault_point call)
# ---------------------------------------------------------------------------

_rules: Optional[Tuple[Rule, ...]] = None
_hits: Dict[str, int] = {}
_lock = threading.Lock()


def configure(spec: Optional[str] = None,
              seed: Optional[Any] = None) -> Tuple[Rule, ...]:
    """Install a rule set (``spec=None`` reads ``MMLSPARK_TPU_FAILPOINTS``);
    returns the parsed rules and resets every site's hit counter. Seed
    defaults to ``MMLSPARK_TPU_FAILPOINTS_SEED`` (or 0)."""
    global _rules
    if spec is None:
        spec = os.environ.get(FAILPOINTS_ENV, "")
    if seed is None:
        seed = os.environ.get(SEED_ENV, "") or 0
    parsed = parse_spec(spec, seed)
    with _lock:
        _rules = parsed
        _hits.clear()
    return parsed


def clear() -> None:
    """Drop every rule (tests); fault points go back to the no-op path."""
    global _rules
    with _lock:
        _rules = ()
        _hits.clear()


def active() -> bool:
    return bool(_rules)


def ensure_configured() -> bool:
    """Load the env spec if this process hasn't yet; True when any
    rules are installed. The async serving plane gates its off-loop
    fault evaluation on this — a ``delay`` rule sleeps inside
    :func:`fault_point`, which must never run ON the event loop (one
    injected delay there would stall every in-flight connection, not
    the one request chaos meant to slow)."""
    if _rules is None:
        configure()
    return bool(_rules)


def rules() -> Tuple[Rule, ...]:
    return _rules or ()


def hit_count(site: str) -> int:
    """Evaluations of ``site`` since configure() (0 when never hit)."""
    with _lock:
        return _hits.get(site, 0)


def fault_point(site: str, **ctx: Any) -> Optional[FaultAction]:
    """The one call a production site makes. No rules configured: returns
    None after a single check, touching nothing (the byte-identity
    contract). With matching rules: ``delay`` sleeps here (call sites
    stay one-liners), ``error_<status>`` returns a :class:`FaultAction`
    whose ``status`` the site turns into a synthetic failure, ``error``
    raises :class:`InjectedFault`, and ``exit`` hard-kills the process.
    Every fired rule is counted and flight-logged before its effect, so
    the ring replays the chaos sequence even when the effect kills the
    thread."""
    rules_now = _rules
    if rules_now is None:
        configure()
        rules_now = _rules or ()
    if not rules_now:
        return None
    site_rules = [r for r in rules_now if r.site == site]
    if not site_rules:
        return None
    with _lock:
        hit = _hits.get(site, 0) + 1
        _hits[site] = hit
        fired = [r for r in site_rules if r.try_fire(hit)]
    action: Optional[FaultAction] = None
    for rule in fired:
        _metrics.safe_counter("failpoints_fired_total", site=site,
                              kind=rule.kind_label).inc()
        _flight.record("failpoint", site=site, fault=rule.kind_label,
                       rule=rule.spec, hit=hit, **ctx)
        if rule.kind == "exit":
            os._exit(rule.exit_code)
        if rule.kind == "error":
            raise InjectedFault(site, hit)
        if rule.kind == "delay":
            time.sleep(rule.delay_s)
            if action is None:
                action = FaultAction(site, "delay", None, rule.delay_s,
                                     rule.spec)
        else:
            # error_<status> is terminal for this site: first one wins
            return FaultAction(site, rule.kind_label, rule.status, 0.0,
                               rule.spec)
    return action
