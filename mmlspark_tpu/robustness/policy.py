"""Resilience policy: jittered backoff, retry budgets, breakers, deadlines.

The primitives the serving request plane (io/serving.py,
io/distributed_serving.py, io/http.py) degrades gracefully through —
factored out of the call sites so the ROADMAP item 2 async rebuild
inherits the policy wholesale:

- :func:`backoff` / :class:`RetryPolicy` — exponential backoff with FULL
  jitter (a fixed schedule retries synchronized clients in lockstep;
  jitter decorrelates them), honoring ``Retry-After`` in BOTH RFC 9110
  forms (delta-seconds and HTTP-date), deadline-aware, with an attempt
  budget. The ONLY sanctioned sleep in an ``io/`` retry loop
  (graftlint's ``retry-sleep-funnel`` rule).
- :class:`RetryBudget` — token bucket that caps retries at a fraction of
  live traffic, so a failing backend sees load shed instead of a retry
  storm that finishes it off.
- :class:`CircuitBreaker` / :class:`BreakerBoard` — per-worker
  closed/open/half-open state driven by consecutive failures, error rate,
  or hard (connection-level) failures; half-open probes piggyback on the
  gateway health loop.
- :class:`Deadline` — ``X-Deadline-Ms`` propagation, attenuated per hop,
  so no hop scores work nobody is still waiting for.
- :func:`retry_after_seconds` — the shared Retry-After math, derived
  from observed latency so well-behaved clients back off realistically.

Everything is observable: breaker transitions, budget exhaustion, and
deadline expiries land in the metrics registry and the flight ring.
"""

from __future__ import annotations

import math
import os
import random
import threading
import time
from collections import deque
from email.utils import parsedate_to_datetime
from typing import (Any, Callable, Dict, Iterable, Mapping, Optional,
                    Sequence, Tuple)

from ..observability import flight as _flight
from ..observability import metrics as _metrics
# the one shared env-parsing fallback semantics (re-exported: io/ and
# the gateway read their knobs through policy)
from ..observability.env_registry import env_float, env_int  # noqa: F401
from ..observability.logging import get_logger

logger = get_logger("mmlspark_tpu.robustness.policy")

__all__ = [
    "DEADLINE_HEADER", "RETRY_AFTER_CAP_SECONDS",
    "CLOSED", "OPEN", "HALF_OPEN",
    "backoff", "backoff_delay", "parse_retry_after",
    "env_float", "env_int",
    "RetryPolicy", "RetryBudget",
    "BreakerConfig", "CircuitBreaker", "BreakerBoard",
    "Deadline", "Ewma", "retry_after_seconds",
]

#: remaining-milliseconds deadline header, attenuated at every hop (the
#: one definition — graftlint's ``deadline-header-literal`` rule pins
#: the literal to this module, like the trace headers)
DEADLINE_HEADER = "X-Deadline-Ms"

#: RFC-compliant servers may send huge Retry-After values; we never
#: honour more than this (both delta-seconds and HTTP-date forms)
RETRY_AFTER_CAP_SECONDS = 30.0


class Ewma:
    """Tiny thread-safe exponentially-weighted moving average; ``value``
    is None until the first observation (callers pick their fallback)."""

    def __init__(self, alpha: float = 0.2):
        self.alpha = alpha
        self._value: Optional[float] = None
        self._lock = threading.Lock()

    def update(self, x: float) -> float:
        with self._lock:
            if self._value is None:
                self._value = float(x)
            else:
                self._value += self.alpha * (float(x) - self._value)
            return self._value

    @property
    def value(self) -> Optional[float]:
        return self._value


# ---------------------------------------------------------------------------
# Backoff with full jitter
# ---------------------------------------------------------------------------

_rng = random.Random()


def parse_retry_after(value: Optional[str],
                      now: Optional[float] = None) -> Optional[float]:
    """Seconds to wait per a ``Retry-After`` header value — RFC 9110
    accepts delta-seconds ("120") *and* an HTTP-date ("Wed, 21 Oct 2015
    07:28:00 GMT"); both are honoured and both are capped at
    :data:`RETRY_AFTER_CAP_SECONDS`. Returns None for absent,
    unparseable, or non-positive values — a past HTTP-date (clock skew)
    or "0" carries no pacing information, and a zero-second override
    would turn the retry loop into a zero-delay hammer on a recovering
    server; the caller's own backoff schedule applies instead."""
    if not value:
        return None
    value = value.strip()
    try:
        delay = float(value)
    except ValueError:
        try:
            dt = parsedate_to_datetime(value)
        except (TypeError, ValueError):
            return None
        if dt is None:
            return None
        if dt.tzinfo is None:
            from datetime import timezone
            dt = dt.replace(tzinfo=timezone.utc)
        delay = dt.timestamp() - (time.time() if now is None else now)
    if delay <= 0:
        return None
    return min(delay, RETRY_AFTER_CAP_SECONDS)


def backoff_delay(attempt: int, *, schedule_ms: Optional[Iterable[float]] = None,
                  base_ms: float = 100.0, cap_ms: float = 10_000.0,
                  retry_after: Optional[str] = None,
                  rng: Optional[random.Random] = None) -> float:
    """Delay in seconds for retry ``attempt`` (0-based).

    A parseable ``Retry-After`` (either RFC 9110 form) wins outright,
    capped at :data:`RETRY_AFTER_CAP_SECONDS` — the server said when to
    come back; jittering *that* would defeat it. Otherwise: full jitter,
    ``uniform(0, upper)`` where ``upper`` is the schedule entry (last
    entry repeats) or ``min(cap, base * 2^attempt)``.
    """
    ra = parse_retry_after(retry_after)
    if ra is not None:
        return ra
    if schedule_ms is not None:
        sched = list(schedule_ms)
        upper = float(sched[min(attempt, len(sched) - 1)]) if sched else 0.0
    else:
        upper = min(float(cap_ms), float(base_ms) * (2.0 ** max(0, attempt)))
    if upper <= 0:
        return 0.0
    return (rng or _rng).uniform(0.0, upper) / 1000.0


def backoff(attempt: int, *, schedule_ms: Optional[Iterable[float]] = None,
            base_ms: float = 100.0, cap_ms: float = 10_000.0,
            retry_after: Optional[str] = None,
            rng: Optional[random.Random] = None,
            sleep: Optional[Callable[[float], None]] = None) -> float:
    """Compute the jittered delay AND sleep it; returns the seconds slept.
    This is the funnel ``io/`` retry loops must route their sleeps
    through (tests/test_lint.py bans bare ``time.sleep`` there)."""
    d = backoff_delay(attempt, schedule_ms=schedule_ms, base_ms=base_ms,
                      cap_ms=cap_ms, retry_after=retry_after, rng=rng)
    if d > 0:
        (sleep or time.sleep)(d)
    return d


class RetryPolicy:
    """Attempt budget + full-jitter backoff, deadline-aware.

    ``sleep_before(attempt)`` (attempt 0 = first retry) routes through
    :func:`backoff`: full jitter by default, an explicit millisecond
    ``schedule`` for the HTTP-on-X ``backoffs`` parity path, and a
    server-directed ``Retry-After`` (either RFC 9110 form) overriding
    both. With a :class:`Deadline`, sleeps are clamped to the remaining
    budget and :meth:`should_retry` refuses attempts the budget can no
    longer cover. An optional :class:`RetryBudget` gates every retry —
    token-bucket exhaustion stops the loop even when attempts remain.

    Env defaults: ``MMLSPARK_TPU_RETRY_MAX_ATTEMPTS`` (3),
    ``MMLSPARK_TPU_RETRY_BASE_MS`` (25), ``MMLSPARK_TPU_RETRY_MAX_MS``
    (2000).
    """

    def __init__(self, max_attempts: Optional[int] = None,
                 base_ms: Optional[float] = None,
                 max_ms: Optional[float] = None,
                 schedule_ms: Optional[Sequence[float]] = None,
                 budget: Optional["RetryBudget"] = None,
                 rng: Optional[random.Random] = None):
        self.max_attempts = max(1, int(
            max_attempts if max_attempts is not None
            else env_int("MMLSPARK_TPU_RETRY_MAX_ATTEMPTS", 3)))
        self.base_ms = max(0.0, float(
            base_ms if base_ms is not None
            else env_float("MMLSPARK_TPU_RETRY_BASE_MS", 25.0)))
        self.max_ms = max(0.0, float(
            max_ms if max_ms is not None
            else env_float("MMLSPARK_TPU_RETRY_MAX_MS", 2000.0)))
        self.schedule_ms = (None if schedule_ms is None
                            else [float(s) for s in schedule_ms])
        if self.schedule_ms is not None:
            self.max_attempts = len(self.schedule_ms) + 1
        self.budget = budget
        self._rng = rng

    @classmethod
    def from_schedule(cls, backoffs_ms: Sequence[float],
                      budget: Optional["RetryBudget"] = None
                      ) -> "RetryPolicy":
        """Explicit millisecond schedule: one retry per entry
        (HandlingUtils.advancedUDF parity; each step still jitters
        ``uniform(0, step)`` unless Retry-After overrides)."""
        return cls(schedule_ms=list(backoffs_ms), budget=budget)

    def should_retry(self, attempt: int,
                     deadline: Optional["Deadline"] = None) -> bool:
        """True when retry ``attempt`` (0-based) exists in the attempt
        budget, the deadline (if any) has time left, and the token
        bucket (if any) grants it. The bucket is spent HERE — call once
        per retry decision."""
        if attempt + 1 >= self.max_attempts:
            return False
        if deadline is not None and deadline.expired:
            return False
        return self.budget is None or self.budget.try_spend()

    def sleep_before(self, attempt: int,
                     retry_after: Optional[str] = None,
                     deadline: Optional["Deadline"] = None,
                     sleep: Optional[Callable[[float], None]] = None
                     ) -> float:
        """Back off before retry ``attempt`` (0-based) via the
        :func:`backoff` funnel; the delay is clamped to the deadline's
        remaining budget. Returns the seconds slept."""
        d = backoff_delay(attempt, schedule_ms=self.schedule_ms,
                          base_ms=self.base_ms, cap_ms=self.max_ms,
                          retry_after=retry_after, rng=self._rng)
        if deadline is not None:
            d = deadline.clamp(d)
        if d > 0:
            (sleep or time.sleep)(d)
        return max(0.0, d)

    def run(self, fn: Callable[[], Any], *,
            retry_on: Tuple[type, ...] = (Exception,),
            deadline: Optional["Deadline"] = None) -> Any:
        """Call ``fn`` under the attempt budget; re-raises the last
        exception when attempts (or the deadline / token bucket) run
        out."""
        attempt = 0
        while True:
            try:
                return fn()
            except retry_on:
                if not self.should_retry(attempt, deadline):
                    raise
                self.sleep_before(attempt, deadline=deadline)
                attempt += 1


# ---------------------------------------------------------------------------
# Retry budget (token bucket)
# ---------------------------------------------------------------------------


class RetryBudget:
    """Retries capped at a fraction of live traffic.

    Every admitted request deposits ``ratio`` tokens (clamped to
    ``cap``); every retry spends one. Under a total backend outage the
    retry load converges to ``ratio`` × the request rate instead of
    multiplying it — the storm a fixed retry count produces.
    ``min_tokens`` is the starting balance, so cold starts and tests can
    fail over before any traffic has accrued budget.

    Env defaults: ``MMLSPARK_TPU_RETRY_BUDGET_RATIO`` (0.1),
    ``MMLSPARK_TPU_RETRY_BUDGET_MIN`` (10),
    ``MMLSPARK_TPU_RETRY_BUDGET_CAP`` (100).
    """

    def __init__(self, ratio: Optional[float] = None,
                 min_tokens: Optional[float] = None,
                 cap: Optional[float] = None, **labels: Any):
        self.ratio = (ratio if ratio is not None else
                      env_float("MMLSPARK_TPU_RETRY_BUDGET_RATIO", 0.1))
        self.min_tokens = (min_tokens if min_tokens is not None else
                           env_float("MMLSPARK_TPU_RETRY_BUDGET_MIN", 10.0))
        self.cap = (cap if cap is not None else
                    env_float("MMLSPARK_TPU_RETRY_BUDGET_CAP", 100.0))
        self.cap = max(self.cap, self.min_tokens)
        self._tokens = float(self.min_tokens)
        self._labels = {str(k): str(v) for k, v in labels.items()}
        self._lock = threading.Lock()
        self._publish()

    def _publish(self) -> None:
        _metrics.safe_gauge("retry_budget_tokens",
                            **self._labels).set(self._tokens)

    @property
    def tokens(self) -> float:
        return self._tokens

    def deposit(self, n: float = 1.0) -> None:
        """Called once per admitted request: accrue ``ratio`` per unit."""
        with self._lock:
            self._tokens = min(self.cap, self._tokens + self.ratio * n)
        self._publish()

    def try_spend(self, n: float = 1.0) -> bool:
        """Take ``n`` tokens for a retry; False (and accounting) when the
        budget is exhausted — the caller must NOT retry then."""
        with self._lock:
            if self._tokens >= n:
                self._tokens -= n
                ok = True
            else:
                ok = False
        if ok:
            _metrics.safe_counter("retry_budget_spent_total",
                                  **self._labels).inc(n)
        else:
            _metrics.safe_counter("retry_budget_exhausted_total",
                                  **self._labels).inc()
            _flight.record("retry_budget_exhausted", tokens=self._tokens,
                           **self._labels)
        self._publish()
        return ok


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
#: breaker_state gauge encoding
_STATE_VALUE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}


class BreakerConfig:
    """Thresholds shared by every breaker on a board.

    Env defaults: ``MMLSPARK_TPU_BREAKER_CONSECUTIVE`` (5),
    ``MMLSPARK_TPU_BREAKER_ERROR_RATE`` (0.5),
    ``MMLSPARK_TPU_BREAKER_WINDOW`` (20),
    ``MMLSPARK_TPU_BREAKER_MIN_VOLUME`` (10),
    ``MMLSPARK_TPU_BREAKER_OPEN_SECONDS`` (caller default),
    ``MMLSPARK_TPU_BREAKER_HALF_OPEN_SUCCESSES`` (1).
    """

    def __init__(self, consecutive_failures: Optional[int] = None,
                 error_rate: Optional[float] = None,
                 window: Optional[int] = None,
                 min_volume: Optional[int] = None,
                 open_seconds: Optional[float] = None,
                 half_open_successes: Optional[int] = None,
                 default_open_seconds: float = 10.0):
        env_open = os.environ.get("MMLSPARK_TPU_BREAKER_OPEN_SECONDS")
        self.consecutive_failures = (
            consecutive_failures if consecutive_failures is not None
            else env_int("MMLSPARK_TPU_BREAKER_CONSECUTIVE", 5))
        self.error_rate = (error_rate if error_rate is not None else
                           env_float("MMLSPARK_TPU_BREAKER_ERROR_RATE", 0.5))
        self.window = (window if window is not None else
                       env_int("MMLSPARK_TPU_BREAKER_WINDOW", 20))
        self.min_volume = (min_volume if min_volume is not None else
                           env_int("MMLSPARK_TPU_BREAKER_MIN_VOLUME", 10))
        if open_seconds is not None:
            self.open_seconds = open_seconds
        elif env_open:
            self.open_seconds = env_float(
                "MMLSPARK_TPU_BREAKER_OPEN_SECONDS", default_open_seconds)
        else:
            self.open_seconds = default_open_seconds
        self.half_open_successes = (
            half_open_successes if half_open_successes is not None
            else env_int("MMLSPARK_TPU_BREAKER_HALF_OPEN_SUCCESSES", 1))


class CircuitBreaker:
    """closed → open → half_open → closed, per backend.

    Opens on: a hard failure (connection-level — the worker is GONE, one
    strike is enough, matching the old dead-marking), ``consecutive``
    soft failures, or a windowed error rate past the threshold at
    minimum volume. While open, :meth:`allow` is False (callers route
    around). After ``open_seconds``, :meth:`probe_due` turns true and the
    owner's health loop calls :meth:`begin_probe` (→ half_open) and
    probes; probe success(es) close it, a probe failure reopens it.
    Request traffic never probes a half-open backend itself — the health
    loop owns recovery, so one sick worker can't eat live requests.
    """

    def __init__(self, key: str, config: Optional[BreakerConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 label: str = "worker"):
        self.key = key
        self.cfg = config or BreakerConfig()
        self._clock = clock
        self._label = {label: key}
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._window = deque(maxlen=max(1, self.cfg.window))
        self._opened_at = 0.0
        self._half_open_hits = 0
        _metrics.safe_gauge("breaker_state", **self._label).set(0.0)

    @property
    def state(self) -> str:
        return self._state

    def allow(self) -> bool:
        """May live traffic go to this backend right now?"""
        return self._state == CLOSED

    def probe_due(self) -> bool:
        return (self._state == OPEN
                and self._clock() - self._opened_at >= self.cfg.open_seconds)

    def begin_probe(self) -> bool:
        """open → half_open when the cooldown has elapsed (health loop)."""
        with self._lock:
            if not self.probe_due():
                return False
            self._transition(HALF_OPEN)
            return True

    def record_success(self) -> None:
        """Live-traffic outcome. Deliberately inert outside CLOSED: a
        success arriving while OPEN/HALF_OPEN is from a request that was
        in flight before the breaker tripped — recovery is the probe
        path's call (:meth:`probe_success`), not a stale reply's."""
        with self._lock:
            self._window.append(True)
            self._consecutive = 0

    def record_failure(self, hard: bool = False) -> None:
        """Live-traffic outcome. ``hard``: connection-level — the
        backend is unreachable, open immediately. Soft failures
        (retryable statuses) accumulate. Inert while HALF_OPEN for the
        same stale-in-flight reason as :meth:`record_success` — only a
        failed probe (:meth:`probe_failure`) may re-open from there."""
        with self._lock:
            self._window.append(False)
            self._consecutive += 1
            if self._state == CLOSED:
                if hard or self._consecutive >= self.cfg.consecutive_failures \
                        or self._rate_tripped():
                    self._transition(OPEN)
            # already OPEN: stale in-flight failures don't restart the clock

    def probe_success(self) -> None:
        """Health-loop probe verdict: counts toward closing a HALF_OPEN
        breaker (``half_open_successes`` of these close it)."""
        with self._lock:
            self._window.append(True)
            self._consecutive = 0
            if self._state == HALF_OPEN:
                self._half_open_hits += 1
                if self._half_open_hits >= self.cfg.half_open_successes:
                    self._transition(CLOSED)

    def probe_failure(self) -> None:
        """Health-loop probe verdict: re-opens a HALF_OPEN breaker (and
        restarts its cooldown)."""
        with self._lock:
            self._window.append(False)
            self._consecutive += 1
            if self._state == HALF_OPEN:
                self._transition(OPEN)

    def _rate_tripped(self) -> bool:
        if len(self._window) < max(1, self.cfg.min_volume):
            return False
        failures = sum(1 for ok in self._window if not ok)
        return failures / len(self._window) >= self.cfg.error_rate

    def _transition(self, to: str) -> None:
        # caller holds self._lock (every public mutator takes it before
        # delegating here — the lexical with-block lives one frame up)
        frm = self._state
        if frm == to:
            return
        self._state = to
        if to == OPEN:
            self._opened_at = self._clock()
        if to == HALF_OPEN:
            self._half_open_hits = 0  # graftlint: disable=lock-discipline (caller holds self._lock; _transition is only reached from under it)
        if to == CLOSED:
            self._consecutive = 0  # graftlint: disable=lock-discipline (caller holds self._lock; _transition is only reached from under it)
            self._window.clear()
        _metrics.safe_gauge("breaker_state",
                            **self._label).set(_STATE_VALUE[to])
        _metrics.safe_counter("breaker_transitions_total", to=to,
                              **self._label).inc()
        _flight.record("breaker_transition", breaker=self.key,
                       frm=frm, to=to)
        if to == OPEN:
            logger.warning("breaker opened: %s", self.key, breaker=self.key)
        elif frm != CLOSED and to == CLOSED:
            logger.info("breaker closed: %s", self.key, breaker=self.key)

    def describe(self) -> Dict[str, Any]:
        return {"state": self._state, "consecutive": self._consecutive,
                "window": len(self._window),
                "failures": sum(1 for ok in self._window if not ok)}


class BreakerBoard:
    """Per-key breakers sharing one config (the gateway keys by
    ``host:port`` — a bounded slot set, per the federation labeling
    rule, so worker churn can't grow the registry unboundedly)."""

    def __init__(self, config: Optional[BreakerConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 label: str = "worker"):
        self.config = config or BreakerConfig()
        self._clock = clock
        self._label = label
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()

    def breaker(self, key: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(key)
            if b is None:
                b = CircuitBreaker(key, self.config, self._clock,
                                   self._label)
                self._breakers[key] = b
            return b

    def get(self, key: str) -> Optional[CircuitBreaker]:
        return self._breakers.get(key)

    def allow(self, key: str) -> bool:
        """True when no breaker exists yet (innocent until failing) or
        the existing one is closed."""
        b = self._breakers.get(key)
        return True if b is None else b.allow()

    def items(self) -> Tuple[Tuple[str, CircuitBreaker], ...]:
        with self._lock:
            return tuple(self._breakers.items())

    def states(self) -> Dict[str, str]:
        return {k: b.state for k, b in self.items()}

    def forget(self, key: str) -> None:
        """Drop state for a deregistered backend (the gateway health
        sweep prunes addresses that left the registry — ephemeral-port
        churn must not grow the board without bound)."""
        with self._lock:
            self._breakers.pop(key, None)

    def describe(self) -> Dict[str, Dict[str, Any]]:
        return {k: b.describe() for k, b in self.items()}


# ---------------------------------------------------------------------------
# Deadline propagation
# ---------------------------------------------------------------------------


class Deadline:
    """A request's remaining time, carried as ``X-Deadline-Ms`` and
    attenuated per hop: each hop converts the remaining-milliseconds
    header into an absolute local deadline on arrival, then re-emits
    what is left (minus a safety margin for the wire) on the way out —
    remaining-time transfer needs no clock sync between hosts."""

    __slots__ = ("expires_at", "_clock")

    #: per-hop attenuation margin (network + serialization slack)
    MARGIN_MS_ENV = "MMLSPARK_TPU_DEADLINE_MARGIN_MS"

    def __init__(self, expires_at: float,
                 clock: Callable[[], float] = time.monotonic):
        self.expires_at = expires_at
        self._clock = clock

    @classmethod
    def from_ms(cls, ms: float,
                clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(clock() + float(ms) / 1000.0, clock)

    @classmethod
    def from_headers(cls, headers: Optional[Mapping[str, str]],
                     clock: Callable[[], float] = time.monotonic
                     ) -> Optional["Deadline"]:
        """Parse the deadline header from any header mapping (stdlib
        ``Message`` is case-insensitive; plain dicts are tried both
        spelled and lowercased). Unparseable values mean no deadline —
        a malformed client header must not fail the request."""
        if headers is None:
            return None
        raw = headers.get(DEADLINE_HEADER)
        if raw is None and hasattr(headers, "get"):
            raw = headers.get(DEADLINE_HEADER.lower())
        if raw is None:
            return None
        try:
            return cls.from_ms(float(raw), clock)
        except (TypeError, ValueError):
            return None

    def remaining_seconds(self) -> float:
        return max(0.0, self.expires_at - self._clock())

    def clamp(self, timeout: float) -> float:
        """``timeout`` bounded by the remaining budget."""
        return min(float(timeout), self.remaining_seconds())

    def remaining_ms(self) -> float:
        return self.remaining_seconds() * 1000.0

    @property
    def expired(self) -> bool:
        return self._clock() >= self.expires_at

    def header_value(self, margin_ms: Optional[float] = None) -> str:
        """The attenuated remaining budget for the NEXT hop."""
        if margin_ms is None:
            margin_ms = env_float(self.MARGIN_MS_ENV, 5.0)
        return str(max(0, int(self.remaining_ms() - margin_ms)))


# ---------------------------------------------------------------------------
# Retry-After math
# ---------------------------------------------------------------------------


def retry_after_seconds(est_seconds: Optional[float], floor: float = 1.0,
                        cap: float = 60.0) -> int:
    """Integer Retry-After from an estimated time-to-capacity (observed
    queue drain time, worker latency, or a health-sweep interval);
    clamped so a cold estimator still produces a sane hint."""
    est = float(est_seconds) if est_seconds else 0.0
    return int(math.ceil(min(max(est, floor), cap)))
