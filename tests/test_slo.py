"""SLO plane: objectives grammar, burn-rate windows, tail attribution.

The acceptance scenario end-to-end: a deliberately tight objective +
synthetic overload on BOTH serving engines must trip the fast-window
burn rate past 1.0, surface the breach on ``/debug/slo``, deposit
stage timelines on ``/debug/tail`` whose sums reconcile (±5%) with the
request's end-to-end latency, and let ``tools/tail_report.py`` name
the dominant stage. Plus the contracts around it: the grammar rejects
malformed specs loudly (env path degrades with a flight event), an
unconfigured process stays a no-op, and the gateway's federation sweep
folds the fleet-worst burn into ``cluster_autoscale_hint``.
"""

import http.client
import json
import os
import sys
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from mmlspark_tpu.io.serving import DEBUG_ROUTES, debug_body, serve
from mmlspark_tpu.observability import flight, metrics
from mmlspark_tpu.observability import slo, tailsampler
from mmlspark_tpu.observability.federation import (MetricsFederator,
                                                   parse_prometheus_text)
from tools import tail_report


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(slo.SLO_ENV, raising=False)
    prev = metrics.set_enabled(True)
    metrics.reset()
    flight.clear()
    slo.reset()
    tailsampler.reset()
    yield
    slo.reset()
    tailsampler.reset()
    metrics.set_enabled(prev)
    metrics.reset()
    flight.clear()


# ---------------------------------------------------------------------------
# Grammar
# ---------------------------------------------------------------------------


class TestGrammar:
    def test_full_spec(self):
        objs = slo.parse_spec("predict:p99<25ms,err<0.1%;embed:p95<5ms")
        assert set(objs) == {"predict", "embed"}
        p = objs["predict"]
        assert p.percentile == 99.0
        assert p.threshold_seconds == pytest.approx(0.025)
        assert p.error_ceiling == pytest.approx(0.001)
        assert p.latency_budget == pytest.approx(0.01)
        e = objs["embed"]
        assert e.threshold_seconds == pytest.approx(0.005)
        assert e.error_ceiling is None

    def test_seconds_unit_and_error_only(self):
        objs = slo.parse_spec("train:p50<2s; audit:err<5%")
        assert objs["train"].threshold_seconds == pytest.approx(2.0)
        assert objs["audit"].percentile is None
        assert objs["audit"].error_ceiling == pytest.approx(0.05)

    @pytest.mark.parametrize("bad", [
        "predict",                       # no clauses / no colon
        "predict:",                      # empty clause list
        "predict:p99<25parsecs",         # unknown unit
        "predict:q99<25ms",              # unknown clause
        "predict:p99<25ms,p50<1ms",      # two latency clauses
        "predict:err<0.1%,err<2%",       # two error clauses
        "predict:p0<25ms",               # percentile out of range
        "predict:err<200%",              # ceiling out of range
        "a:p99<1ms;a:p99<2ms",           # duplicate endpoint
    ])
    def test_malformed_specs_raise(self, bad):
        with pytest.raises(ValueError):
            slo.parse_spec(bad)

    def test_env_adoption_and_degrade(self, monkeypatch):
        monkeypatch.setenv(slo.SLO_ENV, "predict:p99<25ms")
        slo.reset()
        assert slo.configured()
        assert "predict" in slo.objectives()
        # malformed env degrades to unconfigured with a flight event —
        # an operator typo must not kill a worker at boot
        monkeypatch.setenv(slo.SLO_ENV, "predict:zzz")
        slo.reset()
        flight.clear()
        assert not slo.configured()
        assert any(e["kind"] == "slo_config"
                   and e["decision"] == "rejected"
                   for e in flight.events())


# ---------------------------------------------------------------------------
# Burn-rate windows
# ---------------------------------------------------------------------------


class TestBurnRate:
    def test_unconfigured_is_a_noop(self):
        """No SLO -> observe_request leaves zero trace: no gauges, no
        counters, no reservoir entries (byte-identical contract)."""
        assert not slo.configured()
        slo.observe_request("predict", 9.0, 500,
                            stages={"score": 9.0}, trace_id="t")
        snap = metrics.get_registry().snapshot()
        assert not any(k.startswith(("slo_", "tail_")) for k in snap)
        assert tailsampler.snapshot_payload()["samples"] == []
        assert slo.snapshot_payload()["configured"] is False

    def test_latency_burn_and_budget(self):
        slo.configure("predict:p99<10ms")
        # 100 requests, 10 over threshold: bad fraction 0.1 against a
        # 1% budget -> burn 10x on both windows
        for i in range(100):
            slow = i < 10
            slo.observe_request("predict", 0.5 if slow else 0.001, 200)
        slo.refresh()
        payload = slo.snapshot_payload()
        for window in ("fast5m", "slow1h"):
            v = payload["endpoints"]["predict"]["windows"][window]
            assert v["requests"] == 100
            assert v["slow"] == 10
            assert v["burn_rate"] == pytest.approx(10.0)
            assert v["budget_remaining"] == 0.0
            assert metrics.gauge("slo_burn_rate", api="predict",
                                 window=window).value \
                == pytest.approx(10.0)
        assert payload["endpoints"]["predict"]["breaching"] is True
        assert metrics.counter("slo_breach_total", api="predict",
                               signal="latency").value == 10.0

    def test_error_burn(self):
        slo.configure("predict:err<10%")
        for i in range(20):
            slo.observe_request("predict", 0.001, 503 if i < 2 else 200)
        v = slo.snapshot_payload()["endpoints"]["predict"]["windows"]
        # 2/20 errors on a 10% ceiling: burning exactly at budget
        assert v["fast5m"]["error_burn"] == pytest.approx(1.0)
        assert v["fast5m"]["burn_rate"] == pytest.approx(1.0)
        assert v["fast5m"]["budget_remaining"] == pytest.approx(0.0)

    def test_within_objective_no_breach(self):
        slo.configure("predict:p99<10ms,err<50%")
        for _ in range(50):
            slo.observe_request("predict", 0.001, 200)
        payload = slo.snapshot_payload()
        v = payload["endpoints"]["predict"]["windows"]["fast5m"]
        assert v["burn_rate"] == 0.0
        assert v["budget_remaining"] == 1.0
        assert payload["endpoints"]["predict"]["breaching"] is False
        assert tailsampler.snapshot_payload()["samples"] == []

    def test_unlisted_endpoint_ignored(self):
        slo.configure("predict:p99<1ms")
        slo.observe_request("other_api", 9.0, 200)
        assert "other_api" not in slo.snapshot_payload()["endpoints"]
        assert tailsampler.snapshot_payload()["samples"] == []


# ---------------------------------------------------------------------------
# Tail sampler
# ---------------------------------------------------------------------------


class TestTailSampler:
    def test_reservoir_bounds_and_eviction(self, monkeypatch):
        monkeypatch.setenv(tailsampler.TAIL_SAMPLES_ENV, "4")
        tailsampler.reset()
        for i in range(7):
            tailsampler.sample("api", 0.1 + i, 200,
                               stages={"score": 0.1 + i},
                               trace_id=f"t{i}")
        p = tailsampler.snapshot_payload()
        assert p["capacity"] == 4
        assert len(p["samples"]) == 4
        assert p["sampled_total"] == 7
        assert p["dropped_total"] == 3
        # most recent survive
        assert [s["trace_id"] for s in p["samples"]] \
            == ["t3", "t4", "t5", "t6"]

    def test_attribution_names_dominant_stage(self):
        for _ in range(3):
            tailsampler.sample("api", 0.05, 200, stages={
                "admission": 0.001, "forming_wait": 0.036,
                "score": 0.012, "write": 0.001})
        attr = tailsampler.snapshot_payload()["attribution"]
        assert attr["dominant_stage"] == "forming_wait"
        assert attr["stage_share_pct"]["forming_wait"] \
            == pytest.approx(72.0)

    def test_breach_feeds_sampler_with_trace(self):
        slo.configure("predict:p99<1ms")
        slo.observe_request("predict", 0.2, 200,
                            stages={"score": 0.19, "write": 0.01},
                            trace_id="abc123")
        s = tailsampler.snapshot_payload()["samples"]
        assert len(s) == 1
        assert s[0]["trace_id"] == "abc123"
        assert s[0]["breach"] == "latency"
        assert s[0]["dominant_stage"] == "score"
        assert metrics.counter("tail_samples_total", api="predict",
                               breach="latency").value == 1.0


# ---------------------------------------------------------------------------
# End-to-end on both engines
# ---------------------------------------------------------------------------


def _request(host, port, path, body=None, timeout=30):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    conn.request("POST" if body is not None else "GET", path, body=body)
    r = conn.getresponse()
    payload = r.read()
    conn.close()
    return r.status, payload


@pytest.mark.parametrize("engine", ["threaded", "async"])
def test_overload_breach_end_to_end(engine):
    """Synthetic overload vs a tight objective on a live engine: burn
    trips past 1.0 within the fast window, /debug/slo reports the
    breach, /debug/tail holds timelines whose stage sums reconcile
    (±5%) with the end-to-end latency, and tail_report names the
    dominant stage (the sleeping transform makes it `score`)."""
    def slow_echo(ds):
        time.sleep(0.03)                     # every request breaches
        return ds.with_column("reply", [
            {"entity": {"i": (v or {}).get("i")}, "statusCode": 200}
            for v in ds["value"]])

    slo.configure("slo_e2e:p99<5ms")
    q = (serve().address("localhost", 0, "slo_e2e").batch(4, 2)
         .engine(engine).transform(slow_echo).start())
    host, port = q.server.host, q.server.port
    try:
        for i in range(6):
            status, _ = _request(host, port, "/slo_e2e",
                                 json.dumps({"i": i}).encode())
            assert status == 200
        status, body = _request(host, port, "/debug/slo")
        assert status == 200
        page = json.loads(body)
        ep = page["endpoints"]["slo_e2e"]
        assert ep["breaching"] is True
        assert ep["windows"]["fast5m"]["burn_rate"] > 1.0
        # the gauge tripped too (snapshot re-exports)
        assert metrics.gauge("slo_burn_rate", api="slo_e2e",
                             window="fast5m").value > 1.0
        status, body = _request(host, port, "/debug/tail")
        assert status == 200
        tail = json.loads(body)
        timed = [s for s in tail["samples"] if s["stages"]]
        assert timed, tail
        for s in timed:
            # stage decomposition partitions the request wall time
            assert s["stage_sum_seconds"] \
                == pytest.approx(s["seconds"], rel=0.05)
            assert s["trace_id"]
        assert tail["attribution"]["dominant_stage"] == "score"
        rendered = tail_report.render_text(tail)
        assert "tail is" in rendered and "score" in rendered
        assert "roofline" in rendered        # the remediation hint
    finally:
        q.stop()


def test_slo_and_tail_ride_the_debug_funnel():
    """Both routes are in DEBUG_ROUTES and debug_body renders them —
    the single-funnel contract that keeps engines from drifting."""
    paths = dict(DEBUG_ROUTES)
    assert paths["slo"] == "/debug/slo"
    assert paths["tail"] == "/debug/tail"
    body, ctype = debug_body("slo", "api")
    assert ctype == "application/json"
    assert json.loads(body)["configured"] is False
    body, _ = debug_body("tail", "api")
    assert json.loads(body)["samples"] == []


# ---------------------------------------------------------------------------
# Federation fold
# ---------------------------------------------------------------------------


class TestFederationFold:
    def _fed_with(self, exposition):
        fed = MetricsFederator(lambda: [], interval=1.0)
        st = fed._worker("w1")
        st.families = parse_prometheus_text(exposition)
        st.last_success = time.time()
        return fed

    def test_burn_raises_autoscale_hint(self):
        fed = self._fed_with(
            "# TYPE serving_queue_depth gauge\n"
            'serving_queue_depth{api="a"} 0\n'
            "# TYPE slo_burn_rate gauge\n"
            'slo_burn_rate{api="a",window="fast5m"} 40\n'
            'slo_burn_rate{api="a",window="slow1h"} 2\n')
        hint = fed.autoscale_hint()
        # max across series, NOT their sum (42 would double-count the
        # same breach across windows)
        assert hint["slo_burn_rate_max"] == 40.0
        assert hint["hint"] == 40.0 and hint["queue_hint"] == 0.0
        assert hint["workers"]["w1"]["slo_burn_rate_max"] == 40.0
        assert metrics.gauge("cluster_autoscale_hint").value == 40.0
        over = fed.slo_overview()
        assert over["max_burn_rate"] == 40.0
        assert over["workers"]["w1"]["burn_rate_max"] == 40.0

    def test_burn_within_budget_adds_no_pressure(self):
        fed = self._fed_with(
            "# TYPE serving_queue_depth gauge\n"
            'serving_queue_depth{api="a"} 2\n'
            "# TYPE slo_burn_rate gauge\n"
            'slo_burn_rate{api="a",window="fast5m"} 0.5\n')
        hint = fed.autoscale_hint()
        assert hint["slo_burn_rate_max"] == 0.5
        assert hint["hint"] == 2.0           # queue depth only

    def test_gateway_debug_slo_carries_cluster_view(self):
        fed = self._fed_with(
            "# TYPE slo_burn_rate gauge\n"
            'slo_burn_rate{api="a",window="fast5m"} 3\n')
        body, _ = debug_body("slo", "gw", federation=fed)
        page = json.loads(body)
        assert page["cluster"]["max_burn_rate"] == 3.0
