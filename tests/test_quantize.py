"""Quantized predict lane: int8/bf16 end to end against the f32 truth.

The int8 lane's safety argument has three legs, each pinned here:

* **Routing is bit-exact.** Features and thresholds quantize onto the
  model's OWN binning grid (``searchsorted`` left on the binner's upper
  bounds — the strict-compare convention device binning uses), so
  ``x > thr`` and ``q(x) > q(thr)`` agree exactly; the only accuracy
  delta comes from per-tree symmetric leaf quantization (amax/127).
  The cross-dtype equivalence tests pin that delta.
* **Resolution happens once, before any cache key.** Unknown env values
  degrade loudly to f32, imported models without a binner grid degrade
  with a reason, and the predictor cache key carries the resolved lane
  — a pickled booster under the same env hits the same executable.
* **The serving path stages narrow bytes.** Slot-table admission
  quantizes request rows into uint8 staging buffers (4x fewer bytes
  per h2d), bucket views stay zero-copy, and quantized executables
  ride the same AOT bundle machinery as f32 (warm start = zero
  compiles).
"""

import os
import pickle

import numpy as np
import pytest

from mmlspark_tpu.models.gbdt import quantize
from mmlspark_tpu.models.gbdt.booster import (Booster, LightGBMDataset,
                                              train_booster)
from mmlspark_tpu.models.gbdt.growth import GrowConfig


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(7)


@pytest.fixture(scope="module")
def binary(rng):
    X = rng.normal(size=(600, 8)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    b = train_booster(X, y, objective="binary", num_iterations=10,
                      cfg=GrowConfig(num_leaves=15), max_bin=63)
    return b, X, y


@pytest.fixture(scope="module")
def multiclass(rng):
    X = rng.normal(size=(600, 6)).astype(np.float32)
    y = (np.digitize(X[:, 0], [-0.5, 0.5])).astype(np.float32)
    b = train_booster(X, y, objective="multiclass", num_class=3,
                      num_iterations=6, cfg=GrowConfig(num_leaves=15),
                      max_bin=63)
    return b, X, y


@pytest.fixture(scope="module")
def regression(rng):
    X = rng.normal(size=(600, 8)).astype(np.float32)
    y = (X[:, 0] * 2 + np.sin(X[:, 1])).astype(np.float32)
    b = train_booster(X, y, objective="regression", num_iterations=10,
                      cfg=GrowConfig(num_leaves=15), max_bin=63)
    return b, X, y


# ---------------------------------------------------------------------------
# the resolver funnel
# ---------------------------------------------------------------------------


class TestResolvePredictDtype:
    def test_default_is_f32(self, monkeypatch):
        monkeypatch.delenv(quantize.PREDICT_DTYPE_ENV, raising=False)
        assert quantize.resolve_predict_dtype(None, max_bin=63) == "f32"

    def test_env_pins_the_lane(self, monkeypatch):
        monkeypatch.setenv(quantize.PREDICT_DTYPE_ENV, "int8")
        assert quantize.resolve_predict_dtype(None, max_bin=63) == "int8"

    def test_explicit_overrides_env(self, monkeypatch):
        monkeypatch.setenv(quantize.PREDICT_DTYPE_ENV, "int8")
        assert quantize.resolve_predict_dtype("bf16", max_bin=63) == "bf16"

    def test_unknown_env_degrades_unknown_explicit_raises(self, monkeypatch):
        monkeypatch.setenv(quantize.PREDICT_DTYPE_ENV, "fp4")
        assert quantize.resolve_predict_dtype(None, max_bin=63) == "f32"
        with pytest.raises(ValueError):
            quantize.resolve_predict_dtype("fp4", max_bin=63)

    def test_capability_degrades(self):
        # imported missing-semantics models and grid-less models cannot
        # take the int8 lane — degrade, never mis-route
        assert quantize.resolve_predict_dtype(
            "int8", has_mdec=True, max_bin=63) == "f32"
        assert quantize.resolve_predict_dtype(
            "int8", has_mdec=False, max_bin=0) == "f32"
        assert quantize.resolve_predict_dtype(
            "int8", has_mdec=False, max_bin=1000) == "f32"

    def test_booster_resolved_predict_dtype(self, binary):
        b, _X, _y = binary
        assert b.resolved_predict_dtype("int8") == "int8"
        # a .txt roundtrip loses the binner grid -> int8 degrades
        b2 = Booster.from_string(b.to_lightgbm_string())
        assert b2.resolved_predict_dtype("int8") == "f32"


class TestGridQuantization:
    def test_feature_quantization_matches_training_binning(self, binary):
        # the whole routing-exactness argument: q(x) computed on the host
        # equals the bin ids training used (strict-compare, NaN -> 0)
        b, X, _y = binary
        ub = quantize.feature_bounds(b.binner_state)
        Xn = X.copy()
        Xn[::7, 0] = np.nan
        q = quantize.quantize_features(Xn, ub)
        assert q.dtype == np.uint8
        for f in range(X.shape[1]):
            expect = np.searchsorted(ub[f], Xn[:, f], side="left")
            expect[~np.isfinite(Xn[:, f])] = 0
            np.testing.assert_array_equal(q[:, f], expect)

    def test_threshold_feature_order_is_preserved(self, binary):
        # x > thr  <=>  q(x) > q(thr) for every (feature, threshold) the
        # model actually splits on — routing is bit-exact by construction
        b, X, _y = binary
        ub = quantize.feature_bounds(b.binner_state)
        trees = b.trees
        internal = ~trees.is_leaf
        feats = np.asarray(trees.feat)[internal].astype(np.int64)
        thrs = np.asarray(b.thr_raw)[internal].astype(np.float32)
        qthr = quantize.quantize_thresholds(
            np.asarray(b.thr_raw, np.float32),
            np.asarray(trees.feat), ub)[internal]
        qX = quantize.quantize_features(X, ub)
        for f, t, qt in zip(feats[:64], thrs[:64], qthr[:64]):
            col, qcol = X[:, f], qX[:, f].astype(np.int32)
            np.testing.assert_array_equal(col > t, qcol > qt)


# ---------------------------------------------------------------------------
# cross-dtype equivalence (the accuracy-delta policy of performance.md)
# ---------------------------------------------------------------------------


class TestCrossDtypeEquivalence:
    def _deltas(self, booster, X, lane):
        ref = np.asarray(booster.predict(X))
        out = np.asarray(booster.predict(X, predict_dtype=lane))
        assert out.shape == ref.shape
        d = np.abs(out - ref)
        return float(d.max()), float(d.mean())

    @pytest.mark.parametrize("fixture", ["binary", "multiclass",
                                         "regression"])
    def test_int8_pinned_delta(self, fixture, request):
        b, X, _y = request.getfixturevalue(fixture)
        dmax, dmean = self._deltas(b, X, "int8")
        # leaf quantization only: scale-relative rounding, never routing
        if fixture == "regression":
            scale = float(np.abs(np.asarray(b.predict(X))).max()) or 1.0
            assert dmax / scale < 0.02 and dmean / scale < 0.004, \
                (dmax, dmean, scale)
        else:
            assert dmax < 0.01, dmax
            assert dmean < 0.002, dmean

    @pytest.mark.parametrize("fixture", ["binary", "multiclass",
                                         "regression"])
    def test_bf16_pinned_mean_delta(self, fixture, request):
        # bf16 casts thresholds AND features: rows landing exactly on a
        # rounded threshold can flip subtree — the max delta is allowed
        # to spike on those rows, the MEAN is what the lane pins
        b, X, _y = request.getfixturevalue(fixture)
        _dmax, dmean = self._deltas(b, X, "bf16")
        if fixture == "regression":
            scale = float(np.abs(np.asarray(b.predict(X))).max()) or 1.0
            assert dmean / scale < 0.01, (dmean, scale)
        else:
            assert dmean < 0.005, dmean

    def test_prequantized_input_passthrough_is_exact(self, binary):
        # rows already staged in the lane's dtype (the slot-table path)
        # skip host quantization entirely — same executable, same scores
        b, X, _y = binary
        ub = quantize.feature_bounds(b.binner_state)
        q = quantize.quantize_features(X, ub)
        via_raw = np.asarray(b.predict(X, predict_dtype="int8"))
        via_staged = np.asarray(b.predict(q, predict_dtype="int8"))
        np.testing.assert_allclose(via_staged, via_raw, rtol=0, atol=1e-6)


# ---------------------------------------------------------------------------
# cache key / pickle discipline
# ---------------------------------------------------------------------------


class TestPredictPlanKey:
    def test_key_carries_the_resolved_lane(self, binary):
        b, _X, _y = binary
        k_f32 = b.predict_plan(8).key
        k_int8 = b.predict_plan(8, predict_dtype="int8").key
        assert k_f32 != k_int8
        assert "f32" in k_f32 and "int8" in k_int8

    def test_degraded_lane_dedupes_into_f32_key(self, binary):
        b, _X, _y = binary
        b2 = Booster.from_string(b.to_lightgbm_string())  # grid-less
        assert b2.predict_plan(8, predict_dtype="int8").key == \
            b2.predict_plan(8).key

    def test_pickled_booster_hits_same_quantized_executable(self, binary):
        from mmlspark_tpu.models.gbdt import booster as bmod
        b, X, _y = binary
        p1 = np.asarray(b.predict(X[:16], predict_dtype="int8"))
        key = b.predict_plan(16, predict_dtype="int8").key
        assert key in bmod._PREDICT_CACHE
        n_keys = len(bmod._PREDICT_CACHE)
        b2 = pickle.loads(pickle.dumps(b))
        p2 = np.asarray(b2.predict(X[:16], predict_dtype="int8"))
        assert len(bmod._PREDICT_CACHE) == n_keys, \
            "pickle roundtrip recompiled the quantized lane"
        np.testing.assert_allclose(p2, p1, rtol=0, atol=1e-6)


# ---------------------------------------------------------------------------
# int8 slot-table admission (serving)
# ---------------------------------------------------------------------------


class TestSlotTableAdmission:
    def test_int8_round_trip_zero_copy_and_live_scores(self, binary):
        from mmlspark_tpu.io.aserve.slots import SlotTable
        b, X, _y = binary
        ub = quantize.feature_bounds(b.binner_state)
        quantizer = quantize.row_quantizer("int8", ub)
        F = X.shape[1]
        table = SlotTable(slots=8, width=F, dtype=np.uint8,
                          quantizer=quantizer)
        try:
            self._round_trip(b, X, table, ub)
        finally:
            table.release_claim()

    def _round_trip(self, b, X, table, ub):
        from mmlspark_tpu.io.aserve.slots import SlotTable
        F = X.shape[1]
        n_live = 5
        for i in range(n_live):
            table.write(i, X[i])
        buf = table.flip()
        # staging really is narrow: uint8 slots, 4x fewer h2d bytes
        assert buf.dtype == np.uint8 and buf.nbytes == 8 * F
        view, bucket = SlotTable.bucket_view(buf, n_live)
        assert bucket == 8
        assert np.shares_memory(view, buf), "bucket view copied"
        np.testing.assert_array_equal(
            view[:n_live], quantize.quantize_features(X[:n_live], ub))
        # staged rows score through the int8 lane's pass-through; compare
        # LIVE rows only — bucket padding repeats row 0, not X[5:8]
        preds = np.asarray(b.predict(view, predict_dtype="int8"))[:n_live]
        ref = np.asarray(b.predict(X[:n_live]))
        assert float(np.abs(preds - ref).max()) < 0.01

    def test_hbm_claim_shrinks_4x(self):
        from mmlspark_tpu.io.aserve.slots import SlotTable
        wide = SlotTable(slots=16, width=32)
        narrow = SlotTable(slots=16, width=32, dtype=np.uint8)
        try:
            wide_bytes = sum(buf.nbytes for buf in wide._bufs)
            narrow_bytes = sum(buf.nbytes for buf in narrow._bufs)
            assert wide_bytes == 4 * narrow_bytes
        finally:
            wide.release_claim()
            narrow.release_claim()

    def test_row_quantizer_lanes(self, binary):
        b, X, _y = binary
        ub = quantize.feature_bounds(b.binner_state)
        assert quantize.row_quantizer("f32", None) is None
        qz = quantize.row_quantizer("int8", ub)
        np.testing.assert_array_equal(
            qz(X[0]), quantize.quantize_features(X[:1], ub)[0])
        bz = quantize.row_quantizer("bf16", None)
        assert bz(X[0]).dtype == quantize.staging_dtype("bf16")


# ---------------------------------------------------------------------------
# ingest: int8 device matrices + host-quant streaming
# ---------------------------------------------------------------------------


class TestQuantizedIngest:
    def test_int8_bin_dtype_device_matrix(self, rng):
        X = rng.normal(size=(256, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        ds = LightGBMDataset.construct(X, y, max_bin=63, bin_dtype="int8")
        assert str(ds.Xbt_d.dtype) == "int8"
        with pytest.raises(ValueError):
            LightGBMDataset.construct(X, y, max_bin=255, bin_dtype="int8")

    def test_host_quant_streaming_parity(self, rng, tmp_path,
                                         monkeypatch):
        # MMLSPARK_TPU_INGEST_HOST_QUANT=1 bins chunks on the host and
        # ships uint8 — the device matrix must be bit-identical to the
        # default path's device-binned one
        from mmlspark_tpu.models.gbdt.ingest import write_shards
        X = rng.normal(size=(512, 5)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        xdir, ydir = str(tmp_path / "x"), str(tmp_path / "y")
        write_shards(list(np.array_split(X, 4)), xdir)
        write_shards(list(np.array_split(y, 4)), ydir)
        monkeypatch.delenv("MMLSPARK_TPU_INGEST_HOST_QUANT", raising=False)
        ds0 = LightGBMDataset.construct(path=xdir, label_path=ydir,
                                        max_bin=63, chunk_rows=128)
        monkeypatch.setenv("MMLSPARK_TPU_INGEST_HOST_QUANT", "1")
        ds1 = LightGBMDataset.construct(path=xdir, label_path=ydir,
                                        max_bin=63, chunk_rows=128)
        assert str(ds1.Xbt_d.dtype) == str(ds0.Xbt_d.dtype)
        assert bool((np.asarray(ds0.Xbt_d) ==
                     np.asarray(ds1.Xbt_d)).all())


# ---------------------------------------------------------------------------
# bundles: quantized executables warm-start like f32 ones
# ---------------------------------------------------------------------------


class TestQuantizedBundle:
    def test_int8_bundle_prewarm_zero_compile(self, binary, tmp_path):
        from mmlspark_tpu.bundles.bundle import build_bundle, prewarm
        from mmlspark_tpu.models.gbdt import booster as bmod
        from mmlspark_tpu.observability import flight
        b, X, _y = binary
        model = str(tmp_path / "m.npz")
        b.save(model)
        out = str(tmp_path / "m.bundle")
        man = build_bundle(model, out, batch_sizes=[8],
                           predict_dtypes=("f32", "int8"))
        assert sorted(e["predict_dtype"] for e in man["entries"]) == \
            ["f32", "int8"]

        bmod._PREDICT_CACHE.clear()
        b2 = Booster.load(model)
        res = prewarm(model, out, boosters=[b2])
        assert res["entries_loaded"] == 2, res

        def compiles():
            return len([e for e in flight.events()
                        if e.get("event") == "compile"])
        n0 = compiles()
        p_int8 = np.asarray(b2.predict(X[:8], predict_dtype="int8"))
        p_f32 = np.asarray(b2.predict(X[:8]))
        assert compiles() == n0, "prewarmed lane compiled anyway"
        assert float(np.abs(p_int8 - p_f32).max()) < 0.01

    def test_degraded_lane_dedupes_in_plan_enumeration(self, binary):
        from mmlspark_tpu.models.gbdt.booster import iter_predict_plans
        b, _X, _y = binary
        txt = Booster.from_string(b.to_lightgbm_string())  # grid-less
        metas = [meta for meta, _plan in iter_predict_plans(
            txt, [8], dtypes=("f32", "int8"))]
        assert all(m["predict_dtype"] == "f32" for m in metas)
        assert len(metas) == 1, "degraded int8 plan did not dedupe"
