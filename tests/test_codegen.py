"""Codegen layer tests: generated mmlspark namespace, accessors, docs.

Reference parity: codegen/CodeGen.scala walks every Wrappable stage and
emits PySpark wrappers + tests; these tests generate into a tmp dir, import
the result, and exercise the generated surface end-to-end.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from mmlspark_tpu.codegen import (attach_pyspark_accessors, generate_all,
                                  generate_api_docs,
                                  generate_compat_namespace,
                                  generate_migration_table)
from mmlspark_tpu.core.dataset import Dataset


@pytest.fixture(scope="module")
def generated(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("api"))
    result = generate_all(out)
    sys.path.insert(0, out)
    yield out, result
    sys.path.remove(out)
    for mod in [m for m in sys.modules if m.startswith("mmlspark")
                and not m.startswith("mmlspark_tpu")]:
        del sys.modules[mod]


def test_namespace_layout(generated):
    out, result = generated
    assert os.path.exists(os.path.join(out, "mmlspark", "__init__.py"))
    assert os.path.exists(os.path.join(out, "mmlspark", "lightgbm.py"))
    assert os.path.exists(os.path.join(out, "mmlspark", "io", "http.py"))
    assert len(result["namespace_files"]) > 10


def test_reference_style_imports_work(generated):
    from mmlspark.lightgbm import LightGBMClassifier
    from mmlspark.vw import VowpalWabbitClassifier  # noqa: F401
    from mmlspark.cognitive import TextSentiment  # noqa: F401
    from mmlspark.stages import DropColumns  # noqa: F401
    from mmlspark.cyber import AccessAnomaly  # noqa: F401
    from mmlspark.cntk import CNTKModel, DNNModel

    assert CNTKModel is DNNModel               # reference-name alias
    assert LightGBMClassifier.__module__ == "mmlspark_tpu.models.gbdt.api"


def test_generated_accessors_roundtrip_and_fit(generated):
    from mmlspark.lightgbm import LightGBMClassifier

    clf = (LightGBMClassifier()
           .setNumIterations(4).setNumLeaves(7).setMinDataInLeaf(2)
           .setLabelCol("label"))
    assert clf.getNumIterations() == 4
    assert clf.getNumLeaves() == 7
    rng = np.random.default_rng(0)
    X = rng.normal(size=(60, 3)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    model = clf.fit(Dataset({"features": X, "label": y}))
    acc = (model.transform(Dataset({"features": X})).array("prediction")
           == y).mean()
    assert acc > 0.85


def test_accessor_reflection_covers_all_params():
    from mmlspark_tpu.models.vw.api import VowpalWabbitRegressor

    cls = attach_pyspark_accessors(VowpalWabbitRegressor)
    stage = cls()
    for p in cls.params():
        cap = p.name[0].upper() + p.name[1:]
        assert callable(getattr(stage, f"set{cap}"))
        assert callable(getattr(stage, f"get{cap}"))
    assert stage.setNumPasses(7).getNumPasses() == 7


def test_api_docs_generated(generated, tmp_path):
    path = generate_api_docs(str(tmp_path / "API.md"))
    text = open(path).read()
    assert "## mmlspark.lightgbm" in text
    assert "LightGBMClassifier" in text
    assert "numIterations" in text
    assert "## mmlspark.cyber" in text


def test_migration_table_generated(generated, tmp_path):
    path = generate_migration_table(str(tmp_path / "MIG.md"))
    text = open(path).read()
    # every namespace section and a spot-check row per major family
    assert "## mmlspark.lightgbm" in text
    assert "`from mmlspark.lightgbm import LightGBMClassifier`" in text
    assert "`mmlspark_tpu.models.gbdt.api.LightGBMClassifier`" in text
    assert "## mmlspark.vw" in text
    # checked-in copy must match a fresh regeneration (sync gate, same as
    # the namespace modules)
    repo_copy = os.path.join(os.path.dirname(__file__), "..",
                             "python_api", "MIGRATION_TABLE.md")
    assert open(repo_copy).read() == text


def test_r_wrappers_generated(generated):
    out, result = generated
    assert any(p.endswith("mmlspark_lightgbm.R") for p in result["r_files"])
    core = next(p for p in result["r_files"]
               if p.endswith("mmlspark_runtime.R"))
    assert "mmlspark_initialize" in open(core).read()
    lgbm = next(p for p in result["r_files"]
                if p.endswith("mmlspark_lightgbm.R"))
    text = open(lgbm).read()
    assert "ml_light_g_b_m_classifier <- function(...)" in text \
        or "ml_light_gbm_classifier" in text or "LightGBMClassifier" in text
    # balanced braces (rough syntax sanity for every generated R file)
    for p in result["r_files"]:
        s = open(p).read()
        assert s.count("{") == s.count("}"), p


def test_generated_smoke_tests_pass(generated):
    out, result = generated
    env = dict(os.environ, PYTHONPATH=out + os.pathsep +
               os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", result["tests"], "-q", "-p",
         "no:cacheprovider"],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
