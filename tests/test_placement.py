"""The placement funnel + topology-independent sharded GBDT training.

Two contracts land here:

* **placement decisions are funneled and observable** — every estimator's
  replicate-vs-batch-shard choice routes through
  ``parallel/placement.plan_for`` and lands in the flight ring as a
  ``placement`` event (deduped per distinct decision), and the resolver
  helpers (``resolve_hist_blocks``, the ``MMLSPARK_TPU_MESH_DEVICES`` mesh
  cap) behave per their docs.

* **cross-device-count tree identity** — with the canonical blocked
  reduction pinned (``GrowConfig.hist_blocks=8``), training the same data
  on 1, 2 and 8 virtual devices produces BIT-IDENTICAL boosters
  (``model_string()`` equality), for all three histogram engines, across
  depthwise/leafwise growth, categorical splits and int8 quantized
  gradients. Each run is a subprocess because the device count is fixed
  at jax init (``xla_force_host_platform_device_count``).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ENGINES = ["onehot", "scatter", "pallas"]

_IDENT_DRIVER = """
import sys
import numpy as np
from mmlspark_tpu.models.gbdt.booster import train_booster
from mmlspark_tpu.models.gbdt.growth import GrowConfig

out = sys.argv[1]
rng = np.random.default_rng(7)
n = 960
X = rng.normal(size=(n, 6)).astype(np.float32)
X[:, 3] = rng.integers(0, 8, size=n)
y = (X[:, 0] * X[:, 1] + 0.4 * X[:, 2] > 0).astype(np.float32)
parts = []
for tag, policy, quant, cats in [
        ("depthwise", "depthwise", False, ()),
        ("leafwise", "leafwise", False, ()),
        ("categorical", "depthwise", False, (3,)),
        ("quantized", "depthwise", True, ())]:
    cfg = GrowConfig(num_leaves=7, min_data_in_leaf=5, growth_policy=policy,
                     quantized_grad=quant, hist_blocks=8)
    b = train_booster(X, y, objective="binary", num_iterations=2, cfg=cfg,
                      max_bin=63, bin_sample_count=n, seed=0,
                      categorical_features=cats)
    parts.append(tag + chr(10) + b.model_string())
open(out, "w").write((chr(10) + "====" + chr(10)).join(parts))
"""


def _run_ident(tmp_path, engine: str, devices: int) -> dict:
    """One subprocess fit at a pinned engine/device-count; returns
    {config_tag: model_string}."""
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": ROOT,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={devices}",
        "MMLSPARK_TPU_HIST_ENGINE": engine,
        # repeat runs hit warm executables (the suite's persistent cache)
        "JAX_COMPILATION_CACHE_DIR": "/tmp/jax_test_cache",
    })
    if engine == "pallas":
        env["MMLSPARK_TPU_PALLAS_INTERPRET"] = "1"
    else:
        env.pop("MMLSPARK_TPU_PALLAS_INTERPRET", None)
    out = tmp_path / f"model.{engine}.{devices}.txt"
    r = subprocess.run([sys.executable, "-c", _IDENT_DRIVER, str(out)],
                       env=env, capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, (engine, devices, r.stderr[-3000:])
    chunks = out.read_text().split("\n====\n")
    return {c.split("\n", 1)[0]: c.split("\n", 1)[1] for c in chunks}


class TestCrossDeviceTreeIdentity:
    """The data_parallel contract, stronger than LightGBM's own: not just
    the same split decisions, the same bytes. The canonical blocked
    reduction (hist_blocks=8) pins the f32 fold order, the quantization
    scales and the stochastic-rounding bits to GLOBAL row geometry, so the
    mesh size stops being an input to the model."""

    # tier-1 runs the backend-default engine (scatter on CPU); the full
    # engine matrix rides the `slow` tier + the ci_check dryrun_multichip
    # lane, keeping the tier-1 wall budget honest (9 subprocess fits would
    # cost ~6 min on the 2-CPU runner)
    @pytest.mark.parametrize(
        "engine",
        [e if e == "scatter" else pytest.param(e, marks=pytest.mark.slow)
         for e in ENGINES])
    def test_1_2_8_devices_bit_identical(self, tmp_path, engine):
        runs = {k: _run_ident(tmp_path, engine, k) for k in (1, 2, 8)}
        for tag in runs[1]:
            for k in (2, 8):
                assert runs[k][tag] == runs[1][tag], (
                    f"{engine}/{tag}: {k}-device trees differ from "
                    "1-device trees")
        # and the fits actually trained something nontrivial
        assert all(len(s) > 200 for s in runs[1].values())


class TestHistBlocksResolution:
    def test_auto_default_is_plain(self, mesh8, monkeypatch):
        from mmlspark_tpu.parallel import placement
        monkeypatch.delenv("MMLSPARK_TPU_HIST_BLOCKS", raising=False)
        assert placement.resolve_hist_blocks("auto", mesh8, 960) == 0

    def test_env_knob_engages_and_degrades(self, mesh8, monkeypatch):
        from mmlspark_tpu.observability import flight
        from mmlspark_tpu.parallel import placement
        monkeypatch.setenv("MMLSPARK_TPU_HIST_BLOCKS", "8")
        assert placement.resolve_hist_blocks("auto", mesh8, 960) == 8
        # indivisible padding: the env-knob path degrades with a flight
        # event instead of failing the fit
        before = len([e for e in flight.events()
                      if e.get("site") == "gbdt.hist_blocks"])
        assert placement.resolve_hist_blocks("auto", mesh8, 8 * 123 + 4) == 0
        after = [e for e in flight.events()
                 if e.get("site") == "gbdt.hist_blocks"]
        assert len(after) == before + 1
        assert after[-1]["decision"] == "fallback_plain"

    def test_explicit_invalid_raises(self, mesh8):
        from mmlspark_tpu.parallel import placement
        with pytest.raises(ValueError, match="multiple"):
            # 6 blocks cannot tile an 8-shard data axis
            placement.resolve_hist_blocks(6, mesh8, 960)
        with pytest.raises(ValueError, match="row count"):
            placement.resolve_hist_blocks(8, mesh8, 8 * 100 + 4)
        with pytest.raises(ValueError, match="voting"):
            placement.resolve_hist_blocks(8, mesh8, 960, voting=True)

    def test_blocked_quantized_totals_widen_before_the_fold(self):
        """Per-BLOCK quantized sums accumulate int32 (bounded by q_max *
        rows_per_block) but must widen to f32 before the cross-block fold
        — folding raw int32 across all blocks would wrap once q_max *
        total_rows crosses 2^31 (~17M rows at q_max=127)."""
        import jax.numpy as jnp

        from mmlspark_tpu.models.gbdt.growth import _stat_totals
        base = (jnp.ones((3, 64), jnp.int8) * 3)
        qs = jnp.asarray([0.5, 0.5, 0.5], jnp.float32)
        tot = _stat_totals(base, qs, None, 8, 8)
        assert tot.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(tot), [96.0, 96.0, 96.0])

    def test_resolved_value_keys_the_config(self):
        """hist_blocks rides GrowConfig, so it reaches every
        compiled-program cache key for free — but it must be CONCRETE by
        growth time (same contract as hist_subtraction='auto')."""
        import jax.numpy as jnp

        from mmlspark_tpu.models.gbdt.growth import (
            GrowConfig, _hist_block_geometry)
        assert _hist_block_geometry(
            GrowConfig(hist_blocks="auto"), None, 960) == (0, 960)
        assert _hist_block_geometry(
            GrowConfig(hist_blocks=8), None, 960) == (8, 120)
        with pytest.raises(ValueError, match="tile"):
            _hist_block_geometry(GrowConfig(hist_blocks=7), None, 960)
        del jnp


class TestPlacementEvents:
    @pytest.fixture(autouse=True)
    def _fresh_decisions(self):
        from mmlspark_tpu.parallel import placement
        placement.reset_decision_log()
        yield
        placement.reset_decision_log()

    @staticmethod
    def _placement_events():
        from mmlspark_tpu.observability import flight
        return [e for e in flight.events() if e.get("kind") == "placement"]

    def test_gbdt_fit_and_predict_decisions(self):
        from mmlspark_tpu.core.dataset import Dataset
        from mmlspark_tpu.models.gbdt.api import LightGBMClassifier

        rng = np.random.default_rng(3)
        X = rng.normal(size=(480, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float64)
        ds = Dataset({"features": X, "label": y})
        n0 = len(self._placement_events())
        model = LightGBMClassifier(numIterations=2, numLeaves=4,
                                   minDataInLeaf=5).fit(ds)
        model.transform(ds)
        ev = self._placement_events()[n0:]
        by_site = {e["site"]: e for e in ev}
        assert by_site["gbdt.ingest"]["decision"] == "shard_rows"
        assert by_site["gbdt.fit"]["decision"] == "shard_rows"
        assert by_site["gbdt.fit"]["backend"] == "cpu"
        assert by_site["gbdt.predict"]["decision"] == "replicate"
        # dedup: an identical second fit emits no new decision events
        n1 = len(self._placement_events())
        LightGBMClassifier(numIterations=2, numLeaves=4,
                           minDataInLeaf=5).fit(ds)
        dup = [e for e in self._placement_events()[n1:]
               if e["site"] in by_site]
        assert dup == []

    def test_plan_for_unit(self, mesh8):
        from mmlspark_tpu.parallel import placement
        p = placement.plan_for("unit.test", mesh=mesh8, rows=64)
        assert p.decision == "shard_rows" and p.nshards == 8
        assert p.backend == "cpu" and p.donate_buffers is False
        # rows are recorded but do NOT flip the decision: shard sites pad
        # short batches to the shard multiple and shard them anyway, so
        # the logged decision must match what shard_rows actually does
        p2 = placement.plan_for("unit.test2", mesh=mesh8, rows=3)
        assert p2.decision == "shard_rows"
        assert placement.shard_rows(np.arange(3.0), mesh8)[0].shape[0] == 8
        ev = self._placement_events()
        assert any(e["site"] == "unit.test" for e in ev)
        assert any(e["site"] == "unit.test2" and e["rows"] == 3
                   for e in ev)

    def test_plan_shardings(self, mesh8):
        from mmlspark_tpu.parallel import placement
        p = placement.plan_for("unit.shardings", mesh=mesh8, rows=64)
        sh = p.batch(ndim=2)
        assert sh.spec == placement.pspec("data", None)
        assert p.replicated().spec == placement.pspec()


class TestMeshDeviceCap:
    def test_mesh_devices_knob_caps_default(self, monkeypatch):
        from mmlspark_tpu.parallel.mesh import make_mesh
        monkeypatch.setenv("MMLSPARK_TPU_MESH_DEVICES", "2")
        assert make_mesh().shape["data"] == 2
        # explicit shape/devices are honored as given
        assert make_mesh({"data": 8}).shape["data"] == 8
        monkeypatch.delenv("MMLSPARK_TPU_MESH_DEVICES")
        assert make_mesh().shape["data"] == 8
