"""Fast source-level lint for the telemetry layer.

Two invariants keep the observability subsystem safe to import from every
other layer:

* **No import cycle.** Every package (core, io, train, models, ...)
  imports ``mmlspark_tpu.observability`` at module top level, so
  observability itself must never import those packages back at top level
  — its only framework dependency (``utils.profiling``) is deferred into
  function bodies. Enforced by AST walk + a fresh-interpreter import.
* **Valid metric names.** Every metric name passed as a literal to
  ``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` must match
  ``[a-z_]+`` or the Prometheus text rendering stops parsing.
"""

import ast
import os
import re
import subprocess
import sys

import pytest

_PKG_ROOT = os.path.join(os.path.dirname(__file__), "..", "mmlspark_tpu")
_NAME_RE = re.compile(r"^[a-z_]+$")
_METRIC_FACTORIES = {"counter", "gauge", "histogram",
                     "safe_counter", "safe_gauge", "safe_histogram"}


def _py_files(root):
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _parse(path):
    with open(path, encoding="utf-8") as f:
        return ast.parse(f.read(), filename=path)


def _top_level_imports(tree):
    """(module, level) pairs imported at module scope (not inside defs)."""
    out = []
    for node in ast.iter_child_nodes(tree):
        # top-level try/if wrappers around imports still count
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(n, ast.Import):
                out.extend((a.name, 0) for a in n.names)
            elif isinstance(n, ast.ImportFrom):
                out.append((n.module or "", n.level))
            else:
                stack.extend(ast.iter_child_nodes(n))
    return out


def test_observability_has_no_top_level_framework_imports():
    """observability/* may import stdlib and its own siblings at top level,
    nothing else from mmlspark_tpu — that is what makes 'every layer
    imports observability' cycle-free by construction."""
    obs_dir = os.path.join(_PKG_ROOT, "observability")
    offenders = []
    for path in _py_files(obs_dir):
        for mod, level in _top_level_imports(_parse(path)):
            top = mod.split(".")[0]
            if level >= 2 or top == "mmlspark_tpu":
                # parent-relative (..) or absolute framework import
                offenders.append(f"{os.path.basename(path)}: "
                                 f"{'.' * level}{mod}")
            elif level == 1 and top not in (
                    "metrics", "spans", "device", "tracing", "flight",
                    "logging", "watchdog", "federation", ""):
                offenders.append(f"{os.path.basename(path)}: .{mod}")
    assert not offenders, (
        "observability must defer framework imports into function bodies "
        f"(import-cycle guard); found top-level: {offenders}")


def test_observability_imports_standalone():
    """A fresh interpreter can import the telemetry layer on its own —
    the runtime proof of the AST rule above (and it keeps the import
    cheap: no jax, no framework)."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         "import mmlspark_tpu.observability as o\n"
         "assert 'jax' not in sys.modules, 'observability imported jax'\n"
         "o.counter('lint_total').inc()\n"
         "print(o.get_registry().render_prometheus())"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(_PKG_ROOT))
    assert proc.returncode == 0, proc.stderr
    assert "lint_total 1" in proc.stdout


def _literal_metric_names():
    """Every string literal passed as the metric name to a
    counter/gauge/histogram call anywhere under mmlspark_tpu/."""
    found = []
    for path in _py_files(_PKG_ROOT):
        for node in ast.walk(_parse(path)):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else None
            if name not in _METRIC_FACTORIES or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and \
                    isinstance(first.value, str):
                found.append((os.path.relpath(path, _PKG_ROOT),
                              node.lineno, first.value))
    return found


def test_metric_name_literals_are_prometheus_safe():
    names = _literal_metric_names()
    # the instrumentation exists: an empty scan would mean this lint is
    # silently matching nothing
    assert len(names) >= 10, names
    bad = [(p, ln, n) for p, ln, n in names if not _NAME_RE.match(n)]
    assert not bad, f"metric names must match [a-z_]+: {bad}"


def test_metric_names_unique_per_kind():
    """One metric name, one kind — the registry raises at runtime on a
    kind conflict; catch it at lint time across the whole tree."""
    kinds = {}
    conflicts = []
    for path in _py_files(_PKG_ROOT):
        for node in ast.walk(_parse(path)):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            kind = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else None
            if kind not in _METRIC_FACTORIES or not node.args:
                continue
            kind = kind.removeprefix("safe_")  # same family either way
            first = node.args[0]
            if isinstance(first, ast.Constant) and \
                    isinstance(first.value, str):
                prev = kinds.setdefault(first.value, kind)
                if prev != kind:
                    conflicts.append((first.value, prev, kind))
    assert not conflicts, conflicts


def _loop_body_calls(fn_node):
    """Call nodes inside For/While bodies of ``fn_node``, excluding nested
    function/lambda bodies (helpers DEFINED outside the loop and merely
    called inside it are the sanctioned pattern)."""
    calls = []
    for node in ast.walk(fn_node):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        stack = list(node.body) + list(node.orelse)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                calls.append(n)
            stack.extend(ast.iter_child_nodes(n))
    return calls


def test_streaming_chunk_loops_have_no_host_syncs():
    """Hot-path guard for the double-buffered streaming loops
    (io/streaming.py): ``np.asarray`` / ``float()`` inside a per-chunk
    loop body is a host sync that serializes device compute against the
    loop and defeats the prefetch overlap. Materialization belongs in a
    helper defined OUTSIDE the loop (e.g. ``_score``), where it is one
    deliberate, testable sync per chunk."""
    tree = _parse(os.path.join(_PKG_ROOT, "io", "streaming.py"))
    fns = [n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)]
    assert any(f.name == "stream_apply" for f in fns)
    offenders = []
    for fn in fns:
        for call in _loop_body_calls(fn):
            callee = call.func
            name = callee.attr if isinstance(callee, ast.Attribute) else \
                callee.id if isinstance(callee, ast.Name) else None
            if name in ("asarray", "float"):
                offenders.append((fn.name, call.lineno, name))
    assert not offenders, (
        "host syncs inside per-chunk streaming loop bodies "
        f"(move into a pre-loop helper): {offenders}")


def test_booster_predict_path_takes_trees_as_arguments():
    """Hot-path guard for the device-resident predictor
    (models/gbdt/booster.py): the forest must ride as jit ARGUMENTS, not
    constants — ``jnp.asarray(self.trees...)`` (or a device_put of them)
    anywhere in the predictor build path would bake the trees into the
    executable, making it per-Booster and bringing back the
    recompile-after-unpickle serving stall this PR removed."""
    tree = _parse(os.path.join(_PKG_ROOT, "models", "gbdt", "booster.py"))
    predict_path = {"predict", "predict_raw", "_predict_device",
                    "_device_forest_args", "_device_active",
                    "_build_predict_program", "_predict_program"}
    fns = [n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)
           and n.name in predict_path]
    # the predictor build path exists — an empty scan would mean this
    # lint silently matches nothing
    assert len(fns) >= 4, sorted(f.name for f in fns)
    offenders = []
    for fn in fns:
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            callee = call.func
            name = callee.attr if isinstance(callee, ast.Attribute) else \
                callee.id if isinstance(callee, ast.Name) else None
            if name not in ("asarray", "array", "device_put"):
                continue
            # numpy host-side staging (np.asarray) is allowed; only
            # device placement of the raw tree arrays is baking
            mod = callee.value.id if (isinstance(callee, ast.Attribute)
                                      and isinstance(callee.value,
                                                     ast.Name)) else None
            if mod == "np":
                continue
            for arg in ast.walk(ast.Module(body=[ast.Expr(a) for a
                                                 in call.args],
                                           type_ignores=[])):
                if isinstance(arg, ast.Attribute) and arg.attr == "trees":
                    offenders.append((fn.name, call.lineno))
                    break
    assert not offenders, (
        "predictor build path must pass trees as packed jit arguments, "
        f"not bake them via jnp.asarray/device_put: {offenders}")


def _functions_containing(tree):
    """Map every AST node to its innermost enclosing function name."""
    owner = {}

    def walk(node, fn_name):
        for child in ast.iter_child_nodes(node):
            name = fn_name
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = child.name
            owner[child] = name
            walk(child, name)

    owner[tree] = None
    walk(tree, None)
    return owner


def test_io_handlers_route_through_shared_response_helper():
    """Every do_GET/do_POST branch in io/ must emit its response through
    serving.py's ``write_http_response`` — the shared status-counter
    funnel — so no handler branch can skip Content-Length, the
    per-status counters, or future response policy. A raw
    ``send_response`` call anywhere else under io/ is the violation."""
    io_dir = os.path.join(_PKG_ROOT, "io")
    offenders = []
    seen_helper = False
    for path in _py_files(io_dir):
        tree = _parse(path)
        owner = _functions_containing(tree)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "send_response"):
                continue
            fn = owner.get(node)
            if fn == "write_http_response" and \
                    os.path.basename(path) == "serving.py":
                seen_helper = True
                continue
            offenders.append((os.path.relpath(path, _PKG_ROOT),
                              node.lineno, fn))
    assert seen_helper, "write_http_response helper not found in serving.py"
    assert not offenders, (
        "io/ handlers must route responses through "
        f"serving.write_http_response: {offenders}")


def test_shard_map_routes_through_compat_funnel():
    """``parallel/compat.py`` is the ONE place the jax shard_map API skew
    (jax.shard_map vs jax.experimental.shard_map.shard_map, check_vma vs
    check_rep) is resolved. A bare ``jax.shard_map(`` — or a direct
    experimental import — anywhere else reintroduces the version skew
    that cost 240 tier-1 tests before the funnel existed."""
    compat_rel = os.path.join("parallel", "compat.py")
    repo_root = os.path.dirname(_PKG_ROOT)
    scan = list(_py_files(_PKG_ROOT))
    scan += list(_py_files(os.path.join(repo_root, "tests")))
    scan += list(_py_files(os.path.join(repo_root, "tools")))
    for fn in ("__graft_entry__.py", "bench.py", "graft_test_env.py"):
        p = os.path.join(repo_root, fn)
        if os.path.exists(p):
            scan.append(p)
    offenders = []
    for path in scan:
        if os.path.relpath(path, _PKG_ROOT) == compat_rel:
            continue
        for node in ast.walk(_parse(path)):
            if (isinstance(node, ast.Attribute)
                    and node.attr == "shard_map"
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "jax"):
                offenders.append((os.path.relpath(path, repo_root),
                                  node.lineno, "jax.shard_map"))
            elif (isinstance(node, ast.ImportFrom) and node.module
                    and node.module.startswith("jax.experimental.shard_map")):
                offenders.append((os.path.relpath(path, repo_root),
                                  node.lineno, f"from {node.module} import"))
    assert not offenders, (
        "shard_map must be imported from mmlspark_tpu.parallel.compat "
        f"(the version-skew funnel): {offenders}")


def _first_lineno(fn_node, match):
    """Smallest lineno inside ``fn_node`` for which ``match(node)``."""
    best = None
    for node in ast.walk(fn_node):
        if match(node):
            ln = getattr(node, "lineno", None)
            if ln is not None and (best is None or ln < best):
                best = ln
    return best


def test_auto_sentinel_resolved_before_program_cache_keys():
    """GrowConfig's backend-adaptive tri-states (hist_subtraction /
    compact_selector = "auto") must be resolved to concrete values BEFORE
    the config reaches any compiled-program cache key: an unresolved
    sentinel would alias programs across backends. Source-level pin:
    ``train_booster`` calls ``resolve_growth_backend`` before its first
    ``cache_key`` construction / ``_cached_program`` call, and the
    estimator layer's ``_grow_config`` routes through the resolver too.
    (tests/test_histogram_engines.py proves it at runtime by scanning the
    live step-cache keys after a default-config fit.)"""
    booster_py = os.path.join(_PKG_ROOT, "models", "gbdt", "booster.py")
    tree = _parse(booster_py)
    tb = next(n for n in ast.walk(tree)
              if isinstance(n, ast.FunctionDef) and n.name == "train_booster")

    def is_resolver_call(n):
        return (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id == "resolve_growth_backend")

    def is_cache_use(n):
        if isinstance(n, ast.Assign):
            return any(isinstance(t, ast.Name) and "cache_key" in t.id
                       for t in n.targets)
        return (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == "_cached_program")

    resolver_ln = _first_lineno(tb, is_resolver_call)
    cache_ln = _first_lineno(tb, is_cache_use)
    assert resolver_ln is not None, \
        "train_booster no longer resolves the 'auto' tri-states"
    assert cache_ln is not None, "lint matched no cache-key construction"
    assert resolver_ln < cache_ln, (
        f"resolve_growth_backend (line {resolver_ln}) must run before the "
        f"first cache-key construction (line {cache_ln})")

    api_py = os.path.join(_PKG_ROOT, "models", "gbdt", "api.py")
    gc = next(n for n in ast.walk(_parse(api_py))
              if isinstance(n, ast.FunctionDef) and n.name == "_grow_config")
    assert _first_lineno(gc, is_resolver_call) is not None, (
        "_grow_config must resolve 'auto' before handing GrowConfig to "
        "direct consumers (the sweep path bypasses train_booster)")


_LOG_FUNNEL = os.path.join("observability", "logging.py")


def test_no_raw_text_output_outside_logging_funnel():
    """``observability/logging.py`` is the ONE textual-output path for the
    framework: structured records via ``get_logger`` (JSON lines +
    flight ring + rate limit + trace ids) and ``console()`` for the few
    sanctioned CLI ready-lines. A bare ``print(`` or
    ``sys.stderr/stdout.write`` anywhere else under ``mmlspark_tpu/``
    bypasses all of that — records with no trace identity, no collection
    path, and no kill-switch discipline."""
    offenders = []
    for path in _py_files(_PKG_ROOT):
        if os.path.relpath(path, _PKG_ROOT) == _LOG_FUNNEL:
            continue
        for node in ast.walk(_parse(path)):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "print":
                offenders.append((os.path.relpath(path, _PKG_ROOT),
                                  node.lineno, "print("))
            elif (isinstance(node, ast.Attribute)
                    and node.attr == "write"
                    and isinstance(node.value, ast.Attribute)
                    and node.value.attr in ("stderr", "stdout")
                    and isinstance(node.value.value, ast.Name)
                    and node.value.value.id == "sys"):
                offenders.append((os.path.relpath(path, _PKG_ROOT),
                                  node.lineno,
                                  f"sys.{node.value.attr}.write"))
    assert not offenders, (
        "textual output must route through observability.logging "
        f"(get_logger / console): {offenders}")


def test_no_stdlib_getlogger_outside_logging_funnel():
    """Framework code must log through ``observability.logging.get_logger``
    — records then carry trace ids, rate limiting, and the flight-ring
    mirror. A direct stdlib ``logging.getLogger`` creates a parallel,
    unstructured stream that the kill switch and collectors never see."""
    offenders = []
    for path in _py_files(_PKG_ROOT):
        if os.path.relpath(path, _PKG_ROOT) == _LOG_FUNNEL:
            continue
        for node in ast.walk(_parse(path)):
            if isinstance(node, ast.Attribute) and \
                    node.attr == "getLogger":
                offenders.append((os.path.relpath(path, _PKG_ROOT),
                                  node.lineno))
    assert not offenders, (
        "use observability.logging.get_logger, not stdlib "
        f"logging.getLogger: {offenders}")


def test_trace_header_names_come_from_tracing_module():
    """The wire contract lives in observability/tracing.py
    (TRACEPARENT_HEADER / REQUEST_ID_HEADER); a string literal at any
    other call site can drift per hop and silently break cross-process
    stitching."""
    header_names = {"traceparent", "x-request-id"}
    tracing_py = os.path.join("observability", "tracing.py")
    offenders = []
    for path in _py_files(_PKG_ROOT):
        if os.path.relpath(path, _PKG_ROOT) == tracing_py:
            continue
        for node in ast.walk(_parse(path)):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    node.value.strip().lower() in header_names:
                offenders.append((os.path.relpath(path, _PKG_ROOT),
                                  node.lineno, node.value))
    assert not offenders, (
        "trace header names must come from observability.tracing "
        f"constants, not literals: {offenders}")


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
