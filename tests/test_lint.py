"""Fast source-level lint for the telemetry layer.

Two invariants keep the observability subsystem safe to import from every
other layer:

* **No import cycle.** Every package (core, io, train, models, ...)
  imports ``mmlspark_tpu.observability`` at module top level, so
  observability itself must never import those packages back at top level
  — its only framework dependency (``utils.profiling``) is deferred into
  function bodies. Enforced by AST walk + a fresh-interpreter import.
* **Valid metric names.** Every metric name passed as a literal to
  ``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` must match
  ``[a-z_]+`` or the Prometheus text rendering stops parsing.
"""

import ast
import os
import re
import subprocess
import sys

import pytest

_PKG_ROOT = os.path.join(os.path.dirname(__file__), "..", "mmlspark_tpu")
_NAME_RE = re.compile(r"^[a-z_]+$")
_METRIC_FACTORIES = {"counter", "gauge", "histogram",
                     "safe_counter", "safe_gauge", "safe_histogram"}


def _py_files(root):
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _parse(path):
    with open(path, encoding="utf-8") as f:
        return ast.parse(f.read(), filename=path)


def _top_level_imports(tree):
    """(module, level) pairs imported at module scope (not inside defs)."""
    out = []
    for node in ast.iter_child_nodes(tree):
        # top-level try/if wrappers around imports still count
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(n, ast.Import):
                out.extend((a.name, 0) for a in n.names)
            elif isinstance(n, ast.ImportFrom):
                out.append((n.module or "", n.level))
            else:
                stack.extend(ast.iter_child_nodes(n))
    return out


def test_observability_has_no_top_level_framework_imports():
    """observability/* may import stdlib and its own siblings at top level,
    nothing else from mmlspark_tpu — that is what makes 'every layer
    imports observability' cycle-free by construction."""
    obs_dir = os.path.join(_PKG_ROOT, "observability")
    offenders = []
    for path in _py_files(obs_dir):
        for mod, level in _top_level_imports(_parse(path)):
            top = mod.split(".")[0]
            if level >= 2 or top == "mmlspark_tpu":
                # parent-relative (..) or absolute framework import
                offenders.append(f"{os.path.basename(path)}: "
                                 f"{'.' * level}{mod}")
            elif level == 1 and top not in (
                    "metrics", "spans", "device", ""):
                offenders.append(f"{os.path.basename(path)}: .{mod}")
    assert not offenders, (
        "observability must defer framework imports into function bodies "
        f"(import-cycle guard); found top-level: {offenders}")


def test_observability_imports_standalone():
    """A fresh interpreter can import the telemetry layer on its own —
    the runtime proof of the AST rule above (and it keeps the import
    cheap: no jax, no framework)."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         "import mmlspark_tpu.observability as o\n"
         "assert 'jax' not in sys.modules, 'observability imported jax'\n"
         "o.counter('lint_total').inc()\n"
         "print(o.get_registry().render_prometheus())"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(_PKG_ROOT))
    assert proc.returncode == 0, proc.stderr
    assert "lint_total 1" in proc.stdout


def _literal_metric_names():
    """Every string literal passed as the metric name to a
    counter/gauge/histogram call anywhere under mmlspark_tpu/."""
    found = []
    for path in _py_files(_PKG_ROOT):
        for node in ast.walk(_parse(path)):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else None
            if name not in _METRIC_FACTORIES or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and \
                    isinstance(first.value, str):
                found.append((os.path.relpath(path, _PKG_ROOT),
                              node.lineno, first.value))
    return found


def test_metric_name_literals_are_prometheus_safe():
    names = _literal_metric_names()
    # the instrumentation exists: an empty scan would mean this lint is
    # silently matching nothing
    assert len(names) >= 10, names
    bad = [(p, ln, n) for p, ln, n in names if not _NAME_RE.match(n)]
    assert not bad, f"metric names must match [a-z_]+: {bad}"


def test_metric_names_unique_per_kind():
    """One metric name, one kind — the registry raises at runtime on a
    kind conflict; catch it at lint time across the whole tree."""
    kinds = {}
    conflicts = []
    for path in _py_files(_PKG_ROOT):
        for node in ast.walk(_parse(path)):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            kind = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else None
            if kind not in _METRIC_FACTORIES or not node.args:
                continue
            kind = kind.removeprefix("safe_")  # same family either way
            first = node.args[0]
            if isinstance(first, ast.Constant) and \
                    isinstance(first.value, str):
                prev = kinds.setdefault(first.value, kind)
                if prev != kind:
                    conflicts.append((first.value, prev, kind))
    assert not conflicts, conflicts


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
