"""Tier-1 bridge for graftlint (thin runner — the rules moved out).

The 12 ad-hoc AST guards that used to live here are now declarative
checkers in ``tools/graftlint/`` (one rule each; see
``docs/static_analysis.md`` for the old-guard -> rule mapping). This
shim runs the full pass as one parameterized test per rule, so a
violation fails tier-1 with the exact rule id and file:line — identical
coverage, one engine, one parse per file.

The only guard that stays here is the *runtime* complement of
``obs-import-cycle``: a fresh interpreter importing the telemetry layer
standalone, proving the static rule's conclusion (no jax, no framework)
against the real import system.
"""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from tools.graftlint import core  # noqa: E402

core.load_checkers()


@pytest.fixture(scope="module")
def repo():
    """One parsed tree shared by every per-rule test."""
    return core.Repo(ROOT)


@pytest.mark.parametrize("rule", sorted(core.REGISTRY))
def test_rule_clean(repo, rule):
    active, _suppressed = core.run(repo, rules=[rule])
    assert not active, "\n".join(
        f"{f.location()}: {f.rule}: {f.message}" for f in active)


def test_observability_imports_standalone():
    """A fresh interpreter can import the telemetry layer on its own —
    the runtime proof of the obs-import-cycle rule (and it keeps the
    import cheap: no jax, no framework)."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         "import mmlspark_tpu.observability as o\n"
         "assert 'jax' not in sys.modules, 'observability imported jax'\n"
         "o.counter('lint_total').inc()\n"
         "print(o.get_registry().render_prometheus())"],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr
    assert "lint_total 1" in proc.stdout


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
